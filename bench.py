"""Benchmark harness — prints ONE JSON line for the driver, always.

Headline workload (BASELINE.md Config 2 scaled to the available chips): 3D
Gray-Scott reaction-diffusion advanced in-situ, rendered through the VDI
generate + composite pipeline each frame. On a single chip the composite
degenerates to N=1 but still runs the full sort-merge kernel, so the
measured ms/frame covers the whole hot path (sim → generate → composite).

Engine: the MXU slice-march raycaster (ops/slicer.py) by default — VDI
generation as banded-matmul slice resampling; the metric name carries the
true rendered grid (the slice march renders on its intermediate grid,
sized by the volume × scale, NOT SITPU_BENCH_WIDTH/HEIGHT — those apply
only to the legacy gather engine).

Robustness (round-1 lesson — BENCH_r01 died in TPU backend init): the
parent process NEVER touches a JAX backend. Each TPU attempt is gated by
a cheap subprocess probe with a hard timeout (this environment's ``axon``
TPU shim can HANG backend access when the tunnel is down), the platform
list (default tpu,tpu,cpu = one TPU retry with backoff) runs each
candidate in its own subprocess, the CPU fallback is pinned, and on
total failure one parseable JSON error line is still printed (exit 0).

Knobs via env (defaults are platform-dependent: the TPU child runs the
BASELINE primary scale 512^3 x 25 frames; the CPU fallback drops to
128^3 x 5 so an outage doesn't burn the recording window):
  SITPU_BENCH_GRID=512|128  SITPU_BENCH_WIDTH=1280 SITPU_BENCH_HEIGHT=720
  SITPU_BENCH_STEPS=256 SITPU_BENCH_K=16 SITPU_BENCH_FRAMES=25|5
  SITPU_BENCH_SIM_STEPS=10 SITPU_BENCH_ADAPTIVE_ITERS=2
  SITPU_BENCH_ENGINE=mxu|gather
  SITPU_BENCH_FOLD=auto|pallas_seg|seg|pallas|xla  (auto = pallas_seg on
    TPU, probe-gated; see config.SliceMarchConfig.fold for the schedules)
  SITPU_BENCH_PLATFORMS=tpu,tpu,cpu  SITPU_BENCH_CHILD_TIMEOUT=900
  SITPU_BENCH_AUTOTUNE=1|0  (default ON for TPU temporal runs at
    grid<=512 with no explicit FOLD: times 2 frames each of
    auto/fused_stream/xla at warmup and benches the winner — set 0, or
    set SITPU_BENCH_FOLD, for fixed-fold A/B captures)
  SITPU_BENCH_SCAN_FRAMES=1  (whole frame loop in ONE lax.scan launch)
  SITPU_BENCH_SIM_STEPS=0    (render-only: static field, moving camera)
  SITPU_BENCH_REBALANCE=even|occupancy  (render rebalancing: single-chip
    runs have one band either way; the knob carries the config and the
    MODELED 8-rank plan/straggler block into the artifact — the measured
    distributed A/B is benchmarks/rank_slab_bench.py --rebalance both)
  SITPU_BENCH_SCHEDULE=frame|waves  SITPU_BENCH_WAVE_TILES=4  (tile-wave
    pipelined frames — docs/PERF.md "Tile waves"; single-chip it carries
    the config + modeled 8-rank overlap into the artifact)
The second consecutive tpu attempt falls back to SITPU_BENCH_FOLD=seg
(the same segmented-scan fold without Mosaic exposure) — but only if a
TPU child actually ran and died, so a probe-level tunnel flap never
demotes the flagship Pallas schedule.
Roofline fields: hbm_gbps / hbm_frac_peak give achieved HBM bandwidth
(XLA cost analysis of the compiled step, or a stated lower-bound traffic
model) next to mfu_matmul, so a capture says which bound it sits at.
When better platforms failed, latest_hw carries the newest COMMITTED
TPU artifact so a fallback line never reads as a regression.
Baseline: the north star of 30 FPS at the 512^3 primary scale.
vs_baseline is CONFIG-MATCHED: fps/30 at grid=512 (mxu), null otherwise
(render work scales ~grid^4, sim ~grid^3 — no single exponent converts a
small-grid fps honestly); vs_baseline_unscaled = fps/30 always.
"""

import json
import os
import subprocess
import sys
import time
import traceback

_CHILD_MARKER = "_SITPU_BENCH_CHILD"


def _env_int(name, default):
    return int(os.environ.get(name, default))


# Peak tables + lookup live in obs/roofline.py now — ONE copy read by
# the MFU report fields here, the roofline verdicts and the divergence
# engine (a slice march is plausibly bandwidth-bound, in which case a
# sub-1% MFU is the wrong alarm and achieved GB/s vs peak is the
# decision metric — VERDICT r4 weak #6). Re-bound under the old names
# for the report helpers below; roofline is JAX-free, parent-safe.
from scenery_insitu_tpu.obs.roofline import (  # noqa: E402
    PEAK_HBM_GBPS as _PEAK_HBM_GBPS, PEAK_TFLOPS as _PEAK_TFLOPS,
    kind_lookup as _kind_lookup)


def _peak_flops(device_kind: str, platform: str):
    v = _kind_lookup(_PEAK_TFLOPS, device_kind, platform, 197.0)
    return v * 1e12 if v else None


def _peak_hbm(device_kind: str, platform: str):
    return _kind_lookup(_PEAK_HBM_GBPS, device_kind, platform, 819.0)


def _frame_cost(jitted, *args):
    """Cost-analysis snapshot of the compiled frame (bytes/flops) via
    the shared ``obs.device.device_cost`` join (identical keys for
    bench artifacts, phase_bench, roofline and divergence); the caller
    falls back to a min-traffic model when the backend reports nothing.
    Lowering hits the jit/persistent compile cache — the warmup call
    already compiled this exact (shapes, donations) step."""
    from scenery_insitu_tpu.obs.device import device_cost

    snap = device_cost(jitted, *args)
    if "bytes_accessed" not in snap:
        print(f"[bench] cost analysis unavailable "
              f"({snap.get('error')})", file=sys.stderr, flush=True)
        return None, None, snap
    return snap["bytes_accessed"], snap["source"], snap


def _model_frame_bytes(grid: int, sim_steps: int, marches: int,
                       render_bytes: int, sim_fused: bool) -> float:
    """Floor-model of one frame's HBM traffic when XLA cost analysis is
    unavailable: the sim term comes from the fused-stencil schedule model
    (sim/pallas_stencil.modeled_sim_traffic — r+w of u,v per step when
    unfused), the render copy is written once and read once per march.
    Fold-state and stream traffic are schedule-dependent and EXCLUDED —
    this is a lower bound, so achieved-GB/s derived from it is also a
    lower bound."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    vox = float(grid) ** 3
    sim = ps.modeled_sim_traffic((grid, grid, grid), sim_steps,
                                 fused=sim_fused) if sim_steps else 0.0
    render_copy = vox * render_bytes
    return sim + render_copy + marches * vox * render_bytes


def _mod_exchange(n: int, k: int, height: int, width: int,
                  exchange: str, wire: str, schedule: str = "frame",
                  wave_tiles: int = 1) -> dict:
    """Modeled per-rank sort-last exchange bytes for the configured
    wire/schedule at an n-rank shape (ops.composite.modeled_exchange_traffic
    — probe-free, so the single-chip bench can still report the lever).
    ``schedule="waves"`` adds the tile-wave overlap accounting (what
    fraction of the exchange hides behind march compute)."""
    from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic

    return modeled_exchange_traffic(
        n, k, height, width, k_out=k,
        mode=("ring" if exchange == "ring" else "all_to_all"), wire=wire,
        schedule=schedule, wave_tiles=wave_tiles)


def _slice_march_flops(spec, grid: int, marches: int) -> float:
    """Matmul FLOPs of one frame of the MXU engine: ``marches`` full
    marches (counting + write) × grid slices × the two banded resampling
    matmuls per slice ([Nj,Nv]@[Nv,Nu] then @[Nu,Ni]ᵀ). Elementwise work
    (sim stencil, TF, supersegment folds) excluded — matmul-only MFU."""
    nv = nu = grid  # in-plane voxel counts (cubic grid)
    per_slice = 2.0 * spec.nj * nu * (nv + spec.ni)
    return marches * grid * per_slice


def main():
    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.utils.backend import enable_compile_cache

    # repeat runs (driver retries, the platform fallback chain) skip the
    # ~25 s flagship compile
    enable_compile_cache()

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.models.pipelines import grayscott_vdi_frame_step
    from scenery_insitu_tpu.sim import grayscott as gs

    dev = jax.devices()[0]
    platform = dev.platform
    print(f"[bench] backend={platform} device={dev.device_kind}",
          file=sys.stderr, flush=True)

    on_tpu = platform == "tpu"
    # platform-dependent defaults: TPU measures the BASELINE primary
    # scale (512^3, >=25 frames — 5-frame windows showed ~10% noise);
    # the CPU fallback stays small enough to finish inside the window
    grid = _env_int("SITPU_BENCH_GRID", 512 if on_tpu else 128)
    width = _env_int("SITPU_BENCH_WIDTH", 1280)
    height = _env_int("SITPU_BENCH_HEIGHT", 720)
    steps = _env_int("SITPU_BENCH_STEPS", 256)
    k = _env_int("SITPU_BENCH_K", 16)
    frames = _env_int("SITPU_BENCH_FRAMES", 25 if on_tpu else 5)
    sim_steps = _env_int("SITPU_BENCH_SIM_STEPS", 10)
    ad_iters = _env_int("SITPU_BENCH_ADAPTIVE_ITERS", 2)
    # histogram: ONE counting march for all candidate thresholds (higher
    # segment fidelity than a 2-iter search AND fewer marches).
    # temporal: NO counting march in steady state — threshold carried
    # across frames (seeded by one histogram march at warmup); mxu-only,
    # so the gather engine downgrades to histogram.
    ad_mode = os.environ.get("SITPU_BENCH_ADAPTIVE_MODE", "temporal")
    fold = os.environ.get("SITPU_BENCH_FOLD", "auto")
    chunk = _env_int("SITPU_BENCH_CHUNK", 16)   # slices per fold kernel
    # 1024^3 memory plan: sim stays f32 (donated), the RENDERED field
    # copy drops to bf16 — the march's permuted volume halves to ~2.1 GB
    # and the resampling matmuls cast to bf16 regardless (see
    # models/pipelines.py render_dtype). Explicit env overrides.
    render_dtype = os.environ.get("SITPU_BENCH_RENDER_DTYPE",
                                  "bf16" if grid >= 1024 else "f32")
    # accept the long spellings; config validation only knows the short
    render_dtype = {"bfloat16": "bf16", "float32": "f32"}.get(render_dtype,
                                                              render_dtype)
    # in-plane occupancy tiles (0 = chunk skipping only; -1 = the
    # backend-resolved default, 16 on TPU — see
    # SliceMarchConfig.occupancy_vtiles)
    vtiles = _env_int("SITPU_BENCH_VTILES", -1)
    # empty-space-skipping A/B ladder (docs/PERF.md "Empty-space
    # skipping"; benchmarks/occupancy_bench.py is the dedicated A/B):
    # off | chunk | pyramid | sim — unset keeps the slicer-config
    # defaults (skip on, vtiles as above). "sim" feeds the march's
    # occupancy pyramid from ranges riding the fused sim stencil.
    skip_mode = os.environ.get("SITPU_BENCH_SKIP") or None
    if skip_mode not in (None, "off", "chunk", "pyramid", "sim"):
        raise ValueError(f"SITPU_BENCH_SKIP must be off|chunk|pyramid|sim,"
                         f" got {skip_mode!r}")
    # sim-fusion lever A/B: 0 pins the XLA roll formulation (the un-fused
    # baseline the time-fused Pallas stencil is measured against)
    sim_fused = bool(_env_int("SITPU_BENCH_SIM_FUSED", 1))
    # sort-last exchange schedule A/B (docs/PERF.md "Exchange modes"):
    # single-chip both schedules are the identity exchange, so this knob
    # exists to keep the flagship config in lockstep with the distributed
    # A/B in benchmarks/composite_bench.py (which measures the virtual
    # mesh) and to carry the choice into the artifact's config block
    exchange = os.environ.get("SITPU_BENCH_EXCHANGE", "all_to_all")
    # supersegment wire format A/B (docs/PERF.md "Wire formats"): same
    # single-chip story as the exchange knob — the distributed byte
    # shrink is composite_bench's to measure; here the knob carries the
    # config and the modeled per-wire exchange bytes into the artifact
    wire = os.environ.get("SITPU_BENCH_WIRE", "f32")
    # frame schedule A/B (docs/PERF.md "Tile waves"): single-chip frames
    # have no exchange to overlap (waves degrade to frame on the ledger),
    # so like the exchange/wire knobs this carries the config and the
    # modeled 8-rank overlap accounting into the artifact; the measured
    # distributed A/B is benchmarks/composite_bench.py --schedule both
    schedule = os.environ.get("SITPU_BENCH_SCHEDULE", "frame")
    wave_tiles = _env_int("SITPU_BENCH_WAVE_TILES", 4)
    # render-rebalancing A/B (docs/PERF.md "Render rebalancing"): a
    # single chip has one z band whatever the plan, so like the
    # exchange/wire/schedule knobs this carries the config and the
    # MODELED 8-rank plan + straggler factors into the artifact; the
    # measured distributed A/B lives in benchmarks/rank_slab_bench.py
    rebalance = os.environ.get("SITPU_BENCH_REBALANCE", "even")
    if rebalance not in ("even", "occupancy"):
        raise ValueError(f"SITPU_BENCH_REBALANCE must be even|occupancy, "
                         f"got {rebalance!r}")

    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    engine = os.environ.get("SITPU_BENCH_ENGINE", "mxu")
    engine = slicer.resolve_engine(engine)
    if ad_mode == "temporal" and engine != "mxu":
        print("[bench] temporal mode is mxu-only; using histogram",
              file=sys.stderr, flush=True)
        obs.degrade("bench.adaptive_mode", "temporal", "histogram",
                    "temporal mode is mxu-only", warn=False)
        ad_mode = "histogram"

    base = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)

    def make_step(fold_name):
        from scenery_insitu_tpu.models.pipelines import \
            resolve_occupancy_cfg

        # the SAME resolver the pipeline applies, so the reported march
        # config cannot drift from the march actually benched
        mc = resolve_occupancy_cfg(
            SliceMarchConfig(fold=fold_name, chunk=chunk,
                             occupancy_vtiles=vtiles), skip_mode)
        return mc, grayscott_vdi_frame_step(
            width, height, sim_steps=sim_steps, max_steps=steps,
            vdi_cfg=VDIConfig(max_supersegments=k, adaptive_iters=ad_iters,
                              adaptive_mode=ad_mode),
            comp_cfg=CompositeConfig(max_output_supersegments=k,
                                     adaptive_iters=ad_iters,
                                     exchange=exchange, wire=wire,
                                     schedule=schedule,
                                     wave_tiles=wave_tiles,
                                     rebalance=rebalance),
            engine=engine, grid_shape=(grid, grid, grid),
            axis_sign=slicer.choose_axis(base) if engine == "mxu" else None,
            slicer_cfg=mc, render_dtype=render_dtype, sim_fused=sim_fused,
            occupancy=skip_mode)

    # the mxu step is compiled for the base camera's march regime (axis z
    # here); oscillate the orbit within ±0.35 rad so every benched frame
    # stays inside that regime no matter how many frames are requested
    temporal = ad_mode == "temporal" and engine == "mxu"

    # warmup-time fold AUTOTUNE (TPU default; SITPU_BENCH_AUTOTUNE=0 or an
    # explicit SITPU_BENCH_FOLD disables): the fold-schedule ranking has
    # disagreed with the synthetic microbench across rounds, and tunnel
    # windows are too scarce to guess — so when the hardware IS there,
    # measure 2 frames per candidate and bench the winner. Candidates:
    # the platform default, the whole-march stream fold, and the
    # fuses-into-the-march XLA fold (the round-2 256^3 frame-context
    # winner). Per-candidate guarded; compile cache makes repeats cheap.
    # gated to <=512 grids: the tuning jits are NOT donated (each timed
    # call holds input + output sim copies), which is fine at 512^3
    # (~1 GB extra) but would OOM the 1024^3 memory plan before the
    # donated main loop even runs
    autotune = _env_int("SITPU_BENCH_AUTOTUNE",
                        1 if (on_tpu and grid <= 512) else 0)
    autotune_ms = None
    st0 = None
    if (autotune and temporal and grid <= 512
            and "SITPU_BENCH_FOLD" not in os.environ):
        st0 = gs.GrayScott.init((grid, grid, grid))
        autotune_ms = {}
        thr0 = None
        for fname in ("auto", "fused_stream", "xla"):
            try:
                _, fs = make_step(fname)
                fr = jax.jit(lambda u_, v_, yaw, th, fs=fs:
                             fs(u_, v_, orbit(base, yaw).eye, th))
                # (not donated: st0 must survive for the main loop)
                if thr0 is None:
                    thr0 = jax.jit(fs.init_threshold)(st0.u, st0.v,
                                                      base.eye)
                c2, d2, u2, v2, t2 = fr(st0.u, st0.v, jnp.float32(0.0),
                                        thr0)
                jax.block_until_ready(c2)          # compile + settle
                t0 = time.perf_counter()
                for _ in range(2):
                    c2, d2, u2, v2, t2 = fr(u2, v2, jnp.float32(0.01), t2)
                jax.block_until_ready(c2)
                autotune_ms[fname] = round(
                    (time.perf_counter() - t0) / 2 * 1e3, 1)
            except Exception as e:
                autotune_ms[fname] = f"error: {type(e).__name__}"
                # a candidate that died is silently dropped from the
                # autotune race — ledger it so the artifact says WHY the
                # surviving fold won
                obs.degrade("bench.autotune_fold", fname, "skipped",
                            f"autotune candidate failed "
                            f"({type(e).__name__}: {str(e)[:120]})",
                            warn=False)
            finally:
                fr = fs = c2 = d2 = u2 = v2 = t2 = None
        timed = {f: m for f, m in autotune_ms.items()
                 if isinstance(m, float)}
        if timed:
            fold = min(timed, key=timed.get)
            print(f"[bench] autotune {autotune_ms} -> fold={fold}",
                  file=sys.stderr, flush=True)

    march_cfg, frame_step = make_step(fold)
    if temporal:
        def frame(u, v, yaw, thr):
            return frame_step(u, v, orbit(base, yaw).eye, thr)
    else:
        def frame(u, v, yaw):
            return frame_step(u, v, orbit(base, yaw).eye)

    # donate the carried sim/threshold state: at the 512^3 primary scale
    # u+v alone are 1 GB — without donation every frame holds two copies
    frame = jax.jit(frame, donate_argnums=(0, 1, 3) if temporal else (0, 1))
    st = st0 or gs.GrayScott.init((grid, grid, grid))
    u, v = st.u, st.v

    # warmup / compile (temporal: seed the threshold state + 2 settle
    # frames so the measured loop is the steady-state one-march regime)
    t_c = time.perf_counter()
    if temporal:
        thr = jax.jit(frame_step.init_threshold)(u, v, base.eye)
        for _ in range(3):
            c, d, u, v, thr = frame(u, v, jnp.float32(0.0), thr)
    else:
        c, d, u, v = frame(u, v, jnp.float32(0.0))
    jax.block_until_ready(c)
    compile_s = time.perf_counter() - t_c
    print(f"[bench] warmup+compile {compile_s:.1f}s", file=sys.stderr,
          flush=True)

    import math
    # SCAN_FRAMES=1: run the whole frame loop as ONE lax.scan inside ONE
    # jit call — a single executable launch for all frames. If the axon
    # shim taxes every launch (dispatch_tiny_us in hbm_bench decides),
    # this A/B isolates that tax from real device time. Per-frame means
    # of the VDI planes are returned so every frame's fold stays live
    # (no DCE of non-final frames); sim/threshold state is carried.
    scan_frames = _env_int("SITPU_BENCH_SCAN_FRAMES", 0)
    yaw_arr = jnp.asarray([0.35 * math.sin(0.7 * (i + 1))
                           for i in range(frames)], jnp.float32)
    partial_jit_donate = lambda f: jax.jit(f, donate_argnums=(0, 1, 2))
    if scan_frames and temporal:
        @partial_jit_donate
        def run_all(u, v, thr, yaws):
            def body(carry, yaw):
                u, v, thr = carry
                c, d, u, v, thr = frame_step(u, v, orbit(base, yaw).eye,
                                             thr)
                return (u, v, thr), (jnp.mean(c), jnp.mean(d))
            carry, means = jax.lax.scan(body, (u, v, thr), yaws)
            return carry, means

        # warm the scan-loop executable too (compile excluded from timing)
        (u, v, thr), _ = run_all(u, v, thr, yaw_arr)
        jax.block_until_ready(u)
        t0 = time.perf_counter()
        (u, v, thr), means = run_all(u, v, thr, yaw_arr)
        jax.block_until_ready(means)
        dt = (time.perf_counter() - t0) / frames
        c, d, u, v, thr = frame(u, v, jnp.float32(0.0), thr)
    else:
        if scan_frames:
            print("[bench] SCAN_FRAMES needs temporal mxu mode; ignoring",
                  file=sys.stderr, flush=True)
            obs.degrade("bench.scan_frames", "scan", "eager",
                        "SCAN_FRAMES needs temporal mxu mode", warn=False)
            scan_frames = 0
        t0 = time.perf_counter()
        for i in range(frames):
            yaw = yaw_arr[i]
            if temporal:
                c, d, u, v, thr = frame(u, v, yaw, thr)
            else:
                c, d, u, v = frame(u, v, yaw)
        jax.block_until_ready(c)
        dt = (time.perf_counter() - t0) / frames

    fps = 1.0 / dt
    # report what was actually rendered: the mxu engine marches the volume's
    # slices onto its intermediate grid; the gather engine marches `steps`
    # per-ray samples at (width, height)
    mfu = None
    peak = _peak_flops(dev.device_kind, platform)
    marches = 1
    if engine == "mxu":
        spec = slicer.make_spec(base, (grid, grid, grid), march_cfg)
        render_cfg = {"image": [spec.ni, spec.nj], "steps": grid,
                      "fold": spec.fold, "render_dtype": render_dtype,
                      "vtiles": spec.vtiles,
                      "skip_empty": spec.skip_empty}
        res_tag = f"{spec.ni}x{spec.nj}"
        marches = (1 if temporal else
                   2 if ad_mode == "histogram" else ad_iters + 1)
        if peak:
            mfu = round(_slice_march_flops(spec, grid, marches) * fps / peak,
                        5)
    else:
        render_cfg = {"image": [width, height], "steps": steps}
        res_tag = f"{width}x{height}"

    # roofline companion to MFU: achieved HBM GB/s over the frame, so the
    # optimization loop can tell compute-bound from bandwidth-bound
    # without xprof archaeology. XLA's cost analysis of the compiled step
    # when available; a stated lower-bound traffic model otherwise.
    frame_args = ((u, v, jnp.float32(0.0), thr) if temporal
                  else (u, v, jnp.float32(0.0)))
    hbm_bytes, hbm_src, cost_snap = _frame_cost(frame, *frame_args)
    if hbm_bytes is None and engine == "mxu":
        # the model charges a full-volume read per march — a floor only
        # for the slice march; the gather engine's traffic is sample-
        # driven and can undercut it, so no model fallback there
        rb = 2 if render_dtype in ("bf16", "bfloat16") else 4
        hbm_bytes = _model_frame_bytes(grid, sim_steps, marches, rb,
                                       sim_fused)
        hbm_src = "min_traffic_model"
    hbm_gbps = hbm_bytes / dt / 1e9 if hbm_bytes else None
    peak_bw = _peak_hbm(dev.device_kind, platform)
    # attribution plane (docs/OBSERVABILITY.md "Phase attribution"):
    # SITPU_BENCH_PROFILE=1 runs N traced frames of the SAME compiled
    # step, joins device op time back to the sitpu_* phase scopes, adds
    # roofline verdicts per phase and a divergence report against the
    # committed modeled projection — all riding inside this artifact
    profile_attr = profile_roofline = divergence = None
    if _env_int("SITPU_BENCH_PROFILE", 0):
        from scenery_insitu_tpu.obs.profiler import (ProfileCapture,
                                                     publish_attribution)
        from scenery_insitu_tpu.obs.roofline import (peaks_for,
                                                     roofline_verdicts)

        # the frame donates its inputs, so the capture threads state
        # through a closure instead of re-calling with dead buffers
        _pstate = {"u": u, "v": v, "thr": thr}
        # host-delivery meter (ISSUE 19): each profiled frame pays the
        # real delivery path — device->host copy of the frame payload,
        # CRC, and the deflate-class compress the vdi disk sink runs —
        # and the timed seconds feed ProfileCapture's host_time_fn hook
        # so attribution carries a host phase instead of folding
        # delivery into unattributed (on CPU the old normalization
        # structurally zeroed it: device op time already covered the
        # wall)
        _host_s = [0.0]

        def _deliver(c_, d_):
            import zlib as _zlib

            import numpy as _np

            t0_ = time.perf_counter()
            for leaf in (c_, d_):
                blob = _np.asarray(leaf).tobytes()
                _zlib.crc32(blob)
                _zlib.compress(blob, 6)
            _host_s[0] += time.perf_counter() - t0_

        def _profile_step():
            if temporal:
                c_, d_, _pstate["u"], _pstate["v"], _pstate["thr"] = \
                    frame(_pstate["u"], _pstate["v"], jnp.float32(0.0),
                          _pstate["thr"])
            else:
                c_, d_, _pstate["u"], _pstate["v"] = frame(
                    _pstate["u"], _pstate["v"], jnp.float32(0.0))
            _deliver(c_, d_)
            return c_

        cap = ProfileCapture(
            frames=_env_int("SITPU_BENCH_PROFILE_FRAMES", 3),
            host_time_fn=lambda: _host_s[0])
        profile_attr = cap.capture(frame, *frame_args,
                                   step=_profile_step)
        u, v, thr = _pstate["u"], _pstate["v"], _pstate["thr"]
        if profile_attr is not None:
            publish_attribution(profile_attr)
            profile_roofline = roofline_verdicts(
                profile_attr, cost_snap,
                peaks_for(dev.device_kind, platform))
            try:
                from benchmarks.divergence import (divergence_report,
                                                   latest_modeled)

                mp = latest_modeled()
                if mp:
                    with open(mp) as f:
                        mdoc = json.load(f)
                    divergence = divergence_report(
                        profile_attr, mdoc, roofline=profile_roofline,
                        measured_config={
                            "exchange": exchange, "wire": wire,
                            "schedule": schedule,
                            "sim_fused": sim_fused,
                            "render_dtype": render_dtype},
                        modeled_path=os.path.relpath(
                            mp, os.path.dirname(
                                os.path.abspath(__file__))))
            except Exception as e:   # noqa: BLE001 — a broken modeled
                # artifact must not kill the bench artifact
                obs.degrade("divergence.modeled", "modeled_projection",
                            "none", f"divergence join failed: {e}",
                            warn=False)
    # occupancy of the FINAL benched field (post-timing, host-side): the
    # artifact records how sparse the measured scene actually was — the
    # live fraction is what decides whether skip modes can pay, and the
    # per-chunk histogram says whether the sparsity is banded or diffuse
    occupancy_info = None
    if engine == "mxu":
        try:
            import numpy as _np

            from scenery_insitu_tpu.core.transfer import for_dataset
            from scenery_insitu_tpu.core.volume import Volume
            from scenery_insitu_tpu.ops import occupancy as occ_mod

            fld = (v.astype(jnp.bfloat16)
                   if render_dtype == "bf16" else v)
            pyr = occ_mod.pyramid_from_volume(
                Volume.centered(fld, extent=2.0),
                for_dataset("gray_scott"), spec)
            clf = _np.asarray(pyr.chunk_live_fractions())
            occupancy_info = {
                "mode": skip_mode or ("pyramid" if spec.vtiles > 0 else
                                      "chunk" if spec.skip_empty else
                                      "off"),
                "vtiles": spec.vtiles,
                "live_fraction": round(float(pyr.live_fraction()), 4),
                "chunk_live_hist": _np.histogram(
                    clf, bins=8, range=(0.0, 1.0))[0].tolist(),
            }
        except Exception as e:   # never let reporting kill the artifact
            occupancy_info = {"error": f"{type(e).__name__}: {e}"}
    # render-rebalance block (post-timing, host-side, engine-agnostic):
    # the z live profile of the FINAL benched field at the reference
    # 8-rank shape -> the plan slice_plan would adopt and the modeled
    # straggler factor it removes (max/mean per-rank march work; the
    # measured distributed A/B is benchmarks/rank_slab_bench.py)
    rebalance_info = None
    try:
        from scenery_insitu_tpu.core.transfer import for_dataset as _fd
        from scenery_insitu_tpu.ops import occupancy as occ_mod

        n_model = 8
        prof = occ_mod.z_live_profile(v, _fd("gray_scott"))
        even8 = occ_mod.even_plan(grid, n_model)
        plan8 = occ_mod.slice_plan(prof, grid, n_model, min_depth=4,
                                   quantum=4)
        rebalance_info = {
            "mode": rebalance,
            "modeled_ranks": n_model,
            "plan": list(plan8),
            "plan_histogram": {str(d): sum(1 for p_ in plan8 if p_ == d)
                               for d in sorted(set(plan8))},
            "straggler_even": round(
                occ_mod.straggler_factor(prof, grid, even8), 3),
            "straggler_planned": round(
                occ_mod.straggler_factor(prof, grid, plan8), 3),
        }
    except Exception as e:       # never let reporting kill the artifact
        rebalance_info = {"error": f"{type(e).__name__}: {e}"}
    # CONFIG-MATCHED vs_baseline: fps/30 only at the 512^3 primary scale
    # on the flagship engine, null otherwise — the mxu render work scales
    # ~grid^4 and the sim ~grid^3, so no single exponent converts a
    # small-grid fps to the primary metric honestly. The raw figure stays
    # available as vs_baseline_unscaled for cross-round comparison.
    matched = engine == "mxu" and grid == 512 and sim_steps > 0
    # sim_steps=0 measures the RENDER path on a static field — the same
    # semantics as the reference's own FPS harness (static volume, moving
    # camera: VolumeFromFileExample.kt:777-794), and the honest in-situ
    # split: the reference's sim runs on 20 CPU cores/node while its GPU
    # only renders (README.md:4-8), so render-only fps is the number its
    # harness would have produced
    tag = "_render_only" if sim_steps == 0 else ""
    if scan_frames:
        tag += "_scanloop"
    print(json.dumps({
        "metric": f"gray_scott_{grid}c_vdi_fps_{res_tag}_{platform}"
                  f"_1chip{tag}",
        "value": round(fps, 3),
        "unit": "frames/s",
        "vs_baseline": round(fps / 30.0, 4) if matched else None,
        "vs_baseline_unscaled": round(fps / 30.0, 4),
        "vs_baseline_note": (
            "fps/30 at the config-matched 512^3 mxu primary scale"
            if matched else
            "null: not the 512^3 mxu primary config — see "
            "vs_baseline_unscaled (raw fps/30)"),
        "ms_per_frame": round(dt * 1000.0, 2),
        "mfu_matmul": mfu,
        "hbm_gbps": round(hbm_gbps, 2) if hbm_gbps else None,
        "hbm_frac_peak": (round(hbm_gbps / peak_bw, 4)
                          if hbm_gbps and peak_bw else None),
        "hbm_bytes_per_frame": round(hbm_bytes) if hbm_bytes else None,
        "hbm_bytes_source": hbm_src,
        # observability (ISSUE 3): the per-regime device-cost snapshot of
        # the compiled frame and the fallback ledger, so the artifact
        # records WHY a number is what it is — every degradation (codec,
        # fold probe, sim stencil, scan mode, platform) that fired in
        # this child is listed, machine-readable
        "cost_analysis": {
            (f"regime={slicer.choose_axis(base)}" if engine == "mxu"
             else "gather"): cost_snap},
        # what the configured wire WOULD ship per rank at the reference
        # 8-rank distributed shape of this config (modeled — single-chip
        # runs have no exchange; composite_bench measures the real one)
        "modeled_exchange_8rank": _mod_exchange(
            8, k, height, width, exchange, wire, schedule, wave_tiles),
        "occupancy": occupancy_info,
        "rebalance": rebalance_info,
        # attribution plane (SITPU_BENCH_PROFILE=1, else nulls): traced
        # per-phase device time, roofline verdicts per phase, and the
        # model-vs-measured divergence report — docs/OBSERVABILITY.md
        "phase_attribution": profile_attr,
        "roofline_verdicts": profile_roofline,
        "divergence": divergence,
        "degradations": obs.ledger(),
        "config": {"grid": grid, **render_cfg,
                   "k": k, "frames": frames, "sim_steps": sim_steps,
                   "sim_fused": sim_fused, "exchange": exchange,
                   "wire": wire, "schedule": schedule,
                   "wave_tiles": wave_tiles, "skip": skip_mode,
                   "rebalance": rebalance,
                   "adaptive_iters": ad_iters, "adaptive_mode": ad_mode,
                   "chunk": chunk, "scan_frames": bool(scan_frames),
                   "autotune_ms": autotune_ms,
                   "compile_s": round(compile_s, 1),
                   "platform": platform, "device": dev.device_kind,
                   "assumed_peak_tflops": (peak / 1e12 if peak else None),
                   "assumed_peak_hbm_gbps": peak_bw,
                   "engine": engine},
    }), flush=True)


def _child_env(platform: str) -> dict:
    env = dict(os.environ)
    env[_CHILD_MARKER] = "1"
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # neutralized in-child too (see __main__ branch below), but make the
        # intent visible in the env for diagnosability
        env["_SITPU_POP_AXON"] = "1"
    return env


def _probe_tpu() -> bool:
    """Can the TPU backend actually answer? Must run BEFORE committing the
    full benchmark to the TPU attempt (a probe false-negative demotes the
    headline number to the CPU fallback; the second platforms entry
    retries the probe). One shared implementation: utils.backend."""
    from scenery_insitu_tpu.utils.backend import probe_tpu

    return probe_tpu() > 0


def _run_child(platform: str, timeout_s: int, extra_env=None,
               attempt: int = 1):
    """Run the benchmark on one platform candidate in a subprocess; return
    the parsed result dict or an error string. ``attempt`` is the
    1-based per-platform attempt index — it goes into the failure reason
    so retries of the same platform stay DISTINCT entries in
    ``failed_attempts`` instead of two identical lines (which read as a
    copy-paste bug and dedupe to one ledger entry)."""
    if platform == "tpu":
        t0 = time.perf_counter()
        if not _probe_tpu():
            return None, (f"tpu attempt {attempt}: backend probe failed "
                          f"after {time.perf_counter() - t0:.1f}s "
                          f"(tunnel dead or hung)")
    print(f"[bench] trying platform={platform} attempt {attempt} "
          f"(timeout {timeout_s}s"
          + (f", {extra_env}" if extra_env else "") + ")",
          file=sys.stderr, flush=True)
    env = _child_env(platform)
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
            stdout=subprocess.PIPE, stderr=None,
            timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, (f"{platform} attempt {attempt}: child timed out "
                      f"after {timeout_s}s")
    out = proc.stdout.decode("utf-8", "replace")
    if proc.returncode != 0:
        tail = out.strip().splitlines()[-3:]
        return None, (f"{platform} attempt {attempt}: rc={proc.returncode} "
                      f"{' | '.join(tail)}")
    for line in reversed(out.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                break
    return None, f"{platform} attempt {attempt}: no JSON line in child output"


def _latest_hw():
    """Newest COMMITTED TPU benchmark artifact (path + value + commit
    date), attached to every driver capture so a CPU-fallback line never
    reads as a regression when the tunnel is down (VERDICT r4 item 8).
    Prefers the primary-scale (512^3) metric over newer small-grid runs."""
    try:
        root = os.path.dirname(os.path.abspath(__file__))
        tracked = subprocess.run(
            ["git", "ls-files", "benchmarks/results"], cwd=root,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=15).stdout.decode().split()
        best = None
        for rel in tracked:
            if not rel.endswith(".json"):
                continue
            try:
                with open(os.path.join(root, rel)) as f:
                    d = json.load(f)
            except Exception:
                continue
            cfg = d.get("config") or {}
            if cfg.get("platform") != "tpu" or not d.get("value"):
                continue
            date = subprocess.run(
                ["git", "log", "-1", "--format=%cs", "--", rel], cwd=root,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                timeout=15).stdout.decode().strip()
            primary = cfg.get("grid") == 512 and cfg.get("engine") == "mxu"
            rank = (primary, date, rel)
            if best is None or rank > best[0]:
                best = (rank, {
                    "path": rel, "metric": d.get("metric"),
                    "value": d.get("value"), "unit": d.get("unit"),
                    "committed": date, "primary_scale": primary})
        return best[1] if best else None
    except Exception:
        return None


def _orchestrate():
    # for the all-failed error label only; children pick platform-
    # dependent defaults (512 tpu / 128 cpu) when the env is unset
    grid = os.environ.get("SITPU_BENCH_GRID", "default")
    # worst case must stay well inside the driver's recording window: a
    # dead tunnel costs one cheap probe per TPU attempt (not the full
    # child timeout) + the CPU fallback
    timeout_s = _env_int("SITPU_BENCH_CHILD_TIMEOUT", 900)
    platforms = [p.strip() for p in os.environ.get(
        "SITPU_BENCH_PLATFORMS", "tpu,tpu,cpu").split(",")]
    errors = []
    tpu_children_failed = 0
    attempts = {}
    from scenery_insitu_tpu import obs

    for i, platform in enumerate(platforms):
        attempts[platform] = attempts.get(platform, 0) + 1
        if i > 0:
            # bounded exponential backoff between platform probes: a
            # tunnel mid-flap gets a real chance to recover before the
            # retry probe instead of two back-to-back identical failures
            # (shared ladder: utils/retry.py, same pacing the stream
            # endpoints use to reconnect)
            from scenery_insitu_tpu.utils.retry import backoff_delay
            delay = backoff_delay(i - 1, base_s=5.0, cap_s=30.0)
            print(f"[bench] backing off {delay}s before {platform} "
                  f"attempt {attempts[platform]}", file=sys.stderr,
                  flush=True)
            time.sleep(delay)
        extra = {}
        if (platform == "tpu" and tpu_children_failed >= 1
                and "SITPU_BENCH_FOLD" not in os.environ):
            # a TPU child actually RAN and died (not a probe failure —
            # a tunnel flap must not demote the flagship Pallas schedule):
            # retry with the pure-XLA segmented-scan fold in case the
            # Pallas seg kernel is what killed it (same algorithm, no
            # Mosaic exposure — and still chunk-granular state traffic,
            # unlike the per-slice "xla" machine fold)
            extra["SITPU_BENCH_FOLD"] = "seg"
        result, err = _run_child(platform, timeout_s, extra,
                                 attempt=attempts[platform])
        if (platform == "tpu" and err is not None
                and "probe failed" not in err):
            tpu_children_failed += 1
        if result is not None:
            if errors:
                # a fallback number must carry WHY the better platforms
                # failed (a CPU figure with no context reads as the
                # framework's speed; with this it reads as an outage),
                # and the newest committed hardware truth for comparison
                result["failed_attempts"] = errors
                # the per-attempt failures were ledgered as they happened
                # (distinct reasons, so retries don't dedupe away); this
                # entry records the DOWNGRADE itself — only when the run
                # landed on a DIFFERENT platform than configured (a retry
                # of the same platform that succeeds is not a downgrade)
                if platform != platforms[0]:
                    obs.degrade("bench.platform", platforms[0], platform,
                                f"downgraded after {len(errors)} failed "
                                f"attempt(s): {errors[-1]}", warn=False)
                result["degradations"] = (
                    result.get("degradations") or []) + obs.ledger()
                hw = _latest_hw()
                if hw:
                    result["latest_hw"] = hw
            print(json.dumps(result), flush=True)
            return
        errors.append(err)
        # ledger each failed attempt at failure time with its DISTINCT
        # reason (attempt index + phase), so the final artifact's ledger
        # separates "probe never answered" from "child ran and died"
        obs.degrade("bench.platform_attempt",
                    f"{platform} attempt {attempts[platform]}",
                    "failed", err, warn=False)
        print(f"[bench] attempt failed: {err}", file=sys.stderr, flush=True)
    obs.degrade("bench.platform", platforms[0], "none",
                f"all {len(errors)} attempts failed", warn=False)
    out = {
        "metric": f"gray_scott_{grid}c_vdi_fps",
        "grid_note": "default = 512 on tpu, 128 on cpu",
        "value": None,
        "unit": "frames/s",
        "vs_baseline": None,
        "error": "; ".join(errors)[-800:],
        "degradations": obs.ledger(),
    }
    hw = _latest_hw()
    if hw:
        out["latest_hw"] = hw
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if os.environ.get(_CHILD_MARKER) == "1":
        if os.environ.get("_SITPU_POP_AXON") == "1":
            from scenery_insitu_tpu.utils.backend import pin_cpu_backend

            pin_cpu_backend()
        try:
            main()
        except Exception:
            traceback.print_exc()
            sys.exit(1)
    else:
        _orchestrate()
