"""Benchmark harness — prints ONE JSON line for the driver.

Headline workload (BASELINE.md Config 2 scaled to the available chips): 3D
Gray-Scott reaction-diffusion advanced in-situ, rendered through the VDI
generate + composite pipeline each frame. On a single chip the composite
degenerates to N=1 but still runs the full sort-merge kernel, so the
measured ms/frame covers the whole hot path (sim → generate → composite).

Engine: the MXU slice-march raycaster (ops/slicer.py) by default — VDI
generation as banded-matmul slice resampling; the intermediate VDI grid is
sized by the volume (scale 1.25), so SITPU_BENCH_STEPS only applies to the
legacy gather engine (SITPU_BENCH_ENGINE=gather), which marches per-ray.

Knobs via env (defaults tuned for one v5e chip):
  SITPU_BENCH_GRID=256  SITPU_BENCH_WIDTH=1280 SITPU_BENCH_HEIGHT=720
  SITPU_BENCH_STEPS=256 SITPU_BENCH_K=16 SITPU_BENCH_FRAMES=5
  SITPU_BENCH_SIM_STEPS=10 SITPU_BENCH_ADAPTIVE_ITERS=2
  SITPU_BENCH_ENGINE=mxu|gather
Baseline: the project north star of 30 FPS (BASELINE.json) — vs_baseline is
measured_fps / 30.
"""

import json
import os
import time


def _env_int(name, default):
    return int(os.environ.get(name, default))


def main():
    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.models.pipelines import grayscott_vdi_frame_step
    from scenery_insitu_tpu.sim import grayscott as gs

    grid = _env_int("SITPU_BENCH_GRID", 256)
    width = _env_int("SITPU_BENCH_WIDTH", 1280)
    height = _env_int("SITPU_BENCH_HEIGHT", 720)
    steps = _env_int("SITPU_BENCH_STEPS", 256)
    k = _env_int("SITPU_BENCH_K", 16)
    frames = _env_int("SITPU_BENCH_FRAMES", 5)
    sim_steps = _env_int("SITPU_BENCH_SIM_STEPS", 10)
    ad_iters = _env_int("SITPU_BENCH_ADAPTIVE_ITERS", 2)

    platform = jax.devices()[0].platform

    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    engine = os.environ.get("SITPU_BENCH_ENGINE", "mxu")
    engine = slicer.resolve_engine(engine)

    base = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    frame_step = grayscott_vdi_frame_step(
        width, height, sim_steps=sim_steps, max_steps=steps,
        vdi_cfg=VDIConfig(max_supersegments=k, adaptive_iters=ad_iters),
        comp_cfg=CompositeConfig(max_output_supersegments=k,
                                 adaptive_iters=ad_iters),
        engine=engine, grid_shape=(grid, grid, grid),
        axis_sign=slicer.choose_axis(base) if engine == "mxu" else None)

    # the mxu step is compiled for the base camera's march regime (axis z
    # here); oscillate the orbit within ±0.35 rad so every benched frame
    # stays inside that regime no matter how many frames are requested
    def frame(u, v, yaw):
        return frame_step(u, v, orbit(base, yaw).eye)

    frame = jax.jit(frame)
    st = gs.GrayScott.init((grid, grid, grid))
    u, v = st.u, st.v

    # warmup / compile
    c, d, u, v = frame(u, v, jnp.float32(0.0))
    jax.block_until_ready(c)

    import math
    t0 = time.perf_counter()
    for i in range(frames):
        yaw = 0.35 * math.sin(0.7 * (i + 1))
        c, d, u, v = frame(u, v, jnp.float32(yaw))
    jax.block_until_ready(c)
    dt = (time.perf_counter() - t0) / frames

    fps = 1.0 / dt
    # report what was actually rendered: the mxu engine marches the volume's
    # slices onto its intermediate grid; the gather engine marches `steps`
    # per-ray samples at (width, height)
    if engine == "mxu":
        spec = slicer.make_spec(base, (grid, grid, grid), SliceMarchConfig())
        render_cfg = {"image": [spec.ni, spec.nj], "steps": grid}
    else:
        render_cfg = {"image": [width, height], "steps": steps}
    print(json.dumps({
        "metric": f"gray_scott_{grid}c_vdi_fps_{platform}_1chip",
        "value": round(fps, 3),
        "unit": "frames/s",
        "vs_baseline": round(fps / 30.0, 4),
        "ms_per_frame": round(dt * 1000.0, 2),
        "config": {"grid": grid, **render_cfg,
                   "k": k, "frames": frames, "sim_steps": sim_steps,
                   "platform": platform, "engine": engine},
    }))


if __name__ == "__main__":
    main()
