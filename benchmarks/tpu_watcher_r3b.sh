#!/bin/bash
# Round-3 second-window watcher. The first window (22:12-22:48 UTC) captured
# the 512^3/256^3/histogram flagship numbers and exposed the write-fold as
# the bottleneck (~390 of 420 ms/frame at 512^3); the tunnel died before the
# diagnostics ran. This suite is ordered by marginal value for the NEXT
# window:
#   1. fold_microbench      - decides the fold schedule (new two-phase
#                             Pallas kernel vs XLA scan vs counting floor)
#   2. bench 512 (new fold) - flagship number with the rewritten kernel
#   3. bench 512 fold=xla   - the schedule comparison at primary scale
#   4. novel-view bench     - re-run with the HLO-constant fix (HTTP 413)
#   5. composite bench      - re-run with the 1-chip rank clamp
#   6. profile_march        - per-stage march breakdown (now line-buffered)
#   7. profile_frame        - xprof steady-state trace
#   8. scaling sweep        - 1-chip strong-scaling row
# Every step has a hard timeout; JSON-validated steps keep output only when
# it parses. Log: /tmp/tpu_watcher_r3b.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
L=/tmp/tpu_watcher_r3b.log
step() {  # step <outfile> <timeout_s> <cmd...>
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" 2>>"$L" | tail -1 > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" "$out.tmp" \
        2>>"$L"; then
    mv "$out.tmp" "$out"; echo "ok: $out" >> "$L"
  else
    rm -f "$out.tmp"; echo "FAILED: $out" >> "$L"
  fi
}
for i in $(seq 1 200); do
  if timeout 120 python -c "
import jax
assert jax.devices()[0].platform == 'tpu'
import jax.numpy as jnp
assert float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()) > 0
" 2>/dev/null; then
    echo "tunnel alive at $(date -u) attempt $i" | tee -a "$L"
    date -u >> "$R/tpu_alive_r3.marker"
    if timeout 2400 python benchmarks/fold_microbench.py --grid 256 \
         --iters 3 --variants none,count,xla,pallas \
         > "$R/fold_microbench_tpu_r3.jsonl.tmp" 2>>"$L"; then
      mv "$R/fold_microbench_tpu_r3.jsonl.tmp" "$R/fold_microbench_tpu_r3.jsonl"
      echo "ok: fold_microbench" >> "$L"
      cat "$R/fold_microbench_tpu_r3.jsonl"
    else
      rm -f "$R/fold_microbench_tpu_r3.jsonl.tmp"
      echo "FAILED: fold_microbench" >> "$L"
    fi
    step "$R/bench_tpu_r3_512_newfold.json" 4000 env \
      SITPU_BENCH_PLATFORMS=tpu,tpu SITPU_BENCH_CHILD_TIMEOUT=1700 \
      python bench.py
    cat "$R/bench_tpu_r3_512_newfold.json" 2>/dev/null
    step "$R/bench_tpu_r3_512_xlafold.json" 2100 env \
      SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_FOLD=xla \
      SITPU_BENCH_CHILD_TIMEOUT=1700 python bench.py
    cat "$R/bench_tpu_r3_512_xlafold.json" 2>/dev/null
    step "$R/bench_tpu_r3_256_newfold.json" 2400 env SITPU_BENCH_GRID=256 \
      SITPU_BENCH_PLATFORMS=tpu,tpu python bench.py
    step "$R/novel_view_tpu_r3.json" 1500 \
      python benchmarks/novel_view_bench.py --iters 3
    step "$R/composite_tpu_r3.json" 1200 env SITPU_BENCH_REAL=1 \
      python benchmarks/composite_bench.py
    if timeout 1500 python -u benchmarks/profile_march.py 256 \
         2>>"$L" > "$R/profile_march_tpu_r3.txt.tmp"; then
      mv "$R/profile_march_tpu_r3.txt.tmp" "$R/profile_march_tpu_r3.txt"
      echo "ok: profile_march" >> "$L"
    else
      # keep partial output: the per-stage lines stream now, and even a
      # truncated breakdown is evidence
      mv "$R/profile_march_tpu_r3.txt.tmp" \
         "$R/profile_march_tpu_r3_partial.txt" 2>/dev/null
      echo "FAILED: profile_march (partial kept)" >> "$L"
    fi
    step "$R/profile_frame_tpu_r3.json" 1200 \
      python benchmarks/profile_frame.py --out "$R/trace_r3"
    step "$R/scaling_tpu_r3.json" 1800 env SITPU_BENCH_REAL=1 \
      python benchmarks/scaling_bench.py --grid 128 --frames 10
    echo "suite done at $(date -u)" >> "$L"
    exit 0
  fi
  sleep 120
done
echo "tunnel never returned" >> "$L"
exit 1
