"""Distributed per-phase breakdown (VERDICT weak #6): the production frame
is ONE jitted SPMD program (by design — XLA overlaps generate, all_to_all,
composite), so the session's timers can only see dispatch+fetch. This
diagnostic splits the chain into separately-jitted stages with
block_until_ready between them — the TPU analog of the reference's
per-phase timer taxonomy (total / all_to_all / composite / gather,
DistributedVolumeRenderer.kt:622-648). The split forces materialization
between stages, so the SUM here is an upper bound on the fused frame time
(also printed for comparison).

Inputs are chained across iterations so no execution-dedup layer can fake
the timings. Runs on the virtual CPU mesh by default; SITPU_BENCH_REAL=1
uses real devices.

Usage: python benchmarks/phase_bench.py [--ranks 8] [--grid 64] [--iters 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_PHASEBENCH_CHILD"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--grid", type=int, default=64)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--sim-steps", type=int, default=5)
    # HBM-traffic lever A/Bs (ISSUE 1): bf16 marched-volume copy,
    # time-fused sim stencil, and the scanned frame loop (N frames in
    # one executable; 0 = skip that measurement)
    ap.add_argument("--render-dtype", choices=("f32", "bf16"),
                    default="f32")
    ap.add_argument("--sim-fused", type=int, default=0)
    ap.add_argument("--scan-frames", type=int, default=0)
    # fleet-telemetry overhead guard (ISSUE 17): A/B the per-frame cost
    # of the obs plane (span + lineage + SLO observe) and fail if the
    # enabled path costs more than --obs-budget over the disabled one
    ap.add_argument("--obs-guard", action="store_true")
    ap.add_argument("--obs-budget", type=float, default=0.02)
    args = ap.parse_args()
    n = args.ranks

    from scenery_insitu_tpu.utils.backend import (pin_cpu_backend,
                                                  reexec_virtual_mesh)

    if os.environ.get(_CHILD) != "1" and os.environ.get(
            "SITPU_BENCH_REAL") != "1":
        reexec_virtual_mesh(n, _CHILD)

    import jax

    from scenery_insitu_tpu.utils.compat import shard_map

    if os.environ.get(_CHILD) == "1":
        pin_cpu_backend()

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.composite import composite_vdis
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (_exchange_columns,
                                                      _mxu_rank_generate,
                                                      distributed_vdi_step_mxu,
                                                      shard_volume)
    from scenery_insitu_tpu.sim import grayscott as gs

    mesh = make_mesh(n)
    axis = mesh.axis_names[0]
    g = args.grid
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.5, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_iters=2)
    comp_cfg = CompositeConfig(max_output_supersegments=args.k,
                               adaptive_iters=2)
    mcfg = SliceMarchConfig(
        matmul_dtype="f32" if jax.default_backend() != "tpu" else "bf16",
        render_dtype=args.render_dtype)
    spec = slicer.make_spec(cam, (g, g, g), mcfg, multiple_of=n)

    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.full((3,), 2.0 / g, jnp.float32)

    # --------------------------------------------------- split-stage fns
    from scenery_insitu_tpu import obs

    sim_fused = bool(args.sim_fused)
    if sim_fused and n > 1:
        # the fused Pallas stencil's periodic wrap is per-buffer, so it
        # cannot run on z-sharded state (sim/pallas_stencil.py) — the
        # multi-rank sim lever is the roll path, same as the session's
        # scan guard
        print("[phase_bench] --sim-fused needs a 1-rank mesh (the Pallas "
              "stencil is not partitionable); using the roll path",
              file=sys.stderr)
        obs.degrade("phase_bench.sim_fused", "pallas", "xla_roll",
                    "fused stencil needs a 1-rank mesh (periodic wrap "
                    "is per-buffer)", warn=False)
        sim_fused = False
    advance = gs.multi_step_fast if sim_fused else gs.multi_step
    sim_fn = jax.jit(lambda u, v: advance(
        gs.GrayScott(u, v, gs.GrayScottParams.create()), args.sim_steps))

    def gen(local, o, s, c):
        # (vdi, meta, axcam, thr', reuse') since the temporal-delta PR
        vdi, meta, *_ = _mxu_rank_generate(local, o, s, c, slicer, spec,
                                           tf, vdi_cfg, axis, n)
        return vdi.color, vdi.depth

    gen_fn = jax.jit(shard_map(
        gen, mesh=mesh, in_specs=(P(axis, None, None), P(), P(), P()),
        out_specs=(P(axis), P(axis)), check_vma=False))

    def exch(color, depth):
        return (_exchange_columns(color, n, axis),
                _exchange_columns(depth, n, axis))

    exch_fn = jax.jit(shard_map(
        exch, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)), check_vma=False))

    def comp(colors, depths):
        out = composite_vdis(colors, depths, comp_cfg)
        return out.color, out.depth

    comp_fn = jax.jit(shard_map(
        comp, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(None, None, None, axis), P(None, None, None, axis)),
        check_vma=False))

    fused = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, comp_cfg)

    st = gs.GrayScott.init((g, g, g))
    u = shard_volume(st.u, mesh)
    v = shard_volume(st.v, mesh)

    phases = {k: 0.0 for k in
              ("sim", "generate", "all_to_all", "composite", "gather",
               "fused_total")}

    def tick(key, fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        jax.block_until_ready(out)
        phases[key] += time.perf_counter() - t0
        return out

    # warm up every stage
    stw = sim_fn(u, v)
    cw, dw = gen_fn(stw.v, origin, spacing, cam)
    ce, de = exch_fn(cw, dw)
    comp_out = comp_fn(ce, de)
    fused_out = fused(stw.v, origin, spacing, cam)
    jax.block_until_ready((comp_out, fused_out))

    for it in range(args.iters):
        stp = tick("sim", sim_fn, u, v)
        u, v = stp.u, stp.v
        c, d = tick("generate", gen_fn, v, origin, spacing, cam)
        ce, de = tick("all_to_all", exch_fn, c, d)
        oc, od = tick("composite", comp_fn, ce, de)
        t0 = time.perf_counter()
        host = (jnp.asarray(oc).block_until_ready()
                if hasattr(oc, "block_until_ready") else oc)
        import numpy as _np
        _np.asarray(host)
        phases["gather"] += time.perf_counter() - t0
        vdi_f, _ = tick("fused_total", fused, v, origin, spacing, cam)

    ms = {k: round(t / args.iters * 1000, 2) for k, t in phases.items()}

    # scanned frame loop: sim+render frames rolled into ONE executable
    # (the session's scan_frames path) — per-frame ms against the eager
    # fused_total isolates the per-launch dispatch tax
    scan_ms = None
    if args.scan_frames > 1:
        from scenery_insitu_tpu.parallel.pipeline import frame_scan

        params = gs.GrayScottParams.create()
        # the same advance the eager phases measured (sim_fused already
        # downgraded to the roll path on multi-rank meshes above), so
        # scanloop isolates the launch lever and nothing else
        runner = frame_scan(
            fused, lambda s: advance(s, args.sim_steps),
            args.scan_frames)
        state = gs.GrayScott(u, v, params)
        # warm TWICE: the chained state's sharding/layout can differ
        # between the fresh inputs and the runner's own outputs, and the
        # second compilation must not land in the timed window
        for _ in range(2):
            (state, _, _), outs = runner(state, origin, spacing, cam,
                                         jnp.float32(0.0))
        jax.block_until_ready(outs[0].color)               # warm
        t0 = time.perf_counter()
        (state, _, _), outs = runner(state, origin, spacing, cam,
                                     jnp.float32(0.0))
        jax.block_until_ready(outs[0].color)
        scan_ms = round((time.perf_counter() - t0)
                        / args.scan_frames * 1000, 2)

    # obs plane A/B: the identical warm fused frame, once under a
    # disabled Recorder and once under an enabled one doing everything
    # Session.run does per frame (span + lineage instant + SLO observe).
    # The fleet-obs CI lane gates overhead_frac at --obs-budget (2%).
    from scenery_insitu_tpu.config import SLOConfig
    from scenery_insitu_tpu.obs.collector import lineage
    from scenery_insitu_tpu.obs.slo import SLOEngine

    obs_ab = {}
    saved_rec = obs.get_recorder()
    for mode in (False, True):
        rec = obs.Recorder(enabled=mode)
        obs.set_recorder(rec)
        slo = SLOEngine(SLOConfig(enabled=mode, frame_p99_ms=1e9), rec)
        t0 = time.perf_counter()
        for it in range(args.iters):
            t_f = time.perf_counter()
            with rec.span("frame", frame=it):
                out = fused(v, origin, spacing, cam)
                jax.block_until_ready(out[0].color)
            lineage("publish", "send", it)
            slo.observe("frame_ms", (time.perf_counter() - t_f) * 1e3,
                        frame=it)
        obs_ab["enabled_ms" if mode else "disabled_ms"] = round(
            (time.perf_counter() - t0) / args.iters * 1000, 2)
    obs.set_recorder(saved_rec)
    obs_ab["overhead_frac"] = round(
        obs_ab["enabled_ms"] / max(obs_ab["disabled_ms"], 1e-9) - 1.0, 4)

    # the fused step covers generate+all_to_all+composite ONLY (sim runs
    # before it, gather after) — compare like with like
    split_render = sum(ms[k] for k in ("generate", "all_to_all", "composite"))
    from scenery_insitu_tpu.obs.device import device_cost

    print(json.dumps({
        "metric": f"phase_breakdown_{n}ranks_{g}c",
        "unit": "ms/frame",
        "phases": ms,
        "split_render_ms": round(split_render, 2),
        "fused_render_ms": ms["fused_total"],
        "overlap_gain": round(split_render / max(ms["fused_total"], 1e-9), 2),
        "levers": {"render_dtype": args.render_dtype,
                   "sim_fused": sim_fused,    # EFFECTIVE (multi-rank
                   "scan_frames": args.scan_frames,  # downgrades to roll)
                   "scanloop_ms_per_frame": scan_ms},
        "obs_overhead": obs_ab,
        # device-cost truth + everything that did not run as configured
        # (same record shape bench.py embeds — see docs/OBSERVABILITY.md)
        "cost_analysis": {"fused_step": device_cost(
            fused, v, origin, spacing, cam)},
        "degradations": obs.ledger(),
        "backend": jax.default_backend(),
    }))

    if args.obs_guard and obs_ab["overhead_frac"] > args.obs_budget:
        print(f"[phase_bench] obs overhead {obs_ab['overhead_frac']:.2%} "
              f"exceeds budget {args.obs_budget:.0%}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
