"""BASELINE.md Configs 1-5 as one runnable harness — one JSON line each.

| # | Workload (full scale)                                   | Ranks |
|---|---------------------------------------------------------|-------|
| 1 | Gray-Scott 128³, single rank                            | 1     |
| 2 | Gray-Scott 512³, VDI generate + composite               | 8     |
| 3 | Vortex-in-cell Navier-Stokes (vorticity volume) 256³    | 4     |
| 4 | Lennard-Jones MD, 1M particles, sphere render           | 8     |
| 5 | Hybrid: vortex volume + 500k tracers concurrently       | 8     |

Every config runs through InSituSession — the same frame loop, engine
selection and sinks path a production run uses — so the numbers cover
sim advance + render + fetch, not a stripped kernel.

Scale: ``--scale full`` uses the BASELINE sizes (needs real chips);
``--scale small`` (default) shrinks grids 4× and particle counts 50× so
the whole matrix runs on one host / the CI virtual mesh.

Backend: each config runs in its own subprocess. A config whose rank
count exceeds the available devices runs on a virtual CPU mesh (the
driver machine has one TPU chip; multi-rank numbers are then functional
checks, not perf). The parent process never touches a JAX backend
(this environment's TPU shim can hang backend init — see bench.py).
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_CONFIGS_CHILD"

CONFIGS = {
    1: dict(kind="gray_scott", grid=128, ranks=1),
    2: dict(kind="gray_scott", grid=512, ranks=8),
    3: dict(kind="vortex", grid=256, ranks=4),
    4: dict(kind="lennard_jones", particles=1_000_000, ranks=8),
    5: dict(kind="hybrid", grid=256, particles=500_000, ranks=8),
}


def _scaled(c, scale):
    c = dict(c)
    if scale == "small":
        if "grid" in c:
            c["grid"] = max(32, c["grid"] // 4)
        if "particles" in c:
            c["particles"] = max(2000, c["particles"] // 50)
    return c


def run_config(n: int, scale: str, frames: int,
               force_ranks: int = 0) -> dict:
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession
    import jax

    c = _scaled(CONFIGS[n], scale)
    if force_ranks:
        # single-chip hardware captures of the multi-rank configs: the
        # workload (grid/particles) stays full-scale, only the mesh
        # shrinks — an honest per-family device number, not Config N's
        # distributed figure. Clamp-only: forcing ranks UP would demote
        # an intended hardware run to the virtual CPU mesh silently.
        c["ranks"] = min(force_ranks, c["ranks"])
    g = c.get("grid", 0)
    volume_vdi = c["kind"] in ("gray_scott", "vortex")
    overrides = [
        f"sim.kind={c['kind']}",
        f"mesh.num_devices={c['ranks']}",
        "sim.steps_per_frame=5",
        "vdi.max_supersegments=16",
        # volume + hybrid configs: flagship engine + carried temporal
        # thresholds (mxu also runs on the CPU mesh — make_spec downgrades
        # the matmul dtype); hybrid gained temporal support in round 3, so
        # Config 5 now pays ONE march/frame like the plain VDI path
        ("vdi.adaptive_mode=temporal"
         if volume_vdi or c["kind"] == "hybrid"
         else "vdi.adaptive_mode=histogram"),
        "composite.max_output_supersegments=16",
    ]
    if volume_vdi:
        overrides.append("slicer.engine=mxu")
    if g:
        overrides.append(f"sim.grid=[{g},{g},{g}]")
    if "particles" in c:
        overrides.append(f"sim.num_particles={c['particles']}")
    cfg = FrameworkConfig().with_overrides(*overrides)

    sess = InSituSession(cfg)
    sess.run(2)                                      # warmup + compile
    t0 = time.perf_counter()
    payload = sess.run(frames)
    jax.block_until_ready(payload.get("vdi_color", payload.get("image")))
    dt = (time.perf_counter() - t0) / frames
    dev = jax.devices()[0]
    return {
        "metric": f"baseline_config_{n}",
        "workload": c,
        "mode": sess.mode,
        "engine": sess.engine,
        "ms_per_frame": round(dt * 1000.0, 2),
        "fps": round(1.0 / dt, 2),
        "frames": frames,
        "platform": dev.platform,
        "n_devices": jax.device_count(),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--frames", type=int, default=5)
    ap.add_argument("--scale", choices=("small", "full"), default="small")
    ap.add_argument("--timeout", type=int, default=1200,
                    help="per-config subprocess timeout (s)")
    ap.add_argument("--force-ranks", type=int, default=0,
                    help="clamp every config's mesh to N ranks (0=off): "
                    "full-scale single-chip family captures on a 1-chip "
                    "tunnel")
    args = ap.parse_args()

    from scenery_insitu_tpu.utils.backend import probe_tpu, virtual_mesh_env

    tpu_devices = probe_tpu()
    ok_count = 0
    for n in (int(x) for x in args.configs.split(",")):
        ranks = (min(args.force_ranks, CONFIGS[n]["ranks"])
                 if args.force_ranks else CONFIGS[n]["ranks"])
        if tpu_devices >= ranks:
            env = dict(os.environ)          # real chips
        else:
            from scenery_insitu_tpu import obs

            obs.degrade("bench.platform", f"tpu x{ranks}",
                        "cpu_virtual_mesh",
                        f"config {n}: probe found {tpu_devices} TPU "
                        f"device(s), need {ranks}", warn=False)
            env = virtual_mesh_env(max(ranks, 1))
            env["_SITPU_PIN_CPU"] = "1"
        env[_CHILD] = (f"{n},{args.scale},{args.frames},"
                       f"{args.force_ranks}")
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, timeout=args.timeout,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT)
            out = p.stdout.decode("utf-8", "replace").strip()
            line = next((l for l in reversed(out.splitlines())
                         if l.startswith("{")), None)
            if p.returncode == 0 and line:
                print(line, flush=True)
                if '"error"' not in line:
                    ok_count += 1
            else:
                print(json.dumps({"metric": f"baseline_config_{n}",
                                  "error": f"rc={p.returncode}",
                                  "tail": out[-300:]}), flush=True)
        except subprocess.TimeoutExpired:
            from scenery_insitu_tpu import obs

            obs.degrade("bench.config_run", f"config {n}", "error_row",
                        f"child timed out after {args.timeout}s",
                        warn=False)
            print(json.dumps({"metric": f"baseline_config_{n}",
                              "error": f"timeout {args.timeout}s"}),
                  flush=True)
    if ok_count == 0:
        # all configs failed: a caller treating exit 0 as a done-marker
        # (the TPU watcher) must retry, not archive an all-error artifact
        sys.exit(1)


if __name__ == "__main__":
    if _CHILD in os.environ:
        if os.environ.get("_SITPU_PIN_CPU") == "1":
            from scenery_insitu_tpu.utils.backend import pin_cpu_backend
            pin_cpu_backend()
        parts = os.environ[_CHILD].split(",")
        n, scale, frames = parts[0], parts[1], parts[2]
        force = int(parts[3]) if len(parts) > 3 else 0
        print(json.dumps(run_config(int(n), scale, int(frames),
                                    force_ranks=force)),
              flush=True)
    else:
        main()
