"""Micro-roofline: what does THIS chip actually deliver?

The round-5 window-1 flagship capture reported 29 GB of HBM traffic per
419 ms frame — 69 GB/s achieved against an assumed 819 GB/s v5e peak,
with MFU at 0.5%. Two very different diagnoses fit that datapoint:

  (a) our kernels are occupancy/latency-bound and leave ~10x bandwidth
      on the table (fixable by schedule work), or
  (b) the axon-virtualized chip simply delivers far less than the
      data-sheet peak, and the frame is already near ITS roofline
      (schedule A/Bs will all come back flat — which is exactly what
      rounds 3-5 measured: pallas 420 ms, xla 482 ms, pallas_seg
      419 ms).

This 30-second harness settles it with four primitives, each timed on
device via async dispatch + one final block:

  copy     y = x                 (pure HBM stream, 2 bytes/elem-byte)
  axpy     y = 2x + y            (stream + 1 flop)
  stencil  7-point Gray-Scott-shaped Laplacian on 512^3 (the sim's
           memory pattern: ~3 arrays of traffic per step when fused)
  sim      10 real Gray-Scott steps at 512^3 (the flagship's in-situ
           component, exactly as bench.py runs it)
  matmul   8k x 8k x 8k bf16 (the MXU sanity point)

Prints one JSON line: achieved GB/s per primitive + TFLOP/s for the
matmul + the implied best-case frame time for the flagship's measured
29 GB, so the next capture can say "the frame is at N% of the COPY
roofline" instead of quoting a data-sheet number the chip never hits.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _time(fn, *args, iters=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    n = int(os.environ.get("SITPU_HBM_BENCH_MB", "512")) * (1 << 20) // 4
    x = jnp.arange(n, dtype=jnp.float32)  # 512 MB by default
    nbytes = x.size * 4

    gb = 1e9

    # Incremental artifact (ROADMAP item 1: the round-4/5 watcher runs
    # died mid-tunnel and left DANGLING `.partial` stdout dumps that no
    # tooling could parse). With SITPU_HBM_BENCH_OUT set, every landed
    # primitive ATOMICALLY rewrites a well-formed JSON artifact with
    # {"partial": true, "points": {...so far...}} — a timeout at any
    # instant leaves a loadable file whose completed points still carry
    # their numbers; the final summary rewrites it with partial: false.
    out_path = os.environ.get("SITPU_HBM_BENCH_OUT", "")
    points = {}

    def _write_artifact(record):
        if not out_path:
            return
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, out_path)

    def partial(**kv):
        # one line per landed primitive: if the tunnel window closes
        # mid-run, the watcher keeps stdout as <artifact>.failed and the
        # primitives that DID run still carry their numbers
        print(json.dumps({"partial": kv}), flush=True)
        points.update(kv)
        _write_artifact({"metric": "hbm_micro_roofline",
                         "device": dev.device_kind,
                         "platform": dev.platform,
                         "partial": True, "points": dict(points)})

    # dispatch tax first (trivial compiles, and it qualifies every
    # number that follows): a tiny jitted op called back-to-back with
    # async dispatch exactly like the bench frame loop, then a dependent
    # chain (pipelined transports hide round trips; a synchronous shim
    # cannot)
    tiny = jax.jit(lambda s: s + 1.0)
    t_tiny = _time(tiny, jnp.float32(0.0), iters=100, warmup=3)
    partial(dispatch_tiny_us=round(t_tiny * 1e6, 1))

    def chain(s, n=10):
        for _ in range(n):
            s = tiny(s)
        return s
    t_chain = _time(chain, jnp.float32(0.0), iters=5) / 10.0
    partial(dispatch_chain_us=round(t_chain * 1e6, 1))

    copy = jax.jit(lambda a: a + 0.0)
    axpy = jax.jit(lambda a, b: 2.0 * a + b)
    t_copy = _time(copy, x)                      # read + write
    partial(copy_gbps=round(2 * nbytes / t_copy / gb, 1))
    t_axpy = _time(axpy, x, x)                   # 2 reads + write
    partial(axpy_gbps=round(3 * nbytes / t_axpy / gb, 1))

    m = 8192
    a = jnp.zeros((m, m), jnp.bfloat16) + 0.5
    mm = jax.jit(lambda p, q: (p @ q).astype(jnp.bfloat16))
    t_mm = _time(mm, a, a, iters=5)
    partial(matmul_tflops=round(2.0 * m ** 3 / t_mm / 1e12, 1))

    # the sim's shape of traffic: 7-point Laplacian over 512^3
    g = int(os.environ.get("SITPU_HBM_BENCH_GRID", "512"))
    u = jnp.zeros((g, g, g), jnp.float32) + 0.25

    @jax.jit
    def stencil(a):
        return (jnp.roll(a, 1, 0) + jnp.roll(a, -1, 0)
                + jnp.roll(a, 1, 1) + jnp.roll(a, -1, 1)
                + jnp.roll(a, 1, 2) + jnp.roll(a, -1, 2) - 6.0 * a)

    t_sten = _time(stencil, u, iters=5)          # >= read + write
    partial(stencil_gbps=round(2 * 4 * g ** 3 / t_sten / gb, 1))

    # LAST: the real sim's 10 steps — multi_step_fast walks Mosaic
    # compile probes for the fused stencil schedules, much the costliest
    # compiles here; everything decisive has already been printed if the
    # window closes on it
    from scenery_insitu_tpu.sim import grayscott as gs
    st = gs.GrayScott.init((g, g, g))
    sim10 = jax.jit(lambda s: gs.multi_step_fast(s, 10))
    t_sim = _time(sim10, st, iters=3)
    partial(sim10_ms=round(t_sim * 1e3, 2))

    sim_bytes = 10 * 4 * g ** 3 * 4.0            # 10 steps x (r+w of u,v)
    out = {
        "metric": "hbm_micro_roofline",
        "device": dev.device_kind, "platform": dev.platform,
        "partial": False,
        "copy_gbps": round(2 * nbytes / t_copy / gb, 1),
        "axpy_gbps": round(3 * nbytes / t_axpy / gb, 1),
        "stencil_gbps": round(2 * 4 * g ** 3 / t_sten / gb, 1),
        "sim10_ms": round(t_sim * 1e3, 2),
        "sim10_gbps_floor": round(sim_bytes / t_sim / gb, 1),
        "matmul_tflops": round(2.0 * m ** 3 / t_mm / 1e12, 1),
        "dispatch_tiny_us": round(t_tiny * 1e6, 1),
        "dispatch_chain_us": round(t_chain * 1e6, 1),
        "buf_mb": nbytes >> 20,
        "flagship_frame_gb": 29.0,
        "implied_frame_ms_at_copy_bw": round(
            29.0 * gb / (2 * nbytes / t_copy) * 1e3, 1),
    }
    print(json.dumps(out), flush=True)
    # the completed artifact keeps the incremental schema's "points"
    # nesting alongside the flat summary keys, so a reader written
    # against either layout works on both partial and final files
    _write_artifact({**out, "points": dict(points)})


if __name__ == "__main__":
    main()
