"""Multi-view render benchmark CLI (≅ the reference's single-GPU benchmark
modes: 9 camera angles x fps CSV + screenshots — VolumeFromFileExample.kt:
765-795, DistributedVolumes.kt:527-623 — plus the camera flythrough
recorder :631-745).

Usage:
  python benchmarks/render_bench.py [--dataset procedural|gray_scott|<name>]
      [--grid 64] [--data-dir DIR] [--engine auto|mxu|gather]
      [--mode plain|vdi] [--views 9] [--frames 5] [--width 320]
      [--height 240] [--k 12] [--out-dir bench_out] [--flythrough N]
Prints the fps CSV to stdout and writes screenshots (and flythrough frames)
under --out-dir.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default="procedural")
    p.add_argument("--grid", type=int, default=64)
    p.add_argument("--data-dir", default=None,
                   help="directory with <dataset>.raw for real datasets")
    p.add_argument("--engine", default="auto")
    p.add_argument("--mode", choices=["plain", "vdi"], default="plain")
    p.add_argument("--views", type=int, default=9)
    p.add_argument("--frames", type=int, default=5)
    p.add_argument("--width", type=int, default=320)
    p.add_argument("--height", type=int, default=240)
    p.add_argument("--k", type=int, default=12)
    p.add_argument("--steps", type=int, default=128)
    p.add_argument("--out-dir", default="bench_out")
    p.add_argument("--flythrough", type=int, default=0,
                   help="also record an N-frame orbit flythrough")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import (RenderConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import (load_dataset,
                                                procedural_volume)
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.raycast import raycast
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
    from scenery_insitu_tpu.runtime.benchmark import (benchmark_views,
                                                      fps_csv,
                                                      interpolate_path,
                                                      record_flythrough)

    if args.data_dir:
        vol = load_dataset(args.dataset, args.data_dir)
    elif args.dataset == "gray_scott":
        from scenery_insitu_tpu.core.volume import Volume
        from scenery_insitu_tpu.sim import grayscott as gs
        st = gs.multi_step(gs.GrayScott.init((args.grid,) * 3), 200)
        vol = Volume.centered(st.field)
    else:
        vol = procedural_volume(args.grid, kind="blobs")
    tf = for_dataset(args.dataset)
    cam0 = Camera.create((0.0, 0.5, 2.8), fov_y_deg=50.0, near=0.3, far=20.0)
    engine = slicer.resolve_engine(args.engine)
    w, h = args.width, args.height

    # one jitted render per march regime (mxu) or a single jit (gather)
    if engine == "mxu":
        cfg = SliceMarchConfig()
        compiled = {}

        # the volume rides as a jit ARGUMENT: a closed-over array bakes
        # into the HLO as a literal, and a >=256^3 grid then exceeds the
        # axon shim's remote-compile request limit (HTTP 413)
        def render_plain(cam):
            regime = slicer.choose_axis(cam)
            fn = compiled.get(("p", regime))
            if fn is None:
                spec = slicer.make_spec(cam, vol.data.shape, cfg, regime)
                fn = jax.jit(lambda v, c: slicer.raycast_mxu(
                    v, tf, c, w, h, spec).image)
                compiled[("p", regime)] = fn
            return fn(vol, cam)

        def render_vdi_step(cam):
            regime = slicer.choose_axis(cam)
            fn = compiled.get(("v", regime))
            if fn is None:
                spec = slicer.make_spec(cam, vol.data.shape, cfg, regime)
                fn = jax.jit(lambda v, c: slicer.generate_vdi_mxu(
                    v, tf, c, spec,
                    VDIConfig(max_supersegments=args.k,
                              adaptive_iters=2))[0])
                compiled[("v", regime)] = fn
            return fn(vol, cam)
    else:
        rcfg = RenderConfig(width=w, height=h, max_steps=args.steps)
        plain_j = jax.jit(
            lambda v, c: raycast(v, tf, c, w, h, rcfg).image)
        vdi_j = jax.jit(
            lambda v, c: generate_vdi(v, tf, c, w, h,
                                      VDIConfig(max_supersegments=args.k,
                                                adaptive_iters=2),
                                      max_steps=args.steps)[0])
        render_plain = lambda c: plain_j(vol, c)
        render_vdi_step = lambda c: vdi_j(vol, c)

    if args.mode == "plain":
        render, to_image = render_plain, None
    else:
        render = render_vdi_step
        to_image = lambda vdi: render_vdi_same_view(vdi)

    shots = os.path.join(args.out_dir, f"{args.dataset}_{engine}_{args.mode}")
    results = benchmark_views(render, cam0, num_views=args.views,
                              frames=args.frames, screenshot_dir=shots,
                              to_image=to_image)
    csv = fps_csv(results)
    sys.stdout.write(csv)
    os.makedirs(args.out_dir, exist_ok=True)
    csv_path = os.path.join(
        args.out_dir, f"fps_{args.dataset}_{engine}_{args.mode}.csv")
    with open(csv_path, "w") as f:
        f.write(csv)

    if args.flythrough:
        keys = [orbit(cam0, jnp.float32(a))
                for a in (0.0, 1.5, 3.0, 4.5, 6.0)]
        path = interpolate_path(keys, max(1, args.flythrough // 4))
        n = record_flythrough(render_plain, path,
                              os.path.join(args.out_dir, "flythrough"))
        print(f"flythrough: {n} frames", file=sys.stderr)


if __name__ == "__main__":
    main()
