"""Phase profiler for the MXU slice-march frame (diagnostic; VERDICT weak
#6): times each stage of the flagship pipeline separately so optimization
targets facts, not guesses. Usage: python benchmarks/profile_march.py
[grid]."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, n=3, label=""):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1000
    print(f"{label:42s} {dt:9.1f} ms", flush=True)
    return dt


def main():
    if os.environ.get("SITPU_CPU") == "1":
        # JAX_PLATFORMS=cpu alone does not stop the axon shim's hang on a
        # dead tunnel — same pin every other harness uses
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops import supersegments as ss
    from scenery_insitu_tpu.ops.composite import composite_vdis
    from scenery_insitu_tpu.sim import grayscott as gs

    grid = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    k = 16
    ad_iters = 2
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    spec = slicer.make_spec(cam, (grid, grid, grid), SliceMarchConfig())
    print(f"grid={grid} spec ni={spec.ni} nj={spec.nj} chunk={spec.chunk} "
          f"dtype={spec.matmul_dtype} fold={spec.fold} "
          f"backend={jax.default_backend()}", flush=True)

    st = gs.GrayScott.init((grid, grid, grid))
    st = gs.multi_step(st, 30)
    jax.block_until_ready(st.u)
    vol = Volume.centered(st.field, extent=2.0)

    timeit(jax.jit(lambda u, v: gs.multi_step(gs.GrayScott(u, v, st.params),
                                              10).u),
           st.u, st.v, label="sim advance x10")

    # march with trivial consume: measures resample matmuls + TF + rgba prep
    def march_sum(data):
        v = Volume.centered(data, extent=2.0)
        axcam = slicer.make_axis_camera(v, cam, spec)
        def consume(c, rgba, t0, t1):
            return c + rgba.sum((0, 1))
        return slicer.slice_march(v, tf, axcam, spec, consume,
                                  jnp.zeros((spec.nj, spec.ni)))
    timeit(jax.jit(march_sum), vol.data, label="march only (sum consume)")

    # march with no TF: isolates the TF lookup cost
    def march_no_tf(data):
        v = Volume.centered(data, extent=2.0)
        axcam = slicer.make_axis_camera(v, cam, spec)
        ident = lambda val: (jnp.stack([val] * 3, -1), val * 0.3)
        def consume(c, rgba, t0, t1):
            return c + rgba.sum((0, 1))
        return slicer.slice_march(v, ident, axcam, spec, consume,
                                  jnp.zeros((spec.nj, spec.ni)))
    timeit(jax.jit(march_no_tf), vol.data, label="march, identity TF")

    # one counting pass
    def count_pass(data):
        v = Volume.centered(data, extent=2.0)
        axcam = slicer.make_axis_camera(v, cam, spec)
        thr = jnp.full((spec.nj, spec.ni), 0.1, jnp.float32)
        def consume(cst, rgba, t0, t1):
            for i in range(rgba.shape[0]):
                cst = ss.push_count(cst, thr, rgba[i])
            return cst
        return slicer.slice_march(v, tf, axcam, spec, consume,
                                  ss.init_count(spec.nj, spec.ni)).count
    timeit(jax.jit(count_pass), vol.data, label="one counting march")

    # one writing march (fixed threshold)
    def write_pass(data):
        v = Volume.centered(data, extent=2.0)
        axcam = slicer.make_axis_camera(v, cam, spec)
        thr = jnp.full((spec.nj, spec.ni), 0.1, jnp.float32)
        def consume(sst, rgba, t0, t1):
            for i in range(rgba.shape[0]):
                sst = ss.push(sst, k, thr, rgba[i], t0[i], t1[i])
            return sst
        stf = slicer.slice_march(v, tf, axcam, spec, consume,
                                 ss.init_state(k, spec.nj, spec.ni))
        return ss.finalize(stf)
    timeit(jax.jit(write_pass), vol.data, label="one writing march")

    # the round-4 fold schedules head to head: ONE write march each
    # (adaptive off -> fixed threshold, no counting pass), guarded per
    # variant so a Mosaic rejection can't kill the rest of the profile
    folds = ["xla", "seg"]
    if jax.default_backend() == "tpu":
        folds += ["pallas_seg", "pallas_fused"]
    for fname in folds:
        try:
            spec_f = slicer.make_spec(cam, (grid, grid, grid),
                                      SliceMarchConfig(fold=fname))

            def wf(data, spec_f=spec_f):
                v = Volume.centered(data, extent=2.0)
                vdi, _, _ = slicer.generate_vdi_mxu(
                    v, tf, cam, spec_f,
                    VDIConfig(max_supersegments=k, adaptive=False,
                              threshold=0.1))
                return vdi.color

            timeit(jax.jit(wf), vol.data, label=f"write march fold={fname}")
        except Exception as e:
            print(f"write march fold={fname}: FAILED "
                  f"{type(e).__name__}: {str(e)[:150]}", flush=True)

    # full VDI generation (ad_iters counting + 1 write)
    def gen(data):
        v = Volume.centered(data, extent=2.0)
        vdi, meta, _ = slicer.generate_vdi_mxu(
            v, tf, cam, spec, VDIConfig(max_supersegments=k,
                                        adaptive_iters=ad_iters))
        return vdi.color
    timeit(jax.jit(gen), vol.data, label=f"generate_vdi_mxu (ad={ad_iters})")

    # composite N=1
    def comp(color, depth):
        return composite_vdis(color[None], depth[None],
                              CompositeConfig(max_output_supersegments=k,
                                              adaptive_iters=ad_iters)).color
    vdi, _, _ = jax.jit(lambda d: slicer.generate_vdi_mxu(
        Volume.centered(d, extent=2.0), tf, cam, spec,
        VDIConfig(max_supersegments=k, adaptive_iters=ad_iters)))(vol.data)
    timeit(jax.jit(comp), vdi.color, vdi.depth, label="composite (N=1)")


if __name__ == "__main__":
    main()
