#!/bin/bash
# Round-3 TPU watcher: poll the axon tunnel; the moment it answers, capture
# every TPU number VERDICT.md round 2 asked for (items 1 and 6):
#   - flagship bench, TPU defaults (512^3, 25 frames)   -> bench_tpu_r3_512.json
#   - histogram-mode comparison at the same scale       -> bench_tpu_r3_hist.json
#   - 256^3 run comparable to the round-2 capture       -> bench_tpu_r3_256.json
#   - novel-view client vs portable gather renderer     -> novel_view_tpu_r3.json
#   - composite bench on the real chip                  -> composite_tpu_r3.json
#   - steady-state march profile (where the ms go)      -> profile_march_tpu_r3.txt
# A dead tunnel HANGS backend access, so every probe/bench gets a hard
# timeout. Results land in benchmarks/results/ for commit.
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
# Run one suite step; only keep the output file if the command succeeded
# AND produced parseable JSON (a timed-out/failed step must not leave a
# file that reads as a captured measurement).
step() {  # step <outfile> <timeout_s> <cmd...>
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" 2>>/tmp/tpu_watcher_r3.log | tail -1 > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" "$out.tmp" \
        2>>/tmp/tpu_watcher_r3.log; then
    mv "$out.tmp" "$out"; echo "ok: $out" >> /tmp/tpu_watcher_r3.log
  else
    rm -f "$out.tmp"; echo "FAILED: $out" >> /tmp/tpu_watcher_r3.log
  fi
}
for i in $(seq 1 140); do
  if timeout 120 python -c "
import jax
assert jax.devices()[0].platform == 'tpu'
import jax.numpy as jnp
assert float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()) > 0
" 2>/dev/null; then
    echo "tunnel alive at $(date -u) attempt $i" | tee /tmp/tpu_watcher_r3.log
    date -u > "$R/tpu_alive_r3.marker"
    # outer window must fit BOTH tpu attempts (pallas + xla-fold rescue)
    step "$R/bench_tpu_r3_512.json" 4000 env \
      SITPU_BENCH_PLATFORMS=tpu,tpu SITPU_BENCH_CHILD_TIMEOUT=1700 \
      python bench.py
    cat "$R/bench_tpu_r3_512.json" 2>/dev/null
    step "$R/bench_tpu_r3_hist.json" 2100 env \
      SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_ADAPTIVE_MODE=histogram \
      SITPU_BENCH_CHILD_TIMEOUT=1700 python bench.py
    step "$R/bench_tpu_r3_256.json" 2400 env SITPU_BENCH_GRID=256 \
      SITPU_BENCH_PLATFORMS=tpu,tpu python bench.py
    cat "$R/bench_tpu_r3_256.json" 2>/dev/null
    step "$R/novel_view_tpu_r3.json" 1500 \
      python benchmarks/novel_view_bench.py --iters 3
    step "$R/composite_tpu_r3.json" 1200 env SITPU_BENCH_REAL=1 \
      python benchmarks/composite_bench.py
    if timeout 1200 python benchmarks/profile_march.py 256 \
         2>>/tmp/tpu_watcher_r3.log > "$R/profile_march_tpu_r3.txt.tmp"; then
      mv "$R/profile_march_tpu_r3.txt.tmp" "$R/profile_march_tpu_r3.txt"
    else
      rm -f "$R/profile_march_tpu_r3.txt.tmp"
    fi
    step "$R/profile_frame_tpu_r3.json" 1200 \
      python benchmarks/profile_frame.py --out "$R/trace_r3"
    step "$R/scaling_tpu_r3.json" 1800 env SITPU_BENCH_REAL=1 \
      python benchmarks/scaling_bench.py --grid 128 --frames 10
    echo "suite done at $(date -u)" >> /tmp/tpu_watcher_r3.log
    exit 0
  fi
  sleep 180
done
echo "tunnel never returned" > /tmp/tpu_watcher_r3.log
exit 1
