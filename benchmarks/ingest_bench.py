"""Shared-memory ingest micro-benchmark (≅ the reference's IPC transport
matrix: sem/heap/sysv/mmap/fifo/tcp × 1 KB–1 GB × 5000 iters,
src/test/cpp/benchmark/test_params.hpp:21-44, and the C++↔JVM TestConsumer
harness). Measures the TPU-relevant chain: producer memcpy → shm → consumer
(zero-copy pin vs copy) → optional device_put to HBM.

Usage: python benchmarks/ingest_bench.py [--iters 200] [--max-mb 64]
       [--device]
Prints one row per size: publish, consume(copy), consume(pin), and with
--device the host→HBM hop.
"""

from __future__ import annotations

import argparse
import time
import uuid

import numpy as np

from scenery_insitu_tpu.ingest.shm import ShmConsumer, ShmProducer


def bench_size(nfloats: int, iters: int, device: bool):
    shape = (nfloats,)
    ch = f"/sitpu_bench_{uuid.uuid4().hex[:8]}"
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    frame = np.random.default_rng(0).random(nfloats).astype(np.float32)
    mb = frame.nbytes / 1e6
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
        t_pub = (time.perf_counter() - t0) / iters

        t_copy = t_pin = t_dev = float("nan")
        # consume with copy
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
            cons.latest(timeout_ms=1000)
        t_copy = (time.perf_counter() - t0) / iters - t_pub

        # consume zero-copy pin/release
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
            pinned, _ = cons.latest(timeout_ms=1000, copy=False)
            cons.release(pinned.slot)
        t_pin = (time.perf_counter() - t0) / iters - t_pub

        if device:
            import jax
            t0 = time.perf_counter()
            for _ in range(iters):
                prod.publish(frame)
                arr, _ = cons.latest(timeout_ms=1000)
                jax.device_put(arr).block_until_ready()
            t_dev = (time.perf_counter() - t0) / iters - t_pub

        def mbs(t):
            return mb / t if t > 0 else float("inf")

        print(f"{frame.nbytes:>12} B: publish {mbs(t_pub):9.0f} MB/s  "
              f"consume+copy {mbs(t_copy):9.0f} MB/s  "
              f"consume+pin {mbs(t_pin):9.0f} MB/s"
              + (f"  +device_put {mbs(t_dev):9.0f} MB/s" if device else ""))
    finally:
        cons.close()
        prod.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--max-mb", type=float, default=64.0)
    ap.add_argument("--device", action="store_true",
                    help="include the host->HBM device_put hop")
    args = ap.parse_args()

    n = 256
    while n * 4 <= args.max_mb * 1e6:
        bench_size(n, max(args.iters, 3), args.device)
        n *= 4


if __name__ == "__main__":
    main()
