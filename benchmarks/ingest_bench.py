"""IPC ingest micro-benchmark (≅ the reference's transport matrix:
sem/heap/sysv/mmap/fifo/tcp × 1 KB–1 GB × 5000 iters,
src/test/cpp/benchmark/test_params.hpp:21-44, test_producer.cpp,
test_consumer.cpp, and the C++↔JVM TestConsumer harness).

Transports benchmarked here:
- shm ring (the framework's C++ transport): publish, consume(copy),
  consume(zero-copy pin) — the TPU-relevant chain, optionally + device_put
  to HBM (--device).
- mmap file, FIFO pipe, TCP loopback (--matrix): the classical alternatives
  the reference measures, to show why the shm ring is the default.

Usage: python benchmarks/ingest_bench.py [--iters 200] [--max-mb 64]
       [--device] [--matrix]
Prints one row per size per transport, MB/s per hop.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
import time
import uuid

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scenery_insitu_tpu.ingest.shm import ShmConsumer, ShmProducer  # noqa: E402


def bench_size(nfloats: int, iters: int, device: bool):
    shape = (nfloats,)
    ch = f"/sitpu_bench_{uuid.uuid4().hex[:8]}"
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    frame = np.random.default_rng(0).random(nfloats).astype(np.float32)
    mb = frame.nbytes / 1e6
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
        t_pub = (time.perf_counter() - t0) / iters

        t_copy = t_pin = t_dev = float("nan")
        # consume with copy
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
            cons.latest(timeout_ms=1000)
        t_copy = (time.perf_counter() - t0) / iters - t_pub

        # consume zero-copy pin/release
        t0 = time.perf_counter()
        for _ in range(iters):
            prod.publish(frame)
            pinned, _ = cons.latest(timeout_ms=1000, copy=False)
            cons.release(pinned.slot)
        t_pin = (time.perf_counter() - t0) / iters - t_pub

        if device:
            import jax
            t0 = time.perf_counter()
            for _ in range(iters):
                prod.publish(frame)
                arr, _ = cons.latest(timeout_ms=1000)
                jax.device_put(arr).block_until_ready()
            t_dev = (time.perf_counter() - t0) / iters - t_pub

        def mbs(t):
            return mb / t if t > 0 else float("inf")

        print(f"{frame.nbytes:>12} B: publish {mbs(t_pub):9.0f} MB/s  "
              f"consume+copy {mbs(t_copy):9.0f} MB/s  "
              f"consume+pin {mbs(t_pin):9.0f} MB/s"
              + (f"  +device_put {mbs(t_dev):9.0f} MB/s" if device else ""))
    finally:
        cons.close()
        prod.close()


def bench_mmap(nfloats: int, iters: int) -> float:
    """Round-trip through an mmapped file (≅ the PosixMemory strategy,
    reference benchmark/TestConsumer.kt:88-143). Returns seconds/frame."""
    import mmap

    path = f"/dev/shm/sitpu_mmap_{uuid.uuid4().hex[:8]}"
    frame = np.random.default_rng(0).random(nfloats).astype(np.float32)
    try:
        with open(path, "wb+") as f:
            f.truncate(frame.nbytes)
            mm = mmap.mmap(f.fileno(), frame.nbytes)
        view = np.frombuffer(mm, np.float32)
        t0 = time.perf_counter()
        for _ in range(iters):
            view[:] = frame                      # producer write
            _ = view.copy()                      # consumer read
        dt = (time.perf_counter() - t0) / iters
        del view                # drop the exported buffer so close() works
        mm.close()
        return dt
    finally:
        os.unlink(path)


def bench_fifo(nfloats: int, iters: int) -> float:
    """Round-trip through a named pipe (≅ the FIFO strategy,
    test_params.hpp:21-44). Returns seconds/frame."""
    path = f"/tmp/sitpu_fifo_{uuid.uuid4().hex[:8]}"
    os.mkfifo(path)
    frame = np.random.default_rng(0).random(nfloats).astype(np.float32)

    def producer():
        with open(path, "wb") as f:
            for _ in range(iters):
                f.write(frame.tobytes())
                f.flush()

    try:
        th = threading.Thread(target=producer, daemon=True)
        th.start()
        nbytes = frame.nbytes
        t0 = time.perf_counter()
        with open(path, "rb") as f:
            for _ in range(iters):
                got = f.read(nbytes)
                while len(got) < nbytes:
                    chunk = f.read(nbytes - len(got))
                    if not chunk:
                        raise IOError("producer closed early")
                    got += chunk
        dt = (time.perf_counter() - t0) / iters
        th.join(timeout=10)
        return dt
    finally:
        os.unlink(path)


def bench_tcp(nfloats: int, iters: int) -> float:
    """Round-trip over a TCP loopback socket (≅ the TCP strategy,
    test_params.hpp:21-44). Returns seconds/frame."""
    frame = np.random.default_rng(0).random(nfloats).astype(np.float32)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def producer():
        s = socket.socket()
        s.connect(("127.0.0.1", port))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        data = frame.tobytes()
        for _ in range(iters):
            s.sendall(data)
        s.close()

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    conn, _ = srv.accept()
    nbytes = frame.nbytes
    t0 = time.perf_counter()
    for _ in range(iters):
        got = 0
        while got < nbytes:
            chunk = conn.recv(min(1 << 20, nbytes - got))
            if not chunk:
                raise IOError("producer closed early")
            got += len(chunk)
    dt = (time.perf_counter() - t0) / iters
    conn.close()
    srv.close()
    th.join(timeout=10)
    return dt


def bench_matrix(nfloats: int, iters: int) -> None:
    mb = nfloats * 4 / 1e6
    rows = [("mmap", bench_mmap), ("fifo", bench_fifo), ("tcp", bench_tcp)]
    cells = []
    for name, fn in rows:
        dt = fn(nfloats, iters)
        cells.append(f"{name} {mb / dt:9.0f} MB/s")
    print(f"{nfloats * 4:>12} B: " + "  ".join(cells))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--max-mb", type=float, default=64.0)
    ap.add_argument("--device", action="store_true",
                    help="include the host->HBM device_put hop")
    ap.add_argument("--matrix", action="store_true",
                    help="also benchmark mmap/fifo/tcp alternatives")
    args = ap.parse_args()

    n = 256
    while n * 4 <= args.max_mb * 1e6:
        bench_size(n, max(args.iters, 3), args.device)
        if args.matrix:
            bench_matrix(n, max(args.iters, 3))
        n *= 4


if __name__ == "__main__":
    main()
