"""PSNR-vs-FLOPs-vs-ms ladder of the multi-resolution brick march
(LODConfig; docs/PERF.md "LOD marching"; ISSUE 16).

The scene is the LOD-shaped skewed scenario: dense NOISY content in the
near-camera z quarter (pinned fine by distance and by the TF-straddle
gate at its air boundary), exact-zero AIR in the next quarter (coarsens
to the admissible cap via ``lod.coarsen_empty``), and a SMOOTH visible
field in the far half (coarsens by the screen-space error bound, and
pooling a smooth field is nearly exact — this is where the PSNR cost
lives). The transfer function is the test ramp (0.05, 0.8) so the air
band is genuinely invisible and the content/air boundary bricks
straddle the 0.05 edge.

Each ladder rung is one ``lod.error_px`` budget: the REAL planner
(parallel.lod.select_levels, the exact function the session replan
calls) picks the level tuple from the live/range profiles + camera,
the distributed MXU brick step renders it on an 8-rank mesh (virtual
CPU devices or real chips), and the rung reports

  levels        the planner's tuple (histogram in the artifact)
  psnr_db       vs the level-0 frame (render_vdi_same_view decode)
  flop_reduction  modeled march FLOPs, level-0 / rung
                (parallel.lod.modeled_march_flops — the two resample
                matmuls per slice; the second keeps the FINE output
                grid, so a level-l brick is NOT 8^-l but ~2^-l on its
                dominant term: the model is honest about that)
  frame_ms      measured distributed frame time (march + composite)

``value`` is the best flop_reduction among rungs holding
``--psnr-floor`` (default 40 dB) — the committed CPU capture
(results/lod_ab_r16_cpu.json) gates >= 2x at >= 40 dB, and the CI lod
lane re-checks the committed artifact's claim. Infinite PSNR (a rung
that only coarsened air) is reported as the JSON string "inf".

KNOB_MATRIX below is the registry of every march-path config knob this
ladder (or a sibling bench named in the entry) covers; the SITPU-KNOB
lint rule (tools/lint/knobs.py) fails when a knob is added to
LODConfig / SliceMarchConfig without registering it here — an
unbenched march knob is an unmeasured regression surface.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the distributed A/B needs the rank mesh BEFORE jax initializes
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    if os.environ.get("SITPU_CPU") == "1" or not os.environ.get(
            "JAX_PLATFORMS", "").startswith("tpu"):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count="
            + os.environ.get("SITPU_BENCH_RANKS", "8")).strip()

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import (CompositeConfig, LODConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction, opacity_edges
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.ops import occupancy as occ
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.parallel import bricks as bk
from scenery_insitu_tpu.parallel import lod as lodm
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (distributed_vdi_step_mxu,
                                                  shard_volume)

# Every march-path knob (LODConfig + SliceMarchConfig) and the ladder /
# sibling bench that measures it. Keys are config override paths; the
# SITPU-KNOB rule diffs this dict against the dataclass fields.
KNOB_MATRIX = {
    "lod.enabled": "the A/B itself: every rung vs the level-0 baseline",
    "lod.max_level": "ladder cap; rungs report the admissible clamp",
    "lod.error_px": "THE ladder axis: one rung per budget",
    "lod.coarsen_empty": "air-quarter rungs isolate the empty coarsen",
    "lod.live_eps": "sets the air/visible cut of coarsen_empty rungs",
    "lod.tf_edge_eps": "straddle-gate width; boundary bricks in every "
                       "rung's level histogram pin its effect",
    "lod.hysteresis": "replan damping — session-side; lod_bench plans "
                      "each rung cold (prev=None), the session A/B in "
                      "benchmarks/scenario_bench.py carries it",
    "slicer.engine": "mxu is the only coarse consumer (gather ledgers "
                     "lod.engine); render_bench.py A/Bs the engines",
    "slicer.scale": "virtual-grid multiplier; render_bench.py sweeps it",
    "slicer.chunk": "fold chunking; benchmarks/fold_microbench.py",
    "slicer.matmul_dtype": "bf16/f32 operand A/B in render_bench.py",
    "slicer.render_dtype": "marched-copy storage dtype; hbm_bench.py",
    "slicer.s_floor": "near-plane clip; fixed across rungs (geometry, "
                      "not cost) — render_bench.py owns it",
    "slicer.skip_empty": "empty-space skipping; occupancy_bench.py "
                         "(composes with LOD: a coarse brick still "
                         "chunk-skips)",
    "slicer.occupancy_vtiles": "in-plane skip tiles; occupancy_bench.py",
    "slicer.fold": "supersegment fold schedule; fold_microbench.py",
}


def _t(fn, *args, iters=3, warmup=1):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def lod_field(grid: int) -> np.ndarray:
    """The LOD-shaped skewed scene (module docstring): far smooth half,
    exact-zero air quarter, near SPARSE noisy quarter. The near noise is
    sparse (~8% live, vortex-filament-like) so the far half stays
    genuinely visible through it — a solid near quarter occludes
    everything behind it and makes any far-coarsening PSNR vacuous."""
    rng = np.random.default_rng(16)
    data = np.zeros((grid, grid, grid), np.float32)
    z = np.arange(grid // 2)[:, None, None] / grid
    y = np.linspace(0, np.pi, grid)[None, :, None]
    x = np.linspace(0, np.pi, grid)[None, None, :]
    data[:grid // 2] = (0.3 + 0.12 * np.sin(4 * np.pi * z)
                        * np.sin(y) * np.sin(x)).astype(np.float32)
    lo = 3 * grid // 4
    shape = (grid - lo, grid, grid)
    mask = rng.random(shape) < 0.08
    data[lo:] = np.where(mask, 0.3 + 0.5 * rng.random(shape), 0.0
                         ).astype(np.float32)
    return data


def _psnr(a: np.ndarray, b: np.ndarray) -> float:
    mse = float(np.mean((np.asarray(a, np.float64)
                         - np.asarray(b, np.float64)) ** 2))
    return float("inf") if mse == 0.0 else 10.0 * np.log10(1.0 / mse)


def main(args):
    dev = jax.devices()[0]
    # a 1-chip TPU tunnel clamps the rank mesh (watcher step 16); the
    # brick count stays at the full ladder width so the level histogram
    # is comparable across captures
    n = min(args.ranks, len(jax.devices()))
    grid, nb = args.grid, args.bricks or max(16, 2 * n)
    field = jnp.asarray(lod_field(grid))
    tf = TransferFunction.ramp(0.05, 0.8, 0.7)
    # near the NOISY quarter (high z): distance separates far-smooth
    # from near-noisy by about one level octave
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    march_cfg = SliceMarchConfig(
        matmul_dtype="f32" if dev.platform != "tpu" else "bf16",
        scale=args.scale)
    spec = slicer.make_spec(cam, (grid, grid, grid), march_cfg,
                            multiple_of=n)
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_iters=2)

    vox = 2.0 / grid
    origin = jnp.asarray([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    mesh = make_mesh(n)
    sdata = shard_volume(field, mesh)

    # the planner's inputs, exactly as the session replan fetches them
    live = lodm.per_brick(np.asarray(occ.z_live_profile(field, tf,
                                                        nzb=nb)), nb)
    lo_p, hi_p = occ.z_range_profile(field, nzb=nb)
    lo_p, hi_p = np.asarray(lo_p), np.asarray(hi_p)
    edges = opacity_edges(tf)
    dims = (grid, grid, grid)
    plan_kw = dict(dims=dims, origin=np.asarray(origin),
                   spacing=np.asarray(spacing),
                   eye=np.asarray(cam.eye), fov_y=float(cam.fov_y),
                   height_px=spec.nj)

    base_map = bk.BrickMap.contiguous(grid, n, nb)
    base_flops = lodm.modeled_march_flops((0,) * nb, dims, spec.ni,
                                          spec.nj)

    def render(levels):
        bm = base_map.with_levels(levels)
        step = distributed_vdi_step_mxu(
            mesh, tf, spec, vdi_cfg,
            CompositeConfig(max_output_supersegments=2 * args.k,
                            adaptive_iters=2, rebalance="bricks"),
            bricks=bm)
        dt, (vdi, _) = _t(lambda: step(sdata, origin, spacing, cam),
                          iters=args.iters)
        return dt * 1e3, np.asarray(render_vdi_same_view(vdi))

    ms0, img0 = render((0,) * nb)
    ladder = [{"error_px": None, "levels": [0] * nb, "psnr_db": "inf",
               "flop_reduction": 1.0, "frame_ms": round(ms0, 2),
               "note": "level-0 baseline (bitwise the pre-LOD path)"}]
    for err_px in args.ladder:
        cfg = LODConfig(enabled=True, max_level=args.max_level,
                        error_px=err_px, live_eps=args.live_eps)
        levels = lodm.select_levels(live, lo_p, hi_p, edges, cfg=cfg,
                                    **plan_kw)
        ms, img = render(levels)
        psnr = _psnr(img0, img)
        flops = lodm.modeled_march_flops(levels, dims, spec.ni, spec.nj)
        ladder.append({
            "error_px": err_px,
            "levels": list(levels),
            "level_hist": {str(l): int(sum(1 for x in levels if x == l))
                           for l in sorted(set(levels))},
            "psnr_db": "inf" if psnr == float("inf") else round(psnr, 2),
            "flop_reduction": round(base_flops / flops, 3),
            "frame_ms": round(ms, 2),
            "march_speedup": round(ms0 / ms, 3),
        })

    def _admissible(r):
        return r["psnr_db"] == "inf" or r["psnr_db"] >= args.psnr_floor

    good = [r for r in ladder[1:] if _admissible(r)]
    best = max(good, key=lambda r: r["flop_reduction"]) if good else None
    out = {
        "metric": f"lod_ladder_{grid}c_{n}ranks_{dev.platform}",
        "unit": "modeled march FLOP reduction at the PSNR floor "
                "(level-0 / best admissible rung)",
        "value": best["flop_reduction"] if best else 0.0,
        "psnr_db": best["psnr_db"] if best else None,
        "psnr_floor_db": args.psnr_floor,
        "best_error_px": best["error_px"] if best else None,
        "ladder": ladder,
        "scene": {"grid": grid, "layout": "far smooth half / zero air "
                  "quarter / near noisy quarter", "nbricks": nb,
                  "brick_depth": grid // nb,
                  "tf_edges": [round(float(e), 4) for e in edges]},
        "config": {"ranks": n, "k": args.k, "nbricks": nb,
                   "max_level": args.max_level, "live_eps": args.live_eps,
                   "image": [spec.ni, spec.nj], "fold": spec.fold,
                   "iters": args.iters, "platform": dev.platform,
                   "device": dev.device_kind},
        "note": ("levels chosen by parallel.lod.select_levels from the "
                 "real live/range profiles (the session replan path); "
                 "frames rendered by the distributed MXU brick step on "
                 f"{n} ranks; FLOPs modeled per parallel.lod"
                 ".modeled_march_flops. frame_ms at toy grids is "
                 "dominated by per-brick fixed cost (thresholds, fold "
                 "state, compile-shaped dispatch), so CPU march_speedup "
                 "< 1 here is expected — the FLOP model is the claim "
                 "that transfers to 2048^3+ (see "
                 "modeled_projection.py --lod)"),
    }
    print(json.dumps(out), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int,
                    default=int(os.environ.get("SITPU_BENCH_GRID", "64")))
    ap.add_argument("--ranks", type=int,
                    default=int(os.environ.get("SITPU_BENCH_RANKS", "8")))
    ap.add_argument("--bricks", type=int, default=0,
                    help="brick count (0 = 2 per rank)")
    ap.add_argument("--k", type=int,
                    default=int(os.environ.get("SITPU_BENCH_K", "8")))
    ap.add_argument("--ladder", type=float, nargs="+",
                    default=[1.5, 3.0, 6.0, 12.0],
                    help="lod.error_px budgets, one rung each")
    ap.add_argument("--max-level", type=int, default=2)
    ap.add_argument("--live-eps", type=float, default=1e-3)
    ap.add_argument("--scale", type=float, default=2.0)
    ap.add_argument("--psnr-floor", type=float, default=40.0)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--out", default=None)
    cli = ap.parse_args()
    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()
    main(cli)
