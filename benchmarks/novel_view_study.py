"""Novel-view error study: quantify the PROXY cross-regime path (and the
sampled gather renderer) against the EXACT closed-form renderer
(ops/vdi_novel.render_vdi_exact ≅ EfficientVDIRaycast.comp:274-450) over
a view-angle sweep from the generating view around to the orthogonal
regime — the stated-bounds table VERDICT r4 item 7 asked for.

Writes a markdown table (docs/NOVEL_VIEW.md when --write-docs, else
stdout) and one JSON line with the worst-case numbers. CPU-safe.

Usage: python benchmarks/novel_view_study.py [--grid 32] [--size 80 64]
       [--write-docs]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scenery_insitu_tpu.utils.backend import pin_cpu_backend


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=32)
    ap.add_argument("--size", type=int, nargs=2, default=(80, 64))
    ap.add_argument("--write-docs", action="store_true")
    ap.add_argument("--gather-steps", type=int, default=1200)
    args = ap.parse_args()

    import numpy as np

    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.vdi_novel import (render_vdi_any,
                                                  render_vdi_exact)
    from scenery_insitu_tpu.ops.vdi_render import render_vdi
    from scenery_insitu_tpu.utils.image import psnr

    w, h = args.size
    vol = procedural_volume(args.grid, kind="blobs", seed=3)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.0, 0.3, 2.8), fov_y_deg=45.0, near=0.3,
                         far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5))
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=8,
                                       adaptive_iters=3))

    center = np.array([0.5, 0.5, 0.5])
    r = float(np.linalg.norm(np.asarray(cam0.eye) - center))

    rows = []
    for deg in (0, 10, 20, 30, 40, 50, 60, 70, 80, 90):
        th = math.radians(deg)
        eye = center + r * np.array([math.sin(th), 0.12, math.cos(th)])
        cam1 = Camera.create(tuple(eye), fov_y_deg=45.0, near=0.3,
                             far=10.0)
        axis_new = slicer.choose_axis(cam1)[0]
        regime = "same" if axis_new == spec.axis else "cross"
        ex = np.asarray(render_vdi_exact(vdi, axcam, spec, cam1, w, h))
        pr = np.asarray(render_vdi_any(vdi, axcam, spec, cam1, w, h,
                                       num_slices=vol.data.shape[0]))
        ga = np.asarray(render_vdi(vdi, meta, cam1, w, h,
                                   steps=args.gather_steps))
        rows.append((deg, regime, psnr(pr, ex), psnr(ga, ex)))
        print(f"[study] {deg:3d} deg ({regime:5s}): proxy/sweep "
              f"{rows[-1][2]:5.1f} dB, gather {rows[-1][3]:5.1f} dB",
              file=sys.stderr, flush=True)

    lines = [
        "# Novel-view error study",
        "",
        "Ground truth: `render_vdi_exact` (closed-form in-slab path",
        "lengths, any regime — ops/vdi_novel.py; ≅ the reference's",
        "EfficientVDIRaycast.comp:274-450). The fast paths are measured",
        "against it over a horizontal orbit from the generating view",
        f"(0°) to the orthogonal regime (90°); {args.grid}^3 blobs volume,",
        f"{w}x{h} output, K=8, regenerate with",
        "`python benchmarks/novel_view_study.py --write-docs`.",
        "",
        "- **proxy/sweep** = `render_vdi_any` default: same-regime plane",
        "  sweep while the view shares the VDI's march axis, RGBA proxy",
        "  volume once it crosses regimes.",
        "- **gather** = `render_vdi` sampled march "
        f"({args.gather_steps} steps).",
        "",
        "| view angle | regime | proxy/sweep vs exact (dB) | "
        "sampled gather vs exact (dB) |",
        "|---:|---|---:|---:|",
    ]
    for deg, regime, p_pr, p_ga in rows:
        lines.append(f"| {deg}° | {regime} | {p_pr:.1f} | {p_ga:.1f} |")
    worst_pr = min(p for _, _, p, _ in rows)
    lines += [
        "",
        f"Worst proxy/sweep deviation across the sweep: **{worst_pr:.1f} "
        "dB** (floor pinned by tests/test_vdi_novel.py::"
        "test_proxy_error_bound_vs_exact).",
        "",
        "Clients that need the exact result (validation, stills) pass",
        "`exact=True` to `render_vdi_any`; the proxy stays the fast path",
        "for interactive use (one resample per received VDI, then every",
        "view is an ordinary slice march).",
    ]
    table = "\n".join(lines) + "\n"
    if args.write_docs:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "NOVEL_VIEW.md")
        with open(path, "w") as f:
            f.write(table)
        print(f"[study] wrote {path}", file=sys.stderr)
    else:
        print(table)
    print(json.dumps({
        "metric": "novel_view_proxy_vs_exact_worst_psnr",
        "value": round(worst_pr, 2), "unit": "dB",
        "config": {"grid": args.grid, "size": [w, h],
                   "angles_deg": [r0 for r0, _, _, _ in rows]},
        "rows": [{"deg": d, "regime": g, "proxy_psnr": round(p, 2),
                  "gather_psnr": round(q, 2)} for d, g, p, q in rows],
    }), flush=True)


if __name__ == "__main__":
    if os.environ.get("SITPU_BENCH_REAL") != "1":
        pin_cpu_backend()          # the axon shim hangs when tunnel is down
    main()
