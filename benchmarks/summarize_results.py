"""Print a one-table summary of every committed measurement artifact in
benchmarks/results/ (bench JSON lines, microbench/config JSONL sweeps).
Usage: python benchmarks/summarize_results.py
No JAX import — safe to run anywhere, any time."""

from __future__ import annotations

import glob
import json
import os

R = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def rows_of(path: str):
    out = []
    with open(path) as f:
        text = f.read()
    for line in text.splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            pass
    if not out:
        # pretty-printed (multi-line) artifacts — composite/wire/waves
        # A/Bs and the modeled projection are written with indent
        try:
            doc = json.loads(text)
            if isinstance(doc, dict):
                out.append(doc)
        except json.JSONDecodeError:
            pass
    return out


def _fmt_attribution(a: dict, head: str = "phase_attribution") -> list:
    """Lines for one phase_attribution record (bare or embedded in a
    bench artifact — the attribution plane, docs/OBSERVABILITY.md)."""
    lines = [f"{head}: [{a.get('backend', '?')}] "
             f"{a.get('wall_ms_per_frame')} ms/frame wall, coverage "
             f"{a.get('coverage')}"
             + (f" (op_parallelism {a.get('op_parallelism')}, "
                f"normalized)" if a.get("normalized") else "")]
    wall = float(a.get("wall_ms_per_frame") or 0.0)
    phs = sorted((a.get("phases") or {}).items(),
                 key=lambda kv: -float(kv[1].get("ms") or 0.0))
    for name, p in phs:
        ms = float(p.get("ms") or 0.0)
        share = f" ({ms / wall:5.1%})" if wall > 0 else ""
        lines.append(f"  {name:14s} {ms:10.2f} ms{share} "
                     f"events={p.get('events')}")
    return lines


def fmt(r: dict) -> str:
    if r.get("type") == "phase_attribution":     # bare attribution capture
        return "\n   ".join(_fmt_attribution(r))
    if r.get("type") == "divergence_report":     # model-vs-measured deltas
        lines = [f"divergence_report: vs {r.get('modeled_row')} "
                 f"[{r.get('modeled_artifact')}] unmodeled_share="
                 f"{r.get('unmodeled_share')}"]
        for lv, e in sorted((r.get("levers") or {}).items()):
            lines.append(
                f"  {lv:18s} modeled={e.get('modeled_ms')} measured="
                f"{e.get('measured_ms')} ms  share "
                f"{e.get('modeled_share')} -> {e.get('measured_share')} "
                f"(d={e.get('share_delta')}, bound={e.get('bound')})")
        for row in (r.get("next_perf_pr") or [])[:3]:
            lines.append(f"  next: {row.get('lever')} "
                         f"d_share={row.get('share_delta')} — "
                         f"{row.get('verdict')}")
        return "\n   ".join(lines)
    if r.get("type") == "slo_report":            # live SLO engine snapshot
        lines = [f"slo_report: healthy={r.get('healthy')} "
                 f"breaches={r.get('total_breaches')} "
                 f"(window={r.get('window')}, "
                 f"min_samples={r.get('min_samples')})"]
        for name, m in sorted((r.get("metrics") or {}).items()):
            budget = m.get("budget") or 0
            gate = (f"  budget {budget:g} "
                    f"{'BREACHED' if m.get('breached') else 'ok'}"
                    if budget else "  (untracked)")
            lines.append(f"  {name:22s} p50={m.get('p50'):8.2f} "
                         f"p99={m.get('p99'):8.2f} n={m.get('n')}{gate}")
        return "\n   ".join(lines)
    if r.get("type") == "trajectory":            # regression-gate ledger row
        keys = " ".join(f"{k}={v:g}" for k, v in
                        sorted((r.get("keys") or {}).items()))
        return (f"trajectory[{r.get('family')}]: {r.get('artifact')} "
                f"vs {r.get('baseline')}  {keys}")
    if "variant" in r:                           # fold microbench row
        if "error" in r:
            return f"variant={r['variant']:14s} ERROR {r['error'][:50]}"
        return (f"variant={r['variant']:14s} {r['ms_per_march']:8.2f} ms/march"
                f"  hw={r['hw'][0]}x{r['hw'][1]} k={r['k']} c={r['chunk']}")
    if "workload" in r:                          # configs sweep row
        w = r["workload"]
        return (f"{r.get('metric', '?')}: {r['ms_per_frame']:.0f} ms/frame "
                f"{w} mode={r.get('mode')} n={r.get('n_devices')}")
    if isinstance(r.get("exchange"), dict):      # composite/wire/waves A/B
        lines = [f"{r.get('metric', 'composite_ab')}: "
                 f"[{r.get('backend', '?')}]"]
        for key, e in sorted(r["exchange"].items()):
            mod = e.get("modeled") or {}
            extra = ""
            if "ici_bytes_per_rank" in mod:
                extra = f"  ici={mod['ici_bytes_per_rank']}B/rank"
            if mod.get("schedule") == "waves":
                extra += (f" hidden={mod.get('overlap_hidden_frac')} "
                          f"(T={mod.get('wave_tiles')})")
            lines.append(f"  {key:22s} {e.get('ms_per_iter')} ms/iter"
                         f"{extra}")
        if "wire_psnr_db" in r:
            lines.append(f"  psnr_db={r['wire_psnr_db']}")
        for pk in ("parity", "schedule_parity"):
            if pk in r:
                lines.append(
                    f"  {pk}: max|dcolor|="
                    f"{r[pk].get('max_abs_diff_color')}")
        return "\n   ".join(lines)
    if r.get("kind") == "delta_ab":              # temporal-delta A/B
        lines = [f"delta_ab: [{r.get('platform', '?')}] "
                 f"verdicts={r.get('verdicts')}"]
        for name, sc in sorted((r.get("scenes") or {}).items()):
            m, w = sc.get("march", {}), sc.get("wire", {})
            lines.append(
                f"  {name:5s} march {m.get('ms_per_frame_off')} -> "
                f"{m.get('ms_per_frame_on')} ms/frame, skip "
                f"{m.get('skip_frac')}")
            if "bytes_ratio" in w:
                lines.append(
                    f"  {name:5s} wire  {w.get('bytes_per_frame_qpack8')}"
                    f" -> {w.get('bytes_per_frame_delta')} B/frame "
                    f"(x{w.get('bytes_ratio')}), records {w.get('records')}"
                    f", bitexact={w.get('recon_bitexact_vs_qpack8')}")
        return "\n   ".join(lines)
    if "plan" in r and "even" in r \
            and ("occupancy" in r or "bricks" in r):   # rebalance A/B
        ev = r["even"]
        lines = [f"{r.get('metric', 'rebalance_ab')}: even straggler "
                 f"{ev.get('straggler_factor')} "
                 f"(max_ms {ev.get('max_ms')})"]
        if "occupancy" in r:
            oc = r["occupancy"]
            lines.append(f"  slabs  -> {oc.get('straggler_factor')} "
                         f"(x{r.get('value')} reduction, frame march "
                         f"x{r.get('frame_march_speedup')}) "
                         f"plan={r['plan']}")
        if "bricks" in r:
            bb = r["bricks"]
            bm = r.get("bricks_map", {})
            lines.append(f"  bricks -> {bb.get('straggler_factor')} "
                         f"(x{r.get('value_bricks')} reduction, frame "
                         f"march x{r.get('frame_march_speedup_bricks')})"
                         f" nbricks={bm.get('nbricks')} "
                         f"slots={bm.get('slots')}")
        return "\n   ".join(lines)
    if "scenarios" in r and str(r.get("metric", "")).startswith(
            "scenario_bench"):                     # scenario zoo bench
        lines = [f"{r['metric']}: {r.get('value')} scenario(s), "
                 f"parity_ok={r.get('parity_ok')}"]
        for name, row in sorted(r["scenarios"].items()):
            par = row.get("parity")
            extra = ""
            if par:
                extra = (f"  parity ok={par.get('ok')} "
                         f"perm_bitwise={par.get('perm_bitwise')}"
                         if "ok" in par else f"  parity {par}")
            if row.get("tf_updates"):
                extra += (f"  tf {row['tf_updates']} upd/"
                          f"{row['tf_steps_reused']} reused")
            lines.append(f"  {name:14s} {row.get('ms_per_frame'):8.1f} "
                         f"ms/frame [{row.get('mode')}/{row.get('engine')}]"
                         f"{extra}")
        return "\n   ".join(lines)
    if "measured" in r and "model" in r:         # occupancy A/B
        modes = (r["measured"] or {}).get("modes", {})
        ms = " ".join(f"{m}={v.get('ms_per_frame')}ms"
                      for m, v in modes.items() if isinstance(v, dict))
        red = (r["model"] or {}).get("reduction_vs_off", {})
        return (f"{r.get('metric', 'occupancy_ab')}: {ms}"
                f"  model reduction_vs_off={red}")
    if "stack" in r:                             # modeled projection
        lines = [f"{r.get('metric', 'modeled_projection')}: "
                 f"{r.get('value')} {r.get('unit', '')} "
                 f"(vs {r.get('baseline_ms_per_frame')} ms flagship)"]
        for row in r["stack"]:
            lines.append(f"  {row.get('lever', '?'):34s} "
                         f"{row.get('modeled_ms_per_frame')} ms/frame "
                         f"x{row.get('speedup_vs_baseline')}")
        return "\n   ".join(lines)
    if str(r.get("metric", "")).startswith("hier_weak_scaling"):
        # hierarchical weak scaling through the subprocess harness
        lines = [f"{r['metric']}: weak_efficiency={r.get('value')} "
                 f"(dcn_wire={r.get('config', {}).get('dcn_wire')})"]
        for row in r.get("sweep", []):
            if "error" in row:
                lines.append(f"  hosts={row.get('hosts')} ERROR "
                             f"{row['error']}")
                continue
            mod = row.get("modeled", {})
            lines.append(
                f"  hosts={row['hosts']} ranks={row['n_ranks']} "
                f"{row['ms_per_frame']:8.1f} ms/frame  dcn "
                f"{row['dcn_bytes_sent_per_host_measured']} B/host "
                f"(modeled raw {mod.get('dcn_bytes_sent_per_host')})")
        return "\n   ".join(lines)
    if str(r.get("metric", "")).startswith("hier_device_ab"):
        # flat vs hierarchical device-path A/B (watcher step 14)
        lines = [f"{r['metric']}: flat {r.get('flat_ms_per_frame')} "
                 f"ms/frame ({r.get('devices')} dev, {r.get('grid')}^3)"]
        for key, h in sorted((r.get("hier") or {}).items()):
            lines.append(
                f"  {key:5s} {h.get('ms_per_frame')} ms/frame "
                f"(x{h.get('vs_flat')} vs flat, parity "
                f"{h.get('parity_max_abs_diff')})")
        if r.get("note"):
            lines.append(f"  note: {r['note']}")
        return "\n   ".join(lines)
    if str(r.get("metric", "")).startswith("lod_ladder"):
        # multi-resolution march ladder (watcher step 16)
        sc = r.get("scene", {})
        lines = [f"{r['metric']}: x{r.get('value')} modeled march FLOPs "
                 f"at {r.get('psnr_db')} dB (floor "
                 f"{r.get('psnr_floor_db')} dB, error_px="
                 f"{r.get('best_error_px')}; {sc.get('nbricks')} bricks)"]
        for rung in r.get("ladder", []):
            hist = rung.get("level_hist") or {"0": len(rung["levels"])}
            hist_s = " ".join(f"L{k}:{v}" for k, v in sorted(hist.items()))
            lines.append(
                f"  err={str(rung.get('error_px')):>4s}px  "
                f"{str(rung.get('psnr_db')):>7s} dB  "
                f"x{rung.get('flop_reduction')} flops  "
                f"{rung.get('frame_ms')} ms  [{hist_s}]")
        return "\n   ".join(lines)
    if str(r.get("metric", "")).startswith("delivery_ab"):
        # async delivery plane A/B (watcher step 19)
        lines = [f"{r['metric']}: exposed host x{r.get('value')} of "
                 f"serial (bit_identical={r.get('bit_identical_all')}, "
                 f"fifo={r.get('ordering_fifo_all')})"]
        for name, a in (r.get("arms") or {}).items():
            lag = (f"  lag p50/p99 {a.get('delivery_lag_p50_ms')}/"
                   f"{a.get('delivery_lag_p99_ms')} ms"
                   if a.get("delivery_lag_p50_ms") is not None else "")
            lines.append(
                f"  {name:9s} frame {a.get('frame_ms'):9.2f} ms  "
                f"exposed {a.get('exposed_host_ms_per_frame'):7.2f} ms  "
                f"offloaded {a.get('offloaded_host_ms_per_frame'):7.2f} "
                f"ms{lag}")
        te = r.get("tile_encode") or {}
        if te:
            par = te.get(f"ms_workers{te.get('workers')}")
            lines.append(
                f"  tile encode w1 {te.get('ms_workers1')} ms -> "
                f"w{te.get('workers')} {par} ms "
                f"(byte_identical={te.get('byte_identical')})")
        return "\n   ".join(lines)
    if r.get("metric") == "serve_bench":          # edge-serving tier
        am = r.get("amortization", {})
        lines = [f"serve_bench: [{r.get('platform', '?')}] per-viewer "
                 f"N=16 is x{r.get('value')} of N=1 "
                 f"(verdicts={r.get('verdicts')})"]
        for n, row in sorted(am.get("proxy", {}).items(),
                             key=lambda kv: int(kv[0])):
            lines.append(f"  N={n:>2s} {row['per_viewer_ms']:8.2f} "
                         f"ms/viewer  {row['viewers_per_second']:7.1f} "
                         "viewers/s")
        lat = r.get("latency_ms", {})
        lines.append(f"  fetch {am.get('fetch_ms')} ms + proxy build "
                     f"{am.get('proxy_build_ms')} ms/frame; p50/p99 "
                     f"{lat.get('p50')}/{lat.get('p99')} ms; "
                     f"bytes/viewer {r.get('bytes_per_viewer')}")
        return "\n   ".join(lines)
    if "metric" in r:
        val = r.get("value")
        unit = r.get("unit", "")
        cfg = r.get("config", {})
        plat = cfg.get("platform", r.get("platform", "?"))
        extra = ""
        if "ms_per_frame" in r:
            extra = f"  {r['ms_per_frame']:.1f} ms/frame"
        elif "ms_per_frame" in cfg:
            extra = f"  {cfg['ms_per_frame']:.1f} ms/frame"
        if r.get("error"):
            return f"{r['metric']}: ERROR {str(r['error'])[:60]}"
        vs = r.get("vs_baseline")
        vs_s = f"  vs_baseline={vs}" if vs is not None else ""
        line = (f"{r['metric']}: {val} {unit} [{plat}]"
                f"{extra}{vs_s}")
        if isinstance(r.get("phase_attribution"), dict):
            # bench artifact with the attribution plane riding along
            return "\n   ".join(
                [line] + _fmt_attribution(r["phase_attribution"],
                                          head="attribution"))
        return line
    return json.dumps(r)[:100]


def main():
    for path in sorted(glob.glob(os.path.join(R, "*.json*"))):
        name = os.path.basename(path)
        rows = rows_of(path)
        if not rows:
            continue
        print(f"\n== {name}")
        for r in rows:
            print("   " + fmt(r))


if __name__ == "__main__":
    main()
