"""Print a one-table summary of every committed measurement artifact in
benchmarks/results/ (bench JSON lines, microbench/config JSONL sweeps).
Usage: python benchmarks/summarize_results.py
No JAX import — safe to run anywhere, any time."""

from __future__ import annotations

import glob
import json
import os

R = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def rows_of(path: str):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def fmt(r: dict) -> str:
    if "variant" in r:                           # fold microbench row
        if "error" in r:
            return f"variant={r['variant']:14s} ERROR {r['error'][:50]}"
        return (f"variant={r['variant']:14s} {r['ms_per_march']:8.2f} ms/march"
                f"  hw={r['hw'][0]}x{r['hw'][1]} k={r['k']} c={r['chunk']}")
    if "workload" in r:                          # configs sweep row
        w = r["workload"]
        return (f"{r.get('metric', '?')}: {r['ms_per_frame']:.0f} ms/frame "
                f"{w} mode={r.get('mode')} n={r.get('n_devices')}")
    if "metric" in r:
        val = r.get("value")
        unit = r.get("unit", "")
        cfg = r.get("config", {})
        plat = cfg.get("platform", r.get("platform", "?"))
        extra = ""
        if "ms_per_frame" in r:
            extra = f"  {r['ms_per_frame']:.1f} ms/frame"
        elif "ms_per_frame" in cfg:
            extra = f"  {cfg['ms_per_frame']:.1f} ms/frame"
        if r.get("error"):
            return f"{r['metric']}: ERROR {str(r['error'])[:60]}"
        vs = r.get("vs_baseline")
        vs_s = f"  vs_baseline={vs}" if vs is not None else ""
        return (f"{r['metric']}: {val} {unit} [{plat}]"
                f"{extra}{vs_s}")
    return json.dumps(r)[:100]


def main():
    for path in sorted(glob.glob(os.path.join(R, "*.json*"))):
        name = os.path.basename(path)
        rows = rows_of(path)
        if not rows:
            continue
        print(f"\n== {name}")
        for r in rows:
            print("   " + fmt(r))


if __name__ == "__main__":
    main()
