#!/bin/bash
# Round-3 third-window watcher. Lessons from the first two windows baked in:
#   - window 1 (22:12-22:48 UTC 07-30): 512^3 flagship captured; fold is the
#     bottleneck; diagnostics died with the tunnel.
#   - window 2 (03:16-03:19 UTC 07-31): fold_microbench@256 + the 512^3
#     fold-fallback flagship landed, then the tunnel wedged MID-SUITE and
#     the r3b watcher burned its per-step timeouts against a dead tunnel.
# So this watcher re-probes the tunnel BEFORE EVERY STEP and keeps a
# done-marker per step (the output file): a mid-suite tunnel death pauses
# the suite at the next boundary and it resumes at the first undone step
# when the tunnel answers again. Steps are ordered by marginal value.
# Log: /tmp/tpu_watcher_r3c.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
L=/tmp/tpu_watcher_r3c.log

probe() {
  timeout 120 python - <<'EOF' 2>/dev/null
import jax
assert jax.devices()[0].platform == "tpu"
import jax.numpy as jnp
assert float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum()) > 0
EOF
}

# run_json <outfile> <timeout_s> <cmd...>  — keep last stdout line iff the
# command ITSELF succeeded and that line is JSON (status captured before
# tail so a killed/crashed bench can't be recorded as a done step)
run_json() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.full.tmp" 2>>"$L" \
     && tail -1 "$out.full.tmp" > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" \
          "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; rm -f "$out.full.tmp"
    echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    rm -f "$out.tmp" "$out.full.tmp"
    echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

# run_jsonl <outfile> <timeout_s> <cmd...>  — keep full stdout (jsonl/text)
run_jsonl() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    # partial output is still evidence for streaming harnesses
    if [ -s "$out.tmp" ]; then mv "$out.tmp" "$out.partial"; fi
    rm -f "$out.tmp"; echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

run_step() {  # run_step <n>
  case "$1" in
    1) run_json "$R/bench_tpu_r3_512_tiledfold.json" 1000 env \
         SITPU_BENCH_PLATFORMS=tpu,tpu SITPU_BENCH_CHILD_TIMEOUT=420 \
         python bench.py ;;
       # window-1 evidence: a real 512^3 child finishes in <90 s (compile
       # 17 s + 25 frames x 0.5 s + transfers), so 420 s/child is ample
       # while capping the cost of a mid-step tunnel wedge at ~15 min —
       # short windows (window 2 was ~3 min) must not be burned waiting
       # on dead children
    2) run_jsonl "$R/fold_microbench_512_tpu_r3.jsonl" 2400 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --variants count,xla,pallas,pallas_gated,pallas_w128,pallas_t16,scratch ;;
    3) run_json "$R/novel_view_tpu_r3.json" 1500 \
         python benchmarks/novel_view_bench.py --iters 3 ;;
    4) run_json "$R/composite_tpu_r3.json" 1200 env SITPU_BENCH_REAL=1 \
         python benchmarks/composite_bench.py ;;
    5) run_jsonl "$R/profile_march_tpu_r3.txt" 1500 \
         python -u benchmarks/profile_march.py 256 ;;
    6) run_json "$R/profile_frame_tpu_r3.json" 1200 \
         python benchmarks/profile_frame.py --out "$R/trace_r3" ;;
    7) run_json "$R/scaling_tpu_r3.json" 1800 env SITPU_BENCH_REAL=1 \
         python benchmarks/scaling_bench.py --grid 128 --frames 10 ;;
    8) run_json "$R/bench_tpu_r3_256_tiledfold.json" 1500 env \
         SITPU_BENCH_GRID=256 SITPU_BENCH_PLATFORMS=tpu,tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    9) run_json "$R/bench_tpu_r3_512_xlafold.json" 1500 env \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_FOLD=xla \
         SITPU_BENCH_CHILD_TIMEOUT=900 python bench.py ;;
    10) run_jsonl "$R/fold_microbench_512_c32_tpu_r3.jsonl" 1800 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --chunk 32 --variants xla,pallas,pallas_gated ;;
    11) run_json "$R/bench_tpu_r3_1024.json" 2100 env \
         SITPU_BENCH_GRID=1024 SITPU_BENCH_FRAMES=5 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=1800 \
         python bench.py ;;
    12) run_jsonl "$R/profile_march_512_tpu_r3.txt" 1800 \
         python -u benchmarks/profile_march.py 512 ;;
  esac
}

step_out() {  # marker file for step <n>
  case "$1" in
    1) echo "$R/bench_tpu_r3_512_tiledfold.json" ;;
    2) echo "$R/fold_microbench_512_tpu_r3.jsonl" ;;
    3) echo "$R/novel_view_tpu_r3.json" ;;
    4) echo "$R/composite_tpu_r3.json" ;;
    5) echo "$R/profile_march_tpu_r3.txt" ;;
    6) echo "$R/profile_frame_tpu_r3.json" ;;
    7) echo "$R/scaling_tpu_r3.json" ;;
    8) echo "$R/bench_tpu_r3_256_tiledfold.json" ;;
    9) echo "$R/bench_tpu_r3_512_xlafold.json" ;;
    10) echo "$R/fold_microbench_512_c32_tpu_r3.jsonl" ;;
    11) echo "$R/bench_tpu_r3_1024.json" ;;
    12) echo "$R/profile_march_512_tpu_r3.txt" ;;
  esac
}

# a step that fails MAXFAIL times with the tunnel alive is benched (fail
# marker) so a deterministic failure can't starve the steps behind it; a
# later tunnel recovery doesn't resurrect it — rerun by deleting
# /tmp/r3c_fail.<n>
NSTEPS=12
MAXFAIL=2
for i in $(seq 1 300); do
  next=""
  for s in $(seq 1 $NSTEPS); do
    fails=$(cat "/tmp/r3c_fail.$s" 2>/dev/null || echo 0)
    [ -e "$(step_out "$s")" ] || [ "$fails" -ge $MAXFAIL ] \
      || { next="$s"; break; }
  done
  [ -z "$next" ] && { echo "suite done $(date -u)" >> "$L"; exit 0; }
  if probe; then
    echo "tunnel alive $(date -u +%H:%M:%S), step $next" | tee -a "$L"
    date -u >> "$R/tpu_alive_r3.marker"
    run_step "$next"
    if [ -e "$(step_out "$next")" ]; then
      rm -f "/tmp/r3c_fail.$next"
    elif probe; then
      # only count failures the tunnel can't explain: the step died while
      # the tunnel still answers -> likely deterministic
      fails=$(cat "/tmp/r3c_fail.$next" 2>/dev/null || echo 0)
      echo $((fails + 1)) > "/tmp/r3c_fail.$next"
      echo "step $next failed with tunnel alive ($((fails + 1))/$MAXFAIL)" \
        >> "$L"
    fi
  else
    echo "tunnel dead $(date -u +%H:%M:%S), step $next pending" >> "$L"
    sleep 120
  fi
done
echo "watcher budget exhausted $(date -u)" >> "$L"
exit 1
