"""Novel-view VDI renderer benchmark: the MXU plane-sweep client
(ops/vdi_novel.render_vdi_mxu) vs the portable per-step gather renderer
(ops/vdi_render.render_vdi) at display resolution — the reference's
EfficientVDIRaycast role (SURVEY.md §2d).

Prints one JSON line with both times and the speedup. Inputs are chained
across iterations (the camera pose advances and consumes the previous
frame's checksum) so no execution-dedup layer can fake the timing.

Usage: python benchmarks/novel_view_bench.py [--grid 256] [--width 1280]
       [--height 720] [--iters 5] [--skip-gather]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=256)
    ap.add_argument("--width", type=int, default=1280)
    ap.add_argument("--height", type=int, default=720)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--gather-steps", type=int, default=256)
    ap.add_argument("--skip-gather", action="store_true",
                    help="only time the MXU path (the gather path can take "
                    "minutes per frame at 720p)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_mxu
    from scenery_insitu_tpu.ops.vdi_render import render_vdi

    g = args.grid
    vol = procedural_volume(g, kind="blobs", seed=7)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.1, 0.4, 2.9), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape, SliceMarchConfig())
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=args.k,
                                       adaptive_iters=2))
    jax.block_until_ready(vdi.color)
    print(f"[bench] VDI {vdi.color.shape} on "
          f"{jax.default_backend()}", file=sys.stderr, flush=True)

    def timed(fn, label):
        out = fn(jnp.float32(0.0))
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        chain = jnp.float32(0.0)
        for i in range(args.iters):
            out = fn(0.03 * (i + 1) + chain * 1e-9)
            chain = out[3].sum()            # data-dependence chain
        jax.block_until_ready(chain)
        dt = (time.perf_counter() - t0) / args.iters
        print(f"[bench] {label}: {dt * 1000:.1f} ms/frame",
              file=sys.stderr, flush=True)
        return dt

    # the VDI / proxy volume ride as jit ARGUMENTS, not closures: a closed-
    # over array is baked into the HLO as a literal constant, and this
    # environment's axon shim ships the serialized program to a remote
    # compile service — a 256^3 proxy constant (268 MB) exceeds its request
    # limit (HTTP 413) before compilation even starts
    regime = slicer.choose_axis(cam0)      # host-side; yaw stays in-regime
    mxu_j = jax.jit(lambda v, ac, yaw: render_vdi_mxu(
        v, ac, spec, orbit(cam0, yaw), args.width, args.height,
        num_slices=g, axis_sign=regime))
    t_mxu = timed(lambda yaw: mxu_j(vdi, axcam, yaw), "mxu plane sweep")

    # cross-regime: a view marching a different axis goes through the
    # pre-shaded proxy volume — built ONCE per VDI, reused per view
    from scenery_insitu_tpu.ops.vdi_novel import (render_vdi_any,
                                                  vdi_to_rgba_volume)
    proxy = jax.jit(lambda v, ac: vdi_to_rgba_volume(
        v, ac, spec, num_slices=g))(vdi, axcam)
    jax.block_until_ready(proxy.data)
    cam_x = Camera.create((2.9, 0.2, 0.3), fov_y_deg=45.0, near=0.3,
                          far=10.0)
    regime_x = slicer.choose_axis(cam_x)
    cross_j = jax.jit(lambda v, ac, p, yaw: render_vdi_any(
        v, ac, spec, orbit(cam_x, yaw), args.width, args.height,
        num_slices=g, axis_sign=regime_x, proxy=p))
    t_cross = timed(lambda yaw: cross_j(vdi, axcam, proxy, yaw),
                    "cross-regime proxy")

    t_gather = None
    if not args.skip_gather:
        gather_j = jax.jit(lambda v, yaw: render_vdi(
            v, meta, orbit(cam0, yaw), args.width, args.height,
            steps=args.gather_steps))
        t_gather = timed(lambda yaw: gather_j(vdi, yaw), "gather per-step")

    print(json.dumps({
        "metric": f"novel_view_{g}c_{args.width}x{args.height}_ms",
        "value": round(t_mxu * 1000, 2),
        "unit": "ms/frame",
        "cross_regime_ms": round(t_cross * 1000, 2),
        "gather_ms": round(t_gather * 1000, 2) if t_gather else None,
        "speedup_vs_gather": round(t_gather / t_mxu, 1) if t_gather else None,
        "backend": jax.default_backend(),
        "config": {"grid": g, "k": args.k, "image": [args.width, args.height],
                   "num_slices": g, "gather_steps": args.gather_steps},
    }))


if __name__ == "__main__":
    main()
