"""Edge-serving bench (ISSUE 13 / ROADMAP item 2; docs/SERVING.md):
the viewers/chip/frame amortization curve, p99 camera-to-pixel latency
through a real loopback server, and bytes/viewer per tier.

The claim under test is the VDI value proposition itself (PAPER.md §0):
the representation is render-once, so N viewers must cost far less than
N renders. Measured here as the per-viewer cost of one batched dispatch
(`ops.vdi_novel.render_vdi_batch`) at growing batch sizes on the proxy
tier — the per-frame proxy expansion is shared, each extra viewer adds
only its march — plus the bitwise parity verdict (batched ==
per-camera) and the serving-loop latency distribution with admission
sheds exercised (every shed lands in the embedded ledger, like every
bench artifact).

Usage::

    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py \
        --out benchmarks/results/serve_bench_r13_cpu.json

The last stdout line is the artifact JSON (tpu_watcher.sh step 13
captures it with run_json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, iters):
    fn()                                     # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=48)
    ap.add_argument("--k", type=int, default=20,
                    help="supersegments (20 = the reference default)")
    ap.add_argument("--width", type=int, default=96)
    ap.add_argument("--height", type=int, default=72)
    ap.add_argument("--num-slices", type=int, default=48)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=4,
                    help="camera requests per client in the latency loop")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import (FrameworkConfig,
                                           SliceMarchConfig, VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.vdi import VDI
    from scenery_insitu_tpu.core.volume import Volume, procedural_volume
    from scenery_insitu_tpu.ops import slicer, vdi_novel

    platform = jax.default_backend()
    mdt = "bf16" if platform == "tpu" else "f32"
    W, H, NS = args.width, args.height, args.num_slices

    vol = procedural_volume(args.grid, kind="blobs", seed=3)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.1, 0.3, 2.8), fov_y_deg=45.0, near=0.3,
                         far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape,
                            SliceMarchConfig(matmul_dtype=mdt, scale=1.5))
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=args.k,
                                       adaptive_iters=2))
    regime = slicer.choose_axis(cam0)
    cams = [orbit(cam0, 0.02 * i, 0.01 * i) for i in range(16)]

    # ------------------------------------------- amortization (proxy tier)
    # the per-frame VDI FETCH (wire receive + decompress + dequantize) is
    # part of what one batch amortizes — "one VDI fetch and one device
    # dispatch across all viewers" — so it belongs in the frame cost.
    # Timed from AFTER publish returns: the producer's quantize/compress/
    # send is the render side's bill, not the serving tier's — folding it
    # in would inflate the very fixed cost the amortization gate divides
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    fpub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                        precision="qpack8")
    fsub = VDISubscriber(fpub.endpoint)
    try:
        time.sleep(0.3)
        fpub.publish(vdi, meta)                       # join + warm
        assert fsub.receive(timeout_ms=10000) is not None
        acc = 0.0
        for _ in range(args.iters):
            fpub.publish(vdi, meta)
            t1 = time.perf_counter()
            got = fsub.receive(timeout_ms=10000)
            assert got is not None and not hasattr(got, "kind")
            acc += time.perf_counter() - t1
        t_fetch = acc / args.iters
    finally:
        fpub.close()
        fsub.close()

    build = jax.jit(lambda c, d, ax: vdi_novel.vdi_to_rgba_volume(
        VDI(c, d), ax, spec, num_slices=NS))
    proxy = jax.block_until_ready(build(vdi.color, vdi.depth, axcam))
    t_build = _timeit(lambda: jax.block_until_ready(
        build(vdi.color, vdi.depth, axcam)), args.iters)
    # serve.march_scale=1.0: the proxy is pre-shaded at VDI resolution
    spec_new = slicer.make_spec(cam0, proxy.data.shape[-3:],
                                SliceMarchConfig(matmul_dtype=mdt,
                                                 scale=1.0),
                                axis_sign=regime)

    def batch_fn(n):
        stacked = vdi_novel.stack_cameras(cams[:n])
        f = jax.jit(lambda pd, po, ps, cs: vdi_novel.render_vdi_batch(
            None, None, spec, cs, W, H, tier="proxy",
            proxy=Volume(pd, po, ps), spec_new=spec_new))
        return lambda: jax.block_until_ready(
            f(proxy.data, proxy.origin, proxy.spacing, stacked))

    curve = {}
    for n in (1, 2, 4, 8, 16):
        t_batch = _timeit(batch_fn(n), args.iters)
        per_frame = t_fetch + t_build + t_batch
        curve[str(n)] = {
            "batch_ms": round(t_batch * 1e3, 2),
            "frame_ms": round(per_frame * 1e3, 2),
            "per_viewer_ms": round(per_frame / n * 1e3, 3),
            "viewers_per_second": round(n / per_frame, 1),
        }
    ratio16 = (curve["16"]["per_viewer_ms"] / curve["1"]["per_viewer_ms"])

    # one exact-tier point for the tier-cost ladder (small batch — the
    # exact tier unrolls, so its compile cost scales with the bucket)
    f_exact = jax.jit(lambda c, d, ax, cs: vdi_novel.render_vdi_batch(
        VDI(c, d), ax, spec, cs, W, H, tier="exact"))
    st2 = vdi_novel.stack_cameras(cams[:2])
    t_exact2 = _timeit(lambda: jax.block_until_ready(
        f_exact(vdi.color, vdi.depth, axcam, st2)), 1)

    # ------------------------------------------------------ parity verdict
    b = np.asarray(batch_fn(4)()[:4])
    single = jax.jit(lambda pd, po, ps, c: vdi_novel.render_vdi_proxy(
        Volume(pd, po, ps), c, W, H, spec_new))
    s = np.stack([np.asarray(single(proxy.data, proxy.origin,
                                    proxy.spacing, c)) for c in cams[:4]])
    parity_proxy = bool(np.array_equal(b, s))
    be = np.asarray(f_exact(vdi.color, vdi.depth, axcam, st2))
    se = np.stack([np.asarray(jax.jit(
        lambda c, d, ax, cc: vdi_novel.render_vdi_exact(
            VDI(c, d), ax, spec, cc, W, H))(vdi.color, vdi.depth, axcam,
                                            c)) for c in cams[:2]])
    parity_exact = bool(np.array_equal(be, se))

    # --------------------------------------- loopback latency + sheds
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher
    from scenery_insitu_tpu.serve import (ServeDrop, ViewerClient,
                                          ViewerFrame, ViewerServer)

    cfg = FrameworkConfig().with_overrides(
        f"serve.width={W}", f"serve.height={H}",
        f"serve.num_slices={NS}", f"serve.max_viewers={args.clients}",
        f"serve.batch_size={max(args.clients, 1)}",
        f"serve.buckets={json.dumps(sorted({1, 2, 4, 8, args.clients}))}",
        "serve.client_timeout_s=120")
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    srv = ViewerServer(cfg, connect=pub.endpoint, bind="tcp://127.0.0.1:0")
    # tier mix weighted toward the cheap tiers (one exact client per 4 —
    # the exact tier is the quality reference, not the scale path)
    tiers = ["proxy", "wire", "proxy", "exact"]
    clients = [ViewerClient(srv.endpoint, tier=tiers[i % 4])
               for i in range(args.clients)]
    shed_client = None
    latencies = []
    lat_by_tier = {}
    bytes_by_tier = {}
    sheds_seen = 0
    try:
        time.sleep(0.3)
        pub.publish(vdi, meta._replace(index=np.int32(0)))
        deadline = time.monotonic() + 60
        while srv.frame is None and time.monotonic() < deadline:
            srv.pump_stream(timeout_ms=100)
        assert srv.frame is not None, "server never adopted the frame"
        # hello handshake (tier negotiation) before the timed rounds
        for c in clients:
            c.hello(timeout_ms=0)
        welcomed = set()
        deadline = time.monotonic() + 30
        while len(welcomed) < len(clients) \
                and time.monotonic() < deadline:
            srv.run_once(timeout_ms=5)
            for c in clients:
                got = c.poll(timeout_ms=0)
                if isinstance(got, dict) and got.get("type") == "welcome":
                    welcomed.add(c.identity)
        assert len(welcomed) == len(clients), "hello handshake incomplete"
        for r in range(args.requests):
            t_sent = {}
            for i, c in enumerate(clients):
                c.request(orbit(cam0, 0.02 * i + 0.005 * r, 0.01 * i))
                t_sent[c.identity] = time.perf_counter()
            pending = set(t_sent)
            deadline = time.monotonic() + 120
            while pending and time.monotonic() < deadline:
                srv.run_once(timeout_ms=5)
                for c in clients:
                    if c.identity not in pending:
                        continue
                    got = c.poll(timeout_ms=0)
                    if isinstance(got, ViewerFrame):
                        dt = time.perf_counter() - t_sent[c.identity]
                        latencies.append(dt)
                        lat_by_tier.setdefault(got.tier, []).append(dt)
                        bytes_by_tier.setdefault(got.tier,
                                                 got.wire_bytes)
                        pending.discard(c.identity)
            assert not pending, f"unanswered clients in round {r}"
        # admission shed: one client past max_viewers (ledgered, typed)
        shed_client = ViewerClient(srv.endpoint, tier="proxy")
        shed_client.hello(timeout_ms=0)
        deadline = time.monotonic() + 30
        while sheds_seen == 0 and time.monotonic() < deadline:
            srv.run_once(timeout_ms=5)
            got = shed_client.poll(timeout_ms=0)
            if isinstance(got, ServeDrop) and got.kind == "shed":
                sheds_seen = 1
        server_stats = dict(srv.stats)
    finally:
        for c in clients:
            c.close()
        if shed_client is not None:
            shed_client.close()
        srv.close()
        pub.close()

    lat_ms = sorted(x * 1e3 for x in latencies)

    def quantile(values, q):
        return values[min(len(values) - 1, int(q * (len(values) - 1)))]

    pick = lambda q: quantile(lat_ms, q)
    ledger = obs.ledger()
    verdicts = {
        "amortization_n16_leq_0p25x": ratio16 <= 0.25,
        "parity_proxy_bitwise": parity_proxy,
        "parity_exact_bitwise": parity_exact,
        "sheds_ledgered_not_raised": sheds_seen == 1 and any(
            e["component"] == "serve.shed" for e in ledger),
    }
    out = {
        "metric": "serve_bench",
        "value": round(ratio16, 4),
        "unit": "per_viewer_cost_ratio_n16_vs_n1",
        "platform": platform,
        "config": {"grid": args.grid, "k": args.k, "width": W,
                   "height": H, "num_slices": NS,
                   "vdi_shape": list(np.asarray(vdi.color).shape),
                   "proxy_shape": list(np.asarray(proxy.data).shape),
                   "clients": args.clients, "requests": args.requests,
                   "iters": args.iters},
        "amortization": {"fetch_ms": round(t_fetch * 1e3, 2),
                         "proxy_build_ms": round(t_build * 1e3, 2),
                         "proxy": curve,
                         "exact_batch2_ms": round(t_exact2 * 1e3, 2)},
        "latency_ms": {"n": len(lat_ms), "p50": round(pick(0.50), 2),
                       "p90": round(pick(0.90), 2),
                       "p99": round(pick(0.99), 2),
                       "max": round(lat_ms[-1], 2),
                       "by_tier_p99": {
                           t: round(quantile(sorted(x * 1e3 for x in v),
                                             0.99), 2)
                           for t, v in sorted(lat_by_tier.items())}},
        "bytes_per_viewer": {t: int(b) for t, b in
                             sorted(bytes_by_tier.items())},
        "server_stats": server_stats,
        "verdicts": verdicts,
        "degradations": ledger,
    }
    blob = json.dumps(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(out, indent=2) + "\n")
    print(blob, flush=True)
    # exit code gates the CORRECTNESS verdicts only — the amortization
    # ratio is a measurement (the committed artifact documents it; a
    # noisy shared runner must not flip a timing number into a failure)
    hard = ("parity_proxy_bitwise", "parity_exact_bitwise",
            "sheds_ledgered_not_raised")
    return 0 if all(verdicts[k] for k in hard) else 1


if __name__ == "__main__":
    raise SystemExit(main())
