"""Bench regression sentinel: schema-aware gates over the committed
measurement artifacts (docs/OBSERVABILITY.md, "Regression sentinel").

The committed artifacts in benchmarks/results/ are the repo's memory of
what the system could do — but nothing re-reads them, so a PR that
quietly halves the delta-encoding win or sinks weak-scaling efficiency
ships green. This module closes that loop:

- **self-check** (default): extract the gated keys from every committed
  artifact of a known family and assert each invariant floor still
  holds (the LOD ladder still clears x2 march-FLOP reduction at the
  PSNR floor, weak scaling stays above 0.7, scenario parity stays
  bitwise, ...). This is what CI runs — it fails if someone commits a
  regressed artifact.
- **check** (``--fresh FILE``): compare a freshly produced artifact
  against the committed baseline of the same family, key by key, each
  key with its own direction and noise band — timing-derived keys get
  wide bands (CPU CI jitter is real), modeled/deterministic keys get
  tight ones. Exit 1 on any move beyond the band in the worse
  direction, or any floor violation.
- **--record**: append one row per checked artifact to
  ``benchmarks/results/trajectory.jsonl`` so the history of every gated
  number is a ledger, not diff archaeology.

Unknown-schema artifacts are skipped and ledgered
(``regression.artifact``); a missing committed baseline in check mode
degrades that artifact's gate to record-only (``regression.baseline``)
instead of failing the world on a new benchmark's first landing.

No JAX import — safe to run anywhere, any time (CI's fleet-obs lane).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scenery_insitu_tpu import obs  # noqa: E402

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results")
TRAJECTORY = os.path.join(RESULTS, "trajectory.jsonl")

_UNKNOWN_REASON = ("artifact schema not recognized by any gate family; "
                   "it is summarized but not regression-gated — add an "
                   "extractor to benchmarks/regression_gate.py")
_NOBASE_REASON = ("no committed baseline artifact for this family; the "
                  "gate degrades to record-only for the first landing — "
                  "commit the fresh artifact to arm it")
_BADJSON_REASON = ("artifact is not parseable JSON; it cannot be gated "
                   "and is skipped — regenerate or remove it")

# band semantics: fractional tolerance on the baseline value before a
# worse-direction move counts as a regression. Two tiers only, so the
# table stays auditable: modeled/deterministic numbers vs wall-clock.
DET = 0.01      # modeled, counted, or bitwise-derived quantities
NOISY = 0.35    # wall-clock-derived quantities on shared CPU runners


class Gate:
    """One gated number: direction, noise band, optional hard floor
    (worst absolute value acceptable regardless of the baseline)."""

    __slots__ = ("value", "better", "band", "floor")

    def __init__(self, value, better="higher", band=NOISY, floor=None):
        self.value = float(value)
        self.better = better
        self.band = band
        self.floor = floor

    def violates_floor(self) -> bool:
        if self.floor is None:
            return False
        if self.better == "higher":
            return self.value < self.floor
        return self.value > self.floor

    def regressed_vs(self, base: "Gate") -> bool:
        tol = abs(base.value) * self.band
        if self.better == "higher":
            return self.value < base.value - tol
        return self.value > base.value + tol


# ---------------------------------------------------------------- families

def _x_lod(doc: dict) -> Dict[str, Gate]:
    floor = float(doc.get("psnr_floor_db") or 40.0)
    return {
        "flop_reduction_at_floor": Gate(doc["value"], "higher", NOISY,
                                        floor=2.0),
        "psnr_db": Gate(doc["psnr_db"], "higher", DET, floor=floor),
    }


def _x_serve(doc: dict) -> Dict[str, Gate]:
    out = {"per_viewer_cost_ratio_n16": Gate(doc["value"], "lower", NOISY,
                                             floor=1.0)}
    bpv = doc.get("bytes_per_viewer") or {}
    if "wire" in bpv and "exact" in bpv:
        # the q-packed wire must stay strictly cheaper than raw slabs
        out["wire_bytes_ratio"] = Gate(bpv["wire"] / max(1, bpv["exact"]),
                                       "lower", DET, floor=1.0)
    return out


def _x_delta(doc: dict) -> Dict[str, Gate]:
    out = {}
    for scene, sc in sorted((doc.get("scenes") or {}).items()):
        wire, march = sc.get("wire") or {}, sc.get("march") or {}
        if "bytes_ratio" in wire:
            # fast scenes can honestly land a hair over 1.0 (delta can't
            # win on an incompressible scene) — floor at pathology, gate
            # the rest via the baseline band
            out[f"{scene}.wire_bytes_ratio"] = Gate(
                wire["bytes_ratio"], "lower", DET, floor=1.05)
        if "skip_frac" in march:
            out[f"{scene}.march_skip_frac"] = Gate(
                march["skip_frac"], "higher", DET)
        if "max_abs_err_vs_off" in march:
            out[f"{scene}.march_max_abs_err"] = Gate(
                march["max_abs_err_vs_off"], "lower", DET, floor=1e-5)
    return out


def _x_rebalance(doc: dict) -> Dict[str, Gate]:
    out = {"straggler_reduction": Gate(doc["value"], "higher", NOISY,
                                       floor=1.0)}
    if "value_bricks" in doc:
        out["straggler_reduction_bricks"] = Gate(
            doc["value_bricks"], "higher", NOISY, floor=1.0)
    mod = doc.get("modeled") or {}
    if "straggler_planned" in mod and "straggler_even" in mod:
        out["modeled_planned_over_even"] = Gate(
            mod["straggler_planned"] / mod["straggler_even"],
            "lower", DET, floor=1.0)
    return out


def _x_hier(doc: dict) -> Dict[str, Gate]:
    return {"weak_efficiency": Gate(doc["value"], "higher", NOISY,
                                    floor=0.7)}


def _x_scenario(doc: dict) -> Dict[str, Gate]:
    return {
        "scenarios_registered": Gate(doc["value"], "higher", 0.0,
                                     floor=4),
        "parity_ok": Gate(1.0 if doc.get("parity_ok") else 0.0,
                          "higher", 0.0, floor=1.0),
    }


def _x_waves(doc: dict) -> Dict[str, Gate]:
    out = {}
    for key, e in sorted((doc.get("exchange") or {}).items()):
        mod = e.get("modeled") or {}
        if "overlap_hidden_frac" in mod:
            out[f"{key}.overlap_hidden_frac"] = Gate(
                mod["overlap_hidden_frac"], "higher", DET, floor=0.5)
    par = doc.get("schedule_parity") or {}
    if "max_abs_diff_color" in par:
        out["schedule_parity_max_abs_diff"] = Gate(
            par["max_abs_diff_color"], "lower", DET, floor=1e-5)
    return out


def _x_divergence(doc: dict) -> Dict[str, Gate]:
    """The attribution plane's divergence report (ISSUE 18): gate the
    model's stated blind spot and each lever's share divergence. All
    shares are wall-clock-derived on shared runners → NOISY band; the
    floors only catch a capture whose attribution collapsed entirely
    (unmodeled_share ~1.0 means the scopes joined nothing new)."""
    out = {}
    if doc.get("unmodeled_share") is not None:
        out["unmodeled_share"] = Gate(doc["unmodeled_share"], "lower",
                                      NOISY, floor=0.99)
    for lever, e in sorted((doc.get("levers") or {}).items()):
        if e.get("share_delta") is not None:
            out[f"{lever}.abs_share_delta"] = Gate(
                abs(e["share_delta"]), "lower", NOISY)
    return out


def _x_delivery(doc: dict) -> Dict[str, Gate]:
    """The async delivery plane's A/B (ISSUE 19): the exposed-host
    ratio is the tentpole number (async must keep the loop thread out
    of the sink work — acceptance <= 0.5x serial, hence the floor);
    the bitwise verdicts are the correctness contract and gate at
    exactly 1."""
    out = {}
    if doc.get("value") is not None:
        out["exposed_host_ratio"] = Gate(doc["value"], "lower", NOISY,
                                         floor=0.5)
    out["bit_identical"] = Gate(
        1.0 if doc.get("bit_identical_all") else 0.0, "higher", 0.0,
        floor=1.0)
    out["ordering_fifo"] = Gate(
        1.0 if doc.get("ordering_fifo_all") else 0.0, "higher", 0.0,
        floor=1.0)
    te = doc.get("tile_encode") or {}
    if "byte_identical" in te:
        out["tile_encode_byte_identical"] = Gate(
            1.0 if te["byte_identical"] else 0.0, "higher", 0.0,
            floor=1.0)
    return out


# (family name, matcher over the parsed doc, extractor)
FAMILIES: Tuple[Tuple[str, object, object], ...] = (
    ("lod_ladder",
     lambda d: str(d.get("metric", "")).startswith("lod_ladder"), _x_lod),
    ("serve_bench",
     lambda d: d.get("metric") == "serve_bench", _x_serve),
    ("delta_ab",
     lambda d: d.get("kind") == "delta_ab", _x_delta),
    ("rebalance_ab",
     lambda d: str(d.get("metric", "")).startswith("rebalance_ab"),
     _x_rebalance),
    ("hier_weak_scaling",
     lambda d: str(d.get("metric", "")).startswith("hier_weak_scaling"),
     _x_hier),
    ("scenario_bench",
     lambda d: str(d.get("metric", "")).startswith("scenario_bench"),
     _x_scenario),
    ("composite_ab",
     lambda d: isinstance(d.get("exchange"), dict), _x_waves),
    ("divergence_report",
     lambda d: d.get("type") == "divergence_report", _x_divergence),
    ("delivery_ab",
     lambda d: str(d.get("metric", "")).startswith("delivery_ab"),
     _x_delivery),
)


def load_artifact(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        obs.degrade("regression.artifact", os.path.basename(path),
                    "skipped", _BADJSON_REASON, warn=False)
        return None
    return doc if isinstance(doc, dict) else None


def classify(doc: dict) -> Optional[Tuple[str, Dict[str, Gate]]]:
    """(family, gates) for a known artifact schema, else None (ledgered
    by the caller that wanted it gated)."""
    for name, match, extract in FAMILIES:
        if match(doc):
            try:
                return name, extract(doc)
            except (KeyError, TypeError, ZeroDivisionError):
                obs.degrade("regression.artifact", name, "skipped",
                            _UNKNOWN_REASON, warn=False)
                return None
    return None


def committed_baseline(family: str,
                       results_dir: str = RESULTS
                       ) -> Optional[Tuple[str, Dict[str, Gate]]]:
    """Newest committed artifact of the family (lexicographically last
    wins — the rN naming convention sorts by PR)."""
    best = None
    for path in sorted(glob.glob(os.path.join(results_dir, "*.json"))):
        doc = load_artifact(path)
        if doc is None:
            continue
        got = classify(doc)
        if got and got[0] == family:
            best = (os.path.basename(path), got[1])
    return best


# ------------------------------------------------------------------ checks

def check_floors(name: str, gates: Dict[str, Gate]) -> List[str]:
    return [f"{name}: {k} = {g.value:g} violates floor {g.floor:g} "
            f"({g.better} is better)"
            for k, g in sorted(gates.items()) if g.violates_floor()]


def check_fresh(fresh_path: str, baseline_path: Optional[str] = None,
                results_dir: str = RESULTS) -> Tuple[List[str], dict]:
    """(failures, report) for a fresh artifact vs its family baseline."""
    doc = load_artifact(fresh_path)
    if doc is None:
        return [f"{fresh_path}: unreadable artifact"], {}
    got = classify(doc)
    if got is None:
        obs.degrade("regression.artifact", os.path.basename(fresh_path),
                    "skipped", _UNKNOWN_REASON, warn=False)
        return [], {"family": None, "keys": {}}
    family, gates = got
    failures = check_floors(os.path.basename(fresh_path), gates)
    base_name, base = None, None
    if baseline_path:
        bdoc = load_artifact(baseline_path)
        bgot = classify(bdoc) if bdoc else None
        if bgot:
            base_name, base = os.path.basename(baseline_path), bgot[1]
    else:
        found = committed_baseline(family, results_dir)
        if found:
            base_name, base = found
    report = {"family": family, "baseline": base_name,
              "keys": {k: g.value for k, g in sorted(gates.items())}}
    if base is None:
        obs.degrade("regression.baseline", family, "record_only",
                    _NOBASE_REASON, warn=False)
        return failures, report
    for k, g in sorted(gates.items()):
        if k not in base:
            continue            # new key: arms on the next baseline
        if g.regressed_vs(base[k]):
            failures.append(
                f"{family}: {k} regressed {base[k].value:g} -> "
                f"{g.value:g} (band {g.band:.0%}, {g.better} is better, "
                f"baseline {base_name})")
    for k in sorted(set(base) - set(gates)):
        failures.append(f"{family}: key {k} present in baseline "
                        f"{base_name} but missing from fresh artifact")
    return failures, report


def self_check(results_dir: str = RESULTS) -> Tuple[List[str], dict]:
    """Floors over every committed artifact of a known family."""
    failures: List[str] = []
    families: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(results_dir, "*.json"))):
        doc = load_artifact(path)
        if doc is None:
            continue
        got = classify(doc)
        if got is None:
            continue            # legacy/unmatched schemas are summarized,
        family, gates = got     # not gated — by design, not by accident
        name = os.path.basename(path)
        failures += check_floors(name, gates)
        families.setdefault(family, {})[name] = {
            k: g.value for k, g in sorted(gates.items())}
    report = {"type": "regression_report", "mode": "self-check",
              "families": families, "failures": failures,
              "ok": not failures}
    return failures, report


def record_trajectory(report: dict, artifact: str,
                      path: str = TRAJECTORY) -> None:
    row = {"type": "trajectory", "ts": round(time.time(), 3),
           "artifact": artifact, "family": report.get("family"),
           "baseline": report.get("baseline"),
           "keys": report.get("keys", {})}
    with open(path, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fresh", help="fresh artifact to gate against the "
                    "committed baseline of its family")
    ap.add_argument("--baseline", help="explicit baseline artifact "
                    "(default: newest committed artifact of the family)")
    ap.add_argument("--results-dir", default=RESULTS)
    ap.add_argument("--record", action="store_true",
                    help="append a trajectory row for the checked artifact")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    args = ap.parse_args(argv)

    if args.fresh:
        failures, report = check_fresh(args.fresh, args.baseline,
                                       args.results_dir)
        if args.record and report.get("family"):
            record_trajectory(report, os.path.basename(args.fresh),
                              os.path.join(args.results_dir,
                                           "trajectory.jsonl"))
    else:
        failures, report = self_check(args.results_dir)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for f in failures:
            print(f"REGRESSION: {f}")
        if not failures:
            n = (len(report.get("families", {}))
                 or (1 if report.get("family") else 0))
            print(f"regression gate: OK ({n} famil"
                  f"{'y' if n == 1 else 'ies'} gated)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
