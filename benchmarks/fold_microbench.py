"""Supersegment-fold schedule microbenchmark.

The slice march = resampling matmuls (MXU) + a per-pixel fold
(`ops.supersegments.push`) over the depth-ordered sample stream. The
round-3 512^3 TPU captures put the WRITE march at ~390 ms/frame while the
counting march costs ~34 ms — the fold schedule, not the matmuls, owns the
frame budget (bench_tpu_r3_512.json vs bench_tpu_r3_hist.json). This
harness times the fold alone, on synthetic streams generated on the fly
inside the scan (so a 512-slice 640^2 stream never materializes 2.7 GB),
for each schedule:

  xla          lax.scan over chunks, C sequential ss.push per chunk
               (ops/slicer.py generate_vdi_mxu fold="xla")
  seg          round-4 segmented-scan fold, pure XLA (ops/seg_fold.py,
               fold="seg"): start flags / ids / transmittance parallel,
               K-state touched once per chunk
  pallas_seg   the seg fold's VMEM pixel-strip twin (ops/pallas_seg.py,
               fold="pallas_seg" — the round-4 TPU default)
  pallas_seg_c pallas_seg with COMPACT depth (sk ratios + length,
               t = sk*length computed in-kernel — the round-5 production
               schedule; the [C,2,H,W] depth planes never exist in HBM)
  pallas       pm.fold_chunk per chunk (fold="pallas") — since the
               two-phase rewrite this IS the events schedule with a
               rolled phase 2
  pallas_t16/32  same kernel, taller strips (monkeypatched TILE_H)
  events       local phase-2-UNROLLED twin of the production kernel
               (rolled-vs-unrolled phase-2 A/B; see _events_kernel)
  scratch      twin writing close events to an explicit VMEM scratch
               array instead of SSA live ranges (see _scratch_kernel)
  count        pm.count_multi_chunk with 1 candidate — the O(1)-state
               floor: stream generation + predicate, no K-slot writes
  none         stream generation only (the harness overhead floor)
  fused        shade-in-kernel seg fold (ops/pallas_seg.fused_fold_chunk,
               fold="pallas_fused"): consumes the 1-channel raw VALUE
               stream, TF + opacity + depths computed in-kernel
  fused_stream whole-march fused fold (fold="fused_stream"): chunk loop
               inside the kernel grid, [K] state VMEM-resident per strip
               (one HBM round trip per march); stream pre-materialized
  tf_pallas_seg / tf_xla_seg
               same value stream shaded in XLA feeding pallas_seg / seg —
               the controlled baselines for 'fused' (this family is
               parity-checked against tf_xla_seg, not the rgba family)

Usage: python benchmarks/fold_microbench.py [--grid 256] [--k 16]
       [--chunk 16] [--iters 5] [--variants xla,pallas,...]
Prints one JSON line per variant: {"variant", "ms_per_march", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.ops import pallas_march as pm
from scenery_insitu_tpu.ops import pallas_seg as psg
from scenery_insitu_tpu.ops import seg_fold as sfold
from scenery_insitu_tpu.ops import supersegments as ss


def stream_chunk(ci: jnp.ndarray, c: int, h: int, w: int):
    """Deterministic synthetic sample chunk [C,4,H,W] + t0/t1 [C,H,W].

    Mimics a real generation stream: two density blobs along depth with an
    empty gap between them (so segments start, accumulate, break on the
    gap, and re-open), color drifting with depth (so the premultiplied-RGB
    break metric fires at plausible rates). ~10 elementwise ops per sample
    — negligible next to the ~120-op fold it feeds.
    """
    s = ci * c + jnp.arange(c, dtype=jnp.float32)          # [C]
    jj = jnp.arange(h, dtype=jnp.float32)[:, None]         # [H,1]
    ii = jnp.arange(w, dtype=jnp.float32)[None, :]         # [1,W]
    # per-pixel blob centers drift across the image
    c0 = 60.0 + 0.15 * jj + 0.05 * ii                      # [H,W]
    c1 = c0 + 90.0
    d0 = jnp.abs(s[:, None, None] - c0[None])              # [C,H,W]
    d1 = jnp.abs(s[:, None, None] - c1[None])
    alpha = jnp.maximum(jnp.maximum(0.0, 0.9 - d0 * 0.03),
                        jnp.maximum(0.0, 0.7 - d1 * 0.025))
    shade = 0.5 + 0.5 * jnp.sin(s * 0.21)[:, None, None]
    rgba = jnp.stack([alpha * shade, alpha * (1.0 - shade),
                      alpha * 0.3, alpha], axis=1)         # [C,4,H,W]
    t0 = (s[:, None, None] + 0.0) * 0.01 + jj[None] * 0.0 + ii[None] * 0.0
    t0 = jnp.broadcast_to(t0, (c, h, w))
    t1 = t0 + 0.01
    return rgba, t0, t1


def stream_val_chunk(ci: jnp.ndarray, c: int, h: int, w: int):
    """Deterministic RAW VALUE chunk [C,H,W] + per-slice depth ratios
    [C] — the fused-kernel feed (shading happens downstream, either
    in-kernel or in XLA, so 'fused' vs 'tf_*' variants consume the SAME
    stream and are directly comparable; NOT comparable to the rgba-stream
    variants above, whose colors no 1-D transfer function can produce)."""
    s = ci * c + jnp.arange(c, dtype=jnp.float32)
    jj = jnp.arange(h, dtype=jnp.float32)[:, None]
    ii = jnp.arange(w, dtype=jnp.float32)[None, :]
    c0 = 60.0 + 0.15 * jj + 0.05 * ii
    c1 = c0 + 90.0
    d0 = jnp.abs(s[:, None, None] - c0[None])
    d1 = jnp.abs(s[:, None, None] - c1[None])
    val = jnp.maximum(jnp.maximum(0.0, 0.9 - d0 * 0.03),
                      jnp.maximum(0.0, 0.7 - d1 * 0.025))
    # a dead-sample margin exercises the sentinel path
    val = jnp.where((jj < 2)[None] | (ii < 2)[None], -1.0, val)
    sk = 1.0 + s * 0.01
    return val, sk


def _fused_tf():
    from scenery_insitu_tpu.core.transfer import TransferFunction

    return TransferFunction.from_polylines(
        [(0.0, 0.0), (0.2, 0.1), (0.8, 0.8)],
        np.asarray([0.0, 0.5, 1.0]),
        np.asarray([[0.1, 0.2, 0.9], [0.9, 0.4, 0.1], [1.0, 0.9, 0.2]],
                   np.float32))


def _shade_xla(val, sk, tf, length, ratio, ds):
    """XLA twin of the fused kernel's in-kernel shading — produces the
    rgba/t0/t1 streams slice_march's non-raw path would feed the fold."""
    from scenery_insitu_tpu.ops.sampling import adjust_opacity

    x = jnp.clip(val, 0.0, 1.0)
    rgb, a = tf(x)
    a = jnp.where(val < -0.5, 0.0, a)
    a = adjust_opacity(a, ratio[None])
    rgba = jnp.concatenate([jnp.moveaxis(rgb, -1, 1) * a[:, None],
                            a[:, None]], axis=1)
    t0 = sk[:, None, None] * length[None]
    t1 = (sk + ds)[:, None, None] * length[None]
    return rgba, t0, t1


def _events_kernel(rgba_ref, td_ref, thr_ref,
                   ci_, di_, smi_, co, do_, smo, *, max_k: int):
    """Phase-2-UNROLLED twin of the production two-phase fold.

    This prototype was promoted into pm._fold_kernel (which replaced the
    original per-slice load/store schedule after the 2026-07-30 512^3
    captures showed it at ~390 ms/march). The production kernel rolls
    phase 2 over K with a fori_loop + dynamic ref writes to keep the
    kernel graph small; this copy keeps the fully-unrolled K×C phase 2,
    so '--variants pallas,events' A/Bs rolled vs unrolled phase-2
    lowering on hardware. It deliberately omits count/gap_eps support;
    if ops/supersegments.py semantics change, update both (the --check
    mode and tests/test_pallas_march.py catch drift).

    State packing (small): smi_/smo f32[12, TH, W] =
      seg_rgba[0:4], seg_start[4], seg_end[5], prev_rgb[6:9],
      open[9], prev_empty[10], k[11] (f32-encoded count).
    Big state: ci_/co color [K,4,TH,W]; di_/do_ depth [K,2,TH,W].
    """
    nc = rgba_ref.shape[0]
    thr = thr_ref[...]
    sm = smi_[...]
    seg_rgba = sm[0:4]
    seg_start, seg_end = sm[4], sm[5]
    prev_rgb = sm[6:9]
    open_ = sm[9] > 0.5
    prev_empty = sm[10] > 0.5
    kcnt = sm[11]

    ev = []                                   # per-slice close records
    for i in range(nc):
        rgba = rgba_ref[i]
        t0 = td_ref[i, 0]
        t1 = td_ref[i, 1]
        is_empty = rgba[3] < ss.EMPTY_ALPHA
        d = rgba[:3] - prev_rgb
        diff = jnp.sqrt(jnp.sum(d * d, axis=0))
        want_break = ((~is_empty & ~prev_empty & (diff > thr))
                      | (is_empty & ~prev_empty))
        do_close = open_ & want_break & (kcnt < max_k - 1)
        # record the close event; slot = kcnt at close time, else -1
        ev.append((jnp.where(do_close, kcnt, -1.0),
                   jnp.where(do_close[None], seg_rgba, 0.0),
                   jnp.where(do_close, seg_start, 0.0),
                   jnp.where(do_close, seg_end, 0.0)))
        kcnt = jnp.where(do_close, kcnt + 1.0, kcnt)
        open_ = open_ & ~do_close
        start_new = ~is_empty & ~open_
        accumulate = ~is_empty & open_
        seg_rgba = jnp.where(start_new[None], rgba,
                             jnp.where(accumulate[None],
                                       seg_rgba + (1.0 - seg_rgba[3:4])
                                       * rgba, seg_rgba))
        seg_start = jnp.where(start_new, t0, seg_start)
        seg_end = jnp.where(start_new | accumulate, t1, seg_end)
        open_ = open_ | start_new
        prev_rgb = jnp.where(is_empty[None], prev_rgb, rgba[:3])
        prev_empty = is_empty

    smo[...] = jnp.concatenate([
        seg_rgba, seg_start[None], seg_end[None], prev_rgb,
        open_.astype(jnp.float32)[None],
        prev_empty.astype(jnp.float32)[None], kcnt[None]])

    # phase 2: fold events into the K-state, one slot row at a time
    for kk in range(max_k):
        hit = None
        acc_c = None
        acc_s = None
        acc_e = None
        for slot, c_rgba, c_s, c_e in ev:
            m = slot == kk                     # [TH, W] bool
            mf = m.astype(jnp.float32)
            hit = m if hit is None else (hit | m)
            acc_c = c_rgba * mf[None] if acc_c is None \
                else acc_c + c_rgba * mf[None]
            acc_s = c_s * mf if acc_s is None else acc_s + c_s * mf
            acc_e = c_e * mf if acc_e is None else acc_e + c_e * mf
        # a slot is closed at most once over the whole march, so + is a
        # select; start/end need where (init is +inf, not 0)
        co[kk] = ci_[kk] + acc_c
        do_[kk, 0] = jnp.where(hit, acc_s, di_[kk, 0])
        do_[kk, 1] = jnp.where(hit, acc_e, di_[kk, 1])


def _fpp_events(c: int, k: int) -> int:
    """Per-pixel-column VMEM estimate for the events/scratch twins: the
    shared production budget (pm.strip_fpp) minus the count plane the
    twins don't carry — so they width-tile to comparable geometry
    instead of OOMing Mosaic's scoped VMEM at full-width 512 strips."""
    return pm.strip_fpp(c, k, count_plane=False)


def events_fold_chunk(big, small, rgba, t0, t1, threshold, *, max_k: int,
                      tile_h: int = 8):
    """Driver for `_events_kernel`: big = (color [K,4,H,W], depth
    [K,2,H,W]), small = f32[12,H,W] (see kernel docstring)."""
    import functools

    from jax.experimental import pallas as pl
    color, depth = big
    _, _, h, w = color.shape
    c = rgba.shape[0]
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    td = jnp.stack([t0, t1], axis=1)
    kk = color.shape[0]
    wb = pm._pick_block_w(w, 4 * tile_h * _fpp_events(c, kk))
    row = lambda *lead: pl.BlockSpec(lead + (tile_h, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    out = pl.pallas_call(
        functools.partial(_events_kernel, max_k=max_k),
        grid=(h // tile_h, pl.cdiv(w, wb)),
        in_specs=[row(c, 4), row(c, 2), row(),
                  row(kk, 4), row(kk, 2), row(12)],
        out_specs=[row(kk, 4), row(kk, 2), row(12)],
        out_shape=[jax.ShapeDtypeStruct(color.shape, jnp.float32),
                   jax.ShapeDtypeStruct(depth.shape, jnp.float32),
                   jax.ShapeDtypeStruct((12, h, w), jnp.float32)],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=pm.should_interpret(),
    )(rgba, td, threshold, color, depth, small)
    return (out[0], out[1]), out[2]


def _scratch_kernel(rgba_ref, td_ref, thr_ref,
                    ci_, di_, smi_, co, do_, smo,
                    ev_ref, *, max_k: int):
    """Scratch-buffer twin of the production two-phase fold: identical
    phases, but the per-slice close events are WRITTEN to an explicit
    VMEM scratch array (`ev_ref` f32[C, 7, TH, W]: slot, rgba[4], t0,
    t1) as they are produced, instead of carried as SSA values until
    phase 2. Hypothesis under test ('--variants scratch'): the
    production kernel's 7xC deferred event values live across the whole
    unrolled slice loop, and Mosaic's spill schedule for those live
    ranges — not the state machine or the K-state traffic — is where
    the fold's 300x-above-floor cost hides. If this kernel beats the
    production one on hardware, the scratch layout gets promoted."""
    nc = rgba_ref.shape[0]
    thr = thr_ref[...]
    sm = smi_[...]
    seg_rgba = sm[0:4]
    seg_start, seg_end = sm[4], sm[5]
    prev_rgb = sm[6:9]
    open_ = sm[9] > 0.5
    prev_empty = sm[10] > 0.5
    kcnt = sm[11]

    for i in range(nc):
        rgba = rgba_ref[i]
        t0 = td_ref[i, 0]
        t1 = td_ref[i, 1]
        is_empty = rgba[3] < ss.EMPTY_ALPHA
        d = rgba[:3] - prev_rgb
        diff = jnp.sqrt(jnp.sum(d * d, axis=0))
        want_break = ((~is_empty & ~prev_empty & (diff > thr))
                      | (is_empty & ~prev_empty))
        do_close = open_ & want_break & (kcnt < max_k - 1)
        ev_ref[i] = jnp.concatenate([
            jnp.where(do_close, kcnt, -1.0)[None],
            jnp.where(do_close[None], seg_rgba, 0.0),
            jnp.where(do_close, seg_start, 0.0)[None],
            jnp.where(do_close, seg_end, 0.0)[None]])
        kcnt = jnp.where(do_close, kcnt + 1.0, kcnt)
        open_ = open_ & ~do_close
        start_new = ~is_empty & ~open_
        accumulate = ~is_empty & open_
        seg_rgba = jnp.where(start_new[None], rgba,
                             jnp.where(accumulate[None],
                                       seg_rgba + (1.0 - seg_rgba[3:4])
                                       * rgba, seg_rgba))
        seg_start = jnp.where(start_new, t0, seg_start)
        seg_end = jnp.where(start_new | accumulate, t1, seg_end)
        open_ = open_ | start_new
        prev_rgb = jnp.where(is_empty[None], prev_rgb, rgba[:3])
        prev_empty = is_empty

    smo[...] = jnp.concatenate([
        seg_rgba, seg_start[None], seg_end[None], prev_rgb,
        open_.astype(jnp.float32)[None],
        prev_empty.astype(jnp.float32)[None], kcnt[None]])

    import jax as _jax
    from jax.experimental import pallas as _pl

    def slot_body(kk, _):
        ev = ev_ref[...]                       # [C, 7, TH, W]
        m = ev[:, 0] == kk.astype(jnp.float32)
        mf = m.astype(jnp.float32)
        hit = jnp.any(m, axis=0)
        acc_c = jnp.sum(ev[:, 1:5] * mf[:, None], axis=0)
        acc_s = jnp.sum(ev[:, 5] * mf, axis=0)
        acc_e = jnp.sum(ev[:, 6] * mf, axis=0)
        co[_pl.dslice(kk, 1)] = (ci_[_pl.dslice(kk, 1)] + acc_c[None])
        drow = di_[_pl.dslice(kk, 1)]
        do_[_pl.dslice(kk, 1)] = jnp.stack(
            [jnp.where(hit, acc_s, drow[0, 0]),
             jnp.where(hit, acc_e, drow[0, 1])])[None]
        return 0

    _jax.lax.fori_loop(0, max_k, slot_body, 0)


def scratch_fold_chunk(big, small, rgba, t0, t1, threshold, *,
                       max_k: int, tile_h: int = 8):
    """Driver for `_scratch_kernel` (same state layout as events_*)."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    color, depth = big
    _, _, h, w = color.shape
    c = rgba.shape[0]
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    td = jnp.stack([t0, t1], axis=1)
    kk = color.shape[0]
    wb = pm._pick_block_w(w, 4 * tile_h * _fpp_events(c, kk))
    row = lambda *lead: pl.BlockSpec(lead + (tile_h, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    out = pl.pallas_call(
        functools.partial(_scratch_kernel, max_k=max_k),
        grid=(h // tile_h, pl.cdiv(w, wb)),
        in_specs=[row(c, 4), row(c, 2), row(),
                  row(kk, 4), row(kk, 2), row(12)],
        out_specs=[row(kk, 4), row(kk, 2), row(12)],
        out_shape=[jax.ShapeDtypeStruct(color.shape, jnp.float32),
                   jax.ShapeDtypeStruct(depth.shape, jnp.float32),
                   jax.ShapeDtypeStruct((12, h, w), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((c, 7, tile_h, wb), jnp.float32)],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=pm.should_interpret(),
    )(rgba, td, threshold, color, depth, small)
    return (out[0], out[1]), out[2]


def events_init(k: int, h: int, w: int):
    color = jnp.zeros((k, 4, h, w), jnp.float32)
    depth = jnp.full((k, 2, h, w), jnp.inf, jnp.float32)
    small = jnp.zeros((12, h, w), jnp.float32)
    small = small.at[10].set(1.0)             # prev_empty = True
    return (color, depth), small


def events_finalize(big, small):
    """Close the trailing open segment exactly like ss.finalize."""
    color, depth = big
    st = ss.SegState(
        out_color=color, out_start=depth[:, 0], out_end=depth[:, 1],
        k=small[11].astype(jnp.int32), open_=small[9] > 0.5,
        seg_rgba=small[0:4], seg_start=small[4], seg_end=small[5],
        prev_rgb=small[6:9], prev_empty=small[10] > 0.5)
    return ss.finalize(st)


def build(variant: str, s_total: int, c: int, k: int, h: int, w: int):
    nchunks = s_total // c
    thr = jnp.full((h, w), 0.35, jnp.float32)

    if variant == "xla":
        def run():
            def body(st, ci):
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                for i in range(c):
                    st = ss.push(st, k, thr, rgba[i], t0[i], t1[i])
                return st, None
            st, _ = jax.lax.scan(body, ss.init_state(k, h, w),
                                 jnp.arange(nchunks))
            return ss.finalize(st)
    elif variant == "seg":
        def run():
            def body(st, ci):
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                return sfold.seg_fold_chunk(st, rgba, t0, t1, thr,
                                            max_k=k), None
            st, _ = jax.lax.scan(body, sfold.init_seg_state(k, h, w),
                                 jnp.arange(nchunks))
            return sfold.seg_finalize(st)
    elif variant == "pallas_seg":
        def run():
            # packed carry — the production schedule (see slicer)
            def body(packed, ci):
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                return psg.fold_chunk_packed(packed, rgba, t0, t1, thr,
                                             max_k=k), None
            packed, _ = jax.lax.scan(body, psg.init_seg_packed(k, h, w),
                                     jnp.arange(nchunks))
            return sfold.seg_finalize(psg.unpack_seg_state(packed))
    elif variant == "pallas_seg_c":
        # COMPACT depth form — the round-5 production schedule: the
        # kernel computes t = sk*length in-kernel, so the [C,2,H,W]
        # depth planes never exist (stream_chunk's t0 = s*0.01 with
        # length ≡ 1 is exactly this outer product, so parity against
        # the xla reference is exact)
        length1 = jnp.ones((h, w), jnp.float32)

        def run():
            def body(packed, ci):
                rgba, _, _ = stream_chunk(ci, c, h, w)
                sk0 = (ci * c + jnp.arange(c, dtype=jnp.float32)) * 0.01
                return psg.fold_chunk_packed(
                    packed, rgba, threshold=thr, max_k=k, sk0=sk0,
                    sk1=sk0 + 0.01, length=length1), None
            packed, _ = jax.lax.scan(body, psg.init_seg_packed(k, h, w),
                                     jnp.arange(nchunks))
            return sfold.seg_finalize(psg.unpack_seg_state(packed))
    elif variant in ("fused", "fused_stream", "tf_pallas_seg",
                     "tf_xla_seg"):
        # VAL-STREAM family: same raw value stream, shading either
        # in-kernel (fused) or in XLA feeding a seg fold — the direct
        # measure of what fusing the TF + depth streams into the kernel
        # buys. Parity-checked against each other, not the rgba family.
        tf = _fused_tf()
        length = jnp.ones((h, w), jnp.float32)
        ratio = jnp.ones((h, w), jnp.float32)
        ds = jnp.float32(0.01)
        if variant == "fused":
            def run():
                def body(packed, ci):
                    val, sk = stream_val_chunk(ci, c, h, w)
                    return psg.fused_fold_chunk(
                        packed, val, length, ratio, sk, sk + ds, thr,
                        max_k=k, tf=tf), None
                packed, _ = jax.lax.scan(body, psg.init_seg_packed(k, h, w),
                                         jnp.arange(nchunks))
                return sfold.seg_finalize(psg.unpack_seg_state(packed))
        elif variant == "fused_stream":
            def run():
                # materialize the whole value stream (the march's matmul
                # phase would write this buffer), then ONE whole-march
                # pallas_call with the [K] state VMEM-resident per strip
                def fill(carry, ci):
                    buf, skb = carry
                    val, sk = stream_val_chunk(ci, c, h, w)
                    buf = jax.lax.dynamic_update_slice(buf, val,
                                                       (ci * c, 0, 0))
                    skb = jax.lax.dynamic_update_slice(skb, sk, (ci * c,))
                    return (buf, skb), None
                (buf, skb), _ = jax.lax.scan(
                    fill, (jnp.zeros((s_total, h, w), jnp.float32),
                           jnp.zeros((s_total,), jnp.float32)),
                    jnp.arange(nchunks))
                packed = psg.fused_stream_fold(
                    psg.init_seg_packed(k, h, w), buf, length, ratio,
                    skb, skb + ds, thr, max_k=k, chunk=c, tf=tf)
                return sfold.seg_finalize(psg.unpack_seg_state(packed))
        elif variant == "tf_pallas_seg":
            def run():
                def body(packed, ci):
                    val, sk = stream_val_chunk(ci, c, h, w)
                    rgba, t0, t1 = _shade_xla(val, sk, tf, length, ratio,
                                              ds)
                    return psg.fold_chunk_packed(packed, rgba, t0, t1,
                                                 thr, max_k=k), None
                packed, _ = jax.lax.scan(body, psg.init_seg_packed(k, h, w),
                                         jnp.arange(nchunks))
                return sfold.seg_finalize(psg.unpack_seg_state(packed))
        else:
            def run():
                def body(st, ci):
                    val, sk = stream_val_chunk(ci, c, h, w)
                    rgba, t0, t1 = _shade_xla(val, sk, tf, length, ratio,
                                              ds)
                    return sfold.seg_fold_chunk(st, rgba, t0, t1, thr,
                                                max_k=k), None
                st, _ = jax.lax.scan(body, sfold.init_seg_state(k, h, w),
                                     jnp.arange(nchunks))
                return sfold.seg_finalize(st)
    elif variant.startswith("pallas"):
        # pallas_tN: strip height N; pallas_wN: block width N (the
        # production kernel picks width by VMEM budget — see
        # pm._pick_block_w; these variants sweep the geometry on hardware)
        tile = wblk = None
        gated = False
        if variant != "pallas":
            suffix = variant[6:]
            if suffix.startswith("_t") and suffix[2:].isdigit():
                tile = int(suffix[2:])
            elif suffix.startswith("_w") and suffix[2:].isdigit():
                wblk = int(suffix[2:])
            elif suffix == "_gated":
                gated = True
            else:
                # fail fast: a typo'd sweep name must not silently record
                # the default geometry under the sweep label
                raise ValueError(f"unknown pallas variant {variant!r} "
                                 "(expected pallas, pallas_gated, "
                                 "pallas_tN or pallas_wN)")

        def run():
            # snapshot BEFORE any mutation, mutate only inside the try:
            # an exception anywhere (incl. the force_w computation) must
            # not leak overrides into later variants of the sweep
            old = pm.TILE_H
            old_w = pm._FORCE_BLOCK_W
            old_g = pm._PHASE2_GATED
            try:
                pm._PHASE2_GATED = gated
                force_w = wblk
                if tile is not None:
                    pm.TILE_H = tile
                    if force_w is None:
                        # pin the block width to the DEFAULT geometry's
                        # choice (the budget-driven pick scales with strip
                        # height, so without this a t-sweep would also
                        # narrow the blocks and confound the two geometry
                        # axes) — clamped to what the budget allows AT the
                        # forced height, else a taller strip at the
                        # default width would blow the scoped-VMEM limit
                        # outright; when the clamp engages, compare
                        # against the matching pallas_wN row for the
                        # controlled same-width height comparison
                        fpp = pm.strip_fpp(c, k)
                        force_w = min(pm._pick_block_w(w, 4 * 8 * fpp),
                                      pm._pick_block_w(w, 4 * tile * fpp))
                if force_w is not None:
                    pm._FORCE_BLOCK_W = force_w

                def body(packed, ci):
                    rgba, t0, t1 = stream_chunk(ci, c, h, w)
                    return pm.fold_chunk(packed, rgba, t0, t1, thr,
                                         max_k=k), None
                packed, _ = jax.lax.scan(body, pm.init_packed(k, h, w),
                                         jnp.arange(nchunks))
                return ss.finalize(pm.unpack_state(packed))
            finally:
                pm.TILE_H = old
                pm._FORCE_BLOCK_W = old_w
                pm._PHASE2_GATED = old_g
    elif variant == "events":
        def run():
            def body(carry, ci):
                big, small = carry
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                return events_fold_chunk(big, small, rgba, t0, t1, thr,
                                         max_k=k), None
            carry, _ = jax.lax.scan(body, events_init(k, h, w),
                                    jnp.arange(nchunks))
            return events_finalize(*carry)
    elif variant == "scratch":
        def run():
            def body(carry, ci):
                big, small = carry
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                return scratch_fold_chunk(big, small, rgba, t0, t1, thr,
                                          max_k=k), None
            carry, _ = jax.lax.scan(body, events_init(k, h, w),
                                    jnp.arange(nchunks))
            return events_finalize(*carry)
    elif variant == "count":
        def run():
            def body(carry, ci):
                rgba, _, _ = stream_chunk(ci, c, h, w)
                return pm.count_multi_chunk(carry, rgba, [0.35]), None
            carry, _ = jax.lax.scan(body,
                                    pm.init_count_multi_packed(1, h, w),
                                    jnp.arange(nchunks))
            return carry[0]
    elif variant == "none":
        def run():
            def body(acc, ci):
                rgba, t0, t1 = stream_chunk(ci, c, h, w)
                return acc + rgba.sum(0) + (t0.sum(0) + t1.sum(0))[None], None
            acc, _ = jax.lax.scan(body, jnp.zeros((4, h, w)),
                                  jnp.arange(nchunks))
            return acc
    else:
        raise ValueError(f"unknown variant {variant!r}")
    return run


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=256,
                    help="slices S; H=W=grid*1.25 (the 512->640 ratio)")
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--variants", default="none,count,xla,pallas")
    ap.add_argument("--check", action="store_true",
                    help="assert events/pallas outputs match the xla fold "
                    "on this stream before timing")
    args = ap.parse_args()

    if os.environ.get("SITPU_CPU") == "1":
        # JAX_PLATFORMS=cpu alone does not stop the axon TPU shim
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()

    s_total = args.grid
    h = w = args.grid * 5 // 4
    h = -(-h // 32) * 32  # keep every TILE_H variant happy
    w = h
    dev = jax.devices()[0]
    print(f"[fold_microbench] {dev.platform} {dev.device_kind} "
          f"S={s_total} HxW={h}x{w} K={args.k} C={args.chunk}",
          file=sys.stderr, flush=True)

    timed_variants = [v.strip() for v in args.variants.split(",")]
    _VAL_FAMILY = ("fused", "fused_stream", "tf_pallas_seg",
                   "tf_xla_seg")
    if args.check:
        ref = jax.jit(build("xla", s_total, args.chunk, args.k, h, w))()
        # the val-stream family consumes a different (raw value) stream:
        # its reference is the XLA-shaded seg fold on that same stream
        ref_val = None
        if any(v in _VAL_FAMILY for v in timed_variants):
            ref_val = jax.jit(build("tf_xla_seg", s_total, args.chunk,
                                    args.k, h, w))()
        # every requested fold-producing variant (anything but the xla
        # reference and the non-folding floors) must match the xla fold —
        # a geometry/schedule variant with wrong numerics must not get
        # its timing recorded as a valid datapoint. Each check is guarded
        # PER VARIANT: one compile rejection / mismatch emits an error
        # row and drops only that variant from the timing loop, instead
        # of aborting before ANY timing is printed (a hardware window
        # must never lose the whole sweep to one bad variant).
        passed, failed = [], []
        for v in [x for x in timed_variants
                  if x not in ("xla", "count", "none", "tf_xla_seg")]:
            try:
                got = jax.jit(build(v, s_total, args.chunk, args.k, h, w))()
                base = ref_val if v in _VAL_FAMILY else ref
                # the fused family shades IN-KERNEL: on hardware Mosaic's
                # pow/TF transcendental lowerings differ from XLA-on-TPU's
                # at ~1e-3 relative (observed max 6.3e-4 abs on the 512
                # stream, 2026-08-01), so the hardware gate for those
                # variants is the transcendental band, not ULP equality;
                # interpret/CPU keeps the strict bound
                hw_fused = (dev.platform == "tpu"
                            and v in ("fused", "fused_stream"))
                tol = (dict(rtol=5e-3, atol=2e-3) if hw_fused
                       else dict(rtol=1e-5, atol=1e-5))
                for a, b, name in [(base[0], got[0], "color"),
                                   (base[1], got[1], "depth")]:
                    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                               err_msg=f"{v} {name}", **tol)
                passed.append(v)
            except Exception as e:
                failed.append(v)
                print(json.dumps({"variant": v, "error":
                                  f"check: {type(e).__name__}: {e}"[:300]}),
                      flush=True)
        timed_variants = [v for v in timed_variants if v not in failed]
        print(f"[fold_microbench] parity check: passed={passed} "
              f"failed={failed}", file=sys.stderr, flush=True)

    for variant in timed_variants:
        try:
            run = jax.jit(build(variant, s_total, args.chunk, args.k, h, w))
            t_c = time.perf_counter()
            out = run()
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t_c
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = run()
            jax.block_until_ready(out)
            ms = (time.perf_counter() - t0) / args.iters * 1e3
            print(json.dumps({
                "variant": variant, "ms_per_march": round(ms, 2),
                "compile_s": round(compile_s, 1),
                "grid": s_total, "hw": [h, w], "k": args.k,
                "chunk": args.chunk, "platform": dev.platform,
            }), flush=True)
        except Exception as e:  # keep the sweep alive past one bad variant
            print(json.dumps({"variant": variant,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
