"""Per-scenario frame cost + brick-parity gates of the scenario zoo
(scenery_insitu_tpu/scenarios; docs/SCENARIOS.md; ISSUE 15).

For every registered scenario (or ``--scenarios a,b``): build the
session from the scenario's bench recipe, run one warmup frame (the
compile), then time ``bench_frames`` STEERED frames (the scenario's own
steering hook fires through the protocol consumer — TF schedules
included, so the recompile-or-reuse counters land in the artifact).

Volume scenarios additionally run the composite PARITY block: one
frame of the scenario's final field rendered through the gather
distributed step under (a) the even decomposition, (b) a non-convex
single-brick-per-rank BrickMap, and (c) an ownership permutation of
(b) — asserting brick-vs-even <= 1e-5 and permutation-vs-permutation
BITWISE (the ISSUE-15 invariance contract, on real scenario content).

One JSON line per run; ``--out`` writes the committed artifact
(results/scenario_bench_r15_cpu.json is the CPU capture).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parity_block(field, tf, n=8):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.parallel.bricks import BrickMap
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (distributed_vdi_step,
                                                      shard_volume)

    d, h, w = field.shape
    if jax.device_count() < n:
        return {"skipped": f"needs {n} devices, have "
                           f"{jax.device_count()}"}
    if d % n or (d // n) < 1:
        return {"skipped": f"depth {d} does not split over {n} ranks"}
    vox = 2.0 / max(d, h, w)
    origin = jnp.asarray([-w * vox / 2, -h * vox / 2, -d * vox / 2],
                         jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.3,
                        far=20.0)
    mesh = make_mesh(n)
    sdata = shard_volume(jnp.asarray(field), mesh)
    vc = VDIConfig(max_supersegments=6, adaptive_iters=2)
    owner = (3, 0, 5, 1, 4, 7, 2, 6)
    bm = BrickMap(d, n, owner)
    outs = {}
    for key, bricks in (("even", None), ("bricks", bm),
                        ("bricks_perm", bm.permute((2, 0, 3, 1, 5, 7,
                                                    4, 6)))):
        cc = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                             rebalance="bricks" if bricks else "even")
        step = distributed_vdi_step(mesh, tf, 32, 32, vc, cc,
                                    max_steps=48, bricks=bricks)
        v = step(sdata, origin, spacing, cam)
        outs[key] = (np.asarray(v.color), np.asarray(v.depth))
    perm_bitwise = bool(
        (outs["bricks"][0] == outs["bricks_perm"][0]).all()
        and (outs["bricks"][1] == outs["bricks_perm"][1]).all())
    dc = float(np.max(np.abs(outs["bricks"][0] - outs["even"][0])))
    # finiteness patterns must MATCH before masking — a dropped brick
    # fragment (finite even depth, +inf bricks depth) is a coverage
    # regression, not a pixel to exclude
    inf_match = bool((np.isinf(outs["even"][1])
                      == np.isinf(outs["bricks"][1])).all())
    fin = np.isfinite(outs["even"][1]) & np.isfinite(outs["bricks"][1])
    dd = float(np.max(np.abs(outs["bricks"][1] - outs["even"][1]),
                      initial=0.0, where=fin))
    return {"owner": list(owner),
            "perm_bitwise": perm_bitwise,
            "inf_pattern_match_vs_even": inf_match,
            "max_color_diff_vs_even": dc,
            "max_depth_diff_vs_even": dd,
            "ok": bool(perm_bitwise and inf_match and dc <= 1e-5
                       and dd <= 1e-5)}


def bench_scenario(name: str, frames: int) -> dict:
    import jax

    from scenery_insitu_tpu import scenarios

    scn = scenarios.get(name)
    n_frames = frames or scn.bench_frames
    sess = scenarios.make_session(
        name, extra_overrides=scn.bench_overrides
        + ("obs.enabled=true", "render.max_steps=64"))
    # warmup = the compile frame (steering hooks held back)
    jax.block_until_ready(sess.render_frame())
    t0 = time.perf_counter()
    scenarios.run_steered(sess, scn, n_frames)
    dt = time.perf_counter() - t0
    row = {
        "frames": n_frames,
        "ms_per_frame": round(dt * 1e3 / n_frames, 2),
        "mode": sess.mode,
        "engine": sess.engine,
        "steered": scn.steering is not None,
        "tf_updates": int(sess.obs.counters.get("tf_updates", 0)),
        "tf_steps_reused": int(sess.obs.counters.get("tf_steps_reused",
                                                     0)),
    }
    if scn.brick_parity and hasattr(sess.sim, "field"):
        import numpy as np

        row["parity"] = _parity_block(np.asarray(sess.sim.field), sess.tf)
    return row


def main() -> int:
    if os.environ.get("SITPU_CPU") == "1" \
            or os.environ.get("JAX_PLATFORMS") == "cpu":
        # the parity block runs the 8-rank distributed step on the
        # virtual CPU mesh (the tests/conftest.py stand-in)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()
    import jax

    from scenery_insitu_tpu import scenarios

    ap = argparse.ArgumentParser()
    ap.add_argument("--scenarios", default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--frames", type=int, default=0,
                    help="override per-scenario bench frame count")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    picks = ([s for s in args.scenarios.split(",") if s]
             or list(scenarios.names()))
    dev = jax.devices()[0]
    rows = {}
    for name in picks:
        rows[name] = bench_scenario(name, args.frames)
        print(json.dumps({name: rows[name]}), flush=True)

    parity_ok = all(r.get("parity", {}).get("ok", True)
                    for r in rows.values())
    out = {
        "metric": f"scenario_bench_{dev.platform}",
        "unit": "ms/frame per registered scenario (steered; includes "
                "TF-update recompiles)",
        "value": len(rows),
        "scenarios": rows,
        "parity_ok": parity_ok,
        "config": {"platform": dev.platform,
                   "device": dev.device_kind,
                   "registered": list(scenarios.names())},
    }
    print(json.dumps(out), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    return 0 if parity_ok else 1


if __name__ == "__main__":
    sys.exit(main())
