"""Async delivery A/B (docs/PERF.md "Async delivery"): with a heavy
compressing sink on the frame stream, the serial loop pays
device + host every frame; the delivery plane (ISSUE 19 tentpole —
RuntimeConfig.pipeline_depth + DeliveryConfig) must take the host term
off the critical path so the loop pays ~max(device, host) and the
EXPOSED host time (delivery work still running on the loop thread)
collapses.

The A/B runs the real InSituSession on the virtual CPU mesh with one
deflate-6 frame sink (what vdi_sink's codec actually costs) across:

- **serial**:   delivery disabled, pipeline_depth=1 — the pre-PR-19
                behavior, every sink inline on the loop thread;
- **async d1/d2/d4**: delivery enabled at pipeline depth 1/2/4 — the
                sink runs on the delivery worker; the loop's only
                delivery cost is the (async-started) host copy.

Per arm it reports frame ms, exposed host ms (sink seconds observed ON
the loop thread), delivery lag p50/p99 from the SLO engine, the
delivery counters, and the bit-exactness verdict: a running digest of
every delivered (frame, color, depth) byte stream, which must be
IDENTICAL across all arms (the ordering contract: frames strictly
FIFO, payload bytes untouched by the executor).

A second section A/Bs the parallel per-tile encode satellite:
io.vdi_io.save_vdi with workers=1 vs workers=N on the same VDI — the
artifacts must be byte-identical (per-member compress calls are
independent; only the wall clock may change).

Acceptance (regression_gate family ``delivery_ab``): async exposed
host <= 0.5x serial, delivered bytes bit-identical, tile encode
byte-identical. Writes one JSON artifact (--out; committed as
results/delivery_ab_r19_cpu.json).

Runs anywhere: re-execs itself onto an N-device virtual CPU mesh
(SITPU_DELIVERY_RANKS, default 4) exactly like delta_bench.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_DELIVERYBENCH_CHILD"

from scenery_insitu_tpu.utils.backend import (pin_cpu_backend,  # noqa: E402
                                              reexec_virtual_mesh)


def _env_int(name, default):
    return int(os.environ.get(name, default))


class HeavySink:
    """Deflate-6 compressing frame sink with per-call accounting: which
    thread ran it, how long it took, and a running digest of the
    delivered byte stream (frame index + raw color/depth bytes) for the
    cross-arm bit-exactness verdict."""

    def __init__(self, level: int = 6):
        self.level = level
        self.lock = threading.Lock()
        self.calls = []                 # (frame, thread_name, seconds)
        self._digest = hashlib.sha256()
        self.bytes_compressed = 0

    def __call__(self, index: int, payload: dict) -> None:
        import numpy as np

        t0 = time.perf_counter()
        blob = (np.asarray(payload["vdi_color"]).tobytes()
                + np.asarray(payload["vdi_depth"]).tobytes())
        zlib.crc32(blob)
        comp = zlib.compress(blob, self.level)
        dt = time.perf_counter() - t0
        with self.lock:
            self._digest.update(str(int(payload["frame"])).encode())
            self._digest.update(blob)
            self.calls.append((int(payload["frame"]),
                               threading.current_thread().name, dt))
            self.bytes_compressed += len(comp)

    @property
    def digest(self) -> str:
        return self._digest.hexdigest()


def _base_cfg(width: int, height: int):
    from scenery_insitu_tpu.config import FrameworkConfig

    return FrameworkConfig().with_overrides(
        f"render.width={width}", f"render.height={height}",
        "render.max_steps=48", "vdi.max_supersegments=8",
        "vdi.adaptive_iters=2", "composite.max_output_supersegments=12",
        "composite.adaptive_iters=2", "sim.grid=[32,32,32]",
        "sim.steps_per_frame=2", "runtime.stats_window=4",
        "slo.enabled=true")


def _run_arm(name, overrides, frames, ranks, width, height):
    """One session run under one delivery configuration; returns the
    measurements the A/B compares."""
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    sink = HeavySink()
    cfg = _base_cfg(width, height).with_overrides(*overrides)
    sess = InSituSession(cfg, mesh=make_mesh(ranks), sinks=[sink])
    loop_thread = threading.current_thread().name
    t0 = time.perf_counter()
    sess.run(frames)                    # drains delivery before returning
    wall_ms = (time.perf_counter() - t0) * 1e3

    order = [f for f, _, _ in sink.calls]
    exposed_s = sum(dt for _, th, dt in sink.calls if th == loop_thread)
    offloaded_s = sum(dt for _, th, dt in sink.calls if th != loop_thread)
    lag = (sess.slo.snapshot()["metrics"] or {}).get("delivery_lag_ms")
    counters = {k: v for k, v in sorted(sess.obs.counters.items())
                if k.startswith("delivery_")}
    return {
        "arm": name,
        "config": {ov.split("=")[0]: ov.split("=")[1] for ov in overrides},
        "frames_delivered": len(order),
        "ordering_fifo": order == sorted(order) and len(set(order)) == len(order),
        "frame_ms": round(wall_ms / frames, 3),
        "exposed_host_ms_per_frame": round(exposed_s * 1e3 / frames, 3),
        "offloaded_host_ms_per_frame": round(offloaded_s * 1e3 / frames,
                                             3),
        "delivery_lag_p50_ms": (lag or {}).get("p50"),
        "delivery_lag_p99_ms": (lag or {}).get("p99"),
        "counters": counters,
        "compressed_bytes": sink.bytes_compressed,
        "digest": sink.digest,
    }


def _tile_encode_ab(workers: int, tmpdir: str):
    """save_vdi workers=1 vs workers=N on one synthetic VDI: artifacts
    must be byte-identical (the parallel per-tile encode contract)."""
    import numpy as np

    from scenery_insitu_tpu.core.vdi import VDI
    from scenery_insitu_tpu.io.vdi_io import save_vdi

    rng = np.random.default_rng(7)
    vdi = VDI(color=rng.random((16, 4, 128, 160), np.float32),
              depth=np.sort(rng.random((16, 2, 128, 160),
                                       np.float32), axis=1))
    out = {}
    blobs = {}
    for w in (1, workers):
        path = os.path.join(tmpdir, f"enc_w{w}.npz")
        t0 = time.perf_counter()
        save_vdi(path, vdi, codec="zlib", workers=w)
        out[f"ms_workers{w}"] = round((time.perf_counter() - t0) * 1e3, 2)
        with open(path, "rb") as f:
            blobs[w] = f.read()
    out["workers"] = workers
    out["byte_identical"] = blobs[1] == blobs[workers]
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None)
    ap.add_argument("--frames",
                    default=_env_int("SITPU_DELIVERY_FRAMES", 8),
                    type=int)
    ap.add_argument("--ranks",
                    default=_env_int("SITPU_DELIVERY_RANKS", 4), type=int)
    ap.add_argument("--width", default=128, type=int)
    ap.add_argument("--height", default=96, type=int)
    ap.add_argument("--encode-workers", default=4, type=int)
    args = ap.parse_args()

    if os.environ.get(_CHILD) != "1":
        reexec_virtual_mesh(args.ranks, _CHILD)
    pin_cpu_backend()

    arms = {
        "serial": ["delivery.enabled=false", "runtime.pipeline_depth=1"],
        "async_d1": ["delivery.enabled=true", "runtime.pipeline_depth=1"],
        "async_d2": ["delivery.enabled=true", "runtime.pipeline_depth=2"],
        "async_d4": ["delivery.enabled=true", "runtime.pipeline_depth=4"],
    }
    results = {}
    for name, ovs in arms.items():
        results[name] = _run_arm(name, ovs, args.frames, args.ranks,
                                 args.width, args.height)
        print(f"[delivery] {name}: frame "
              f"{results[name]['frame_ms']} ms, exposed host "
              f"{results[name]['exposed_host_ms_per_frame']} ms",
              file=sys.stderr)

    serial = results["serial"]
    bit_identical = all(r["digest"] == serial["digest"]
                        for r in results.values())
    ordering = all(r["ordering_fifo"] for r in results.values())
    exp0 = serial["exposed_host_ms_per_frame"]
    best = results["async_d4"]["exposed_host_ms_per_frame"]
    ratio = round(best / exp0, 4) if exp0 > 0 else None

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        tile_encode = _tile_encode_ab(args.encode_workers, td)

    out = {
        "metric": f"delivery_ab_{args.ranks}rank_cpu",
        "value": ratio,
        "unit": "async/serial exposed host ratio (lower is better)",
        "frames": args.frames,
        "render": [args.width, args.height],
        "sink": "deflate-6 frame compressor (vdi_sink codec class)",
        "arms": results,
        "bit_identical_all": bit_identical,
        "ordering_fifo_all": ordering,
        "tile_encode": tile_encode,
        "note": "exposed host = sink seconds observed on the loop "
                "thread; async arms run the sink on the delivery "
                "worker, so the loop only pays the async-started host "
                "copy — delivered bytes must stay bit-identical "
                "(FIFO frames, untouched payloads) across every arm",
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    # hard acceptance: overlap pays and correctness holds
    ok = bit_identical and ordering and (ratio is None or ratio <= 0.5)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
