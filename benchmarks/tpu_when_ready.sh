#!/bin/bash
# Poll the TPU tunnel; when it answers, capture the round's TPU numbers.
# Results land in benchmarks/results/*.json for inspection/commit.
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>/dev/null; then
    echo "TPU back at attempt $i ($(date -u +%H:%M:%S))"
    python bench.py 2>/dev/null | tail -1 > benchmarks/results/bench_tpu.json
    cat benchmarks/results/bench_tpu.json
    SITPU_BENCH_ADAPTIVE_MODE=search python bench.py 2>/dev/null | tail -1 \
      > benchmarks/results/bench_tpu_search.json
    cat benchmarks/results/bench_tpu_search.json
    timeout 1200 python benchmarks/novel_view_bench.py --iters 3 \
      2>/dev/null | tail -1 > benchmarks/results/novel_view_tpu.json
    cat benchmarks/results/novel_view_tpu.json
    timeout 900 python benchmarks/profile_march.py 256 2>/dev/null \
      > benchmarks/results/profile_march_tpu.txt
    tail -8 benchmarks/results/profile_march_tpu.txt
    exit 0
  fi
  sleep 180
done
echo "TPU never recovered"
exit 1
