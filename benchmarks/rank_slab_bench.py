"""Per-rank cost of BASELINE Config 2 (the PRIMARY metric's own terms).

BASELINE.md defines the primary metric as "Gray-Scott 512^3 FPS +
VDI-composite ms/frame" on **v5e-8** — an 8-rank sort-last pipeline
(Config 2), where each chip sims and marches a D/8 z-slab and
composites one W/8 output strip. Every committed flagship number so far
measured the WHOLE 512^3 volume on ONE chip, i.e. 8x the per-rank march
work the metric actually asks one chip to do.

Only one chip is reachable through the axon tunnel, so this harness
measures the real per-rank constituents on it and models the one part
that needs 8 chips (the ICI all_to_all), with the assumption printed:

  sim_slab    10 Gray-Scott steps of the [D/n, H, W] slab
              (multi_step_fast — the production path; the ~4 MB/step
              halo exchange the real pipeline overlaps is noted, not
              modeled)
  march_slab  one temporal write march of the slab through the real
              distributed geometry (shifted origin + global clip box,
              exactly what _mxu_rank_generate runs per rank), VDI on
              the full virtual pixel grid
  composite   composite_vdis over n rank-VDI column strips ([n, K, 4,
              Nj, Ni/n] — the real shapes; contents replicated, cost
              identical)
  a2a_model   per-chip egress (n-1)/n of the VDI bytes at an ASSUMED
              ICI effective bandwidth (default 45 GB/s per chip,
              overridable via SITPU_A2A_GBPS)

Prints ONE JSON line with the pieces and two projections:
projected_fps_v5e8 (sim + march + a2a + composite) and
projected_render_fps_v5e8 (in-situ split: sim feeds from elsewhere).

--rebalance both|even|occupancy (ISSUE 10; docs/PERF.md "Render
rebalancing") switches the harness to the render-rebalancing A/B: on a
SKEWED scene (live work concentrated low-z, >=4x live-fraction spread
across rank bands) it measures every rank's band-march time under the
even z-slab split and under the occupancy plan
(ops/occupancy.slice_plan on the z live profile; planned bands padded
to max(plan) exactly like mesh.reslab_z pads them), and reports the
straggler factor (max/mean per-rank march ms) of each — the frame
barrier is the MAX over ranks, so the straggler reduction is the frame
speedup the rebalance buys. One chip marches the bands serially
(band contents and shapes are exactly the distributed ones; only
concurrency is serialized), so the per-rank times are the real
constituents. ``--out`` writes the JSON artifact
(rebalance_ab_r10_cpu.json is the committed CPU capture).

--rebalance bricks|all additionally measures the NON-CONVEX brick map
(ISSUE 15; docs/SCENARIOS.md "Brick maps"): the steal planner
(parallel.bricks.steal_plan) is converged on the scene's per-brick
live work and each rank's time is the SUM of its per-brick marches —
contiguity gone, min-depth/max-depth padding gone, so the dense region
spreads one brick per rank (bricks_ab_r15_cpu.json is the committed
CPU capture: even 2.90 -> slabs 1.82 -> bricks 1.08 straggler).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig, \
    CompositeConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.composite import composite_vdis
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.sim import grayscott as gs


def _t(fn, *args, iters=5, warmup=1):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def _skewed_field(grid: int) -> "jnp.ndarray":
    """Deterministic skewed scene: dense content in the low QUARTER of z
    only — under the even 8-rank split, ranks 0-1 march solid live
    chunks while ranks 2-7 march air (live-fraction spread >> 4x), the
    regime ROADMAP item 3 left open (PR 6 measured live-cell 0.41 at
    512^3 with exactly this kind of banding)."""
    import numpy as np

    data = np.zeros((grid, grid, grid), np.float32)
    rng = np.random.default_rng(7)
    lo, hi = 1, grid // 4
    data[lo:hi] = (0.3 + 0.5 * rng.random((hi - lo, grid, grid))
                   ).astype(np.float32)
    return jnp.asarray(data)


def rebalance_ab(args):
    """Per-rank march-time A/B: even z-slab split vs the occupancy
    plan, straggler factor (max/mean) each — the frame-barrier term."""
    import numpy as np

    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.ops import occupancy as occ

    dev = jax.devices()[0]
    grid = args.grid
    n = args.ranks
    field = _skewed_field(grid)
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    # the march's cost granularity IS the fold chunk: a band holding 1
    # live slice still pays its whole chunk of resampling matmuls, so
    # the plan quantum and the chunk must agree or the planned bands
    # round up to chunk-sized work anyway (docs/PERF.md "Render
    # rebalancing" — the production default ties rebalance_quantum=4 to
    # chunked skipping the same way)
    march_cfg = SliceMarchConfig(fold=args.fold,
                                 chunk=max(4, args.quantum),
                                 matmul_dtype="f32" if
                                 dev.platform != "tpu" else "bf16")
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_iters=2,
                        adaptive_mode="histogram")
    spec = slicer.make_spec(cam, (grid, grid, grid), march_cfg,
                            multiple_of=n)

    spacing = 2.0 / grid
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spc = jnp.array([spacing] * 3, jnp.float32)
    gmax = origin + jnp.array([grid] * 3, jnp.float32) * spc

    prof = np.asarray(occ.z_live_profile(field, tf))
    even = occ.even_plan(grid, n)
    plan = occ.slice_plan(prof, grid, n, min_depth=args.min_depth,
                          quantum=args.quantum)
    band_live = [float(w) for w in occ.plan_work(prof, grid, even,
                                                 base_cost=0.0)]
    spread = (max(band_live) / max(min(band_live), 1e-9)
              if min(band_live) > 0 else float("inf"))

    def march_band(g0: int, depth: int, pad_to: int):
        """Time one rank's band march through the REAL distributed
        geometry: band volume (zero-padded to the plan max like
        mesh.reslab_z pads it), shifted origin, global box, w_bounds
        ownership."""
        band = np.zeros((pad_to, grid, grid), np.float32)
        band[:depth] = np.asarray(field[g0:g0 + depth])
        l_origin = origin.at[2].add(g0 * spacing)
        z_lo = origin[2] + g0 * spacing
        z_hi = origin[2] + (g0 + depth) * spacing

        @jax.jit
        def march(data):
            vol = Volume(data, l_origin, spc)
            vdi, _, _ = slicer.generate_vdi_mxu(
                vol, tf, cam, spec, vdi_cfg, box_min=origin, box_max=gmax,
                w_bounds=(z_lo, z_hi))
            return vdi.color, vdi.depth

        dt, _ = _t(march, jnp.asarray(band), iters=args.iters)
        return dt * 1e3

    def mode_times(p):
        pad_to = max(p)
        starts = np.concatenate([[0], np.cumsum(p)])[:n]
        return [march_band(int(starts[r]), int(p[r]), int(pad_to))
                for r in range(n)]

    def brick_times(bmap):
        """Per-rank march time under a brick map = the SUM of the
        rank's per-brick marches (the real brick path marches each
        slot separately; serialized here like the band A/B — band
        contents, bounds and shapes are exactly the distributed
        ones)."""
        bz = bmap.brick_depth
        out_ms = []
        for r in range(n):
            ms = 0.0
            for z0, _ in bmap.intervals(r):
                ms += march_band(z0, bz, bz)
            out_ms.append(ms)
        return out_ms

    # brick-stealing map (ISSUE 15; docs/SCENARIOS.md "Brick maps"):
    # converge the session's move-capped steal loop up front — the bench
    # measures the steady-state assignment the replans settle on
    from scenery_insitu_tpu.parallel import bricks as bk

    nb = getattr(args, "bricks", 0) or bk.auto_nbricks(grid, n)
    bwork = bk.brick_work(prof, grid, nb)
    bmap = bk.BrickMap.contiguous(grid, n, nb)
    for _ in range(4 * nb):
        nxt = bk.steal_plan(bmap, bwork, max_moves=4, hysteresis=0.05)
        if nxt is bmap:
            break
        bmap = nxt

    run_modes = {"both": ("even", "occupancy"),
                 "all": ("even", "occupancy", "bricks"),
                 "bricks": ("even", "bricks"),
                 "even": ("even",), "occupancy": ("occupancy",)}[
                     args.rebalance]
    out = {"metric": f"rebalance_ab_{grid}c_{n}ranks_{dev.platform}",
           "unit": "straggler factor reduction (max/mean per-rank march"
                   " ms, even / rebalanced)",
           "scene": {"grid": grid,
                     "band_live_spread": round(spread, 2),
                     "z_profile_bins": len(prof)},
           "plan": list(plan),
           "bricks_map": {"nbricks": nb, "brick_depth": grid // nb,
                          "owner": list(bmap.owner),
                          "slots": bmap.slots},
           "modeled": {
               "straggler_even": round(
                   occ.straggler_factor(prof, grid, even), 3),
               "straggler_planned": round(
                   occ.straggler_factor(prof, grid, plan), 3),
               "straggler_bricks": round(
                   bk.straggler_factor(bmap, bwork), 3)},
           "config": {"ranks": n, "k": args.k, "fold": spec.fold,
                      "image": [spec.ni, spec.nj],
                      "min_depth": args.min_depth,
                      "quantum": args.quantum, "iters": args.iters,
                      "platform": dev.platform,
                      "device": dev.device_kind}}
    for mode in ("even", "occupancy", "bricks"):
        if mode not in run_modes:
            continue
        if mode == "bricks":
            ms = brick_times(bmap)
        else:
            ms = mode_times(even if mode == "even" else plan)
        out[mode] = {
            "per_rank_march_ms": [round(m, 2) for m in ms],
            "max_ms": round(max(ms), 2),
            "mean_ms": round(float(np.mean(ms)), 2),
            "straggler_factor": round(max(ms) / float(np.mean(ms)), 3),
        }
    if "even" in out and "occupancy" in out:
        out["value"] = round(out["even"]["straggler_factor"]
                             / out["occupancy"]["straggler_factor"], 3)
        out["frame_march_speedup"] = round(
            out["even"]["max_ms"] / out["occupancy"]["max_ms"], 3)
    if "even" in out and "bricks" in out:
        out["value_bricks"] = round(out["even"]["straggler_factor"]
                                    / out["bricks"]["straggler_factor"],
                                    3)
        out["frame_march_speedup_bricks"] = round(
            out["even"]["max_ms"] / out["bricks"]["max_ms"], 3)
    print(json.dumps(out), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


def main():
    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    grid = int(os.environ.get("SITPU_BENCH_GRID", "512"))
    n = int(os.environ.get("SITPU_BENCH_RANKS", "8"))
    k = int(os.environ.get("SITPU_BENCH_K", "16"))
    sim_steps = int(os.environ.get("SITPU_BENCH_SIM_STEPS", "10"))
    a2a_gbps = float(os.environ.get("SITPU_A2A_GBPS", "45"))
    fold = os.environ.get("SITPU_BENCH_FOLD", "auto")

    d_loc = grid // n
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    march_cfg = SliceMarchConfig(fold=fold, chunk=min(16, d_loc))
    vdi_cfg = VDIConfig(max_supersegments=k, adaptive_mode="temporal")
    comp_cfg = CompositeConfig(max_output_supersegments=k)
    tf = for_dataset("gray_scott")

    # ---- per-rank slab state: middle slab of a developed global field
    st = gs.GrayScott.init((grid, grid, grid))
    st = jax.jit(lambda s: gs.multi_step(s, 5))(st)
    r0 = (n // 2) * d_loc
    slab_u = st.u[r0:r0 + d_loc]
    slab_v = st.v[r0:r0 + d_loc]
    slab = gs.GrayScott(slab_u, slab_v, st.params)

    # ---- sim of one slab (the production fast path)
    sim_fn = jax.jit(lambda s: gs.multi_step_fast(s, sim_steps))
    t_sim, _ = _t(sim_fn, slab, iters=3)

    # ---- per-rank march: the distributed geometry (shifted origin,
    # global clip box), exactly what _mxu_rank_generate does per rank
    # (parallel/pipeline.py), VDI on the full virtual pixel grid
    spacing = 2.0 / grid
    g_origin = jnp.array([-1.0 + 0.5 * spacing] * 3, jnp.float32)
    l_origin = g_origin.at[2].add(r0 * spacing)   # z slab offset (D axis)
    vol = Volume.create(slab_v, origin=l_origin,
                        spacing=jnp.array([spacing] * 3, jnp.float32))
    spec = slicer.make_spec(cam, (grid, grid, grid), march_cfg)
    box_min = g_origin - 0.5 * spacing
    box_max = box_min + 2.0

    thr = slicer.initial_threshold(vol, tf, cam, spec, vdi_cfg,
                                   box_min=box_min, box_max=box_max)

    @jax.jit
    def march(vol_data, thr):
        v2 = Volume(vol_data, vol.origin, vol.spacing)
        vdi, meta, axcam, thr2 = slicer.generate_vdi_mxu_temporal(
            v2, tf, cam, spec, thr, vdi_cfg, box_min=box_min,
            box_max=box_max)
        return vdi.color, vdi.depth, thr2

    t_march, (color, depth, _) = _t(march, vol.data, thr, iters=5)

    # ---- composite over n rank strips (real shapes, replicated content)
    ni = spec.ni
    strip = ni // n
    colors = jnp.stack([color[..., :strip]] * n)   # [n, K, 4, Nj, Ni/n]
    depths = jnp.stack([depth[..., :strip]] * n)

    @jax.jit
    def comp(colors, depths):
        out = composite_vdis(colors, depths, comp_cfg)
        return out.color, out.depth

    t_comp, _ = _t(comp, colors, depths, iters=5)

    # ---- modeled ICI all_to_all: per-chip egress of (n-1)/n VDI bytes
    vdi_bytes = (color.size + depth.size) * 4
    a2a_bytes = vdi_bytes * (n - 1) / n
    t_a2a = a2a_bytes / (a2a_gbps * 1e9)

    total = t_sim + t_march + t_a2a + t_comp
    render = t_march + t_a2a + t_comp
    print(json.dumps({
        "metric": f"config2_per_rank_{grid}c_{n}ranks_projection",
        "value": round(1.0 / total, 3),
        "unit": "frames/s (projected v5e-8, a2a modeled)",
        "per_rank_sim_ms": round(t_sim * 1e3, 2),
        "per_rank_march_ms": round(t_march * 1e3, 2),
        "composite_ms": round(t_comp * 1e3, 2),
        "a2a_model_ms": round(t_a2a * 1e3, 3),
        "a2a_assumed_gbps": a2a_gbps,
        "a2a_bytes": round(a2a_bytes),
        "projected_fps_v5e8": round(1.0 / total, 3),
        "projected_render_fps_v5e8": round(1.0 / render, 3),
        "note": ("per-rank sim+march+composite MEASURED on one chip with "
                 "the real distributed slab geometry and shapes; ICI "
                 "all_to_all modeled at the stated bandwidth; sim halo "
                 "exchange (~4 MB/step) not modeled"),
        "config": {"grid": grid, "ranks": n, "k": k,
                   "sim_steps": sim_steps, "fold": spec.fold,
                   "image": [spec.ni, spec.nj], "chunk": march_cfg.chunk,
                   "platform": dev.platform, "device": dev.device_kind},
    }), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rebalance",
                    choices=("both", "all", "even", "occupancy",
                             "bricks"),
                    default=None,
                    help="run the render-rebalancing A/B instead of the "
                         "legacy Config-2 projection ('bricks' = even "
                         "vs the brick-stealing map, 'all' = all three)")
    ap.add_argument("--bricks", type=int, default=0,
                    help="brick count of the --rebalance bricks mode "
                         "(0 = auto_nbricks)")
    ap.add_argument("--grid", type=int,
                    default=int(os.environ.get("SITPU_BENCH_GRID",
                                               "64")))
    ap.add_argument("--ranks", type=int,
                    default=int(os.environ.get("SITPU_BENCH_RANKS", "8")))
    ap.add_argument("--k", type=int,
                    default=int(os.environ.get("SITPU_BENCH_K", "8")))
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--min-depth", type=int, default=2)
    ap.add_argument("--quantum", type=int, default=4)
    ap.add_argument("--fold",
                    default=os.environ.get("SITPU_BENCH_FOLD", "auto"))
    ap.add_argument("--out", default=None)
    cli = ap.parse_args()
    if cli.rebalance is not None:
        if os.environ.get("SITPU_CPU") == "1":
            from scenery_insitu_tpu.utils.backend import pin_cpu_backend
            pin_cpu_backend()
        from scenery_insitu_tpu.utils.backend import enable_compile_cache
        enable_compile_cache()
        rebalance_ab(cli)
    else:
        main()
