"""Per-rank cost of BASELINE Config 2 (the PRIMARY metric's own terms).

BASELINE.md defines the primary metric as "Gray-Scott 512^3 FPS +
VDI-composite ms/frame" on **v5e-8** — an 8-rank sort-last pipeline
(Config 2), where each chip sims and marches a D/8 z-slab and
composites one W/8 output strip. Every committed flagship number so far
measured the WHOLE 512^3 volume on ONE chip, i.e. 8x the per-rank march
work the metric actually asks one chip to do.

Only one chip is reachable through the axon tunnel, so this harness
measures the real per-rank constituents on it and models the one part
that needs 8 chips (the ICI all_to_all), with the assumption printed:

  sim_slab    10 Gray-Scott steps of the [D/n, H, W] slab
              (multi_step_fast — the production path; the ~4 MB/step
              halo exchange the real pipeline overlaps is noted, not
              modeled)
  march_slab  one temporal write march of the slab through the real
              distributed geometry (shifted origin + global clip box,
              exactly what _mxu_rank_generate runs per rank), VDI on
              the full virtual pixel grid
  composite   composite_vdis over n rank-VDI column strips ([n, K, 4,
              Nj, Ni/n] — the real shapes; contents replicated, cost
              identical)
  a2a_model   per-chip egress (n-1)/n of the VDI bytes at an ASSUMED
              ICI effective bandwidth (default 45 GB/s per chip,
              overridable via SITPU_A2A_GBPS)

Prints ONE JSON line with the pieces and two projections:
projected_fps_v5e8 (sim + march + a2a + composite) and
projected_render_fps_v5e8 (in-situ split: sim feeds from elsewhere).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig, \
    CompositeConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.composite import composite_vdis
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.sim import grayscott as gs


def _t(fn, *args, iters=5, warmup=1):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def main():
    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    grid = int(os.environ.get("SITPU_BENCH_GRID", "512"))
    n = int(os.environ.get("SITPU_BENCH_RANKS", "8"))
    k = int(os.environ.get("SITPU_BENCH_K", "16"))
    sim_steps = int(os.environ.get("SITPU_BENCH_SIM_STEPS", "10"))
    a2a_gbps = float(os.environ.get("SITPU_A2A_GBPS", "45"))
    fold = os.environ.get("SITPU_BENCH_FOLD", "auto")

    d_loc = grid // n
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    march_cfg = SliceMarchConfig(fold=fold, chunk=min(16, d_loc))
    vdi_cfg = VDIConfig(max_supersegments=k, adaptive_mode="temporal")
    comp_cfg = CompositeConfig(max_output_supersegments=k)
    tf = for_dataset("gray_scott")

    # ---- per-rank slab state: middle slab of a developed global field
    st = gs.GrayScott.init((grid, grid, grid))
    st = jax.jit(lambda s: gs.multi_step(s, 5))(st)
    r0 = (n // 2) * d_loc
    slab_u = st.u[r0:r0 + d_loc]
    slab_v = st.v[r0:r0 + d_loc]
    slab = gs.GrayScott(slab_u, slab_v, st.params)

    # ---- sim of one slab (the production fast path)
    sim_fn = jax.jit(lambda s: gs.multi_step_fast(s, sim_steps))
    t_sim, _ = _t(sim_fn, slab, iters=3)

    # ---- per-rank march: the distributed geometry (shifted origin,
    # global clip box), exactly what _mxu_rank_generate does per rank
    # (parallel/pipeline.py), VDI on the full virtual pixel grid
    spacing = 2.0 / grid
    g_origin = jnp.array([-1.0 + 0.5 * spacing] * 3, jnp.float32)
    l_origin = g_origin.at[2].add(r0 * spacing)   # z slab offset (D axis)
    vol = Volume.create(slab_v, origin=l_origin,
                        spacing=jnp.array([spacing] * 3, jnp.float32))
    spec = slicer.make_spec(cam, (grid, grid, grid), march_cfg)
    box_min = g_origin - 0.5 * spacing
    box_max = box_min + 2.0

    thr = slicer.initial_threshold(vol, tf, cam, spec, vdi_cfg,
                                   box_min=box_min, box_max=box_max)

    @jax.jit
    def march(vol_data, thr):
        v2 = Volume(vol_data, vol.origin, vol.spacing)
        vdi, meta, axcam, thr2 = slicer.generate_vdi_mxu_temporal(
            v2, tf, cam, spec, thr, vdi_cfg, box_min=box_min,
            box_max=box_max)
        return vdi.color, vdi.depth, thr2

    t_march, (color, depth, _) = _t(march, vol.data, thr, iters=5)

    # ---- composite over n rank strips (real shapes, replicated content)
    ni = spec.ni
    strip = ni // n
    colors = jnp.stack([color[..., :strip]] * n)   # [n, K, 4, Nj, Ni/n]
    depths = jnp.stack([depth[..., :strip]] * n)

    @jax.jit
    def comp(colors, depths):
        out = composite_vdis(colors, depths, comp_cfg)
        return out.color, out.depth

    t_comp, _ = _t(comp, colors, depths, iters=5)

    # ---- modeled ICI all_to_all: per-chip egress of (n-1)/n VDI bytes
    vdi_bytes = (color.size + depth.size) * 4
    a2a_bytes = vdi_bytes * (n - 1) / n
    t_a2a = a2a_bytes / (a2a_gbps * 1e9)

    total = t_sim + t_march + t_a2a + t_comp
    render = t_march + t_a2a + t_comp
    print(json.dumps({
        "metric": f"config2_per_rank_{grid}c_{n}ranks_projection",
        "value": round(1.0 / total, 3),
        "unit": "frames/s (projected v5e-8, a2a modeled)",
        "per_rank_sim_ms": round(t_sim * 1e3, 2),
        "per_rank_march_ms": round(t_march * 1e3, 2),
        "composite_ms": round(t_comp * 1e3, 2),
        "a2a_model_ms": round(t_a2a * 1e3, 3),
        "a2a_assumed_gbps": a2a_gbps,
        "a2a_bytes": round(a2a_bytes),
        "projected_fps_v5e8": round(1.0 / total, 3),
        "projected_render_fps_v5e8": round(1.0 / render, 3),
        "note": ("per-rank sim+march+composite MEASURED on one chip with "
                 "the real distributed slab geometry and shapes; ICI "
                 "all_to_all modeled at the stated bandwidth; sim halo "
                 "exchange (~4 MB/step) not modeled"),
        "config": {"grid": grid, "ranks": n, "k": k,
                   "sim_steps": sim_steps, "fold": spec.fold,
                   "image": [spec.ni, spec.nj], "chunk": march_cfg.chunk,
                   "platform": dev.platform, "device": dev.device_kind},
    }), flush=True)


if __name__ == "__main__":
    main()
