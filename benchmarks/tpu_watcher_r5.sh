#!/bin/bash
# Round-5 watcher. Same resumable skeleton as tpu_watcher_r4.sh (probe
# before EVERY step, output file = done marker, fail counter after
# MAXFAIL tunnel-alive failures) with the queue REORDERED for what the
# first round-5 window measured: the tunnel comes up for ~4-minute
# windows, which is enough for one flagship bench.py run (~60 s
# compile+25 frames) but not for the 10-variant fold microbench (step 2
# of the r4 queue hung mid-compile when the window closed). So the
# 30-second micro-roofline and the short one-compile flagship A/Bs
# lead — each IS a full-scale fold-schedule datapoint — and the
# compile-heavy sweeps (split in two), profiles and the 1024^3 attempt
# follow. Artifact names are unchanged from the r4 queue where the step
# is unchanged, so done markers carry.
# Log: /tmp/tpu_watcher_r5.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
L=/tmp/tpu_watcher_r5.log
LAYOUT=r5v9
if [ "$(cat /tmp/r5_layout 2>/dev/null)" != "$LAYOUT" ]; then
  rm -f /tmp/r5_fail.*
  echo "$LAYOUT" > /tmp/r5_layout
fi

probe() {
  timeout 120 python - <<'EOF' 2>/dev/null
import jax
assert jax.devices()[0].platform == "tpu"
import jax.numpy as jnp
assert float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum()) > 0
EOF
}

run_json() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.full.tmp" 2>>"$L" \
     && tail -1 "$out.full.tmp" > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" \
          "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; rm -f "$out.full.tmp" "$out.failed"
    echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    if [ -s "$out.full.tmp" ]; then mv "$out.full.tmp" "$out.failed"; fi
    rm -f "$out.tmp" "$out.full.tmp"
    echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

run_jsonl() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    if [ -s "$out.tmp" ]; then mv "$out.tmp" "$out.partial"; fi
    rm -f "$out.tmp"; echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

run_step() {  # run_step <n>
  case "$1" in
    # ---- short steps first: one compile + 25 frames each ----
    # flagship 512^3, default fold (done in window 1: 2.38 fps)
    1) run_json "$R/bench_tpu_r4_512.json" 1000 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_PLATFORMS=tpu,tpu SITPU_BENCH_CHILD_TIMEOUT=420 \
         python bench.py ;;
    # the 30-second micro-roofline — what does THIS chip deliver?
    # copy/axpy/stencil/sim/matmul achieved GB/s + TFLOP/s decides
    # whether "69 GB/s achieved" means "kernels leave 10x on the table"
    # or "the axon chip never delivers data-sheet bandwidth" (in which
    # case every schedule A/B will come back flat, as rounds 3-5 did)
    2) run_json "$R/hbm_micro_tpu_r5.json" 600 \
         python benchmarks/hbm_bench.py ;;
    # RENDER-ONLY flagship (sim_steps=0, static field, moving camera
    # — the reference's own FPS-harness semantics, and the honest
    # in-situ split: its sim runs on CPU nodes while the GPU renders)
    3) run_json "$R/bench_tpu_r5_512_render.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SIM_STEPS=0 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # flagship RE-capture after the round-5 traffic levers (2D T-step
    # sim fusion + compact-depth fold; the step-1 artifact is the
    # pre-lever baseline — steps 8-11 isolate the fold dimension)
    4) run_json "$R/bench_tpu_r5_512_simfused.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # AUTOTUNED flagship: warmup times auto/fused_stream/xla for 2
    # frames each and benches the winner (the best-of capture; the
    # fixed-fold steps above/below stay single-variable A/Bs)
    5) run_json "$R/bench_tpu_r5_512_autotuned.json" 1000 env \
         SITPU_BENCH_AUTOTUNE=1 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=850 python bench.py ;;
    # whole-loop-in-one-jit flagship (25 frames via lax.scan, ONE
    # executable launch) — isolates any per-launch axon dispatch tax
    # from device time (pairs with hbm_bench's dispatch_tiny_us)
    6) run_json "$R/bench_tpu_r5_512_scanloop.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SCAN_FRAMES=1 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # BASELINE Config 2 on its own terms — per-rank slab sim/march/
    # composite MEASURED (real distributed geometry + shapes), ICI a2a
    # modeled with stated bandwidth: the honest v5e-8 projection
    7) run_json "$R/rank_slab_tpu_r5.json" 900 \
         python benchmarks/rank_slab_bench.py ;;
    # fused shade+fold kernel (rgba/depth streams never hit HBM)
    8) run_json "$R/bench_tpu_r4_512_fused.json" 900 env \
         SITPU_BENCH_FOLD=pallas_fused SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # whole-march stream fold ([K] state crosses HBM once per march)
    9) run_json "$R/bench_tpu_r4_512_fstream.json" 900 env \
         SITPU_BENCH_FOLD=fused_stream SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # pure-XLA seg fold (Mosaic-free A/B)
    10) run_json "$R/bench_tpu_r4_512_segxla.json" 900 env \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_FOLD=seg \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # the missing cell of the (fold x mode) matrix at 512: round 2's
    # 256^3 winner {xla fold, histogram} — at 256 it did TWO marches in
    # 29 ms while {pallas, temporal} did ONE in 49 ms, contradicting the
    # synthetic-stream microbench; this tests whether the frame-context
    # XLA fold wins at the flagship scale too
    11) run_json "$R/bench_tpu_r5_512_xlahist.json" 900 env \
         SITPU_BENCH_FOLD=xla SITPU_BENCH_ADAPTIVE_MODE=histogram \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=700 \
         python bench.py ;;
    # bf16 RENDER copy — the HBM-traffic lever (matmuls already bf16)
    12) run_json "$R/bench_tpu_r5_512_bf16.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_RENDER_DTYPE=bf16 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # in-plane occupancy v-tiles
    13) run_json "$R/bench_tpu_r4_512_vtiles8.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_VTILES=8 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 256^3 exact round-2 config A/B (the regression attribution)
    14) run_json "$R/bench_tpu_r4_256_r2config.json" 900 env \
         SITPU_BENCH_GRID=256 SITPU_BENCH_ADAPTIVE_MODE=histogram \
         SITPU_BENCH_FOLD=xla SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 256^3 round-default (temporal + seg fold)
    15) run_json "$R/bench_tpu_r4_256.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_GRID=256 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # flagship at chunk 32
    16) run_json "$R/bench_tpu_r4_512_c32.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_CHUNK=32 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # ---- medium steps: profiles and split microbench sweeps ----
    # march-stage profile at 512 (where do the ms go?)
    # full-scale SINGLE-chip family captures — vortex 256^3, LJ 1M
    # particles, hybrid 256^3+500k through the real session loop: a
    # hardware number for every BASELINE model family (their multi-rank
    # figures need chips this tunnel does not have; workload full-scale,
    # mesh clamped to 1)
    17) run_jsonl "$R/configs_full_1chip_tpu_r5.jsonl" 2000 \
         python benchmarks/configs_bench.py --configs 1,3,4,5 \
         --scale full --force-ranks 1 --frames 10 --timeout 450 ;;
    18) run_jsonl "$R/profile_march_512_r4.txt" 1800 \
         python -u benchmarks/profile_march.py 512 ;;
    # fold microbench, core schedules (floors + seg family)
    19) run_jsonl "$R/fold_microbench_512_core_r5.jsonl" 1500 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --variants none,count,xla,seg,pallas_seg,pallas_seg_c ;;
    # fold microbench, fused family (+ its controlled baselines)
    20) run_jsonl "$R/fold_microbench_512_fused_r5.jsonl" 1500 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --variants pallas,fused,fused_stream,tf_pallas_seg,tf_xla_seg ;;
    # the 1024^3 north-star attempt (diagnosed OOM is also a result)
    21) run_json "$R/bench_tpu_r4_1024.json" 2100 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_GRID=1024 SITPU_BENCH_FRAMES=5 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=1800 \
         python bench.py ;;
    # ---- the rest of the r4 queue ----
    22) run_jsonl "$R/fold_microbench_256_seg_r4.jsonl" 1500 \
         python benchmarks/fold_microbench.py --grid 256 --iters 5 --check \
         --variants none,count,xla,seg,pallas_seg,pallas,fused,fused_stream,tf_pallas_seg,tf_xla_seg ;;
    23) run_json "$R/novel_view_tpu_r4.json" 1500 \
         python benchmarks/novel_view_bench.py --iters 3 ;;
    24) run_json "$R/composite_tpu_r4.json" 1200 env SITPU_BENCH_REAL=1 \
         python benchmarks/composite_bench.py ;;
    25) run_json "$R/scaling_tpu_r4.json" 1800 env SITPU_BENCH_REAL=1 \
         python benchmarks/scaling_bench.py --grid 128 --frames 10 ;;
    26) run_json "$R/profile_frame_tpu_r4.json" 1200 \
         python benchmarks/profile_frame.py --out "$R/trace_r4" ;;
    27) run_jsonl "$R/fold_microbench_512_c32_seg_r4.jsonl" 1800 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --chunk 32 --variants xla,seg,pallas_seg,fused,fused_stream,tf_xla_seg ;;
    28) run_jsonl "$R/fold_microbench_512_c64_seg_r4.jsonl" 1800 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --chunk 64 --variants seg,pallas_seg,fused,fused_stream,tf_xla_seg ;;
    29) run_json "$R/novel_view_study_tpu_r5.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/novel_view_study.py ;;
  esac
}

step_out() {
  case "$1" in
    1) echo "$R/bench_tpu_r4_512.json" ;;
    2) echo "$R/hbm_micro_tpu_r5.json" ;;
    3) echo "$R/bench_tpu_r5_512_render.json" ;;
    4) echo "$R/bench_tpu_r5_512_simfused.json" ;;
    5) echo "$R/bench_tpu_r5_512_autotuned.json" ;;
    6) echo "$R/bench_tpu_r5_512_scanloop.json" ;;
    7) echo "$R/rank_slab_tpu_r5.json" ;;
    8) echo "$R/bench_tpu_r4_512_fused.json" ;;
    9) echo "$R/bench_tpu_r4_512_fstream.json" ;;
    10) echo "$R/bench_tpu_r4_512_segxla.json" ;;
    11) echo "$R/bench_tpu_r5_512_xlahist.json" ;;
    12) echo "$R/bench_tpu_r5_512_bf16.json" ;;
    13) echo "$R/bench_tpu_r4_512_vtiles8.json" ;;
    14) echo "$R/bench_tpu_r4_256_r2config.json" ;;
    15) echo "$R/bench_tpu_r4_256.json" ;;
    16) echo "$R/bench_tpu_r4_512_c32.json" ;;
    17) echo "$R/configs_full_1chip_tpu_r5.jsonl" ;;
    18) echo "$R/profile_march_512_r4.txt" ;;
    19) echo "$R/fold_microbench_512_core_r5.jsonl" ;;
    20) echo "$R/fold_microbench_512_fused_r5.jsonl" ;;
    21) echo "$R/bench_tpu_r4_1024.json" ;;
    22) echo "$R/fold_microbench_256_seg_r4.jsonl" ;;
    23) echo "$R/novel_view_tpu_r4.json" ;;
    24) echo "$R/composite_tpu_r4.json" ;;
    25) echo "$R/scaling_tpu_r4.json" ;;
    26) echo "$R/profile_frame_tpu_r4.json" ;;
    27) echo "$R/fold_microbench_512_c32_seg_r4.jsonl" ;;
    28) echo "$R/fold_microbench_512_c64_seg_r4.jsonl" ;;
    29) echo "$R/novel_view_study_tpu_r5.json" ;;
  esac
}

NSTEPS=29
MAXFAIL=2
# Hard deadline (epoch s): the driver runs its own bench.py at the round
# boundary (~20:28 UTC), and a watcher step holding the single-chip
# grant would starve that capture into a CPU fallback (window-1
# evidence: probes hang while another process holds the chip). The
# check runs between steps, so the default leaves room for the longest
# step budget (2400 s): 19:40 + 40 min < 20:28. Override via
# SITPU_WATCHER_DEADLINE.
DEADLINE=${SITPU_WATCHER_DEADLINE:-$(date -u -d "today 19:40" +%s)}
for i in $(seq 1 900); do
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "deadline reached, exiting so the driver owns the chip $(date -u)" \
      >> "$L"
    exit 0
  fi
  next=""
  for s in $(seq 1 $NSTEPS); do
    fails=$(cat "/tmp/r5_fail.$s" 2>/dev/null || echo 0)
    [ -e "$(step_out "$s")" ] || [ "$fails" -ge $MAXFAIL ] \
      || { next="$s"; break; }
  done
  [ -z "$next" ] && { echo "suite done $(date -u)" >> "$L"; exit 0; }
  if probe; then
    echo "tunnel alive $(date -u +%H:%M:%S), step $next" | tee -a "$L"
    date -u >> "$R/tpu_alive_r4.marker"
    run_step "$next"
    if [ -e "$(step_out "$next")" ]; then
      rm -f "/tmp/r5_fail.$next"
    elif probe; then
      fails=$(cat "/tmp/r5_fail.$next" 2>/dev/null || echo 0)
      echo $((fails + 1)) > "/tmp/r5_fail.$next"
      echo "fail $((fails + 1))/$MAXFAIL for step $next (tunnel alive)" \
        >> "$L"
    fi
  else
    echo "tunnel dead $(date -u +%H:%M:%S), step $next pending" >> "$L"
    sleep 45
  fi
done
