"""Distributed compositing benchmark — replay stored VDI fixtures through
the real distribute/composite path (≅ VDICompositingTest.kt:207-330, the
reference's C++-driven MPI compositing benchmark).

The reference replays stored VDI dumps through ``distributeVDIsForBenchmark``
(plain MPI all-to-all) or ``distributeVDIsWithVariableLength`` (per-segment
LZ4 + alltoallv, :251-304), composites on the GPU, and emits machine-
greppable ``#COMP:rank:iter:sec#`` / ``#DECOM:rank:iter:sec#`` / ``#IT:...#``
markers (:301,336,397-398). This harness does the same on the TPU path:

- **ici mode** (default): per-rank sub-VDIs are placed rank-sharded on the
  device mesh and each iteration runs the one jitted SPMD step — width-axis
  column exchange + fused sort-merge composite — exactly the production
  pipeline's chain. ``--exchange both`` (the default) A/Bs the
  ``all_to_all`` schedule against the ring-pipelined one
  (CompositeConfig.exchange; docs/PERF.md "Exchange modes"), reporting
  per-mode ms/iter, the modeled exchange + composite working-set bytes
  (the N·K → ring_slots+K reduction) and output parity. ``--wire all``
  additionally A/Bs the supersegment wire formats (CompositeConfig.wire;
  docs/PERF.md "Wire formats"): each lossy mode reports ms/iter, the
  modeled per-wire exchange bytes, the XLA-cost-analysis bytes of the
  compiled step, and a PSNR block against the same-schedule f32 output.
- **compressed mode** (``--compressed``): the host hop — each rank's VDI is
  split into per-destination column segments, compressed (zstd by default),
  "exchanged", decompressed (timed as #DECOM) and composited (#COMP) — the
  variable-length-collective wire format of io.vdi_io.pack_vdi_segments.

Fixtures: ``--save-fixtures DIR`` writes per-rank sub-VDI .npz dumps from a
procedural volume (the fake-sim fixture strategy, SURVEY.md §4.3);
``--dir DIR`` replays existing dumps. Without either, fixtures are built
in-memory.

Runs on the virtual CPU mesh by default (set SITPU_BENCH_REAL=1 to use real
devices when you have >= n of them). Prints markers + one JSON summary.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_COMPBENCH_CHILD"


from scenery_insitu_tpu.utils.backend import (pin_cpu_backend,  # noqa: E402
                                              reexec_virtual_mesh)


def build_fixtures(n: int, grid: int, width: int, height: int, k: int,
                   max_steps: int):
    """Per-rank sub-VDIs: each rank raycasts its z-slab of a procedural
    volume, clipped half-open — the same decomposition the pipeline uses."""
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

    vol = procedural_volume(grid, kind="blobs", seed=11)
    tf = for_dataset("procedural")
    cam = Camera.create((0.2, 0.5, 2.9), fov_y_deg=45.0, near=0.3, far=10.0)
    cfg = VDIConfig(max_supersegments=k, adaptive_iters=2)
    d = grid
    dz = float(vol.spacing[2])
    vdis, metas = [], []
    for r in range(n):
        z0 = float(vol.origin[2]) + r * (d // n) * dz
        z1 = float(vol.origin[2]) + (r + 1) * (d // n) * dz
        cmin = jnp.asarray([vol.world_min[0], vol.world_min[1], z0])
        cmax = jnp.asarray([vol.world_max[0], vol.world_max[1], z1])
        vdi, meta = generate_vdi(vol, tf, cam, width, height, cfg,
                                 max_steps=max_steps,
                                 clip_min=cmin, clip_max=cmax)
        vdis.append(vdi)
        metas.append(meta)
    return vdis, metas


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--grid", type=int, default=64)
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--height", type=int, default=144)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--k-out", type=int, default=8)
    ap.add_argument("--max-steps", type=int, default=96)
    ap.add_argument("--compressed", action="store_true",
                    help="host-hop per-segment compression variant")
    ap.add_argument("--exchange", default="both",
                    choices=("all_to_all", "ring", "both"),
                    help="ici-mode exchange schedule(s) to run")
    ap.add_argument("--ring-slots", type=int, default=0,
                    help="ring accumulator cap (0 = lossless N*K)")
    ap.add_argument("--wire", default="f32",
                    choices=("f32", "bf16", "qpack8", "all"),
                    help="ici-mode supersegment wire format(s) to run "
                         "(lossy modes always run f32 too, as the PSNR "
                         "reference)")
    ap.add_argument("--schedule", default="frame",
                    choices=("frame", "waves", "both"),
                    help="frame schedule(s) to run (docs/PERF.md 'Tile "
                         "waves'): 'waves' scans the exchange+composite "
                         "per column-block wave; 'both' A/Bs them and "
                         "reports parity + the modeled overlap win")
    ap.add_argument("--wave-tiles", type=int, default=4,
                    help="column-block waves per rank block under the "
                         "waves schedule")
    ap.add_argument("--out", default=None,
                    help="also write the JSON summary to PATH (CI artifact)")
    ap.add_argument("--codec", default="zstd")
    ap.add_argument("--dir", default=None,
                    help="replay stored *_subvdi_*.npz fixtures from DIR")
    ap.add_argument("--save-fixtures", default=None,
                    help="write the generated fixtures to DIR and exit")
    args = ap.parse_args()
    n = args.ranks

    if os.environ.get(_CHILD) != "1" and os.environ.get(
            "SITPU_BENCH_REAL") != "1":
        reexec_virtual_mesh(n, _CHILD)

    import jax

    from scenery_insitu_tpu.utils.compat import shard_map

    if os.environ.get(_CHILD) == "1":
        pin_cpu_backend()
    elif os.environ.get("SITPU_BENCH_REAL") == "1":
        # real chips: this environment tunnels ONE TPU — clamp the rank
        # count to what exists instead of dying in make_mesh. n=1 still
        # measures the composite kernel itself (the column exchange is an
        # identity there), which is the Pallas-vs-XLA number this bench
        # exists to capture.
        avail = jax.device_count()
        if avail < n:
            print(f"[composite_bench] {avail} real device(s) < {n} ranks; "
                  f"clamping to {avail}", file=sys.stderr, flush=True)
            n = avail
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu.config import CompositeConfig
    from scenery_insitu_tpu.core.vdi import VDI
    from scenery_insitu_tpu.io.vdi_io import (dump_path, load_vdi,
                                              pack_vdi_segments, save_vdi,
                                              unpack_vdi_segments)

    if args.dir:
        paths = sorted(glob.glob(os.path.join(args.dir, "*_subvdi_*.npz")))
        if len(paths) < n:
            raise SystemExit(f"need {n} fixtures in {args.dir}, "
                             f"found {len(paths)}")
        vdis = [load_vdi(p)[0] for p in paths[:n]]
        vdis = [VDI(jnp.asarray(v.color), jnp.asarray(v.depth))
                for v in vdis]
    else:
        vdis, metas = build_fixtures(n, args.grid, args.width, args.height,
                                     args.k, args.max_steps)
        if args.save_fixtures:
            for r, (v, m) in enumerate(zip(vdis, metas)):
                p = dump_path(args.save_fixtures, "bench", r, "subvdi")
                save_vdi(p, v, m, codec=args.codec)
            print(f"wrote {n} fixtures to {args.save_fixtures}")
            return

    k, _, h, w = vdis[0].color.shape
    comp_cfg = CompositeConfig(max_output_supersegments=args.k_out,
                               adaptive_iters=2)

    if not args.compressed:
        # --------------------------- ICI path: the production SPMD chain
        import dataclasses

        from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic
        from scenery_insitu_tpu.parallel.mesh import make_mesh
        from scenery_insitu_tpu.parallel.pipeline import (
            _composite_exchanged_sched)
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh(n)
        axis = mesh.axis_names[0]
        modes = (["all_to_all", "ring"] if args.exchange == "both"
                 else [args.exchange])
        wires = (["f32", "bf16", "qpack8"] if args.wire == "all"
                 else [args.wire])
        if "f32" not in wires:          # the lossy modes' PSNR reference
            wires = ["f32"] + wires
        scheds = (["frame", "waves"] if args.schedule == "both"
                  else [args.schedule])

        base_c = jnp.concatenate([v.color for v in vdis])
        base_d = jnp.concatenate([v.depth for v in vdis])

        per_mode = {}
        first_out = {}
        for sched in scheds:
            for mode in modes:
              for wire in wires:
                # f32 frame entries keep the bare exchange-mode key (the
                # PR-4 artifact shape); lossy wires nest under
                # "mode/wire" and the waves schedule under "waves/..."
                key = mode if wire == "f32" else f"{mode}/{wire}"
                if sched == "waves":
                    key = f"waves/{key}"
                cfg_m = dataclasses.replace(comp_cfg, exchange=mode,
                                            ring_slots=args.ring_slots,
                                            wire=wire, schedule=sched,
                                            wave_tiles=args.wave_tiles)

                def step(color, depth, cfg_m=cfg_m):  # [K,4,H,W] per rank
                    out = _composite_exchanged_sched(color, depth, n,
                                                     axis, cfg_m)
                    return out.color, out.depth

                f = jax.jit(shard_map(
                    step, mesh=mesh, in_specs=(P(axis), P(axis)),
                    out_specs=(P(None, None, None, axis),
                               P(None, None, None, axis)),
                    check_vma=False))

                stack_c = jax.device_put(base_c,
                                         NamedSharding(mesh, P(axis)))
                stack_d = jax.device_put(base_d,
                                         NamedSharding(mesh, P(axis)))

                oc, od = f(stack_c, stack_d)            # compile
                jax.block_until_ready(oc)
                first_out[key] = (np.asarray(oc), np.asarray(od))
                # measured whole-step bytes from XLA's own cost analysis —
                # the wire shrink shows up as the bytes_accessed delta
                # between wire modes of the same schedule
                from scenery_insitu_tpu.obs.device import cost_snapshot
                snap = cost_snapshot(f, stack_c, stack_d)
                total = 0.0
                # chain an input perturbation so no layer can dedupe
                # identical executions (see axon notes)
                for it in range(args.iters):
                    t0 = time.perf_counter()
                    oc, od = f(stack_c, stack_d)
                    jax.block_until_ready(oc)
                    dt = time.perf_counter() - t0
                    total += dt
                    stack_c = stack_c.at[0, 0, 0, 0].add(
                        float(oc[0, 0, 0, 0]) * 1e-6)
                    print(f"#COMP:{key}:{it}:{dt:.6f}#")
                    print(f"#IT:{key}:{it}:{dt:.6f}#")
                per_mode[key] = {
                    "ms_per_iter": round(total / args.iters * 1000, 3),
                    # modeled per-rank exchange + composite working set —
                    # the N·K → ring_slots+K live-state lever, the
                    # per-wire ici byte shrink, and (waves) the overlap
                    # accounting (docs/PERF.md)
                    "modeled": modeled_exchange_traffic(
                        n, k, h, w, k_out=args.k_out, mode=mode,
                        ring_slots=args.ring_slots, wire=wire,
                        schedule=sched, wave_tiles=args.wave_tiles),
                    "cost_analysis": snap,
                }

        key0 = (modes[0] if scheds[0] == "frame"
                else f"waves/{modes[0]}")
        summary = {
            "metric": f"composite_ici_{n}ranks_k{k}_{w}x{h}",
            "value": per_mode[key0]["ms_per_iter"],
            "unit": "ms/iter",
            "mode": "ici",
            "exchange": per_mode,
            "ring_slots": args.ring_slots,
            "wire": args.wire,
            "schedule": args.schedule,
            "wave_tiles": args.wave_tiles,
            "backend": jax.default_backend(),
        }
        if len(scheds) == 2:
            # parity of the two SCHEDULES on the same inputs at the first
            # exchange mode: lossless waves must reproduce the frame
            # schedule's composite (the tile is a column partition of
            # the same per-pixel merge)
            fc, fd = first_out[modes[0]]
            wc, wd = first_out[f"waves/{modes[0]}"]
            dc = float(np.abs(fc - wc).max())
            fin = np.isfinite(fd) & np.isfinite(wd)
            dd = float(np.abs(fd[fin] - wd[fin]).max()) if fin.any() \
                else 0.0
            summary["schedule_parity"] = {
                "exchange": modes[0],
                "max_abs_diff_color": dc,
                "max_abs_diff_depth_finite": dd,
                "empty_slot_layout_match":
                    bool((np.isinf(fd) == np.isinf(wd)).all()),
            }
        if len(wires) > 1:
            # PSNR of each lossy wire's same-view render against the
            # SAME schedule's f32 output — the quality side of the 4×
            from scenery_insitu_tpu.core.vdi import (VDI as _VDI,
                                                     render_vdi_same_view)
            from scenery_insitu_tpu.utils.image import psnr

            _rendered = {}

            def rend(key):
                if key not in _rendered:
                    oc, od = first_out[key]
                    _rendered[key] = np.asarray(render_vdi_same_view(
                        _VDI(jnp.asarray(oc), jnp.asarray(od))))
                return _rendered[key]

            pfx = {"frame": "", "waves": "waves/"}
            summary["wire_psnr_db"] = {
                f"{pfx[s]}{mode}/{wire}":
                    round(psnr(rend(f"{pfx[s]}{mode}/{wire}"),
                               rend(f"{pfx[s]}{mode}")), 2)
                for s in scheds for mode in modes
                for wire in wires if wire != "f32"}
        if len(modes) == 2:
            # parity of the two exchange modes on the SAME (unperturbed)
            # inputs: lossless ring must match all_to_all exactly — under
            # whichever schedule actually ran (a waves-only run compares
            # its own waves/ keys instead of silently skipping)
            pfx = "" if "frame" in scheds else "waves/"
            summary["parity_schedule"] = "frame" if not pfx else "waves"
            ac, ad = first_out[pfx + "all_to_all"]
            rc, rd = first_out[pfx + "ring"]
            dc = float(np.abs(ac - rc).max())
            fin = np.isfinite(ad) & np.isfinite(rd)
            dd = float(np.abs(ad[fin] - rd[fin]).max()) if fin.any() else 0.0
            summary["parity"] = {
                "max_abs_diff_color": dc,
                "max_abs_diff_depth_finite": dd,
                "empty_slot_layout_match":
                    bool((np.isinf(ad) == np.isinf(rd)).all()),
            }
    else:
        # ------------------- compressed host hop (DCN / disk wire format)
        from scenery_insitu_tpu.ops.composite import composite_vdis

        total_comp = total_decom = 0.0
        wire_bytes = 0
        raw_bytes = n * (vdis[0].color.nbytes + vdis[0].depth.nbytes)
        comp_jit = jax.jit(lambda c, d: composite_vdis(c, d, comp_cfg))
        for it in range(args.iters):
            # pack: each rank splits + compresses its VDI per destination
            t0 = time.perf_counter()
            packed = [pack_vdi_segments(v, n, codec=args.codec)
                      for v in vdis]
            t_pack = time.perf_counter() - t0
            wire_bytes = sum(int(cl.sum() + dl.sum())
                             for _, cl, dl in packed)

            # "exchange": destination r receives segment r of every rank
            t0 = time.perf_counter()
            received = []
            for r in range(n):
                blobs = []
                for src in range(n):
                    sb, _, _ = packed[src]
                    blobs.append(sb[r])             # color seg r
                for src in range(n):
                    sb, _, _ = packed[src]
                    blobs.append(sb[n + r])         # depth seg r
                received.append(unpack_vdi_segments(blobs, k, h, w // n * n,
                                                    codec=args.codec))
            t_decom = time.perf_counter() - t0
            total_decom += t_pack + t_decom
            print(f"#DECOM:all:{it}:{t_pack + t_decom:.6f}#")

            # composite each destination's column block: received[r] holds
            # n ranks' segments concatenated on W; restack to [n,K,.,H,W/n]
            t0 = time.perf_counter()
            outs = []
            for r in range(n):
                rc = np.asarray(received[r].color).reshape(k, 4, h, n, w // n)
                rd = np.asarray(received[r].depth).reshape(k, 2, h, n, w // n)
                cc = jnp.asarray(np.moveaxis(rc, 3, 0))
                dd = jnp.asarray(np.moveaxis(rd, 3, 0))
                outs.append(comp_jit(cc, dd))
            jax.block_until_ready(outs[-1].color)
            dt = time.perf_counter() - t0
            total_comp += dt
            print(f"#COMP:all:{it}:{dt:.6f}#")
            print(f"#IT:all:{it}:{t_pack + t_decom + dt:.6f}#")
        summary = {
            "metric": f"composite_compressed_{n}ranks_k{k}_{w}x{h}",
            "value": round((total_comp + total_decom) / args.iters * 1000, 3),
            "unit": "ms/iter",
            "mode": f"compressed/{args.codec}",
            "compression_ratio": round(raw_bytes / max(wire_bytes, 1), 2),
            "decompress_ms": round(total_decom / args.iters * 1000, 3),
            "composite_ms": round(total_comp / args.iters * 1000, 3),
            "backend": jax.default_backend(),
        }
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(summary, fh, indent=2)


if __name__ == "__main__":
    main()
