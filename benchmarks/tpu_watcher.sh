#!/bin/bash
# Consolidated TPU-window watcher — supersedes the five per-round copies
# (tpu_watcher_r3.sh .. tpu_watcher_r5.sh; their round logs live in
# benchmarks/results/README.md). Same resumable skeleton the r4/r5
# rounds converged on: probe the tunnel before EVERY step, output file =
# done marker (relaunch resumes), a per-step fail counter retires steps
# that died MAXFAIL times while the tunnel was alive, and a hard
# deadline hands the chip back to the driver. Round and knobs come from
# the environment instead of a fork-per-round copy:
#
#   SITPU_WATCHER_ROUND=r8         artifact suffix (results/*_${ROUND}.*)
#   SITPU_WATCHER_STEPS="1 2 5"    run a subset (default: all, in order)
#   SITPU_WATCHER_MAXFAIL=2        tunnel-alive failures before retiring
#   SITPU_WATCHER_DEADLINE=<epoch> hard stop (default: +6h from launch)
#   SITPU_WATCHER_POLLS=900        probe attempts before giving up
#   SITPU_WATCHER_SLEEP=45         seconds between dead-tunnel probes
#   SITPU_WATCHER_PROFILE=1        attribution plane on EVERY bench step
#                                  (exports SITPU_BENCH_PROFILE=1, so
#                                  each artifact embeds the per-phase
#                                  attribution + roofline verdicts +
#                                  divergence report); step 18 captures
#                                  the dedicated profiled flagship
#                                  either way
#
# Any SITPU_BENCH_* in the environment passes through to every step, so
# one-off knob sweeps don't need to edit the queue. The companion
# benchmarks/tpu_when_ready.sh stays the minimal "poll then capture the
# defaults" one-shot.
# Log: /tmp/tpu_watcher_${ROUND}.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
ROUND=${SITPU_WATCHER_ROUND:-r10}
L=/tmp/tpu_watcher_${ROUND}.log
MAXFAIL=${SITPU_WATCHER_MAXFAIL:-2}
DEADLINE=${SITPU_WATCHER_DEADLINE:-$(($(date +%s) + 6 * 3600))}
LAYOUT=${ROUND}v1
if [ "$(cat /tmp/watcher_layout 2>/dev/null)" != "$LAYOUT" ]; then
  rm -f /tmp/watcher_fail.*
  echo "$LAYOUT" > /tmp/watcher_layout
fi
# attribution plane on every bench step (docs/OBSERVABILITY.md):
# SITPU_BENCH_* passes through to each step, so one export suffices
if [ "${SITPU_WATCHER_PROFILE:-0}" = "1" ]; then
  export SITPU_BENCH_PROFILE=1
fi

probe() {
  timeout 120 python - <<'EOF' 2>/dev/null
import jax
assert jax.devices()[0].platform == "tpu"
import jax.numpy as jnp
assert float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum()) > 0
EOF
}

# Keep an output only if the command succeeded AND its last line parses
# as JSON (a timed-out step must not leave a file that reads as a
# captured measurement). Failures keep the raw output as *.failed.
run_json() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.full.tmp" 2>>"$L" \
     && tail -1 "$out.full.tmp" > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" \
          "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; rm -f "$out.full.tmp" "$out.failed"
    echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    if [ -s "$out.full.tmp" ]; then mv "$out.full.tmp" "$out.failed"; fi
    rm -f "$out.tmp" "$out.full.tmp"
    echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

# Whole-file artifacts (JSONL sweeps, profiles): keep on success, keep
# partial output as *.partial on failure (resumable sweeps).
run_jsonl() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    if [ -s "$out.tmp" ]; then mv "$out.tmp" "$out.partial"; fi
    rm -f "$out.tmp"; echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

# ---- the round queue (short one-compile captures first; ROADMAP
# item 1's per-lever hardware A/Bs + waves + this round's render
# rebalancing A/B) ----
run_step() {
  case "$1" in
    # flagship 512^3, fixed default fold (the lever-stack re-capture)
    1) run_json "$R/bench_tpu_${ROUND}_512.json" 1000 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_PLATFORMS=tpu,tpu \
         SITPU_BENCH_CHILD_TIMEOUT=420 python bench.py ;;
    # 30-second micro-roofline (finishes hbm_bench's owed TPU capture)
    2) run_json "$R/hbm_micro_tpu_${ROUND}.json" 600 \
         python benchmarks/hbm_bench.py ;;
    # render-only flagship (sim_steps=0 — the sim-vs-render split)
    3) run_json "$R/bench_tpu_${ROUND}_512_render.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SIM_STEPS=0 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=700 \
         python bench.py ;;
    # sim-fused occupancy pyramid at 512^3 (ROADMAP item 3's owed A/B)
    4) run_json "$R/bench_tpu_${ROUND}_512_skipsim.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SKIP=sim \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=700 \
         python bench.py ;;
    # tile-wave flagship (single-chip: schedule config + modeled overlap
    # in the artifact; the measured distributed A/B is step 6)
    5) run_json "$R/bench_tpu_${ROUND}_512_waves.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SCHEDULE=waves \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=700 \
         python bench.py ;;
    # waves-vs-frame measured A/B on real device(s) (clamps to 1 chip)
    6) run_json "$R/composite_waves_tpu_${ROUND}.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/composite_bench.py \
         --schedule both --exchange ring \
         --out "$R/composite_waves_tpu_${ROUND}.json" ;;
    # wire + exchange matrix on real device(s)
    7) run_json "$R/composite_wire_tpu_${ROUND}.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/composite_bench.py \
         --wire all --out "$R/composite_wire_tpu_${ROUND}.json" ;;
    # occupancy ladder A/B at 512 (dedicated bench, measured ms/frame)
    8) run_json "$R/occupancy_ab_tpu_${ROUND}_512.json" 1800 \
         python benchmarks/occupancy_bench.py --grid 512 \
         --out "$R/occupancy_ab_tpu_${ROUND}_512.json" ;;
    # whole-loop-in-one-jit flagship (scan dispatch tax isolation)
    9) run_json "$R/bench_tpu_${ROUND}_512_scanloop.json" 900 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_SCAN_FRAMES=1 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=700 \
         python bench.py ;;
    # the 1024^3 north-star attempt (a diagnosed OOM is also a result)
    10) run_json "$R/bench_tpu_${ROUND}_1024.json" 2100 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_GRID=1024 \
         SITPU_BENCH_FRAMES=5 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=1800 python bench.py ;;
    # render-rebalancing A/B: per-rank march straggler factor, even vs
    # occupancy plan on a skewed 256^3 scene (docs/PERF.md "Render
    # rebalancing"; the committed CPU capture is rebalance_ab_r10_cpu)
    11) run_json "$R/rebalance_ab_tpu_${ROUND}.json" 1200 \
         python benchmarks/rank_slab_bench.py --rebalance both \
         --grid 256 --iters 3 \
         --out "$R/rebalance_ab_tpu_${ROUND}.json" ;;
    # temporal-delta A/B on real devices (docs/PERF.md "Temporal
    # deltas"; the committed CPU capture is delta_ab_r12_cpu)
    12) run_json "$R/delta_ab_tpu_${ROUND}.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/delta_bench.py \
         --grid 128 --frames 12 \
         --out "$R/delta_ab_tpu_${ROUND}.json" ;;
    # edge-serving tier: viewers/chip/frame amortization curve + p99
    # camera-to-pixel latency + bytes/viewer (docs/SERVING.md; the
    # committed CPU capture is serve_bench_r13_cpu)
    13) run_json "$R/serve_bench_tpu_${ROUND}.json" 1500 \
         python benchmarks/serve_bench.py --grid 128 --k 20 \
         --width 256 --height 192 --num-slices 128 \
         --out "$R/serve_bench_tpu_${ROUND}.json" ;;
    # hierarchical two-level composite A/B on real devices (domains as
    # mesh sub-axes — docs/MULTIHOST.md; the committed CPU captures are
    # hier_scaling_r14_cpu + the emulated-path parity tests). On a
    # 1-chip tunnel this records the documented degenerate note.
    14) run_json "$R/hier_device_tpu_${ROUND}.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/scaling_bench.py \
         --mode hier-device --grid 128 --k 8 --frames 10 ;;
    # brick-stealing A/B: per-rank march straggler, even vs slab plan
    # vs the non-convex brick map on a skewed 256^3 scene
    # (docs/SCENARIOS.md "Brick maps"; the committed CPU capture is
    # bricks_ab_r15_cpu)
    15) run_json "$R/bricks_ab_tpu_${ROUND}.json" 1500 \
         python benchmarks/rank_slab_bench.py --rebalance all \
         --grid 256 --iters 3 \
         --out "$R/bricks_ab_tpu_${ROUND}.json" ;;
    # LOD marching ladder on a real chip: PSNR vs modeled march FLOPs
    # vs MEASURED ms/frame at 512^3, where per-brick fixed cost no
    # longer hides the 2^-l march saving (docs/PERF.md "LOD marching";
    # the committed CPU capture is lod_ab_r16_cpu — its frame_ms
    # column is the toy-grid caveat this step exists to replace)
    16) run_json "$R/lod_ab_tpu_${ROUND}.json" 1800 \
         python benchmarks/lod_bench.py --grid 512 --iters 3 \
         --out "$R/lod_ab_tpu_${ROUND}.json" ;;
    # the 2048^3 coarse-heavy attempt (ISSUE 16 / ROADMAP item 3's
    # "honest route past 1024^3"): max_level 3, generous error budgets
    # — most bricks should coarsen, which is the only way this grid
    # fits a march budget. Like step 10, a diagnosed OOM is a result.
    17) run_json "$R/lod_2048_tpu_${ROUND}.json" 2400 env \
         SITPU_BENCH_CHILD_TIMEOUT=2100 \
         python benchmarks/lod_bench.py --grid 2048 --iters 1 \
         --max-level 3 --ladder 4.0 8.0 16.0 --k 8 \
         --out "$R/lod_2048_tpu_${ROUND}.json" ;;
    # attribution plane on the flagship (ISSUE 18; the committed CPU
    # capture is attribution_r18_cpu): traced frames joined to the
    # sitpu_* phase scopes + roofline verdicts + divergence report vs
    # the committed modeled projection, then the standalone report file
    18) run_json "$R/attribution_tpu_${ROUND}.json" 1200 env \
         SITPU_BENCH_AUTOTUNE=0 SITPU_BENCH_PROFILE=1 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=900 \
         python bench.py
       if [ -e "$R/attribution_tpu_${ROUND}.json" ]; then
         timeout 120 python benchmarks/divergence.py \
           --attribution "$R/attribution_tpu_${ROUND}.json" \
           --out "$R/divergence_tpu_${ROUND}.json" 2>>"$L" \
           && echo "ok: $R/divergence_tpu_${ROUND}.json" >> "$L"
       fi ;;
    # async delivery plane A/B on real devices (ISSUE 19; the committed
    # CPU capture is delivery_ab_r19_cpu): serial vs async at pipeline
    # depth 1/2/4 under a heavy compressing sink — exposed host ms,
    # delivery lag percentiles, cross-arm bit-exactness, and the
    # parallel per-tile encode byte-identity check
    19) run_json "$R/delivery_ab_tpu_${ROUND}.json" 1200 env \
         SITPU_DELIVERY_FRAMES=12 \
         python benchmarks/delivery_bench.py \
         --out "$R/delivery_ab_tpu_${ROUND}.json" ;;
  esac
}

step_out() {
  case "$1" in
    1) echo "$R/bench_tpu_${ROUND}_512.json" ;;
    2) echo "$R/hbm_micro_tpu_${ROUND}.json" ;;
    3) echo "$R/bench_tpu_${ROUND}_512_render.json" ;;
    4) echo "$R/bench_tpu_${ROUND}_512_skipsim.json" ;;
    5) echo "$R/bench_tpu_${ROUND}_512_waves.json" ;;
    6) echo "$R/composite_waves_tpu_${ROUND}.json" ;;
    7) echo "$R/composite_wire_tpu_${ROUND}.json" ;;
    8) echo "$R/occupancy_ab_tpu_${ROUND}_512.json" ;;
    9) echo "$R/bench_tpu_${ROUND}_512_scanloop.json" ;;
    10) echo "$R/bench_tpu_${ROUND}_1024.json" ;;
    11) echo "$R/rebalance_ab_tpu_${ROUND}.json" ;;
    12) echo "$R/delta_ab_tpu_${ROUND}.json" ;;
    13) echo "$R/serve_bench_tpu_${ROUND}.json" ;;
    14) echo "$R/hier_device_tpu_${ROUND}.json" ;;
    15) echo "$R/bricks_ab_tpu_${ROUND}.json" ;;
    16) echo "$R/lod_ab_tpu_${ROUND}.json" ;;
    17) echo "$R/lod_2048_tpu_${ROUND}.json" ;;
    18) echo "$R/attribution_tpu_${ROUND}.json" ;;
    19) echo "$R/delivery_ab_tpu_${ROUND}.json" ;;
  esac
}

NSTEPS=19
STEPS=${SITPU_WATCHER_STEPS:-$(seq 1 $NSTEPS)}
POLLS=${SITPU_WATCHER_POLLS:-900}
SLEEP=${SITPU_WATCHER_SLEEP:-45}

for i in $(seq 1 "$POLLS"); do
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "deadline reached, exiting so the driver owns the chip $(date -u)" \
      >> "$L"
    exit 0
  fi
  next=""
  for s in $STEPS; do
    fails=$(cat "/tmp/watcher_fail.$s" 2>/dev/null || echo 0)
    [ -e "$(step_out "$s")" ] || [ "$fails" -ge "$MAXFAIL" ] \
      || { next="$s"; break; }
  done
  [ -z "$next" ] && { echo "suite done $(date -u)" >> "$L"; exit 0; }
  if probe; then
    echo "tunnel alive $(date -u +%H:%M:%S), step $next" | tee -a "$L"
    date -u >> "$R/tpu_alive_${ROUND}.marker"
    run_step "$next"
    if [ -e "$(step_out "$next")" ]; then
      rm -f "/tmp/watcher_fail.$next"
    elif probe; then
      fails=$(cat "/tmp/watcher_fail.$next" 2>/dev/null || echo 0)
      echo $((fails + 1)) > "/tmp/watcher_fail.$next"
      echo "fail $((fails + 1))/$MAXFAIL for step $next (tunnel alive)" \
        >> "$L"
    fi
  else
    echo "tunnel dead $(date -u +%H:%M:%S), step $next pending" >> "$L"
    sleep "$SLEEP"
  fi
done
echo "tunnel never answered in $POLLS polls" >> "$L"
exit 1
