#!/bin/bash
# Round-4 watcher. Same resumable skeleton as tpu_watcher_r3c.sh (probe
# before EVERY step, output file = done marker, fail-bench after MAXFAIL
# tunnel-alive failures) with the round-4 queue: the segmented-scan fold
# flagship leads (a ~3-minute window must yield the headline number),
# then the fold-schedule microbench that decides whether the round's
# redesign killed the ~390 ms write-fold overhead (VERDICT round 3,
# item 1), the march-stage profile (item 2), the controlled
# 256^3 round-2 A/B (item 6), chunk sweeps, the 1024^3 attempt (item 3),
# and the round-3 diagnostics that never got a window.
# Log: /tmp/tpu_watcher_r4.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p benchmarks/results
R=benchmarks/results
L=/tmp/tpu_watcher_r4.log
# fail counters are POSITION-keyed; invalidate them when the step layout
# changes (done-markers are filename-keyed and migrate on their own —
# NOTE: a step whose COMMAND changes while keeping its filename must
# also rename its artifact if that artifact already exists; as of the
# v3 layout no r4 artifact had ever been produced, so the microbench
# variant additions kept their names)
LAYOUT=v3
if [ "$(cat /tmp/r4_layout 2>/dev/null)" != "$LAYOUT" ]; then
  rm -f /tmp/r4_fail.*
  echo "$LAYOUT" > /tmp/r4_layout
fi

probe() {
  timeout 120 python - <<'EOF' 2>/dev/null
import jax
assert jax.devices()[0].platform == "tpu"
import jax.numpy as jnp
assert float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum()) > 0
EOF
}

run_json() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.full.tmp" 2>>"$L" \
     && tail -1 "$out.full.tmp" > "$out.tmp" \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" \
          "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; rm -f "$out.full.tmp" "$out.failed"
    echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    # keep the full stdout of a failed step — a 30-minute hardware
    # window must never end with nothing to diagnose
    if [ -s "$out.full.tmp" ]; then mv "$out.full.tmp" "$out.failed"; fi
    rm -f "$out.tmp" "$out.full.tmp"
    echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

run_jsonl() {
  local out="$1" tmo="$2"; shift 2
  if timeout "$tmo" "$@" > "$out.tmp" 2>>"$L"; then
    mv "$out.tmp" "$out"; echo "ok: $out $(date -u +%H:%M:%S)" >> "$L"
    cat "$out"
  else
    if [ -s "$out.tmp" ]; then mv "$out.tmp" "$out.partial"; fi
    rm -f "$out.tmp"; echo "FAILED: $out $(date -u +%H:%M:%S)" >> "$L"
  fi
}

run_step() {  # run_step <n>
  case "$1" in
    # 1: flagship 512^3 with the new default fold (auto -> pallas_seg) —
    # FIRST: a short window (window 2 was ~3 min) must yield the headline
    1) run_json "$R/bench_tpu_r4_512.json" 1000 env \
         SITPU_BENCH_PLATFORMS=tpu,tpu SITPU_BENCH_CHILD_TIMEOUT=420 \
         python bench.py ;;
    # 2: THE round-4 diagnostic — every fold schedule head to head at
    # the flagship 512 scale, parity-checked (per-variant guarded).
    2) run_jsonl "$R/fold_microbench_512_seg_r4.jsonl" 2400 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --variants none,count,xla,seg,pallas_seg,pallas,fused,fused_stream,tf_pallas_seg,tf_xla_seg ;;
    # 3: same flagship on the pure-XLA seg fold (Mosaic-free A/B)
    3) run_json "$R/bench_tpu_r4_512_segxla.json" 900 env \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_FOLD=seg \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 4: 256-scale microbench — directly comparable to the committed
    # round-3 numbers (xla 15.4 / two-phase pallas 16.0 ms per march)
    4) run_jsonl "$R/fold_microbench_256_seg_r4.jsonl" 1500 \
         python benchmarks/fold_microbench.py --grid 256 --iters 5 --check \
         --variants none,count,xla,seg,pallas_seg,pallas,fused,fused_stream,tf_pallas_seg,tf_xla_seg ;;
    # 5: march-stage profile at the flagship scale (VERDICT item 2: where
    # do the ~34 counting-march ms go — einsums, TF, opacity, fold?)
    5) run_jsonl "$R/profile_march_512_r4.txt" 1800 \
         python -u benchmarks/profile_march.py 512 ;;
    # 6: controlled 256^3 A/B vs round 2 (VERDICT item 6): exact round-2
    # config — histogram mode, xla fold, chunk 16, 25 frames — on the
    # round-4 build; compare against bench_tpu_2026-07-30_25frames.json
    6) run_json "$R/bench_tpu_r4_256_r2config.json" 900 env \
         SITPU_BENCH_GRID=256 SITPU_BENCH_ADAPTIVE_MODE=histogram \
         SITPU_BENCH_FOLD=xla SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 7: same config, temporal + new fold — the mode/fold deltas at 256
    7) run_json "$R/bench_tpu_r4_256.json" 900 env \
         SITPU_BENCH_GRID=256 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 8: chunk sweep for the seg folds (state traffic halves per doubling;
    # einsum batches grow) at 512
    8) run_jsonl "$R/fold_microbench_512_c32_seg_r4.jsonl" 1800 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --chunk 32 --variants xla,seg,pallas_seg,fused,fused_stream,tf_xla_seg ;;
    9) run_jsonl "$R/fold_microbench_512_c64_seg_r4.jsonl" 1800 \
         python benchmarks/fold_microbench.py --grid 512 --iters 3 --check \
         --chunk 64 --variants seg,pallas_seg,fused,fused_stream,tf_xla_seg ;;
    # 10: flagship at chunk 32 if the sweep says it matters
    10) run_json "$R/bench_tpu_r4_512_c32.json" 900 env \
         SITPU_BENCH_CHUNK=32 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 11: the 1024^3 north-star attempt (VERDICT item 3) — f32 sim state
    # (donated) + bf16 RENDER copy (bench.py render_dtype defaults to
    # bf16 at grid>=1024); a diagnosed OOM is also a result
    11) run_json "$R/bench_tpu_r4_1024.json" 2100 env \
         SITPU_BENCH_GRID=1024 SITPU_BENCH_FRAMES=5 \
         SITPU_BENCH_PLATFORMS=tpu SITPU_BENCH_CHILD_TIMEOUT=1800 \
         python bench.py ;;
    # 12-15: round-3 diagnostics that never got a window
    12) run_json "$R/novel_view_tpu_r4.json" 1500 \
         python benchmarks/novel_view_bench.py --iters 3 ;;
    13) run_json "$R/composite_tpu_r4.json" 1200 env SITPU_BENCH_REAL=1 \
         python benchmarks/composite_bench.py ;;
    14) run_json "$R/scaling_tpu_r4.json" 1800 env SITPU_BENCH_REAL=1 \
         python benchmarks/scaling_bench.py --grid 128 --frames 10 ;;
    15) run_json "$R/profile_frame_tpu_r4.json" 1200 \
         python benchmarks/profile_frame.py --out "$R/trace_r4" ;;
    # 16: in-plane occupancy tiles A/B at the flagship scale (VERDICT
    # item 5) — early Gray-Scott frames are sparse, so vtiles=8 should
    # show the (chunk x v-tile) skip against step 1's whole-slab flagship
    16) run_json "$R/bench_tpu_r4_512_vtiles8.json" 900 env \
         SITPU_BENCH_VTILES=8 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 17: flagship on the fused shade+fold kernel — the rgba and depth
    # streams never exist in HBM (the reference's one-kernel generation)
    17) run_json "$R/bench_tpu_r4_512_fused.json" 900 env \
         SITPU_BENCH_FOLD=pallas_fused SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 18: flagship on the whole-march stream fold — [K] state crosses
    # HBM once per march (the endgame fold schedule)
    18) run_json "$R/bench_tpu_r4_512_fstream.json" 900 env \
         SITPU_BENCH_FOLD=fused_stream SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 19 (round 5): flagship with the RENDER copy in bf16 — if the march
    # is HBM-bound (the roofline fields now in bench.py decide), halving
    # the marched volume's bytes is the single biggest lever; the
    # resampling matmuls already cast to bf16 so MXU work is unchanged
    19) run_json "$R/bench_tpu_r5_512_bf16.json" 900 env \
         SITPU_BENCH_RENDER_DTYPE=bf16 SITPU_BENCH_PLATFORMS=tpu \
         SITPU_BENCH_CHILD_TIMEOUT=700 python bench.py ;;
    # 20 (round 5): novel-view error study on hardware (exact renderer +
    # proxy PSNR sweep — the docs table's TPU twin)
    20) run_json "$R/novel_view_study_tpu_r5.json" 1200 env \
         SITPU_BENCH_REAL=1 python benchmarks/novel_view_study.py ;;
  esac
}

step_out() {
  case "$1" in
    1) echo "$R/bench_tpu_r4_512.json" ;;
    2) echo "$R/fold_microbench_512_seg_r4.jsonl" ;;
    3) echo "$R/bench_tpu_r4_512_segxla.json" ;;
    4) echo "$R/fold_microbench_256_seg_r4.jsonl" ;;
    5) echo "$R/profile_march_512_r4.txt" ;;
    6) echo "$R/bench_tpu_r4_256_r2config.json" ;;
    7) echo "$R/bench_tpu_r4_256.json" ;;
    8) echo "$R/fold_microbench_512_c32_seg_r4.jsonl" ;;
    9) echo "$R/fold_microbench_512_c64_seg_r4.jsonl" ;;
    10) echo "$R/bench_tpu_r4_512_c32.json" ;;
    11) echo "$R/bench_tpu_r4_1024.json" ;;
    12) echo "$R/novel_view_tpu_r4.json" ;;
    13) echo "$R/composite_tpu_r4.json" ;;
    14) echo "$R/scaling_tpu_r4.json" ;;
    15) echo "$R/profile_frame_tpu_r4.json" ;;
    16) echo "$R/bench_tpu_r4_512_vtiles8.json" ;;
    17) echo "$R/bench_tpu_r4_512_fused.json" ;;
    18) echo "$R/bench_tpu_r4_512_fstream.json" ;;
    19) echo "$R/bench_tpu_r5_512_bf16.json" ;;
    20) echo "$R/novel_view_study_tpu_r5.json" ;;
  esac
}

NSTEPS=20
MAXFAIL=2
for i in $(seq 1 500); do
  next=""
  for s in $(seq 1 $NSTEPS); do
    fails=$(cat "/tmp/r4_fail.$s" 2>/dev/null || echo 0)
    [ -e "$(step_out "$s")" ] || [ "$fails" -ge $MAXFAIL ] \
      || { next="$s"; break; }
  done
  [ -z "$next" ] && { echo "suite done $(date -u)" >> "$L"; exit 0; }
  if probe; then
    echo "tunnel alive $(date -u +%H:%M:%S), step $next" | tee -a "$L"
    date -u >> "$R/tpu_alive_r4.marker"
    run_step "$next"
    if [ -e "$(step_out "$next")" ]; then
      rm -f "/tmp/r4_fail.$next"
    elif probe; then
      fails=$(cat "/tmp/r4_fail.$next" 2>/dev/null || echo 0)
      echo $((fails + 1)) > "/tmp/r4_fail.$next"
      echo "fail $((fails + 1))/$MAXFAIL for step $next (tunnel alive)" \
        >> "$L"
    fi
  else
    echo "tunnel dead $(date -u +%H:%M:%S), step $next pending" >> "$L"
    sleep 45
  fi
done