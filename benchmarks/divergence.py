"""The model-vs-measured divergence engine (ROADMAP item 1: "where
measurement and model disagree, the delta IS the next perf PR").

Joins a fresh ``phase_attribution`` capture (obs/profiler.py) against
the committed ``modeled_projection_*.json`` lever stack
(benchmarks/model_projection.py) and emits a per-lever delta report:
measured/modeled ratio, which side of the roofline the error sits on
(from the attribution's roofline verdicts when present), and a ranked
"next perf PR" list.

Scale honesty: the modeled stack is minted for its OWN assumptions
(e.g. 8 ranks at 512^3 on v5e-class HBM/ICI) while a capture may be a
1-chip CPU 128^3 run — raw ms ratios are then scale-polluted, so the
ranking key is the **share delta**: each lever's fraction of its own
frame total, modeled vs measured. A lever whose share grew is eating
more of the frame than the model promised, whatever the absolute
clock; the report states both scales so a reader can judge.

JAX-free on purpose: runs in bench.py's parent orchestrator, in
tpu_watcher post-steps and in CI over committed artifacts.

Usage:
    python benchmarks/divergence.py --attribution FILE [--modeled FILE]
                                    [--out FILE]
    python benchmarks/divergence.py --self-check
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

RESULTS_DIR = os.path.join(_HERE, "results")

# Measured phase names (obs/profiler.py PHASES + synthetic) → the
# modeled stack's per-lever "ms" keys (benchmarks/model_projection.py).
# Since PR 19 the host-delivery path (tile slicing, compression, CRC,
# sinks — measured through ProfileCapture's host_time_fn hook) is a
# modeled lever (bytes × codec throughput, overlap factor from
# pipeline_depth), so "host" joins the lever table; only "unattributed"
# stays in the unmodeled bucket — device time the sitpu_* scopes could
# not explain, the model's remaining stated blind spot.
LEVER_PHASES: Dict[str, tuple] = {
    "sim": ("sim_step",),
    "march": ("march", "halo", "wave"),
    "composite_stream": ("merge", "resegment", "wire_encode"),
    "exchange_exposed": ("exchange",),
    "dcn_exchange": ("dcn_hop",),
    "host_delivery": ("host",),
}
UNMODELED = ("unattributed",)


def latest_modeled(results_dir: str = RESULTS_DIR) -> Optional[str]:
    """Newest committed modeled projection (lexicographic == revision
    order for modeled_projection_r*.json)."""
    paths = sorted(glob.glob(os.path.join(results_dir,
                                          "modeled_projection_*.json")))
    return paths[-1] if paths else None


def extract_attribution(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Accept either a bare ``phase_attribution`` record or a bench
    artifact embedding one."""
    if doc.get("type") == "phase_attribution":
        return doc
    emb = doc.get("phase_attribution")
    if isinstance(emb, dict) and emb.get("phases"):
        return emb
    return None


def _config_score(row_cfg: Dict[str, Any],
                  measured_cfg: Dict[str, Any]) -> int:
    """How many of the lever-defining knobs a stack row shares with the
    measured run. Ties resolve to the LAST matching row — deeper in the
    stack, i.e. the most-levered row consistent with the measurement."""
    score = 0
    for key in ("exchange", "wire", "schedule", "sim_fused",
                "render_dtype", "temporal_reuse", "num_hosts"):
        if key in row_cfg and key in measured_cfg \
                and row_cfg[key] == measured_cfg[key]:
            score += 1
    return score


def select_row(stack: List[Dict[str, Any]],
               measured_cfg: Optional[Dict[str, Any]]
               ) -> Dict[str, Any]:
    """The modeled row to confront the measurement with: best config
    match, else the baseline (first) row."""
    if not stack:
        raise ValueError("modeled projection has an empty stack")
    if not measured_cfg:
        return stack[0]
    best, best_score = stack[0], -1
    for row in stack:
        s = _config_score(row.get("config") or {}, measured_cfg)
        if s >= best_score:
            best, best_score = row, s
    return best


def divergence_report(attribution: Dict[str, Any],
                      modeled_doc: Dict[str, Any],
                      roofline: Optional[Dict[str, Any]] = None,
                      measured_config: Optional[Dict[str, Any]] = None,
                      modeled_path: Optional[str] = None
                      ) -> Dict[str, Any]:
    """Per-lever model-vs-measured delta over one attribution capture.

    Each lever row carries: modeled/measured ms, the raw ratio, both
    shares of their respective frame totals, the share delta (ranking
    key) and — when roofline verdicts ride along — the bound class the
    measured time predominantly sits on (so the reader knows WHICH side
    of the roofline to attack)."""
    phases = attribution.get("phases") or {}
    row = select_row(modeled_doc.get("stack") or [], measured_config)
    modeled_ms: Dict[str, float] = dict(row.get("ms") or {})
    measured_by_lever: Dict[str, float] = {}
    covered = set()
    for lever, names in LEVER_PHASES.items():
        ms = sum(float((phases.get(p) or {}).get("ms") or 0.0)
                 for p in names)
        covered.update(names)
        if lever in modeled_ms or ms > 0:
            measured_by_lever[lever] = ms
    unmodeled_ms = sum(
        float(p.get("ms") or 0.0) for name, p in phases.items()
        if name in UNMODELED or name not in covered)

    modeled_total = sum(modeled_ms.values()) or None
    measured_total = (sum(measured_by_lever.values()) + unmodeled_ms) \
        or None

    def bound_of(names) -> Optional[str]:
        if not roofline:
            return None
        verdicts = roofline.get("verdicts") or {}
        best, best_ms = None, 0.0
        for p in names:
            v = verdicts.get(p)
            if v and float(v.get("ms") or 0.0) >= best_ms:
                best, best_ms = v.get("bound"), float(v.get("ms") or 0.0)
        return best

    levers = {}
    for lever, measured in measured_by_lever.items():
        modeled = modeled_ms.get(lever)
        m_share = (measured / measured_total) if measured_total else None
        p_share = (modeled / modeled_total) \
            if (modeled is not None and modeled_total) else None
        entry = {
            "modeled_ms": modeled,
            "measured_ms": round(measured, 4),
            "ratio": (round(measured / modeled, 3)
                      if modeled else None),
            "modeled_share": (round(p_share, 4)
                              if p_share is not None else None),
            "measured_share": (round(m_share, 4)
                               if m_share is not None else None),
            "share_delta": (round(m_share - p_share, 4)
                            if None not in (m_share, p_share) else None),
            "bound": bound_of(LEVER_PHASES[lever]),
        }
        levers[lever] = entry

    # ranked next-perf-PR list: biggest absolute share divergence first;
    # levers the model doesn't even carry rank by raw measured share
    def rank_key(item):
        e = item[1]
        if e["share_delta"] is not None:
            return abs(e["share_delta"])
        return e["measured_share"] or 0.0

    ranked = []
    for lever, e in sorted(levers.items(), key=rank_key, reverse=True):
        if e["share_delta"] is not None and e["share_delta"] == 0.0:
            continue
        direction = None
        if e["share_delta"] is not None:
            direction = ("measured share above model — attack this "
                         "lever" if e["share_delta"] > 0 else
                         "measured share below model — model too "
                         "pessimistic here")
        ranked.append({"lever": lever, "share_delta": e["share_delta"],
                       "bound": e["bound"], "verdict": direction})

    assumptions = modeled_doc.get("assumptions") or {}
    return {
        "type": "divergence_report",
        "modeled_artifact": modeled_path,
        "modeled_row": row.get("lever"),
        "modeled_assumptions_scale": {
            k: assumptions.get(k) for k in ("ranks", "grid", "hbm_gbps",
                                            "ici_gbps_effective")},
        "measured_scale": {
            "backend": attribution.get("backend"),
            "device_kind": attribution.get("device_kind"),
            "devices": attribution.get("devices"),
            "wall_ms_per_frame": attribution.get("wall_ms_per_frame"),
            "coverage": attribution.get("coverage"),
        },
        "scale_note": (
            "modeled and measured scales differ unless this capture ran "
            "the model's own assumptions — rank levers by share_delta "
            "(scale-free), read raw ratios only on matching hardware"),
        "modeled_total_ms": modeled_total,
        "measured_total_ms": (round(measured_total, 4)
                              if measured_total else None),
        "unmodeled_ms": round(unmodeled_ms, 4),
        "unmodeled_share": (round(unmodeled_ms / measured_total, 4)
                            if measured_total else None),
        "levers": levers,
        "next_perf_pr": ranked,
    }


def report_from_files(attribution_path: str,
                      modeled_path: Optional[str] = None
                      ) -> Dict[str, Any]:
    with open(attribution_path) as f:
        doc = json.load(f)
    attr = extract_attribution(doc)
    if attr is None:
        raise ValueError(
            f"{attribution_path}: no phase_attribution record (neither "
            "bare nor embedded in a bench artifact)")
    modeled_path = modeled_path or latest_modeled()
    if modeled_path is None:
        raise FileNotFoundError(
            "no modeled_projection_*.json under benchmarks/results/")
    with open(modeled_path) as f:
        modeled_doc = json.load(f)
    return divergence_report(
        attr, modeled_doc,
        roofline=doc.get("roofline_verdicts") or doc.get("roofline"),
        measured_config=doc.get("config"),
        modeled_path=os.path.relpath(modeled_path,
                                     os.path.dirname(_HERE)))


def self_check(results_dir: str = RESULTS_DIR) -> int:
    """CI self-check: every committed attribution artifact must produce
    a schema-complete divergence report against the committed modeled
    projection. Returns a process exit code."""
    attrs = sorted(glob.glob(os.path.join(results_dir,
                                          "attribution_*.json")))
    modeled = latest_modeled(results_dir)
    if not attrs or modeled is None:
        print(f"[divergence] self-check needs >=1 attribution_*.json "
              f"and a modeled projection under {results_dir}",
              file=sys.stderr)
        return 1
    failures = 0
    for path in attrs:
        try:
            rep = report_from_files(path, modeled)
            assert rep["type"] == "divergence_report"
            assert rep["levers"], "no levers joined"
            assert rep["next_perf_pr"] is not None
            for e in rep["levers"].values():
                assert e["measured_ms"] is not None
            print(f"[divergence] OK {os.path.basename(path)}: "
                  f"{len(rep['levers'])} levers vs {rep['modeled_row']}"
                  f" (top: {rep['next_perf_pr'][0]['lever'] if rep['next_perf_pr'] else 'none'})")
        except Exception as e:      # noqa: BLE001 — each artifact judged
            # independently; a broken one fails the check loudly instead
            # of aborting the sweep
            from scenery_insitu_tpu import obs

            obs.degrade("divergence.modeled", os.path.basename(path),
                        "failed", f"divergence self-check failed: {e}",
                        warn=False)
            failures += 1
            print(f"[divergence] FAIL {os.path.basename(path)}: {e}",
                  file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--attribution",
                    help="phase_attribution artifact (bare or a bench "
                         "artifact embedding one)")
    ap.add_argument("--modeled",
                    help="modeled_projection_*.json (default: newest "
                         "committed)")
    ap.add_argument("--out", help="write the report here (default: "
                                  "stdout)")
    ap.add_argument("--self-check", action="store_true",
                    help="validate every committed attribution artifact")
    args = ap.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.attribution:
        ap.error("--attribution is required (or use --self-check)")
    rep = report_from_files(args.attribution, args.modeled)
    text = json.dumps(rep, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"[divergence] wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
