"""Incremental profile of the FUSED flagship frame (the exact bench.py
path): times cumulative prefixes of the pipeline inside one jit each, so
phase costs reflect what XLA actually schedules (fusion included), not
isolated-kernel estimates. Usage: python benchmarks/fused_phase_profile.py
[grid]."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def timeit(fn, args, n=5, label=""):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n * 1000
    print(f"{label:46s} {dt:9.2f} ms")
    return dt


def main():
    from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops import supersegments as ss
    from scenery_insitu_tpu.ops.composite import composite_vdis
    from scenery_insitu_tpu.sim import grayscott as gs

    grid = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    k = 16
    sim_steps = 10
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    spec = slicer.make_spec(cam, (grid, grid, grid), SliceMarchConfig())
    vdi_cfg = VDIConfig(max_supersegments=k, adaptive_iters=2,
                        adaptive_mode="histogram")
    comp_cfg = CompositeConfig(max_output_supersegments=k, adaptive_iters=2)
    print(f"grid={grid} ni={spec.ni} nj={spec.nj} chunk={spec.chunk} "
          f"dtype={spec.matmul_dtype} backend={jax.default_backend()}")

    st = gs.GrayScott.init((grid, grid, grid))
    st = gs.multi_step(st, 30)
    jax.block_until_ready(st.u)
    params = st.params
    args = (st.u, st.v)

    def sim_only(u, v):
        s = gs.multi_step_fast(gs.GrayScott(u, v, params), sim_steps)
        return s.u, s.v

    timeit(jax.jit(sim_only), args, label=f"sim x{sim_steps} (fast path)")

    def sim_count(u, v):
        s = gs.multi_step_fast(gs.GrayScott(u, v, params), sim_steps)
        vol = Volume.centered(s.field, extent=2.0)
        axcam = slicer.make_axis_camera(vol, cam, spec)
        occ = slicer.chunk_occupancy(vol, tf, spec)
        tvec = ss.threshold_candidates(vdi_cfg.histogram_bins)

        def consume(cst, rgba, t0, t1):
            for i in range(rgba.shape[0]):
                cst = ss.push_count(cst, tvec[:, None, None], rgba[i])
            return cst

        counts = slicer.slice_march(
            vol, tf, axcam, spec, consume,
            ss.init_count_multi(vdi_cfg.histogram_bins, spec.nj, spec.ni),
            occupancy=occ).count
        return counts

    timeit(jax.jit(sim_count), args, label="+ histogram counting march")

    def sim_gen(u, v):
        s = gs.multi_step_fast(gs.GrayScott(u, v, params), sim_steps)
        vol = Volume.centered(s.field, extent=2.0)
        vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, vdi_cfg)
        return vdi.color

    timeit(jax.jit(sim_gen), args, label="+ write march (full generate)")

    def full(u, v):
        s = gs.multi_step_fast(gs.GrayScott(u, v, params), sim_steps)
        vol = Volume.centered(s.field, extent=2.0)
        vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, vdi_cfg)
        out = composite_vdis(vdi.color[None], vdi.depth[None], comp_cfg)
        return out.color

    timeit(jax.jit(full), args, label="+ composite (full frame)")


if __name__ == "__main__":
    main()
