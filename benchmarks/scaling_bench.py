"""1→N chip scaling sweep — the BASELINE "1→8 chip scaling efficiency"
metric, ready to run the moment multi-chip hardware appears (VERDICT r2
item 8). One command, one JSON line out:

    python benchmarks/scaling_bench.py                  # virtual CPU mesh
    SITPU_BENCH_REAL=1 python benchmarks/scaling_bench.py   # real chips

For each mesh size n (powers of two up to --max-ranks, clipped to the
device count) the sweep runs the PRODUCTION steady-state path — the
distributed temporal MXU VDI step (one march/frame, carried thresholds) —
on the same global workload (strong scaling; --mode weak scales the
z extent with n) and reports per-n FPS, speedup vs n=1, parallel
efficiency, and the all_to_all share measured by separately timing the
column-exchange stage on that n's own VDI tensors (the split forces a
materialization, so the share is an upper bound — same caveat as
benchmarks/phase_bench.py; for the ground-truth overlap use
``session.run(profile_dir=...)`` and xprof).

Inputs are chained across frames (the sim state advances through the
measured step) so no execution-dedup layer can fake the timing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_SCALING_CHILD"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-ranks", type=int, default=8)
    ap.add_argument("--grid", type=int, default=64,
                    help="global cubic grid (strong) / per-chip z base (weak)")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--sim-steps", type=int, default=5)
    ap.add_argument("--mode", choices=("strong", "weak"), default="strong")
    args = ap.parse_args()

    from scenery_insitu_tpu.utils.backend import (enable_compile_cache,
                                                  pin_cpu_backend,
                                                  reexec_virtual_mesh)

    real = os.environ.get("SITPU_BENCH_REAL") == "1"
    if os.environ.get(_CHILD) != "1" and not real:
        reexec_virtual_mesh(args.max_ranks, _CHILD)

    tpu_probe_failed = False
    if real and os.environ.get(_CHILD) != "1":
        # a dead axon tunnel HANGS backend access (it does not error):
        # probe in a subprocess with a hard timeout before touching
        # devices, like bench.py — fall back to CPU with the failure
        # recorded instead of hanging silently behind the README's
        # `> scaling_tpu.json` redirection
        from scenery_insitu_tpu.utils.backend import probe_tpu

        if probe_tpu() == 0:
            from scenery_insitu_tpu import obs

            obs.degrade("bench.platform", "tpu", "cpu",
                        "scaling_bench: TPU probe found no devices",
                        warn=False)
            tpu_probe_failed = True

    import jax

    from scenery_insitu_tpu.utils.compat import shard_map

    if os.environ.get(_CHILD) == "1" or tpu_probe_failed:
        pin_cpu_backend()
    enable_compile_cache()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        _exchange_columns, distributed_initial_threshold_mxu,
        distributed_vdi_step_mxu_temporal, shard_volume)
    from scenery_insitu_tpu.sim import grayscott as gs

    ndev = jax.device_count()
    sizes = [n for n in (1, 2, 4, 8, 16, 32)
             if n <= min(args.max_ranks, ndev)]
    platform = jax.devices()[0].platform
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.5, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_mode="temporal")
    comp_cfg = CompositeConfig(max_output_supersegments=args.k,
                               adaptive_iters=2)
    mcfg = SliceMarchConfig(
        matmul_dtype="f32" if platform != "tpu" else "bf16")
    axis = "ranks"
    sweep = []

    for n in sizes:
        g = args.grid
        gz = g if args.mode == "strong" else g * n
        if gz % n:
            print(f"[scaling] skip n={n}: z={gz} not divisible",
                  file=sys.stderr, flush=True)
            continue
        mesh = make_mesh(n, axis)
        # one spec per n is fine (ni rounded per n); strong scaling keeps
        # the IMAGE workload identical because the volume extent is fixed
        spec = slicer.make_spec(cam, (gz, g, g), mcfg,
                                multiple_of=max(sizes))
        origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
        spacing = jnp.array([2.0 / g, 2.0 / g, 2.0 / gz], jnp.float32)

        step = distributed_vdi_step_mxu_temporal(mesh, tf, spec, vdi_cfg,
                                                 comp_cfg)
        seed = distributed_initial_threshold_mxu(mesh, tf, spec, vdi_cfg)
        sim = jax.jit(lambda u, v: gs.multi_step(
            gs.GrayScott(u, v, gs.GrayScottParams.create()),
            args.sim_steps))

        st = gs.GrayScott.init((gz, g, g), n_seeds=4)
        u = shard_volume(st.u, mesh)
        v = shard_volume(st.v, mesh)

        t_c = time.perf_counter()
        stw = sim(u, v)
        thr = seed(stw.v, origin, spacing, cam)
        (vdi, _), thr = step(stw.v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        compile_s = time.perf_counter() - t_c

        t0 = time.perf_counter()
        for _ in range(args.frames):
            stw = sim(stw.u, stw.v)
            (vdi, _), thr = step(stw.v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        dt = (time.perf_counter() - t0) / args.frames

        # all_to_all share: time ONLY the column exchange at this n's
        # true wire shape — each rank holds a FULL-width sub-VDI
        # [K, 4, Nj, Ni] pre-exchange (split-stage upper bound)
        a2a_ms = 0.0
        if n > 1:
            def exch_roundtrip(c, d):
                # exchange, then locally repack the received column blocks
                # to the input layout so outputs CHAIN into the next
                # iteration's inputs (dedup-proof); the local repack is a
                # per-rank transpose, small next to the ICI transfer, and
                # keeps a2a_ms an upper bound like the split itself
                def rt(x):
                    parts = _exchange_columns(x, n, axis)  # [n, ..., W/n]
                    return jnp.moveaxis(parts, 0, -2).reshape(x.shape)

                return rt(c), rt(d)

            exch = jax.jit(shard_map(
                exch_roundtrip, mesh=mesh, in_specs=(P(axis), P(axis)),
                out_specs=(P(axis), P(axis)), check_vma=False))
            sh = NamedSharding(mesh, P(axis))
            cs = jax.device_put(jnp.tile(vdi.color, (n, 1, 1, 1)), sh)
            ds = jax.device_put(jnp.tile(vdi.depth, (n, 1, 1, 1)), sh)
            jax.block_until_ready(exch(cs, ds))        # warm
            t0 = time.perf_counter()
            for _ in range(args.frames):
                cs, ds = exch(cs, ds)                  # chained inputs
            jax.block_until_ready(ds)
            a2a_ms = (time.perf_counter() - t0) / args.frames * 1000.0

        sweep.append({"n": n, "grid": [gz, g, g],
                      "fps": round(1.0 / dt, 3),
                      "ms_per_frame": round(dt * 1000.0, 2),
                      "all_to_all_ms": round(a2a_ms, 2),
                      "all_to_all_share": round(a2a_ms / (dt * 1000.0), 4),
                      "compile_s": round(compile_s, 1)})
        print(f"[scaling] n={n}: {sweep[-1]['fps']} fps "
              f"(a2a {a2a_ms:.1f} ms)", file=sys.stderr, flush=True)

    base = sweep[0]["fps"] if sweep else 0.0
    for row in sweep:
        row["speedup"] = round(row["fps"] / base, 3) if base else None
        if args.mode == "strong":
            row["efficiency"] = (round(row["fps"] / (base * row["n"]), 3)
                                 if base else None)
        else:
            row["efficiency"] = (round(row["fps"] / base, 3)
                                 if base else None)

    print(json.dumps({
        "metric": f"scaling_{args.mode}_{platform}",
        "value": sweep[-1]["efficiency"] if sweep else None,
        "unit": "parallel_efficiency",
        "sweep": sweep,
        "config": {"mode": args.mode, "grid": args.grid, "k": args.k,
                   "frames": args.frames, "platform": platform,
                   "devices": ndev,
                   "tpu_probe_failed": tpu_probe_failed,
                   "note": ("all_to_all numbers are split-stage upper "
                            "bounds; xprof a profile_dir run for the "
                            "fused overlap")},
    }), flush=True)


if __name__ == "__main__":
    main()
