"""1→N chip scaling sweep — the BASELINE "1→8 chip scaling efficiency"
metric, ready to run the moment multi-chip hardware appears (VERDICT r2
item 8). One command, one JSON line out:

    python benchmarks/scaling_bench.py                  # virtual CPU mesh
    SITPU_BENCH_REAL=1 python benchmarks/scaling_bench.py   # real chips

For each mesh size n (powers of two up to --max-ranks, clipped to the
device count) the sweep runs the PRODUCTION steady-state path — the
distributed temporal MXU VDI step (one march/frame, carried thresholds) —
on the same global workload (strong scaling; --mode weak scales the
z extent with n) and reports per-n FPS, speedup vs n=1, parallel
efficiency, and the all_to_all share measured by separately timing the
column-exchange stage on that n's own VDI tensors (the split forces a
materialization, so the share is an upper bound — same caveat as
benchmarks/phase_bench.py; for the ground-truth overlap use
``session.run(profile_dir=...)`` and xprof).

Inputs are chained across frames (the sim state advances through the
measured step) so no execution-dedup layer can fake the timing.

Two scale-OUT modes ride along (ISSUE 14; docs/MULTIHOST.md):

- ``--mode hosts``: WEAK-scaling growing-HOST runs through the real
  multi-process subprocess harness (testing/multiproc.py) — fixed
  per-rank volume, 1..--max-hosts jax.distributed processes, each
  running the host-path two-level composite (per-host domain partials
  on the local mesh, qpack8-capable tile streams over loopback DCN,
  incremental head assembly). Reports per-host-count ms/frame, weak
  efficiency, and MEASURED per-host DCN bytes next to the
  ``modeled_dcn_traffic`` prediction.
- ``--mode hier-device``: the device-path hierarchical composite
  (domains as mesh sub-axes) vs the flat composite on THIS machine's
  devices — the A/B tpu_watcher step 14 captures on real silicon
  (on the virtual CPU mesh it doubles as the emulated-path timing).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_SCALING_CHILD"

# ------------------------------------------------- hosts mode (harness)

HOSTS_G = 24          # in-plane grid of the weak-scaling scene
HOSTS_GPR = 6         # z slices per RANK (fixed — weak scaling)
HOSTS_K = 6
HOSTS_KOUT = 8
HOSTS_W = HOSTS_H = 16


def _entry_weak(ctx):
    """Harness worker of --mode hosts: render `frames` frames of the
    host-path two-level composite at a FIXED per-rank volume; the head
    (process 0) times barrier->assembled-frame and writes the row."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.vdi import VDIMetadata
    from scenery_insitu_tpu.parallel import multihost
    from scenery_insitu_tpu.parallel.hier import (assemble_hier_frame,
                                                  domain_partial_vdi_step,
                                                  modeled_dcn_traffic,
                                                  publish_partial_tiles)
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import shard_volume
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)
    from scenery_insitu_tpu.sim import grayscott as gs

    frames, dcn_wire = int(ctx.args[0]), ctx.args[1]
    pid, nproc = ctx.process_id, ctx.num_processes
    rec = obs.Recorder(enabled=True, rank=pid)
    obs.set_recorder(rec)

    d_local = len(jax.local_devices())
    n_total = nproc * d_local
    gz = HOSTS_GPR * n_total
    g = HOSTS_G
    st = gs.GrayScott.init((gz, g, g), n_seeds=4)      # same seed everywhere
    field = np.asarray(st.v)
    dn = HOSTS_GPR
    rank0 = pid * d_local
    lo, hi = rank0 * dn, (rank0 + d_local) * dn
    halo_lo = field[lo - 1:lo] if lo > 0 else field[0:1]
    halo_hi = field[hi:hi + 1] if hi < gz else field[gz - 1:gz]

    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.4, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.array([2.0 / g, 2.0 / g, 2.0 / gz], jnp.float32)
    vcfg = VDIConfig(max_supersegments=HOSTS_K, adaptive_iters=2)
    ccfg = CompositeConfig(max_output_supersegments=HOSTS_KOUT,
                           adaptive_iters=2)

    mesh = make_mesh(d_local, devices=jax.local_devices())
    step = domain_partial_vdi_step(mesh, tf, HOSTS_W, HOSTS_H, vcfg, ccfg,
                                   max_steps=24, rank_offset=rank0,
                                   n_total=n_total)
    local = shard_volume(jnp.asarray(field[lo:hi]), mesh)
    hlo, hhi = jnp.asarray(halo_lo), jnp.asarray(halo_hi)
    meta = VDIMetadata.create(np.eye(4, dtype=np.float32),
                              np.eye(4, dtype=np.float32),
                              volume_dims=(gz, g, g),
                              window_dims=(HOSTS_W, HOSTS_H))

    precision = "qpack8" if dcn_wire == "qpack8" else "f32"
    pub = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                       precision=precision, epoch=300 + pid)
    multihost.kv_put_bytes(f"ws/ep/{pid}", pub.endpoint.encode())
    multihost.barrier("ws_eps")
    subs = None
    if pid == 0:
        subs = {h: VDISubscriber(connect=multihost.kv_get_bytes(
            f"ws/ep/{h}").decode()) for h in range(nproc)}
        time.sleep(0.5)
    multihost.barrier("ws_subs")

    sent_total = 0
    recv_base = 0
    frame_ms = []
    for f in range(frames + 1):          # frame 0 = compile, dropped
        multihost.barrier(f"ws_f{f}", timeout_ms=300_000)
        t0 = time.perf_counter()
        acc_c, acc_d = step(local, origin, spacing, cam, hlo, hhi)
        m = meta._replace(index=np.int32(f))
        sent = publish_partial_tiles(pub, acc_c, acc_d, m, tiles=d_local)
        if pid == 0:
            frame, degraded = assemble_hier_frame(
                subs, nproc, ccfg, tiles=d_local, timeout_ms=120_000)
            assert frame is not None and not degraded, (f, degraded)
            dt = (time.perf_counter() - t0) * 1000.0
            if f > 0:
                frame_ms.append(dt)
            else:
                # the dropped compile frame's receives must not inflate
                # the per-frame received-bytes average below
                recv_base = int(rec.counters.get("dcn_bytes_received", 0))
        if f > 0:
            sent_total += sent
    multihost.barrier("ws_done", timeout_ms=300_000)
    pub.close()

    if pid == 0:
        for s in subs.values():
            s.close()
        row = {
            "hosts": nproc, "devices_per_host": d_local,
            "n_ranks": n_total, "grid": [gz, g, g],
            "frames": frames, "dcn_wire": dcn_wire,
            "ms_per_frame": round(float(np.mean(frame_ms)), 2),
            "fps": round(1000.0 / float(np.mean(frame_ms)), 3),
            "dcn_bytes_sent_per_host_measured": sent_total // frames,
            "dcn_bytes_received_head_measured":
                (int(rec.counters.get("dcn_bytes_received", 0))
                 - recv_base) // frames,
            "modeled": modeled_dcn_traffic(
                nproc, d_local, HOSTS_K, HOSTS_H, HOSTS_W,
                dcn_wire=dcn_wire),
        }
        with open(os.path.join(ctx.workdir,
                               f"ws_hosts_{nproc}.json"), "w") as fp:
            json.dump(row, fp)


def _hosts_mode(args) -> None:
    """Parent of --mode hosts: one harness fleet per host count."""
    import tempfile

    from scenery_insitu_tpu.testing import multiproc

    sweep = []
    sizes = [h for h in (1, 2, 4, 8) if h <= args.max_hosts]
    with tempfile.TemporaryDirectory() as workdir:
        for hosts in sizes:
            t0 = time.perf_counter()
            results = multiproc.run_multiproc(
                "benchmarks.scaling_bench:_entry_weak", n_procs=hosts,
                devices_per_proc=args.devices_per_host, workdir=workdir,
                args=(args.frames, args.dcn_wire), timeout_s=600.0)
            bad = [r for r in results if not r.ok]
            if bad:
                print(f"[hier] hosts={hosts} FAILED:\n{bad[0].output}",
                      file=sys.stderr, flush=True)
                sweep.append({"hosts": hosts, "error":
                              f"worker {bad[0].process_id} rc="
                              f"{bad[0].returncode}"})
                continue
            row = json.load(open(os.path.join(workdir,
                                              f"ws_hosts_{hosts}.json")))
            row["wall_s"] = round(time.perf_counter() - t0, 1)
            sweep.append(row)
            print(f"[hier] hosts={hosts}: {row['ms_per_frame']} ms/frame"
                  f" dcn {row['dcn_bytes_sent_per_host_measured']} "
                  f"B/host/frame", file=sys.stderr, flush=True)
    base = next((r.get("fps") for r in sweep if r.get("hosts") == 1
                 and "fps" in r), None)
    for row in sweep:
        if base and "fps" in row:
            # weak scaling: ideal keeps per-host throughput flat
            row["weak_efficiency"] = round(row["fps"] / base, 3)
    print(json.dumps({
        "metric": "hier_weak_scaling_cpu",
        "value": (sweep[-1].get("weak_efficiency")
                  if sweep and "weak_efficiency" in sweep[-1] else None),
        "unit": "weak_parallel_efficiency",
        "sweep": sweep,
        "config": {"mode": "hosts", "per_rank_z": HOSTS_GPR,
                   "grid_inplane": HOSTS_G, "k": HOSTS_K,
                   "frames": args.frames, "dcn_wire": args.dcn_wire,
                   "devices_per_host": args.devices_per_host,
                   "note": ("host-path two-level composite through the "
                            "subprocess harness: per-host local-mesh "
                            "domain partials + tile streams over "
                            "loopback DCN + incremental head assembly; "
                            "ms/frame includes the head merge")},
    }, indent=2), flush=True)


def _hier_device_mode(args) -> None:
    """--mode hier-device: flat vs hierarchical (domains as mesh
    sub-axes) A/B of the production temporal MXU step on this machine's
    devices — tpu_watcher step 14."""
    from scenery_insitu_tpu.utils.backend import (enable_compile_cache,
                                                  pin_cpu_backend,
                                                  reexec_virtual_mesh)

    real = os.environ.get("SITPU_BENCH_REAL") == "1"
    if os.environ.get(_CHILD) != "1" and not real:
        reexec_virtual_mesh(8, _CHILD)
    if os.environ.get(_CHILD) == "1":
        pin_cpu_backend()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu.config import (CompositeConfig,
                                           SliceMarchConfig,
                                           TopologyConfig, VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.hier import modeled_dcn_traffic
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu,
        distributed_vdi_step_mxu_temporal, shard_volume)
    from scenery_insitu_tpu.parallel.topology import make_topology_mesh
    from scenery_insitu_tpu.sim import grayscott as gs

    ndev = jax.device_count()
    platform = jax.devices()[0].platform
    g = args.grid
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.5, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    vcfg = VDIConfig(max_supersegments=args.k, adaptive_mode="temporal")
    ccfg = CompositeConfig(max_output_supersegments=args.k,
                           adaptive_iters=2)
    mcfg = SliceMarchConfig(
        matmul_dtype="f32" if platform != "tpu" else "bf16")
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.array([2.0 / g] * 3, jnp.float32)
    st = gs.GrayScott.init((g, g, g), n_seeds=4)

    def run(mesh, topology):
        spec = slicer.make_spec(cam, (g, g, g), mcfg, multiple_of=ndev)
        step = distributed_vdi_step_mxu_temporal(
            mesh, tf, spec, vcfg, ccfg, topology=topology)
        seed = distributed_initial_threshold_mxu(mesh, tf, spec, vcfg)
        v = shard_volume(st.v, mesh)
        thr = seed(v, origin, spacing, cam)
        (vdi, _), thr = step(v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        t0 = time.perf_counter()
        for _ in range(args.frames):
            (vdi, _), thr = step(v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        dt = (time.perf_counter() - t0) / args.frames * 1000.0
        return dt, np.asarray(vdi.color)

    flat_ms, flat_c = run(make_mesh(ndev), None)
    out = {"metric": f"hier_device_ab_{platform}", "devices": ndev,
           "grid": g, "k": args.k, "flat_ms_per_frame": round(flat_ms, 2),
           "hier": {}}
    hosts_sizes = [h for h in (2, 4) if ndev % h == 0 and ndev // h >= 1
                   and h <= ndev]
    for hosts in hosts_sizes:
        tcfg = TopologyConfig(num_hosts=hosts, dcn_wire=args.dcn_wire)
        mesh, topo = make_topology_mesh(tcfg)
        ms, c = run(mesh, tcfg)
        spec_ni = slicer.make_spec(cam, (g, g, g), mcfg,
                                   multiple_of=ndev).ni
        out["hier"][f"{hosts}x{ndev // hosts}"] = {
            "ms_per_frame": round(ms, 2),
            "vs_flat": round(ms / flat_ms, 3) if flat_ms else None,
            "parity_max_abs_diff": float(np.abs(c - flat_c).max()),
            "modeled_dcn": modeled_dcn_traffic(
                hosts, ndev // hosts, args.k, spec_ni, spec_ni,
                dcn_wire=args.dcn_wire),
        }
        print(f"[hier-device] {hosts}x{ndev // hosts}: {ms:.1f} ms "
              f"(flat {flat_ms:.1f})", file=sys.stderr, flush=True)
    if not hosts_sizes:
        out["note"] = (f"{ndev} device(s) cannot split into >1 domain — "
                       "degenerate capture (flat only)")
    # one line: the watcher's run_json validates the LAST stdout line
    print(json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-ranks", type=int, default=8)
    ap.add_argument("--grid", type=int, default=64,
                    help="global cubic grid (strong) / per-chip z base (weak)")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--sim-steps", type=int, default=5)
    ap.add_argument("--mode",
                    choices=("strong", "weak", "hosts", "hier-device"),
                    default="strong")
    ap.add_argument("--max-hosts", type=int, default=2,
                    help="hosts mode: largest process count in the sweep")
    ap.add_argument("--devices-per-host", type=int, default=2,
                    help="hosts mode: virtual devices per process")
    ap.add_argument("--dcn-wire", default="f32",
                    choices=("f32", "bf16", "qpack8"),
                    help="wire format of the inter-host (DCN) hop")
    args = ap.parse_args()

    if args.mode == "hosts":
        return _hosts_mode(args)
    if args.mode == "hier-device":
        return _hier_device_mode(args)

    from scenery_insitu_tpu.utils.backend import (enable_compile_cache,
                                                  pin_cpu_backend,
                                                  reexec_virtual_mesh)

    real = os.environ.get("SITPU_BENCH_REAL") == "1"
    if os.environ.get(_CHILD) != "1" and not real:
        reexec_virtual_mesh(args.max_ranks, _CHILD)

    tpu_probe_failed = False
    if real and os.environ.get(_CHILD) != "1":
        # a dead axon tunnel HANGS backend access (it does not error):
        # probe in a subprocess with a hard timeout before touching
        # devices, like bench.py — fall back to CPU with the failure
        # recorded instead of hanging silently behind the README's
        # `> scaling_tpu.json` redirection
        from scenery_insitu_tpu.utils.backend import probe_tpu

        if probe_tpu() == 0:
            from scenery_insitu_tpu import obs

            obs.degrade("bench.platform", "tpu", "cpu",
                        "scaling_bench: TPU probe found no devices",
                        warn=False)
            tpu_probe_failed = True

    import jax

    from scenery_insitu_tpu.utils.compat import shard_map

    if os.environ.get(_CHILD) == "1" or tpu_probe_failed:
        pin_cpu_backend()
    enable_compile_cache()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                           VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        _exchange_columns, distributed_initial_threshold_mxu,
        distributed_vdi_step_mxu_temporal, shard_volume)
    from scenery_insitu_tpu.sim import grayscott as gs

    ndev = jax.device_count()
    sizes = [n for n in (1, 2, 4, 8, 16, 32)
             if n <= min(args.max_ranks, ndev)]
    platform = jax.devices()[0].platform
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.5, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_mode="temporal")
    comp_cfg = CompositeConfig(max_output_supersegments=args.k,
                               adaptive_iters=2)
    mcfg = SliceMarchConfig(
        matmul_dtype="f32" if platform != "tpu" else "bf16")
    axis = "ranks"
    sweep = []

    for n in sizes:
        g = args.grid
        gz = g if args.mode == "strong" else g * n
        if gz % n:
            print(f"[scaling] skip n={n}: z={gz} not divisible",
                  file=sys.stderr, flush=True)
            continue
        mesh = make_mesh(n, axis)
        # one spec per n is fine (ni rounded per n); strong scaling keeps
        # the IMAGE workload identical because the volume extent is fixed
        spec = slicer.make_spec(cam, (gz, g, g), mcfg,
                                multiple_of=max(sizes))
        origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
        spacing = jnp.array([2.0 / g, 2.0 / g, 2.0 / gz], jnp.float32)

        step = distributed_vdi_step_mxu_temporal(mesh, tf, spec, vdi_cfg,
                                                 comp_cfg)
        seed = distributed_initial_threshold_mxu(mesh, tf, spec, vdi_cfg)
        sim = jax.jit(lambda u, v: gs.multi_step(
            gs.GrayScott(u, v, gs.GrayScottParams.create()),
            args.sim_steps))

        st = gs.GrayScott.init((gz, g, g), n_seeds=4)
        u = shard_volume(st.u, mesh)
        v = shard_volume(st.v, mesh)

        t_c = time.perf_counter()
        stw = sim(u, v)
        thr = seed(stw.v, origin, spacing, cam)
        (vdi, _), thr = step(stw.v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        compile_s = time.perf_counter() - t_c

        t0 = time.perf_counter()
        for _ in range(args.frames):
            stw = sim(stw.u, stw.v)
            (vdi, _), thr = step(stw.v, origin, spacing, cam, thr)
        jax.block_until_ready(vdi.color)
        dt = (time.perf_counter() - t0) / args.frames

        # all_to_all share: time ONLY the column exchange at this n's
        # true wire shape — each rank holds a FULL-width sub-VDI
        # [K, 4, Nj, Ni] pre-exchange (split-stage upper bound)
        a2a_ms = 0.0
        if n > 1:
            def exch_roundtrip(c, d):
                # exchange, then locally repack the received column blocks
                # to the input layout so outputs CHAIN into the next
                # iteration's inputs (dedup-proof); the local repack is a
                # per-rank transpose, small next to the ICI transfer, and
                # keeps a2a_ms an upper bound like the split itself
                def rt(x):
                    parts = _exchange_columns(x, n, axis)  # [n, ..., W/n]
                    return jnp.moveaxis(parts, 0, -2).reshape(x.shape)

                return rt(c), rt(d)

            exch = jax.jit(shard_map(
                exch_roundtrip, mesh=mesh, in_specs=(P(axis), P(axis)),
                out_specs=(P(axis), P(axis)), check_vma=False))
            sh = NamedSharding(mesh, P(axis))
            cs = jax.device_put(jnp.tile(vdi.color, (n, 1, 1, 1)), sh)
            ds = jax.device_put(jnp.tile(vdi.depth, (n, 1, 1, 1)), sh)
            jax.block_until_ready(exch(cs, ds))        # warm
            t0 = time.perf_counter()
            for _ in range(args.frames):
                cs, ds = exch(cs, ds)                  # chained inputs
            jax.block_until_ready(ds)
            a2a_ms = (time.perf_counter() - t0) / args.frames * 1000.0

        sweep.append({"n": n, "grid": [gz, g, g],
                      "fps": round(1.0 / dt, 3),
                      "ms_per_frame": round(dt * 1000.0, 2),
                      "all_to_all_ms": round(a2a_ms, 2),
                      "all_to_all_share": round(a2a_ms / (dt * 1000.0), 4),
                      "compile_s": round(compile_s, 1)})
        print(f"[scaling] n={n}: {sweep[-1]['fps']} fps "
              f"(a2a {a2a_ms:.1f} ms)", file=sys.stderr, flush=True)

    base = sweep[0]["fps"] if sweep else 0.0
    for row in sweep:
        row["speedup"] = round(row["fps"] / base, 3) if base else None
        if args.mode == "strong":
            row["efficiency"] = (round(row["fps"] / (base * row["n"]), 3)
                                 if base else None)
        else:
            row["efficiency"] = (round(row["fps"] / base, 3)
                                 if base else None)

    print(json.dumps({
        "metric": f"scaling_{args.mode}_{platform}",
        "value": sweep[-1]["efficiency"] if sweep else None,
        "unit": "parallel_efficiency",
        "sweep": sweep,
        "config": {"mode": args.mode, "grid": args.grid, "k": args.k,
                   "frames": args.frames, "platform": platform,
                   "devices": ndev,
                   "tpu_probe_failed": tpu_probe_failed,
                   "note": ("all_to_all numbers are split-stage upper "
                            "bounds; xprof a profile_dir run for the "
                            "fused overlap")},
    }), flush=True)


if __name__ == "__main__":
    main()
