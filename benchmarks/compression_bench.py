"""VDI compression benchmark (≅ reference VDICompressionBenchmarks.kt:
LZ4 / Snappy / LZMA / Gzip over stored VDI color+depth buffers with verify
+ timed iterations, :226-309). Codecs: the vendored native LZ4 block
codec (ingest/native/lz4_block.cpp — the reference's actual wire-codec
family), zstd, zlib, lzma.

Usage: python benchmarks/compression_bench.py [--size 720p] [--k 16]
       [--iters 20] [--grid 64]
Prints one row per codec/level: ratio, compress/decompress throughput,
round-trip verification.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_vdi(width: int, height: int, k: int, grid: int):
    from scenery_insitu_tpu.config import VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

    vol = procedural_volume(grid, kind="blobs", seed=1)
    cam = Camera.create((0.2, 0.6, 3.2), fov_y_deg=50.0, near=0.3, far=20.0)
    vdi, _ = generate_vdi(vol, for_dataset("procedural"), cam, width, height,
                          VDIConfig(max_supersegments=k, adaptive_iters=3),
                          max_steps=128)
    return np.asarray(vdi.color), np.asarray(vdi.depth)


def bench_codec(name: str, level: int, payloads, iters: int):
    from scenery_insitu_tpu.io.vdi_io import compress, decompress

    raw = sum(p.nbytes for p in payloads)
    blobs = [compress(p.tobytes(), name, level) for p in payloads]
    for p, b in zip(payloads, blobs):                      # verify
        back = np.frombuffer(decompress(b, name), p.dtype).reshape(p.shape)
        assert np.array_equal(back, p), f"{name} round-trip mismatch"
    comp = sum(len(b) for b in blobs)

    t0 = time.perf_counter()
    for _ in range(iters):
        for p in payloads:
            compress(p.tobytes(), name, level)
    t_c = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        for b in blobs:
            decompress(b, name)
    t_d = (time.perf_counter() - t0) / iters

    mb = raw / 1e6
    print(f"{name:>5} lvl {level:>2}: ratio {raw / comp:6.2f}x  "
          f"compress {mb / t_c:8.1f} MB/s  decompress {mb / t_d:8.1f} MB/s  "
          f"({raw} -> {comp} bytes)  verified")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=640)
    ap.add_argument("--height", type=int, default=360)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--grid", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    color, depth = make_vdi(args.width, args.height, args.k, args.grid)
    print(f"VDI {args.width}x{args.height} K={args.k}: color {color.nbytes} B"
          f" + depth {depth.nbytes} B")
    codecs = [("lz4", -1), ("zstd", 1), ("zstd", 3), ("zstd", 9),
              ("zlib", 1), ("zlib", 6), ("lzma", 0), ("none", 0)]
    from scenery_insitu_tpu.io import lz4 as _lz4
    if not _lz4.available():
        from scenery_insitu_tpu import obs

        obs.degrade("bench.codec", "lz4", "skipped",
                    "native lz4 block codec unavailable (build failed "
                    "or no toolchain)", warn=False)
        print("  (lz4: native build unavailable, skipped)")
        codecs = [(c, l) for c, l in codecs if c != "lz4"]
    for name, level in codecs:
        bench_codec(name, level, [color, depth], args.iters)


if __name__ == "__main__":
    main()
