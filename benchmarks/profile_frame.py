"""Capture a device-side xprof trace of the steady-state flagship frame
(VERDICT r2 item 1: "one committed xprof trace of a steady-state frame ...
showing where the ms go"). The frame is the same fused program bench.py
times (sim advance → temporal MXU VDI generate → composite), so the trace
is the op-level breakdown behind the headline number — open with
xprof / tensorboard.

    python benchmarks/profile_frame.py [--grid 256] [--frames 10]
        [--out benchmarks/results/trace_r3]

Writes <out>/plugins/profile/**/*.xplane.pb plus a one-line JSON summary
on stdout. Off-TPU it still runs (CPU trace) for smoke-testing.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=256)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--sim-steps", type=int, default=10)
    ap.add_argument("--out", default="benchmarks/results/trace_r3")
    args = ap.parse_args()

    from scenery_insitu_tpu.utils.backend import (enable_compile_cache,
                                                  pin_cpu_backend, probe_tpu)

    if os.environ.get("JAX_PLATFORMS") == "cpu" or probe_tpu() == 0:
        if os.environ.get("JAX_PLATFORMS") != "cpu":
            from scenery_insitu_tpu import obs

            obs.degrade("bench.platform", "tpu", "cpu",
                        "profile_frame: TPU probe found no devices",
                        warn=False)
        pin_cpu_backend()
    enable_compile_cache()

    import jax

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.models.pipelines import grayscott_vdi_frame_step
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.sim import grayscott as gs

    g = args.grid
    base = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5,
                         far=20.0)
    step = grayscott_vdi_frame_step(
        1280, 720, sim_steps=args.sim_steps,
        vdi_cfg=VDIConfig(max_supersegments=args.k,
                          adaptive_mode="temporal"),
        comp_cfg=CompositeConfig(max_output_supersegments=args.k,
                                 adaptive_iters=2),
        engine="mxu", grid_shape=(g, g, g),
        axis_sign=slicer.choose_axis(base))
    frame = jax.jit(step)

    st = gs.GrayScott.init((g, g, g))
    u, v = st.u, st.v
    thr = jax.jit(step.init_threshold)(u, v, base.eye)
    for _ in range(3):                      # compile + reach steady state
        c, d, u, v, thr = frame(u, v, base.eye, thr)
    jax.block_until_ready(c)

    t0 = time.perf_counter()
    with jax.profiler.trace(args.out):
        for _ in range(args.frames):
            c, d, u, v, thr = frame(u, v, base.eye, thr)
        jax.block_until_ready(c)
    dt = (time.perf_counter() - t0) / args.frames

    files = glob.glob(os.path.join(args.out, "**", "*.xplane.pb"),
                      recursive=True)
    print(json.dumps({
        "metric": f"profiled_frame_{g}c",
        "value": round(dt * 1000.0, 2),
        "unit": "ms/frame",
        "platform": jax.devices()[0].platform,
        "trace_files": [os.path.relpath(f) for f in files],
        "frames": args.frames,
    }), flush=True)


if __name__ == "__main__":
    main()
