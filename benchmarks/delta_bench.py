"""Temporal-delta A/B (docs/PERF.md "Temporal deltas"): steady frames
should cost bytes and FLOPs proportional to WHAT CHANGED, not to the
grid.

Two Gray-Scott scenes through the real distributed MXU chain on the
8-rank virtual mesh (SITPU_BENCH_REAL=1 for real devices):

- **slow**: a dense static background with a small evolving Gray-Scott
  feature composed over it (``max(bg, v)``) — most of the domain is
  structure that stopped changing, the steady-state in-situ regime the
  delta plane targets (outer slabs hold bit-for-bit, so exact-mode
  ``range_tol = 0`` already skips);
- **fast**: globally re-randomized amplitude-modulated noise — every
  tile changes every frame, the worst case, which must degrade
  gracefully to ~I-frame cost.

Per scene it A/Bs:

1. **march**: ``CompositeConfig.temporal_reuse = "ranges"`` against the
   re-march-everything baseline — ms/frame plus the per-frame dirty
   histogram (tiles skipped come from the carried ReuseState);
2. **wire**: per-tile qpack8+delta publish (`VDIPublisher(delta=...)`)
   against qpack8-only — wire bytes/frame (compressed, as sent), the
   record mix (I/P/SKIP), bit-exact reconstruction through a live
   VDISubscriber + FrameAssembler, and PSNR vs the f32 frame (equal to
   qpack8's by construction — the delta is lossless ON TOP of qpack8).

Writes one JSON artifact (--out; committed as
results/delta_ab_r12_*.json) with the acceptance verdicts: slow-scene
wire bytes <= 0.4x qpack8-only and >= 30 % of tiles skipping
re-marching.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = "_SITPU_DELTABENCH_CHILD"

from scenery_insitu_tpu.utils.backend import (pin_cpu_backend,  # noqa: E402
                                              reexec_virtual_mesh)


def _psnr(a, b, peak=1.0):
    import numpy as np

    mse = float(np.mean((np.asarray(a) - np.asarray(b)) ** 2))
    if mse == 0:
        return float("inf")
    import math

    return 10.0 * math.log10(peak * peak / mse)


def _scenes(grid: int, frames: int, steps: int, seed: int):
    """Per-scene frame generators yielding the global f32 field."""
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu.sim import grayscott as gs

    def slow():
        # a dense STATIC background texture with a small evolving
        # Gray-Scott feature composed over it (field = max(bg, v)):
        # the in-situ steady-state archetype — most of the domain is
        # structure that stopped changing, a localized front is alive.
        # Outside the feature, v (<= ~1e-3 diffusion tails) never beats
        # the bg floor, so the outer slabs are EXACTLY static — their
        # ranges and codes hold bit-for-bit even at range_tol = 0.
        d = h = w = grid
        rng = np.random.default_rng(seed)
        bg = (0.2 + 0.25 * rng.random((d, h, w))).astype(np.float32)
        u = np.ones((d, h, w), np.float32)
        v = np.zeros((d, h, w), np.float32)
        r = max(grid // 8, 2)
        c = grid // 2
        u[c - r:c + r, c - r:c + r, c - r:c + r] = 0.5
        v[c - r:c + r, c - r:c + r, c - r:c + r] = 0.9
        state = gs.GrayScott(jnp.asarray(u), jnp.asarray(v),
                             gs.GrayScottParams.create())
        bgj = jnp.asarray(bg)
        for _ in range(frames):
            state = gs.multi_step(state, steps)
            yield jnp.maximum(bgj, state.field)

    def fast():
        # fully re-randomized every frame WITH amplitude modulation:
        # every tile's codes change AND every cell's [hi] moves by ~0.3
        # (plain re-randomized uniform noise keeps per-cell min/max
        # statistically pinned — a range detector with a tolerance
        # correctly calls that clean, which is not the worst case this
        # scene exists to measure)
        rng = np.random.default_rng(seed)
        for i in range(frames):
            amp = 0.7 + 0.3 * (i % 2)
            yield jnp.asarray((amp * rng.random((grid, grid, grid)))
                              .astype(np.float32))

    return {"slow": slow, "fast": fast}


def run_scene(name, make_frames, mesh, args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scenery_insitu_tpu.config import (CompositeConfig, DeltaConfig,
                                           SliceMarchConfig, VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import TransferFunction
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_reuse_mxu, distributed_vdi_step_mxu,
        shard_volume)

    n = args.ranks
    t = args.wave_tiles
    tf = TransferFunction.ramp(0.1, 0.9, 0.8, "hot")
    cam = Camera.create((0.0, 0.4, 2.5))
    vdi_cfg = VDIConfig(max_supersegments=args.k,
                        adaptive_mode="histogram")
    spec = slicer.make_spec(cam, (args.grid,) * 3,
                            SliceMarchConfig(scale=1.0),
                            multiple_of=n * t)
    origin = jnp.asarray([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.full((3,), 2.0 / args.grid, jnp.float32)
    kw = (dict(schedule="waves", wave_tiles=t) if t > 1 else {})
    cc_off = CompositeConfig(max_output_supersegments=args.k, **kw)
    cc_on = CompositeConfig(max_output_supersegments=args.k,
                            temporal_reuse="ranges", **kw)
    step_off = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc_off)
    step_on = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc_on,
                                       reuse_tol=args.range_tol)
    rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg,
                                          cc_on)

    fields = [jax.device_put(f) for f in make_frames()]
    frames = len(fields)
    tiles_total = n * t

    # ---- march A/B: identical frame ladder, reuse off vs on
    def loop(step, reuse):
        outs = []
        ru = None
        t0 = time.perf_counter()
        for f in fields:
            sf = shard_volume(f, mesh)
            if reuse:
                if ru is None:
                    ru = rseed(sf, origin, spacing, cam)
                (vdi, _), ru = step(sf, origin, spacing, cam, ru)
            else:
                vdi, _ = step(sf, origin, spacing, cam)
            jax.block_until_ready(vdi.color)
            outs.append((np.asarray(vdi.color), np.asarray(vdi.depth),
                         None if ru is None else np.asarray(ru.dirty)))
        dt = (time.perf_counter() - t0) / frames
        return outs, dt

    loop(step_off, False)                       # compile
    outs_off, ms_off = loop(step_off, False)
    loop(step_on, True)                         # compile
    outs_on, ms_on = loop(step_on, True)

    skipped = sum(int((d == 0).sum()) * t
                  for _, _, d in outs_on[1:] if d is not None)
    possible = (frames - 1) * tiles_total
    max_err = max(float(np.max(np.abs(a[0] - b[0])))
                  for a, b in zip(outs_off, outs_on))

    march = {
        "ms_per_frame_off": round(ms_off * 1e3, 2),
        "ms_per_frame_on": round(ms_on * 1e3, 2),
        "speedup": round(ms_off / ms_on, 3) if ms_on else None,
        "tiles_skipped": skipped,
        "tiles_possible": possible,
        "skip_frac": round(skipped / possible, 4) if possible else 0.0,
        "dirty_per_frame": [[int(x) for x in d] for _, _, d in outs_on
                            if d is not None],
        "max_abs_err_vs_off": max_err,
        "range_tol": args.range_tol,
    }

    # ---- wire A/B: per-tile delta publish vs qpack8-only
    wire = {"skipped": "pyzmq not installed"}
    try:
        import zmq  # noqa: F401
        from scenery_insitu_tpu.core.vdi import VDI
        from scenery_insitu_tpu.runtime.streaming import (FrameAssembler,
                                                          VDIPublisher,
                                                          VDISubscriber)

        pub_d = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                             precision="qpack8", epoch=101,
                             delta=DeltaConfig(
                                 enabled=True,
                                 iframe_period=args.iframe_period))
        pub_q = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                             precision="qpack8", epoch=102)
        sub = VDISubscriber(connect=pub_d.endpoint)
        time.sleep(0.3)
        asm = FrameAssembler(window=4)
        bytes_d = bytes_q = pay_d = pay_q = 0
        recon = {}
        from scenery_insitu_tpu.core.vdi import VDIMetadata

        meta0 = VDIMetadata.create(
            projection=np.eye(4, dtype=np.float32),
            view=np.eye(4, dtype=np.float32),
            volume_dims=(args.grid,) * 3,
            window_dims=(spec.ni, spec.nj), nw=float(spacing[0]),
            index=0)
        # the wire A/B publishes the GROUND-TRUTH (reuse-off) frames:
        # the two levers are independent, and a reuse-tolerance
        # approximation must not leak into the wire measurement
        for i, (c, d, _) in enumerate(outs_off):
            m = meta0._replace(index=np.int32(i))
            wb = c.shape[-1] // tiles_total
            for tt in range(tiles_total):
                sl = slice(tt * wb, (tt + 1) * wb)
                bytes_d += pub_d.publish_tile(
                    VDI(c[..., sl], d[..., sl]), m, tt, tiles_total,
                    tt * wb)
                pay_d += (pub_d.last_bytes["color"]
                          + pub_d.last_bytes["depth"])
                bytes_q += pub_q.publish_tile(
                    VDI(c[..., sl], d[..., sl]), m, tt, tiles_total,
                    tt * wb)
                pay_q += (pub_q.last_bytes["color"]
                          + pub_q.last_bytes["depth"])
            for _ in range(tiles_total):
                got = sub.receive_tile(timeout_ms=3000)
                if got is None or hasattr(got, "kind"):
                    continue
                out = asm.add(*got)
                if out is not None:
                    recon[int(np.asarray(out[1].index))] = out[0]
        st = pub_d.delta_stats
        # reconstruction parity: delta decode == qpack8 quantize cycle
        from scenery_insitu_tpu.ops.wire import (qpack8_dequantize_np,
                                                 qpack8_quantize_np)

        bitexact = True
        psnr_delta = psnr_qpack8 = None
        for i, (c, d, _) in enumerate(outs_off):
            if i not in recon:
                bitexact = False
                continue
            wb = c.shape[-1] // tiles_total
            ref_c = []
            ref_d = []
            for tt in range(tiles_total):
                sl = slice(tt * wb, (tt + 1) * wb)
                qc, qd, near, far = qpack8_quantize_np(c[..., sl],
                                                       d[..., sl])
                rc, rd = qpack8_dequantize_np(qc, qd, near, far)
                ref_c.append(rc)
                ref_d.append(rd)
            ref_c = np.concatenate(ref_c, axis=-1)
            ref_d = np.concatenate(ref_d, axis=-1)
            ok = (np.array_equal(np.asarray(recon[i].color), ref_c)
                  and np.array_equal(np.asarray(recon[i].depth), ref_d))
            bitexact = bitexact and ok
            if i == frames - 1:
                psnr_delta = round(_psnr(recon[i].color, c), 2)
                psnr_qpack8 = round(_psnr(ref_c, c), 2)
        # payload = the compressed record blobs (what scales with
        # content); the ~0.7 KB msgpack header (camera matrices, CRCs)
        # is identical in both modes and constant per message, so at
        # this bench's toy tile size it swamps total bytes — flagship
        # tiles are ~100x larger, where the payload ratio IS the total
        # ratio. Both are recorded; the verdict reads the payload.
        wire = {
            "bytes_per_frame_delta": bytes_d // frames,
            "bytes_per_frame_qpack8": bytes_q // frames,
            "bytes_ratio": round(bytes_d / bytes_q, 4) if bytes_q else None,
            "payload_per_frame_delta": pay_d // frames,
            "payload_per_frame_qpack8": pay_q // frames,
            "payload_ratio": (round(pay_d / pay_q, 4) if pay_q
                              else None),
            "records": {k: st[k] for k in ("i", "p", "skip",
                                           "forced_i")},
            "precodec_bytes_full": st["bytes_full"],
            "precodec_bytes_wire": st["bytes_wire"],
            "tiles_per_frame": tiles_total,
            "iframe_period": args.iframe_period,
            "recon_bitexact_vs_qpack8": bitexact,
            "psnr_db_delta_vs_f32": psnr_delta,
            "psnr_db_qpack8_vs_f32": psnr_qpack8,
        }
        for s in (pub_d, pub_q, sub):
            s.close()
    except ImportError:
        from scenery_insitu_tpu import obs

        obs.degrade("bench.codec", "delta wire A/B", "skipped",
                    "pyzmq is not installed — the march A/B stands, "
                    "the publish-bytes half is skipped", warn=False)

    return {"march": march, "wire": wire}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--grid", type=int, default=48)
    ap.add_argument("--frames", type=int, default=8)
    ap.add_argument("--sim-steps", type=int, default=5,
                    help="Gray-Scott steps per frame (slow scene)")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--wave-tiles", type=int, default=2,
                    help="tiles per rank block (the dirty/publish unit)")
    ap.add_argument("--iframe-period", type=int, default=8)
    ap.add_argument("--range-tol", type=float, default=0.0,
                    help="dirty tolerance (0 = exact mode; the slow "
                         "scene's static background masks diffusion "
                         "tails, so exact mode already skips)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scenes", default="slow,fast")
    ap.add_argument("--out", default=None, help="write JSON artifact")
    args = ap.parse_args()

    if os.environ.get("SITPU_BENCH_REAL") != "1" \
            and os.environ.get(_CHILD) != "1":
        reexec_virtual_mesh(args.ranks, _CHILD)
    if os.environ.get(_CHILD) == "1":
        pin_cpu_backend()

    import jax

    from scenery_insitu_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(args.ranks)
    gens = _scenes(args.grid, args.frames, args.sim_steps, args.seed)
    scenes = {}
    for name in args.scenes.split(","):
        scenes[name] = run_scene(name, gens[name], mesh, args)
        m, w = scenes[name]["march"], scenes[name]["wire"]
        print(f"#DELTA:{name}:march: off {m['ms_per_frame_off']} ms -> "
              f"on {m['ms_per_frame_on']} ms, skip "
              f"{m['skip_frac']:.0%}#")
        if "bytes_ratio" in w:
            print(f"#DELTA:{name}:wire: payload "
                  f"{w['payload_per_frame_qpack8']} -> "
                  f"{w['payload_per_frame_delta']} B/frame "
                  f"(x{w['payload_ratio']}; total x{w['bytes_ratio']}), "
                  f"records {w['records']}#")

    # march verdicts never depend on the wire half (it needs pyzmq and
    # degrades on the ledger when absent); an empty verdict dict must
    # read as FAILURE, not success
    verdicts = {}
    if "slow" in scenes:
        verdicts["slow_skip_geq_30pct"] = \
            scenes["slow"]["march"]["skip_frac"] >= 0.30
        w = scenes["slow"]["wire"]
        if "payload_ratio" in w:
            verdicts["slow_wire_leq_0p4x"] = w["payload_ratio"] <= 0.4
            verdicts["slow_recon_bitexact"] = \
                w["recon_bitexact_vs_qpack8"]
    if "fast" in scenes:
        # graceful degradation: at worst ~I-frame cost (+ small headers)
        w = scenes["fast"]["wire"]
        if "payload_ratio" in w:
            verdicts["fast_wire_graceful"] = w["payload_ratio"] <= 1.1

    result = {
        "kind": "delta_ab",
        "platform": jax.default_backend(),
        "config": {k: getattr(args, k) for k in
                   ("ranks", "grid", "frames", "sim_steps", "k",
                    "wave_tiles", "iframe_period", "range_tol")},
        "scenes": scenes,
        "verdicts": verdicts,
    }
    print(json.dumps({"kind": "delta_ab", "verdicts": verdicts}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.out}")
    return 0 if verdicts and all(verdicts.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
