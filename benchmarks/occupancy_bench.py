"""Empty-space-skipping A/B: off / chunk / pyramid / sim (ISSUE 6).

Two halves, one artifact:

1. **Measured** — time the full VDI generation (histogram-adaptive, the
   two-march shape) at ``--grid`` on the CURRENT backend for each skip
   mode, with the XLA cost-analysis bytes of every compiled step and a
   skip-on vs skip-off parity check (the march's skip path is exact, so
   max|diff| ~ fp noise; the bit-exact composite parity lives in
   tests/test_occupancy.py). On CPU the measured grid defaults small —
   the CPU timings say nothing about the TPU march and are labeled so;
   run on hardware for the ms/frame deltas that matter.

2. **Modeled** — build the REAL occupancy pyramid of the sparse
   Gray-Scott scene at ``--model-grid`` (default 512, the flagship
   scale: the canonical seed-cube init advanced ``--model-sim-steps``
   steps) and convert its live fractions into per-march volume-read
   bytes per mode:

     off      every chunk's slices are read:  S_pad x Nv x Nu x itemsize
     chunk    only live chunks are read       (exactly what slice_march's
              lax.cond skip does — skipped chunks' dynamic_slice never
              executes)
     pyramid  only live (chunk x v-tile) cells are read — IDEALIZED for
              the in-plane level: the banded-matmul gate skips the
              resampling matmuls + TF of gated output-row blocks, and
              this model charges volume reads proportionally (the
              block's slice reads fuse away with every consumer gated);
              treat the pyramid row as the structure's ceiling, the
              chunk row as its floor. The same accounting the reference
              wins with per-cell (VDIGenerator.comp:232-254).

   The occupancy pass itself is charged as one extra volume read for
   the volume-built modes and ~zero for sim-fused ranges (the stencil
   epilogue rides the sim's own pass — sim/pallas_stencil.py).

Writes one JSON artifact (--out); the driver's acceptance gate reads
``model["reduction_vs_off"]["pyramid"]`` (>= 2x on the sparse 512^3
scene). SITPU_CPU=1 pins the CPU backend.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=0,
                    help="measured grid (0 = 512 on TPU, 48 on CPU)")
    ap.add_argument("--model-grid", type=int, default=512)
    ap.add_argument("--model-sim-steps", type=int, default=10,
                    help="Gray-Scott steps developing the model scene")
    ap.add_argument("--sim-steps", type=int, default=5,
                    help="sim steps per measured frame (timed separately)")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--vtiles", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    if os.environ.get("SITPU_CPU") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()
    from scenery_insitu_tpu.utils.backend import enable_compile_cache
    enable_compile_cache()

    import dataclasses

    import jax
    import numpy as np

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.obs.device import cost_snapshot
    from scenery_insitu_tpu.ops import occupancy as occ_mod
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.sim import grayscott as gs

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    grid = args.grid or (512 if on_tpu else 48)
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    print(f"[occupancy_bench] backend={dev.platform} measured grid={grid} "
          f"model grid={args.model_grid}", file=sys.stderr, flush=True)

    # ---------------------------------------------------------- measured
    def spec_for(mode, shape):
        mc = SliceMarchConfig(matmul_dtype="f32" if not on_tpu else "bf16",
                              chunk=args.chunk)
        if mode == "off":
            mc = dataclasses.replace(mc, skip_empty=False,
                                     occupancy_vtiles=0)
        elif mode == "chunk":
            mc = dataclasses.replace(mc, skip_empty=True,
                                     occupancy_vtiles=0)
        else:
            mc = dataclasses.replace(mc, skip_empty=True,
                                     occupancy_vtiles=args.vtiles)
        return slicer.make_spec(cam, shape, mc)

    st = gs.GrayScott.init((grid, grid, grid))
    st = gs.multi_step(st, 10)               # develop the benched scene
    vdi_cfg = VDIConfig(max_supersegments=args.k, adaptive_iters=2,
                        adaptive_mode="histogram")

    # EVERY mode times the same unit of work — one in-situ frame: sim
    # advance + occupancy derivation (whatever the mode's source is) +
    # generation. All frames advance from the SAME (u, v), so the
    # rendered field is identical across modes and the parity check
    # below compares like with like.
    measured = {}
    outs = {}
    for mode in ("off", "chunk", "pyramid", "sim"):
        spec = spec_for(mode, st.v.shape)

        if mode == "sim":
            # the pyramid rides the sim advance (fused epilogue on TPU,
            # ledgered lax fallback elsewhere)
            def frame(u, v, spec=spec):
                st2, rng = gs.multi_step_fast_ranges(
                    gs.GrayScott(u, v, st.params), args.sim_steps)
                vol2 = Volume.centered(st2.field, extent=2.0)
                pyr = occ_mod.pyramid_from_ranges(rng, vol2, tf, spec)
                vdi, _, _ = slicer.generate_vdi_mxu(
                    vol2, tf, cam, spec, vdi_cfg, occupancy=pyr)
                return vdi.color, vdi.depth
        else:
            def frame(u, v, spec=spec):
                st2 = gs.multi_step_fast(
                    gs.GrayScott(u, v, st.params), args.sim_steps)
                vdi, _, _ = slicer.generate_vdi_mxu(
                    Volume.centered(st2.field, extent=2.0), tf, cam,
                    spec, vdi_cfg)
                return vdi.color, vdi.depth
        f = jax.jit(frame)
        fargs = (st.u, st.v)

        try:
            out = f(*fargs)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = f(*fargs)
            jax.block_until_ready(out)
            ms = (time.perf_counter() - t0) / args.iters * 1e3
            outs[mode] = tuple(np.asarray(o) for o in out)
            snap = cost_snapshot(f, *fargs) or {}
            measured[mode] = {
                "ms_per_frame": round(ms, 2),
                "vtiles": spec.vtiles,
                "cost_bytes": snap.get("bytes_accessed"),
                "cost_source": snap.get("source"),
            }
        except Exception as e:
            measured[mode] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[occupancy_bench] measured {mode}: "
              f"{measured[mode]}", file=sys.stderr, flush=True)

    parity = None
    if "off" in outs:
        ref_c, ref_d = outs["off"]
        parity = {}
        for mode in ("chunk", "pyramid"):
            if mode not in outs:
                continue
            dc = float(np.abs(outs[mode][0] - ref_c).max())
            dd = float(np.abs(np.nan_to_num(outs[mode][1], posinf=1e9)
                              - np.nan_to_num(ref_d, posinf=1e9)).max())
            parity[mode] = {"max_abs_diff_color": dc,
                            "max_abs_diff_depth": dd}

    # ----------------------------------------------------------- modeled
    mg = args.model_grid
    print(f"[occupancy_bench] building {mg}^3 model scene "
          f"({args.model_sim_steps} steps)...", file=sys.stderr, flush=True)
    stm = gs.GrayScott.init((mg, mg, mg))
    if args.model_sim_steps:
        stm = gs.multi_step(stm, args.model_sim_steps)
    mvol = Volume.centered(stm.field, extent=2.0)
    mspec = spec_for("pyramid", mvol.data.shape)
    pyr = occ_mod.pyramid_from_volume(mvol, tf, mspec)
    chunks = np.asarray(pyr.chunks)
    tiles = np.asarray(pyr.tiles)
    live_chunks = float(chunks.mean())
    live_cells = float(tiles.mean())

    itemsize = 4.0          # the model scene marches f32 (render_dtype)
    vol_read = float(mg) ** 3 * itemsize          # one full march's reads
    occupancy_pass = vol_read                     # one reduction sweep
    march_bytes = {
        "off": vol_read,
        "chunk": live_chunks * vol_read + occupancy_pass / _marches(),
        "pyramid": live_cells * vol_read + occupancy_pass / _marches(),
        "sim": live_cells * vol_read,   # ranges ride the sim kernel
    }
    model = {
        "grid": mg,
        "sim_steps": args.model_sim_steps,
        "chunk": args.chunk, "vtiles": int(tiles.shape[1]),
        "nchunks": int(chunks.size),
        "live_chunk_fraction": round(live_chunks, 4),
        "live_cell_fraction": round(live_cells, 4),
        "chunk_live_hist": np.histogram(
            tiles.mean(axis=1), bins=8, range=(0.0, 1.0))[0].tolist(),
        "march_read_bytes": {k2: round(v2) for k2, v2
                             in march_bytes.items()},
        "reduction_vs_off": {
            k2: round(march_bytes["off"] / v2, 2)
            for k2, v2 in march_bytes.items() if k2 != "off"},
        "assumptions": (
            "volume-read bytes per march; chunk row is exact "
            "(skipped chunks' dynamic_slice never executes), pyramid/sim "
            "rows idealize the in-plane gate to proportional reads (its "
            "matmul+TF skip is exact, the slice-read saving needs every "
            "consumer of a block gated); occupancy build charged as one "
            "volume sweep amortized over the frame's marches for "
            "volume-built modes, ~0 for sim-fused ranges"),
    }

    out = {
        "metric": f"occupancy_ab_{grid}c_{dev.platform}",
        "platform": dev.platform, "device": dev.device_kind,
        "measured": {"grid": grid, "iters": args.iters,
                     "k": args.k, "modes": measured, "parity": parity},
        "model": model,
        "degradations": obs.ledger(),
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as fo:
            fo.write(line + "\n")
        print(f"[occupancy_bench] wrote {args.out}", file=sys.stderr,
              flush=True)


def _marches() -> float:
    """Marches per frame the occupancy pass amortizes over (histogram
    mode: one counting + one writing)."""
    return 2.0


if __name__ == "__main__":
    main()
