"""Modeled 8-rank Config-2 projection — the per-lever ms/frame stack
ROADMAP item 1 owes when the TPU tunnel is unreachable (commit the model
with stated assumptions rather than nothing).

Composes the EXISTING committed traffic models — nothing new is invented
here, the stack is just their sum at the BASELINE.md Config-2 shape
(8 ranks, 512^3 global Gray-Scott, 640x640 intermediate grid, K=16,
temporal adaptive = one march/frame):

- sim:       sim.pallas_stencil.modeled_sim_traffic (fused vs roll)
- march:     one volume read of the rank slab per march, f32 vs bf16
             (SliceMarchConfig.render_dtype), scaled by the committed
             sim-fused occupancy-pyramid reduction
             (benchmarks/results/occupancy_ab_r06_512.json, 2.43x)
- exchange:  ops.composite.modeled_exchange_traffic (all_to_all vs ring,
             f32 vs qpack8 wire, frame vs waves schedule — the waves row
             charges only the EXPOSED exchange bytes, docs/PERF.md
             "Tile waves")
- rebalance: the skewed-occupancy scenario rows multiply the march term
             by the per-rank straggler factor (max/mean march work —
             the frame barrier is the MAX over ranks): the even split's
             factor and the occupancy plan's come from the committed
             rank_slab_bench A/B (rebalance_ab_r10_cpu.json), with the
             stated assumption that the measured CPU 96^3 skew (dense
             low-z quarter) transfers to the 512^3 banded Gray-Scott
             regime PR 6 measured at live-cell 0.41
- composite: the same model's stream_bytes_per_rank (merge working set
             + k_out output write)
- delivery:  the host delivery plane (PR 19) — one rank's frame share
             over PCIe plus a codec sweep (quantize/pack + CRC) of the
             input bytes; every ladder row prices it SERIALLY (the
             pre-PR-19 critical path where the loop blocks on
             np.asarray and encodes inline) and the +async_delivery
             scenario row shows the depth-k pipeline + encode-worker
             fan-out leaving only max(0, host - device) exposed

Every row converts bytes -> ms with the stated bandwidth assumptions and
adds them (a traffic LOWER BOUND: compute, dispatch and host time are
excluded; the measured flagship runs well below peak bandwidth, so the
honest reading is the RELATIVE per-lever deltas, not the absolute ms).
The flagship datum (419.43 ms/frame, 1 chip, pre-lever) is carried for
reference. Usage:

    python benchmarks/modeled_projection.py \
        [--out benchmarks/results/modeled_projection_r08.json]

No accelerator access — safe anywhere (JAX_PLATFORMS=cpu is fine).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")

# ---- Config-2 shape (BASELINE.md; flagship capture bench_tpu_r4_512) ----
RANKS = 8
GRID = 512
SIM_STEPS = 10
NI = NJ = 640                    # flagship intermediate grid at 512^3
K = 16
WAVE_TILES = 4

# ---- bandwidth assumptions (stated, not measured) ----
# v5e HBM data-sheet peak; the flagship capture achieved ~8.4% of it, so
# absolute ms here are optimistic floors — the deltas are the signal.
HBM_GBPS = 819.0
# effective per-link ICI assumption for a v5e 1-D ring (conservative
# fraction of the ~400 GB/s aggregate the data sheet quotes per chip).
ICI_GBPS = 45.0
# effective per-host DCN assumption for the inter-domain hop of the
# hierarchical composite (docs/MULTIHOST.md) — a conservative 25 Gbit/s
# of usable cross-host bandwidth (~1/14 of the ICI link): DCN is the
# slow level by construction, which is the whole reason the composite
# splits into two levels instead of running one flat exchange over it.
DCN_GBPS = 3.125
# ---- host delivery plane (PR 19) ----
# PCIe Gen4 x16 assumption for the device->host copy of the rendered
# frame (the copy the async fetch overlaps behind the next dispatch).
PCIE_GBPS = 32.0
# single-worker codec throughput over the INPUT f32 bytes of the
# delivery path — qpack8 quantize/pack + CRC32 (or memcpy + CRC32 on
# an f32 wire): vectorized quantize plus zlib.crc32 land around
# 2 GB/s/core on the CPU reference; deflate-class codecs are slower
# and belong in delivery_bench's heavy-sink scenario, not here.
CODEC_GBPS = 2.0
# the committed async-delivery configuration (RuntimeConfig
# .pipeline_depth / DeliveryConfig.encode_workers defaults the bench
# sweeps around)
DELIVERY_WORKERS = 4
PIPELINE_DEPTH = 4


def _load(rel, default=None):
    try:
        with open(os.path.join(R, rel)) as f:
            return json.load(f)
    except Exception:
        return default


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON artifact to PATH")
    ap.add_argument("--lod", action="store_true",
                    help="expand every committed LOD ladder rung into "
                         "its own scenario row (default: only the best "
                         "rung holding the artifact's PSNR floor)")
    args = ap.parse_args()

    from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    flagship = _load("bench_tpu_r4_512.json", {})
    base_ms = float(flagship.get("ms_per_frame", 419.43))

    occ = _load("occupancy_ab_r06_512.json", {})
    pyr_reduction = float(
        (occ.get("model") or {}).get("reduction_vs_off", {}).get("sim",
                                                                 2.43))
    reb = _load("rebalance_ab_r10_cpu.json", {})
    strag_even = float((reb.get("even") or {}).get("straggler_factor",
                                                   2.88))
    strag_plan = float((reb.get("occupancy") or {}).get(
        "straggler_factor", 1.85))

    slab = (GRID // RANKS, GRID, GRID)
    slab_vox = slab[0] * slab[1] * slab[2]

    def ms_hbm(nbytes):
        return nbytes / (HBM_GBPS * 1e9) * 1e3

    def ms_ici(nbytes):
        return nbytes / (ICI_GBPS * 1e9) * 1e3

    # one rank's share of the delivered frame: K supersegments x
    # (4 color + 2 depth) planes x NI x NJ f32 over RANKS column bands —
    # the payload _fetch hands the delivery plane every frame
    frame_bytes_per_rank = K * 6 * NI * NJ * 4 // RANKS

    def ms_host_delivery(workers=1):
        """Serial host cost of delivering one rank's frame share:
        device->host copy over PCIe plus the codec sweep (quantize/pack
        + CRC) over the input bytes, fanned across ``workers`` per-tile
        encode threads (PCIe is serial regardless — one link)."""
        copy = frame_bytes_per_rank / (PCIE_GBPS * 1e9) * 1e3
        codec = frame_bytes_per_rank / (CODEC_GBPS * workers * 1e9) * 1e3
        return copy + codec

    def row(lever, sim_fused, march_bytes_per_vox, march_scale,
            exchange, wire, ring_slots, schedule, note):
        sim_b = ps.modeled_sim_traffic(slab, SIM_STEPS, fused=sim_fused)
        march_b = slab_vox * march_bytes_per_vox / march_scale
        ex = modeled_exchange_traffic(
            RANKS, K, NJ, NI, k_out=K, mode=exchange,
            ring_slots=ring_slots, wire=wire, schedule=schedule,
            wave_tiles=WAVE_TILES)
        ici_b = (ex["ici_bytes_exposed_per_rank"]
                 if schedule == "waves" else ex["ici_bytes_per_rank"])
        stream_b = ex["stream_bytes_per_rank"]
        # every ladder row prices delivery SERIALLY (pipeline_depth=1,
        # the pre-PR-19 behavior): the host term sits fully on the
        # frame's critical path — the +async_delivery scenario row at
        # the end is where it comes off
        host = ms_host_delivery()
        total = (ms_hbm(sim_b + march_b + stream_b) + ms_ici(ici_b)
                 + host)
        return {
            "lever": lever,
            "config": {"sim_fused": sim_fused,
                       "render_dtype": ("bf16" if march_bytes_per_vox == 2
                                        else "f32"),
                       "occupancy_march_reduction": march_scale,
                       "exchange": exchange, "wire": wire,
                       "ring_slots": ring_slots, "schedule": schedule,
                       "pipeline_depth": 1, "delivery": "serial"},
            "bytes": {"sim_hbm": round(sim_b),
                      "march_hbm": round(march_b),
                      "composite_stream_hbm": round(stream_b),
                      "exchange_ici_exposed": round(ici_b),
                      "exchange_ici_total": ex["ici_bytes_per_rank"],
                      "delivery_host": frame_bytes_per_rank},
            "ms": {"sim": round(ms_hbm(sim_b), 2),
                   "march": round(ms_hbm(march_b), 2),
                   "composite_stream": round(ms_hbm(stream_b), 3),
                   "exchange_exposed": round(ms_ici(ici_b), 3),
                   "host_delivery": round(host, 2)},
            "modeled_ms_per_frame": round(total, 2),
            "note": note,
        }

    stack = [
        row("baseline_no_levers", False, 4, 1.0, "all_to_all", "f32", 0,
            "frame", "roll-formulation sim, f32 march, monolithic "
            "all_to_all frame — the pre-PR-1 schedule at 8 ranks"),
        row("+sim_fused_stencil", True, 4, 1.0, "all_to_all", "f32", 0,
            "frame", "time-fused Pallas stencil (PR 1): T steps per "
            "u,v round trip"),
        row("+bf16_march", True, 2, 1.0, "all_to_all", "f32", 0,
            "frame", "bf16 marched-volume copy (PR 1): march + halo "
            "bytes halve, f32 accumulation"),
        row("+simfused_occupancy_pyramid", True, 2, pyr_reduction,
            "all_to_all", "f32", 0, "frame",
            f"sim-fused value-range pyramid (PR 6): march reads / "
            f"{pyr_reduction} at the committed 512^3 live fraction"),
        row("+ring_exchange", True, 2, pyr_reduction, "ring", "f32", K,
            "frame", "ring ppermute chain with ring_slots=K (PR 4): "
            "merge working set N*K -> 2K"),
        row("+qpack8_wire", True, 2, pyr_reduction, "ring", "qpack8", K,
            "frame", "qpack8 supersegment wire (PR 5): ICI bytes / 4"),
        row("+tile_waves", True, 2, pyr_reduction, "ring", "qpack8", K,
            "waves", f"tile-wave pipeline (this PR): {WAVE_TILES} waves "
            f"hide {(WAVE_TILES - 1)}/{WAVE_TILES} of the exchange "
            "behind march compute — only the last wave's bytes stay on "
            "the critical path"),
    ]
    # ---- skewed-occupancy scenario (ISSUE 10): the ladder above
    # assumes balanced bands; these two rows re-price the final stack's
    # march term under a skewed scene — frame march = mean * straggler
    # (max over ranks is the barrier) — first with the even split, then
    # with the occupancy render plan. Sim stays balanced (the SIM
    # decomposition is always the even z-slab; only the RENDER bands
    # re-plan).
    last = stack[-1]
    for lever, strag, note in (
            ("skewed_scene_even_split", strag_even,
             f"SCENARIO row: same levers, but the scene banding makes "
             f"the even split's densest rank the frame barrier — march "
             f"term x{strag_even} (measured straggler factor, "
             f"rank_slab_bench CPU A/B)"),
            ("+render_rebalance", strag_plan,
             f"occupancy render plan (this PR): uneven z bands re-planned "
             f"from pyramid live fractions cut the straggler factor to "
             f"x{strag_plan} (measured; plan recompiles bounded by "
             f"quantum+hysteresis)")):
        ms = dict(last["ms"])
        ms["march"] = round(ms["march"] * strag, 2)
        total = sum(ms.values())
        stack.append({
            "lever": lever,
            "config": {**last["config"], "scenario": "skewed-occupancy",
                       "rebalance": ("occupancy" if "rebalance" in lever
                                     else "even"),
                       "straggler_factor": strag},
            "bytes": last["bytes"],
            "ms": ms,
            "modeled_ms_per_frame": round(total, 2),
            "note": note,
        })

    # ---- steady-state temporal-delta scenario (ISSUE 12): frames are
    # coherent, so the march term scales with WHAT CHANGED. Clean ranks
    # skip their march entirely (temporal_reuse="ranges"; the dirty
    # detector reads the sim-fused ranges already in the stack, so a
    # skipped frame pays no extra sweep). skip_frac is the measured
    # slow-scene tile fraction from the committed delta_ab artifact.
    dab = _load("delta_ab_r12_cpu.json", {})
    slow = (dab.get("scenes") or {}).get("slow", {})
    skip_frac = float((slow.get("march") or {}).get("skip_frac", 0.75))
    wire_ratio = float((slow.get("wire") or {}).get("payload_ratio",
                                                    0.25))
    # base on the balanced-scene full ladder row BY NAME — positional
    # indexing rots silently as scenario rows accrete around it
    full_stack = next(r for r in stack if r["lever"] == "+tile_waves")
    ms = dict(full_stack["ms"])
    ms["march"] = round(ms["march"] * (1.0 - skip_frac), 2)
    stack.append({
        "lever": "steady_scene_temporal_reuse",
        "config": {**full_stack["config"],
                   "scenario": "steady-state (slow-evolving)",
                   "temporal_reuse": "ranges",
                   "skip_frac": skip_frac},
        "bytes": full_stack["bytes"],
        "ms": ms,
        "modeled_ms_per_frame": round(sum(ms.values()), 2),
        "note": f"SCENARIO row: dirty-tile re-march (ISSUE 12) on a "
                f"slow-evolving scene — {skip_frac:.0%} of tiles reuse "
                f"last frame's fragments (measured slow-scene skip "
                f"fraction, delta_bench CPU A/B); the win scales with "
                f"run steadiness, not grid size",
    })

    # ---- multi-resolution LOD scenario (ISSUE 16): the march term
    # re-priced by the committed LOD ladder (lod_ab_r16_cpu.json). The
    # planner's level tuple cuts modeled march FLOPs ~2^-l per coarse
    # brick (the resample's second matmul keeps the FINE output grid);
    # the HBM read of a level-l brick shrinks faster (~8^-l, the pooled
    # copy), so dividing this model's march TRAFFIC term by the ladder's
    # FLOP reduction is conservative. Default row: the best rung holding
    # the artifact's 40 dB floor; --lod expands every rung.
    lab = _load("lod_ab_r16_cpu.json", {})
    lod_rungs = [r_ for r_ in (lab.get("ladder") or [])
                 if r_.get("error_px") is not None]
    if args.lod:
        picked = lod_rungs
    else:
        floor = float(lab.get("psnr_floor_db", 40.0))
        picked = [r_ for r_ in lod_rungs
                  if r_.get("flop_reduction", 0) == lab.get("value")
                  and (r_["psnr_db"] == "inf"
                       or float(r_["psnr_db"]) >= floor)][:1]
    full_stack = next(r for r in stack if r["lever"] == "+tile_waves")
    for rung in picked:
        red = float(rung.get("flop_reduction", 1.0))
        if red <= 1.0:
            continue
        ms = dict(full_stack["ms"])
        ms["march"] = round(ms["march"] / red, 2)
        hist = rung.get("level_hist", {})
        stack.append({
            "lever": f"+lod_march_err{rung['error_px']}px",
            "config": {**full_stack["config"],
                       "scenario": "multi-resolution LOD",
                       "lod_error_px": rung["error_px"],
                       "level_hist": hist,
                       "psnr_db": rung["psnr_db"]},
            "bytes": full_stack["bytes"],
            "ms": ms,
            "modeled_ms_per_frame": round(sum(ms.values()), 2),
            "note": f"SCENARIO row (ISSUE 16): per-brick LOD marching "
                    f"at error_px={rung['error_px']} — the committed "
                    f"ladder's level histogram {hist} cuts modeled "
                    f"march FLOPs x{red} at {rung['psnr_db']} dB "
                    f"(lod_ab_r16_cpu); march traffic shrinks at least "
                    f"as fast (coarse reads are ~8^-l of fine)",
        })

    # ---- multi-host scale-out scenario (ISSUE 14): the full-lever
    # stack per DOMAIN plus the inter-domain DCN hop of the two-level
    # composite (parallel/hier.py). Per host the DCN term is
    # modeled_dcn_traffic's ring bytes over the stated DCN bandwidth —
    # what a FLAT exchange would pay instead is every rank's whole
    # (n-1)-fragment exchange crossing DCN, priced alongside so the
    # two-level win is explicit. Grid scales weakly (fixed per-rank
    # volume: H hosts render an H-times-deeper volume at the same
    # per-frame cost + the DCN term).
    from scenery_insitu_tpu.parallel.hier import modeled_dcn_traffic

    def ms_dcn(nbytes):
        return nbytes / (DCN_GBPS * 1e9) * 1e3

    full_stack = next(r for r in stack if r["lever"] == "+tile_waves")
    flat_ex = modeled_exchange_traffic(RANKS, K, NJ, NI, k_out=K,
                                       mode="ring", ring_slots=K,
                                       wire="qpack8")
    for hosts in (2, 4):
        dcn = modeled_dcn_traffic(hosts, RANKS, K, NJ, NI,
                                  dcn_wire="qpack8", ring_slots=K)
        ms = dict(full_stack["ms"])
        # PER-HOST bytes over the PER-HOST link: all of a host's ranks
        # funnel through its shared DCN NIC (DCN_GBPS is per host)
        ms["dcn_exchange"] = round(
            ms_dcn(dcn["dcn_bytes_sent_per_host"]), 2)
        # a flat H*RANKS-rank exchange would push (H-1)/H of every
        # rank's fragment traffic across DCN instead — the same
        # per-host funnel prices all RANKS ranks' share
        flat_over_dcn = round(
            ms_dcn(flat_ex["ici_bytes_per_rank"] * RANKS
                   * (hosts - 1) / hosts), 2)
        stack.append({
            "lever": f"+hier_composite_{hosts}hosts",
            "config": {**full_stack["config"],
                       "scenario": "multi-host weak scale-out",
                       "num_hosts": hosts, "dcn_wire": "qpack8",
                       "grid": [GRID * hosts, GRID, GRID]},
            "bytes": {**full_stack["bytes"],
                      "dcn_per_rank": dcn["dcn_bytes_sent_per_rank"],
                      "dcn_per_host": dcn["dcn_bytes_sent_per_host"]},
            "ms": ms,
            "modeled_ms_per_frame": round(sum(ms.values()), 2),
            "flat_exchange_over_dcn_ms": flat_over_dcn,
            "note": f"SCENARIO row (ISSUE 14): {hosts} ICI domains over "
                    f"DCN at {DCN_GBPS} GB/s/host — the two-level "
                    f"composite ships the capped accumulator's column "
                    f"sub-blocks ({ms['dcn_exchange']} ms) where a flat "
                    f"{hosts * RANKS}-rank exchange would drag "
                    f"{flat_over_dcn} ms of fragment traffic across "
                    f"DCN; volume scales weakly to "
                    f"{GRID * hosts}x{GRID}x{GRID}",
        })

    # ---- async delivery plane (ISSUE 19): every row above prices the
    # host delivery path (device->host copy + codec + sinks) SERIALLY —
    # the pre-PR-19 critical path, where the render loop blocks on
    # np.asarray and then encodes inline. The delivery executor takes it
    # off that path: with pipeline_depth >= 2 the async fetch of frame
    # i-1 and the worker-tier encode overlap frame i's dispatch, so the
    # steady-state frame is max(device, host), not device + host — the
    # exposed host term is what max() leaves sticking out. encode
    # workers fan the codec sweep across cores; the PCIe copy stays
    # serial (one link). depth bounds how many frames of host jitter the
    # bounded queue absorbs before the block/drop_oldest policy engages;
    # the steady-state model below assumes the queue never saturates.
    full_stack = next(r for r in stack if r["lever"] == "+tile_waves")
    host_serial = ms_host_delivery()
    host_async = ms_host_delivery(DELIVERY_WORKERS)
    ms = dict(full_stack["ms"])
    device_total = sum(v for k, v in ms.items() if k != "host_delivery")
    exposed = max(0.0, host_async - device_total)
    ms["host_delivery"] = round(exposed, 2)
    stack.append({
        "lever": "+async_delivery",
        "config": {**full_stack["config"],
                   "pipeline_depth": PIPELINE_DEPTH,
                   "delivery": "async",
                   "encode_workers": DELIVERY_WORKERS},
        "bytes": full_stack["bytes"],
        "ms": ms,
        "host_delivery_serial_ms": round(host_serial, 2),
        "host_delivery_async_ms": round(host_async, 2),
        "host_delivery_hidden_ms": round(host_async - exposed, 2),
        "modeled_ms_per_frame": round(sum(ms.values()), 2),
        "note": f"async delivery plane (this PR): depth-{PIPELINE_DEPTH} "
                f"fetch pipeline + background delivery executor + "
                f"{DELIVERY_WORKERS} per-tile encode workers — host "
                f"work drops {round(host_serial, 2)} -> "
                f"{round(host_async, 2)} ms ({DELIVERY_WORKERS}x codec "
                f"fan-out) and overlaps the device frame, leaving "
                f"{round(exposed, 2)} ms exposed: steady-state frame = "
                f"max(device, host)",
    })

    b0 = stack[0]["modeled_ms_per_frame"]
    for r_ in stack:
        r_["speedup_vs_baseline"] = round(b0 / r_["modeled_ms_per_frame"],
                                          2)

    from scenery_insitu_tpu.ops.delta import modeled_delta_traffic

    delta_wire = modeled_delta_traffic(
        K, NJ, NI, skip_frac=skip_frac,
        p_frac=max(0.0, 1.0 - skip_frac - 1.0 / RANKS), iframe_period=8)
    delta_wire["measured_slow_scene_payload_ratio"] = wire_ratio
    delta_wire["source"] = ("benchmarks/results/delta_ab_r12_cpu.json "
                            "(slow scene; compressed record payloads — "
                            "headers are constant per message and "
                            "vanish at flagship tile sizes)")

    out = {
        "metric": f"modeled_projection_{RANKS:02d}rank_config2_{GRID}",
        "value": stack[-1]["modeled_ms_per_frame"],
        "unit": "ms/frame (modeled lower bound)",
        "baseline_ms_per_frame": base_ms,
        "baseline_artifact": "benchmarks/results/bench_tpu_r4_512.json",
        "modeled_stack_speedup": stack[-1]["speedup_vs_baseline"],
        "assumptions": {
            "ranks": RANKS, "grid": GRID, "sim_steps": SIM_STEPS,
            "intermediate": [NI, NJ], "k": K,
            "wave_tiles": WAVE_TILES,
            "marches_per_frame": 1,
            "hbm_gbps": HBM_GBPS, "ici_gbps_effective": ICI_GBPS,
            "dcn_gbps_effective_per_host": DCN_GBPS,
            "pcie_gbps": PCIE_GBPS,
            "delivery_codec_gbps_per_worker": CODEC_GBPS,
            "delivery_pipeline_depth": PIPELINE_DEPTH,
            "delivery_encode_workers": DELIVERY_WORKERS,
            "host_delivery_source":
                "benchmarks/results/delivery_ab_r19_cpu.json (codec "
                "throughput order; assumption: quantize+CRC sweeps the "
                "input f32 bytes once at ~2 GB/s/worker, PCIe copy is "
                "serial per host link)",
            "occupancy_march_reduction_source":
                "benchmarks/results/occupancy_ab_r06_512.json (sim row)",
            "straggler_factor_source":
                "benchmarks/results/rebalance_ab_r10_cpu.json (measured "
                "CPU 96^3 skewed scene; assumption: the skew transfers "
                "to 512^3 banded Gray-Scott, PR-6 live-cell 0.41)",
            "excluded": "compute time, kernel launch/dispatch, "
                        "fold-state traffic beyond the composite "
                        "stream model — this is a TRAFFIC lower bound; "
                        "the flagship runs at ~8.4% of HBM peak, so "
                        "read the RELATIVE deltas, not the absolute ms "
                        "(host delivery joined the model in PR 19: "
                        "bytes x codec throughput + PCIe copy, "
                        "overlapped per the +async_delivery row)",
            "note_sim_attribution": "the '~290 of 419 ms is sim' split "
                                    "(ROADMAP item 1) is still "
                                    "hardware-unconfirmed; this model "
                                    "keeps sim and render terms "
                                    "separate so either outcome maps "
                                    "onto a subset of rows",
            "delta_skip_frac_source":
                "benchmarks/results/delta_ab_r12_cpu.json (slow scene; "
                "assumption: steady in-situ runs look like the "
                "slow-evolving scene most frames)",
        },
        "stack": stack,
        "delta_wire_steady_state": delta_wire,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
