"""VDI IO round-trips: file artifacts, codecs, variable-length segment wire
format (SURVEY.md §7 step 10a; ≅ the reference's golden-file strategy §4.2)."""

import numpy as np
import pytest

from scenery_insitu_tpu.config import VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.io.vdi_io import (CODECS, compress, decompress,
                                          dump_path, load_vdi,
                                          pack_vdi_segments, save_vdi,
                                          unpack_vdi_segments)
from scenery_insitu_tpu.ops.composite import composite_vdis
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

W = H = 32
K = 8


@pytest.fixture(scope="module")
def vdi_meta():
    vol = procedural_volume(16, kind="blobs", seed=5)
    tf = TransferFunction.ramp(0.1, 0.9, 0.6)
    cam = Camera.create((0.0, 0.0, 4.0), fov_y_deg=50.0, near=0.5, far=20.0)
    return generate_vdi(vol, tf, cam, W, H,
                        VDIConfig(max_supersegments=K, adaptive_iters=2),
                        max_steps=48)


@pytest.mark.parametrize("codec", sorted(CODECS))
def test_codec_roundtrip(codec):
    if codec == "lz4":
        from scenery_insitu_tpu.io import lz4
        if not lz4.available():
            pytest.skip("no C++ toolchain for the native lz4 codec")
    if codec == "zstd":
        from scenery_insitu_tpu.io.vdi_io import have_zstd, resolve_codec
        if not have_zstd():
            # optional dep absent: the writer entry points degrade the
            # codec to stdlib zlib with a ledger entry, so the
            # round-trip must still hold — assert THAT path instead of
            # skipping (raw zstd compress stays strict by design).
            import warnings

            from scenery_insitu_tpu import obs
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                codec = resolve_codec("zstd")
            assert codec == "zlib"
            assert any(e["component"] == "io.vdi_codec"
                       and e["to"] == "zlib" for e in obs.ledger())
    data = np.random.default_rng(0).standard_normal(1000).astype(np.float32)
    blob = compress(data.tobytes(), codec)
    assert decompress(blob, codec) == data.tobytes()


def test_unknown_codec_raises():
    with pytest.raises(ValueError):
        compress(b"x", "snappy")
    with pytest.raises(ValueError):
        decompress(b"x", "snappy")


@pytest.mark.parametrize("codec", ["zstd", "none"])
def test_save_load_bit_exact(tmp_path, vdi_meta, codec):
    vdi, meta = vdi_meta
    p = str(tmp_path / "a.npz")
    nbytes = save_vdi(p, vdi, meta, codec=codec)
    assert nbytes > 0
    back, bmeta = load_vdi(p)
    np.testing.assert_array_equal(np.asarray(vdi.color), back.color)
    np.testing.assert_array_equal(np.asarray(vdi.depth), back.depth)
    for f in meta._fields:
        np.testing.assert_array_equal(np.asarray(getattr(meta, f)),
                                      np.asarray(getattr(bmeta, f)))


def test_save_without_meta(tmp_path, vdi_meta):
    vdi, _ = vdi_meta
    p = str(tmp_path / "b.npz")
    save_vdi(p, vdi)
    back, meta = load_vdi(p)
    assert meta is None
    np.testing.assert_array_equal(np.asarray(vdi.color), back.color)


def test_compression_helps_on_real_vdi(tmp_path, vdi_meta):
    vdi, meta = vdi_meta
    raw = save_vdi(str(tmp_path / "raw.npz"), vdi, meta, codec="none")
    z = save_vdi(str(tmp_path / "z.npz"), vdi, meta, codec="zstd")
    # sparse supersegment tensors compress heavily
    assert z < raw / 2


def test_segment_pack_unpack(vdi_meta):
    vdi, _ = vdi_meta
    for n in (1, 2, 4):
        blobs, climits, dlimits = pack_vdi_segments(vdi, n)
        assert len(blobs) == 2 * n
        assert [len(b) for b in blobs[:n]] == list(climits)
        assert [len(b) for b in blobs[n:]] == list(dlimits)
        back = unpack_vdi_segments(blobs, K, H, W)
        np.testing.assert_array_equal(np.asarray(vdi.color), back.color)
        np.testing.assert_array_equal(np.asarray(vdi.depth), back.depth)


def test_segment_width_must_divide(vdi_meta):
    vdi, _ = vdi_meta
    with pytest.raises(ValueError):
        pack_vdi_segments(vdi, 5)      # 32 % 5 != 0


def test_fixture_replay_through_compositor(tmp_path, vdi_meta):
    """The golden-file loop: dump -> reload -> run a pipeline stage on the
    fixture (≅ VDICompositingExample re-compositing a stored VDI set)."""
    import jax.numpy as jnp

    vdi, meta = vdi_meta
    p = dump_path(str(tmp_path), "procedural", 0, "vdi")
    save_vdi(p, vdi, meta)
    back, _ = load_vdi(p)
    out = composite_vdis(jnp.asarray(back.color)[None],
                         jnp.asarray(back.depth)[None])
    ref = composite_vdis(vdi.color[None], vdi.depth[None])
    np.testing.assert_allclose(np.asarray(out.color), np.asarray(ref.color),
                               atol=1e-6)


def test_vdi_sink(tmp_path, vdi_meta):
    from scenery_insitu_tpu.runtime.session import vdi_sink
    vdi, _ = vdi_meta
    sink = vdi_sink(str(tmp_path), "ds", every=2)
    for i in range(4):
        sink(i, {"vdi_color": np.asarray(vdi.color),
                 "vdi_depth": np.asarray(vdi.depth), "frame": i})
    import glob
    files = sorted(glob.glob(str(tmp_path / "*.npz")))
    assert len(files) == 2
    back, _ = load_vdi(files[0])
    np.testing.assert_array_equal(np.asarray(vdi.color), back.color)
