"""Non-convex brick partitions (CompositeConfig.rebalance == "bricks";
docs/SCENARIOS.md "Brick maps"): BrickMap / steal_plan units, the
reslab_bricks shuffle, adversarial property tests of the composite
primitives the brick path leans on (merge_vdis_pairwise /
resegment_stream under interleaved non-convex inputs), and the
correctness keystone — COMPOSITE INVARIANCE: permuting brick ownership
leaves the composited frame unchanged on the 8-device virtual mesh.

Parity gates, and why each is what it is:
- gather VDI step: BITWISE between ownership permutations. Every
  brick's fragment is generated against the brick's clip AABB on the
  GLOBAL sample ladder — identical whichever rank marched it — and the
  composite's per-pixel stable sort canonicalizes the stacked order.
- mxu steps (both march regimes, waves + ring crosses, temporal): 1e-5
  (the PR-6 fusion-noise gate for separately-compiled programs; on the
  power-of-two-spacing scene the diffs measure 0.0).
- bricks vs the plain even split: same gates — the scene keeps content
  >= 2 slices clear of every brick AND slab boundary and under the
  per-region K budget, so segment structure coincides (the PR-10
  K-truncation caveat applies to bricks identically).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.ops.composite import (merge_vdis_pairwise,
                                              resegment_stream,
                                              sort_stream)
from scenery_insitu_tpu.parallel import bricks as bk
from scenery_insitu_tpu.parallel.mesh import make_mesh, reslab_bricks
from scenery_insitu_tpu.parallel.pipeline import (_resolve_bricks,
                                                  distributed_vdi_step,
                                                  distributed_vdi_step_mxu,
                                                  shard_volume)
from scenery_insitu_tpu.utils.compat import shard_map

N = 8
D = 32
HW = 16
ATOL = 1e-5

# single-brick-per-rank non-convex assignment + an ownership relabeling
OWNER = (3, 0, 5, 1, 4, 7, 2, 6)
PERM = (2, 0, 3, 1, 5, 7, 4, 6)
# two disjoint interleaved slabs per rank (B = 2)
INTERLEAVED = tuple(list(range(N)) + list(range(N)))
# ownership islands + an empty rank (rank 7 owns nothing)
ISLANDS = (0, 0, 1, 2, 3, 4, 5, 6)


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _scene():
    """Smooth constant-value blobs >= 2 slices clear of every brick
    boundary (bz=4 and bz=2 grids) and of the even split, power-of-two
    voxel spacing — the same construction as tests/test_rebalance.py."""
    data = np.zeros((D, HW, HW), np.float32)
    blobs = [(1, 3, 0.3), (5, 7, 0.5), (9, 11, 0.7), (13, 15, 0.4),
             (17, 19, 0.6), (21, 23, 0.8), (29, 31, 0.45)]
    for a, b, v in blobs:
        data[a:b] = v
    vox = 2.0 / D
    origin = jnp.asarray([-HW * vox / 2, -HW * vox / 2, -1.0], jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    return jnp.asarray(data), origin, spacing


def _mxu_spec(cam, **cfg_kw):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, (D, HW, HW),
                            SliceMarchConfig(matmul_dtype="f32", scale=2.0,
                                             **cfg_kw),
                            multiple_of=N)


def _cfgs(rebalance="bricks", **comp_kw):
    return (VDIConfig(max_supersegments=6, adaptive_iters=2),
            CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                            rebalance=rebalance, **comp_kw))


# ---------------------------------------------------------- BrickMap units


def test_brickmap_validation():
    with pytest.raises(ValueError, match="divide"):
        bk.BrickMap(30, 4, (0, 1, 2, 3, 0, 1, 2))       # 7 bricks / 30
    with pytest.raises(ValueError, match="outside"):
        bk.BrickMap(32, 4, (0, 1, 2, 4))
    with pytest.raises(ValueError, match="permutation"):
        bk.BrickMap(32, 4, (0, 1, 2, 3)).permute([0, 0, 1, 2])
    with pytest.raises(ValueError, match="n_ranks"):
        bk.BrickMap.even(32, 3, nbricks=4)


def test_brickmap_geometry_and_tables():
    bm = bk.BrickMap(D, N, ISLANDS)
    assert bm.nbricks == 8 and bm.brick_depth == 4
    assert bm.slots == 2
    assert bm.rank_bricks(0) == (0, 1)
    assert bm.rank_bricks(7) == ()
    table = bm.start_table()
    assert table.shape == (N, 2)
    assert list(table[0]) == [0, 4]
    assert list(table[7]) == [-1, -1]
    assert bm.intervals(1) == [(8, 12)]


def test_brickmap_even_convex_detection():
    assert bk.BrickMap.even(D, N).is_even_convex()
    assert bk.BrickMap.even(D, N, nbricks=16).is_even_convex()
    assert bk.BrickMap.contiguous(D, N, 16).is_even_convex()
    assert not bk.BrickMap(D, N, OWNER).is_even_convex()
    # contiguous with a non-dividing brick count is a valid seed but
    # not the even map
    assert not bk.BrickMap.contiguous(30 * N, N, 30).is_even_convex()


def test_auto_nbricks_divides():
    for d, n in [(96, 8), (100, 8), (32, 8), (512, 8), (7, 2)]:
        nb = bk.auto_nbricks(d, n)
        assert d % nb == 0
        assert nb <= max(n, 4 * n)


def test_brick_work_and_straggler():
    prof = np.zeros(16)
    prof[:4] = 1.0                       # live work in the low quarter
    work = bk.brick_work(prof, D, 16, base_cost=0.0)
    assert work[:4].sum() > 0 and work[4:].sum() == 0
    even = bk.BrickMap.even(D, N, nbricks=16)
    assert bk.straggler_factor(even, work) > 2.0


def test_steal_plan_equalizes_and_caps_moves():
    prof = np.zeros(16)
    prof[:4] = 1.0
    work = bk.brick_work(prof, D, 16)
    even = bk.BrickMap.even(D, N, nbricks=16)
    s0 = bk.straggler_factor(even, work)
    bm = bk.steal_plan(even, work, max_moves=2, hysteresis=0.0)
    # the move cap binds per replan; iterating replans converges
    assert sum(a != b for a, b in zip(bm.owner, even.owner)) <= 2
    assert bk.straggler_factor(bm, work) < s0
    for _ in range(8):
        bm = bk.steal_plan(bm, work, max_moves=2, hysteresis=0.0)
    assert bk.straggler_factor(bm, work) < s0 / 1.5


def test_steal_plan_hysteresis_object_equal():
    work = np.ones(16)                   # perfectly balanced already
    even = bk.BrickMap.even(D, N, nbricks=16)
    assert bk.steal_plan(even, work, hysteresis=0.1) is even
    # and a converged skewed plan stays put
    prof = np.zeros(16)
    prof[:4] = 1.0
    w = bk.brick_work(prof, D, 16)
    bm = even
    for _ in range(10):
        bm = bk.steal_plan(bm, w, max_moves=2, hysteresis=0.1)
    assert bk.steal_plan(bm, w, max_moves=2, hysteresis=0.1) is bm


# ------------------------------------------------------- reslab_bricks


def test_reslab_bricks_contents_halo_and_absent_slots():
    mesh = make_mesh(N)
    data = np.arange(D * 4 * 4, dtype=np.float32).reshape(D, 4, 4)
    sdata = shard_volume(jnp.asarray(data), mesh)
    bm = bk.BrickMap(D, N, ISLANDS)
    from jax.sharding import PartitionSpec as P

    f = jax.jit(shard_map(
        lambda x: reslab_bricks(x, bm, "ranks", h=1), mesh=mesh,
        in_specs=P("ranks", None, None),
        out_specs=P("ranks", None, None, None), check_vma=False))
    out = np.asarray(f(sdata)).reshape(N, bm.slots, bm.brick_depth + 2,
                                       4, 4)
    table = bm.start_table()
    for r in range(N):
        for s in range(bm.slots):
            st = table[r, s]
            if st < 0:
                assert (out[r, s] == 0).all()
                continue
            rows = np.clip(np.arange(st - 1, st + bm.brick_depth + 1),
                           0, D - 1)
            np.testing.assert_array_equal(out[r, s], data[rows])


def test_reslab_bricks_rejects_mismatched_geometry():
    mesh = make_mesh(N)
    data = shard_volume(jnp.zeros((D, 4, 4)), mesh)
    from jax.sharding import PartitionSpec as P

    for bm, msg in ((bk.BrickMap(D, 4, (0, 1, 2, 3)), "ranks"),
                    (bk.BrickMap(2 * D, N, tuple(range(N))), "depth")):
        with pytest.raises(ValueError, match=msg):
            jax.jit(shard_map(
                lambda x, bm=bm: reslab_bricks(x, bm, "ranks"),
                mesh=mesh, in_specs=P("ranks", None, None),
                out_specs=P("ranks", None, None, None),
                check_vma=False))(data)


# ------------------------- adversarial merge / resegment property tests


def _random_sorted_stream(rng, k, h, w, n_live, lo=0.0, hi=1.0):
    """Per-pixel depth-sorted, empty-masked stream with ``n_live`` live
    slots drawn from disjoint sub-intervals of [lo, hi) — the shape a
    brick fragment has after sort_stream."""
    starts = np.full((k, h, w), np.inf, np.float32)
    ends = np.full((k, h, w), np.inf, np.float32)
    colors = np.zeros((k, 4, h, w), np.float32)
    if n_live:
        edges = np.sort(rng.uniform(lo, hi, size=(2 * n_live, h, w)),
                        axis=0)
        starts[:n_live] = edges[0::2]
        ends[:n_live] = edges[1::2]
        a = rng.uniform(0.05, 0.9, size=(n_live, h, w)).astype(np.float32)
        rgb = rng.uniform(0.0, 1.0, size=(n_live, 3, h, w)) * a[:, None]
        colors[:n_live, :3] = rgb
        colors[:n_live, 3] = a
    depth = np.stack([starts, ends], axis=1).astype(np.float32)
    return jnp.asarray(colors), jnp.asarray(depth)


def _merge_reference(ca, da, cb, db):
    """Stable concat + argsort-by-start — the sorted-reference merge."""
    c = jnp.concatenate([ca, cb], axis=0)
    d = jnp.concatenate([da, db], axis=0)
    return sort_stream(c, d)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_merge_pairwise_interleaved_matches_sorted_reference(seed):
    """Two ranks owning interleaved disjoint depth ranges (the
    non-convex case): the pairwise merge equals the sorted reference,
    payloads bit-for-bit (+inf empties included)."""
    rng = np.random.default_rng(seed)
    # stream a in even-indexed bands, stream b in odd — interleaved
    ca, da = _random_sorted_stream(rng, 6, 3, 4, 4, lo=0.0, hi=1.0)
    cb, db = _random_sorted_stream(rng, 6, 3, 4, 3, lo=0.05, hi=1.05)
    mc, md = merge_vdis_pairwise(ca, da, cb, db)
    rc, rd = _merge_reference(ca, da, cb, db)
    np.testing.assert_array_equal(np.asarray(mc), np.asarray(rc))
    np.testing.assert_array_equal(np.asarray(md), np.asarray(rd))


def test_merge_pairwise_empty_brick_ranks():
    """An empty-brick rank (all +inf) merges as the identity on the
    other stream; two empties merge to all-empty."""
    rng = np.random.default_rng(3)
    ca, da = _random_sorted_stream(rng, 5, 2, 3, 4)
    ce, de = _random_sorted_stream(rng, 5, 2, 3, 0)
    mc, md = merge_vdis_pairwise(ca, da, ce, de)
    np.testing.assert_array_equal(np.asarray(mc[:5]), np.asarray(ca))
    np.testing.assert_array_equal(np.asarray(md[:5]), np.asarray(da))
    assert np.isinf(np.asarray(md[5:, 0])).all()
    mc2, md2 = merge_vdis_pairwise(ce, de, ce, de)
    assert np.isinf(np.asarray(md2[:, 0])).all()
    assert (np.asarray(mc2) == 0).all()


def test_merge_truncation_radiance_monotone():
    """K-truncation keeps the NEAREST k_cap segments: retained radiance
    (summed premultiplied energy of kept live slots) is monotone
    non-decreasing in k_cap, and the kept prefix is bit-stable."""
    rng = np.random.default_rng(4)
    ca, da = _random_sorted_stream(rng, 8, 3, 3, 6)
    cb, db = _random_sorted_stream(rng, 8, 3, 3, 6, lo=0.02, hi=1.02)
    prev_rad = -1.0
    prev = None
    for cap in (8, 10, 12, 16):
        mc, md = merge_vdis_pairwise(ca, da, cb, db, k_cap=cap)
        live = np.isfinite(np.asarray(md[:, 0]))
        rad = float(np.sum(np.asarray(mc) * live[:, None]))
        assert rad >= prev_rad - 1e-6
        if prev is not None:
            np.testing.assert_array_equal(np.asarray(mc)[:prev.shape[0]],
                                          prev)
        prev_rad = rad
        prev = np.asarray(mc)


@pytest.mark.parametrize("seed", [0, 5])
def test_resegment_invariant_to_empty_slot_padding(seed):
    """The brick-path invariant: a sorted stream and the same stream
    with extra +inf empty slots appended (what padded brick slots
    contribute) re-segment IDENTICALLY — slot count is shape, not
    content."""
    rng = np.random.default_rng(seed)
    sc, sd = _random_sorted_stream(rng, 6, 3, 4, 5)
    pad_c = jnp.zeros((4,) + tuple(sc.shape[1:]), jnp.float32)
    pad_d = jnp.full((4,) + tuple(sd.shape[1:]), jnp.inf, jnp.float32)
    cfg = CompositeConfig(max_output_supersegments=5, adaptive_iters=3,
                          backend="xla")
    a = resegment_stream(sc, sd, cfg)
    b = resegment_stream(jnp.concatenate([sc, pad_c]),
                         jnp.concatenate([sd, pad_d]), cfg)
    np.testing.assert_array_equal(np.asarray(a.color), np.asarray(b.color))
    np.testing.assert_array_equal(np.asarray(a.depth), np.asarray(b.depth))


# --------------------------------------------- composite invariance matrix


def _assert_vdi_close(a, b, atol=ATOL):
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


def test_gather_brick_permutation_bitwise():
    """The keystone: permuting brick ownership leaves the gather
    builder's composited frame BITWISE unchanged, and the brick frame
    matches the even decomposition."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    bm = bk.BrickMap(D, N, OWNER)
    outs = []
    for b in (bm, bm.permute(PERM)):
        vc, cc = _cfgs()
        step = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc,
                                    max_steps=48, bricks=b)
        v = step(sdata, origin, spacing, _cam())
        outs.append((np.asarray(v.color), np.asarray(v.depth)))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    vc, cc = _cfgs(rebalance="even")
    even = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc,
                                max_steps=48)(sdata, origin, spacing,
                                              _cam())
    _assert_vdi_close(outs[0], (even.color, even.depth))


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z
                                 (3.8, 0.3, 0.6)])   # march axis x
def test_mxu_brick_permutation_matches_even(eye):
    """MXU engine, both march regimes: ownership permutations agree and
    the brick frame equals the even frame at the 1e-5 gate (z bricks own
    marched slices through w_bounds, x/y bricks through v_bounds)."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam(eye)
    spec = _mxu_spec(cam)
    bm = bk.BrickMap(D, N, OWNER)
    outs = []
    for b in (bm, bm.permute(PERM)):
        vc, cc = _cfgs()
        v, meta = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                           bricks=b)(
            sdata, origin, spacing, cam)
        outs.append((v.color, v.depth, np.asarray(meta.volume_dims)))
    _assert_vdi_close(outs[0][:2], outs[1][:2])
    # metadata keeps describing the GLOBAL volume
    np.testing.assert_array_equal(outs[0][2],
                                  np.asarray([HW, HW, D], np.float32))
    vc, cc = _cfgs(rebalance="even")
    even, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc)(
        sdata, origin, spacing, cam)
    _assert_vdi_close(outs[0][:2], (even.color, even.depth))


def test_mxu_interleaved_and_empty_rank_maps_match_even():
    """Adversarial maps: two interleaved disjoint slabs per rank (B=2)
    and ownership islands with an empty rank — all equal the even
    frame."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    vc, cc = _cfgs(rebalance="even")
    even, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc)(
        sdata, origin, spacing, cam)
    for owner in (INTERLEAVED, ISLANDS):
        vc, cc = _cfgs()
        v, _ = distributed_vdi_step_mxu(
            mesh, _tf(), spec, vc, cc,
            bricks=bk.BrickMap(D, N, owner))(sdata, origin, spacing, cam)
        _assert_vdi_close((v.color, v.depth), (even.color, even.depth))


def test_mxu_brick_waves_and_ring_cross_match_frame():
    """Waves x bricks and ring x bricks: the tile-wave overlap pipeline
    and the pairwise-merge ring both reproduce the brick frame
    schedule's all_to_all output."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    bm = bk.BrickMap(D, N, OWNER)
    vc, cc = _cfgs()
    base, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                       bricks=bm)(
        sdata, origin, spacing, cam)
    for kw in (dict(schedule="waves", wave_tiles=2),
               dict(exchange="ring")):
        vc, cc = _cfgs(**kw)
        v, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                        bricks=bm)(
            sdata, origin, spacing, cam)
        _assert_vdi_close((v.color, v.depth), (base.color, base.depth))


def test_mxu_brick_temporal_carry_matches_even():
    """Temporal mode: per-slot threshold maps (row-stacked carry) over 3
    frames match the even decomposition's frames."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    cfg_t = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    bm = bk.BrickMap(D, N, OWNER)
    runs = {}
    for b in (None, bm):
        cc = CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                             rebalance="bricks" if b else "even")
        thr = distributed_initial_threshold_mxu(
            mesh, _tf(), spec, cfg_t, bricks=b)(sdata, origin, spacing,
                                                cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, cfg_t,
                                                 cc, bricks=b)
        frames = []
        for _ in range(3):
            (v, _), thr = step(sdata, origin, spacing, cam, thr)
            frames.append((np.asarray(v.color), np.asarray(v.depth)))
        runs[b is not None] = frames
    for fr_b, fr_e in zip(runs[True], runs[False]):
        _assert_vdi_close(fr_b, fr_e)


# --------------------------------------------- resolution + observability


def test_even_convex_map_short_circuits():
    """The even-convex map resolves to None — builders take the
    pre-brick path bitwise, and no brick build markers mint."""
    from scenery_insitu_tpu import obs

    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        cc = CompositeConfig(rebalance="bricks")
        assert _resolve_bricks(cc, N, bk.BrickMap.even(D, N)) is None
        assert _resolve_bricks(cc, N, bk.BrickMap.even(D, N, 16)) is None
        assert _resolve_bricks(cc, 1, bk.BrickMap(D, 1, (0,))) is None
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("bricks_steps_built", 0) == 0


def test_resolve_bricks_validation():
    bm = bk.BrickMap(D, N, OWNER)
    with pytest.raises(ValueError, match="rebalance"):
        _resolve_bricks(CompositeConfig(), N, bm)
    with pytest.raises(ValueError, match="ranks"):
        _resolve_bricks(CompositeConfig(rebalance="bricks"), 4, bm)
    with pytest.raises(TypeError):
        _resolve_bricks(CompositeConfig(rebalance="bricks"), N, (0, 1))


def test_brick_build_emits_obs_counters():
    from scenery_insitu_tpu import obs

    data, origin, spacing = _scene()
    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        mesh = make_mesh(N)
        vc, cc = _cfgs()
        bm = bk.BrickMap(D, N, ISLANDS)
        step = distributed_vdi_step_mxu(mesh, _tf(), _mxu_spec(_cam()),
                                        vc, cc, bricks=bm)
        step(shard_volume(data, mesh), origin, spacing, _cam())
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("bricks_steps_built", 0) >= 1
    builds = [e for e in rec.events if e.get("name") == "bricks_build"]
    assert builds and builds[0]["attrs"]["owner"] == list(ISLANDS)
    assert builds[0]["attrs"]["slots"] == 2
    assert builds[0]["attrs"]["bricks_per_rank"][7] == 0


def test_bricks_inert_builders_ledger():
    """Hybrid/plain builders have no brick march — a configured map
    lands on the bricks.partition ledger, not a silent even render."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu, distributed_plain_step)

    obs.clear_ledger()
    mesh = make_mesh(N)
    bm = bk.BrickMap(D, N, OWNER)
    vc, cc = _cfgs()
    distributed_hybrid_step_mxu(mesh, _tf(), _mxu_spec(_cam()), vc, cc,
                                bricks=bm)
    distributed_plain_step(mesh, _tf(), HW, HW, rebalance="bricks",
                           bricks=bm)
    rows = [e for e in obs.ledger()
            if e["component"] == "bricks.partition"]
    assert len(rows) >= 2


# -------------------------------------------------------------- session


class _SkewedSim:
    """Static skewed field (content low-z only) for session replans."""

    kind = "skewed"

    def __init__(self):
        data = np.zeros((D, HW, HW), np.float32)
        data[1:8] = 0.6
        self._f = jnp.asarray(data)

    def advance(self, n):
        pass

    @property
    def field(self):
        return self._f


def test_session_brick_replan_rebuilds_and_balances():
    """rebalance="bricks" e2e: the session fetches the live profile,
    steals bricks off the loaded ranks (move-capped), recompiles, and
    keeps rendering — the adopted map reduces the modeled straggler."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "composite.rebalance=bricks", "composite.rebalance_period=2",
        "composite.rebalance_bricks=16", "render.width=32",
        "render.height=32", "slicer.engine=mxu",
        "slicer.matmul_dtype=f32", "obs.enabled=true")
    sess = InSituSession(cfg, sim=_SkewedSim())
    out = None
    for _ in range(5):
        out = sess.render_frame()
    jax.block_until_ready(out)
    assert sess.obs.counters.get("rebalance_replans", 0) >= 1
    assert sess.obs.counters.get("bricks_steps_built", 0) >= 1
    assert sess._bricks is not None and not sess._bricks.is_even_convex()
    ev = [e for e in sess.obs.events if e.get("name") == "rebalance_plan"]
    assert ev and ev[0]["attrs"]["kind"] == "bricks"
    assert ev[0]["attrs"]["straggler_planned"] \
        < ev[0]["attrs"]["straggler_even"]


def test_session_rejects_non_dividing_brick_count():
    """Impossible brick geometry fails at session build, naming the
    knob — not minutes into a run at the first replan."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "composite.rebalance=bricks", "composite.rebalance_bricks=10",
        "sim.grid=[32,16,16]", "render.width=32", "render.height=32")
    with pytest.raises(ValueError, match="rebalance_bricks"):
        InSituSession(cfg)


def test_session_brick_replan_inert_off_vdi_mode():
    """Modes whose builders ledger the brick map inert (plain/hybrid)
    must not replan at all — an adopted map would recompile steps that
    render even slabs regardless."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    obs.clear_ledger()
    cfg = FrameworkConfig().with_overrides(
        "composite.rebalance=bricks", "composite.rebalance_period=1",
        "runtime.generate_vdis=false", "slicer.engine=gather",
        "render.width=32", "render.height=32", "render.max_steps=32",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "obs.enabled=true")
    sess = InSituSession(cfg)
    assert sess.mode == "plain"
    for _ in range(2):
        out = sess.render_frame()
    jax.block_until_ready(out)
    assert sess.obs.counters.get("rebalance_replans", 0) == 0
    assert sess._bricks is None
    assert any(e["component"] == "bricks.partition"
               for e in obs.ledger())


def test_session_brick_replan_inert_on_single_rank():
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    obs.clear_ledger()
    cfg = FrameworkConfig().with_overrides(
        "composite.rebalance=bricks", "mesh.num_devices=1",
        "render.width=32", "render.height=32", "slicer.engine=gather",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1")
    sess = InSituSession(cfg)
    jax.block_until_ready(sess.render_frame())
    assert any(e["component"] == "occupancy.rebalance"
               for e in obs.ledger())
    assert sess._bricks is None
