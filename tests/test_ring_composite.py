"""Ring-pipelined sort-last compositing (CompositeConfig.exchange="ring")
vs the monolithic all_to_all path: exact-parity checks on the 8-device
virtual mesh across the plain, VDI, temporal and hybrid steps, plus unit
tests of the pairwise ordered merge (ops.composite.merge_vdis_pairwise).
docs/PERF.md "Exchange modes" documents the memory model the capped test
exercises."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops.composite import (merge_vdis_pairwise,
                                              modeled_exchange_traffic)
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)

W = H = 16
STEPS = 48
N = 8


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _stream(rng, k, h, w, live, lo=1.0, hi=5.0):
    """Random per-pixel depth-sorted segment stream with ``live`` live
    slots (empties masked: zero color, +inf depth)."""
    s = np.sort(rng.uniform(lo, hi, (k, h, w)), axis=0).astype(np.float32)
    e = (s + rng.uniform(0.01, 0.2, (k, h, w))).astype(np.float32)
    c = rng.uniform(0.0, 1.0, (k, 4, h, w)).astype(np.float32)
    mask = np.arange(k)[:, None, None] < live
    s = np.where(mask, s, np.inf)
    e = np.where(mask, e, np.inf)
    c = np.where(mask[:, None], c, 0.0)
    return jnp.asarray(c), jnp.asarray(np.stack([s, e], axis=1))


def _assert_vdi_equal(a, b, atol=0.0):
    """Color/depth equality that treats +inf empty slots as equal."""
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


# ------------------------------------------------ merge_vdis_pairwise units

def test_merge_pairwise_disjoint():
    """Depth-disjoint lists (the sort-last invariant): B entirely behind A
    → merged = concatenation, payloads moved bit-exactly."""
    rng = np.random.default_rng(1)
    ca, da = _stream(rng, 3, 2, 2, live=3, lo=1.0, hi=2.0)
    cb, db = _stream(rng, 3, 2, 2, live=3, lo=3.0, hi=4.0)
    mc, md = merge_vdis_pairwise(ca, da, cb, db)
    np.testing.assert_array_equal(np.asarray(mc),
                                  np.concatenate([ca, cb], axis=0))
    np.testing.assert_array_equal(np.asarray(md),
                                  np.concatenate([da, db], axis=0))


def test_merge_pairwise_overlapping():
    """Interleaved depth ranges merge into the globally sorted stream
    (matching a reference sort of the concatenation)."""
    rng = np.random.default_rng(2)
    ca, da = _stream(rng, 5, 3, 4, live=5)
    cb, db = _stream(rng, 4, 3, 4, live=4)
    mc, md = merge_vdis_pairwise(ca, da, cb, db)
    alls = np.concatenate([np.asarray(da)[:, 0], np.asarray(db)[:, 0]], 0)
    order = np.argsort(alls, axis=0, kind="stable")
    allc = np.concatenate([np.asarray(ca), np.asarray(cb)], axis=0)
    ref_c = np.take_along_axis(allc, order[:, None], axis=0)
    np.testing.assert_array_equal(np.asarray(mc), ref_c)
    np.testing.assert_array_equal(np.asarray(md)[:, 0],
                                  np.sort(alls, axis=0))


def test_merge_pairwise_empty_slots():
    """Empty (+inf) slots from both lists collect at the back with zero
    color; live counts add."""
    rng = np.random.default_rng(3)
    ca, da = _stream(rng, 4, 2, 3, live=2)
    cb, db = _stream(rng, 4, 2, 3, live=1)
    mc, md = merge_vdis_pairwise(ca, da, cb, db)
    mc, md = np.asarray(mc), np.asarray(md)
    assert np.isfinite(md[:3, 0]).all()          # 2 + 1 live slots first
    assert np.isinf(md[3:]).all()                # empties at the back
    assert (mc[3:] == 0.0).all()                 # with masked colors
    # one fully-empty pair stays fully empty
    ce, de = _stream(rng, 3, 2, 2, live=0)
    mc2, md2 = merge_vdis_pairwise(ce, de, ce, de)
    assert np.isinf(np.asarray(md2)).all()
    assert (np.asarray(mc2) == 0.0).all()


def test_merge_pairwise_truncation():
    """k_cap keeps the NEAREST segments and drops the farthest — the
    bounded-memory ring mode's contract."""
    rng = np.random.default_rng(4)
    ca, da = _stream(rng, 4, 2, 2, live=4)
    cb, db = _stream(rng, 4, 2, 2, live=4)
    full_c, full_d = merge_vdis_pairwise(ca, da, cb, db)
    cap_c, cap_d = merge_vdis_pairwise(ca, da, cb, db, k_cap=5)
    assert cap_c.shape[0] == 5 and cap_d.shape[0] == 5
    np.testing.assert_array_equal(np.asarray(cap_c), np.asarray(full_c)[:5])
    np.testing.assert_array_equal(np.asarray(cap_d), np.asarray(full_d)[:5])
    # a cap at or above Ka+Kb is a no-op
    same_c, same_d = merge_vdis_pairwise(ca, da, cb, db, k_cap=8)
    np.testing.assert_array_equal(np.asarray(same_c), np.asarray(full_c))
    np.testing.assert_array_equal(np.asarray(same_d), np.asarray(full_d))


def test_merge_pairwise_tie_prefers_accumulator():
    """Exactly-equal start depths order the accumulator (A) first."""
    da = jnp.asarray([[[[2.0]], [[2.5]]]])        # [1, 2, 1, 1]
    db = jnp.asarray([[[[2.0]], [[2.6]]]])
    ca = jnp.full((1, 4, 1, 1), 0.25, jnp.float32)
    cb = jnp.full((1, 4, 1, 1), 0.75, jnp.float32)
    mc, md = merge_vdis_pairwise(ca, da, cb, db)
    assert float(mc[0, 0, 0, 0]) == 0.25 and float(mc[1, 0, 0, 0]) == 0.75
    assert float(md[0, 1, 0, 0]) == 2.5
    assert float(md[1, 1, 0, 0]) == float(np.float32(2.6))


# -------------------------------------------- ring vs all_to_all step parity

def _vdi_steps_both(vcfg, ccfg_kw, vol, cam):
    mesh = make_mesh(N)
    data = shard_volume(vol.data, mesh)
    outs = {}
    for ex in ("all_to_all", "ring"):
        ccfg = CompositeConfig(exchange=ex, **ccfg_kw)
        step = distributed_vdi_step(mesh, _tf(), W, H, vcfg, ccfg,
                                    max_steps=STEPS)
        vdi = step(data, vol.origin, vol.spacing, cam)
        outs[ex] = (vdi.color, vdi.depth)
    return outs


def test_ring_vdi_step_matches_all_to_all():
    """8-rank gather-engine VDI chain: the ring composite must reproduce
    the all_to_all composite exactly (acceptance: bitwise or atol<=1e-6)."""
    vol = procedural_volume(16, kind="blobs")
    outs = _vdi_steps_both(
        VDIConfig(max_supersegments=6, adaptive_iters=2),
        dict(max_output_supersegments=8, adaptive_iters=2),
        vol, _cam())
    _assert_vdi_equal(outs["ring"], outs["all_to_all"], atol=1e-6)


def test_ring_vdi_step_nonadaptive_matches():
    """Fixed-threshold re-segmentation (no adaptive search) parity."""
    vol = procedural_volume(16, kind="shell")
    outs = _vdi_steps_both(
        VDIConfig(max_supersegments=5, adaptive=False, threshold=0.1),
        dict(max_output_supersegments=6, adaptive=False),
        vol, _cam())
    _assert_vdi_equal(outs["ring"], outs["all_to_all"], atol=1e-6)


def test_ring_capped_renders_close():
    """ring_slots=2K (the bounded-memory mode) is approximate on overfull
    pixels but must stay a faithful image of the lossless composite."""
    from scenery_insitu_tpu.core.vdi import VDI, render_vdi_same_view
    from scenery_insitu_tpu.utils.image import psnr

    vol = procedural_volume(16, kind="blobs")
    mesh = make_mesh(N)
    data = shard_volume(vol.data, mesh)
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    imgs = {}
    for slots in (0, 12):
        ccfg = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                               exchange="ring", ring_slots=slots)
        step = distributed_vdi_step(mesh, _tf(), W, H, vcfg, ccfg,
                                    max_steps=STEPS)
        vdi = step(data, vol.origin, vol.spacing, _cam())
        imgs[slots] = np.asarray(render_vdi_same_view(
            VDI(vdi.color, vdi.depth)))
    assert np.isfinite(imgs[12]).all()
    q = psnr(imgs[0], imgs[12])
    assert q > 30.0, f"capped-ring PSNR {q:.1f} dB"


def test_ring_slots_below_k_rejected():
    vol = procedural_volume(16, kind="blobs")
    mesh = make_mesh(N)
    step = distributed_vdi_step(
        mesh, _tf(), W, H, VDIConfig(max_supersegments=6, adaptive_iters=2),
        CompositeConfig(max_output_supersegments=8, exchange="ring",
                        ring_slots=3), max_steps=STEPS)
    with pytest.raises(ValueError, match="ring_slots"):
        step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, _cam())


def test_exchange_config_validation():
    with pytest.raises(ValueError, match="exchange"):
        CompositeConfig(exchange="butterfly")
    with pytest.raises(ValueError, match="ring_slots"):
        CompositeConfig(ring_slots=-1)


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z (sharded)
                                 (3.8, 0.3, 0.6)])   # march axis x (in-plane)
def test_ring_mxu_step_matches_all_to_all(eye):
    """MXU slice-march VDI chain in both march regimes: ring parity."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam(eye)
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    data = shard_volume(vol.data, mesh)
    outs = {}
    for ex in ("all_to_all", "ring"):
        ccfg = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                               exchange=ex)
        step = distributed_vdi_step_mxu(mesh, _tf(), spec, vcfg, ccfg)
        vdi, _ = step(data, vol.origin, vol.spacing, cam)
        outs[ex] = (vdi.color, vdi.depth)
    _assert_vdi_equal(outs["ring"], outs["all_to_all"], atol=1e-6)


def test_ring_mxu_temporal_threshold_carry_matches():
    """Temporal mode under ring exchange: the carried per-rank threshold
    state must evolve identically to the all_to_all run (generation is
    upstream of the exchange) and every frame's composite must match —
    the threshold-carry-across-ring-steps check."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    cfg_t = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    data = shard_volume(vol.data, mesh)
    runs = {}
    for ex in ("all_to_all", "ring"):
        comp = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                               exchange=ex)
        thr = distributed_initial_threshold_mxu(mesh, _tf(), spec, cfg_t)(
            data, vol.origin, vol.spacing, cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, cfg_t,
                                                 comp)
        frames = []
        for _ in range(3):
            (vdi, _), thr = step(data, vol.origin, vol.spacing, cam, thr)
            frames.append((np.asarray(vdi.color), np.asarray(vdi.depth)))
        runs[ex] = (frames, np.asarray(thr.thr))
    np.testing.assert_allclose(runs["ring"][1], runs["all_to_all"][1],
                               atol=1e-6, rtol=0)
    for fr_r, fr_a in zip(runs["ring"][0], runs["all_to_all"][0]):
        _assert_vdi_equal(fr_r, fr_a, atol=1e-6)


@pytest.mark.parametrize("background", [(0.0, 0.0, 0.0, 0.0),
                                        (1.0, 0.2, 0.1, 1.0)])
def test_ring_plain_step_matches_all_to_all(background):
    """Plain gather-path exchange: ring is restacked to source-rank order
    before the nearest-first composite → bitwise-identical frames."""
    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="shell")
    cfg = RenderConfig(max_steps=STEPS, early_exit_alpha=1.1,
                       background=background)
    data = shard_volume(vol.data, mesh)
    imgs = {}
    for ex in ("all_to_all", "ring"):
        step = distributed_plain_step(mesh, _tf(), W, H, cfg, exchange=ex)
        imgs[ex] = np.asarray(step(data, vol.origin, vol.spacing, _cam()))
    np.testing.assert_array_equal(imgs["ring"], imgs["all_to_all"])


def test_ring_plain_mxu_step_matches_all_to_all():
    """Plain MXU exchange parity (intermediate-grid image + axcam)."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    data = shard_volume(vol.data, mesh)
    imgs = {}
    for ex in ("all_to_all", "ring"):
        step = distributed_plain_step_mxu(mesh, _tf(), spec, exchange=ex)
        img, _ = step(data, vol.origin, vol.spacing, cam)
        imgs[ex] = np.asarray(img)
    np.testing.assert_array_equal(imgs["ring"], imgs["all_to_all"])


def test_ring_hybrid_step_matches_all_to_all():
    """Hybrid volume+particle frame: the VDI half composites under the
    configured exchange; the splat half is exchange-independent — whole
    frames must match."""
    import jax

    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu)
    from scenery_insitu_tpu.parallel.particles import shard_particles

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    key = jax.random.PRNGKey(7)
    pos = jax.random.uniform(key, (64, 3), minval=-0.8, maxval=0.8)
    vel = jax.random.normal(jax.random.PRNGKey(8), (64, 3)) * 0.1
    data = shard_volume(vol.data, mesh)
    p = shard_particles(pos, mesh)
    v = shard_particles(vel, mesh)
    imgs = {}
    for ex in ("all_to_all", "ring"):
        ccfg = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                               exchange=ex)
        step = distributed_hybrid_step_mxu(mesh, _tf(), spec, vcfg, ccfg,
                                           radius=0.05, stamp=3)
        img, _ = step(data, vol.origin, vol.spacing, p, v, cam)
        imgs[ex] = np.asarray(img)
    np.testing.assert_allclose(imgs["ring"], imgs["all_to_all"],
                               atol=1e-6, rtol=0)


def test_ring_build_emits_obs_counters():
    """The ring build mints per-hop counters and a modeled-traffic event
    (docs/OBSERVABILITY.md) at trace time."""
    from scenery_insitu_tpu import obs

    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        mesh = make_mesh(4)
        vol = procedural_volume(16, kind="blobs")
        step = distributed_vdi_step(
            mesh, _tf(), W, H,
            VDIConfig(max_supersegments=6, adaptive_iters=2),
            CompositeConfig(max_output_supersegments=8, exchange="ring"),
            max_steps=STEPS)
        step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, _cam())
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("ring_exchange_builds", 0) >= 1
    assert rec.counters.get("ring_steps_built", 0) >= 3   # n-1 hops
    builds = [e for e in rec.events
              if e.get("name") == "ring_exchange_build"]
    assert builds and "traffic" in builds[0]["attrs"]
    t = builds[0]["attrs"]["traffic"]
    assert t["peak_stream_slots_per_pixel"] == 4 * 6      # lossless = N*K


def test_modeled_exchange_traffic_memory_model():
    """The N·K → ring_slots+K working-set reduction the docs claim.
    stream_bytes_per_rank covers the merge working set PLUS the
    resegmented k_out-slot output write (k_out used to be echoed but
    never accounted)."""
    a2a = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16)
    ring = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16,
                                    mode="ring", ring_slots=16)
    assert a2a["peak_stream_slots_per_pixel"] == 8 * 16
    assert ring["peak_stream_slots_per_pixel"] == 2 * 16
    assert ring["ici_bytes_per_rank"] == a2a["ici_bytes_per_rank"]
    px = 720 * (1280 // 8)
    assert a2a["stream_bytes_per_rank"] == (8 * 16 + 16) * px * 24
    assert ring["stream_bytes_per_rank"] == (2 * 16 + 16) * px * 24
