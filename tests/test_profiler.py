"""The attribution plane (ISSUE 18; docs/OBSERVABILITY.md "Phase
attribution"): named-scope presence in the lowered HLO of every
distributed step builder across schedules, ProfileCapture accounting
(the per-phase sum IS the step wall-clock by construction), roofline
verdict classification on synthetic attributions, the divergence engine
against a perturbed modeled stack, and the disabled-capture
zero-overhead path."""

import jax
import jax.numpy as jnp
import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                       TopologyConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.obs.profiler import (EXTRA_PHASES, PHASES,
                                             ProfileCapture,
                                             parse_hlo_scopes, phase,
                                             scope_names, scope_of)
from scenery_insitu_tpu.obs.roofline import (COMM_PHASES, peaks_for,
                                             roofline_verdicts)
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  distributed_vdi_step_mxu,
                                                  shard_volume)
from scenery_insitu_tpu.parallel.topology import make_topology_mesh

W = H = 16
STEPS = 48
N = 8


def _cam():
    return Camera.create((0.0, 0.2, 4.0), fov_y_deg=50.0, near=0.5,
                         far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _vol():
    return procedural_volume(16, kind="blobs")


def _mxu_spec(cam, vol):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=2.0),
                            multiple_of=N)


def _vcfg():
    return VDIConfig(max_supersegments=6, adaptive_iters=2)


def _compiled_scopes(step, vol, mesh, cam):
    # named scopes survive into compiled-HLO op_name metadata (the join
    # key ProfileCapture uses); the StableHLO dump strips its locs
    fn = step if hasattr(step, "lower") else jax.jit(step)
    data = shard_volume(vol.data, mesh)
    text = fn.lower(data, vol.origin, vol.spacing,
                    cam).compile().as_text()
    return scope_names(text) & set(PHASES)


# --------------------------------------------- scope-name mechanics

def test_scope_of_innermost_wins():
    assert scope_of("jit(step)/sitpu_wave/while/sitpu_march/dot") == \
        "march"
    assert scope_of("jit(step)/transpose") is None
    assert scope_of("sitpu_exchange/ppermute") == "exchange"


def test_phase_scope_lands_in_compiled_hlo():
    @jax.jit
    def f(x):
        with phase("march"):
            y = x @ x
        with phase("merge"):
            return y + 1.0

    x = jnp.ones((8, 8), jnp.float32)
    text = f.lower(x).compile().as_text()
    assert {"march", "merge"} <= scope_names(text)
    module, ops = parse_hlo_scopes(text)
    assert module
    assert set(ops.values()) >= {"march"}, ops


# ------------------------------------- per-builder scope presence

def test_scopes_vdi_mxu_frame_schedule():
    vol, cam = _vol(), _cam()
    mesh = make_mesh(N)
    step = distributed_vdi_step_mxu(
        mesh, _tf(), _mxu_spec(cam, vol), _vcfg(),
        CompositeConfig(max_output_supersegments=8, adaptive_iters=2))
    got = _compiled_scopes(step, vol, mesh, cam)
    assert {"march", "exchange", "merge", "resegment"} <= got, got


def test_scopes_vdi_mxu_waves_schedule():
    vol, cam = _vol(), _cam()
    mesh = make_mesh(N)
    step = distributed_vdi_step_mxu(
        mesh, _tf(), _mxu_spec(cam, vol), _vcfg(),
        CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                        schedule="waves", wave_tiles=2, exchange="ring"))
    got = _compiled_scopes(step, vol, mesh, cam)
    assert {"wave", "march", "merge"} <= got, got
    # the ring hop scope rides inside the wave pipeline
    assert "exchange" in got or "wire_encode" in got, got


def test_scopes_vdi_gather_ring_exchange():
    vol, cam = _vol(), _cam()
    mesh = make_mesh(N)
    step = distributed_vdi_step(
        mesh, _tf(), W, H, _vcfg(),
        CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                        exchange="ring"),
        max_steps=STEPS)
    got = _compiled_scopes(step, vol, mesh, cam)
    assert {"march", "exchange", "merge", "resegment"} <= got, got


def test_scopes_hier_dcn_hop():
    """The two-level composite tags its inter-host hops dcn_hop so the
    attribution can split ICI from DCN time."""
    vol, cam = _vol(), _cam()
    tcfg = TopologyConfig(num_hosts=2)
    mesh, _ = make_topology_mesh(tcfg)
    step = distributed_vdi_step(
        mesh, _tf(), W, H, _vcfg(),
        CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                        exchange="ring"),
        max_steps=STEPS, topology=tcfg)
    got = _compiled_scopes(step, vol, mesh, cam)
    assert "dcn_hop" in got, got
    assert {"march", "merge", "resegment"} <= got, got


def test_scopes_plain_step():
    from scenery_insitu_tpu.config import RenderConfig

    vol, cam = _vol(), _cam()
    mesh = make_mesh(N)
    step = distributed_plain_step(
        mesh, _tf(), W, H, RenderConfig(max_steps=STEPS))
    got = _compiled_scopes(step, vol, mesh, cam)
    assert "march" in got, got
    assert "merge" in got or "exchange" in got, got


# ------------------------------------------- capture accounting

def test_capture_sum_matches_wall():
    """The acceptance gate: per-phase ms (scoped + unattributed + host)
    sums to the measured wall-clock — exact by construction (host-gap +
    thread-pool normalization), asserted within rounding."""
    @jax.jit
    def f(x):
        with phase("march"):
            y = x @ x
        with phase("merge"):
            return jnp.tanh(y).sum()

    x = jnp.ones((256, 256), jnp.float32)
    attr = ProfileCapture(frames=3, warmup=1, devices=1).capture(f, x)
    assert attr is not None, "trace backend absent on CPU?"
    assert attr["type"] == "phase_attribution"
    total = sum(p["ms"] for p in attr["phases"].values())
    wall = attr["wall_ms_per_frame"]
    assert abs(total - wall) <= max(0.15 * wall, 0.05), (total, wall)
    for name in attr["phases"]:
        assert name in PHASES or name in EXTRA_PHASES, name
    assert attr["coverage"] is not None and attr["coverage"] <= 1.0
    assert attr["phases"]["host"]["ms"] >= 0.0


def test_capture_joins_scoped_ops():
    @jax.jit
    def f(x):
        with phase("march"):
            return (x @ x).sum()

    x = jnp.ones((512, 512), jnp.float32)
    attr = ProfileCapture(frames=2, devices=1).capture(f, x)
    assert attr is not None
    assert attr["scoped_ops"] > 0
    assert attr["events_joined"] > 0
    assert "march" in attr["phases"], attr["phases"]
    assert attr["phases"]["march"]["events"] > 0


def test_capture_disabled_is_inert():
    calls = []

    class Boom:
        def lower(self, *a):            # must never be touched
            calls.append("lower")
            raise AssertionError

    out = ProfileCapture(enabled=False).capture(Boom())
    assert out is None and not calls


def test_capture_failure_degrades_not_raises():
    class NotJitted:
        pass

    obs.clear_ledger()
    out = ProfileCapture().capture(NotJitted())
    assert out is None
    assert any(e["component"] == "obs.profiler" for e in obs.ledger())


# ------------------------------------------------ roofline verdicts

def _attr(phases, wall=None, devices=1):
    total = sum(phases.values())
    wall = wall if wall is not None else total
    return {"type": "phase_attribution", "backend": "cpu",
            "device_kind": "cpu", "frames": 1, "devices": devices,
            "wall_ms_per_frame": wall, "device_ms_per_frame": total,
            "coverage": min(1.0, total / wall),
            "phases": {k: {"ms": v, "events": 1}
                       for k, v in phases.items()}}


def test_roofline_hbm_bound_classification():
    """march moving 82 GB/s against a 100 GB/s peak with negligible
    flops must classify hbm."""
    peaks = {"tflops": 100.0, "hbm_gbps": 100.0, "ici_gbps": 45.0,
             "dcn_gbps": 3.125, "device_kind": "synthetic",
             "platform": "tpu", "peaks_source": "test"}
    cost = {"source": "xla_cost_analysis",
            "bytes_accessed": 8.2e9, "flops": 1e9}
    v = roofline_verdicts(_attr({"march": 100.0}), cost, peaks)
    verdict = v["verdicts"]["march"]
    assert verdict["bound"] == "hbm", verdict
    assert verdict["hbm_frac_peak"] > verdict["mxu_frac_peak"]


def test_roofline_mxu_bound_classification():
    peaks = {"tflops": 100.0, "hbm_gbps": 1000.0, "ici_gbps": 45.0,
             "dcn_gbps": 3.125, "device_kind": "synthetic",
             "platform": "tpu", "peaks_source": "test"}
    cost = {"source": "xla_cost_analysis",
            "bytes_accessed": 1e9, "flops": 9e13}
    v = roofline_verdicts(_attr({"march": 1000.0}), cost, peaks)
    assert v["verdicts"]["march"]["bound"] == "mxu"


def test_roofline_comm_and_host_bounds():
    """exchange/dcn_hop classify on their link; a phase under the host
    floor classifies host regardless of its compute fractions."""
    peaks = {"tflops": 100.0, "hbm_gbps": 100.0, "ici_gbps": 45.0,
             "dcn_gbps": 3.125, "device_kind": "synthetic",
             "platform": "tpu", "peaks_source": "test"}
    cost = {"source": "xla_cost_analysis",
            "bytes_accessed": 1e6, "flops": 1e6}
    attr = _attr({"march": 1.0, "exchange": 5.0, "dcn_hop": 5.0,
                  "host": 10.0})
    v = roofline_verdicts(
        attr, cost, peaks,
        modeled={"ici_bytes_per_frame": 200e6,
                 "dcn_bytes_per_frame": 10e6})
    assert v["verdicts"]["exchange"]["bound"] in ("ici", "ici-dcn")
    assert v["verdicts"]["dcn_hop"]["bound"] in ("dcn", "ici-dcn")
    assert v["verdicts"]["host"]["bound"] == "host"
    # tiny compute fractions → below the floor → host-bound
    assert v["verdicts"]["march"]["bound"] == "host"
    assert set(COMM_PHASES) == {"exchange", "dcn_hop"}


def test_roofline_cpu_peaks_are_relative_only():
    peaks = peaks_for("cpu", "cpu")
    assert peaks["device_kind"] is None or peaks["platform"] == "cpu"
    assert "relative" in peaks["peaks_source"]
    v = roofline_verdicts(_attr({"march": 1.0}),
                          {"source": "xla_cost_analysis",
                           "bytes_accessed": 1e6, "flops": 1e6}, peaks)
    assert "march" in v["verdicts"]
    assert v["assumptions"]["peaks_source"] == peaks["peaks_source"]


# ------------------------------------------------ divergence engine

def _modeled_doc():
    return {
        "type": "modeled_projection",
        "assumptions": {"ranks": 8, "grid": 512, "hbm_gbps": 819,
                        "ici_gbps_effective": 45.0},
        "stack": [
            {"lever": "baseline", "config": {},
             "ms": {"sim": 3.0, "march": 1.0, "composite_stream": 0.5,
                    "exchange_exposed": 3.0}},
            {"lever": "ring", "config": {"exchange": "ring"},
             "ms": {"sim": 3.0, "march": 1.0, "composite_stream": 0.5,
                    "exchange_exposed": 1.0}},
        ],
    }


def test_divergence_ranks_the_perturbed_lever():
    """Measured march share triple the model's → march must top the
    next-perf-PR ranking with a positive share delta."""
    from benchmarks.divergence import divergence_report

    attr = _attr({"sim_step": 3.0, "march": 9.0, "merge": 0.3,
                  "resegment": 0.2, "exchange": 3.0})
    rep = divergence_report(attr, _modeled_doc())
    assert rep["type"] == "divergence_report"
    assert rep["modeled_row"] == "baseline"
    top = rep["next_perf_pr"][0]
    assert top["lever"] == "march", rep["next_perf_pr"]
    assert top["share_delta"] > 0
    assert "attack" in top["verdict"]


def test_divergence_selects_config_matched_row():
    from benchmarks.divergence import divergence_report

    attr = _attr({"sim_step": 3.0, "march": 1.0, "merge": 0.5,
                  "exchange": 1.0})
    rep = divergence_report(attr, _modeled_doc(),
                            measured_config={"exchange": "ring"})
    assert rep["modeled_row"] == "ring"
    # matching scale and shares → exchange ratio ≈ 1
    assert rep["levers"]["exchange_exposed"]["ratio"] == 1.0


def test_divergence_unmodeled_residual_accounted():
    """Only `unattributed` is unmodeled now: PR 19's host-delivery term
    moved the measured `host` phase under the `host_delivery` lever, so
    host time diverges against the model instead of hiding in the
    residual."""
    from benchmarks.divergence import divergence_report

    attr = _attr({"sim_step": 1.0, "march": 1.0, "unattributed": 2.0,
                  "host": 6.0})
    rep = divergence_report(attr, _modeled_doc())
    assert rep["unmodeled_ms"] == 2.0
    assert rep["unmodeled_share"] == 0.2
    assert rep["levers"]["host_delivery"]["measured_ms"] == 6.0
    total = sum(e["measured_ms"] for e in rep["levers"].values()) \
        + rep["unmodeled_ms"]
    assert abs(total - rep["measured_total_ms"]) < 1e-6


def test_divergence_self_check_on_committed_artifacts():
    """CI's gate: every committed attribution artifact must produce a
    schema-complete report against the committed modeled projection."""
    from benchmarks.divergence import self_check

    assert self_check() == 0


def test_divergence_roundtrip_from_bench_artifact(tmp_path):
    """report_from_files accepts a bench artifact embedding the capture
    (the SITPU_BENCH_PROFILE=1 shape)."""
    import json

    from benchmarks.divergence import report_from_files

    doc = {"metric": "x", "config": {"exchange": "ring"},
           "phase_attribution": _attr({"sim_step": 2.0, "march": 1.0,
                                       "exchange": 1.0})}
    p = tmp_path / "bench.json"
    p.write_text(json.dumps(doc))
    m = tmp_path / "modeled_projection_r0.json"
    m.write_text(json.dumps(_modeled_doc()))
    rep = report_from_files(str(p), str(m))
    assert rep["modeled_row"] == "ring"
    assert rep["levers"]["sim"]["measured_ms"] == 2.0


# ------------------------------------------------ chrome-trace export

def test_attribution_rides_fleet_trace(tmp_path):
    from scenery_insitu_tpu.obs.profiler import (append_to_chrome_trace,
                                                 publish_attribution)

    rec = obs.Recorder(enabled=True)
    saved = obs.get_recorder()
    obs.set_recorder(rec)
    try:
        attr = _attr({"march": 2.0, "exchange": 1.0})
        publish_attribution(attr, frame=0)
        path = str(tmp_path / "trace.json")
        rec.export_chrome_trace(path)
        append_to_chrome_trace(attr, path)
    finally:
        obs.set_recorder(saved)
    import json

    doc = json.load(open(path))
    names = [e.get("name") for e in doc["traceEvents"]]
    assert "phase_attribution" in names
    assert "march" in names and "exchange" in names
    procs = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M"]
    assert "device phases (attributed)" in procs
