"""Observability layer (ISSUE 3): structured spans, the fallback ledger,
Chrome-trace/JSONL export, the disabled-recorder no-op path, and the
Timers windowed-dump reset."""

import json
import warnings

import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.obs.recorder import Recorder
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.runtime.session import InSituSession
from scenery_insitu_tpu.runtime.timers import Timers


@pytest.fixture(autouse=True)
def _isolate_global_obs():
    """Sessions with obs enabled install themselves as the process
    recorder and degradations land in a process-global ledger — restore
    both around every test."""
    prev = obs.get_recorder()
    obs.clear_ledger()
    yield
    obs.set_recorder(prev)
    obs.clear_ledger()


def _session_cfg(**kw):
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8", "composite.adaptive_iters=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2",
        "runtime.stats_window=2")
    return cfg.with_overrides(*[f"{k}={v}" for k, v in kw.items()])


# ------------------------------------------------------------ recorder core

def test_span_nesting_and_attribution():
    rec = Recorder(enabled=True, rank=3)
    with rec.span("frame", frame=7):
        with rec.span("sim", frame=7, kind="gray_scott"):
            pass
        with rec.span("dispatch", frame=7):
            pass
    spans = [e for e in rec.events if e["type"] == "span"]
    assert [s["name"] for s in spans] == ["sim", "dispatch", "frame"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["frame"]["depth"] == 0 and "parent" not in by_name["frame"]
    assert by_name["sim"]["depth"] == 1
    assert by_name["sim"]["parent"] == "frame"
    assert by_name["sim"]["attrs"] == {"kind": "gray_scott"}
    for s in spans:
        assert s["frame"] == 7
        assert s["rank"] == 3
        assert s["dur"] >= 0.0
    # spans feed the wrapped Timers' PhaseStats too (one sink among several)
    assert rec.timers.stats["sim"].n == 1


def test_counters_and_summary():
    rec = Recorder(enabled=True)
    rec.count("compile_step")
    rec.count("compile_step")
    rec.count("frames_scan_dispatch", 8)
    s = rec.summary()
    assert s["counters"]["compile_step"] == 2
    assert s["counters"]["frames_scan_dispatch"] == 8
    assert s["enabled"] is True
    assert isinstance(s["degradations"], list)


# ------------------------------------------------------------------- ledger

def test_forced_codec_degrade_in_ledger(monkeypatch):
    from scenery_insitu_tpu.io import vdi_io

    monkeypatch.setattr(vdi_io, "have_zstd", lambda: False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert vdi_io.resolve_codec("zstd") == "zlib"
        assert vdi_io.resolve_codec("zstd") == "zlib"
    entries = [e for e in obs.ledger() if e["component"] == "io.vdi_codec"]
    assert len(entries) == 1, entries
    assert entries[0]["from"] == "zstd" and entries[0]["to"] == "zlib"
    assert entries[0]["count"] == 2          # deduped, counted
    # the warning the inline site used to emit still fires (once)
    assert sum("zstandard" in str(x.message) for x in w) == 1


def test_forced_eager_scan_fallback_in_ledger():
    class OpaqueSim:
        """Custom adapter: no traceable (state, advance) pair, so
        scan_frames must degrade to the eager loop."""

        def __init__(self, inner):
            self._inner = inner
            self.kind = inner.kind

        def advance(self, n):
            self._inner.advance(n)

        @property
        def field(self):
            return self._inner.field

    from scenery_insitu_tpu.runtime.session import VolumeSimAdapter

    cfg = _session_cfg(**{"runtime.scan_frames": 2})
    sess = InSituSession(cfg, mesh=make_mesh(2),
                         sim=OpaqueSim(VolumeSimAdapter(cfg)))
    sess.run(2)
    entries = [e for e in obs.ledger()
               if e["component"] == "session.scan_frames"]
    assert len(entries) == 1, obs.ledger()
    assert entries[0]["from"] == "scan" and entries[0]["to"] == "eager"
    assert "custom sim adapter" in entries[0]["reason"]
    # the frames actually ran eagerly
    assert sess.obs.counters.get("frames_eager_dispatch") == 2


# ---------------------------------------------------------------- exporters

def test_chrome_trace_schema(tmp_path):
    rec = Recorder(enabled=True, rank=1)
    with rec.span("sim", frame=0):
        pass
    rec.count("compile_step")
    rec.event("compile", frame=0, what="vdi_step")
    obs.degrade("test.component", "a", "b", "because", warn=False)
    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    xs = [e for e in evs if e.get("ph") == "X"]
    assert xs, "no complete (X) span events"
    for e in xs:
        for key in ("ph", "ts", "dur", "pid", "name", "tid"):
            assert key in e, (key, e)
        assert e["pid"] == 1
        assert e["args"]["frame"] == 0
    assert any(e.get("ph") == "C" for e in evs)          # counter
    assert any(e.get("cat") == "degrade" for e in evs)   # ledger instants
    assert any(e.get("ph") == "M" for e in evs)          # process name


def test_metrics_jsonl(tmp_path):
    rec = Recorder(enabled=True)
    with rec.span("sim", frame=0):
        pass
    path = rec.export_metrics_jsonl(str(tmp_path / "metrics.jsonl"))
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines[0]["type"] == "span" and lines[0]["name"] == "sim"
    assert lines[-1]["type"] == "summary"
    assert "degradations" in lines[-1]


def test_disabled_recorder_noop(tmp_path):
    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.jsonl"
    rec = Recorder(enabled=False, trace_path=str(trace),
                   metrics_path=str(metrics))
    for i in range(5):
        with rec.span("sim", frame=i):
            pass
    rec.flush()
    assert rec.events == []                  # zero events recorded
    assert not trace.exists() and not metrics.exists()   # no sink writes
    # ...but the PR-1 timer behavior is fully preserved
    assert rec.timers.stats["sim"].n == 5


# ------------------------------------------------------- session integration

def test_session_run_writes_trace_and_metrics(tmp_path):
    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.jsonl"
    cfg = _session_cfg(**{
        "obs.enabled": "true",
        "obs.trace_path": str(trace),
        "obs.metrics_path": str(metrics)})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    sess.run(3)
    with open(trace) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    # every host-visible render phase is covered
    assert {"sim", "dispatch", "fetch", "sinks"} <= names, names
    frames = {e["args"].get("frame") for e in xs if e["name"] == "sim"}
    assert frames == {0, 1, 2}
    assert all(e["pid"] == 0 for e in xs)     # rank attribution
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert lines and lines[-1]["type"] == "summary"
    assert lines[-1]["frames"] == 3
    assert lines[-1]["counters"].get("frames_eager_dispatch") == 3


def test_session_disabled_obs_zero_events():
    sess = InSituSession(_session_cfg(), mesh=make_mesh(2))
    sess.run(2)
    assert sess.obs.events == []
    assert sess.obs.enabled is False
    assert sess.timers.stats["sim"].n == 2   # PR-1 behavior intact


def test_session_device_snapshot():
    sess = InSituSession(_session_cfg(), mesh=make_mesh(2))
    sess.run(1)
    snaps = sess.device_snapshot()
    assert "gather" in snaps
    snap = snaps["gather"]
    assert snap is None or "source" in snap


def test_gather_obs_events_single_process():
    from scenery_insitu_tpu.parallel.multihost import gather_obs_events

    rec = Recorder(enabled=True, rank=0)
    with rec.span("sim", frame=0):
        pass
    merged = gather_obs_events(rec)
    assert merged is not None
    assert merged[0]["name"] == "sim"
    assert merged[-1]["type"] == "summary"


# ------------------------------------------------------------------- timers

def test_window_stats_reset_between_dumps():
    """Regression: each windowed dump must average ONLY its own window —
    never accumulate over the whole run."""
    lines = []
    t = Timers(window=2, log=lines.append)
    for _ in range(2):
        t.record("sim", 1.0)
        t.frame_done()
    assert any("window of 2" in l for l in lines)
    # reset happened: the window accumulator is empty after the dump
    assert all(st.n == 0 for st in t.window_stats.values())
    for _ in range(2):
        t.record("sim", 3.0)
        t.frame_done()
    # second window dump shows the second window's average (3000 ms),
    # not the accumulated 2000 ms
    second = [l for l in lines if "sim" in l][-1]
    assert "3000.000 ms" in second, second
    assert t.stats["sim"].n == 4             # totals still cover the run


def test_dump_totals_flushes_partial_window():
    lines = []
    t = Timers(window=100, log=lines.append)
    for _ in range(3):                        # never reaches a boundary
        t.record("sim", 0.5)
        t.frame_done()
    assert not any("window" in l for l in lines)
    t.dump_totals()
    assert any("final partial window" in l for l in lines)
    assert any("totals over 3 frames" in l for l in lines)
    # idempotent on the window part
    n = len(lines)
    t.close()
    assert not any("final partial window" in l for l in lines[n:])


def test_degrade_dedup_and_warning_once():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        obs.degrade("x.y", "fast", "slow", "why")
        obs.degrade("x.y", "fast", "slow", "why")
        obs.degrade("x.y", "fast", "slow", "other reason")
    entries = [e for e in obs.ledger() if e["component"] == "x.y"]
    assert len(entries) == 2
    assert entries[0]["count"] == 2 and entries[1]["count"] == 1
    assert len(w) == 2                        # one warning per distinct entry


def test_obs_config_roundtrip():
    cfg = FrameworkConfig().with_overrides(
        "obs.enabled=true", "obs.trace_path=/tmp/t.json", "obs.window=7")
    assert cfg.obs.enabled is True
    assert cfg.obs.trace_path == "/tmp/t.json"
    assert cfg.obs.window == 7
    d = cfg.to_dict()
    assert d["obs"]["enabled"] is True
    cfg2 = FrameworkConfig.from_dict(d)
    assert cfg2.obs == cfg.obs


# ---------------------------------------------------------------- SLO engine

def _slo_cfg(**kw):
    from scenery_insitu_tpu.config import SLOConfig
    kw.setdefault("window", 8)
    kw.setdefault("min_samples", 2)
    return SLOConfig(enabled=True, **kw)


def test_slo_disabled_noop():
    from scenery_insitu_tpu.config import SLOConfig
    from scenery_insitu_tpu.obs.slo import SLOEngine

    rec = Recorder(enabled=True)
    slo = SLOEngine(SLOConfig(enabled=False, frame_p99_ms=0.001), rec)
    for i in range(50):
        slo.observe("frame_ms", 1e9, frame=i)
    snap = slo.snapshot()
    assert snap["enabled"] is False
    assert snap["metrics"] == {}
    assert snap["healthy"] is True
    assert rec.counters.get("slo_breaches") is None


def test_slo_breach_fires_on_transition_and_rearms():
    from scenery_insitu_tpu.obs.slo import SLOEngine

    rec = Recorder(enabled=True)
    slo = SLOEngine(_slo_cfg(frame_p99_ms=10.0), rec)
    for i in range(8):                     # comfortably under budget
        slo.observe("frame_ms", 1.0, frame=i)
    assert not slo.breached("frame_ms")
    for i in range(4):                     # p99 over budget: ONE episode
        slo.observe("frame_ms", 100.0, frame=8 + i)
    assert slo.breached("frame_ms")
    assert rec.counters.get("slo_breaches") == 1
    events = [e for e in rec.events if e["name"] == "slo_breach"]
    assert len(events) == 1
    assert events[0]["attrs"]["metric"] == "frame_ms"
    assert events[0]["attrs"]["budget"] == 10.0
    assert [e["component"] for e in obs.ledger()].count("slo.breach") == 1
    # flush the window back under budget -> the gate re-arms ...
    for i in range(8):
        slo.observe("frame_ms", 1.0, frame=12 + i)
    assert not slo.breached("frame_ms")
    # ... and the next excursion is a SECOND counted episode
    for i in range(4):
        slo.observe("frame_ms", 100.0, frame=20 + i)
    assert rec.counters.get("slo_breaches") == 2
    assert slo.snapshot()["metrics"]["frame_ms"]["breaches"] == 2


def test_slo_min_samples_gates_the_check():
    from scenery_insitu_tpu.obs.slo import SLOEngine

    rec = Recorder(enabled=True)
    slo = SLOEngine(_slo_cfg(min_samples=5, frame_p99_ms=1.0), rec)
    for i in range(4):                     # wildly over budget, too few
        slo.observe("frame_ms", 1e6, frame=i)
    assert not slo.breached()
    slo.observe("frame_ms", 1e6, frame=4)  # 5th sample arms the gate
    assert slo.breached("frame_ms")


def test_slo_untracked_metric_is_gate_free():
    from scenery_insitu_tpu.obs.slo import SLOEngine

    slo = SLOEngine(_slo_cfg(), Recorder(enabled=True))
    for i in range(20):
        slo.observe("made_up_metric", 1e9, frame=i)
    m = slo.snapshot()["metrics"]["made_up_metric"]
    assert m["budget"] == 0.0 and m["breaches"] == 0
    assert slo.snapshot()["healthy"] is True


def test_slo_observe_phase_and_quantiles():
    from scenery_insitu_tpu.obs.slo import SLOEngine

    slo = SLOEngine(_slo_cfg(phase_p99_ms=1e9), Recorder(enabled=True))
    for ms in (1.0, 2.0, 3.0, 4.0):
        slo.observe_phase("composite", ms / 1e3)   # seconds, like Timers
    m = slo.snapshot()["metrics"]["phase:composite_ms"]
    assert m["n"] == 4 and m["last"] == 4.0
    assert slo.quantile("phase:composite_ms", 0.50) == 2.0
    assert slo.quantile("phase:composite_ms", 0.99) == 4.0


def test_slo_snapshot_schema():
    from scenery_insitu_tpu.obs.slo import SLOEngine

    slo = SLOEngine(_slo_cfg(frame_p99_ms=5.0), Recorder(enabled=True))
    slo.observe("frame_ms", 2.0, frame=0)
    snap = slo.snapshot()
    assert snap["type"] == "slo_report"
    assert set(snap) == {"type", "enabled", "window", "min_samples",
                         "metrics", "total_breaches", "healthy"}
    assert set(snap["metrics"]["frame_ms"]) == {
        "n", "window_n", "last", "p50", "p99", "budget", "breached",
        "breaches"}
    json.dumps(snap)                       # machine-readable for real


# ------------------------------------------------- fleet telemetry collector

def test_lineage_instants_and_age():
    from scenery_insitu_tpu.obs.collector import lineage, trace_ctx

    rec = Recorder(enabled=True)
    obs.set_recorder(rec)
    lineage("publish", "send", 3)
    ctx = trace_ctx(3, src=1)
    lineage("publish", "recv", None, ctx=ctx)
    send, recv = [e for e in rec.events if e["name"] == "lineage"]
    assert send["attrs"]["stage"] == "publish"
    assert send["attrs"]["role"] == "send" and send["frame"] == 3
    # the recv side decodes the wire trace context: frame comes from the
    # ctx, and the origin stamp yields the measured age
    assert recv["frame"] == 3 and recv["attrs"]["src"] == 1
    assert recv["attrs"]["t_origin"] == ctx["t"]
    assert recv["attrs"]["age_ms"] >= 0.0


def test_publisher_collector_roundtrip():
    zmq = pytest.importorskip("zmq")  # noqa: F841
    from scenery_insitu_tpu.obs.collector import Collector, ObsPublisher

    col = Collector()
    pub = ObsPublisher(col.endpoint, col.hb_endpoint, rank=2,
                       interval_s=0.0)
    try:
        # prove the PUB path first (the channel is legally lossy while
        # the zmq subscription handshake is in flight)
        deadline = __import__("time").monotonic() + 10.0
        while not pub.linked and __import__("time").monotonic() < deadline:
            pub.probe()
            col.poll(10)
        assert pub.linked
        assert col.batches == 0            # probes carry no payload
        rec = Recorder(enabled=True, rank=2)
        with rec.span("frame", frame=0):
            pass
        assert pub.pump(rec, force=True)
        for _ in range(100):
            if col.poll(20):
                break
        assert col.batches == 1
        merged = col.merged_events()
        assert any(e["name"] == "frame" and e["rank"] == 2
                   for e in merged)
        # the pong-driven clock model has a sane bound on loopback
        assert pub.rtt > 0.0
        assert abs(pub.clock_offset) < 5.0
    finally:
        pub.close()
        col.close()


def test_publisher_to_dead_collector_drops_are_ledgered():
    pytest.importorskip("zmq")
    from scenery_insitu_tpu.obs.collector import Collector, ObsPublisher

    col = Collector()
    ep, hb = col.endpoint, col.hb_endpoint
    col.close()                            # collector is GONE
    pub = ObsPublisher(ep, hb, rank=0, interval_s=0.0)
    rec = Recorder(enabled=True)
    try:
        for i in range(5):
            with rec.span("frame", frame=i):
                pass
            pub.pump(rec, force=True)      # never raises, never blocks
        # a PUB socket discards silently, so the verdict comes from the
        # heartbeat liveness: >= 3 unanswered pings = presumed lost
        assert pub.drops > 0
        assert rec.counters.get("obs_batch_drops", 0) > 0
        assert any(e["component"] == "obs.collector"
                   for e in obs.ledger())
    finally:
        pub.close()


# ---------------------------------------------------------- flight recorder

def test_flight_recorder_dumps_partial_artifacts_on_crash(tmp_path):
    """Kill the session mid-run (sim raises at frame 2 of 5): the crash
    path must flush WELL-FORMED partial trace/metrics artifacts before
    the exception propagates — the window that explains the crash is
    exactly the one a normal flush would have lost."""
    from scenery_insitu_tpu.runtime.session import VolumeSimAdapter

    class DyingSim:
        def __init__(self, inner, die_at):
            self._inner = inner
            self._die_at = die_at
            self._calls = 0
            self.kind = inner.kind

        def advance(self, n):
            if self._calls >= self._die_at:
                raise RuntimeError("sim exploded mid-run")
            self._calls += 1
            self._inner.advance(n)

        @property
        def field(self):
            return self._inner.field

    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.jsonl"
    cfg = _session_cfg(**{"obs.enabled": "true",
                          "obs.trace_path": str(trace),
                          "obs.metrics_path": str(metrics)})
    sess = InSituSession(cfg, mesh=make_mesh(2),
                         sim=DyingSim(VolumeSimAdapter(cfg), die_at=2))
    with pytest.raises(RuntimeError, match="sim exploded"):
        sess.run(5)
    # both artifacts exist, parse, and hold the pre-crash frames (the
    # dying frame's sim span still closes, so it may be the last one)
    doc = json.load(open(trace))
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    frames = {e["args"].get("frame") for e in xs if e["name"] == "sim"}
    assert {0, 1} <= frames and max(frames) <= 2
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert lines[-1]["type"] == "summary"
    assert sess.obs.counters.get("flight_dumps") == 1
    assert any(e["name"] == "flight_dump" for e in sess.obs.events)
    assert any(e["component"] == "obs.flight_recorder"
               for e in obs.ledger())


# ------------------------------------------- session x fleet side-channel

def test_session_pumps_configured_collector(tmp_path):
    pytest.importorskip("zmq")
    from scenery_insitu_tpu.obs.collector import Collector

    col = Collector()
    try:
        cfg = _session_cfg(**{
            "obs.enabled": "true",
            "obs.collector": col.endpoint,
            "obs.collector_hb": col.hb_endpoint,
            "obs.collector_interval_s": 0.001})
        sess = InSituSession(cfg, mesh=make_mesh(2))
        # settle the PUB path before the frames (the channel is legally
        # lossy during the zmq subscription handshake)
        deadline = __import__("time").monotonic() + 10.0
        while (not sess._obs_pub.linked
               and __import__("time").monotonic() < deadline):
            sess._obs_pub.probe()
            col.poll(10)
        assert sess._obs_pub.linked
        sess.run(3)
        for _ in range(100):
            col.poll(20)
            if col.batches > 0 and any(
                    e["name"] == "sim" for e in col.merged_events()):
                break
        assert col.batches > 0
        names = {e["name"] for e in col.merged_events()}
        assert "sim" in names              # real session phases arrived
        assert sess.obs.counters.get("obs_batches_published", 0) > 0
    finally:
        col.close()


def test_session_slo_breach_end_to_end():
    # min_samples first: overrides validate one at a time, and the
    # default min_samples (16) would not fit the shrunken window
    cfg = _session_cfg(**{"slo.enabled": "true", "slo.min_samples": "1",
                          "slo.window": "8",
                          "slo.frame_p99_ms": "0.000001"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    sess.run(2)                            # any real frame breaches that
    snap = sess.slo.snapshot()
    assert snap["total_breaches"] >= 1
    assert snap["metrics"]["frame_ms"]["n"] == 2
    assert not snap["healthy"]
    assert sess.obs.counters.get("slo_breaches", 0) >= 1
    assert any(e["component"] == "slo.breach" for e in obs.ledger())
