"""Observability layer (ISSUE 3): structured spans, the fallback ledger,
Chrome-trace/JSONL export, the disabled-recorder no-op path, and the
Timers windowed-dump reset."""

import json
import warnings

import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.obs.recorder import Recorder
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.runtime.session import InSituSession
from scenery_insitu_tpu.runtime.timers import Timers


@pytest.fixture(autouse=True)
def _isolate_global_obs():
    """Sessions with obs enabled install themselves as the process
    recorder and degradations land in a process-global ledger — restore
    both around every test."""
    prev = obs.get_recorder()
    obs.clear_ledger()
    yield
    obs.set_recorder(prev)
    obs.clear_ledger()


def _session_cfg(**kw):
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8", "composite.adaptive_iters=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2",
        "runtime.stats_window=2")
    return cfg.with_overrides(*[f"{k}={v}" for k, v in kw.items()])


# ------------------------------------------------------------ recorder core

def test_span_nesting_and_attribution():
    rec = Recorder(enabled=True, rank=3)
    with rec.span("frame", frame=7):
        with rec.span("sim", frame=7, kind="gray_scott"):
            pass
        with rec.span("dispatch", frame=7):
            pass
    spans = [e for e in rec.events if e["type"] == "span"]
    assert [s["name"] for s in spans] == ["sim", "dispatch", "frame"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["frame"]["depth"] == 0 and "parent" not in by_name["frame"]
    assert by_name["sim"]["depth"] == 1
    assert by_name["sim"]["parent"] == "frame"
    assert by_name["sim"]["attrs"] == {"kind": "gray_scott"}
    for s in spans:
        assert s["frame"] == 7
        assert s["rank"] == 3
        assert s["dur"] >= 0.0
    # spans feed the wrapped Timers' PhaseStats too (one sink among several)
    assert rec.timers.stats["sim"].n == 1


def test_counters_and_summary():
    rec = Recorder(enabled=True)
    rec.count("compile_step")
    rec.count("compile_step")
    rec.count("frames_scan_dispatch", 8)
    s = rec.summary()
    assert s["counters"]["compile_step"] == 2
    assert s["counters"]["frames_scan_dispatch"] == 8
    assert s["enabled"] is True
    assert isinstance(s["degradations"], list)


# ------------------------------------------------------------------- ledger

def test_forced_codec_degrade_in_ledger(monkeypatch):
    from scenery_insitu_tpu.io import vdi_io

    monkeypatch.setattr(vdi_io, "have_zstd", lambda: False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert vdi_io.resolve_codec("zstd") == "zlib"
        assert vdi_io.resolve_codec("zstd") == "zlib"
    entries = [e for e in obs.ledger() if e["component"] == "io.vdi_codec"]
    assert len(entries) == 1, entries
    assert entries[0]["from"] == "zstd" and entries[0]["to"] == "zlib"
    assert entries[0]["count"] == 2          # deduped, counted
    # the warning the inline site used to emit still fires (once)
    assert sum("zstandard" in str(x.message) for x in w) == 1


def test_forced_eager_scan_fallback_in_ledger():
    class OpaqueSim:
        """Custom adapter: no traceable (state, advance) pair, so
        scan_frames must degrade to the eager loop."""

        def __init__(self, inner):
            self._inner = inner
            self.kind = inner.kind

        def advance(self, n):
            self._inner.advance(n)

        @property
        def field(self):
            return self._inner.field

    from scenery_insitu_tpu.runtime.session import VolumeSimAdapter

    cfg = _session_cfg(**{"runtime.scan_frames": 2})
    sess = InSituSession(cfg, mesh=make_mesh(2),
                         sim=OpaqueSim(VolumeSimAdapter(cfg)))
    sess.run(2)
    entries = [e for e in obs.ledger()
               if e["component"] == "session.scan_frames"]
    assert len(entries) == 1, obs.ledger()
    assert entries[0]["from"] == "scan" and entries[0]["to"] == "eager"
    assert "custom sim adapter" in entries[0]["reason"]
    # the frames actually ran eagerly
    assert sess.obs.counters.get("frames_eager_dispatch") == 2


# ---------------------------------------------------------------- exporters

def test_chrome_trace_schema(tmp_path):
    rec = Recorder(enabled=True, rank=1)
    with rec.span("sim", frame=0):
        pass
    rec.count("compile_step")
    rec.event("compile", frame=0, what="vdi_step")
    obs.degrade("test.component", "a", "b", "because", warn=False)
    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    xs = [e for e in evs if e.get("ph") == "X"]
    assert xs, "no complete (X) span events"
    for e in xs:
        for key in ("ph", "ts", "dur", "pid", "name", "tid"):
            assert key in e, (key, e)
        assert e["pid"] == 1
        assert e["args"]["frame"] == 0
    assert any(e.get("ph") == "C" for e in evs)          # counter
    assert any(e.get("cat") == "degrade" for e in evs)   # ledger instants
    assert any(e.get("ph") == "M" for e in evs)          # process name


def test_metrics_jsonl(tmp_path):
    rec = Recorder(enabled=True)
    with rec.span("sim", frame=0):
        pass
    path = rec.export_metrics_jsonl(str(tmp_path / "metrics.jsonl"))
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines[0]["type"] == "span" and lines[0]["name"] == "sim"
    assert lines[-1]["type"] == "summary"
    assert "degradations" in lines[-1]


def test_disabled_recorder_noop(tmp_path):
    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.jsonl"
    rec = Recorder(enabled=False, trace_path=str(trace),
                   metrics_path=str(metrics))
    for i in range(5):
        with rec.span("sim", frame=i):
            pass
    rec.flush()
    assert rec.events == []                  # zero events recorded
    assert not trace.exists() and not metrics.exists()   # no sink writes
    # ...but the PR-1 timer behavior is fully preserved
    assert rec.timers.stats["sim"].n == 5


# ------------------------------------------------------- session integration

def test_session_run_writes_trace_and_metrics(tmp_path):
    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.jsonl"
    cfg = _session_cfg(**{
        "obs.enabled": "true",
        "obs.trace_path": str(trace),
        "obs.metrics_path": str(metrics)})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    sess.run(3)
    with open(trace) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    # every host-visible render phase is covered
    assert {"sim", "dispatch", "fetch", "sinks"} <= names, names
    frames = {e["args"].get("frame") for e in xs if e["name"] == "sim"}
    assert frames == {0, 1, 2}
    assert all(e["pid"] == 0 for e in xs)     # rank attribution
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert lines and lines[-1]["type"] == "summary"
    assert lines[-1]["frames"] == 3
    assert lines[-1]["counters"].get("frames_eager_dispatch") == 3


def test_session_disabled_obs_zero_events():
    sess = InSituSession(_session_cfg(), mesh=make_mesh(2))
    sess.run(2)
    assert sess.obs.events == []
    assert sess.obs.enabled is False
    assert sess.timers.stats["sim"].n == 2   # PR-1 behavior intact


def test_session_device_snapshot():
    sess = InSituSession(_session_cfg(), mesh=make_mesh(2))
    sess.run(1)
    snaps = sess.device_snapshot()
    assert "gather" in snaps
    snap = snaps["gather"]
    assert snap is None or "source" in snap


def test_gather_obs_events_single_process():
    from scenery_insitu_tpu.parallel.multihost import gather_obs_events

    rec = Recorder(enabled=True, rank=0)
    with rec.span("sim", frame=0):
        pass
    merged = gather_obs_events(rec)
    assert merged is not None
    assert merged[0]["name"] == "sim"
    assert merged[-1]["type"] == "summary"


# ------------------------------------------------------------------- timers

def test_window_stats_reset_between_dumps():
    """Regression: each windowed dump must average ONLY its own window —
    never accumulate over the whole run."""
    lines = []
    t = Timers(window=2, log=lines.append)
    for _ in range(2):
        t.record("sim", 1.0)
        t.frame_done()
    assert any("window of 2" in l for l in lines)
    # reset happened: the window accumulator is empty after the dump
    assert all(st.n == 0 for st in t.window_stats.values())
    for _ in range(2):
        t.record("sim", 3.0)
        t.frame_done()
    # second window dump shows the second window's average (3000 ms),
    # not the accumulated 2000 ms
    second = [l for l in lines if "sim" in l][-1]
    assert "3000.000 ms" in second, second
    assert t.stats["sim"].n == 4             # totals still cover the run


def test_dump_totals_flushes_partial_window():
    lines = []
    t = Timers(window=100, log=lines.append)
    for _ in range(3):                        # never reaches a boundary
        t.record("sim", 0.5)
        t.frame_done()
    assert not any("window" in l for l in lines)
    t.dump_totals()
    assert any("final partial window" in l for l in lines)
    assert any("totals over 3 frames" in l for l in lines)
    # idempotent on the window part
    n = len(lines)
    t.close()
    assert not any("final partial window" in l for l in lines[n:])


def test_degrade_dedup_and_warning_once():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        obs.degrade("x.y", "fast", "slow", "why")
        obs.degrade("x.y", "fast", "slow", "why")
        obs.degrade("x.y", "fast", "slow", "other reason")
    entries = [e for e in obs.ledger() if e["component"] == "x.y"]
    assert len(entries) == 2
    assert entries[0]["count"] == 2 and entries[1]["count"] == 1
    assert len(w) == 2                        # one warning per distinct entry


def test_obs_config_roundtrip():
    cfg = FrameworkConfig().with_overrides(
        "obs.enabled=true", "obs.trace_path=/tmp/t.json", "obs.window=7")
    assert cfg.obs.enabled is True
    assert cfg.obs.trace_path == "/tmp/t.json"
    assert cfg.obs.window == 7
    d = cfg.to_dict()
    assert d["obs"]["enabled"] is True
    cfg2 = FrameworkConfig.from_dict(d)
    assert cfg2.obs == cfg.obs
