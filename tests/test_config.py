
from scenery_insitu_tpu.config import FrameworkConfig


def test_defaults():
    cfg = FrameworkConfig()
    assert cfg.vdi.max_supersegments == 20
    assert cfg.render.width == 1280


def test_overrides():
    cfg = FrameworkConfig().with_overrides(
        "render.width=512", "vdi.max_supersegments=12", "runtime.benchmark=true")
    assert cfg.render.width == 512
    assert cfg.vdi.max_supersegments == 12
    assert cfg.runtime.benchmark is True


def test_json_roundtrip(tmp_path):
    cfg = FrameworkConfig().with_overrides("sim.grid=[64,64,64]", "render.gamma=1.0")
    p = tmp_path / "cfg.json"
    p.write_text(cfg.to_json())
    cfg2 = FrameworkConfig.from_json_file(str(p))
    assert cfg2 == cfg
    assert cfg2.sim.grid == (64, 64, 64)


def test_env_override(monkeypatch):
    monkeypatch.setenv("SITPU_RENDER_WIDTH", "320")
    cfg = FrameworkConfig.load()
    assert cfg.render.width == 320


def test_unknown_key_rejected():
    import pytest
    with pytest.raises(AttributeError):
        FrameworkConfig.from_dict({"nope": 1})
