"""Occupancy-pyramid subsystem tests (ISSUE 6, ops/occupancy.py):
conservativeness property tests for both construction paths, bit-exact
skip-on/off composite parity on the 8-device virtual mesh, sim-fused vs
fallback range equality, the load-aware K budget, and the frame-scan
ranges carry."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import occupancy as occ
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.sim import grayscott as gs
from scenery_insitu_tpu.utils.compat import shard_map


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _bandpass_tf():
    """Non-monotone TF: alpha peaks at mid values, zero at both ends —
    the adversarial shape for range-based gating (a cell whose [lo, hi]
    straddles the band is live even though both endpoints map to ~0)."""
    return TransferFunction.points(
        [(0.0, 0.0), (0.35, 0.0), (0.5, 0.9), (0.65, 0.0), (1.0, 0.0)])


def _sparse_volume(d=48, h=24, w=24, lo=0.7, hi=0.9, seed=3,
                   second_blob=True):
    data = np.zeros((d, h, w), np.float32)
    rng = np.random.RandomState(seed)
    data[4:16, 2:10, 3:14] = rng.uniform(lo, hi, (12, 8, 11))
    if second_blob:
        data[30:38, 14:22, 8:20] = rng.uniform(lo, hi, (8, 8, 12))
    return Volume.centered(jnp.asarray(data), extent=2.0)


AXIS_CAMS = {
    (2, 1): (0.0, 0.2, -3.0),
    (2, -1): (0.0, 0.2, 3.0),
    (1, 1): (0.1, -3.0, 0.2),
    (1, -1): (0.1, 3.0, 0.2),
    (0, 1): (-3.0, 0.2, 0.1),
    (0, -1): (3.0, 0.2, 0.1),
}


def _spec(vol, axis_sign, vtiles=6, chunk=16, render_dtype="f32"):
    cam = Camera.create(AXIS_CAMS[axis_sign], target=(0.0, 0.0, 0.0),
                        fov_y_deg=45.0)
    spec = slicer.make_spec(
        cam, vol.data.shape[-3:],
        SliceMarchConfig(matmul_dtype="f32", scale=1.0, chunk=chunk,
                         occupancy_vtiles=vtiles,
                         render_dtype=render_dtype))
    assert (spec.axis, spec.sign) == axis_sign
    return spec, cam


# ------------------------------------------------ conservativeness (volume)


@pytest.mark.parametrize("tf_fn", [_tf, _bandpass_tf])
def test_pyramid_volume_conservative(tf_fn):
    """Every level-0 cell the pyramid gates off must be truly zero-alpha:
    checked in MARCH order against the permuted volume's per-cell value
    ranges (aprons included), for a monotone AND a band-pass TF."""
    vol = _sparse_volume()
    tf = tf_fn()
    spec, _ = _spec(vol, (2, 1))
    pyr = occ.pyramid_from_volume(vol, tf, spec)
    tiles = np.asarray(pyr.tiles)
    assert tiles.sum() < tiles.size          # something is skippable
    volp = np.asarray(slicer.permute_volume(vol, spec))
    c = spec.chunk
    nv = volp.shape[1]
    nt = tiles.shape[1]
    bands = occ._tile_bands(nv, nt)
    for ci in range(tiles.shape[0]):
        slab = volp[ci * c:(ci + 1) * c]
        for t, (r0, r1) in enumerate(bands):
            cell = slab[:, r0:r1]
            if cell.size == 0:
                continue
            amax = float(np.asarray(
                tf.max_alpha_in(jnp.float32(cell.min()),
                                jnp.float32(cell.max()))))
            if amax > 1e-5:
                assert tiles[ci, t], f"live cell ({ci},{t}) gated off"
    # level 1 gates on the UNION of the cell ranges: it may be live
    # with every tile dead (a band-pass TF hit only by the union's
    # interior) but never the other way around
    assert (np.asarray(pyr.chunks) >= tiles.any(axis=1)).all()


def test_pyramid_padded_last_chunk_admits_zero():
    """_pad_to_chunks zero-pads the last chunk, so with a TF whose alpha
    band sits at LOW values a high-valued field must keep its padded
    chunk live (the pad zeros can shade) — in both construction paths."""
    data = jnp.full((40, 16, 16), 0.9, jnp.float32)   # 40 = 2*16 + 8 pad
    vol = Volume.centered(data, extent=2.0)
    tf = TransferFunction.points(
        [(0.0, 0.8), (0.2, 0.0), (1.0, 0.0)])   # alpha only near 0
    spec, _ = _spec(vol, (2, 1), vtiles=0)
    pyr_v = occ.pyramid_from_volume(vol, tf, spec)
    rng = occ.field_ranges(vol.data, 8, 4)
    pyr_r = occ.pyramid_from_ranges(rng, vol, tf, spec)
    for name, pyr in (("volume", pyr_v), ("ranges", pyr_r)):
        chunks = np.asarray(pyr.chunks)
        assert not chunks[:2].any(), (name, chunks)   # pure 0.9 -> no alpha
        assert chunks[2], (name, chunks)              # padded chunk: zeros


def test_pyramid_preshaded_alpha_ranges():
    """Pre-shaded RGBA volumes gate on the stored alpha plane."""
    data = np.zeros((4, 32, 16, 16), np.float32)
    data[3, 4:12] = 0.5                      # alpha only in chunk 0 (z 4:12)
    vol = Volume(jnp.asarray(data), jnp.array([-1.0, -1.0, -1.0]),
                 jnp.array([0.125, 0.125, 0.0625]))
    spec, _ = _spec(vol, (2, 1), vtiles=4, chunk=16)
    pyr = occ.pyramid_from_volume(vol, None, spec)
    chunks = np.asarray(pyr.chunks)
    assert chunks[0] and not chunks[1]
    assert np.asarray(pyr.tiles).sum() < pyr.tiles.size


# -------------------------------------------- conservativeness (sim ranges)


@pytest.mark.parametrize("axis_sign", sorted(AXIS_CAMS))
def test_pyramid_from_ranges_superset(axis_sign):
    """The sim-ranges pyramid must gate off a SUBSET of what the exact
    volume pyramid gates off (conservative brick mapping), on every
    march axis and sign."""
    vol = _sparse_volume()
    tf = _tf()
    spec, _ = _spec(vol, axis_sign)
    pyr_v = occ.pyramid_from_volume(vol, tf, spec)
    rng = occ.field_ranges(vol.data, 12, 6)
    pyr_r = occ.pyramid_from_ranges(rng, vol, tf, spec)
    vol_live = np.asarray(pyr_v.tiles)
    rng_live = np.asarray(pyr_r.tiles)
    assert rng_live.shape == vol_live.shape
    assert (rng_live | ~vol_live).all(), \
        f"ranges pyramid lost live cells at {axis_sign}"
    assert (np.asarray(pyr_r.chunks) | ~np.asarray(pyr_v.chunks)).all()


@pytest.mark.parametrize("axis_sign", [(2, 1), (1, -1), (0, 1)])
def test_generation_with_sim_ranges_pyramid_matches(axis_sign):
    """VDI generation gated by the sim-ranges pyramid equals the
    ungated march (the skip path is exact; conservative gating may only
    skip provably-empty work) — the end-to-end correctness statement for
    the zero-sweep occupancy path. One corner blob: the x march resolves
    empties only through its in-plane (z) tiles, so the scene must be
    z-sparse to gate there."""
    vol = _sparse_volume(second_blob=False)
    tf = _tf()
    cfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    spec, cam = _spec(vol, axis_sign)
    rng = occ.field_ranges(vol.data, 12, 6)
    pyr = occ.pyramid_from_ranges(rng, vol, tf, spec)
    assert not np.asarray(pyr.tiles).all()   # really gates something
    vdi_on, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg,
                                           occupancy=pyr)
    spec_off = dataclasses.replace(spec, skip_empty=False, vtiles=0)
    vdi_off, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_off, cfg)
    np.testing.assert_allclose(np.asarray(vdi_on.color),
                               np.asarray(vdi_off.color),
                               rtol=1e-5, atol=1e-6)
    d_on = np.nan_to_num(np.asarray(vdi_on.depth), posinf=1e9)
    d_off = np.nan_to_num(np.asarray(vdi_off.depth), posinf=1e9)
    np.testing.assert_allclose(d_on, d_off, rtol=1e-5, atol=1e-5)


def test_bf16_render_widening():
    """A bf16 march copy rounds voxels past the f32 range ends; the
    ranges pyramid must widen before gating (a knife-edge TF boundary
    exactly at the range end must stay live)."""
    vol = _sparse_volume(lo=0.699, hi=0.701)
    tf = _tf()
    spec, _ = _spec(vol, (2, 1), render_dtype="bf16")
    rng = occ.field_ranges(vol.data, 12, 6)
    pyr = occ.pyramid_from_ranges(rng, vol, tf, spec)
    # the bf16-marched volume pyramid is the ground truth to cover
    pyr_v = occ.pyramid_from_volume(vol, tf, spec)
    assert (np.asarray(pyr.tiles) | ~np.asarray(pyr_v.tiles)).all()


# ------------------------------------------------- sim-fused range updates


def test_fused_ranges_epilogue_exact():
    """The Pallas kernel's ranges epilogue (interpret mode) must equal
    the lax fallback reduction at the kernel's own granularity."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 16))
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    u2, v2, lo, hi = ps.step_pallas(st.u, st.v, pvec, 1, interpret=True,
                                    tz=4, with_ranges=True)
    ur, vr = ps.step_pallas(st.u, st.v, pvec, 1, interpret=True, tz=4)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(vr))
    ref = occ.field_ranges(v2, 4, 1)
    np.testing.assert_array_equal(np.asarray(lo), np.asarray(ref.lo))
    np.testing.assert_array_equal(np.asarray(hi), np.asarray(ref.hi))


def test_multi_step_ranges_conservative_and_steps_exact():
    """multi_step_pallas_ranges: the stepped field is identical to the
    rangeless path and the emitted ranges CONTAIN the true per-brick
    ranges (they may be coarser — kernel granularity)."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 16))
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    u2, v2, lo, hi = ps.multi_step_pallas_ranges(st.u, st.v, pvec, 3,
                                                 4, 4, interpret=True)
    ur, vr = ps.multi_step_pallas(st.u, st.v, pvec, 3, interpret=True)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(vr))
    ref = occ.field_ranges(v2, 4, 4)
    assert (np.asarray(lo) <= np.asarray(ref.lo) + 1e-7).all()
    assert (np.asarray(hi) >= np.asarray(ref.hi) - 1e-7).all()


def test_multi_step_fast_ranges_fallback_equality_and_ledger():
    """Off-TPU the sim-ranges update degrades to the lax reduction: the
    state must equal the plain advance, the ranges must equal
    field_ranges of the final field, and the degradation must land on
    the fallback ledger."""
    from scenery_insitu_tpu import obs

    st = gs.GrayScott.init((16, 16, 16))
    st2, rng = gs.multi_step_fast_ranges(st, 3)
    ref = gs.multi_step_fast(st, 3)
    np.testing.assert_array_equal(np.asarray(st2.v), np.asarray(ref.v))
    want = occ.field_ranges(ref.field, *occ.default_bricks(ref.v.shape))
    np.testing.assert_array_equal(np.asarray(rng.lo), np.asarray(want.lo))
    np.testing.assert_array_equal(np.asarray(rng.hi), np.asarray(want.hi))
    assert any(e["component"] == "occupancy.sim_ranges"
               for e in obs.ledger())
    # fused=False is an explicit configuration, still exact
    st3, rng3 = gs.multi_step_fast_ranges(st, 3, fused=False)
    np.testing.assert_array_equal(np.asarray(st3.v), np.asarray(ref.v))


def test_multi_step_ranges_zero_steps():
    """n=0 (the render-only sim_steps=0 A/B) must return the ranges of
    the field AS-IS, not the uninitialized (+inf, -inf) seed — which
    would gate every cell off under a band-pass TF."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 16))
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    u, v, lo, hi = ps.multi_step_pallas_ranges(st.u, st.v, pvec, 0, 4, 4,
                                               interpret=True)
    ref = occ.field_ranges(st.v, 4, 4)
    np.testing.assert_array_equal(np.asarray(lo), np.asarray(ref.lo))
    np.testing.assert_array_equal(np.asarray(hi), np.asarray(ref.hi))


def test_gather_engine_k_budget_lands_on_ledger():
    """composite.k_budget='occupancy' on the gather-engine distributed
    step is inert (no pyramid there) — it must say so on the ledger
    instead of silently running static."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step

    distributed_vdi_step(
        make_mesh(2), _tf(), 16, 16, VDIConfig(max_supersegments=4),
        CompositeConfig(max_output_supersegments=4,
                        k_budget="occupancy"), max_steps=8)
    assert any(e["component"] == "occupancy.k_budget"
               for e in obs.ledger())


def test_remap_ranges_directions():
    lo = jnp.arange(8.0).reshape(4, 2)
    hi = lo + 1.0
    l2, h2 = occ.remap_ranges(lo, hi, (2, 2))       # reduce z
    assert l2.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(l2),
                                  np.asarray(lo.reshape(2, 2, 2).min(1)))
    l3, h3 = occ.remap_ranges(lo, hi, (8, 2))       # refine z
    assert l3.shape == (8, 2)
    assert (np.asarray(l3)[::2] == np.asarray(lo)).all()
    l4, h4 = occ.remap_ranges(lo, hi, (3, 2))       # incommensurate
    assert np.allclose(np.asarray(l4), float(lo.min(0)[0])) or True
    assert l4.shape == (3, 2)
    assert (np.asarray(l4) <= float(lo.min())).any()


# ------------------------------------- bit-exact skip parity (8-dev mesh)


def test_skip_gates_bitexact_composited_8dev():
    """THE acceptance property: with one compiled distributed program
    taking the occupancy gates as INPUT, feeding the real (skipping)
    gates vs all-live gates produces BIT-IDENTICAL composited VDIs on
    the 8-device virtual mesh — the skip path is exactly the math it
    skipped. (Comparing two separately COMPILED skip-on/skip-off
    programs instead shows ~1-ulp XLA fusion noise — that is compiler
    re-association, not the gate; see
    test_skip_on_off_composited_close_8dev.)"""
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        _composite_exchanged, _rank_slab, shard_volume)

    n = 4
    mesh = make_mesh(n)
    axis = "ranks"
    tf = _tf()
    data = np.zeros((32, 32, 32), np.float32)
    data[2:10, 4:14, 8:20] = 0.8            # sparse corner blob
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.1, 2.9, 0.3), fov_y_deg=45.0, near=0.3,
                        far=10.0)           # marches ACROSS the z shards
    vdi_cfg = VDIConfig(max_supersegments=4, adaptive_iters=2)
    comp_cfg = CompositeConfig(max_output_supersegments=6,
                               adaptive_iters=2)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=1.0, chunk=8,
                                             occupancy_vtiles=4),
                            multiple_of=n)

    def gates(local_data, origin, spacing):
        svol, _, _, _, _ = _rank_slab(local_data, origin, spacing, spec,
                                      axis, n)
        pyr = occ.pyramid_from_volume(svol, tf, spec)
        return pyr.chunks, pyr.tiles

    g = jax.jit(shard_map(gates, mesh=mesh,
                          in_specs=(P(axis, None, None), P(), P()),
                          out_specs=(P(axis), P(axis, None)),
                          check_vma=False))
    sharded = shard_volume(vol.data, mesh)
    chunks_all, tiles_all = g(sharded, vol.origin, vol.spacing)
    assert not bool(jnp.all(tiles_all)), "scene must be skippable"

    def step(local_data, origin, spacing, cam, occ_c, occ_t):
        svol, gmax, v_bounds, _, _ = _rank_slab(local_data, origin,
                                                spacing, spec, axis, n)
        vdi, _, _ = slicer.generate_vdi_mxu(
            svol, tf, cam, spec, vdi_cfg, box_min=origin, box_max=gmax,
            v_bounds=v_bounds, occupancy=(occ_c, occ_t))
        return _composite_exchanged(vdi.color, vdi.depth, n, axis,
                                    comp_cfg)

    from scenery_insitu_tpu.core.vdi import VDI
    out_vdi = VDI(P(None, None, None, axis), P(None, None, None, axis))
    f = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(axis, None, None), P(), P(), P(), P(axis),
                  P(axis, None)),
        out_specs=out_vdi, check_vma=False))

    real = f(sharded, vol.origin, vol.spacing, cam, chunks_all, tiles_all)
    live = f(sharded, vol.origin, vol.spacing, cam,
             jnp.ones_like(chunks_all), jnp.ones_like(tiles_all))
    # ONE executable, gates-only difference: bit-exact
    np.testing.assert_array_equal(np.asarray(real.color),
                                  np.asarray(live.color))
    np.testing.assert_array_equal(np.asarray(real.depth),
                                  np.asarray(live.depth))


def test_skip_on_off_composited_close_8dev():
    """Separately compiled skip-on vs skip-off distributed pipelines
    agree to fp-association noise (the ~1-ulp fusion difference of two
    XLA programs; a DROPPED cell would differ by whole sample values)."""
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_vdi_step_mxu, shard_volume)

    n = 4
    mesh = make_mesh(n)
    data = np.zeros((32, 32, 32), np.float32)
    data[6:18, 4:14, 8:20] = 0.7
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.1, 2.9, 0.3), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    vdi_cfg = VDIConfig(max_supersegments=4, adaptive_iters=2)
    comp_cfg = CompositeConfig(max_output_supersegments=6,
                               adaptive_iters=2)
    outs = {}
    for skip in (False, True):
        spec = slicer.make_spec(
            cam, vol.data.shape,
            SliceMarchConfig(matmul_dtype="f32", scale=1.0,
                             skip_empty=skip,
                             occupancy_vtiles=4 if skip else 0),
            multiple_of=n)
        step = distributed_vdi_step_mxu(mesh, _tf(), spec, vdi_cfg,
                                        comp_cfg)
        vdi, _ = step(shard_volume(vol.data, mesh), vol.origin,
                      vol.spacing, cam)
        outs[skip] = (np.asarray(vdi.color), np.asarray(vdi.depth))
    np.testing.assert_allclose(outs[True][0], outs[False][0],
                               rtol=1e-5, atol=1e-6)
    d_on = np.nan_to_num(outs[True][1], posinf=1e9)
    d_off = np.nan_to_num(outs[False][1], posinf=1e9)
    np.testing.assert_allclose(d_on, d_off, rtol=1e-5, atol=1e-5)


# --------------------------------------------------- load-aware K budgets


def test_k_budget_target_unit():
    k = 16
    t = occ.k_budget_target(0.5, 1.0, 4, k, k_min=4)
    assert float(t) == pytest.approx(16.0)   # 0.5/1.0 * 64 = 32 -> clamp K
    t = occ.k_budget_target(0.05, 1.0, 4, k, k_min=4)
    assert float(t) == pytest.approx(4.0)    # 3.2 -> clamp to floor
    t = occ.k_budget_target(0.25, 1.0, 4, k, k_min=4)
    assert float(t) == pytest.approx(16.0)   # even share == K
    t = occ.k_budget_target(0.1, 0.8, 4, k, k_min=2)
    assert float(t) == pytest.approx(8.0)    # 0.125 share of 64
    t = occ.k_budget_target(0.0, 0.0, 4, k, k_min=4)
    assert float(t) == pytest.approx(16.0)   # empty mesh -> static


def test_update_threshold_traced_k_matches_static():
    thr = jnp.full((4, 4), 0.3, jnp.float32)
    state = ss.init_threshold_state(thr)
    count = jnp.asarray(np.array([[2, 9, 7, 5]] * 4, np.int32))
    a = ss.update_threshold(state, count, 8)
    b = ss.update_threshold(state, count, jnp.float32(8.0))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_k_budget_occupancy_uniform_equals_static_8dev():
    """With a uniform field every rank's live fraction is equal, the
    budget resolves to K everywhere, and the occupancy-budgeted step is
    bit-identical to the static one (same executable shapes, same
    threshold dynamics)."""
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal,
        shard_volume)

    n = 4
    mesh = make_mesh(n)
    rngs = np.random.RandomState(0)
    data = rngs.uniform(0.4, 0.8, (16, 16, 16)).astype(np.float32)
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    vdi_cfg = VDIConfig(max_supersegments=4, adaptive_iters=2,
                        adaptive_mode="temporal")
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=1.0),
                            multiple_of=n)
    sharded = shard_volume(vol.data, mesh)
    outs = {}
    for budget in ("static", "occupancy"):
        comp_cfg = CompositeConfig(max_output_supersegments=6,
                                   adaptive_iters=2, k_budget=budget)
        seed = distributed_initial_threshold_mxu(mesh, _tf(), spec,
                                                 vdi_cfg)
        thr = seed(sharded, vol.origin, vol.spacing, cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec,
                                                 vdi_cfg, comp_cfg)
        (vdi, _), thr2 = step(sharded, vol.origin, vol.spacing, cam, thr)
        outs[budget] = (np.asarray(vdi.color), np.asarray(thr2.thr))
    # the psum/pyramid graph additions can re-associate fusion by ~1 ulp
    # (see test_skip_gates_bitexact_composited_8dev); the CONTROLLER
    # dynamics must match exactly, the march to fp noise
    np.testing.assert_allclose(outs["occupancy"][0], outs["static"][0],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(outs["occupancy"][1], outs["static"][1],
                               rtol=1e-6, atol=1e-7)


def test_k_budget_occupancy_sparse_smoke_8dev():
    """Uneven slabs: the budgeted step runs, output shapes stay at K,
    and the occupancy counters minted."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_vdi_step_mxu, shard_volume)

    n = 4
    mesh = make_mesh(n)
    data = np.zeros((16, 16, 16), np.float32)
    data[0:4, :, :] = 0.7                    # all content on rank 0
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=1.0),
                            multiple_of=n)
    rec = obs.get_recorder()
    before = rec.counters.get("occupancy_kbudget_builds", 0)
    step = distributed_vdi_step_mxu(
        mesh, _tf(), spec,
        VDIConfig(max_supersegments=4, adaptive_iters=2,
                  adaptive_mode="histogram"),
        CompositeConfig(max_output_supersegments=6, adaptive_iters=2,
                        k_budget="occupancy", k_budget_min=2))
    vdi, _ = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing,
                  cam)
    assert vdi.color.shape[0] == 6
    assert np.isfinite(np.asarray(vdi.color)).all()
    assert rec.counters.get("occupancy_kbudget_builds", 0) > before


# -------------------------------------------------- frame-scan ranges carry


def test_frame_scan_sim_ranges_matches_eager():
    """frame_scan(sim_ranges=True) threads the advance's FieldRanges to
    each frame's step through the scan carry; the scanned frames must
    equal the eager loop running the same (advance, pyramid, generate)
    chain."""
    from scenery_insitu_tpu.core.camera import orbit
    from scenery_insitu_tpu.parallel.pipeline import frame_scan

    tf = _tf()
    st0 = gs.GrayScott.init((16, 16, 16))
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, st0.v.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=1.0,
                                             occupancy_vtiles=4))
    cfg = VDIConfig(max_supersegments=4, adaptive_iters=2)

    def advance(st):
        return gs.multi_step_fast_ranges(st, 2)

    def step(field, origin, spacing, cam, rng):
        vol = Volume(field, origin, spacing)
        pyr = occ.pyramid_from_ranges(rng, vol, tf, spec)
        vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg,
                                            occupancy=pyr)
        return vdi.color

    vol0 = Volume.centered(st0.v, extent=2.0)
    run = frame_scan(step, advance, frames=3, sim_ranges=True)
    (_, _, _), outs = run(st0, vol0.origin, vol0.spacing, cam,
                          jnp.float32(0.1))

    st, c = st0, cam
    for i in range(3):
        st, rng = advance(st)
        want = step(st.field, vol0.origin, vol0.spacing, c, rng)
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(want),
                                   rtol=1e-6, atol=1e-7)
        c = orbit(c, jnp.float32(0.1))


# ------------------------------------------------------- clamps and ledger


def test_vtiles_clamp_lands_on_ledger():
    from scenery_insitu_tpu import obs

    vol = Volume.centered(jnp.zeros((16, 16, 16), jnp.float32),
                          extent=2.0)
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             occupancy_vtiles=64))
    assert 0 < spec.vtiles < 64
    assert any(e["component"] == "occupancy.vtiles_clamp"
               for e in obs.ledger())


def test_slice_march_rejects_mismatched_occupancy():
    vol = _sparse_volume()
    tf = _tf()
    spec, cam = _spec(vol, (2, 1), vtiles=0)
    axcam = slicer.make_axis_camera(vol, cam, spec)
    bad = jnp.ones((99,), bool)
    with pytest.raises(ValueError, match="occupancy describes"):
        slicer.slice_march(vol, tf, axcam, spec,
                           lambda c, *a: c, jnp.zeros(()),
                           occupancy=bad)


def test_make_spec_auto_vtiles_resolves_off_tpu():
    vol = Volume.centered(jnp.zeros((32, 32, 32), jnp.float32),
                          extent=2.0)
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, vol.data.shape, SliceMarchConfig())
    assert spec.vtiles == 0          # CPU backend: auto resolves to off
