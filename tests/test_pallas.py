"""Pallas kernel parity tests: the fused composite merge must produce
exactly what the XLA scan path produces (same state-machine code, two
schedules). Runs in interpret mode on the CPU test backend."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops.composite import composite_vdis
from scenery_insitu_tpu.ops.pallas_composite import resegment_sorted
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi


def _random_sorted_stream(nk, h, w, seed=0, empty_frac=0.4):
    """Depth-sorted slab stream with empties, like post-sort compositor
    input."""
    rng = np.random.default_rng(seed)
    start = np.sort(rng.uniform(1.0, 5.0, (nk, h, w)), axis=0)
    length = rng.uniform(0.01, 0.3, (nk, h, w))
    empty = rng.random((nk, h, w)) < empty_frac
    start = np.where(empty, np.inf, start).astype(np.float32)
    end = (start + length).astype(np.float32)
    rgba = rng.uniform(0.1, 1.0, (nk, 4, h, w)).astype(np.float32)
    a = rgba[:, 3]
    rgba[:, :3] *= a[:, None]                    # premultiply
    rgba = np.where(empty[:, None], 0.0, rgba).astype(np.float32)
    # re-sort by start so empties (inf) go last per pixel
    order = np.argsort(start, axis=0)
    start = np.take_along_axis(start, order, 0)
    end = np.take_along_axis(end, order, 0)
    rgba = np.take_along_axis(rgba, order[:, None], 0)
    return (jnp.asarray(rgba), jnp.asarray(np.stack([start, end], axis=1)),
            jnp.asarray(rng.uniform(0.0, 0.5, (h, w)).astype(np.float32)))


@pytest.mark.parametrize("shape", [(8, 128), (5, 37), (16, 256)])
def test_resegment_matches_scan(shape):
    h, w = shape
    nk, k_out = 12, 5
    sc, sd, thr = _random_sorted_stream(nk, h, w)

    # XLA reference: the same fold via lax.scan
    from scenery_insitu_tpu.ops import supersegments as ss

    def body(st, item):
        c, d = item
        return ss.push(st, k_out, thr, c, d[0], d[1], 1e-4), None

    st, _ = jax.lax.scan(body, ss.init_state(k_out, h, w), (sc, sd))
    ref_color, ref_depth = ss.finalize(st)

    color, depth = resegment_sorted(sc, sd, thr, k_out, 1e-4)
    np.testing.assert_allclose(np.asarray(color), np.asarray(ref_color),
                               atol=1e-6)
    live = np.isfinite(np.asarray(ref_depth))
    np.testing.assert_allclose(np.asarray(depth)[live],
                               np.asarray(ref_depth)[live], atol=1e-6)
    assert np.array_equal(np.isfinite(np.asarray(depth)), live)


def test_composite_backend_parity_on_real_vdis():
    vol = procedural_volume(16, kind="blobs", seed=7)
    tf = TransferFunction.ramp(0.1, 0.9, 0.6)
    vdis = []
    for eye_x in (-0.2, 0.2):
        cam_i = Camera.create((eye_x, 0.0, 4.0), fov_y_deg=50.0,
                              near=0.5, far=20.0)
        vdi, _ = generate_vdi(vol, tf, cam_i, 32, 24,
                              VDIConfig(max_supersegments=6,
                                        adaptive_iters=2), max_steps=48)
        vdis.append(vdi)
    colors = jnp.stack([v.color for v in vdis])
    depths = jnp.stack([v.depth for v in vdis])

    base = CompositeConfig(max_output_supersegments=6, adaptive_iters=2)
    out_x = composite_vdis(colors, depths,
                           dataclasses.replace(base, backend="xla"))
    out_p = composite_vdis(colors, depths,
                           dataclasses.replace(base, backend="pallas"))
    np.testing.assert_allclose(np.asarray(out_x.color),
                               np.asarray(out_p.color), atol=1e-6)
    live = np.isfinite(np.asarray(out_x.depth))
    np.testing.assert_allclose(np.asarray(out_p.depth)[live],
                               np.asarray(out_x.depth)[live], atol=1e-6)


def test_pallas_backend_jits():
    nk, k_out, h, w = 8, 4, 8, 128
    sc, sd, thr = _random_sorted_stream(nk, h, w, seed=3)
    f = jax.jit(lambda a, b, c: resegment_sorted(a, b, c, k_out))
    color, depth = f(sc, sd, thr)
    assert color.shape == (k_out, 4, h, w)
    assert np.isfinite(np.asarray(color)).all()
