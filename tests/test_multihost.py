"""Multi-host (DCN) tests that ACTUALLY RUN: real ``jax.distributed``
processes through the subprocess harness (testing/multiproc.py) on the
CPU backend.

The pre-ISSUE-14 two-process smoke was slow-marked and permanently
failing — it jitted a GLOBAL-mesh program, and the CPU backend cannot
run cross-process device collectives. Everything here rides what a
multi-process CPU runtime CAN do (the host plane): per-host local-mesh
SPMD, the coordinator KV store (``multihost._allgather_blobs``'s
fallback transport), and the PR-11 zmq tile-stream substrate — which is
exactly the HOST PATH of the hierarchical two-level composite
(parallel/hier.py, docs/MULTIHOST.md).

One harness run (module fixture: 2 processes x 2 virtual devices = the
flat 4-rank reference decomposition) exercises all three contracts:

- ``gather_vdi_tiles`` across real processes (KV-transport allgather,
  per-process blocks in column order);
- the obs event merge (``gather_obs_events`` — both ranks' spans in one
  rebased timeline);
- the two-level composite END TO END: per-host domain partials on the
  local mesh (cross-host halo rows shipped host-side), qpack8-capable
  f32 tile streams over loopback DCN, incremental head assembly — whose
  frame must BITWISE match the flat 4-rank ``distributed_vdi_step``
  composite computed in this (pytest) process on the virtual mesh.
"""

import json
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# harness scene — shared verbatim by the workers (deterministic seed)
# and the in-process flat reference below
GRID = 16
N_TOTAL = 4          # 2 hosts x 2 local devices
W = H = 16
K, K_OUT = 4, 6
MAX_STEPS = 24


def _scene():
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.sim import grayscott as gs

    st = gs.GrayScott.init((GRID, GRID, GRID), n_seeds=4)
    field = np.asarray(st.v)
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.4, 3.0), fov_y_deg=50.0, near=0.5,
                        far=20.0)
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.array([2.0 / GRID] * 3, jnp.float32)
    vcfg = VDIConfig(max_supersegments=K, adaptive_iters=2)
    ccfg = CompositeConfig(max_output_supersegments=K_OUT,
                           adaptive_iters=2)
    return field, tf, cam, origin, spacing, vcfg, ccfg


# ----------------------------------------------------- the worker entry

def _entry_all(ctx):
    """Runs inside EVERY harness worker (real jax.distributed process):
    the host-path hierarchical composite + the cross-process gather +
    the obs merge. The head (process 0) writes the artifacts the pytest
    process asserts on."""
    import time

    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
    from scenery_insitu_tpu.parallel import multihost
    from scenery_insitu_tpu.parallel.hier import (assemble_hier_frame,
                                                  domain_partial_vdi_step,
                                                  publish_partial_tiles)
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import shard_volume
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    pid, nproc = ctx.process_id, ctx.num_processes
    rec = obs.Recorder(enabled=True, rank=pid)
    obs.set_recorder(rec)

    field, tf, cam, origin, spacing, vcfg, ccfg = _scene()
    d_local = len(jax.local_devices())
    n_total = nproc * d_local
    dn = GRID // n_total
    rank0 = pid * d_local

    # this host's slab + cross-host halo rows (host-side exchange: here
    # sliced from the deterministic shared state; production ships one
    # boundary slice per seam over the stream plane)
    lo, hi = rank0 * dn, (rank0 + d_local) * dn
    local = field[lo:hi]
    halo_lo = field[lo - 1:lo] if lo > 0 else field[0:1]
    halo_hi = field[hi:hi + 1] if hi < GRID else field[GRID - 1:GRID]

    mesh = make_mesh(d_local, devices=jax.local_devices())
    step = domain_partial_vdi_step(mesh, tf, W, H, vcfg, ccfg,
                                   max_steps=MAX_STEPS,
                                   rank_offset=rank0, n_total=n_total)
    acc_c, acc_d = step(shard_volume(jnp.asarray(local), mesh), origin,
                        spacing, cam, jnp.asarray(halo_lo),
                        jnp.asarray(halo_hi))

    # ---- DCN hop: PR-11 tile streams over loopback, head assembles
    meta = VDIMetadata.create(np.eye(4, dtype=np.float32),
                              np.eye(4, dtype=np.float32),
                              volume_dims=(GRID, GRID, GRID),
                              window_dims=(W, H), index=0)
    pub = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                       precision="f32", epoch=100 + pid)
    multihost.kv_put_bytes(f"hier/ep/{pid}", pub.endpoint.encode())
    multihost.barrier("hier_eps")
    if pid == 0:
        subs = {h: VDISubscriber(connect=multihost.kv_get_bytes(
            f"hier/ep/{h}").decode()) for h in range(nproc)}
        time.sleep(0.5)                    # zmq slow-joiner settle
    multihost.barrier("hier_subs")
    sent = publish_partial_tiles(pub, acc_c, acc_d, meta, tiles=d_local)
    assert sent > 0

    hier_ok = True
    if pid == 0:
        frame, degraded = assemble_hier_frame(subs, nproc, ccfg,
                                              tiles=d_local,
                                              timeout_ms=60_000)
        hier_ok = frame is not None and not degraded
        np.savez(os.path.join(ctx.workdir, "mh_hier.npz"),
                 color=np.asarray(frame.color),
                 depth=np.asarray(frame.depth),
                 degraded=np.array(degraded))
        for s in subs.values():
            s.close()
    multihost.barrier("hier_done", timeout_ms=120_000)
    pub.close()

    # ---- gather_vdi_tiles across real processes (KV transport)
    wp = 8
    color = jnp.full((2, 4, 4, wp), float(pid + 1), jnp.float32)
    depth = jnp.stack([jnp.full((2, 4, wp), 0.1 * (pid + 1), jnp.float32),
                       jnp.full((2, 4, wp), 0.2 * (pid + 1), jnp.float32)],
                      axis=1)
    tiles = multihost.gather_vdi_tiles(VDI(color, depth), codec="zlib")
    gather = None
    if pid == 0:
        gather = list(tiles)
    else:
        assert tiles is None

    # ---- obs event merge across processes
    with rec.span("mh_rank_work", frame=pid):
        pass
    merged = multihost.gather_obs_events(rec)

    if pid == 0:
        g_ok = (len(gather) == nproc
                and [g[0] for g in gather] == [wp * p
                                               for p in range(nproc)]
                and all(np.allclose(g[1], p + 1)
                        for p, g in enumerate(gather))
                and all(g[1].shape == (2, 4, 4, wp) for g in gather)
                and all(g[2].shape == (2, 2, 4, wp) for g in gather))
        span_ranks = sorted({e.get("rank") for e in merged
                             if e.get("name") == "mh_rank_work"})
        ledger = [e["component"] for e in obs.ledger()]
        json.dump({
            "gather_ok": bool(g_ok),
            "span_ranks": span_ranks,
            "hier_ok": bool(hier_ok),
            "kv_transport_ledgered": "multihost.transport" in ledger,
            "dcn_bytes_sent": rec.counters.get("dcn_bytes_sent", 0),
            "dcn_bytes_received": rec.counters.get("dcn_bytes_received",
                                                   0),
            "dcn_span_names": sorted({e.get("name") for e in rec.events
                                      if str(e.get("name",
                                             "")).startswith("dcn")}),
        }, open(os.path.join(ctx.workdir, "mh_results.json"), "w"))
    else:
        assert merged is None


# ------------------------------------------- traced-fleet worker entry

FLEET_FRAMES = 6


def _entry_fleet(ctx):
    """ISSUE 17 fleet-tracing drill: process 1 renders-and-publishes a
    VDI stream (trace context stamped in every wire header), process 0
    subscribes AND hosts the telemetry collector. Both processes pump
    their recorders into the collector; process 0 exports the ONE merged
    Perfetto trace plus an SLO report, and writes the machine-checkable
    verdicts the pytest process asserts on."""
    import time

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import SLOConfig
    from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
    from scenery_insitu_tpu.obs.collector import Collector, ObsPublisher
    from scenery_insitu_tpu.obs.slo import SLOEngine
    from scenery_insitu_tpu.parallel import multihost
    from scenery_insitu_tpu.runtime.streaming import (StreamDrop,
                                                      VDIPublisher,
                                                      VDISubscriber)

    pid = ctx.process_id
    # the distributed CPU backend is created COLLECTIVELY (a cross-
    # process rendezvous on first jax touch) — force it here, while the
    # processes are still symmetric, or the first side to touch an array
    # deadlocks against the other side's coordination barrier
    import jax

    jax.local_devices()
    rec = obs.Recorder(enabled=True, rank=pid)
    obs.set_recorder(rec)

    col = None
    if pid == 0:
        col = Collector()
        multihost.kv_put_bytes("fleet/obs_ep", col.endpoint.encode())
        multihost.kv_put_bytes("fleet/hb_ep", col.hb_endpoint.encode())
    multihost.barrier("fleet_col")
    opub = ObsPublisher(
        multihost.kv_get_bytes("fleet/obs_ep").decode(),
        multihost.kv_get_bytes("fleet/hb_ep").decode(),
        rank=pid, interval_s=0.0)

    if pid == 1:
        # ---------------- the render/publish side of the fleet
        rng = np.random.default_rng(17)
        kk, hh, ww = 3, 10, 12
        vdi = VDI(rng.random((kk, 4, hh, ww)).astype(np.float32),
                  rng.random((kk, 2, hh, ww)).astype(np.float32))
        meta = VDIMetadata.create(np.eye(4, dtype=np.float32),
                                  np.eye(4, dtype=np.float32),
                                  volume_dims=(8, 8, 8),
                                  window_dims=(ww, hh), index=0)
        pub = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib")
        multihost.kv_put_bytes("fleet/vdi_ep", pub.endpoint.encode())
        multihost.barrier("fleet_eps")
        multihost.barrier("fleet_subs")
        # settle the obs PUB path: the channel is loss-tolerant BY
        # DESIGN (a too-eager publisher's first batch dies in the async
        # zmq subscription handshake), but this drill asserts FULL
        # lineage — so prove the link with contentless probes first
        deadline = time.monotonic() + 20.0
        while not opub.linked and time.monotonic() < deadline:
            opub.probe()
            time.sleep(0.02)
        multihost.barrier("fleet_linked")
        for i in range(FLEET_FRAMES):
            with rec.span("frame", frame=i):
                pub.publish(vdi, meta._replace(index=np.int32(i)))
            opub.pump(rec, force=True)
            time.sleep(0.03)
        multihost.barrier("fleet_frames", timeout_ms=120_000)
        opub.close(rec)
        pub.close()
        return

    # -------------------- the head/collector side (pid 0)
    multihost.barrier("fleet_eps")
    sub = VDISubscriber(
        connect=multihost.kv_get_bytes("fleet/vdi_ep").decode())
    time.sleep(0.5)                        # zmq slow-joiner settle
    multihost.barrier("fleet_subs")
    # the collector lives HERE — keep polling so both ranks' probes get
    # ingested and their heartbeat pongs report them linked
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        col.poll(10)
        opub.probe()
        if opub.linked and sorted(col.ranks) == [0, 1]:
            break
    multihost.barrier("fleet_linked")
    slo = SLOEngine(SLOConfig(enabled=True, window=8, min_samples=2,
                              camera_to_pixel_p99_ms=60_000.0), rec)
    frames_got = []
    deadline = time.monotonic() + 60.0
    while len(frames_got) < FLEET_FRAMES and time.monotonic() < deadline:
        got = sub.receive_tile(timeout_ms=200)
        col.poll(0)
        opub.pump(rec, force=True)
        if got is None or isinstance(got, StreamDrop):
            continue
        _, m, _ = got
        fidx = int(np.asarray(m.index))
        frames_got.append(fidx)
        # the receive-side lineage instant carries the sender's origin
        # stamp; its age IS the measured camera-to-pixel latency
        ages = [(e.get("attrs") or {}).get("age_ms") for e in rec.events
                if e.get("name") == "lineage" and e.get("frame") == fidx]
        ages = [a for a in ages if a is not None]
        if ages:
            slo.observe("camera_to_pixel_ms", ages[-1], frame=fidx)
    multihost.barrier("fleet_frames", timeout_ms=120_000)
    opub.close(rec)
    # drain the stragglers (pid 1's close() forced a final pump)
    for _ in range(20):
        col.poll(50)

    trace_path = os.path.join(ctx.workdir, "fleet_trace.json")
    col.export_fleet_trace(trace_path)
    json.dump(slo.snapshot(),
              open(os.path.join(ctx.workdir, "slo_report.json"), "w"))

    # machine-checkable verdicts for the pytest process
    arcs_monotone, arcs_cross_process = [], []
    for f in col.frames_seen():
        arc = col.frame_arc(f)
        ts = [e["t_us"] for e in arc]
        arcs_monotone.append(ts == sorted(ts))
        arcs_cross_process.append(len({e["rank"] for e in arc}) >= 2)
    json.dump({
        "frames_delivered": sorted(frames_got),
        "frames_seen": col.frames_seen(),
        "ranks": sorted(col.ranks),
        "arcs_monotone": arcs_monotone,
        "arcs_cross_process": arcs_cross_process,
        "clock_model": col.clock_model(),
        "batches": col.batches,
    }, open(os.path.join(ctx.workdir, "fleet_results.json"), "w"))
    sub.close()
    col.close()


# ------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    """ONE two-process harness run shared by every test in this module
    (each worker spawn pays a fresh jax import + compile)."""
    from scenery_insitu_tpu.testing import multiproc

    workdir = tmp_path_factory.mktemp("mh")
    results = multiproc.run_multiproc(
        "tests.test_multihost:_entry_all", n_procs=2, devices_per_proc=2,
        workdir=str(workdir), timeout_s=420.0)
    for r in results:
        assert r.ok, f"worker {r.process_id} failed:\n{r.output}"
    data = json.load(open(workdir / "mh_results.json"))
    return workdir, data


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """The ISSUE-17 traced-fleet harness run: two real processes, one
    collector, one merged trace."""
    from scenery_insitu_tpu.testing import multiproc

    workdir = tmp_path_factory.mktemp("fleet")
    results = multiproc.run_multiproc(
        "tests.test_multihost:_entry_fleet", n_procs=2,
        devices_per_proc=1, workdir=str(workdir), timeout_s=420.0)
    for r in results:
        assert r.ok, f"worker {r.process_id} failed:\n{r.output}"
    data = json.load(open(workdir / "fleet_results.json"))
    trace = json.load(open(workdir / "fleet_trace.json"))
    slo = json.load(open(workdir / "slo_report.json"))
    return data, trace, slo


@pytest.mark.multiproc
def test_fleet_every_frame_delivered_and_seen(fleet):
    """The delivery plane delivered every frame, and the collector's
    merged view contains lineage for every one of them from BOTH
    processes."""
    data, _, _ = fleet
    assert data["frames_delivered"] == list(range(FLEET_FRAMES))
    assert data["frames_seen"] == list(range(FLEET_FRAMES))
    assert data["ranks"] == [0, 1]
    assert data["batches"] > 0


@pytest.mark.multiproc
def test_fleet_single_frame_lineage_followable(fleet):
    """The acceptance criterion: in the ONE merged Perfetto trace, a
    single frame's spans/instants appear from both processes, its flow
    links are intact (every 's' has its 'f' on the other end), and the
    clock-aligned arc timestamps are monotone."""
    data, trace, _ = fleet
    assert all(data["arcs_monotone"]), data["arcs_monotone"]
    assert all(data["arcs_cross_process"]), data["arcs_cross_process"]
    evs = trace["traceEvents"]
    pids = {e.get("pid") for e in evs if e.get("ph") == "M"}
    assert pids == {0, 1}
    starts = {e["id"]: e for e in evs
              if e.get("ph") == "s" and e.get("cat") == "lineage"}
    ends = {e["id"]: e for e in evs
            if e.get("ph") == "f" and e.get("cat") == "lineage"}
    assert starts and set(starts) == set(ends)
    # at least one flow arrow crosses the process boundary
    assert any(starts[i]["pid"] != ends[i]["pid"] for i in starts)
    # per-rank clock model shipped with the trace, with finite bounds
    cm = trace["otherData"]["clock_model"]
    assert set(cm) == {"0", "1"}
    assert all(m["error_bound_ms"] < 1000.0 for m in cm.values())


@pytest.mark.multiproc
def test_fleet_slo_report_measures_camera_to_pixel(fleet):
    """The SLO snapshot is the machine-readable health artifact: the
    measured camera-to-pixel latency (from the wire trace context's
    origin stamps) has real samples and an honest rolling p99."""
    _, _, slo = fleet
    assert slo["type"] == "slo_report"
    m = slo["metrics"]["camera_to_pixel_ms"]
    assert m["n"] >= FLEET_FRAMES - 1
    assert m["p99"] >= m["p50"] > 0.0
    assert slo["healthy"] in (True, False)


@pytest.mark.multiproc
def test_gather_vdi_tiles_across_real_processes(harness):
    """Each process's column block arrives on the head in process/column
    order with its content intact — over the KV transport, since the CPU
    backend has no cross-process device collectives (the routing is
    ledgered, not silent)."""
    _, data = harness
    assert data["gather_ok"]
    assert data["kv_transport_ledgered"]


@pytest.mark.multiproc
def test_obs_event_merge_across_real_processes(harness):
    """gather_obs_events returns BOTH ranks' spans in one merged
    timeline on process 0, and the DCN hops show up as dcn_* telemetry
    (spans + byte counters — docs/OBSERVABILITY.md)."""
    _, data = harness
    assert data["span_ranks"] == [0, 1]
    assert "dcn_allgather" in data["dcn_span_names"]
    assert data["dcn_bytes_sent"] > 0
    assert data["dcn_bytes_received"] > 0


@pytest.mark.multiproc
def test_two_level_composite_parity_across_real_processes(harness):
    """The host-path hierarchical frame — per-host local-mesh domain
    partials, f32 tile streams over loopback DCN, incremental head merge
    — must BITWISE match the flat 4-rank composite of the identical
    scene on this process's virtual mesh (re-segmentation happens once,
    at the head, so the merged stream is the flat stream)."""
    import jax.numpy as jnp

    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (distributed_vdi_step,
                                                      shard_volume)

    workdir, data = harness
    assert data["hier_ok"]
    got = np.load(workdir / "mh_hier.npz")
    assert not bool(got["degraded"])

    field, tf, cam, origin, spacing, vcfg, ccfg = _scene()
    mesh = make_mesh(N_TOTAL)
    step = distributed_vdi_step(mesh, tf, W, H, vcfg, ccfg,
                                max_steps=MAX_STEPS)
    ref = step(shard_volume(jnp.asarray(field), mesh), origin, spacing,
               cam)
    rc, rd = np.asarray(ref.color), np.asarray(ref.depth)
    np.testing.assert_array_equal(got["color"], rc)
    assert (np.isinf(got["depth"]) == np.isinf(rd)).all()
    fin = np.isfinite(rd)
    np.testing.assert_array_equal(got["depth"][fin], rd[fin])
