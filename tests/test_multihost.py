"""Multi-host (DCN) smoke: the same distributed_vdi_step running across 2
OS processes (jax.distributed over the coordination service — ≅ the
reference's mpirun deployment, README.md:4-8) must agree with itself
across processes AND with a single-process run of the identical
configuration on the virtual mesh."""

import os
import re
import subprocess
import sys

import jax.numpy as jnp
import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_smoke_matches_single_process():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "scenery_insitu_tpu.parallel.multihost",
         "--launch", "2"],
        cwd=REPO, env=env, capture_output=True, timeout=600)
    out = proc.stdout.decode("utf-8", "replace")
    assert proc.returncode == 0, out + proc.stderr.decode("utf-8", "replace")
    assert "LAUNCH_OK" in out
    norms = [float(m) for m in re.findall(r"MULTIHOST_OK pid=\d+ "
                                          r"norm=([0-9.]+)", out)]
    assert len(norms) == 2 and abs(norms[0] - norms[1]) < 1e-4
    gather = re.search(r"MULTIHOST_GATHER_OK .*norm=([0-9.]+)", out)
    assert gather, out
    # the temporal MXU step must also agree across processes
    mxu = [float(m) for m in re.findall(r"MULTIHOST_MXU_OK pid=\d+ "
                                        r"norm=([0-9.]+)", out)]
    assert len(mxu) == 2 and abs(mxu[0] - mxu[1]) < 1e-4, out

    # single-process reference: the identical configuration on this
    # process's virtual mesh (4 devices = 2 procs x 2 devices)
    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (distributed_vdi_step,
                                                      shard_volume)
    from scenery_insitu_tpu.sim import grayscott as gs

    n = 4
    mesh = make_mesh(n)
    st = gs.GrayScott.init((8 * n, 16, 16), n_seeds=4)
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.4, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    step = distributed_vdi_step(
        mesh, tf, 8 * n, 16,
        VDIConfig(max_supersegments=4, adaptive_iters=2),
        CompositeConfig(max_output_supersegments=6, adaptive_iters=2),
        max_steps=24)
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.array([2.0 / 16, 2.0 / 16, 2.0 / (8 * n)], jnp.float32)
    vdi = step(shard_volume(st.v, mesh), origin, spacing, cam)
    ref_norm = float(jnp.linalg.norm(vdi.color))
    assert abs(ref_norm - norms[0]) < 1e-3, (ref_norm, norms[0])
    assert abs(float(gather.group(1)) - ref_norm) < 1e-3
