"""Multi-resolution brick maps (LODConfig; docs/PERF.md "LOD
marching"): per-brick refinement levels on BrickMap, the
reslab_bricks_lod pooled materialization, the level planner
(parallel/lod.py — screen-space error, empty coarsening, hysteresis,
the TF-straddle gate), the coarse MXU march, and the session replan
loop.

Parity gates, and why each is what it is:
- the all-level-0 LOD map is BITWISE the pre-LOD brick path on the
  gather builder and the MXU builders: level 0 units take the exact
  legacy code path (same bands, same camera object, default
  step_scale), so this is a structural identity the tests pin down as
  a regression gate (the CI `lod` lane runs it).
- coarse levels on EMPTY bricks match the even frame at the 1e-5 MXU
  gate: pooling air is exact, the march of a zero brick emits nothing
  at any level.
- coarse levels on a SMOOTH field hold a PSNR floor vs the exact
  frame: reshape-mean pooling + the step_scale opacity re-correction
  approximate the fine march; the committed bench ladder
  (benchmarks/results/lod_ab_r16_cpu.json) carries the quantitative
  claim, this test guards against regressions that would tank it.
- the TF-straddle gate is a PROPERTY: no brick whose sampled value
  range crosses an opacity edge is ever assigned level > 0 — under
  random ranges/edges and after a steered TF update (scenario zoo
  path).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import (CompositeConfig, LODConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction, opacity_edges
from scenery_insitu_tpu.ops.occupancy import z_range_profile
from scenery_insitu_tpu.parallel import bricks as bk
from scenery_insitu_tpu.parallel import lod as lodm
from scenery_insitu_tpu.parallel.mesh import make_mesh, reslab_bricks_lod
from scenery_insitu_tpu.parallel.pipeline import (distributed_vdi_step,
                                                  distributed_vdi_step_mxu,
                                                  shard_volume)
from scenery_insitu_tpu.utils.compat import shard_map

N = 8
D = 32
HW = 16
ATOL = 1e-5

OWNER = (3, 0, 5, 1, 4, 7, 2, 6)
ISLANDS = (0, 0, 1, 2, 3, 4, 5, 6)


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _scene():
    """The test_bricks.py blob scene: brick 6 of an 8-brick split
    (rows 24..27) is EMPTY."""
    data = np.zeros((D, HW, HW), np.float32)
    blobs = [(1, 3, 0.3), (5, 7, 0.5), (9, 11, 0.7), (13, 15, 0.4),
             (17, 19, 0.6), (21, 23, 0.8), (29, 31, 0.45)]
    for a, b, v in blobs:
        data[a:b] = v
    vox = 2.0 / D
    origin = jnp.asarray([-HW * vox / 2, -HW * vox / 2, -1.0], jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    return jnp.asarray(data), origin, spacing


def _smooth_scene():
    """Gently varying field — the coarse-march quality scene."""
    z = np.arange(D)[:, None, None] / D
    y = np.arange(HW)[None, :, None] / HW
    x = np.arange(HW)[None, None, :] / HW
    data = (0.45 + 0.18 * np.sin(2 * np.pi * z)
            * np.cos(np.pi * y) * np.cos(np.pi * x)).astype(np.float32)
    vox = 2.0 / D
    origin = jnp.asarray([-HW * vox / 2, -HW * vox / 2, -1.0], jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    return jnp.asarray(data), origin, spacing


def _mxu_spec(cam, **cfg_kw):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, (D, HW, HW),
                            SliceMarchConfig(matmul_dtype="f32", scale=2.0,
                                             **cfg_kw),
                            multiple_of=N)


def _cfgs(rebalance="bricks", **comp_kw):
    return (VDIConfig(max_supersegments=6, adaptive_iters=2),
            CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                            rebalance=rebalance, **comp_kw))


def _assert_vdi_close(a, b, atol=ATOL):
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


def _psnr(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    mse = float(np.mean((a - b) ** 2))
    if mse == 0.0:
        return np.inf
    return 10.0 * np.log10(1.0 / mse)


# ------------------------------------------------------------ config/units


def test_lodconfig_validation():
    LODConfig(enabled=True, max_level=3)
    with pytest.raises(ValueError, match="max_level"):
        LODConfig(max_level=-1)
    with pytest.raises(ValueError, match="max_level"):
        LODConfig(max_level=9)
    with pytest.raises(ValueError, match="error_px"):
        LODConfig(error_px=0.0)
    with pytest.raises(ValueError, match="hysteresis"):
        LODConfig(hysteresis=1.0)


def test_brickmap_level_field_and_helpers():
    bm = bk.BrickMap(D, N, OWNER)                 # no levels -> all zero
    assert bm.level == (0,) * 8
    assert bm.max_level == 0 and bm.levels_present() == (0,)
    assert bm.total_slots == bm.slots

    lv = (0, 1, 0, 2, 0, 1, 0, 0)
    bml = bk.BrickMap(D, N, OWNER, lv)
    assert bml.max_level == 2
    assert bml.levels_present() == (0, 1, 2)
    assert not bml.is_even_convex()
    # per-level slot counts are GLOBAL maxima (SPMD shape uniformity)
    for lvl in bml.levels_present():
        t = bml.start_table_at(lvl)
        assert t.shape == (N, bml.slots_at(lvl))
    assert bml.total_slots == sum(bml.slots_at(l)
                                  for l in bml.levels_present())
    # level-2 brick is brick 3 (owner 1): its table row has its start
    t2 = bml.start_table_at(2)
    assert t2[1].max() == 3 * bml.brick_depth
    assert (t2[[0, 2, 3, 4, 5, 6, 7]] == -1).all()

    # with_levels swaps levels, keeps ownership
    assert bml.with_levels((0,) * 8).level == (0,) * 8
    # permute carries levels with the map
    assert bml.permute(tuple(range(N))).level == lv


def test_brickmap_level_validation():
    with pytest.raises(ValueError, match="level"):
        bk.BrickMap(D, N, OWNER, (0,) * 7)        # wrong length
    with pytest.raises(ValueError, match="level"):
        bk.BrickMap(D, N, OWNER, (0, -1) + (0,) * 6)
    # brick depth 4 cannot host a level-3 (f=8) brick
    with pytest.raises(ValueError, match="divide"):
        bk.BrickMap(D, N, OWNER, (3,) + (0,) * 7)


def test_steal_plan_carries_levels():
    lv = (0, 1, 0, 2, 0, 1, 0, 0)
    bm = bk.BrickMap(D, N, OWNER, lv)
    prof = np.zeros(8)
    prof[:2] = 1.0
    work = bk.brick_work(prof, D, 8)
    out = bk.steal_plan(bm, work, max_moves=2, hysteresis=0.0)
    assert out.level == lv


def test_opacity_edges_and_range_profile():
    tf = _tf()
    edges = opacity_edges(tf)
    np.testing.assert_allclose(edges, [0.05, 0.8], atol=1e-6)
    # padding knots (x=2) and zero-slope knots never appear
    assert (edges <= 1.0).all()

    data, _, _ = _scene()
    lo, hi = z_range_profile(data, nzb=8)
    lo, hi = np.asarray(lo), np.asarray(hi)
    assert lo.shape == (8,) and hi.shape == (8,)
    assert lo[6] == 0.0 and hi[6] == 0.0           # empty brick
    assert hi[1] >= 0.5                            # blob (5,7,0.5)


def test_per_brick_regrid():
    prof = np.arange(16, dtype=np.float64)
    np.testing.assert_allclose(lodm.per_brick(prof, 8, "mean"),
                               prof.reshape(8, 2).mean(1))
    np.testing.assert_allclose(lodm.per_brick(prof, 8, "min"),
                               prof.reshape(8, 2).min(1))
    np.testing.assert_allclose(lodm.per_brick(prof, 32, "mean"),
                               np.repeat(prof, 2))
    with pytest.raises(ValueError, match="nest"):
        lodm.per_brick(prof, 6)


def test_admissible_max_level():
    assert lodm.admissible_max_level(4, 16, 16, 8) == 2   # bz=4 caps f=4
    assert lodm.admissible_max_level(8, 16, 16, 2) == 2   # cfg caps
    assert lodm.admissible_max_level(8, 16, 16, 8) == 3   # bz=8 caps f=8
    assert lodm.admissible_max_level(4, 2, 16, 8) == 1    # H=2 caps f=2


def _plan_kw(dims=(HW, HW, D), eye=(0.0, 0.0, 4.0), height_px=64):
    vox = 2.0 / D
    return dict(dims=dims,
                origin=np.asarray([-dims[0] * vox / 2, -dims[1] * vox / 2,
                                   -1.0]),
                spacing=np.full(3, vox), eye=np.asarray(eye),
                fov_y=np.deg2rad(50.0), height_px=height_px)


def test_select_levels_screen_error_monotone_with_distance():
    nb = 8
    live = np.ones(nb)
    lo = np.full(nb, 0.3)
    hi = np.full(nb, 0.4)                          # no straddle of 0.05/0.8
    cfg = LODConfig(enabled=True, max_level=2, error_px=1.0,
                    coarsen_empty=False)
    near = lodm.select_levels(live, lo, hi, opacity_edges(_tf()),
                              cfg=cfg, **_plan_kw(eye=(0, 0, 2.5)))
    far = lodm.select_levels(live, lo, hi, opacity_edges(_tf()),
                             cfg=cfg, **_plan_kw(eye=(0, 0, 60.0)))
    assert all(f >= n for f, n in zip(far, near))
    assert max(far) > 0                            # far away coarsens
    # a huge pixel budget coarsens even near
    loose = LODConfig(enabled=True, max_level=2, error_px=1e4,
                      coarsen_empty=False)
    lv = lodm.select_levels(live, lo, hi, opacity_edges(_tf()),
                            cfg=loose, **_plan_kw(eye=(0, 0, 2.5)))
    assert lv == (2,) * nb


def test_select_levels_empty_bricks_coarsen():
    nb = 8
    live = np.zeros(nb)
    live[2] = 0.5
    lo = np.zeros(nb)
    hi = np.zeros(nb)
    lo[2], hi[2] = 0.3, 0.4
    cfg = LODConfig(enabled=True, max_level=2, error_px=0.01)
    lv = lodm.select_levels(live, lo, hi, opacity_edges(_tf()),
                            cfg=cfg, **_plan_kw(eye=(0, 0, 2.5)))
    # the tight error budget keeps occupied bricks fine; air coarsens
    assert lv[2] == 0
    assert all(l == 2 for i, l in enumerate(lv) if i != 2)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_select_levels_tf_straddle_property(seed):
    """PROPERTY: no brick whose sampled value range crosses an opacity
    edge is ever assigned level > 0 — whatever the camera, occupancy
    or hysteresis state says."""
    rng = np.random.default_rng(seed)
    nb = 16
    lo = rng.uniform(0.0, 0.9, nb)
    hi = lo + rng.uniform(0.0, 0.5, nb)
    live = rng.uniform(0.0, 1.0, nb)
    edges = opacity_edges(_tf())
    cfg = LODConfig(enabled=True, max_level=2, error_px=1e4)
    prev = tuple(int(x) for x in rng.integers(0, 3, nb))
    for p in (None, prev):
        lv = lodm.select_levels(live, lo, hi, edges, cfg=cfg, prev=p,
                                **_plan_kw(eye=(0, 0, 50.0)))
        for i in range(nb):
            straddles = any(lo[i] - cfg.tf_edge_eps < e
                            < hi[i] + cfg.tf_edge_eps for e in edges)
            if straddles:
                assert lv[i] == 0, (i, lo[i], hi[i])


def test_select_levels_hysteresis_coarsens_one_level_per_replan():
    nb = 8
    live = np.ones(nb)
    lo = np.full(nb, 0.3)
    hi = np.full(nb, 0.4)
    cfg = LODConfig(enabled=True, max_level=2, error_px=1e4,
                    coarsen_empty=False, hysteresis=0.2)
    kw = _plan_kw(eye=(0, 0, 50.0))
    edges = opacity_edges(_tf())
    lv0 = lodm.select_levels(live, lo, hi, edges, cfg=cfg, prev=(0,) * nb,
                             **kw)
    assert lv0 == (1,) * nb                        # one step, not two
    lv1 = lodm.select_levels(live, lo, hi, edges, cfg=cfg, prev=lv0, **kw)
    assert lv1 == (2,) * nb
    # refinement is immediate: a near camera snaps straight to 0
    tight = LODConfig(enabled=True, max_level=2, error_px=0.01,
                      coarsen_empty=False, hysteresis=0.2)
    lv2 = lodm.select_levels(live, lo, hi, edges, cfg=tight, prev=lv1,
                             **_plan_kw(eye=(0, 0, 2.5)))
    assert lv2 == (0,) * nb


def test_level_work_scale_and_modeled_flops():
    dims = (HW, HW, D)
    zeros = (0,) * 8
    np.testing.assert_allclose(lodm.level_work_scale(zeros, dims, 32, 32),
                               np.ones(8))
    mixed = (0, 1, 2, 0, 0, 0, 0, 0)
    sc = lodm.level_work_scale(mixed, dims, 32, 32)
    assert sc[0] == 1.0 and sc[1] < 1.0 and sc[2] < sc[1]
    f_exact = lodm.modeled_march_flops(zeros, dims, 32, 32)
    f_lod = lodm.modeled_march_flops(mixed, dims, 32, 32)
    assert 0 < f_lod < f_exact
    # the headline ratio the bench reports is exact/lod
    assert f_exact / lodm.modeled_march_flops((2,) * 8, dims, 32, 32) > 8


# ------------------------------------------------------ pooled reslab


def test_reslab_bricks_lod_pools_and_halos():
    mesh = make_mesh(N)
    rng = np.random.default_rng(7)
    data = rng.uniform(0, 1, (D, 8, 8)).astype(np.float32)
    sdata = shard_volume(jnp.asarray(data), mesh)
    lv = (0, 1, 0, 2, 0, 1, 0, 0)
    bm = bk.BrickMap(D, N, ISLANDS, lv)
    from jax.sharding import PartitionSpec as P

    f = jax.jit(shard_map(
        lambda x: reslab_bricks_lod(x, bm, "ranks", h=1), mesh=mesh,
        in_specs=P("ranks", None, None),
        out_specs={l: P("ranks", None, None, None)
                   for l in bm.levels_present()}, check_vma=False))
    out = {l: np.asarray(v) for l, v in f(sdata).items()}
    bz = bm.brick_depth
    for lvl in bm.levels_present():
        fct = 1 << lvl
        table = bm.start_table_at(lvl)
        slots = table.shape[1]
        got = out[lvl].reshape(N, slots, bz // fct + 2, 8 // fct,
                               8 // fct)
        for r in range(N):
            for s in range(slots):
                st = table[r, s]
                if st < 0:
                    assert (got[r, s] == 0).all()
                    continue
                rows = np.clip(np.arange(st - fct, st + bz + fct), 0,
                               D - 1)
                fine = data[rows]
                ref = fine.reshape(bz // fct + 2, fct, 8 // fct, fct,
                                   8 // fct, fct).mean(axis=(1, 3, 5))
                np.testing.assert_allclose(got[r, s], ref, atol=1e-6)


def test_reslab_bricks_lod_rejects_non_dividing_plane():
    mesh = make_mesh(N)
    data = shard_volume(jnp.zeros((D, 6, 6)), mesh)   # 6 % 4 != 0
    bm = bk.BrickMap(D, N, ISLANDS, (2,) + (0,) * 7)
    from jax.sharding import PartitionSpec as P

    with pytest.raises(ValueError, match="lod.max_level"):
        jax.jit(shard_map(
            lambda x: reslab_bricks_lod(x, bm, "ranks"), mesh=mesh,
            in_specs=P("ranks", None, None),
            out_specs={l: P("ranks", None, None, None)
                       for l in bm.levels_present()},
            check_vma=False))(data)


# ------------------------------------------------- march parity + quality


def test_level0_lod_map_bitwise_parity_gather_and_mxu():
    """The CI parity gate: a BrickMap carrying an EXPLICIT all-level-0
    tuple is the pre-LOD brick path — bitwise on the gather builder,
    bitwise on the MXU builder (both resolve to the identical build)."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    bm = bk.BrickMap(D, N, OWNER)
    bm0 = bk.BrickMap(D, N, OWNER, (0,) * 8)
    assert bm0.max_level == 0 and bm0 == bm

    vc, cc = _cfgs()
    g = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc, max_steps=48,
                             bricks=bm)(sdata, origin, spacing, cam)
    vc, cc = _cfgs()
    g0 = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc, max_steps=48,
                              bricks=bm0)(sdata, origin, spacing, cam)
    np.testing.assert_array_equal(np.asarray(g.color), np.asarray(g0.color))
    np.testing.assert_array_equal(np.asarray(g.depth), np.asarray(g0.depth))

    spec = _mxu_spec(cam)
    vc, cc = _cfgs()
    m, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc, bricks=bm)(
        sdata, origin, spacing, cam)
    vc, cc = _cfgs()
    m0, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                     bricks=bm0)(sdata, origin, spacing,
                                                 cam)
    np.testing.assert_array_equal(np.asarray(m.color), np.asarray(m0.color))
    np.testing.assert_array_equal(np.asarray(m.depth), np.asarray(m0.depth))


def test_mxu_coarse_empty_bricks_match_even():
    """Coarsening an EMPTY brick is exact: the mixed-level frame equals
    the even frame at the MXU gate (pooled air is air; a dead brick
    emits nothing at any level)."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    vc, cc = _cfgs(rebalance="even")
    even, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc)(
        sdata, origin, spacing, cam)
    lv = (0, 0, 0, 0, 0, 0, 2, 0)                  # brick 6 is empty
    vc, cc = _cfgs()
    v, _ = distributed_vdi_step_mxu(
        mesh, _tf(), spec, vc, cc,
        bricks=bk.BrickMap(D, N, OWNER, lv))(sdata, origin, spacing, cam)
    _assert_vdi_close((v.color, v.depth), (even.color, even.depth))


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z
                                 (3.8, 0.3, 0.6)])   # march axis x
def test_mxu_coarse_smooth_field_psnr_floor(eye):
    """Uniform level-1 on a smooth field: the coarse march (pooled
    volume + dwm*2 + step_scale=1/2) holds a PSNR floor against the
    exact frame on both march axes. The committed bench ladder carries
    the quantitative claim; this guards the machinery."""
    data, origin, spacing = _smooth_scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam(eye)
    spec = _mxu_spec(cam)
    vc, cc = _cfgs(rebalance="even")
    even, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc)(
        sdata, origin, spacing, cam)
    vc, cc = _cfgs()
    v, _ = distributed_vdi_step_mxu(
        mesh, _tf(), spec, vc, cc,
        bricks=bk.BrickMap(D, N, tuple(range(N)), (1,) * 8))(
        sdata, origin, spacing, cam)
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view

    fe = render_vdi_same_view(even)
    fl = render_vdi_same_view(v)
    psnr = _psnr(np.asarray(fe), np.asarray(fl))
    assert psnr > 28.0, psnr


def test_mxu_waves_zero_brick_rank_lod():
    """Satellite: a rank owning ZERO bricks runs end-to-end through the
    WAVES builder — with and without coarse levels — and matches the
    frame schedule."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    for lv in (None, (0, 0, 0, 0, 0, 0, 2, 0)):
        bm = (bk.BrickMap(D, N, ISLANDS) if lv is None
              else bk.BrickMap(D, N, ISLANDS, lv))
        vc, cc = _cfgs()
        base, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                           bricks=bm)(
            sdata, origin, spacing, cam)
        vc, cc = _cfgs(schedule="waves", wave_tiles=2)
        w, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                        bricks=bm)(
            sdata, origin, spacing, cam)
        _assert_vdi_close((w.color, w.depth), (base.color, base.depth))


def test_gather_lod_map_renders_fine_and_ledgers():
    """The gather engine has no coarse march: a leveled map renders at
    level 0 (equal to the unleveled brick frame) and says so on the
    lod.engine ledger."""
    from scenery_insitu_tpu import obs

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    obs.clear_ledger()
    vc, cc = _cfgs()
    base = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc, max_steps=48,
                                bricks=bk.BrickMap(D, N, OWNER))(
        sdata, origin, spacing, cam)
    vc, cc = _cfgs()
    v = distributed_vdi_step(
        mesh, _tf(), HW, HW, vc, cc, max_steps=48,
        bricks=bk.BrickMap(D, N, OWNER, (0, 0, 0, 0, 0, 0, 2, 0)))(
        sdata, origin, spacing, cam)
    np.testing.assert_array_equal(np.asarray(base.color),
                                  np.asarray(v.color))
    np.testing.assert_array_equal(np.asarray(base.depth),
                                  np.asarray(v.depth))
    assert any(e["component"] == "lod.engine" for e in obs.ledger())


# -------------------------------------------------------------- session


class _SkewedSim:
    kind = "skewed"

    def __init__(self):
        data = np.zeros((D, HW, HW), np.float32)
        data[1:8] = 0.6
        self._f = jnp.asarray(data)

    def advance(self, n):
        pass

    @property
    def field(self):
        return self._f


def _lod_session(**extra):
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "composite.rebalance=bricks", "composite.rebalance_period=2",
        "composite.rebalance_bricks=8", "render.width=32",
        "render.height=32", "slicer.engine=mxu",
        "slicer.matmul_dtype=f32", "obs.enabled=true",
        "lod.enabled=true", "lod.error_px=1000", *extra.pop("over", []))
    return InSituSession(cfg, sim=_SkewedSim(), **extra)


def test_session_lod_replan_assigns_levels_and_renders():
    """e2e: lod.enabled + rebalance="bricks" — the replan fetches live
    + range profiles, assigns coarse levels to the empty bricks (the
    huge error_px admits coarsening everywhere the TF gate allows),
    recompiles keyed on the level tuple, and keeps rendering."""
    sess = _lod_session()
    out = None
    for _ in range(5):
        out = sess.render_frame()
    jax.block_until_ready(out)
    assert sess._bricks is not None
    assert max(sess._bricks.level) > 0
    # content bricks straddle the 0.05 ramp edge (range 0..0.6) -> fine
    assert sess._bricks.level[0] == 0
    ev = [e for e in sess.obs.events if e.get("name") == "rebalance_plan"]
    assert ev and max(ev[-1]["attrs"]["level"]) > 0


def test_session_lod_tf_straddle_after_steered_update():
    """Scenario-zoo path: a steered TF update moves the opacity edges;
    the very next replan re-runs the gate under the NEW TF (the update
    invalidates the plan clock), so bricks now straddling an edge are
    back at level 0 before the next marched frame."""
    sess = _lod_session()
    for _ in range(3):
        sess.render_frame()
    assert max(sess._bricks.level) > 0
    # new TF: opacity feature at 0.0..0.01 only — the 0.6 blobs go
    # transparent, their bricks' ranges [0, 0.6] straddle 0.01
    sess._apply_tf_message({
        "type": "tf",
        "points": [[0.0, 0.8], [0.01, 0.0], [1.0, 0.0]]})
    assert sess._plan_frame is None                # forced replan
    out = sess.render_frame()
    jax.block_until_ready(out)
    edges = opacity_edges(sess.tf)
    lo, hi = sess._replan_ranges()
    lo_b = lodm.per_brick(lo, sess._bricks.nbricks, "min")
    hi_b = lodm.per_brick(hi, sess._bricks.nbricks, "max")
    for i, lvl in enumerate(sess._bricks.level):
        straddles = any(lo_b[i] - 1e-4 < e < hi_b[i] + 1e-4
                        for e in edges)
        if straddles:
            assert lvl == 0, (i, lo_b[i], hi_b[i], edges)


def test_session_lod_inert_without_bricks_ledger():
    """lod.enabled without rebalance="bricks" has nothing to carry
    levels — the knob ledgers inert instead of silently rendering
    level 0."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    obs.clear_ledger()
    cfg = FrameworkConfig().with_overrides(
        "lod.enabled=true", "render.width=32", "render.height=32",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32")
    sess = InSituSession(cfg, sim=_SkewedSim())
    jax.block_until_ready(sess.render_frame())
    assert any(e["component"] == "lod.inert" for e in obs.ledger())
    assert sess._bricks is None
