import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.core.transfer import (TransferFunction, colormap_lut,
                                              for_dataset)


def test_ramp_endpoints():
    tf = TransferFunction.ramp(0.2, 0.8, max_alpha=0.5)
    _, a0 = tf(jnp.array(0.1))
    _, a1 = tf(jnp.array(0.9))
    _, amid = tf(jnp.array(0.5))
    assert float(a0) < 1e-3
    assert np.isclose(float(a1), 0.5, atol=1e-2)
    assert np.isclose(float(amid), 0.25, atol=1e-2)


def test_points_interpolation():
    tf = TransferFunction.points([(0.0, 0.0), (0.5, 1.0), (1.0, 0.0)])
    _, a = tf(jnp.array([0.25, 0.5, 0.75]))
    assert np.allclose(np.asarray(a), [0.5, 1.0, 0.5], atol=2e-2)


def test_colormaps_shapes_and_range():
    for name in ["grays", "hot", "jet", "viridis"]:
        lut = colormap_lut(name)
        assert lut.shape == (256, 3)
        assert lut.min() >= 0.0 and lut.max() <= 1.0


def test_dataset_tfs_exist():
    for name in ["kingsnake", "beechnut", "simulation", "rayleigh_taylor",
                 "gray_scott", "unknown_falls_back"]:
        tf = for_dataset(name)
        rgb, a = tf(jnp.array(0.5))
        assert rgb.shape == (3,)


def test_batched_sampling():
    tf = TransferFunction.ramp(0.0, 1.0)
    rgb, a = tf(jnp.linspace(0, 1, 7).reshape(7, 1) * jnp.ones((7, 3)))
    assert rgb.shape == (7, 3, 3) and a.shape == (7, 3)
