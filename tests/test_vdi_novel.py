"""MXU-native novel-view VDI rendering (ops/vdi_novel.py; ≅ the reference's
EfficientVDIRaycast.comp client). Parity vs the portable gather renderer,
virtual-camera reconstruction from metadata, and regime guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.vdi_novel import (axis_camera_from_meta,
                                              render_vdi_mxu)
from scenery_insitu_tpu.ops.vdi_render import render_vdi
from scenery_insitu_tpu.utils.image import psnr

F32 = SliceMarchConfig(matmul_dtype="f32", scale=1.5)


@pytest.fixture(scope="module")
def fixture():
    vol = procedural_volume(32, kind="blobs", seed=3)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.1, 0.3, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape, F32)
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=8,
                                       adaptive_iters=3))
    return vol, cam0, spec, vdi, meta, axcam


@pytest.mark.parametrize("eye", [(0.1, 0.3, 2.8),        # same view
                                 (0.45, 0.55, 2.6),      # novel view
                                 (0.7, 0.8, 2.4)])       # stronger shift
def test_parity_vs_gather_renderer(fixture, eye):
    vol, cam0, spec, vdi, meta, axcam = fixture
    cam1 = Camera.create(eye, fov_y_deg=45.0, near=0.3, far=10.0)
    a = np.asarray(render_vdi_mxu(vdi, axcam, spec, cam1, 96, 80,
                                  num_slices=40))
    b = np.asarray(render_vdi(vdi, meta, cam1, 96, 80, steps=200))
    assert np.isfinite(a).all()
    p = psnr(a, b)
    assert p > 25.0, f"novel-view MXU diverges from gather ref: {p:.1f} dB"


def test_cross_regime_raises(fixture):
    vol, cam0, spec, vdi, meta, axcam = fixture
    cam_x = Camera.create((3.0, 0.1, 0.2), fov_y_deg=45.0)  # marches x
    with pytest.raises(ValueError, match="axis"):
        render_vdi_mxu(vdi, axcam, spec, cam_x, 64, 48)


def test_axis_camera_from_meta_roundtrip(fixture):
    """A reconstructed virtual camera must reproduce the stored one's
    geometry (stored/streamed VDIs ship only metadata)."""
    vol, cam0, spec, vdi, meta, axcam = fixture
    rec = axis_camera_from_meta(meta, spec)
    np.testing.assert_allclose(np.asarray(rec.eye_uvw),
                               np.asarray(axcam.eye_uvw), atol=1e-4)
    np.testing.assert_allclose(np.asarray(rec.u_grid),
                               np.asarray(axcam.u_grid), atol=1e-3)
    np.testing.assert_allclose(np.asarray(rec.v_grid),
                               np.asarray(axcam.v_grid), atol=1e-3)
    np.testing.assert_allclose(float(rec.zp), float(axcam.zp), atol=1e-4)
    np.testing.assert_allclose(float(rec.w0), float(axcam.w0), atol=1e-3)
    np.testing.assert_allclose(float(rec.dwm), float(axcam.dwm), atol=1e-5)


def test_render_from_reconstructed_camera(fixture):
    """End-to-end: render a novel view using ONLY (vdi, meta, spec) — the
    streamed-VDI client scenario."""
    vol, cam0, spec, vdi, meta, axcam = fixture
    rec = axis_camera_from_meta(meta, spec)
    cam1 = Camera.create((0.4, 0.5, 2.65), fov_y_deg=45.0,
                         near=0.3, far=10.0)
    a = np.asarray(render_vdi_mxu(vdi, rec, spec, cam1, 96, 80,
                                  num_slices=40))
    b = np.asarray(render_vdi_mxu(vdi, axcam, spec, cam1, 96, 80,
                                  num_slices=40))
    p = psnr(a, b)
    assert p > 40.0, f"reconstructed-camera render diverges: {p:.1f} dB"


def test_axis_camera_from_meta_anisotropic():
    """The reconstructed slice pitch must be the MARCH AXIS spacing, not
    min(spacing) — anisotropic volumes march at spacing[axis]."""
    from scenery_insitu_tpu.core.volume import Volume

    data = jnp.asarray(np.random.default_rng(0).random((16, 24, 24)),
                       jnp.float32)
    # z voxels twice as thick as x/y
    vol = Volume.create(data, origin=(-1, -1, -1),
                        spacing=(2 / 24, 2 / 24, 2 / 12))
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam0, data.shape, F32)
    assert spec.axis == 2
    _, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=4,
                                       adaptive_iters=1))
    rec = axis_camera_from_meta(meta, spec)
    np.testing.assert_allclose(float(rec.dwm), float(axcam.dwm), atol=1e-6)
    np.testing.assert_allclose(float(rec.w0), float(axcam.w0), atol=1e-4)


def test_render_vdi_mxu_jits_with_traced_camera(fixture):
    """The axis_sign override must make the renderer traceable (bench path:
    a jitted orbiting camera)."""
    from scenery_insitu_tpu.core.camera import orbit
    vol, cam0, spec, vdi, meta, axcam = fixture
    regime = slicer.choose_axis(cam0)
    f = jax.jit(lambda yaw: render_vdi_mxu(
        vdi, axcam, spec, orbit(cam0, yaw), 48, 40, num_slices=16,
        axis_sign=regime))
    out = f(jnp.float32(0.05))
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("eye", [(2.6, 0.2, 0.3),        # march axis x
                                 (0.2, -2.7, 0.3)])      # march axis y
def test_cross_regime_via_proxy_volume(fixture, eye):
    """render_vdi_any on a view that marches a DIFFERENT axis than the
    generating camera: VDI -> pre-shaded RGBA proxy volume -> ordinary
    slice march. Parity vs the portable gather renderer on the same VDI."""
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_any

    vol, cam0, spec, vdi, meta, axcam = fixture
    cam1 = Camera.create(eye, fov_y_deg=45.0, near=0.3, far=10.0)
    assert slicer.choose_axis(cam1)[0] != spec.axis
    img = render_vdi_any(vdi, axcam, spec, cam1, 80, 64,
                         num_slices=vol.data.shape[0])
    ref = render_vdi(vdi, meta, cam1, 80, 64, steps=128)
    assert np.isfinite(np.asarray(img)).all()
    q = psnr(np.asarray(ref), np.asarray(img))
    assert q > 24.0, f"PSNR {q:.1f} dB at eye {eye}"


def test_render_vdi_any_same_regime_uses_plane_sweep(fixture):
    vol, cam0, spec, vdi, meta, axcam = fixture
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_any

    cam1 = Camera.create((0.3, 0.4, 2.7), fov_y_deg=45.0, near=0.3,
                         far=10.0)
    a = render_vdi_any(vdi, axcam, spec, cam1, 64, 48,
                       num_slices=vol.data.shape[0])
    b = render_vdi_mxu(vdi, axcam, spec, cam1, 64, 48,
                       num_slices=vol.data.shape[0])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_proxy_volume_same_view_roundtrip(fixture):
    """The proxy volume rendered from the GENERATING camera reproduces the
    VDI's own same-view decode (sanity of layout, origin, alpha coding)."""
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.ops.vdi_novel import vdi_to_rgba_volume

    vol, cam0, spec, vdi, meta, axcam = fixture
    proxy = vdi_to_rgba_volume(vdi, axcam, spec,
                               num_slices=vol.data.shape[0])
    assert proxy.data.ndim == 4 and proxy.data.shape[0] == 4
    spec_new = slicer.make_spec(cam0, proxy.data.shape[-3:], F32)
    out = slicer.raycast_mxu(proxy, None, cam0, 64, 48, spec_new)
    ref_int = render_vdi_same_view(vdi)     # intermediate-grid decode
    ref = slicer.warp_to_camera(ref_int, axcam, spec, cam0, 64, 48)
    q = psnr(np.asarray(ref), np.asarray(out.image))
    assert q > 24.0, f"PSNR {q:.1f} dB"


@pytest.mark.parametrize("gen_eye,new_eye,gen_axis", [
    ((2.8, 0.2, 0.3), (0.1, 0.3, 2.7), 0),  # generate along x, view z
    ((0.2, 2.8, 0.3), (2.7, 0.2, 0.3), 1),  # generate along y, view x
])
def test_cross_regime_other_generating_axes(gen_eye, new_eye, gen_axis):
    """The proxy builder's (w, v, u) -> (z, y, x) arrangement branches for
    x- and y-axis generating cameras (the module fixture only generates
    along z)."""
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_any

    vol = procedural_volume(32, kind="blobs", seed=3)
    tf = for_dataset("procedural")
    cam0 = Camera.create(gen_eye, fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape, F32)
    assert spec.axis == gen_axis      # pins the transpose branch under test
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=8,
                                       adaptive_iters=3))
    cam1 = Camera.create(new_eye, fov_y_deg=45.0, near=0.3, far=10.0)
    assert slicer.choose_axis(cam1)[0] != spec.axis
    img = render_vdi_any(vdi, axcam, spec, cam1, 64, 48,
                         num_slices=vol.data.shape[0])
    ref = render_vdi(vdi, meta, cam1, 64, 48, steps=128)
    assert np.isfinite(np.asarray(img)).all()
    q = psnr(np.asarray(ref), np.asarray(img))
    assert q > 24.0, f"PSNR {q:.1f} dB (gen {gen_eye} -> view {new_eye})"


# ------------------------------------------------- exact renderer (round 5)


def test_exact_is_the_limit_of_the_sampled_renderer(fixture):
    """render_vdi_exact computes closed-form in-slab path lengths (≅
    intersectSupersegment, EfficientVDIRaycast.comp:274-450). The sampled
    gather renderer converges to it as steps grow — agreement must be
    high AND monotonically improving, which pins exactness rather than
    mere similarity."""
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_exact

    vol, cam0, spec, vdi, meta, axcam = fixture
    cam1 = Camera.create((0.45, 0.55, 2.6), fov_y_deg=45.0, near=0.3,
                         far=10.0)
    a = np.asarray(render_vdi_exact(vdi, axcam, spec, cam1, 96, 80))
    assert np.isfinite(a).all()
    ps = [psnr(a, np.asarray(render_vdi(vdi, meta, cam1, 96, 80, steps=s)))
          for s in (150, 600, 2400)]
    assert ps[0] < ps[1] < ps[2], f"no convergence toward exact: {ps}"
    assert ps[2] > 55.0, f"sampled ref converges elsewhere: {ps[2]:.1f} dB"


def test_exact_cross_regime(fixture):
    """The exact renderer needs no regime: a view marching x against a
    z-generated VDI still agrees with the high-step sampled reference."""
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_exact

    vol, cam0, spec, vdi, meta, axcam = fixture
    cam_x = Camera.create((3.0, 0.4, 0.5), fov_y_deg=45.0, near=0.3,
                          far=10.0)
    a = np.asarray(render_vdi_exact(vdi, axcam, spec, cam_x, 80, 64))
    b = np.asarray(render_vdi(vdi, meta, cam_x, 80, 64, steps=1800))
    p = psnr(a, b)
    assert np.isfinite(a).all() and a.max() > 0.1
    assert p > 45.0, f"cross-regime exact diverges from sampled ref: {p}"


def test_exact_uniform_slab_analytic(fixture):
    """A synthetic VDI whose every pixel holds ONE slab of alpha A over
    [len0, 1.2·len0]: a ray from the generating eye traverses exactly its
    own full slab, so the rendered alpha at interior pixels is A — a
    hand-computable exactness check with no reference renderer at all."""
    from scenery_insitu_tpu.core.vdi import VDI as VDI_t
    from scenery_insitu_tpu.ops.vdi_novel import render_vdi_exact

    vol, cam0, spec, vdi, meta, axcam = fixture
    nj, ni = spec.nj, spec.ni
    k = 4
    A = 0.625
    len0 = np.asarray(axcam.ray_lengths())
    starts = np.full((k, nj, ni), np.inf, np.float32)
    ends = np.full((k, nj, ni), -np.inf, np.float32)
    starts[0] = len0 * 1.0
    ends[0] = len0 * 1.2
    color = np.zeros((k, 4, nj, ni), np.float32)
    color[0, 0] = 0.8 * A                       # premultiplied red
    color[0, 3] = A
    synth = VDI_t(jnp.asarray(color),
                  jnp.asarray(np.stack([starts, ends], axis=1)))
    img = np.asarray(render_vdi_exact(synth, axcam, spec, cam0, 96, 80))
    inner = img[3, 30:50, 38:58]                # interior block
    np.testing.assert_allclose(inner, A, atol=0.02)
    np.testing.assert_allclose(img[0, 30:50, 38:58] / inner, 0.8,
                               atol=0.02)


def test_render_vdi_any_exact_route(fixture):
    from scenery_insitu_tpu.ops.vdi_novel import (render_vdi_any,
                                                  render_vdi_exact)

    vol, cam0, spec, vdi, meta, axcam = fixture
    cam_x = Camera.create((3.0, 0.4, 0.5), fov_y_deg=45.0, near=0.3,
                          far=10.0)
    a = render_vdi_any(vdi, axcam, spec, cam_x, 48, 40, exact=True)
    b = render_vdi_exact(vdi, axcam, spec, cam_x, 48, 40)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_proxy_error_bound_vs_exact(fixture):
    """The proxy-volume cross-regime path carries a STATED error bound
    against the exact renderer (docs/NOVEL_VIEW.md table): pin the
    floor of that table here so a regression in either path shows."""
    from scenery_insitu_tpu.ops.vdi_novel import (render_vdi_any,
                                                  render_vdi_exact)

    vol, cam0, spec, vdi, meta, axcam = fixture
    cam_x = Camera.create((3.0, 0.4, 0.5), fov_y_deg=45.0, near=0.3,
                          far=10.0)
    ex = np.asarray(render_vdi_exact(vdi, axcam, spec, cam_x, 80, 64))
    pr = np.asarray(render_vdi_any(vdi, axcam, spec, cam_x, 80, 64,
                                   num_slices=vol.data.shape[0]))
    p = psnr(pr, ex)
    assert p > 24.0, f"proxy fell below its documented bound: {p:.1f} dB"
