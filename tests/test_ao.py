"""Ambient occlusion (ops/ao.py — the working version of the reference's
inactive AO scaffolding, ComputeRaycast.comp:147-191)."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import RenderConfig, SliceMarchConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import ao, slicer
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.utils.image import psnr


def test_box_blur_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.random((5, 7, 9)).astype(np.float32)
    r = 2
    got = np.asarray(ao._box_blur_1d(jnp.asarray(x), r, 1))
    xp = np.pad(x, ((0, 0), (r, r), (0, 0)), mode="edge")
    want = np.stack([xp[:, i:i + 2 * r + 1].mean(axis=1)
                     for i in range(x.shape[1])], axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_occlusion_field_shape_and_range():
    alpha = jnp.zeros((8, 8, 8)).at[2:6, 2:6, 2:6].set(1.0)
    occ = np.asarray(ao.occlusion_field(alpha, radius=2, strength=1.0))
    assert occ.shape == (8, 8, 8)
    assert occ.min() >= 0.0 and occ.max() <= 0.85
    # the block center is more occluded than the far corner
    assert occ[4, 4, 4] > occ[0, 0, 0]
    # empty volume -> zero occlusion
    assert float(np.asarray(
        ao.occlusion_field(jnp.zeros((8, 8, 8)))).max()) == 0.0


@pytest.fixture(scope="module")
def scene():
    vol = procedural_volume(32, kind="blobs", seed=5)
    tf = for_dataset("procedural")
    cam = Camera.create((0.4, 0.7, 2.6), fov_y_deg=50.0, near=0.3, far=20.0)
    return vol, tf, cam


def test_ao_darkens_gather_render(scene):
    vol, tf, cam = scene
    base = raycast(vol, tf, cam, 64, 48, RenderConfig(max_steps=64))
    aod = raycast(vol, tf, cam, 64, 48,
                  RenderConfig(max_steps=64, ao_strength=0.9, ao_radius=3))
    b, a = np.asarray(base.image), np.asarray(aod.image)
    # opacity untouched, rgb strictly darker where there is occlusion
    np.testing.assert_allclose(a[3], b[3], atol=1e-6)
    assert a[:3].sum() < b[:3].sum() * 0.98
    assert (a[:3] <= b[:3] + 1e-6).all()


def test_ao_mxu_preshaded_matches_gather(scene):
    """The MXU AO route (shade_volume_ao + pre-shaded march) agrees with
    the gather AO render (pre- vs post-classification: smooth TF keeps
    them close)."""
    vol, tf, cam = scene
    w, h = 64, 48
    r, s = 3, 0.8
    from scenery_insitu_tpu.ops.ao import ao_field_volume, shade_volume_ao

    g = raycast(vol, tf, cam, w, h,
                RenderConfig(max_steps=64, background=(1, 1, 1, 1)),
                ao_field=ao_field_volume(vol, tf, r, s))
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32"))
    m = slicer.raycast_mxu(shade_volume_ao(vol, tf, r, s), None, cam, w, h,
                           spec, background=(1, 1, 1, 1))
    q = psnr(np.asarray(g.image), np.asarray(m.image))
    assert q > 24.0, f"PSNR {q:.1f} dB"


def test_ao_off_is_identity(scene):
    vol, tf, cam = scene
    a = raycast(vol, tf, cam, 48, 32, RenderConfig(max_steps=48))
    b = raycast(vol, tf, cam, 48, 32,
                RenderConfig(max_steps=48, ao_strength=0.0))
    np.testing.assert_array_equal(np.asarray(a.image), np.asarray(b.image))


def test_distributed_ao_seam_exact_gather(scene):
    """Distributed plain render with AO (radius-deep halos) must match
    the single-device AO render — no banding at slab seams."""

    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                      shard_volume)

    vol, tf, cam = scene
    cfg = RenderConfig(width=64, height=48, max_steps=64,
                       ao_strength=0.9, ao_radius=3)
    ref = raycast(vol, tf, cam, 64, 48, cfg)

    mesh = make_mesh(4)
    step = distributed_plain_step(mesh, tf, 64, 48, cfg)
    img = np.asarray(step(shard_volume(vol.data, mesh), vol.origin,
                          vol.spacing, cam))
    # per-rank ray sampling differs from the single-device schedule (each
    # rank re-discretizes its own clip range — same as the non-AO path,
    # whose parity test bounds PSNR), so assert high PSNR + a tight
    # absolute cap rather than elementwise equality; a halo-less AO blur
    # would band the seams far beyond this
    assert psnr(np.asarray(ref.image), img) > 40.0
    assert np.abs(img - np.asarray(ref.image)).max() < 0.02


def test_distributed_ao_seam_exact_mxu(scene):
    """MXU plain mode with AO: per-rank pre-shading on radius-deep halos
    must reproduce the single-device pre-shaded AO march."""
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu, shard_volume)

    vol, tf, cam = scene
    radius, strength = 3, 0.9
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.0),
                            multiple_of=4)
    shaded = ao.shade_volume_ao(vol, tf, radius, strength)
    axcam = slicer.make_axis_camera(shaded, cam, spec)
    ref = slicer.render_slices(shaded, None, axcam, spec)

    mesh = make_mesh(4)
    cfg = RenderConfig(ao_strength=strength, ao_radius=radius)
    step = distributed_plain_step_mxu(mesh, tf, spec, cfg)
    img, _ = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing,
                  cam)
    np.testing.assert_allclose(np.asarray(img), np.asarray(ref.image),
                               rtol=1e-4, atol=2e-5)
