import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from scenery_insitu_tpu.sim import grayscott as gs


def test_grayscott_stays_bounded():
    st = gs.GrayScott.init((16, 16, 16), n_seeds=2)
    st = gs.multi_step(st, 50)
    u, v = np.asarray(st.u), np.asarray(st.v)
    assert np.isfinite(u).all() and np.isfinite(v).all()
    assert u.min() >= -0.1 and u.max() <= 1.5
    assert v.min() >= -0.1 and v.max() <= 1.5


def test_grayscott_develops_structure():
    st = gs.GrayScott.init((16, 16, 16), n_seeds=2)
    st2 = gs.multi_step(st, 100)
    # the v field must neither die out nor saturate
    v = np.asarray(st2.field)
    assert v.max() > 0.05
    assert v.std() > 1e-3


def test_grayscott_sharded_matches_single():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("ranks",))
    st = gs.GrayScott.init((16, 8, 8), n_seeds=2)
    ref = gs.multi_step(st, 20)
    shard = NamedSharding(mesh, P("ranks", None, None))
    sh = gs.GrayScott(jax.device_put(st.u, shard),
                      jax.device_put(st.v, shard), st.params)
    out = gs.multi_step(sh, 20)
    assert np.allclose(np.asarray(ref.v), np.asarray(out.v), atol=1e-5)


def test_graft_entry_single():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    color, depth, u, v = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(color)).all()
    assert np.isfinite(np.asarray(u)).all() and np.isfinite(np.asarray(v)).all()
    d = np.asarray(depth)
    live = np.asarray(color)[:, 3] > 0
    assert np.isfinite(d[:, 0][live]).all()  # empty slots are +inf by design


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(4)
    ge.dryrun_multichip(8)


def test_pallas_stencil_parity():
    """The fused Pallas Gray-Scott step (TPU fast path) must match the XLA
    roll formulation exactly (interpret mode on CPU)."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((8, 16, 128), n_seeds=2)
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    assert ps.pick_tz(st.u.shape) > 0
    u2, v2 = ps.step_pallas(st.u, st.v, pvec, interpret=True)
    ref = gs.step(st)
    np.testing.assert_allclose(np.asarray(ref.u), np.asarray(u2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ref.v), np.asarray(v2), atol=1e-6)


@pytest.mark.parametrize("t_steps", [2, 4])
def test_pallas_stencil_multistep_parity(t_steps):
    """T fused steps in one kernel pass ≡ T single XLA steps: the T-slice
    halo + shrinking-validity scheme must keep the central slab exact,
    including periodic wrap across the z seam."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 128), n_seeds=2)
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    assert ps.pick_tz(st.u.shape, t_steps) > 0
    u2, v2 = ps.step_pallas(st.u, st.v, pvec, t_steps, interpret=True)
    ref = st
    for _ in range(t_steps):
        ref = gs.step(ref)
    np.testing.assert_allclose(np.asarray(ref.u), np.asarray(u2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref.v), np.asarray(v2), atol=1e-5)


def test_pallas_multistep_remainder():
    """multi_step_pallas must advance exactly n steps for n not divisible
    by the preferred fusion factor."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 128), n_seeds=2)
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    u2, v2 = ps.multi_step_pallas(st.u, st.v, pvec, 6, interpret=True)
    ref = gs.multi_step(st, 6)
    np.testing.assert_allclose(np.asarray(ref.u), np.asarray(u2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref.v), np.asarray(v2), atol=1e-5)


def test_stencil_compile_probe_gates_fused_path():
    """fused_supported must reject (without raising) kernels the backend
    cannot compile: on CPU the Mosaic lowering of step_pallas fails, so
    _compile_ok catches and caches False — the degrade path a real-TPU
    VMEM budget miss takes."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    shape = (8, 8, 128)
    assert ps.pick_tz(shape) > 0
    ps._PROBE_CACHE.clear()
    assert ps._compile_ok(shape, 1) is False        # swallowed, not raised
    # cached (tz=0 = auto, ranges-epilogue variant off)
    assert ps._PROBE_CACHE[(shape, 1, 0, False)] is False
    # fused_supported skips the probe off-TPU (interpret mode is safe)
    assert ps.fused_supported(shape)
    ps._PROBE_CACHE.clear()


@pytest.mark.parametrize("t_steps", [2, 4])
def test_pallas_stencil_2d_multistep_parity(t_steps):
    """T fused steps of the 2D-blocked (z x h) kernel ≡ T single XLA
    steps — the square T-halo (edges + corners, periodic wrap in BOTH
    blocked axes via index_map arithmetic) must keep every central tile
    exact. The asymmetric grid makes a z/h axis swap impossible to miss."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 32, 128), n_seeds=3)
    p = st.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    cands = ps.tile2d_candidates(st.u.shape, t_steps)
    assert cands, "no 2D tile for the test grid"
    # exercise a non-trivial grid in both axes, not just the best tile
    tz, th = [c for c in cands if c[0] < 16 and c[1] < 32][0] \
        if any(c[0] < 16 and c[1] < 32 for c in cands) else cands[-1]
    u2, v2 = ps.step_pallas2d(st.u, st.v, pvec, t_steps, interpret=True,
                              tz=tz, th=th)
    ref = st
    for _ in range(t_steps):
        ref = gs.step(ref)
    np.testing.assert_allclose(np.asarray(ref.u), np.asarray(u2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref.v), np.asarray(v2), atol=1e-5)


def test_best_schedule_prefers_lower_traffic():
    """_best_schedule must rank 2D tiles above the 1D slab when the
    modeled per-step traffic is lower (the 512^3 regime), and fall back
    to 1D when no 2D tile exists."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    kind, tz, th = ps._best_schedule((512, 512, 512), 4, on_tpu=False)
    assert kind == "2d" and tz % 4 == 0 and th % 4 == 0
    # h=48 admits no th in (256,128,64,32): only the 1D slab remains
    sched = ps._best_schedule((64, 48, 128), 1, on_tpu=False)
    assert sched is not None and sched[0] == "1d"
