import glob

import numpy as np

from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.runtime.session import InSituSession, png_sink


def _cfg(**kw):
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8", "composite.adaptive_iters=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2", "runtime.stats_window=2")
    return cfg.with_overrides(*[f"{k}={v}" for k, v in kw.items()])


def test_session_vdi_loop(tmp_path):
    lines = []
    sess = InSituSession(_cfg(), mesh=make_mesh(4),
                         sinks=[png_sink(str(tmp_path))], log=lines.append)
    payload = sess.run(3)
    assert payload["frame"] == 2
    assert payload["vdi_color"].shape == (8, 4, 24, 32)
    assert np.isfinite(payload["vdi_color"]).all()
    assert len(glob.glob(str(tmp_path / "frame*.png"))) == 3
    assert sess.timers.stats["sim"].n == 3
    assert any("window of 2" in l for l in lines)


def test_session_plain_mode(tmp_path):
    cfg = _cfg(**{"runtime.generate_vdis": "false"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)


def test_session_vortex():
    cfg = _cfg(**{"sim.kind": "vortex"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    payload = sess.run(1)
    assert "vdi_color" in payload


def test_session_orbit_changes_camera():
    sess = InSituSession(_cfg(), mesh=make_mesh(2))
    sess.orbit_rate = 0.3
    eye0 = np.asarray(sess.camera.eye)
    sess.run(2)
    assert not np.allclose(eye0, np.asarray(sess.camera.eye))


def test_session_mxu_engine(tmp_path):
    """Session with the MXU slice-march engine: VDI frames on the virtual
    camera grid, metadata from the pipeline, engine cache per march regime."""
    from scenery_insitu_tpu.config import FrameworkConfig

    cfg = FrameworkConfig().with_overrides(
        "slicer.engine=mxu", "slicer.scale=1.0",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8", "mesh.num_devices=4")
    s = InSituSession(cfg)
    payload = s.run(3)
    assert s.engine == "mxu"
    assert payload["frame"] == 2
    assert payload["vdi_color"].ndim == 4
    ni = payload["vdi_color"].shape[-1]
    assert ni % 4 == 0                      # divisible by mesh size
    assert np.isfinite(payload["vdi_color"]).all()
    assert int(payload["meta"].index) == 2
    assert len(s._mxu_steps) == 1


def test_session_mxu_temporal(tmp_path):
    """Session with carried temporal threshold state on the distributed
    MXU pipeline: seeded on the first frame of a regime, threaded after."""
    from scenery_insitu_tpu.config import FrameworkConfig

    cfg = FrameworkConfig().with_overrides(
        "slicer.engine=mxu", "slicer.scale=1.0",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2",
        "vdi.max_supersegments=6", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=8", "mesh.num_devices=4")
    s = InSituSession(cfg)
    payload = s.run(3)
    assert np.isfinite(payload["vdi_color"]).all()
    assert len(s._mxu_thr) == 1             # one regime seeded
    thr = next(iter(s._mxu_thr.values()))
    assert np.isfinite(np.asarray(thr.thr)).all()


def test_session_prewarm_regimes():
    """prewarm_regimes precompiles per-regime steps without touching the
    loop's own state: camera, sim frame index and temporal thresholds all
    restored; a later run() finds its regime already cached."""
    from scenery_insitu_tpu.config import FrameworkConfig

    cfg = FrameworkConfig().with_overrides(
        "slicer.engine=mxu", "slicer.scale=1.0",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2",
        "vdi.max_supersegments=6", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=8", "mesh.num_devices=4")
    s = InSituSession(cfg)
    eye0 = np.asarray(s.camera.eye).copy()
    start_regime = s._slicer.choose_axis(s.camera)
    times = s.prewarm_regimes(regimes=[start_regime, (0, -1)])
    assert set(times) == {start_regime, (0, -1)}
    assert all(t >= 0 for t in times.values())
    assert len(s._mxu_steps) == 2           # both regimes compiled
    assert s._mxu_thr == {}                 # threshold state untouched
    assert s.frame_index == 0               # no frames consumed
    assert np.allclose(eye0, np.asarray(s.camera.eye))
    # the first real frames run in start_regime: must reuse the
    # prewarmed step, not compile a third entry
    payload = s.run(2)
    assert np.isfinite(payload["vdi_color"]).all()
    assert len(s._mxu_steps) == 2           # nothing new compiled


def test_session_prewarm_noop_modes():
    """Engines/modes without per-regime jit return {} untouched."""
    sess = InSituSession(_cfg(), mesh=make_mesh(2))   # gather engine on CPU
    assert sess.prewarm_regimes() == {}


def test_session_particle_mode():
    cfg = _cfg(**{"sim.kind": "lennard_jones", "sim.num_particles": 64,
                  "sim.particle_radius": 0.3})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)
    assert payload["depth"].shape == (24, 32)
    assert np.isfinite(payload["image"]).all()


def test_session_sho_mode():
    cfg = _cfg(**{"sim.kind": "sho", "sim.num_particles": 32,
                  "sim.particle_radius": 0.05})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)


def test_session_hybrid_mode():
    cfg = _cfg(**{"sim.kind": "hybrid", "sim.num_particles": 64,
                  "sim.particle_radius": 0.8,
                  "slicer.engine": "mxu", "slicer.matmul_dtype": "f32"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)
    assert np.isfinite(payload["image"]).all()


def test_bad_env_override_raises(monkeypatch):
    monkeypatch.setenv("SITPU_RENDER_WIDHT", "512")     # typo'd key
    try:
        FrameworkConfig.load()
        raise AssertionError("typo'd SITPU_* key must raise")
    except ValueError as e:
        assert "WIDHT" in str(e)


def test_env_override_applies(monkeypatch):
    monkeypatch.setenv("SITPU_RENDER_WIDTH", "512")
    assert FrameworkConfig.load().render.width == 512


def test_session_profile_trace(tmp_path):
    cfg = _cfg()
    sess = InSituSession(cfg, mesh=make_mesh(2))
    out = sess.run(2, profile_dir=str(tmp_path / "trace"))
    assert out
    import glob as _glob
    assert _glob.glob(str(tmp_path / "trace" / "**" / "*.xplane.pb"),
                      recursive=True)


def test_session_soak_state_bounded():
    """60-frame soak with an orbiting camera crossing march regimes:
    caches stay bounded, threshold state tracks the live regimes only,
    output stays finite (guards against stateful leaks in the temporal /
    compiled-step caches over long runs)."""
    from scenery_insitu_tpu.config import FrameworkConfig

    cfg = FrameworkConfig().with_overrides(
        "slicer.engine=mxu", "slicer.scale=1.0",
        "sim.grid=[12,12,12]", "sim.steps_per_frame=1",
        "vdi.max_supersegments=4", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=4", "mesh.num_devices=2")
    s = InSituSession(cfg)
    s.orbit_rate = 0.12        # ~57 frames per revolution: crosses regimes
    payload = s.run(60)
    assert np.isfinite(payload["vdi_color"]).all()
    # 4 regimes visited at most around one orbit in a horizontal plane
    assert len(s._mxu_steps) <= 4
    assert len(s._mxu_thr) <= 4
    assert len(s._pending_meta) <= 2   # metadata snapshots are drained


def test_session_plain_mxu_mode():
    """Plain-image session on the slice-march engine: mode 'plain' no
    longer routes the MXU engine through the gather raycaster."""
    cfg = _cfg(**{"runtime.generate_vdis": "false",
                  "slicer.engine": "mxu", "slicer.matmul_dtype": "f32"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    assert sess.mode == "plain" and sess.engine == "mxu"
    assert sess._step is None           # per-regime MXU steps, not gather
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)
    assert np.isfinite(payload["image"]).all()


def test_session_hybrid_temporal_mode():
    """Hybrid session with temporal thresholds: accepted (round 2 rejected
    it), carries per-regime threshold state, 1 march/frame."""
    cfg = _cfg(**{"sim.kind": "hybrid", "sim.num_particles": 64,
                  "sim.particle_radius": 0.8,
                  "slicer.engine": "mxu", "slicer.matmul_dtype": "f32",
                  "vdi.adaptive_mode": "temporal"})
    sess = InSituSession(cfg, mesh=make_mesh(2))
    assert sess._temporal
    payload = sess.run(3)
    assert payload["image"].shape == (4, 24, 32)
    assert np.isfinite(payload["image"]).all()
    assert any(k[0] == "hybrid" for k in sess._mxu_thr)


def test_session_pending_meta_bounded_headless():
    """run(fetch=False) must hold constant memory: the metadata snapshot
    dict is bounded even though nothing ever fetches/pops it."""
    sess = InSituSession(_cfg(), mesh=make_mesh(2))
    sess.run(6, fetch=False)
    assert len(sess._pending_meta) <= 2


def test_session_prewarm_covers_orbit_crossing():
    """The verdict-8 'done' criterion, compile-count form: an orbit that
    CROSSES march regimes mid-run must find every step prewarmed — zero
    new compilations after startup (on hardware that is the 10-24 s
    mid-orbit stall; on CPU the cache count is the compile-free proxy)."""
    from scenery_insitu_tpu.config import FrameworkConfig

    cfg = FrameworkConfig().with_overrides(
        "slicer.engine=mxu", "slicer.scale=1.0",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "vdi.max_supersegments=4", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=6", "mesh.num_devices=2")
    s = InSituSession(cfg, mesh=make_mesh(2))
    times = s.prewarm_regimes()
    assert len(times) == 6
    n_steps = len(s._mxu_steps)
    assert n_steps == 6
    # ~0.6 rad/frame crosses at least one regime boundary within 6 frames
    s.orbit_rate = 0.6
    payload = s.run(6)
    assert np.isfinite(payload["vdi_color"]).all()
    # the premise must actually hold: temporal mode seeds one threshold
    # entry per VISITED regime, so >= 2 proves the orbit really crossed
    assert len(s._mxu_thr) >= 2
    assert len(s._mxu_steps) == n_steps     # nothing compiled mid-orbit
