"""VDI generation tests: invariants + render-parity against the plain
raycaster (the numeric-parity tests SURVEY.md §4 notes the reference lacks)."""

import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import RenderConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi, occupancy_grid
from scenery_insitu_tpu.utils.image import psnr

W = H = 16
STEPS = 48


def _cam():
    return Camera.create((0.0, 0.0, 4.0), fov_y_deg=50.0, near=0.5, far=20.0)


def test_constant_volume_single_segment():
    vol = Volume.centered(jnp.ones((8, 8, 8)), extent=1.0)
    tf = TransferFunction.ramp(-1.0, 0.0, 0.4)   # constant alpha
    vdi, meta = generate_vdi(vol, tf, _cam(), W, H,
                             VDIConfig(adaptive=False, threshold=0.5),
                             max_steps=STEPS)
    count = np.asarray(vdi.count)
    center = count[H // 2, W // 2]
    assert center == 1
    d = np.asarray(vdi.depth)[0, :, H // 2, W // 2]
    assert abs(d[0] - 3.5) < 0.05 and abs(d[1] - 4.5) < 0.05


def test_vdi_invariants():
    vol = procedural_volume(12, kind="blobs")
    tf = TransferFunction.ramp(0.1, 0.9, 0.6)
    vdi, _ = generate_vdi(vol, tf, _cam(), W, H,
                          VDIConfig(max_supersegments=8), max_steps=STEPS)
    c = np.asarray(vdi.color)
    d = np.asarray(vdi.depth)
    live = c[:, 3] > 0
    # live slots have finite ordered depths
    assert np.all(np.isfinite(d[:, 0][live]))
    assert np.all(d[:, 1][live] >= d[:, 0][live] - 1e-5)
    # live slots are contiguous from the front and depth-sorted
    for i in range(H):
        for j in range(W):
            ks = np.where(live[:, i, j])[0]
            if len(ks):
                assert ks.max() == len(ks) - 1
                starts = d[ks, 0, i, j]
                assert (np.diff(starts) >= -1e-5).all()
    # empty slots are identically empty
    assert np.all(c * ~live[:, None] == 0)


def test_render_parity_with_raycast():
    vol = procedural_volume(12, kind="shell")
    tf = TransferFunction.ramp(0.05, 0.8, 0.7)
    cam = _cam()
    rc_cfg = RenderConfig(max_steps=STEPS, early_exit_alpha=1.1)
    ref = np.asarray(raycast(vol, tf, cam, W, H, rc_cfg).image)
    vdi, _ = generate_vdi(vol, tf, cam, W, H,
                          VDIConfig(max_supersegments=16, adaptive=True,
                                    adaptive_iters=6), max_steps=STEPS)
    img = np.asarray(render_vdi_same_view(vdi))
    assert psnr(ref, img) > 30.0, psnr(ref, img)


def test_adaptive_respects_budget():
    vol = procedural_volume(12, kind="blobs", seed=5)
    tf = TransferFunction.points([(0.0, 0.0), (0.3, 0.4), (0.5, 0.0),
                                  (0.7, 0.5), (1.0, 0.0)])
    k = 6
    vdi, _ = generate_vdi(vol, tf, _cam(), W, H,
                          VDIConfig(max_supersegments=k), max_steps=STEPS)
    assert np.asarray(vdi.count).max() <= k


def test_background_empty():
    vol = Volume.centered(jnp.ones((8, 8, 8)), extent=0.8)
    tf = TransferFunction.ramp(-1.0, 0.0, 0.9)
    vdi, _ = generate_vdi(vol, tf, _cam(), W, H, max_steps=STEPS)
    assert int(np.asarray(vdi.count)[0, 0]) == 0


def test_occupancy_grid():
    vol = Volume.centered(jnp.ones((8, 8, 8)), extent=1.0)
    tf = TransferFunction.ramp(-1.0, 0.0, 0.5)
    vdi, _ = generate_vdi(vol, tf, _cam(), W, H, max_steps=STEPS)
    tn = jnp.full((H, W), 3.0)
    tfar = jnp.full((H, W), 5.0)
    occ = occupancy_grid(vdi, tn, tfar, cell=8, depth_bins=4)
    occ = np.asarray(occ)
    assert occ.shape == (4, H // 8, W // 8)
    assert occ.sum() > 0


def test_metadata_contents():
    vol = procedural_volume(8)
    tf = TransferFunction.ramp(0.1, 0.9)
    vdi, meta = generate_vdi(vol, tf, _cam(), W, H, max_steps=16,
                             frame_index=7)
    assert meta.projection.shape == (4, 4)
    assert tuple(np.asarray(meta.window_dims)) == (W, H)
    assert int(meta.index) == 7
    assert float(meta.nw) > 0


def test_histogram_threshold_mode_matches_search():
    """One-march histogram thresholding must produce segment counts within
    the K budget, at least as fine as a 6-iter binary search, and decode
    to the same image."""
    import dataclasses

    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.utils.image import psnr

    vol = procedural_volume(24, kind="blobs", seed=4)
    tf = TransferFunction.ramp(0.1, 0.9, 0.6)
    cam = Camera.create((0.1, 0.2, 3.0), fov_y_deg=45.0, near=0.5, far=20.0)
    k = 6
    base = VDIConfig(max_supersegments=k, adaptive_iters=6)
    hist = dataclasses.replace(base, adaptive_mode="histogram",
                               histogram_bins=16)
    v1, _ = generate_vdi(vol, tf, cam, 40, 32, base, max_steps=64)
    v2, _ = generate_vdi(vol, tf, cam, 40, 32, hist, max_steps=64)
    c1 = np.asarray(v1.count)
    c2 = np.asarray(v2.count)
    assert c2.max() <= k
    occ = c1 > 0
    assert c2[occ].mean() >= c1[occ].mean() - 0.5   # at least as fine
    img1 = np.asarray(render_vdi_same_view(v1))
    img2 = np.asarray(render_vdi_same_view(v2))
    p = psnr(img2, img1)
    assert p > 35.0, f"histogram mode decode diverges: {p:.1f} dB"
