"""Parity tests for the fused Pallas march fold (ops/pallas_march.py):
the VMEM pixel-strip schedule must match the XLA lax.scan fold it
replaces to FMA-fusion tolerance (integer counts exactly) — same ops.supersegments state machine, two schedules
(≅ the reference's fused VDIGenerator.comp + AccumulateVDI.comp kernel
vs its own per-stage decomposition)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import (Volume,
                                             procedural_volume)
from scenery_insitu_tpu.ops import pallas_march as pm
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops import supersegments as ss

XLA = SliceMarchConfig(matmul_dtype="f32", scale=1.5, fold="xla")
PALLAS = SliceMarchConfig(matmul_dtype="f32", scale=1.5, fold="pallas")


@pytest.fixture(scope="module")
def vol():
    return procedural_volume(40, kind="blobs", seed=7)


@pytest.fixture(scope="module")
def tf():
    return for_dataset("procedural")


def _stream(key, n, h, w, empty_runs=True):
    """Random depth-ordered item stream with empties and near-duplicates —
    exercises close-on-gap, close-on-diff and merge-overflow paths."""
    kr, ka, kd = jax.random.split(key, 3)
    rgb = jax.random.uniform(kr, (n, 3, h, w))
    alpha = jax.random.uniform(ka, (n, 1, h, w))
    if empty_runs:
        # ~40% empty items, in runs
        gate = jax.random.uniform(kd, (n, 1, h, w)) > 0.4
        alpha = alpha * gate
    rgba = jnp.concatenate([rgb * alpha, alpha], axis=1)
    t0 = jnp.cumsum(jnp.full((n, h, w), 0.1), axis=0)
    return rgba, t0, t0 + 0.1


def _fold_xla(rgba, t0, t1, thr, max_k):
    st = ss.init_state(max_k, rgba.shape[2], rgba.shape[3])
    cst = ss.init_count(rgba.shape[2], rgba.shape[3])
    for i in range(rgba.shape[0]):
        st = ss.push(st, max_k, thr, rgba[i], t0[i], t1[i])
        cst = ss.push_count(cst, thr, rgba[i])
    return st, cst


def test_fold_chunk_matches_sequential_push():
    h, w = 16, 40                       # w deliberately NOT 128-aligned
    max_k = 5
    rgba, t0, t1 = _stream(jax.random.PRNGKey(0), 12, h, w)
    thr = jnp.full((h, w), 0.35, jnp.float32)

    st_ref, cst_ref = _fold_xla(rgba, t0, t1, thr, max_k)
    c_ref, d_ref = ss.finalize(st_ref)

    packed = pm.init_packed(max_k, h, w)
    count = jnp.zeros((h, w), jnp.int32)
    # two chunk calls — state must round-trip exactly between them
    packed, count = pm.fold_chunk(packed, rgba[:7], t0[:7], t1[:7], thr,
                                  max_k=max_k, count=count)
    packed, count = pm.fold_chunk(packed, rgba[7:], t0[7:], t1[7:], thr,
                                  max_k=max_k, count=count)
    c_p, d_p = ss.finalize(pm.unpack_state(packed))

    np.testing.assert_allclose(np.asarray(c_p), np.asarray(c_ref),
                               rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_ref),
                               rtol=2e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(count),
                                  np.asarray(cst_ref.count))


def test_fold_chunk_without_count():
    h, w = 8, 33
    max_k = 4
    rgba, t0, t1 = _stream(jax.random.PRNGKey(3), 9, h, w)
    thr = jnp.float32(0.2)              # scalar threshold broadcast

    st_ref, _ = _fold_xla(rgba, t0, t1, jnp.full((h, w), 0.2), max_k)
    packed = pm.fold_chunk(pm.init_packed(max_k, h, w), rgba, t0, t1, thr,
                           max_k=max_k)
    c_p, d_p = ss.finalize(pm.unpack_state(packed))
    c_ref, d_ref = ss.finalize(st_ref)
    np.testing.assert_allclose(np.asarray(c_p), np.asarray(c_ref),
                               rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_ref),
                               rtol=2e-6, atol=1e-6)


def test_count_multi_matches_push_count():
    h, w = 16, 24
    bins = 6
    rgba, t0, t1 = _stream(jax.random.PRNGKey(5), 10, h, w)
    tvec = ss.threshold_candidates(bins, 2.0)

    st = ss.init_count_multi(bins, h, w)
    for i in range(rgba.shape[0]):
        st = ss.push_count(st, tvec[:, None, None], rgba[i])

    carry = pm.init_count_multi_packed(bins, h, w)
    carry = pm.count_multi_chunk(carry, rgba[:4], np.asarray(tvec))
    carry = pm.count_multi_chunk(carry, rgba[4:], np.asarray(tvec))
    np.testing.assert_array_equal(np.asarray(carry[0]),
                                  np.asarray(st.count))


def test_generate_vdi_mxu_fold_parity(vol, tf):
    """Whole-march parity: fold='pallas' must reproduce fold='xla' exactly
    (histogram adaptive mode — both the counting and write marches fused)."""
    cam = Camera.create((0.25, 0.5, 2.6), fov_y_deg=45.0, near=0.3, far=10.0)
    cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram",
                    histogram_bins=8)
    spec_x = slicer.make_spec(cam, vol.data.shape, XLA)
    spec_p = slicer.make_spec(cam, vol.data.shape, PALLAS)
    assert spec_p.fold == "pallas" and spec_x.fold == "xla"

    vdi_x, meta_x, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_x, cfg)
    vdi_p, meta_p, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_p, cfg)
    np.testing.assert_allclose(np.asarray(vdi_p.color),
                               np.asarray(vdi_x.color), rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vdi_p.depth),
                               np.asarray(vdi_x.depth), rtol=2e-6, atol=1e-6)


def test_temporal_fold_parity(vol, tf):
    """Temporal mode: fused write+count kernel must produce the same VDI
    AND the same next-frame threshold state as the XLA side-by-side fold,
    across several carried frames."""
    cam = Camera.create((0.0, 0.4, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    cfg = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    spec_x = slicer.make_spec(cam, vol.data.shape, XLA)
    spec_p = slicer.make_spec(cam, vol.data.shape, PALLAS)

    thr_x = slicer.initial_threshold(vol, tf, cam, spec_x, cfg)
    thr_p = slicer.initial_threshold(vol, tf, cam, spec_p, cfg)
    np.testing.assert_allclose(np.asarray(thr_p.thr),
                               np.asarray(thr_x.thr), rtol=2e-6, atol=1e-6)

    for _ in range(3):
        vdi_x, _, _, thr_x = slicer.generate_vdi_mxu_temporal(
            vol, tf, cam, spec_x, thr_x, cfg)
        vdi_p, _, _, thr_p = slicer.generate_vdi_mxu_temporal(
            vol, tf, cam, spec_p, thr_p, cfg)
        np.testing.assert_allclose(np.asarray(vdi_p.color),
                               np.asarray(vdi_x.color), rtol=2e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vdi_p.depth),
                               np.asarray(vdi_x.depth), rtol=2e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(thr_p.thr),
                               np.asarray(thr_x.thr), rtol=2e-6, atol=1e-6)


def test_fold_parity_under_jit(vol, tf):
    """The production call shape: the whole generate step jitted, pallas
    fold inside — must still match and must be jit-stable."""
    cam = Camera.create((0.1, 0.5, 2.7), fov_y_deg=45.0, near=0.3, far=10.0)
    cfg = VDIConfig(max_supersegments=5, adaptive_mode="histogram",
                    histogram_bins=8)
    spec_p = slicer.make_spec(cam, vol.data.shape, PALLAS)
    spec_x = slicer.make_spec(cam, vol.data.shape, XLA)

    @jax.jit
    def gen_p(data):
        v = type(vol)(data, vol.origin, vol.spacing)
        vdi, _, _ = slicer.generate_vdi_mxu(v, tf, cam, spec_p, cfg)
        return vdi.color, vdi.depth

    @jax.jit
    def gen_x(data):
        v = type(vol)(data, vol.origin, vol.spacing)
        vdi, _, _ = slicer.generate_vdi_mxu(v, tf, cam, spec_x, cfg)
        return vdi.color, vdi.depth

    cp, dp = gen_p(vol.data)
    cx, dx = gen_x(vol.data)
    np.testing.assert_allclose(np.asarray(cp), np.asarray(cx),
                               rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                               rtol=2e-6, atol=1e-6)


def test_auto_fold_resolution_and_probe():
    """"auto" resolves to the XLA fold off-TPU (interpret-mode pallas is
    slow; conftest pins the cpu backend); the probe caches per
    (backend, shape); an explicit fold choice is always honored."""
    assert jax.default_backend() == "cpu"        # conftest invariant
    cam = Camera.create((0.0, 0.4, 2.8))
    spec = slicer.make_spec(cam, (16, 16, 16), SliceMarchConfig())
    assert spec.fold == "xla"
    pm._FOLD_PROBE.clear()
    pm.fold_compile_ok(4, 2, 128)
    assert ("cpu", 4, 2, 128) in pm._FOLD_PROBE  # cached by shape key
    pm._FOLD_PROBE.clear()
    spec_p = slicer.make_spec(cam, (16, 16, 16), PALLAS)
    assert spec_p.fold == "pallas"


def test_skip_chunks_execute_through_pallas_fold(tf):
    """Occupancy skipping EXECUTES the C=1 empty-sample branch through the
    fused fold (the blob fixture above rarely leaves a whole chunk empty,
    so the lax.cond skip branch only gets traced there, not run): a
    corner blob leaves most chunks provably empty, occupancy must skip
    them, and the pallas fold must still match the xla fold and the
    skip_empty=False reference exactly."""
    size = 40
    z, y, x = np.meshgrid(*(np.linspace(-1, 1, size, dtype=np.float32),)
                          * 3, indexing="ij")
    field = np.exp(-(((x - 0.7) ** 2 + (y - 0.7) ** 2 + (z - 0.7) ** 2)
                     / 0.02)).astype(np.float32)
    vol = Volume.centered(jnp.asarray(field), extent=2.0)

    cam = Camera.create((0.3, 0.5, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    spec_p = slicer.make_spec(cam, vol.data.shape, PALLAS)
    occ = np.asarray(slicer.chunk_occupancy(vol, tf, spec_p))
    assert (~occ).sum() >= 1, "fixture must leave at least one empty chunk"

    cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram",
                    histogram_bins=8)
    vdi_p, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_p, cfg)
    spec_x = slicer.make_spec(cam, vol.data.shape, XLA)
    vdi_x, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_x, cfg)
    spec_off = slicer.make_spec(
        cam, vol.data.shape,
        SliceMarchConfig(matmul_dtype="f32", scale=1.5, fold="pallas",
                         skip_empty=False))
    vdi_off, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_off, cfg)

    np.testing.assert_allclose(np.asarray(vdi_p.color),
                               np.asarray(vdi_x.color), rtol=2e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vdi_p.color),
                               np.asarray(vdi_off.color), rtol=2e-6,
                               atol=1e-6)
    dp = np.nan_to_num(np.asarray(vdi_p.depth), posinf=1e9)
    dx = np.nan_to_num(np.asarray(vdi_x.depth), posinf=1e9)
    doff = np.nan_to_num(np.asarray(vdi_off.depth), posinf=1e9)
    np.testing.assert_allclose(dp, dx, rtol=2e-6, atol=1e-5)
    np.testing.assert_allclose(dp, doff, rtol=2e-6, atol=1e-5)


def test_fold_chunk_width_tiled_matches_sequential_push():
    """Multi-block width tiling (wb < w: 2D grid, masked partial last
    block) must match the sequential push exactly — the production
    trigger is frame widths whose strip VMEM estimate exceeds the
    budget (512^3 -> 640-wide strips OOM'd Mosaic's 16 MB scoped limit
    on hardware), which no test-sized frame reaches, so force the
    geometry through _FORCE_BLOCK_W: 320 = 128 + 128 + 64-masked."""
    h, w = 16, 320
    k, c = 6, 5
    rgba, t0, t1 = _stream(jax.random.PRNGKey(11), c, h, w)
    thr = jnp.full((h, w), 0.25, jnp.float32)

    st, cst = _fold_xla(rgba, t0, t1, thr, k)
    old = pm._FORCE_BLOCK_W
    pm._FORCE_BLOCK_W = 128
    try:
        packed, cnt = pm.fold_chunk(
            pm.init_packed(k, h, w), rgba, t0, t1, thr, max_k=k,
            count=jnp.zeros((h, w), jnp.int32), interpret=True)
        carry = pm.init_count_multi_packed(3, h, w)
        tvec = jnp.asarray([0.1, 0.25, 0.6])
        carry = pm.count_multi_chunk(carry, rgba, tvec, interpret=True)
    finally:
        pm._FORCE_BLOCK_W = old
    got = pm.unpack_state(packed)
    np.testing.assert_allclose(np.asarray(st.out_color),
                               np.asarray(got.out_color), rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(
        np.nan_to_num(np.asarray(st.out_start), posinf=1e9),
        np.nan_to_num(np.asarray(got.out_start), posinf=1e9),
        rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(st.k), np.asarray(got.k))
    np.testing.assert_array_equal(np.asarray(cst.count), np.asarray(cnt))

    cm = ss.init_count_multi(3, h, w)
    for i in range(c):
        cm = ss.push_count(cm, tvec[:, None, None], rgba[i])
    np.testing.assert_array_equal(np.asarray(carry[0]),
                                  np.asarray(cm.count))


def test_fold_chunk_gated_phase2_matches_sequential_push():
    """_PHASE2_GATED skips the event extraction for slot rows with no
    close event anywhere in the block; the passthrough copy must leave
    those rows bit-identical and the gated rows must still extract
    exactly (same stream as the ungated parity test)."""
    h, w = 16, 40
    k, c = 6, 5
    rgba, t0, t1 = _stream(jax.random.PRNGKey(3), c, h, w)
    thr = jnp.full((h, w), 0.25, jnp.float32)
    st, _ = _fold_xla(rgba, t0, t1, thr, k)

    old = pm._PHASE2_GATED
    pm._PHASE2_GATED = True
    try:
        packed = pm.fold_chunk(pm.init_packed(k, h, w), rgba, t0, t1,
                               thr, max_k=k, interpret=True)
    finally:
        pm._PHASE2_GATED = old
    got = pm.unpack_state(packed)
    np.testing.assert_allclose(np.asarray(st.out_color),
                               np.asarray(got.out_color), rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(
        np.nan_to_num(np.asarray(st.out_start), posinf=1e9),
        np.nan_to_num(np.asarray(got.out_start), posinf=1e9),
        rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(st.k), np.asarray(got.k))
