"""The driver runs `python bench.py` at every round boundary and parses
ONE JSON line — round 1 lost its perf artifact to an unhandled backend
crash, so the orchestration path is load-bearing. This runs the real
script as a subprocess on the CPU platform with tiny knobs and checks
the contract."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_one_parseable_json_line():
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _ROOT,            # drops the axon site dir
        "SITPU_BENCH_PLATFORMS": "cpu",
        "SITPU_BENCH_GRID": "24",
        "SITPU_BENCH_K": "4",
        "SITPU_BENCH_FRAMES": "2",
        "SITPU_BENCH_SIM_STEPS": "1",
        "SITPU_BENCH_CHILD_TIMEOUT": "420",
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-800:]
    lines = [l for l in p.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, p.stdout
    d = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in d, d
    assert d["value"] is not None and d["value"] > 0
    assert d["unit"] == "frames/s"
    assert d["config"]["platform"] == "cpu"
    assert d["config"]["adaptive_mode"] == "temporal"   # bench default
    # observability contract (ISSUE 3): every artifact embeds the
    # fallback ledger and the device-cost snapshot of the compiled frame
    assert "degradations" in d, d
    assert any(e["component"] == "sim.fused_stencil"
               for e in d["degradations"])   # CPU run degrades the stencil
    assert "cost_analysis" in d, d


def test_bench_reports_failed_attempts_on_fallback(tmp_path):
    """A platform whose child crashes must be recorded in the successful
    fallback's JSON (the judge reads WHY a number is CPU)."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _ROOT,
        # the parent leaves JAX_PLATFORMS alone for non-cpu attempts (the
        # conftest exports cpu into our env, which would make the bogus
        # platform's child succeed); pin it so the "nope" child dies in
        # backend init while the cpu child's own override still applies
        "JAX_PLATFORMS": "nope",
        "SITPU_BENCH_PLATFORMS": "nope,cpu",
        "SITPU_BENCH_GRID": "24",
        "SITPU_BENCH_K": "4",
        "SITPU_BENCH_FRAMES": "1",
        "SITPU_BENCH_SIM_STEPS": "1",
        "SITPU_BENCH_CHILD_TIMEOUT": "420",
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=900)
    assert p.returncode == 0, p.stderr[-800:]
    d = json.loads([l for l in p.stdout.strip().splitlines()
                    if l.startswith("{")][-1])
    assert d["value"] is not None
    assert any("nope" in e for e in d.get("failed_attempts", [])), d


def test_bench_scanloop_render_only_modes():
    """The round-5 diagnostic modes: SIM_STEPS=0 (render-only, the
    reference FPS-harness semantics) + SCAN_FRAMES=1 (whole loop in one
    lax.scan executable) must produce the tagged metric and a real
    number — these are the watcher's dispatch-tax / in-situ-split A/Bs,
    so a silent breakage would burn a hardware window."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _ROOT,
        "SITPU_BENCH_PLATFORMS": "cpu",
        "SITPU_BENCH_GRID": "24",
        "SITPU_BENCH_K": "4",
        "SITPU_BENCH_FRAMES": "2",
        "SITPU_BENCH_SIM_STEPS": "0",
        "SITPU_BENCH_SCAN_FRAMES": "1",
        "SITPU_BENCH_CHILD_TIMEOUT": "420",
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-800:]
    d = json.loads([l for l in p.stdout.strip().splitlines()
                    if l.startswith("{")][-1])
    assert d["value"] is not None and d["value"] > 0
    assert d["metric"].endswith("_render_only_scanloop"), d["metric"]
    assert d["config"]["scan_frames"] is True
    assert d["config"]["sim_steps"] == 0
    # render-only is not the sim-in-loop primary config: vs_baseline null
    assert d["vs_baseline"] is None


def test_hbm_and_rank_slab_harnesses_emit_json():
    """The round-5 diagnostic harnesses (micro-roofline, Config-2
    per-rank projection) are first-in-queue for scarce hardware windows;
    a silent breakage would burn one. Tiny-shape CPU smoke of both."""
    env = dict(os.environ)
    env.update({"PYTHONPATH": _ROOT, "SITPU_CPU": "1",
                "SITPU_HBM_BENCH_MB": "8", "SITPU_HBM_BENCH_GRID": "32"})
    p = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks/hbm_bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-800:]
    d = json.loads([l for l in p.stdout.strip().splitlines()
                    if l.startswith("{")][-1])
    for key in ("copy_gbps", "sim10_ms", "dispatch_tiny_us",
                "dispatch_chain_us", "matmul_tflops"):
        assert key in d and d[key] is not None, (key, d)

    env = dict(os.environ)
    env.update({"PYTHONPATH": _ROOT, "SITPU_CPU": "1",
                "SITPU_BENCH_GRID": "32", "SITPU_BENCH_RANKS": "4",
                "SITPU_BENCH_SIM_STEPS": "1", "SITPU_BENCH_K": "4"})
    p = subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "benchmarks/rank_slab_bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-800:]
    d = json.loads([l for l in p.stdout.strip().splitlines()
                    if l.startswith("{")][-1])
    assert d["projected_fps_v5e8"] > 0
    assert d["per_rank_march_ms"] > 0
    assert d["a2a_assumed_gbps"] > 0    # the stated-assumption contract
