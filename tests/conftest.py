"""Test harness: force a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 lesson — single-host
stand-ins for the cluster).

The environment may register an external TPU plugin ("axon") at interpreter
start and pin JAX_PLATFORMS to it; tests must never touch that backend (it
tunnels to one shared real chip), so we hard-override the platform AND drop
the plugin's backend factory before any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

assert jax.default_backend() == "cpu"
assert jax.device_count() == 8, jax.devices()

# Persistent XLA compile cache (the same helper bench.py uses): on a
# small CPU host the tier-1 wall clock is dominated by jit compiles of
# the distributed steps, and repeat runs — the common case for the
# verify loop — skip them entirely. Harmless when cold.
try:
    from scenery_insitu_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "multiproc: spawns real jax.distributed subprocesses "
        "(the multiproc CI lane selects these with -m multiproc)")
