"""Generate the committed golden fixtures (run from the repo root):

    JAX_PLATFORMS=cpu python tests/golden/make_golden.py

Writes small deterministic renders of BOTH engines + a VDI artifact into
tests/golden/. tests/test_golden.py regenerates the same configs and
compares within tolerance — a kernel regression breaks a committed-image
test (the reference validated exactly this way, by re-rendering stored
dumps on screen: SURVEY.md §4.2; here the comparison is mechanical).

Regenerate (and commit the diff) ONLY when an intentional rendering
change shifts the images; the test failure message says which config.
"""

from __future__ import annotations

import os

GOLDEN_DIR = os.path.dirname(os.path.abspath(__file__))

# one shared tiny scene: deterministic procedural volume, fixed cameras
GRID = 32
W, H = 96, 72
SEED = 11
EYE = (0.35, 0.55, 2.7)
EYE_NOVEL = (0.9, 0.15, 2.4)
K = 6
STEPS = 96


def build_vdi(fold: str = "xla"):
    """Config 3's scene through VDI generate (histogram) + composite —
    shared by build_all and test_golden's Pallas schedule-independence
    check so the two can never drift apart. Returns (comp, meta, spec)."""
    from scenery_insitu_tpu.config import (CompositeConfig,
                                           SliceMarchConfig, VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.composite import composite_vdis

    vol = procedural_volume(GRID, kind="blobs", seed=SEED)
    cam = Camera.create(EYE, fov_y_deg=50.0, near=0.3, far=20.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", fold=fold))
    vdi, meta, _ = slicer.generate_vdi_mxu(
        vol, for_dataset("procedural"), cam, spec,
        VDIConfig(max_supersegments=K, adaptive_mode="histogram",
                  histogram_bins=8))
    comp = composite_vdis(vdi.color[None], vdi.depth[None],
                          CompositeConfig(max_output_supersegments=K))
    return comp, meta, spec


def build_all(out_dir: str) -> dict:
    """Render every golden config; returns {name: array} (also saved when
    ``out_dir`` is set)."""
    import numpy as np

    from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                           SliceMarchConfig, VDIConfig)
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.vdi import VDI, render_vdi_same_view
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer, vdi_convert
    from scenery_insitu_tpu.ops.composite import composite_vdis
    from scenery_insitu_tpu.ops.raycast import raycast
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
    from scenery_insitu_tpu.ops.vdi_render import render_vdi

    vol = procedural_volume(GRID, kind="blobs", seed=SEED)
    tf = for_dataset("procedural")
    cam = Camera.create(EYE, fov_y_deg=50.0, near=0.3, far=20.0)
    bg = (1.0, 1.0, 1.0, 1.0)
    out = {}

    # 1. gather-path plain raycast (the portable reference engine)
    rc = raycast(vol, tf, cam, W, H,
                 RenderConfig(max_steps=STEPS, background=bg))
    out["raycast_gather"] = np.asarray(rc.image)

    # 2. MXU slice-march plain render, homography-warped to the same camera
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             fold="xla"))
    mx = slicer.raycast_mxu(vol, tf, cam, W, H, spec, background=bg)
    out["raycast_mxu"] = np.asarray(mx.image)

    # 3. VDI generate (histogram) -> composite -> same-view decode; the
    #    VDI tensors themselves are a fixture (replay food for the
    #    compositor / novel-view clients)
    comp, meta, _ = build_vdi(fold="xla")
    out["vdi_color"] = np.asarray(comp.color)
    out["vdi_depth"] = np.asarray(comp.depth)
    out["vdi_decode"] = np.asarray(render_vdi_same_view(
        VDI(comp.color, comp.depth), background=bg))

    # 4. novel-view render of the stored VDI from an offset camera
    #    (portable gather client — the EfficientVDIRaycast role)
    cam2 = Camera.create(EYE_NOVEL, fov_y_deg=50.0, near=0.3, far=20.0)
    out["novel_view"] = np.asarray(render_vdi(
        VDI(comp.color, comp.depth), meta, cam2, W, H, steps=STEPS,
        background=bg))

    # 5. gather-path VDI for cross-engine coverage
    vdi_g, _ = generate_vdi(vol, tf, cam, W, H,
                            VDIConfig(max_supersegments=K,
                                      adaptive_iters=4),
                            max_steps=STEPS)
    out["vdi_gather_decode"] = np.asarray(render_vdi_same_view(
        vdi_g, background=bg))

    # 6. the Vulkan reference-frame normalization of config 2 — pins the
    #    comparison protocol (gamma + y-flip) as a golden image
    out["reference_frame"] = np.asarray(
        vdi_convert.to_reference_frame(mx.image))

    if out_dir:
        from scenery_insitu_tpu.utils.image import save_png

        np.savez_compressed(
            os.path.join(out_dir, "golden_vdi.npz"),
            color=out["vdi_color"], depth=out["vdi_depth"])
        for name in ("raycast_gather", "raycast_mxu", "vdi_decode",
                     "novel_view", "vdi_gather_decode"):
            save_png(os.path.join(out_dir, f"golden_{name}.png"), out[name])
        # reference_frame is ALREADY gamma-encoded by to_reference_frame —
        # store with gamma=1.0 so the PNG carries exactly one encode (the
        # pixels a Vulkan screenshot of the same config would hold)
        save_png(os.path.join(out_dir, "golden_reference_frame.png"),
                 out["reference_frame"], gamma=1.0)
    return out


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from scenery_insitu_tpu.utils.backend import pin_cpu_backend

    pin_cpu_backend()
    arrays = build_all(GOLDEN_DIR)
    print("wrote", sorted(arrays), "to", GOLDEN_DIR)
