import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.core.camera import (Camera, orbit,
                                            perspective, pixel_rays,
                                            projection_matrix, view_matrix,
                                            world_to_ndc)


def _cam(eye=(0.0, 0.0, 3.0)):
    return Camera.create(eye, target=(0, 0, 0), fov_y_deg=60.0, near=0.5, far=10.0)


def test_look_at_maps_eye_to_origin():
    cam = _cam()
    v = view_matrix(cam)
    e = jnp.concatenate([cam.eye, jnp.ones(1)])
    assert np.allclose(v @ e, [0, 0, 0, 1], atol=1e-6)


def test_look_at_target_on_negative_z():
    cam = _cam(eye=(1.0, 2.0, 3.0))
    v = view_matrix(cam)
    t = np.asarray(v @ jnp.concatenate([cam.target, jnp.ones(1)]))
    assert abs(t[0]) < 1e-5 and abs(t[1]) < 1e-5 and t[2] < 0


def test_perspective_near_far_ndc():
    p = perspective(jnp.deg2rad(60.0), 1.0, 0.5, 10.0)
    for z_eye, z_ndc in [(-0.5, -1.0), (-10.0, 1.0)]:
        clip = np.asarray(p @ jnp.array([0.0, 0.0, z_eye, 1.0]))
        assert np.isclose(clip[2] / clip[3], z_ndc, atol=1e-5)


def test_center_ray_points_at_target():
    cam = _cam(eye=(1.0, 1.0, 4.0))
    origin, dirs = pixel_rays(cam, 64, 64)
    center = np.asarray(dirs[:, 32, 32])
    expected = np.array(cam.target - cam.eye)
    expected = expected / np.linalg.norm(expected)
    # pixel center is half a pixel off the optical axis
    assert np.dot(center, expected) > 0.999


def test_rays_unit_length():
    origin, dirs = pixel_rays(_cam(), 16, 8)
    norms = np.linalg.norm(np.asarray(dirs), axis=0)
    assert np.allclose(norms, 1.0, atol=1e-5)


def test_world_to_ndc_roundtrip_with_rays():
    cam = _cam(eye=(0.5, -0.3, 3.0))
    w, h = 32, 24
    origin, dirs = pixel_rays(cam, w, h)
    t = 2.0
    pts = np.asarray(origin)[:, None, None] + t * np.asarray(dirs)  # [3,H,W]
    ndc = world_to_ndc(jnp.moveaxis(jnp.asarray(pts), 0, -1),
                       view_matrix(cam), projection_matrix(cam, w, h))
    # pixel (i, j) center should project back to its own NDC coordinate
    j, i = 7, 5
    exp_x = (j + 0.5) / w * 2 - 1
    exp_y = 1 - (i + 0.5) / h * 2
    assert np.allclose(np.asarray(ndc)[i, j, :2], [exp_x, exp_y], atol=1e-4)


def test_orbit_preserves_distance():
    cam = _cam(eye=(0.0, 1.0, 3.0))
    cam2 = orbit(cam, jnp.pi / 3, 0.2)
    d1 = np.linalg.norm(np.asarray(cam.eye - cam.target))
    d2 = np.linalg.norm(np.asarray(cam2.eye - cam2.target))
    assert np.isclose(d1, d2, atol=1e-5)
