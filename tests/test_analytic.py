"""Analytic image-parity tests: closed-form scenes where the volume
rendering integral is exact, asserting ABSOLUTE transmittance/color error
bounds for the gather engine, the MXU slice-march engine, and the
distributed generate→composite path.

This substitutes for the un-runnable Vulkan reference diff (the image has
no Vulkan and the reference repo ships no rendered goldens — VERDICT round
3, missing #5): instead of engine-vs-engine tolerances, every engine is
held to the same external mathematical truth.

The opacity semantics under test (ops/sampling.adjust_opacity, ≅
adjustOpacity in VDIGenerator.comp:80-82): a sample of corrected opacity
``1-(1-a)^(len/nw)`` composes multiplicatively, so along a ray segment of
in-volume length L through a UNIFORM field with per-nominal-step alpha a0
the transmittance telescopes EXACTLY to ``(1-a0)^(L/nw)`` regardless of
how the march discretizes it — boundary samples contribute the fractional
exponent. Accumulated premultiplied color is then c*(1-T). For a smooth
(Gaussian) field, log-transmittance is ``(1/nw)∫ln(1-a(v(x)))dx`` whose
first two Taylor terms have closed forms over a Gaussian profile; the
third-order remainder is part of the stated bound.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import RenderConfig, SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera, pixel_rays
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.raycast import raycast

W = H = 64
RGB = (0.8, 0.4, 0.2)


def _const_alpha_tf(a0: float) -> TransferFunction:
    """alpha(v) = a0 and rgb(v) = RGB for every value v."""
    return TransferFunction.from_polylines(
        [(0.0, a0), (1.0, a0)],
        np.array([0.0, 1.0]),
        np.array([RGB, RGB], np.float32))


def _linear_alpha_tf(kappa: float) -> TransferFunction:
    """alpha(v) = kappa * v (linear ramp), constant color."""
    return TransferFunction.from_polylines(
        [(0.0, 0.0), (1.0, kappa)],
        np.array([0.0, 1.0]),
        np.array([RGB, RGB], np.float32))


def _ray_geometry(cam: Camera, vol: Volume):
    """Per-pixel (unit dir, origin, in-volume length L) — computed with
    plain numpy slab intersections, independent of the renderers."""
    origin, dirs = pixel_rays(cam, W, H)
    o = np.asarray(origin, np.float64)
    d = np.asarray(dirs, np.float64)                       # [3, H, W]
    bmin = np.asarray(vol.world_min, np.float64)
    bmax = np.asarray(vol.world_max, np.float64)
    t0 = np.full((H, W), -np.inf)
    t1 = np.full((H, W), np.inf)
    for a in range(3):
        da = np.where(np.abs(d[a]) < 1e-12, 1e-12, d[a])
        lo = (bmin[a] - o[a]) / da
        hi = (bmax[a] - o[a]) / da
        t0 = np.maximum(t0, np.minimum(lo, hi))
        t1 = np.minimum(t1, np.maximum(lo, hi))
    L = np.clip(t1 - np.maximum(t0, 0.0), 0.0, None)
    L = np.where(t1 > t0, L, 0.0)
    return o, d, L


def _uniform_case():
    vol = Volume.centered(jnp.full((32, 32, 32), 0.5, jnp.float32),
                          extent=2.0)
    cam = Camera.create((0.15, 0.1, 3.0), fov_y_deg=40.0, near=0.5,
                        far=20.0)
    a0 = 0.15
    tf = _const_alpha_tf(a0)
    _, _, L = _ray_geometry(cam, vol)
    nw = float(np.min(np.asarray(vol.spacing)))
    t_pred = (1.0 - a0) ** (L / nw)
    alpha_pred = 1.0 - t_pred
    # interior pixels only: silhouette pixels see partial-coverage
    # interpolation taper that the AABB closed form doesn't model
    mask = L > 0.8 * L.max()
    return vol, cam, tf, alpha_pred, mask


def _check_alpha_rgb(img, alpha_pred, mask, tol):
    img = np.asarray(img)
    err_a = np.abs(img[3] - alpha_pred)[mask]
    assert err_a.max() < tol, f"alpha err {err_a.max():.4f}"
    for ch in range(3):
        err_c = np.abs(img[ch] - RGB[ch] * alpha_pred)[mask]
        assert err_c.max() < tol, f"rgb[{ch}] err {err_c.max():.4f}"


def test_uniform_slab_gather():
    vol, cam, tf, alpha_pred, mask = _uniform_case()
    out = raycast(vol, tf, cam, W, H, RenderConfig(max_steps=256))
    _check_alpha_rgb(out.image, alpha_pred, mask, 0.02)


def test_uniform_slab_mxu():
    vol, cam, tf, alpha_pred, mask = _uniform_case()
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32"))
    out = slicer.raycast_mxu(vol, tf, cam, W, H, spec)
    _check_alpha_rgb(out.image, alpha_pred, mask, 0.02)


def test_uniform_slab_distributed_vdi_composite():
    """Two z-slab sub-volumes -> generate_vdi each -> composite -> decode:
    the whole distributed VDI path against the same closed form."""
    from scenery_insitu_tpu.config import CompositeConfig
    from scenery_insitu_tpu.ops.composite import composite_vdis
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

    vol, cam, tf, alpha_pred, mask = _uniform_case()
    data = np.asarray(vol.data)
    vox = np.asarray(vol.spacing)
    o = np.asarray(vol.origin)
    half = data.shape[0] // 2
    sub0 = Volume.create(data[:half], origin=o, spacing=vox)
    sub1 = Volume.create(data[half:],
                         origin=o + np.array([0, 0, half * vox[2]]),
                         spacing=vox)
    cfg = VDIConfig(max_supersegments=4, adaptive=False, threshold=0.5)
    colors, depths = [], []
    for sub in (sub0, sub1):
        vdi, _ = generate_vdi(sub, tf, cam, W, H, cfg, max_steps=128)
        colors.append(vdi.color)
        depths.append(vdi.depth)
    out = composite_vdis(jnp.stack(colors), jnp.stack(depths),
                         CompositeConfig(max_output_supersegments=4,
                                         adaptive_iters=2))
    img = render_vdi_same_view(out)
    # the slab boundary adds one interpolation-overlap seam per ray on
    # top of the marching error — slightly wider bound
    _check_alpha_rgb(img, alpha_pred, mask, 0.03)


def _gaussian_case():
    n = 48
    vol_w = 0.3                                    # Gaussian sigma, world
    kappa = 0.08
    ax = (np.arange(n) + 0.5) / n * 2.0 - 1.0      # voxel centers, world
    zz, yy, xx = np.meshgrid(ax, ax, ax, indexing="ij")
    field = np.exp(-(xx**2 + yy**2 + zz**2) / (2 * vol_w**2))
    vol = Volume.centered(jnp.asarray(field, jnp.float32), extent=2.0)
    cam = Camera.create((0.0, 0.0, 3.0), fov_y_deg=35.0, near=0.5,
                        far=20.0)
    tf = _linear_alpha_tf(kappa)

    o, d, L = _ray_geometry(cam, vol)
    # impact parameter of each pixel ray to the Gaussian center (origin)
    oc = -o.reshape(3, 1, 1)
    t_close = np.sum(oc * d, axis=0)
    b2 = np.sum((oc - t_close[None] * d) ** 2, axis=0)
    # ln(1-kv) = -kv - (kv)^2/2 - O((kv)^3); line integrals of v and v^2
    # over the full line (box truncation at |x|>3.3 sigma is negligible):
    #   I1 = exp(-b^2/2w^2) w sqrt(2pi),  I2 = exp(-b^2/w^2) w sqrt(pi)
    i1 = np.exp(-b2 / (2 * vol_w**2)) * vol_w * np.sqrt(2 * np.pi)
    i2 = np.exp(-b2 / vol_w**2) * vol_w * np.sqrt(np.pi)
    nw = float(np.min(np.asarray(vol.spacing)))
    tau = (kappa * i1 + 0.5 * kappa**2 * i2) / nw
    alpha_pred = 1.0 - np.exp(-tau)
    mask = (L > 1.0) & (b2 < (2.5 * vol_w) ** 2)
    return vol, cam, tf, alpha_pred, mask


@pytest.mark.parametrize("engine", ["gather", "mxu"])
def test_gaussian_sphere(engine):
    vol, cam, tf, alpha_pred, mask = _gaussian_case()
    if engine == "gather":
        out = raycast(vol, tf, cam, W, H, RenderConfig(max_steps=384))
        img = out.image
    else:
        spec = slicer.make_spec(cam, vol.data.shape,
                                SliceMarchConfig(matmul_dtype="f32"))
        img = slicer.raycast_mxu(vol, tf, cam, W, H, spec).image
    img = np.asarray(img)
    err = np.abs(img[3] - alpha_pred)[mask]
    # bound = third-order Taylor remainder (~(k v)^3 L/nw <= 4e-3) +
    # trilinear interpolation of the Gaussian (h^2/w^2 curvature ~ 6e-3)
    # + marching quadrature; 0.015 holds with ~2x slack on CPU f32
    assert err.max() < 0.015, f"{engine} alpha err {err.max():.4f}"
    for ch in range(3):
        err_c = np.abs(img[ch] - RGB[ch] * alpha_pred)[mask]
        assert err_c.max() < 0.015, f"{engine} rgb[{ch}] {err_c.max():.4f}"
