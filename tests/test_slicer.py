"""Tests for the MXU slice-march engine (ops/slicer.py): virtual-camera
geometry, cross-engine parity with the gather-path raycaster, VDI
generation equivalence, and edge cases (axes, signs, oblique cameras,
out-of-frustum volumes)."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera, world_to_ndc
from scenery_insitu_tpu.core.transfer import TransferFunction, for_dataset
from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
from scenery_insitu_tpu.ops.vdi_render import render_vdi
from scenery_insitu_tpu.utils.image import psnr


F32 = SliceMarchConfig(matmul_dtype="f32", scale=1.5)


@pytest.fixture(scope="module")
def vol():
    return procedural_volume(48, kind="blobs", seed=3)


@pytest.fixture(scope="module")
def tf():
    return for_dataset("procedural")


def test_choose_axis():
    cam = Camera.create((0.0, 0.1, 3.0), target=(0.0, 0.0, 0.0))
    assert slicer.choose_axis(cam) == (2, -1)
    cam = Camera.create((-4.0, 0.1, 0.5), target=(0.0, 0.0, 0.0))
    assert slicer.choose_axis(cam) == (0, 1)
    cam = Camera.create((0.2, -3.0, 0.5), target=(0.0, 0.0, 0.0))
    assert slicer.choose_axis(cam) == (1, 1)


def test_axis_camera_grid_matches_projection(vol):
    """Grid point (j, i) must project through (proj, view) to the NDC of
    pixel center (i, j) — the invariant every metadata consumer relies on."""
    cam = Camera.create((0.4, 0.7, 2.5), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    axcam = slicer.make_axis_camera(vol, cam, spec)

    a, ua, va = spec.axis, spec.u_axis, spec.v_axis
    for (j, i) in [(0, 0), (spec.nj - 1, spec.ni - 1),
                   (spec.nj // 2, spec.ni // 3)]:
        p = np.zeros(3, np.float32)
        p[ua] = float(axcam.u_grid[i])
        p[va] = float(axcam.v_grid[j])
        p[a] = float(axcam.w0)
        ndc = np.asarray(world_to_ndc(jnp.asarray(p), axcam.view, axcam.proj))
        exp_x = (i + 0.5) / spec.ni * 2 - 1
        exp_y = 1 - (j + 0.5) / spec.nj * 2
        assert abs(ndc[0] - exp_x) < 1e-3, (i, j, ndc)
        assert abs(ndc[1] - exp_y) < 1e-3, (i, j, ndc)
        assert abs(ndc[2] - (-1.0)) < 1e-3  # ref plane == near plane


@pytest.mark.parametrize("eye", [(0.0, 0.3, 2.8), (2.6, 0.4, 0.9),
                                 (-2.4, -0.5, -1.1), (0.5, 2.7, -0.4)])
def test_raycast_parity_vs_gather(vol, tf, eye):
    """Cross-engine parity on all march axes/signs."""
    cam = Camera.create(eye, fov_y_deg=45.0, near=0.3, far=12.0)
    w, h = 96, 80
    ref = raycast(vol, tf, cam, w, h).image
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    got = slicer.raycast_mxu(vol, tf, cam, w, h, spec).image
    q = psnr(ref, got)
    assert q > 28.0, f"PSNR {q:.1f} dB at eye {eye}"


def test_raycast_bf16_close(vol, tf):
    cam = Camera.create((0.0, 0.4, 2.8), fov_y_deg=45.0, near=0.3, far=12.0)
    w, h = 96, 80
    spec32 = slicer.make_spec(cam, vol.data.shape, F32)
    spec16 = slicer.make_spec(
        cam, vol.data.shape,
        SliceMarchConfig(matmul_dtype="bf16", scale=1.5))
    a = slicer.raycast_mxu(vol, tf, cam, w, h, spec32).image
    b = slicer.raycast_mxu(vol, tf, cam, w, h, spec16).image
    assert psnr(a, b) > 35.0


def test_homogeneous_transmittance(tf):
    """A homogeneous box must attenuate per Beer-Lambert regardless of the
    sampling schedule: checks the per-ray path-length opacity correction."""
    data = jnp.full((32, 32, 32), 0.5, jnp.float32)
    vol = Volume.centered(data, extent=1.0)
    tf_c = TransferFunction.ramp(0.0, 1.0, 0.4, "grays")
    cam = Camera.create((0.0, 0.0, 3.0), fov_y_deg=20.0, near=0.5, far=10.0)
    w = h = 32
    ref = raycast(vol, tf_c, cam, w, h, None).image
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    got = slicer.raycast_mxu(vol, tf_c, cam, w, h, spec).image
    # compare center pixel alpha (full path through the cube)
    ra = float(ref[3, h // 2, w // 2])
    ga = float(got[3, h // 2, w // 2])
    assert abs(ra - ga) < 0.03, (ra, ga)


def test_volume_partially_outside(vol, tf):
    """Oblique close-up: part of the image misses the volume; no NaNs and
    misses keep the background."""
    cam = Camera.create((0.9, 0.8, 1.2), target=(0.4, 0.3, 0.0),
                        fov_y_deg=70.0, near=0.1, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    out = slicer.raycast_mxu(vol, tf, cam, 64, 64, spec,
                             background=(0.1, 0.2, 0.3, 1.0))
    img = np.asarray(out.image)
    assert np.isfinite(img).all()
    assert (img >= 0).all() and (img <= 1.0 + 1e-5).all()


def test_generate_vdi_mxu_renders_like_raycast(vol, tf):
    """VDI built by the slice march, decoded by the (unchanged) novel-view
    renderer at the real camera, must approximate the direct render."""
    cam = Camera.create((0.3, 0.5, 2.7), fov_y_deg=45.0, near=0.3, far=12.0)
    w, h = 80, 64
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam, spec, VDIConfig(max_supersegments=12, adaptive_iters=4))
    img = render_vdi(vdi, meta, cam, w, h, steps=160)
    ref = raycast(vol, tf, cam, w, h).image
    q = psnr(ref, img)
    assert q > 22.0, f"PSNR {q:.1f} dB"


def test_generate_vdi_mxu_vs_gather_vdi(vol, tf):
    """Same-view decode of MXU VDI vs gather VDI (both through render_vdi
    at the true camera)."""
    cam = Camera.create((0.0, 0.4, 2.6), fov_y_deg=45.0, near=0.3, far=12.0)
    w, h = 64, 64
    cfg = VDIConfig(max_supersegments=12, adaptive_iters=4)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    vdi_m, meta_m, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg)
    vdi_g, meta_g = generate_vdi(vol, tf, cam, w, h, cfg, max_steps=160)
    img_m = render_vdi(vdi_m, meta_m, cam, w, h, steps=160)
    img_g = render_vdi(vdi_g, meta_g, cam, w, h, steps=160)
    q = psnr(img_g, img_m)
    assert q > 22.0, f"PSNR {q:.1f} dB"


def test_vdi_depths_ordered(vol, tf):
    cam = Camera.create((0.0, 0.4, 2.6), fov_y_deg=45.0, near=0.3, far=12.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    vdi, meta, _ = slicer.generate_vdi_mxu(
        vol, tf, cam, spec, VDIConfig(max_supersegments=8, adaptive_iters=3))
    start = np.asarray(vdi.depth[:, 0])
    end = np.asarray(vdi.depth[:, 1])
    live = np.asarray(vdi.color[:, 3]) > 0
    assert (end[live] >= start[live]).all()
    # consecutive live slots are depth-sorted
    k = vdi.k
    for s in range(k - 1):
        both = live[s] & live[s + 1]
        assert (start[s + 1][both] >= end[s][both] - 1e-4).all()


def test_warp_roundtrip_identity(vol):
    """Warping a smooth intermediate image to a camera looking straight
    down the axis reproduces the image structure (low-frequency check)."""
    cam = Camera.create((0.0, 0.0, 3.0), fov_y_deg=40.0, near=0.5, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    axcam = slicer.make_axis_camera(vol, cam, spec)
    jj, ii = jnp.meshgrid(jnp.linspace(0, 1, spec.nj),
                          jnp.linspace(0, 1, spec.ni), indexing="ij")
    img = jnp.stack([ii, jj, ii * jj, jnp.ones_like(ii)])
    out = slicer.warp_to_camera(img, axcam, spec, cam, 48, 48,
                                background=None)
    o = np.asarray(out)
    assert np.isfinite(o).all()
    # u increases to the right, v decreases downward in both spaces
    assert o[0, 24, 40] > o[0, 24, 8]
    assert o[1, 40, 24] > o[1, 8, 24]


# ------------------------------------------------- occupancy acceleration


def test_occupancy_skip_is_exact(vol, tf):
    """Empty-space skipping must not change a single output value: the
    skipped branch feeds one explicit empty sample, reproducing the gap
    semantics of the full march bit-for-bit."""
    cam = Camera.create((0.3, 0.5, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    spec_on = slicer.make_spec(cam, vol.data.shape, F32)
    spec_off = slicer.make_spec(
        cam, vol.data.shape,
        SliceMarchConfig(matmul_dtype="f32", scale=1.5, skip_empty=False))
    cfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    vdi_on, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_on, cfg)
    vdi_off, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_off, cfg)
    np.testing.assert_allclose(np.asarray(vdi_on.color),
                               np.asarray(vdi_off.color), atol=1e-6)
    d_on = np.nan_to_num(np.asarray(vdi_on.depth), posinf=1e9)
    d_off = np.nan_to_num(np.asarray(vdi_off.depth), posinf=1e9)
    np.testing.assert_allclose(d_on, d_off, atol=1e-5)


def test_occupancy_flags_conservative(tf):
    """Every chunk flagged empty must truly contribute zero alpha — checked
    in MARCH order (chunk_occupancy chunks the permuted+flipped volume), on
    an asymmetric band so a flip-indexing regression cannot pass."""
    data = jnp.zeros((64, 16, 16), jnp.float32)
    data = data.at[8:24].set(0.9)          # asymmetric occupied band
    v = Volume.centered(data, extent=2.0)
    cam = Camera.create((0.0, 0.2, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, v.data.shape, F32)
    assert spec.axis == 2                  # the camera this test assumes
    occ = np.asarray(slicer.chunk_occupancy(v, tf, spec))
    assert occ.sum() < occ.size            # something was skippable
    volp = np.asarray(slicer.permute_volume(v, spec))   # march layout
    c = spec.chunk
    for ci in range(occ.size):
        band = volp[ci * c:(ci + 1) * c]
        if band.size and band.max() > 0.5:
            assert occ[ci], f"occupied chunk {ci} flagged empty"
        if band.size and band.max() < 1e-6:
            assert not occ[ci], f"empty chunk {ci} flagged occupied"


def test_render_slices_early_stop_exact(tf):
    """Saturation early-out must not change the image (gated pixels stop
    accumulating anyway)."""
    data = jnp.full((48, 48, 48), 0.95, jnp.float32)   # dense, saturates fast
    v = Volume.centered(data, extent=2.0)
    cam = Camera.create((0.0, 0.1, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, v.data.shape, F32)
    axcam = slicer.make_axis_camera(v, cam, spec)
    out_fast = slicer.render_slices(v, tf, axcam, spec)
    # reference: no occupancy, no early stop
    spec_off = slicer.make_spec(
        cam, v.data.shape,
        SliceMarchConfig(matmul_dtype="f32", scale=1.5, skip_empty=False))
    axcam2 = slicer.make_axis_camera(v, cam, spec_off)

    def consume(carry, rgba, t0, t1):
        acc, first_t = carry
        for i in range(rgba.shape[0]):
            gate = (acc[3] < 0.999).astype(jnp.float32)
            src = rgba[i] * gate[None]
            acc = acc + (1.0 - acc[3:4]) * src
            first_t = jnp.where((first_t == jnp.inf) & (src[3] > 1e-4),
                                t0[i], first_t)
        return acc, first_t

    acc0 = jnp.zeros((4, spec_off.nj, spec_off.ni), jnp.float32)
    ft0 = jnp.full((spec_off.nj, spec_off.ni), jnp.inf, jnp.float32)
    acc, _ = slicer.slice_march(v, tf, axcam2, spec_off, consume, (acc0, ft0))
    np.testing.assert_allclose(np.asarray(out_fast.image), np.asarray(acc),
                               atol=1e-5)


def test_hittable_mask_conservative():
    """Every pixel that accumulates any alpha must be flagged hittable, and
    the mask must exclude some frustum-margin pixels (it exists so that
    whole-grid predicates can ignore rays that miss the volume)."""
    data = jnp.full((48, 48, 48), 0.95, jnp.float32)
    tf = TransferFunction.ramp(0.0, 0.5, 1.0)
    v = Volume.centered(data, extent=2.0)
    cam = Camera.create((0.0, 0.1, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, v.data.shape, F32)
    axcam = slicer.make_axis_camera(v, cam, spec)
    out = slicer.render_slices(v, tf, axcam, spec)
    miss = ~np.asarray(slicer.hittable_mask(v, axcam, spec))
    hit = np.asarray(out.image[3]) > 1e-4
    assert not (hit & miss).any()
    assert miss.any()                      # margins are excluded


def test_slice_march_early_stop_mechanism():
    """The generic early_stop hook must actually skip chunks: a consumer
    counting processed samples sees fewer once the predicate turns true,
    while a permanently-false predicate reproduces the full march."""
    data = jnp.full((64, 16, 16), 0.5, jnp.float32)
    tf = TransferFunction.ramp(0.0, 0.5, 1.0)
    v = Volume.centered(data, extent=2.0)
    cam = Camera.create((0.0, 0.0, 3.0), fov_y_deg=45.0)
    spec = slicer.make_spec(cam, v.data.shape, F32)
    axcam = slicer.make_axis_camera(v, cam, spec)

    def consume(carry, rgba, t0, t1):
        return carry + rgba.shape[0]       # samples seen

    full = slicer.slice_march(v, tf, axcam, spec, consume,
                              jnp.int32(0),
                              early_stop=lambda c: jnp.bool_(False))
    stopped = slicer.slice_march(v, tf, axcam, spec, consume,
                                 jnp.int32(0),
                                 early_stop=lambda c: c >= spec.chunk)
    assert int(full) > int(stopped)
    # after the first chunk the predicate is true: one full chunk + one
    # empty sample per remaining chunk
    nchunks = int(full) // spec.chunk
    assert int(stopped) == spec.chunk + (nchunks - 1)


def test_update_threshold_controller():
    """One bisection step per frame: over-cap moves up inside the bracket,
    in-band holds (and tightens hi), under-band moves down; the bracket
    makes a knife-edge pixel converge instead of oscillating."""
    from scenery_insitu_tpu.ops import supersegments as ss

    thr = jnp.array([[0.1, 0.1, 0.1]], jnp.float32)
    st = ss.init_threshold_state(thr, thr_min=1e-3, thr_max=2.0)
    cnt = jnp.array([[40, 10, 6]], jnp.int32)   # K=10, delta=0.15
    new = ss.update_threshold(st, cnt, 10, delta=0.15,
                              thr_min=1e-3, thr_max=2.0)
    t = np.asarray(new.thr)
    assert t[0, 0] == pytest.approx(0.5 * (0.1 + 2.0))   # bisect toward hi
    assert t[0, 1] == pytest.approx(0.1)                 # in band: hold
    assert t[0, 2] == pytest.approx(0.5 * (0.1 + 1e-3))  # bisect toward lo
    assert float(new.lo[0, 0]) == pytest.approx(0.1 * 0.9)  # decayed bound
    assert float(new.hi[0, 1]) == pytest.approx(0.1 / 0.9)

    # knife-edge convergence: count jumps 14 -> 4 across thr*, a plain
    # multiplicative controller oscillates forever; the bracket pins it
    def count_of(t):
        return jnp.where(t < 0.31, 14, 4).astype(jnp.int32)

    st = ss.init_threshold_state(jnp.full((1, 1), 0.01, jnp.float32))
    over_frames = 0
    for _ in range(30):
        c = count_of(st.thr)
        over_frames += int((c > 10).sum())
        st = ss.update_threshold(st, c, 10)
    # after convergence the threshold sits on the fitting side of the edge
    final_over = int((count_of(st.thr) > 10).sum())
    assert final_over == 0
    assert over_frames < 10   # transient only, not persistent oscillation


def test_temporal_mode_matches_histogram_quality(vol, tf):
    """After the seeded march, temporal one-march frames render like the
    per-frame histogram mode, and the carried counts sit in/below band."""
    from scenery_insitu_tpu.ops import supersegments as ss

    cam = Camera.create((0.3, 0.5, 2.7), fov_y_deg=45.0, near=0.3, far=12.0)
    w, h = 80, 64
    k = 12
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    cfg_t = VDIConfig(max_supersegments=k, adaptive_mode="temporal")
    cfg_h = VDIConfig(max_supersegments=k, adaptive_mode="histogram")

    thr = slicer.initial_threshold(vol, tf, cam, spec, cfg_t)
    assert thr.thr.shape == (spec.nj, spec.ni)
    for _ in range(6):
        vdi_t, meta_t, _, thr = slicer.generate_vdi_mxu_temporal(
            vol, tf, cam, spec, thr, cfg_t)
    vdi_h, meta_h, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg_h)

    img_t = render_vdi(vdi_t, meta_t, cam, w, h, steps=160)
    img_h = render_vdi(vdi_h, meta_h, cam, w, h, steps=160)
    q = psnr(img_h, img_t)
    assert q > 25.0, f"PSNR {q:.1f} dB"

    # steady state: the TRUE (uncapped) segment count at the converged
    # threshold stays within the cap for (nearly) every pixel — measured
    # by an independent counting march, not the capped writer state
    axcam = slicer.make_axis_camera(vol, cam, spec)

    def consume(cst, rgba, t0, t1):
        for i in range(rgba.shape[0]):
            cst = ss.push_count(cst, thr.thr, rgba[i])
        return cst

    counts = np.asarray(slicer.slice_march(
        vol, tf, axcam, spec, consume,
        ss.init_count(spec.nj, spec.ni)).count)
    frac_over = (counts > k).mean()
    assert frac_over < 0.01, f"{frac_over:.3%} of pixels over cap"


def test_generate_vdi_mxu_rejects_temporal_mode(vol, tf):
    cam = Camera.create((0.0, 0.4, 2.6), fov_y_deg=45.0, near=0.3, far=12.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    with pytest.raises(ValueError, match="temporal"):
        slicer.generate_vdi_mxu(
            vol, tf, cam, spec, VDIConfig(adaptive_mode="temporal"))


def test_vtile_occupancy_gating_is_exact(tf):
    """In-plane occupancy tiles (spec.vtiles > 0) must change NOTHING in
    the output — gated row blocks are provably zero-alpha, so tiled and
    untiled renders and VDIs must match to the bit. Sparse corner blob:
    most (chunk, v-tile) cells empty, so the gate genuinely fires."""
    data = np.zeros((48, 48, 48), np.float32)
    data[4:16, 6:18, 8:20] = 0.8            # one blob near a corner
    svol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.3, 0.4, 2.8), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    base = SliceMarchConfig(matmul_dtype="f32", scale=1.25)
    tiled = SliceMarchConfig(matmul_dtype="f32", scale=1.25,
                             occupancy_vtiles=6)
    spec0 = slicer.make_spec(cam, svol.data.shape, base)
    spec1 = slicer.make_spec(cam, svol.data.shape, tiled)
    assert spec1.vtiles == 6

    # the occupancy structure really is tile-granular and really sparse
    occ = slicer.occupancy_for(svol, tf, spec1)
    assert isinstance(occ, tuple)
    tile_frac = float(np.asarray(occ[1]).mean())
    assert tile_frac < 0.5, f"blob scene not sparse? {tile_frac}"

    img0 = slicer.raycast_mxu(svol, tf, cam, 64, 48, spec0)
    img1 = slicer.raycast_mxu(svol, tf, cam, 64, 48, spec1)
    np.testing.assert_array_equal(np.asarray(img1.image),
                                  np.asarray(img0.image))
    np.testing.assert_array_equal(np.asarray(img1.depth),
                                  np.asarray(img0.depth))

    cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram",
                    histogram_bins=8)
    vdi0, _, _ = slicer.generate_vdi_mxu(svol, tf, cam, spec0, cfg)
    vdi1, _, _ = slicer.generate_vdi_mxu(svol, tf, cam, spec1, cfg)
    np.testing.assert_array_equal(np.asarray(vdi1.color),
                                  np.asarray(vdi0.color))
    np.testing.assert_array_equal(np.asarray(vdi1.depth),
                                  np.asarray(vdi0.depth))


def test_vtile_apron_catches_bandpass_tf():
    """The adversarial case for banded occupancy: two value plateaus
    meeting exactly AT a tile boundary, and a band-pass TF whose alpha
    peak lies strictly between the plateau values. Only interpolated
    rows near the boundary produce visible alpha; apron-less bands would
    both claim 'empty' and the gated march would drop the interface."""
    from scenery_insitu_tpu.core.transfer import TransferFunction

    n = 48
    data = np.zeros((n, n, n), np.float32)
    data[:, n // 2:, :] = 1.0               # plateau split along v (y)
    svol = Volume.centered(jnp.asarray(data), extent=2.0)
    bp_tf = TransferFunction.from_polylines(
        [(0.0, 0.0), (0.5, 0.9), (1.0, 0.0)],      # peak between plateaus
        np.array([0.0, 1.0]),
        np.array([[1.0, 0.5, 0.1], [1.0, 0.5, 0.1]], np.float32))
    cam = Camera.create((0.1, 0.2, 2.9), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    base = SliceMarchConfig(matmul_dtype="f32", scale=1.25)
    tiled = SliceMarchConfig(matmul_dtype="f32", scale=1.25,
                             occupancy_vtiles=6)   # boundary ON a tile edge
    spec0 = slicer.make_spec(cam, svol.data.shape, base)
    spec1 = slicer.make_spec(cam, svol.data.shape, tiled)
    img0 = slicer.raycast_mxu(svol, bp_tf, cam, 64, 48, spec0)
    img1 = slicer.raycast_mxu(svol, bp_tf, cam, 64, 48, spec1)
    # the interface IS visible (nonzero alpha) and the tiled render
    # reproduces it exactly
    assert float(np.asarray(img0.image)[3].max()) > 0.2
    np.testing.assert_array_equal(np.asarray(img1.image),
                                  np.asarray(img0.image))


def test_vtile_clamp_on_small_volumes():
    """An oversized occupancy_vtiles request degrades to coarser tiles
    instead of zero-width bands blowing up at trace time."""
    cam = Camera.create((0.0, 0.1, 2.8), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    spec = slicer.make_spec(cam, (16, 16, 16),
                            SliceMarchConfig(matmul_dtype="f32", scale=1.0,
                                             occupancy_vtiles=64))
    assert 0 < spec.vtiles <= 8


def test_plain_fold_matches_sequential_loop(vol, tf):
    """The chunk-parallel plain alpha-under (with its prefix-gate
    saturation semantics) must reproduce the per-slice sequential
    accumulator exactly — including first-hit depths and gate freezing."""
    cam = Camera.create((0.2, 0.5, 2.9), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.0))
    axcam = slicer.make_axis_camera(vol, cam, spec)
    # aggressive threshold so the gate actually fires mid-volume
    out = slicer.render_slices(vol, tf, axcam, spec,
                               early_exit_alpha=0.6)

    def consume_seq(carry, rgba, t0, t1):
        acc, first_t = carry
        for i in range(rgba.shape[0]):
            gate = (acc[3] < 0.6).astype(jnp.float32)
            src = rgba[i] * gate[None]
            acc = acc + (1.0 - acc[3:4]) * src
            first_t = jnp.where((first_t == jnp.inf) & (src[3] > 1e-4),
                                t0[i], first_t)
        return acc, first_t

    acc0 = jnp.zeros((4, spec.nj, spec.ni), jnp.float32)
    t0 = jnp.full((spec.nj, spec.ni), jnp.inf, jnp.float32)
    occ = slicer.occupancy_for(vol, tf, spec)
    acc, ft = slicer.slice_march(vol, tf, axcam, spec, consume_seq,
                                 (acc0, t0), occupancy=occ)
    # a pixel whose accumulated alpha lands within ~1 ulp of the gate
    # threshold may round the gate differently between the two forms and
    # shift by one full sample — measure-zero, so allow a vanishing
    # mismatch fraction instead of exact equality
    img_ok = np.isclose(np.asarray(out.image), np.asarray(acc),
                        rtol=1e-5, atol=1e-6)
    assert img_ok.mean() > 0.999, f"mismatch {1 - img_ok.mean():.2%}"
    d0, d1 = np.asarray(out.depth), np.asarray(ft)
    depth_ok = (d0 == d1) | np.isclose(d0, d1)
    assert depth_ok.mean() > 0.999
