"""Vortex-ring Navier-Stokes + particle sim tests (physics sanity — the
numeric discipline the reference's eyeball-the-GIF validation lacked)."""

import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.sim import particles as pt
from scenery_insitu_tpu.sim import vortex as vx


def test_vortex_field_normalized_and_ring_shaped():
    fl = vx.VortexFlow.init_ring((16, 16, 16))
    f = np.asarray(fl.field)
    assert f.shape == (16, 16, 16)
    assert 0.99 <= f.max() <= 1.01 and f.min() >= 0.0
    # vorticity concentrates off-axis (a ring, not a center blob)
    assert f[8, 8, 8] < 0.5 * f.max()


def test_vortex_divergence_free_and_stable():
    fl = vx.VortexFlow.init_ring((16, 16, 16))
    fl2 = vx.multi_step(fl, 10)
    u = np.asarray(fl2.u)
    assert np.isfinite(u).all()
    # the Leray projection is exact in the spectral sense (Nyquist-zeroed
    # derivative convention, same as the solver's)
    kz, ky, kx = [np.asarray(a) for a in vx._grad_axes(u.shape[1:])]
    div_hat = (kx * np.fft.rfftn(u[0]) + ky * np.fft.rfftn(u[1])
               + kz * np.fft.rfftn(u[2]))
    scale = np.abs(np.fft.rfftn(u[0])).max() + 1e-9
    assert np.abs(div_hat).max() < 1e-4 * scale


def test_vortex_energy_decays():
    fl = vx.VortexFlow.init_ring((16, 16, 16),
                                 vx.VortexParams.create(viscosity=5e-2))
    e0 = float(jnp.sum(fl.u ** 2))
    e1 = float(jnp.sum(vx.multi_step(fl, 20).u ** 2))
    assert e1 < e0


def test_sho_particles_oscillate():
    st, p = pt.sho_init(100, box=1.0)
    for _ in range(200):
        st = pt.sho_step(st, p)
    assert np.isfinite(np.asarray(st.pos)).all()
    # oscillation about center keeps the center of mass near the middle
    assert np.abs(np.asarray(st.pos.mean(axis=0)) - 0.5).max() < 0.3


def test_lj_energy_conservation():
    st, params, spec = pt.lj_init(256, density=0.4, temperature=0.5)
    _, pot0 = pt.lj_forces(st.pos, st.box, params, spec)
    e0 = float(pt.kinetic_energy(st)) + float(pot0)
    st2 = pt.lj_multi_step(st, params, spec, 40)
    _, pot2 = pt.lj_forces(st2.pos, st2.box, params, spec)
    e2 = float(pt.kinetic_energy(st2)) + float(pot2)
    assert abs(e2 - e0) / abs(e0) < 0.02, (e0, e2)


def test_lj_forces_match_bruteforce():
    st, params, spec = pt.lj_init(64, density=0.3)
    F, _ = pt.lj_forces(st.pos, st.box, params, spec)
    pos = np.asarray(st.pos)
    box = float(st.box)
    dr = pos[:, None, :] - pos[None, :, :]
    dr -= box * np.round(dr / box)
    r2 = (dr ** 2).sum(-1) + np.eye(len(pos)) * 1e10
    mask = r2 < float(params.cutoff * params.sigma) ** 2
    inv6 = (float(params.sigma) ** 2 / r2) ** 3
    fmag = 24 * (2 * inv6 ** 2 - inv6) / r2 * mask
    fref = (fmag[..., None] * dr).sum(1)
    assert np.abs(np.asarray(F) - fref).max() < 1e-3


def test_lj_cell_overflow_is_graceful():
    # cram particles into few cells; forces stay finite
    st, params, spec = pt.lj_init(128, density=2.0)
    F, _ = pt.lj_forces(st.pos, st.box, params, spec)
    assert np.isfinite(np.asarray(F)).all()


def test_speeds_and_props():
    st, p = pt.sho_init(10)
    s = pt.speeds(st)
    assert s.shape == (10,)
    assert (np.asarray(s) >= 0).all()


def test_timers():
    from scenery_insitu_tpu.runtime.timers import Timers
    lines = []
    t = Timers(window=2, log=lines.append, rank=3)
    for i in range(4):
        with t.phase("generate"):
            pass
        t.record("all_to_all", 0.01)
        t.marker("IT", i, 0.02)
        t.frame_done()
    assert t.stats["generate"].n == 4
    assert any(l.startswith("#IT:3:0:") for l in lines)
    assert any("window of 2" in l for l in lines)
    csv = t.csv()
    assert "all_to_all;0.010000" in csv
    assert t.stats["all_to_all"].stddev == 0.0


def test_advect_periodic_at_low_boundary():
    """Uniform flow across the low boundary must wrap, not clamp
    (regression: the wrap pad used to cover only the high faces)."""
    import jax.numpy as jnp
    from scenery_insitu_tpu.sim.vortex import advect_semilagrangian

    d = 8
    f = np.zeros((d, d, d), np.float32)
    f[:, :, 0] = 1.0                       # bright plane at x index 0

    # dt=0 identity check
    carrier = jnp.stack([jnp.asarray(f)] * 3)
    moved = np.asarray(advect_semilagrangian(carrier, jnp.float32(0.0)))
    np.testing.assert_allclose(moved[0], f, atol=1e-6)

    # advection velocity comes from component 0 (+0.5 voxel/t in x);
    # component 1 carries the scalar plane, back-traced by -0.5 voxels
    adv = np.asarray(advect_semilagrangian(
        jnp.stack([jnp.full((d, d, d), 0.5, jnp.float32),
                   jnp.asarray(f),
                   jnp.zeros((d, d, d), jnp.float32)]), jnp.float32(1.0)))
    carr = adv[1]
    # plane at x=0 moved +0.5: columns 0 and 1 each get half, and column 0's
    # other half must come from the wrapped x=d-1 side (which is 0), so
    # column 0 keeps exactly 0.5 -- with the old clamp bug it kept ~1.0
    np.testing.assert_allclose(carr[:, :, 0], 0.5, atol=1e-5)
    np.testing.assert_allclose(carr[:, :, 1], 0.5, atol=1e-5)
    np.testing.assert_allclose(carr[:, :, 2], 0.0, atol=1e-5)


def test_timers_frame_fps():
    import time as _time
    from scenery_insitu_tpu.runtime.timers import Timers
    t = Timers(window=100)
    for _ in range(3):
        _time.sleep(0.01)
        t.frame_done()
    assert t.stats["frame"].n == 2          # inter-frame gaps
    assert 0 < t.fps() < 1000
