"""Multi-grid scene management (core/scene.py): uneven decompositions with
ghost layers must render identically to the assembled single volume —
the seam-exactness the reference gets from OpenFPM ghosts
(DistributedVolumeRenderer.kt:116-160)."""

import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.scene import MultiGridScene
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.utils.image import psnr

VDI_CFG = VDIConfig(max_supersegments=6, adaptive_iters=2)
COMP_CFG = CompositeConfig(max_output_supersegments=8, adaptive_iters=2)
F32 = SliceMarchConfig(matmul_dtype="f32", scale=1.5)


@pytest.fixture(scope="module")
def vol():
    return procedural_volume(24, kind="blobs", seed=5)


@pytest.fixture(scope="module")
def tf():
    return for_dataset("procedural")


def _scene_z_split(vol, cuts):
    """Split a global volume into uneven z-slabs with 1-voxel ghosts."""
    scene = MultiGridScene()
    data = np.asarray(vol.data)
    d = data.shape[0]
    edges = [0] + list(cuts) + [d]
    for i, (z0, z1) in enumerate(zip(edges[:-1], edges[1:])):
        g_lo = 1 if z0 > 0 else 0
        g_hi = 1 if z1 < d else 0
        sub = data[z0 - g_lo:z1 + g_hi]
        origin = np.asarray(vol.origin) + np.array(
            [0, 0, (z0 - g_lo) * float(vol.spacing[2])], np.float32)
        scene.set_grid(0, i, sub, origin, vol.spacing,
                       ghost_lo=(0, 0, g_lo), ghost_hi=(0, 0, g_hi))
    return scene


def test_bookkeeping(vol):
    scene = _scene_z_split(vol, [7])
    assert scene.num_grids == 2
    scene.update_data(1, [np.asarray(vol.data)[:4]],
                      [np.asarray(vol.origin)], vol.spacing)
    assert scene.num_grids == 3
    scene.update_data(1, [], [], vol.spacing)
    assert scene.num_grids == 2
    lo, hi = scene.global_bounds()
    np.testing.assert_allclose(np.asarray(lo), np.asarray(vol.world_min),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(hi), np.asarray(vol.world_max),
                               atol=1e-6)


def test_plain_render_matches_single_volume(vol, tf):
    cam = Camera.create((0.3, 0.6, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    cfg = RenderConfig(width=48, height=40, max_steps=64)
    ref = raycast(vol, tf, cam, 48, 40, cfg)
    scene = _scene_z_split(vol, [7, 15])       # uneven 7/8/9 split
    got = scene.render(tf, cam, 48, 40, cfg)
    p = psnr(np.asarray(got), np.asarray(ref.image))
    assert p > 35.0, f"multi-grid plain render diverges: {p:.1f} dB"


def test_vdi_gather_matches_single_volume(vol, tf):
    cam = Camera.create((0.2, 0.5, 2.9), fov_y_deg=45.0, near=0.3, far=10.0)
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
    from scenery_insitu_tpu.ops.composite import composite_vdis
    ref_vdi, _ = generate_vdi(vol, tf, cam, 40, 32, VDI_CFG, max_steps=64)
    ref = composite_vdis(ref_vdi.color[None], ref_vdi.depth[None], COMP_CFG)
    scene = _scene_z_split(vol, [9])
    got, meta = scene.generate_vdi(tf, cam, 40, 32, VDI_CFG, COMP_CFG,
                                   max_steps=64)
    img_ref = np.asarray(render_vdi_same_view(ref))
    img_got = np.asarray(render_vdi_same_view(got))
    p = psnr(img_got, img_ref)
    assert p > 30.0, f"multi-grid VDI diverges: {p:.1f} dB"
    np.testing.assert_allclose(np.asarray(meta.volume_dims),
                               [24, 24, 24], atol=1e-4)


def test_vdi_mxu_matches_single_volume(vol, tf):
    """The flagship check: uneven multi-grid slice march ≅ one volume."""
    cam = Camera.create((0.1, 0.4, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    from scenery_insitu_tpu.ops.composite import composite_vdis
    ref_vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, VDI_CFG)
    ref = composite_vdis(ref_vdi.color[None], ref_vdi.depth[None], COMP_CFG)
    scene = _scene_z_split(vol, [5, 14])       # uneven 5/9/10 split
    got, _ = scene.generate_vdi_mxu(tf, cam, spec, VDI_CFG, COMP_CFG)
    img_ref = np.asarray(render_vdi_same_view(ref))
    img_got = np.asarray(render_vdi_same_view(got))
    p = psnr(img_got, img_ref)
    assert p > 30.0, f"multi-grid MXU VDI diverges: {p:.1f} dB"


def test_vdi_mxu_in_plane_split(vol, tf):
    """Grids split along an IN-PLANE axis (x) relative to a z-marching
    camera: exercises the u-bounds ownership + ghost-column path."""
    cam = Camera.create((0.0, 0.3, 2.8), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape, F32)
    assert spec.axis == 2
    from scenery_insitu_tpu.ops.composite import composite_vdis
    ref_vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, VDI_CFG)
    ref = composite_vdis(ref_vdi.color[None], ref_vdi.depth[None], COMP_CFG)

    data = np.asarray(vol.data)
    w = data.shape[2]
    scene = MultiGridScene()
    for i, (x0, x1) in enumerate([(0, 10), (10, 24)]):   # uneven x split
        g_lo = 1 if x0 > 0 else 0
        g_hi = 1 if x1 < w else 0
        sub = data[:, :, x0 - g_lo:x1 + g_hi]
        origin = np.asarray(vol.origin) + np.array(
            [(x0 - g_lo) * float(vol.spacing[0]), 0, 0], np.float32)
        scene.set_grid(0, i, sub, origin, vol.spacing,
                       ghost_lo=(g_lo, 0, 0), ghost_hi=(g_hi, 0, 0))
    got, _ = scene.generate_vdi_mxu(tf, cam, spec, VDI_CFG, COMP_CFG)
    img_ref = np.asarray(render_vdi_same_view(ref))
    img_got = np.asarray(render_vdi_same_view(got))
    p = psnr(img_got, img_ref)
    assert p > 30.0, f"in-plane multi-grid MXU VDI diverges: {p:.1f} dB"


def test_scene_session_external_driver(vol, tf, tmp_path):
    """The external-driver loop: push grids through the updateData
    boundary, render frames, update a grid, render again (≅ OpenFPM
    driving the JNI callbacks between frames)."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.scene_session import SceneSession
    from scenery_insitu_tpu.runtime.session import png_sink

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=1",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32",
        "runtime.dataset=procedural")
    sess = SceneSession(cfg, sinks=[png_sink(str(tmp_path))])

    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="no grids"):
        sess.render_frame()

    data = np.asarray(vol.data)
    d = data.shape[0]
    halves = [(0, 11), (11, 24)]               # uneven
    grids, origins, glo, ghi = [], [], [], []
    for z0, z1 in halves:
        g0 = 1 if z0 > 0 else 0
        g1 = 1 if z1 < d else 0
        grids.append(data[z0 - g0:z1 + g1])
        origins.append(np.asarray(vol.origin)
                       + np.array([0, 0, (z0 - g0) * float(vol.spacing[2])],
                                  np.float32))
        glo.append((0, 0, g0))
        ghi.append((0, 0, g1))
    sess.update_data(0, grids, origins, vol.spacing, glo, ghi)

    p1 = sess.render_frame()
    assert p1["vdi_color"].shape[0] == 6
    assert np.isfinite(p1["vdi_color"]).all()

    # new timestep for grid 0 (≅ updateVolume)
    sess.update_grid(0, 0, grids[0] * 0.5)
    p2 = sess.render_frame()
    assert not np.array_equal(p1["vdi_color"], p2["vdi_color"])
    import glob as _glob
    assert len(_glob.glob(str(tmp_path / "frame*.png"))) == 2


def test_scene_session_temporal_mode(vol, tf):
    """SceneSession with adaptive_mode='temporal': threshold state is
    seeded on the first frame, threaded across frames, and re-seeded when
    the grid-set signature changes (repartition)."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.scene_session import SceneSession

    cfg = FrameworkConfig().with_overrides(
        "vdi.max_supersegments=4", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=1",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32",
        "runtime.dataset=procedural")
    sess = SceneSession(cfg)
    assert sess._temporal

    data = np.asarray(vol.data)
    sess.update_data(0, [data], [np.asarray(vol.origin)], vol.spacing)
    p1 = sess.render_frame()
    assert np.isfinite(p1["vdi_color"]).all()
    assert len(sess._thr) == 1
    thr1 = next(iter(sess._thr.values()))
    assert thr1.thr.shape[0] == 1      # one grid

    p2 = sess.render_frame()        # carried state, same compiled step
    assert np.isfinite(p2["vdi_color"]).all()
    assert len(sess._steps) == 1

    # moving the scene (same shapes, new extent) must recompile the step
    # (stale-spec guard) and seed a fresh threshold entry
    sess.update_data(0, [data], [np.asarray(vol.origin) + 1.5], vol.spacing)
    p3 = sess.render_frame()
    assert np.isfinite(p3["vdi_color"]).all()
    assert len(sess._steps) == 2
    assert len(sess._thr) == 2


def test_insitu_session_rejects_temporal():
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides("vdi.adaptive_mode=temporal")
    with pytest.raises(ValueError, match="temporal"):
        InSituSession(cfg)


def test_scene_session_extent_cache_survives_update_grid(vol, tf):
    """update_grid replaces data only (origin/spacing unchanged), so the
    extent cache must NOT be invalidated — the canonical driver loop
    (update_grid every timestep, then render) would otherwise pay a
    device sync per dispatch. update_data CAN change layout and must
    invalidate."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.scene_session import SceneSession

    cfg = FrameworkConfig().with_overrides(
        "vdi.max_supersegments=4", "composite.max_output_supersegments=6",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32",
        "runtime.dataset=procedural")
    sess = SceneSession(cfg)
    data = np.asarray(vol.data)
    sess.update_data(0, [data], [np.asarray(vol.origin)], vol.spacing)
    sess.render_frame()
    assert sess._extent_cache is not None
    cached = sess._extent_cache

    sess.update_grid(0, 0, data * 0.5)
    assert sess._extent_cache is cached     # same layout: no sync forced
    sess.render_frame()

    sess.update_data(0, [data], [np.asarray(vol.origin) + 1.0], vol.spacing)
    assert sess._extent_cache is None       # layout change invalidates


def test_scene_session_temporal_reseeds_on_regime_reentry(vol, tf):
    """A camera returning to a previously visited march regime must NOT
    reuse the threshold map frozen when it left (the grids kept updating):
    the entry is dropped and re-seeded, mirroring InSituSession."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.runtime.scene_session import SceneSession

    cfg = FrameworkConfig().with_overrides(
        "vdi.max_supersegments=4", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=1",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32",
        "runtime.dataset=procedural")
    sess = SceneSession(cfg)
    data = np.asarray(vol.data)
    sess.update_data(0, [data], [np.asarray(vol.origin)], vol.spacing)

    cam_z = Camera.create((0.1, 0.2, 3.0), fov_y_deg=50.0, near=0.3,
                          far=20.0)
    cam_x = Camera.create((3.0, 0.2, 0.1), fov_y_deg=50.0, near=0.3,
                          far=20.0)
    sess.camera = cam_z
    sess.render_frame()
    (key_z,) = list(sess._thr)
    stale = sess._thr[key_z]

    sess.camera = cam_x                      # leave the +z regime
    sess.render_frame()
    sess.update_grid(0, 0, data * 0.25)      # grids evolve meanwhile

    sess.camera = cam_z                      # return: must re-seed
    sess.render_frame()
    assert sess._thr[key_z] is not stale


def test_scene_session_prewarm_regimes(vol, tf):
    """SceneSession.prewarm_regimes: precompiles per-regime steps for the
    current scene, leaves camera/threshold/frame state untouched, and the
    first real frame reuses the prewarmed step."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.scene_session import SceneSession

    cfg = FrameworkConfig().with_overrides(
        "vdi.max_supersegments=4", "vdi.adaptive_mode=temporal",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=1",
        "slicer.engine=mxu", "slicer.matmul_dtype=f32",
        "runtime.dataset=procedural")
    sess = SceneSession(cfg)
    sess.update_data(0, [np.asarray(vol.data)], [np.asarray(vol.origin)],
                     vol.spacing)
    start = sess._slicer.choose_axis(sess.camera)
    eye0 = np.asarray(sess.camera.eye).copy()
    times = sess.prewarm_regimes(regimes=[start, (0, 1)])
    assert set(times) == {start, (0, 1)}
    assert len(sess._steps) == 2
    assert sess._thr == {}                 # invisible to the loop
    assert sess.frame_index == 0
    assert np.allclose(eye0, np.asarray(sess.camera.eye))
    p = sess.render_frame()
    assert np.isfinite(p["vdi_color"]).all()
    assert len(sess._steps) == 2           # no third compile
