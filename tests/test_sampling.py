import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops.sampling import (adjust_opacity, intersect_aabb,
                                             sample_trilinear,
                                             sample_volume_world)


def test_trilinear_at_voxel_centers():
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.random((4, 5, 6), dtype=np.float32))
    zz, yy, xx = np.meshgrid(range(4), range(5), range(6), indexing="ij")
    pos = jnp.asarray(np.stack([xx + 0.5, yy + 0.5, zz + 0.5], -1), jnp.float32)
    out = sample_trilinear(data, pos)
    assert np.allclose(np.asarray(out), np.asarray(data), atol=1e-6)


def test_trilinear_midpoint_linear():
    data = jnp.zeros((2, 2, 2), jnp.float32).at[:, :, 1].set(1.0)
    v = sample_trilinear(data, jnp.array([1.0, 0.5, 0.5]))  # halfway in x
    assert np.isclose(float(v), 0.5, atol=1e-6)


def test_trilinear_clamps_outside():
    data = jnp.ones((3, 3, 3), jnp.float32)
    v = sample_trilinear(data, jnp.array([-5.0, -5.0, -5.0]))
    assert np.isclose(float(v), 1.0)


def test_world_sampling_respects_origin_spacing():
    vol = Volume.create(jnp.arange(8, dtype=jnp.float32).reshape(2, 2, 2) / 7.0,
                        origin=(10.0, 20.0, 30.0), spacing=(2.0, 2.0, 2.0))
    # world pos of voxel (z=0,y=0,x=1) center = origin + (1.5, .5, .5)*spacing
    v = sample_volume_world(vol, jnp.array([13.0, 21.0, 31.0]))
    assert np.isclose(float(v), 1.0 / 7.0, atol=1e-6)


def test_aabb_hit_and_miss():
    origin = jnp.array([0.0, 0.0, 5.0])
    dirs = jnp.stack([jnp.array([0.0, 0.0]),
                      jnp.array([0.0, 1.0]),
                      jnp.array([-1.0, 0.0])])  # [3, 2]: one hit, one miss
    tn, tf = intersect_aabb(origin, dirs, jnp.array([-1.0, -1.0, -1.0]),
                            jnp.array([1.0, 1.0, 1.0]))
    assert float(tn[0]) == 4.0 and float(tf[0]) == 6.0
    assert float(tn[1]) > float(tf[1])


def test_adjust_opacity_composes():
    # compositing N sub-steps with ratio 1/N == one full step
    a = 0.7
    n = 8
    sub = adjust_opacity(jnp.array(a), 1.0 / n)
    total = 1.0 - (1.0 - float(sub)) ** n
    assert np.isclose(total, a, atol=1e-5)


def test_procedural_volume_normalized():
    vol = procedural_volume(16, kind="blobs")
    assert vol.data.shape == (16, 16, 16)
    assert float(vol.data.max()) <= 1.0 and float(vol.data.min()) >= 0.0
    assert np.allclose(np.asarray(vol.world_max + vol.world_min), 0.0, atol=1e-5)
