"""Tests for models/pipelines.py — the composed frame steps that bench.py
and __graft_entry__.py measure/compile-check (the flagship single-chip hot
path; ≅ the reference's manageVDIGeneration loop body,
DistributedVolumes.kt:683-933, collapsed into one jitted function)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
from scenery_insitu_tpu.models.pipelines import grayscott_vdi_frame_step
from scenery_insitu_tpu.sim import grayscott as gs

GRID = 32
EYE = jnp.array([0.0, 0.5, 3.2], jnp.float32)


def _step(mode):
    return grayscott_vdi_frame_step(
        width=48, height=48, sim_steps=2, max_steps=48, engine="mxu",
        vdi_cfg=VDIConfig(max_supersegments=6, adaptive_iters=2,
                          adaptive_mode=mode),
        comp_cfg=CompositeConfig(max_output_supersegments=6,
                                 adaptive_iters=2),
        grid_shape=(GRID,) * 3, axis_sign=(2, -1))


def test_temporal_frame_step_threads_threshold():
    st = gs.GrayScott.init((GRID,) * 3)
    step = _step("temporal")
    thr = jax.jit(step.init_threshold)(st.u, st.v, EYE)
    # intermediate grid is square here
    assert thr.thr.shape[0] == thr.thr.shape[1]

    jstep = jax.jit(step)
    u, v = st.u, st.v
    for _ in range(2):
        c, d, u, v, thr = jstep(u, v, EYE, thr)
    assert np.isfinite(np.asarray(c)).all()
    assert np.isfinite(np.asarray(thr.thr)).all()
    assert (np.asarray(thr.thr) > 0).all()

    # temporal and histogram steps agree on the VDI tensor shapes
    ch, dh, _, _ = jax.jit(_step("histogram"))(st.u, st.v, EYE)
    assert ch.shape == c.shape and dh.shape == d.shape


def test_temporal_requires_mxu_engine():
    with pytest.raises(ValueError, match="temporal"):
        grayscott_vdi_frame_step(
            width=48, height=48, engine="gather",
            vdi_cfg=VDIConfig(adaptive_mode="temporal"),
            grid_shape=(GRID,) * 3, axis_sign=(2, -1))


def test_bf16_render_dtype_close_to_f32():
    """render_dtype='bf16' (the 1024^3 memory plan: f32 sim, bf16 render
    copy) must keep the sim state f32 and the composited VDI close to the
    f32-render reference — the field cast is the only difference."""
    from scenery_insitu_tpu.models.pipelines import grayscott_vdi_frame_step

    st = gs.GrayScott.init((GRID,) * 3)

    def mk(rdt):
        return jax.jit(grayscott_vdi_frame_step(
            width=48, height=48, sim_steps=2, max_steps=48, engine="mxu",
            vdi_cfg=VDIConfig(max_supersegments=6, adaptive_iters=2,
                              adaptive_mode="histogram"),
            comp_cfg=CompositeConfig(max_output_supersegments=6,
                                     adaptive_iters=2),
            grid_shape=(GRID,) * 3, axis_sign=(2, -1), render_dtype=rdt))

    c32, d32, u32, v32 = mk("f32")(st.u, st.v, EYE)
    c16, d16, u16, v16 = mk("bf16")(st.u, st.v, EYE)
    assert u16.dtype == jnp.float32 and v16.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(u16), np.asarray(u32))
    # per-SLOT tensors are not comparable — bf16 value rounding moves
    # knife-edge break decisions, re-cutting segment boundaries — but the
    # DECODED image (alpha-under of all slots) must stay close: that is
    # what segmentation-invariance of the VDI means
    from scenery_insitu_tpu.core.vdi import VDI, render_vdi_same_view
    img32 = np.asarray(render_vdi_same_view(VDI(c32, d32)))
    img16 = np.asarray(render_vdi_same_view(VDI(c16, d16)))
    assert np.nanmax(np.abs(img16 - img32)) < 0.05
