"""H.264 I_PCM elementary-stream writer (io/h264.py): bitstream-level
round trips through an INDEPENDENT minimal parser transcribed from the
spec's syntax tables (so the writer is pinned to H.264 syntax, not to
itself), emulation-prevention behavior, header field checks, the frame
sink, and an opportunistic decode through cv2 when this build can."""

import numpy as np
import pytest

from scenery_insitu_tpu.io.h264 import (BitWriter, H264IPCMWriter,
                                        _emulation_prevent, h264_sink,
                                        rgb_to_yuv420)


# ------------------------------------------------ independent spec parser


class BitReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def u(self, bits: int) -> int:
        v = 0
        for _ in range(bits):
            byte = self.data[self.pos // 8]
            v = (v << 1) | ((byte >> (7 - self.pos % 8)) & 1)
            self.pos += 1
        return v

    def ue(self) -> int:
        zeros = 0
        while self.u(1) == 0:
            zeros += 1
        return (1 << zeros) - 1 + (self.u(zeros) if zeros else 0)

    def se(self) -> int:
        k = self.ue()
        return (k + 1) // 2 if k % 2 else -(k // 2)

    def align(self) -> None:
        self.pos = (self.pos + 7) & ~7

    def raw(self, n: int) -> bytes:
        assert self.pos % 8 == 0
        b = self.data[self.pos // 8:self.pos // 8 + n]
        self.pos += 8 * n
        return b


def split_nals(stream: bytes):
    """Annex-B: split on 00 00 00 01 / 00 00 01 start codes and strip
    emulation-prevention bytes."""
    import re
    parts = re.split(b"\x00\x00\x00\x01|\x00\x00\x01", stream)
    nals = []
    for p in parts:
        if not p:
            continue
        rbsp = re.sub(b"\x00\x00\x03", b"\x00\x00", p[1:])
        nals.append((p[0] & 0x1F, rbsp))
    return nals


def parse_sps(r: BitReader) -> dict:
    d = {"profile": r.u(8), "constraints": r.u(8), "level": r.u(8),
         "sps_id": r.ue(), "log2_mfn_m4": r.ue(), "poc_type": r.ue(),
         "max_ref": r.ue(), "gaps": r.u(1)}
    d["mb_w"] = r.ue() + 1
    d["mb_h"] = r.ue() + 1
    d["frame_mbs_only"] = r.u(1)
    d["direct_8x8"] = r.u(1)
    d["crop"] = r.u(1)
    if d["crop"]:
        d["crop_lrtb"] = (r.ue(), r.ue(), r.ue(), r.ue())
    else:
        d["crop_lrtb"] = (0, 0, 0, 0)
    d["vui"] = r.u(1)
    if d["vui"]:
        assert r.u(4) == 0          # aspect/overscan/signal/chroma flags
        d["timing"] = r.u(1)
        if d["timing"]:
            units = r.u(32)
            scale = r.u(32)
            d["fps"] = scale / (2.0 * units)
            d["fixed_rate"] = r.u(1)
    return d


def decode_ipcm_frame(rbsp: bytes, sps: dict):
    """Parse one IDR slice of all-I_PCM macroblocks -> (Y, Cb, Cr) of
    the PADDED (macroblock-aligned) frame + header fields."""
    r = BitReader(rbsp)
    hdr = {"first_mb": r.ue(), "slice_type": r.ue(), "pps_id": r.ue(),
           "frame_num": r.u(4 + sps["log2_mfn_m4"]), "idr_pic_id": r.ue(),
           "no_output": r.u(1), "long_term": r.u(1), "qp_delta": r.se()}
    mw, mh = sps["mb_w"], sps["mb_h"]
    y = np.zeros((mh * 16, mw * 16), np.uint8)
    cb = np.zeros((mh * 8, mw * 8), np.uint8)
    cr = np.zeros((mh * 8, mw * 8), np.uint8)
    for my in range(mh):
        for mx in range(mw):
            mb_type = r.ue()
            assert mb_type == 25, f"not I_PCM at ({my},{mx}): {mb_type}"
            r.align()
            y[my * 16:(my + 1) * 16, mx * 16:(mx + 1) * 16] = \
                np.frombuffer(r.raw(256), np.uint8).reshape(16, 16)
            cb[my * 8:(my + 1) * 8, mx * 8:(mx + 1) * 8] = \
                np.frombuffer(r.raw(64), np.uint8).reshape(8, 8)
            cr[my * 8:(my + 1) * 8, mx * 8:(mx + 1) * 8] = \
                np.frombuffer(r.raw(64), np.uint8).reshape(8, 8)
    assert r.u(1) == 1                       # rbsp_stop_one_bit
    return y, cb, cr, hdr


# ----------------------------------------------------------------- tests


def test_exp_golomb_roundtrip():
    w = BitWriter()
    vals = [0, 1, 2, 3, 7, 24, 25, 255, 1023]
    for v in vals:
        w.ue(v)
    sv = [0, 1, -1, 3, -6, 12]
    for v in sv:
        w.se(v)
    w.rbsp_trailing()
    r = BitReader(w.getvalue())
    assert [r.ue() for _ in vals] == vals
    assert [r.se() for _ in sv] == sv


def test_emulation_prevention():
    assert _emulation_prevent(b"\x00\x00\x00") == b"\x00\x00\x03\x00"
    assert _emulation_prevent(b"\x00\x00\x01") == b"\x00\x00\x03\x01"
    assert _emulation_prevent(b"\x00\x00\x04") == b"\x00\x00\x04"
    assert _emulation_prevent(b"\x00\x00\x00\x00") == \
        b"\x00\x00\x03\x00\x00"
    assert _emulation_prevent(b"ab\x00\x00\x02cd") == \
        b"ab\x00\x00\x03\x02cd"
    # un-prevention inverts (what any decoder does)
    import re
    rng = np.random.default_rng(0)
    for _ in range(50):
        raw = rng.integers(0, 4, size=rng.integers(1, 200),
                           dtype=np.uint8).tobytes()
        prevented = _emulation_prevent(raw)
        assert b"\x00\x00\x00" not in prevented
        assert b"\x00\x00\x01" not in prevented
        assert b"\x00\x00\x02" not in prevented
        assert re.sub(b"\x00\x00\x03", b"\x00\x00", prevented) == raw


def test_stream_structure_and_lossless_roundtrip():
    rng = np.random.default_rng(1)
    w, h = 52, 38                            # non-multiple-of-16: cropping
    enc = H264IPCMWriter(w, h)
    rgb0 = rng.random((h, w, 3)).astype(np.float32)
    rgb1 = rng.random((h, w, 3)).astype(np.float32)
    stream = enc.headers() + enc.encode_rgb(rgb0) + enc.encode_rgb(rgb1)

    nals = split_nals(stream)
    assert [t for t, _ in nals] == [7, 8, 5, 5]   # SPS, PPS, IDR, IDR
    sps = parse_sps(BitReader(nals[0][1]))
    assert sps["profile"] == 66 and sps["poc_type"] == 2
    assert sps["mb_w"] == 4 and sps["mb_h"] == 3
    # cropping restores the exact frame size (4:2:0 => 2-px crop units)
    assert 16 * sps["mb_w"] - 2 * sps["crop_lrtb"][1] == w
    assert 16 * sps["mb_h"] - 2 * sps["crop_lrtb"][3] == h

    ids = []
    for (rgb, (_, rbsp)) in zip((rgb0, rgb1), nals[2:]):
        y, cb, cr, hdr = decode_ipcm_frame(rbsp, sps)
        assert hdr["slice_type"] == 7 and hdr["frame_num"] == 0
        ids.append(hdr["idr_pic_id"])
        ey, ecb, ecr = rgb_to_yuv420(rgb)
        np.testing.assert_array_equal(y[:h, :w], ey)     # LOSSLESS
        np.testing.assert_array_equal(cb[:h // 2, :w // 2], ecb)
        np.testing.assert_array_equal(cr[:h // 2, :w // 2], ecr)
    assert ids == [0, 1]                      # consecutive IDRs differ


def test_vui_timing_and_level_derivation():
    enc = H264IPCMWriter(64, 48, fps=24.0)
    sps = parse_sps(BitReader(split_nals(enc.sps())[0][1]))
    assert sps["timing"] == 1 and abs(sps["fps"] - 24.0) < 1e-6
    assert sps["level"] == 10                      # 12 MBs fits level 1
    assert H264IPCMWriter(1920, 1088).level_idc == 40   # 8160 MBs
    assert H264IPCMWriter(2560, 1440).level_idc == 50   # > 4.2's MaxFS
    with pytest.raises(ValueError, match="level"):
        H264IPCMWriter(16384, 8192)                # beyond level 5.1


def test_sink_accepts_chw_rgb_and_hwc():
    from scenery_insitu_tpu.io.h264 import h264_sink as mk
    import tempfile, os
    rng = np.random.default_rng(2)
    base = rng.random((34, 46, 3)).astype(np.float32)
    outs = []
    for frame in (np.moveaxis(base, -1, 0),            # [3, H, W] CHW
                  np.concatenate([np.moveaxis(base, -1, 0),
                                  np.ones((1, 34, 46), np.float32)]),
                  base):                               # [H, W, 3] HWC
        path = tempfile.mktemp(suffix=".h264")
        with mk(path) as sink:
            sink(frame)
        outs.append(open(path, "rb").read())
        os.unlink(path)
    assert outs[0] == outs[2]                  # CHW == HWC, same pixels
    sps = parse_sps(BitReader(split_nals(outs[0])[0][1]))
    assert 16 * sps["mb_w"] - 2 * sps["crop_lrtb"][1] == 46
    assert 16 * sps["mb_h"] - 2 * sps["crop_lrtb"][3] == 34


def test_yuv_studio_range():
    rgb = np.stack([np.zeros((16, 16)), np.ones((16, 16)),
                    np.full((16, 16), 0.5)], axis=-1).astype(np.float32)
    y, cb, cr = rgb_to_yuv420(rgb)
    assert y.min() >= 16 and y.max() <= 235
    assert cb.min() >= 16 and cb.max() <= 240
    assert cr.min() >= 16 and cr.max() <= 240


def test_sink_writes_playable_file(tmp_path):
    path = str(tmp_path / "out.h264")
    frames = [np.random.default_rng(i).random((4, 34, 46)).astype(np.float32)
              for i in range(3)]
    with h264_sink(path) as sink:
        for f in frames:
            sink(f)
        assert sink.codec == "h264_ipcm" and sink.frames == 3
    stream = open(path, "rb").read()
    nals = split_nals(stream)
    assert [t for t, _ in nals] == [7, 8, 5, 5, 5]
    sps = parse_sps(BitReader(nals[0][1]))
    y, _, _, _ = decode_ipcm_frame(nals[2][1], sps)
    assert y[:34, :46].std() > 1.0            # real image content


def test_cv2_decodes_when_capable(tmp_path):
    """Conformance through a REAL decoder: this cv2 build ships an H264
    DECODER (it's the encoder that's absent), so the written stream must
    decode, and the decoded image must match our own BT.601 studio-range
    reconstruction of the encoded 4:2:0 planes — i.e. the only loss is
    the chroma subsampling the format itself imposes, proving both the
    bitstream syntax and the color coding are what a decoder expects."""
    cv2 = pytest.importorskip("cv2")
    path = str(tmp_path / "dec.h264")
    rng = np.random.default_rng(7)
    rgb = rng.random((48, 64, 3)).astype(np.float32)
    enc = H264IPCMWriter(64, 48)
    with open(path, "wb") as f:
        f.write(enc.headers() + enc.encode_rgb(rgb))
    cap = cv2.VideoCapture(path)
    ok, img = cap.read() if cap.isOpened() else (False, None)
    cap.release()
    if not ok:
        pytest.skip("this cv2 build cannot decode raw H264")
    assert img.shape[:2] == (48, 64)
    bgr = img.astype(np.float32) / 255.0

    # reference: decode OUR planes back to RGB (BT.601 studio range,
    # nearest chroma upsample — cv2 may use bilinear, hence tolerance)
    y, cb, cr = rgb_to_yuv420(rgb)
    yf = y.astype(np.float32)
    cbu = np.repeat(np.repeat(cb, 2, 0), 2, 1).astype(np.float32) - 128
    cru = np.repeat(np.repeat(cr, 2, 0), 2, 1).astype(np.float32) - 128
    rec = np.clip(np.stack(
        [((yf - 16) * 255 / 219 + 1.402 * cru * 255 / 224),
         ((yf - 16) * 255 / 219 - 0.344136 * cbu * 255 / 224
          - 0.714136 * cru * 255 / 224),
         ((yf - 16) * 255 / 219 + 1.772 * cbu * 255 / 224)],
        axis=-1) / 255.0, 0, 1)
    err = np.abs(bgr[..., ::-1] - rec).mean()
    assert err < 0.02, f"decoded image diverges from the encoded " \
        f"planes: mean err {err:.3f}"
