"""Delivery-plane chaos matrix + integrity/liveness/quarantine units
(docs/ROBUSTNESS.md; ISSUE 11).

Every scenario injects seeded, deterministic faults at a failure-domain
seam (testing/faults.py) and asserts the three-part contract: the
endpoint/session stays ALIVE, the expected ``obs.degrade`` component is
minted, and no exception escapes. The clean-path control asserts parity:
with no faults, the f32 stream decodes bit-identically and the header
stays under 1% of frame bytes."""

import time

import numpy as np
import pytest

pytest.importorskip("zmq")
pytest.importorskip("msgpack")

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import FaultConfig, FrameworkConfig
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.runtime.streaming import (FrameAssembler,
                                                  SteeringEndpoint,
                                                  SteeringPublisher,
                                                  StreamDrop,
                                                  VDIPublisher,
                                                  VDISubscriber,
                                                  seq_delta)
from scenery_insitu_tpu.testing.faults import (ChaosSocket, FaultSpec,
                                               SilentRank, inject,
                                               run_matrix)

K, H, W = 4, 12, 16


def _vdi_meta(index=0):
    rng = np.random.default_rng(0)
    color = rng.random((K, 4, H, W)).astype(np.float32)
    depth = rng.random((K, 2, H, W)).astype(np.float32)
    meta = VDIMetadata.create(np.eye(4), np.eye(4), volume_dims=(8, 8, 8),
                              window_dims=(W, H), nw=0.1, index=index)
    return VDI(color, depth), meta


def _pair(**sub_kw):
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    sub = VDISubscriber(pub.endpoint, **sub_kw)
    time.sleep(0.2)                        # PUB/SUB join settles
    return pub, sub


def _drain(sub, timeout_s=5.0):
    received, drops = [], []
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = sub.receive_tile(timeout_ms=100)
        if got is None:
            break
        (drops if isinstance(got, StreamDrop) else received).append(got)
    return received, drops


# ------------------------------------------------------------- integrity

def test_corrupt_blob_drops_not_raises():
    """A corrupt blob fails the CRC before decode: typed StreamDrop,
    stream.integrity ledger, subscriber still decodes clean frames."""
    pub, sub = _pair()
    try:
        vdi, meta = _vdi_meta()
        inject(pub, FaultSpec(corrupt=1.0), seed=3)
        for i in range(3):
            pub.publish(vdi, meta._replace(index=np.int32(i)))
        received, drops = _drain(sub)
        assert received == []
        assert len(drops) == 3
        assert all(d.kind == "integrity" for d in drops)
        assert any(e["component"] == "stream.integrity"
                   for e in obs.ledger())
        # the stream outlives the bad bytes: unwrap and publish clean
        pub.sock = pub.sock.sock
        pub.publish(vdi, meta._replace(index=np.int32(9)))
        got = sub.receive(timeout_ms=3000)
        assert got is not None and not isinstance(got, StreamDrop)
        np.testing.assert_array_equal(np.asarray(vdi.color), got[0].color)
    finally:
        pub.close()
        sub.close()


def test_truncated_multipart_dropped():
    pub, sub = _pair()
    try:
        vdi, meta = _vdi_meta()
        inject(pub, FaultSpec(truncate=1.0), seed=0)
        pub.publish(vdi, meta)
        received, drops = _drain(sub, timeout_s=2.0)
        assert received == [] and len(drops) == 1
        assert drops[0].kind == "integrity"
    finally:
        pub.close()
        sub.close()


def test_lying_header_shape_dropped_before_reshape():
    """Satellite: a header declaring shapes the blob bytes cannot fill
    must be rejected by the byte-count check, not crash frombuffer/
    reshape (the pre-PR failure mode)."""
    import zlib as _zlib

    import msgpack

    pub, sub = _pair()
    try:
        cblob = _zlib.compress(b"\x00" * 64)   # far too small
        dblob = _zlib.compress(b"\x00" * 64)
        header = msgpack.packb({
            "codec": "zlib", "precision": "f32", "qscale": None,
            "tile": None, "epoch": 1, "seq": 1,
            "crc": [_zlib.crc32(cblob), _zlib.crc32(dblob)],
            "color_shape": [K, 4, H, W], "depth_shape": [K, 2, H, W],
            "meta": {}})
        pub.sock.send_multipart([header, cblob, dblob])
        got = sub.receive_tile(timeout_ms=3000)
        assert isinstance(got, StreamDrop) and got.kind == "integrity"
        assert "declared" in got.reason
    finally:
        pub.close()
        sub.close()


def test_gap_and_duplicate_detection():
    pub, sub = _pair()
    try:
        vdi, meta = _vdi_meta()
        for i in range(2):
            pub.publish(vdi, meta._replace(index=np.int32(i)))
        pub._next_seq()                        # simulate one lost message
        pub.publish(vdi, meta._replace(index=np.int32(2)))
        received, drops = _drain(sub)
        assert len(received) == 3 and drops == []
        assert sub.stats["gaps"] == 1
        assert any(e["component"] == "stream.gap" for e in obs.ledger())
        # duplicates: replay the same seq → stale drop, frame not doubled
        inject(pub, FaultSpec(duplicate=1.0), seed=0)
        pub.publish(vdi, meta._replace(index=np.int32(3)))
        received, drops = _drain(sub, timeout_s=2.0)
        assert len(received) == 1
        assert len(drops) == 1 and drops[0].kind == "stale"
    finally:
        pub.close()
        sub.close()


def test_epoch_change_resets_continuity():
    """A restarted publisher (new epoch, seq reset) must not flood the
    gap accounting — tracking resets on the epoch boundary."""
    pub, sub = _pair()
    try:
        vdi, meta = _vdi_meta()
        for i in range(3):
            pub.publish(vdi, meta._replace(index=np.int32(i)))
        _drain(sub)
        pub2 = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        sub2 = VDISubscriber(pub2.endpoint)
        time.sleep(0.2)
        # same subscriber-side logic, fresh pub: simulate via epoch swap
        pub.epoch, pub.seq = pub.epoch + 1, 0
        pub.publish(vdi, meta._replace(index=np.int32(0)))
        received, drops = _drain(sub, timeout_s=2.0)
        assert len(received) == 1 and drops == []
        assert sub.stats["epoch_changes"] == 1
        pub2.close()
        sub2.close()
    finally:
        pub.close()
        sub.close()


def test_heartbeats_keep_continuity_and_never_surface():
    pub, sub = _pair()
    try:
        vdi, meta = _vdi_meta()
        pub.publish(vdi, meta)
        pub.heartbeat()
        pub.heartbeat()
        pub.publish(vdi, meta._replace(index=np.int32(1)))
        received, drops = _drain(sub)
        assert len(received) == 2 and drops == []
        assert sub.stats["heartbeats"] == 2
        assert sub.stats["gaps"] == 0          # hb seqs fill the gaps
        assert pub.maybe_heartbeat() is False  # just sent
        pub.fault = FaultConfig(heartbeat_period_s=0.01)
        time.sleep(0.03)
        assert pub.maybe_heartbeat() is True
    finally:
        pub.close()
        sub.close()


def test_clean_path_bit_exact_and_header_overhead():
    """Acceptance: no faults → bit-identical f32 decode; header < 1% of
    frame bytes at a realistic frame size."""
    pub, sub = _pair()
    try:
        rng = np.random.default_rng(5)
        vdi = VDI(rng.random((8, 4, 48, 64)).astype(np.float32),
                  rng.random((8, 2, 48, 64)).astype(np.float32))
        meta = VDIMetadata.create(np.eye(4), np.eye(4),
                                  volume_dims=(32, 32, 32),
                                  window_dims=(64, 48), nw=0.1, index=0)
        pub.publish(vdi, meta)
        got = sub.receive(timeout_ms=5000)
        assert got is not None and not isinstance(got, StreamDrop)
        np.testing.assert_array_equal(np.asarray(vdi.color), got[0].color)
        np.testing.assert_array_equal(np.asarray(vdi.depth), got[0].depth)
        raw = np.asarray(vdi.color).nbytes + np.asarray(vdi.depth).nbytes
        assert pub.last_bytes["header"] < 0.01 * raw, pub.last_bytes
    finally:
        pub.close()
        sub.close()


# ---------------------------------------------------------- tile streams

def test_frame_assembler_completes_and_abandons():
    vdi, meta = _vdi_meta()
    color, depth = np.asarray(vdi.color), np.asarray(vdi.depth)
    asm = FrameAssembler(window=2)
    ntiles, wb = 4, W // 4

    def tiles_of(f, skip=()):
        out = []
        for t in range(ntiles):
            if t in skip:
                continue
            tv = VDI(color[..., t * wb:(t + 1) * wb],
                     depth[..., t * wb:(t + 1) * wb])
            out.append((tv, meta._replace(index=np.int32(f)),
                        {"tile": t, "tiles": ntiles, "col0": t * wb}))
        return out

    # frame 0 complete -> assembles bit-exactly
    done = [asm.add(*m) for m in tiles_of(0)]
    full = [d for d in done if d is not None]
    assert len(full) == 1
    np.testing.assert_array_equal(color, full[0][0].color)
    # frame 1 loses tile 2; frames 2..4 complete -> 1 abandoned
    for m in tiles_of(1, skip=(2,)):
        asm.add(*m)
    for f in (2, 3, 4):
        [asm.add(*m) for m in tiles_of(f)]
    assert asm.stats["abandoned"] == 1
    assert asm.stats["assembled"] == 4
    assert any(e["component"] == "stream.gap" for e in obs.ledger())
    # a straggler tile of the abandoned frame must NOT re-create (and
    # re-abandon) it — counted as late, abandoned stays 1
    assert asm.add(*tiles_of(1)[2]) is None
    assert asm.stats["late_tiles"] == 1
    assert asm.stats["abandoned"] == 1
    # whole-frame messages pass straight through
    out = asm.add(vdi, meta, None)
    assert out is not None and out[0] is vdi


# ------------------------------------------------------------- steering

def test_steering_drain_survives_malformed_and_oversized():
    """Satellite: SteeringEndpoint.drain catches per message, ledgers
    stream.steering, caps message size, keeps draining."""
    ep = SteeringEndpoint("tcp://127.0.0.1:0",
                          fault=FaultConfig(max_message_bytes=2048))
    viewer = SteeringPublisher(ep.endpoint)
    try:
        time.sleep(0.3)
        good = []
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not good:
            viewer.sock.send(b"\x82\x01 definitely not msgpack \xff")
            viewer.sock.send(b"\x00" * 4096)           # over the cap
            viewer.sock.send(b"\x01")                  # not a map
            viewer.heartbeat()                         # consumed silently
            viewer.send({"type": "camera", "eye": [1, 2, 3]})
            time.sleep(0.02)
            good.extend(ep.drain())
        assert good and all(m["type"] == "camera" for m in good)
        assert ep.stats["dropped"] >= 3
        assert ep.stats["heartbeats"] >= 1
        assert any(e["component"] == "stream.steering"
                   for e in obs.ledger())
    finally:
        viewer.close()
        ep.close()


# ------------------------------------------------------------- liveness

def test_subscriber_reconnects_with_backoff():
    sub = VDISubscriber("tcp://127.0.0.1:1",
                        fault=FaultConfig(liveness_timeout_s=0.05,
                                          backoff_base_s=0.01,
                                          backoff_cap_s=0.05))
    try:
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and sub.stats["reconnects"] < 2:
            sub.receive(timeout_ms=30)
        assert sub.stats["reconnects"] >= 2
        assert any(e["component"] == "stream.liveness"
                   for e in obs.ledger())
    finally:
        sub.close()


def test_background_heartbeats_prevent_reconnect_churn():
    """A supervised subscriber on an idle-but-alive publisher must NOT
    reconnect when the publisher pumps background heartbeats."""
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                       fault=FaultConfig(heartbeat_period_s=0.05))
    sub = VDISubscriber(pub.endpoint,
                        fault=FaultConfig(liveness_timeout_s=0.4,
                                          backoff_base_s=0.01,
                                          backoff_cap_s=0.05))
    try:
        time.sleep(0.2)                       # SUB join settles
        pub.start_heartbeats()
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            got = sub.receive(timeout_ms=50)
            assert got is None or isinstance(got, StreamDrop) is False
        assert sub.stats["heartbeats"] > 0
        assert sub.stats["reconnects"] == 0   # alive, just idle
        # and a frame published concurrently with the pump still decodes
        vdi, meta = _vdi_meta()
        pub.publish(vdi, meta)
        got = None
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and got is None:
            got = sub.receive(timeout_ms=100)
        assert got is not None and not isinstance(got, StreamDrop)
        np.testing.assert_array_equal(np.asarray(vdi.color), got[0].color)
    finally:
        pub.close()
        sub.close()


def test_reconnected_subscriber_still_receives():
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    sub = VDISubscriber(pub.endpoint,
                        fault=FaultConfig(liveness_timeout_s=0.05,
                                          backoff_base_s=0.01,
                                          backoff_cap_s=0.05))
    try:
        vdi, meta = _vdi_meta()
        # silence past the deadline forces at least one reconnect
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not sub.stats["reconnects"]:
            sub.receive(timeout_ms=30)
        assert sub.stats["reconnects"] >= 1
        # stop further supervised teardowns so the fresh SUB join can
        # settle — the drill is "reconnected socket still receives"
        sub.fault = FaultConfig(liveness_timeout_s=60.0)
        got = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and got is None:
            pub.publish(vdi, meta)         # resend until SUB rejoins
            time.sleep(0.05)
            got = sub.receive(timeout_ms=100)
            if isinstance(got, StreamDrop):
                got = None                  # post-reconnect gap records
        assert got is not None
        np.testing.assert_array_equal(np.asarray(vdi.color), got[0].color)
    finally:
        pub.close()
        sub.close()


# ------------------------------------------------------ sink quarantine

def _tiny_cfg(*extra):
    return FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=16",
        "sim.grid=[12,12,12]", "sim.steps_per_frame=1",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
        "composite.max_output_supersegments=4",
        "composite.adaptive_iters=1", *extra)


def test_failing_sink_is_quarantined_session_survives():
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    calls = {"bad": 0, "good": 0}

    def bad_sink(i, p):
        calls["bad"] += 1
        raise RuntimeError("sink boom")

    def good_sink(i, p):
        calls["good"] += 1

    cfg = _tiny_cfg("fault.max_sink_failures=2")
    sess = InSituSession(cfg, mesh=make_mesh(2),
                         sinks=[bad_sink, good_sink])
    payload = sess.run(5)
    assert np.isfinite(payload["vdi_color"]).all()
    assert calls["bad"] == 2                  # quarantined after 2
    assert calls["good"] == 5                 # never starved
    assert sess._sink_guard.is_quarantined(bad_sink)
    assert any(e["component"] == "session.sink" for e in obs.ledger())


def test_transient_sink_failures_reset_on_success():
    from scenery_insitu_tpu.runtime.failsafe import SinkGuard

    n = {"fails": 0}

    def flaky(i, p):
        n["fails"] += 1
        if n["fails"] % 2:                    # fail, succeed, fail, ...
            raise RuntimeError("transient")

    guard = SinkGuard(max_failures=2)
    for i in range(8):
        guard.call(flaky, i, {})
    assert not guard.is_quarantined(flaky)    # never 2 in a row


def test_throwing_on_steer_callback_contained():
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession
    from scenery_insitu_tpu.runtime.streaming import (SteeringEndpoint,
                                                      SteeringPublisher)

    cfg = _tiny_cfg("fault.max_sink_failures=3")
    sess = InSituSession(cfg, mesh=make_mesh(2))
    ep = SteeringEndpoint("tcp://127.0.0.1:0")
    viewer = SteeringPublisher(ep.endpoint)
    sess.steering = ep
    seen = []

    def boom(msg):
        raise RuntimeError("callback boom")

    sess.on_steer.insert(0, boom)             # before the tf handler
    sess.on_steer.append(seen.append)
    try:
        time.sleep(0.3)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not seen:
            viewer.send({"type": "record", "on": True})
            time.sleep(0.05)
            sess.run(1)                       # must not raise
        assert seen and seen[0]["type"] == "record"
    finally:
        viewer.close()
        ep.close()


# ----------------------------------------------------- head node liveness

def test_head_marks_silent_rank_down_and_readmits():
    from scenery_insitu_tpu.runtime.head import HeadNode, RankImageSender

    got = []
    head = HeadNode(2, bind="tcp://*:0", stale_frames=2,
                    sinks=(lambda i, p: got.append((i, p)),))
    try:
        ep = head.endpoint.replace("*", "localhost")
        s0 = RankImageSender(0, ep)
        s1 = SilentRank(RankImageSender(1, ep), after=2, resume_at=8)
        h, w = 8, 12
        img = np.zeros((4, h, w), np.float32)
        img[3] = 1.0
        dep = np.ones((h, w), np.float32)
        time.sleep(0.2)
        for f in range(12):
            s0.send(f, img, dep)
            s1.send(f, img, dep)
            head.pump(timeout_ms=50)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and head.frames_composited < 10:
            head.pump(timeout_ms=100)
        frames = {i for i, _ in got}
        # rank 1 was silent for frames 2..7: those frames composited
        # DEGRADED without it, flagged in the payload
        degraded = {i for i, p in got if p.get("degraded")}
        complete = {i for i, p in got if not p.get("degraded")}
        assert degraded, got
        assert all(p["missing_ranks"] == [1]
                   for i, p in got if p.get("degraded"))
        assert any(e["component"] == "head.rank_down"
                   for e in obs.ledger())
        # re-admission: frames >= 8 complete again with both ranks
        assert complete & {f for f in frames if f >= 8}
        assert head.frames_degraded == len(degraded)
    finally:
        s0.close()
        s1.close()
        head.close()


def test_head_survives_malformed_rank_message():
    from scenery_insitu_tpu.runtime.head import HeadNode, RankImageSender

    head = HeadNode(1, bind="tcp://*:0")
    try:
        ep = head.endpoint.replace("*", "localhost")
        s = RankImageSender(0, ep)
        time.sleep(0.2)
        s.sock.send_multipart([b"not msgpack at all", b"x", b"y"])
        s.sock.send_multipart([b"short"])
        h, w = 4, 6
        img = np.zeros((4, h, w), np.float32)
        dep = np.ones((h, w), np.float32)
        s.send(0, img, dep)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not head.frames_composited:
            head.pump(timeout_ms=100)
        assert head.frames_composited == 1
        assert any(e["component"] == "stream.integrity"
                   for e in obs.ledger())
        s.close()
    finally:
        head.close()


def test_head_refuses_ragged_cross_rank_shapes():
    """A parseable message whose image shape disagrees with the frame's
    other ranks must drop at intake — not kill the pump in np.stack."""
    from scenery_insitu_tpu.runtime.head import HeadNode, RankImageSender

    got = []
    head = HeadNode(2, bind="tcp://*:0", stale_frames=2,
                    sinks=(lambda i, p: got.append((i, p)),))
    try:
        ep = head.endpoint.replace("*", "localhost")
        s0 = RankImageSender(0, ep)
        s1 = RankImageSender(1, ep)
        time.sleep(0.2)
        img = np.zeros((4, 8, 12), np.float32)
        dep = np.ones((8, 12), np.float32)
        wide = np.zeros((4, 8, 24), np.float32)   # ragged vs rank 0
        wdep = np.ones((8, 24), np.float32)
        for f in range(6):
            s0.send(f, img, dep)
            s1.send(f, wide if f == 0 else img, wdep if f == 0 else dep)
            head.pump(timeout_ms=50)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and head.frames_composited < 5:
            head.pump(timeout_ms=100)          # must never raise
        assert head.frames_composited >= 5
        # the ragged contribution for frame 0 was refused, so frame 0
        # either shipped degraded (rank 0 only) or complete later —
        # never crashed the pump
        assert any(e["component"] == "stream.integrity"
                   for e in obs.ledger())
        s0.close()
        s1.close()
    finally:
        head.close()


def test_head_recovers_from_absurd_frame_index():
    """One corrupt-but-parseable frame counter must not poison liveness
    and eviction forever — the head resets its stream bookkeeping and
    keeps compositing real frames."""
    from scenery_insitu_tpu.runtime.head import HeadNode, RankImageSender

    head = HeadNode(1, bind="tcp://*:0", stale_frames=2)
    try:
        ep = head.endpoint.replace("*", "localhost")
        s = RankImageSender(0, ep)
        time.sleep(0.2)
        img = np.zeros((4, 4, 6), np.float32)
        dep = np.ones((4, 6), np.float32)
        s.send(0, img, dep)
        s.send(10 ** 9, img, dep)              # absurd jump: state reset
        for f in range(1, 5):
            s.send(f, img, dep)                # real frames keep flowing
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and head.frames_composited < 5:
            head.pump(timeout_ms=100)
        # frame 0 + the 4 post-jump real frames all shipped (the absurd
        # frame itself also composites once — it is indistinguishable
        # from a legitimate sender restart)
        assert head.frames_composited >= 5
        assert any(e["component"] == "stream.gap" for e in obs.ledger())
        s.close()
    finally:
        head.close()


# ------------------------------------------------------- chaos injectors

def test_chaos_socket_deterministic():
    sent = []

    class FakeSock:
        def send_multipart(self, parts):
            sent.append(tuple(parts))

    def run(seed):
        sent.clear()
        cs = ChaosSocket(FakeSock(), FaultSpec(drop=0.4, corrupt=0.3),
                         seed=seed)
        for i in range(20):
            cs.send_multipart([b"h", bytes([i] * 8), b"d"])
        cs.flush()
        return list(sent), dict(cs.report.injected)

    a_msgs, a_rep = run(11)
    b_msgs, b_rep = run(11)
    c_msgs, c_rep = run(12)
    assert a_msgs == b_msgs and a_rep == b_rep   # same seed, same faults
    assert a_rep != c_rep or a_msgs != c_msgs    # different seed differs
    assert a_rep.get("drop", 0) > 0 and a_rep.get("corrupt", 0) > 0


def test_chaos_matrix_runs_green():
    """The CI chaos lane's matrix, in-process: >= 8 injector × endpoint
    combinations, every one alive with its expected ledger row."""
    report = run_matrix(seed=1, frames=10)
    assert len(report["scenarios"]) >= 8
    bad = [s for s in report["scenarios"] if not s["ok"]]
    assert report["ok"], bad


# ------------------------------------------------------ video wraparound

def test_video_receiver_survives_frame_id_wraparound():
    """Satellite: the u32 frame counter wraps; eviction and completion
    must keep working across the boundary (no leak, no misorder)."""
    pytest.importorskip("cv2")
    from scenery_insitu_tpu.runtime.streaming import (VideoReceiver,
                                                      VideoStreamer)

    rx = VideoReceiver(port=0, timeout_s=2.0)
    tx = VideoStreamer(port=rx.port, quality=85)
    try:
        tx.CHUNK = 512                        # force multi-datagram
        tx.frame_id = 2 ** 32 - 2
        img = np.zeros((4, 32, 48), np.float32)
        img[3] = 1.0
        got = 0
        for _ in range(4):                    # crosses the wrap at 2^32
            assert tx.send_frame(img) > 0
            if rx.receive_frame() is not None:
                got += 1
        assert got == 4
        assert tx.frame_id == 2               # wrapped, not 2^32 + 2
        assert len(rx._parts) == 0            # nothing leaked
    finally:
        tx.close()
        rx.close()


def test_seq_delta_wraparound():
    assert seq_delta(5, 3) == 2
    assert seq_delta(3, 5) == -2
    assert seq_delta(1, 2 ** 32 - 1) == 2     # across the wrap
    assert seq_delta(2 ** 32 - 1, 1) == -2
    assert seq_delta(0, 0) == 0
