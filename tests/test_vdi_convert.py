"""Tests for VDI depth-convention conversion (ops/vdi_convert.py):
world-t ↔ NDC-z round-trips, ray reconstruction from metadata (pinhole and
off-axis), reference texture layout pack/unpack, and validation."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera, pixel_rays
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import slicer, vdi_convert as vc
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi


@pytest.fixture(scope="module")
def gathered():
    vol = procedural_volume(32, kind="blobs", seed=5)
    tf = for_dataset("procedural")
    cam = Camera.create((0.2, 0.5, 2.6), fov_y_deg=45.0, near=0.4, far=10.0)
    vdi, meta = generate_vdi(vol, tf, cam, 48, 40,
                             VDIConfig(max_supersegments=8, adaptive_iters=3),
                             max_steps=96)
    return vol, tf, cam, vdi, meta


@pytest.fixture(scope="module")
def sliced():
    vol = procedural_volume(32, kind="blobs", seed=5)
    tf = for_dataset("procedural")
    cam = Camera.create((0.2, 0.5, 2.6), fov_y_deg=45.0, near=0.4, far=10.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32"))
    vdi, meta, _ = slicer.generate_vdi_mxu(
        vol, tf, cam, spec, VDIConfig(max_supersegments=8, adaptive_iters=3))
    return vdi, meta


def test_rays_from_metadata_match_pixel_rays(gathered):
    _, _, cam, _, meta = gathered
    eye_m, dirs_m = vc.rays_from_metadata(meta)
    eye_c, dirs_c = pixel_rays(cam, int(meta.window_dims[0]),
                               int(meta.window_dims[1]))
    assert np.allclose(np.asarray(eye_m), np.asarray(eye_c), atol=1e-4)
    assert np.allclose(np.asarray(dirs_m), np.asarray(dirs_c), atol=1e-4)


@pytest.mark.parametrize("fixture", ["gathered", "sliced"])
def test_ndc_roundtrip(fixture, request):
    item = request.getfixturevalue(fixture)
    vdi, meta = (item[3], item[4]) if len(item) == 5 else item
    ndc = vc.depths_to_ndc(vdi, meta)
    live = np.isfinite(np.asarray(vdi.depth[:, 0]))
    s = np.asarray(ndc.depth[:, 0])[live]
    # NDC z of content must lie in the canonical [-1, 1]
    assert (s >= -1.0 - 1e-3).all() and (s <= 1.0 + 1e-3).all()
    # and be front-to-back monotone increasing vs world t
    back = vc.depths_from_ndc(ndc, meta)
    t0 = np.asarray(vdi.depth)[:, :, live.any(axis=0)]
    t1 = np.asarray(back.depth)[:, :, live.any(axis=0)]
    both = np.isfinite(t0)
    assert np.allclose(t0[both], t1[both], rtol=1e-3, atol=1e-3)


def test_reference_layout_roundtrip(gathered):
    vdi = gathered[3]
    color, depth = vc.pack_reference_layout(vdi)
    k = vdi.k
    assert color.shape == (k, vdi.height, vdi.width, 4)
    assert depth.shape == (2 * k, vdi.height, vdi.width)
    back = vc.unpack_reference_layout(color, depth)
    live = np.isfinite(np.asarray(vdi.depth[:, 0]))
    assert np.allclose(np.asarray(back.color), np.asarray(vdi.color))
    assert np.allclose(np.asarray(back.depth[:, 0])[live],
                       np.asarray(vdi.depth[:, 0])[live])
    assert np.allclose(np.asarray(back.depth[:, 1])[live],
                       np.asarray(vdi.depth[:, 1])[live])
    # empties stay empty
    assert np.isinf(np.asarray(back.depth[:, 0])[~live]).all()


def test_validate_vdi_clean(gathered, sliced):
    for vdi, meta in [(gathered[3], gathered[4]), sliced]:
        rep = vc.validate_vdi(vdi)
        assert rep["live_slots"] > 0
        for key in ("inverted_extent", "overlapping", "unsorted",
                    "alpha_out_of_range"):
            assert rep[key] == 0, (key, rep)
        ndc = vc.depths_to_ndc(vdi, meta)
        rep2 = vc.validate_vdi(ndc, ndc=True)
        assert rep2["ndc_out_of_range"] == 0, rep2


def test_validate_vdi_detects_corruption(gathered):
    vdi = gathered[3]
    bad_depth = np.asarray(vdi.depth).copy()
    live = np.isfinite(bad_depth[:, 0])
    # invert one live slot's extent
    k, h, w = np.argwhere(live)[0]
    bad_depth[k, 1, h, w] = bad_depth[k, 0, h, w] - 1.0
    from scenery_insitu_tpu.core.vdi import VDI
    rep = vc.validate_vdi(VDI(vdi.color, jnp.asarray(bad_depth)))
    assert rep["inverted_extent"] >= 1


def test_3layer_packed_roundtrip_and_decode():
    """The legacy 3-layer single-texture layout (InVisVolumeRenderer.kt:
    138-141): pack -> unpack is exact for live slots, and the packed decode
    equals the framework's same-view render."""
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.ops.vdi_convert import (pack_3layer,
                                                    render_packed_vdi,
                                                    unpack_3layer)

    from scenery_insitu_tpu.core.transfer import TransferFunction

    vol = procedural_volume(16, kind="blobs", seed=2)
    tf = TransferFunction.ramp(0.1, 0.9, 0.7)
    cam = Camera.create((0.2, 0.3, 3.0), fov_y_deg=45.0, near=0.5, far=20.0)
    vdi, _ = generate_vdi(vol, tf, cam, 24, 20,
                          VDIConfig(max_supersegments=5, adaptive_iters=2),
                          max_steps=48)
    packed = pack_3layer(vdi)
    assert packed.shape == (15, 20, 24, 4)
    rt = unpack_3layer(packed)
    live = np.isfinite(np.asarray(vdi.depth[:, 0]))
    np.testing.assert_allclose(np.asarray(rt.color)[:, 3][live],
                               np.asarray(vdi.color)[:, 3][live], atol=1e-6)
    np.testing.assert_allclose(np.asarray(rt.depth)[:, 0][live],
                               np.asarray(vdi.depth)[:, 0][live], atol=1e-6)
    assert not np.isfinite(np.asarray(rt.depth)[:, 0][~live]).any()
    img1 = np.asarray(render_vdi_same_view(vdi))
    img2 = np.asarray(render_packed_vdi(packed))
    np.testing.assert_allclose(img2, img1, atol=1e-5)
