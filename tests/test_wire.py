"""Quantized supersegment wire formats for the sort-last exchange
(CompositeConfig.wire = "f32" | "bf16" | "qpack8"; ops/wire.py,
docs/PERF.md "Wire formats"): encode/decode round-trip units (empty-slot
sentinel, near==far fragments, tie depths), PSNR floors for every
distributed builder × both exchange modes on the 8-device virtual mesh,
obs counter assertions, the traffic-model numbers, and the host-side
quantizer reuse (io.vdi_io / runtime.streaming)."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata, render_vdi_same_view
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import wire as wire_mod
from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)
from scenery_insitu_tpu.utils.image import psnr

W = H = 16
STEPS = 48
N = 8
LOSSY = ("bf16", "qpack8")
EXCHANGES = ("all_to_all", "ring")
# the documented floor (docs/PERF.md "Wire formats") on the 8-device
# parity scenes; measured headroom is ~60 dB (qpack8) / ~75 dB (bf16)
PSNR_FLOOR = 40.0


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _stream(rng, k, h, w, live, lo=1.0, hi=5.0, ext=(0.01, 0.2)):
    """Random per-pixel depth-sorted segment stream with ``live`` live
    slots (empties masked: zero color, +inf depth). ``ext`` bounds the
    segment extents — the round-trip unit tests keep the near-degenerate
    default, quality-floor tests pick extents that are wide relative to
    the fragment's depth span (sub-quantum-thin translucent segments are
    outside the documented floor contract; the unit tests bound their
    error exactly instead)."""
    s = np.sort(rng.uniform(lo, hi, (k, h, w)), axis=0).astype(np.float32)
    e = (s + rng.uniform(*ext, (k, h, w))).astype(np.float32)
    c = rng.uniform(0.0, 1.0, (k, 4, h, w)).astype(np.float32)
    mask = np.arange(k)[:, None, None] < live
    s = np.where(mask, s, np.inf)
    e = np.where(mask, e, np.inf)
    c = np.where(mask[:, None], c, 0.0)
    return jnp.asarray(c), jnp.asarray(np.stack([s, e], axis=1))


def _render(color, depth):
    return np.asarray(render_vdi_same_view(VDI(color, depth)))


# ------------------------------------------------------ encode/decode units

def test_f32_encode_is_identity():
    """The f32 wire inserts NOTHING: the very arrays go through."""
    rng = np.random.default_rng(0)
    c, d = _stream(rng, 4, 3, 5, live=2)
    ec, ed, sc = wire_mod.encode_fragment(c, d, "f32")
    assert ec is c and ed is d and sc is None
    dc, dd = wire_mod.decode_fragment(ec, ed, None, "f32")
    assert dc is c and dd is d


@pytest.mark.parametrize("wire", LOSSY)
def test_lossy_roundtrip_preserves_empty_sentinel(wire):
    """+inf empty slots round-trip EXACTLY (bf16 keeps inf; qpack8
    reserves the u16 sentinel 0xFFFF) and their colors stay zero — the
    merge/re-segmentation empty-slot convention is untouched."""
    rng = np.random.default_rng(1)
    c, d = _stream(rng, 6, 4, 4, live=3)
    ec, ed, sc = wire_mod.encode_fragment(c, d, wire)
    dc, dd = wire_mod.decode_fragment(ec, ed, sc, wire)
    dc, dd = np.asarray(dc), np.asarray(dd)
    np.testing.assert_array_equal(np.isinf(dd), np.isinf(np.asarray(d)))
    assert (dc[3:] == 0.0).all()
    assert np.isfinite(dd[:3]).all()


def test_qpack8_error_bounds():
    """|decoded - original| is bounded by one quantum: fragment depth
    span / 254 for depths, 1/255 for colors (half-quantum after round)."""
    rng = np.random.default_rng(2)
    c, d = _stream(rng, 8, 6, 6, live=8)
    ec, ed, sc = wire_mod.encode_fragment(c, d, "qpack8")
    dc, dd = wire_mod.decode_fragment(ec, ed, sc, "qpack8")
    dn, df = np.asarray(d), np.asarray(dd)
    span = dn[np.isfinite(dn)].max() - dn[np.isfinite(dn)].min()
    assert np.abs(np.asarray(dc) - np.asarray(c)).max() <= 0.5 / 255 + 1e-6
    assert np.abs(df - dn).max() <= 0.5 * span / 254 + 1e-5


def test_qpack8_fully_empty_fragment():
    """A fragment with NO finite depth encodes to all-sentinel and
    decodes to all +inf / zero color — no NaNs from the degenerate
    [near, far]."""
    c = jnp.zeros((3, 4, 2, 2), jnp.float32)
    d = jnp.full((3, 2, 2, 2), jnp.inf, jnp.float32)
    ec, ed, sc = wire_mod.encode_fragment(c, d, "qpack8")
    assert (np.asarray(ed) == 0xFFFF).all()
    dc, dd = wire_mod.decode_fragment(ec, ed, sc, "qpack8")
    assert np.isinf(np.asarray(dd)).all()
    assert (np.asarray(dc) == 0.0).all()


def test_qpack8_near_equals_far_fragment():
    """All live depths identical (span 0): codes collapse to 0 and decode
    EXACTLY to that depth (near + 0·span)."""
    rng = np.random.default_rng(3)
    c, d = _stream(rng, 4, 3, 3, live=2)
    d = jnp.where(jnp.isfinite(d), jnp.float32(2.5), jnp.inf)
    ec, ed, sc = wire_mod.encode_fragment(c, d, "qpack8")
    dc, dd = wire_mod.decode_fragment(ec, ed, sc, "qpack8")
    fin = np.isfinite(np.asarray(d))
    assert (np.asarray(dd)[fin] == 2.5).all()
    np.testing.assert_array_equal(np.isinf(np.asarray(dd)), ~fin)


@pytest.mark.parametrize("wire", LOSSY)
def test_lossy_roundtrip_preserves_sort_and_ties(wire):
    """Quantization is monotone: a per-pixel depth-sorted stream decodes
    sorted (the ring pairwise-merge precondition), and exactly-equal
    start depths stay exactly equal (tie structure survives)."""
    rng = np.random.default_rng(4)
    c, d = _stream(rng, 8, 4, 4, live=6)
    d = np.array(d)                         # writable host copy
    d[3, 0] = d[2, 0]                       # manufacture a tie
    ec, ed, sc = wire_mod.encode_fragment(jnp.asarray(c), jnp.asarray(d),
                                          wire)
    _, dd = wire_mod.decode_fragment(ec, ed, sc, wire)
    starts = np.asarray(dd)[:, 0]
    assert (np.sort(starts, axis=0) == starts).all()
    np.testing.assert_array_equal(starts[3], starts[2])


def test_qpack8_np_matches_device_encode():
    """The numpy twin (the vdi_io / VDIPublisher pre-codec pass) produces
    bit-identical codes to the device encode — one format, two hosts."""
    rng = np.random.default_rng(5)
    c, d = _stream(rng, 6, 5, 7, live=4)
    ec, ed, sc = wire_mod.encode_fragment(c, d, "qpack8")
    nc, nd, near, far = wire_mod.qpack8_quantize_np(np.asarray(c),
                                                    np.asarray(d))
    np.testing.assert_array_equal(nc, np.asarray(ec))
    np.testing.assert_array_equal(nd, np.asarray(ed))
    assert np.float32(near) == float(sc[0])
    assert np.float32(far) == float(sc[1])
    bc, bd = wire_mod.qpack8_dequantize_np(nc, nd, near, far)
    dc, dd = wire_mod.decode_fragment(ec, ed, sc, "qpack8")
    np.testing.assert_allclose(bc, np.asarray(dc), atol=1e-7, rtol=0)
    fin = np.isfinite(bd)
    np.testing.assert_array_equal(fin, np.isfinite(np.asarray(dd)))
    np.testing.assert_allclose(bd[fin], np.asarray(dd)[fin], atol=1e-5,
                               rtol=0)


def test_plain_roundtrip():
    """Plain fragments (single depth per pixel): qpack8 gives the lone
    depth the full u16 range; the 0xFFFF sentinel round-trips +inf."""
    rng = np.random.default_rng(6)
    img = rng.uniform(0, 1, (4, 6, 8)).astype(np.float32)
    dep = rng.uniform(1, 5, (6, 8)).astype(np.float32)
    dep[0, 0] = np.inf
    for wire in LOSSY:
        ei, ed, sc = wire_mod.encode_plain(jnp.asarray(img),
                                           jnp.asarray(dep), wire)
        di, dd = wire_mod.decode_plain(ei, ed, sc, wire)
        dd = np.asarray(dd)
        np.testing.assert_array_equal(np.isinf(dd), np.isinf(dep))
        fin = np.isfinite(dep)
        span = dep[fin].max() - dep[fin].min()
        tol = (span / 65534 if wire == "qpack8" else 0.02 * dep[fin].max())
        assert np.abs(dd[fin] - dep[fin]).max() <= tol + 1e-6


def test_wire_validation():
    with pytest.raises(ValueError, match="wire"):
        CompositeConfig(wire="u4")
    with pytest.raises(ValueError, match="wire"):
        wire_mod.wire_slot_bytes("u4")
    with pytest.raises(ValueError, match="wire"):
        wire_mod.encode_fragment(jnp.zeros((1, 4, 1, 1)),
                                 jnp.zeros((1, 2, 1, 1)), "u4")


# ------------------------------------------------------------ traffic model

def test_modeled_traffic_per_wire_itemsizes():
    """The model matches what ships: qpack8 cuts ici_bytes_per_rank 4×
    (24 → 6 B/slot), bf16 2×; HBM stream bytes are wire-independent
    (decode to f32 precedes the fold)."""
    f32 = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16)
    bf = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16, wire="bf16")
    q8 = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16,
                                  wire="qpack8")
    assert f32["wire_color_bytes_per_slot"] == 16
    assert f32["wire_depth_bytes_per_slot"] == 8
    assert q8["wire_color_bytes_per_slot"] == 4
    assert q8["wire_depth_bytes_per_slot"] == 2
    assert f32["ici_bytes_per_rank"] == 2 * bf["ici_bytes_per_rank"]
    assert f32["ici_bytes_per_rank"] == 4 * q8["ici_bytes_per_rank"]
    assert f32["stream_bytes_per_rank"] == q8["stream_bytes_per_rank"]
    # ring wire bytes shrink identically (same fragments, same links)
    ring = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16,
                                    mode="ring", wire="qpack8")
    assert ring["ici_bytes_per_rank"] == q8["ici_bytes_per_rank"]


# ------------------------------------- distributed builders × exchange modes
#
# Two-tier strategy (the 870 s tier-1 budget rules out compiling every
# builder × exchange × wire end to end — 42 full-pipeline jits):
#
# 1. The FULL wire × exchange quality matrix runs on a composite-only
#    SPMD step over fixed per-rank VDI streams (the production
#    `_composite_exchanged` under `shard_map`, exactly what
#    benchmarks/composite_bench.py times) — six small compiles exercise
#    every encode/decode × collective combination and hold the floors.
# 2. Every distributed BUILDER then gets one end-to-end threading check
#    at the widest path (qpack8 over the ring — quantize + packed lanes
#    + scale ppermute) against its own f32 reference: proves
#    `comp_cfg.wire` reaches the exchange inside that builder (generation
#    upstream of the exchange is wire-independent by construction).

_SCENE = {}


def _scene():
    if not _SCENE:
        vol = procedural_volume(16, kind="blobs")
        mesh = make_mesh(N)
        _SCENE.update(vol=vol, mesh=mesh,
                      data=shard_volume(vol.data, mesh))
    return _SCENE["vol"], _SCENE["mesh"], _SCENE["data"]


def _assert_floors(imgs, ref, label):
    """imgs: {(exchange, wire): rendered image}; every lossy image must
    hold the documented floor vs the f32 reference, every f32 image must
    match it exactly (ring f32 == all_to_all f32 == ref)."""
    for (ex, wire), img in imgs.items():
        assert np.isfinite(img).all(), (label, ex, wire)
        if wire == "f32":
            np.testing.assert_allclose(img, ref, atol=1e-6, rtol=0,
                                       err_msg=f"{label} {ex} f32")
        else:
            q = psnr(img, ref)
            assert q >= PSNR_FLOOR, f"{label} {ex}/{wire}: {q:.1f} dB"


def test_wire_exchange_matrix_composite_step():
    """Every wire × exchange combination through the production
    `_composite_exchanged` on the 8-device mesh: f32 output (both
    schedules) is bitwise the baseline composite; bf16/qpack8 hold the
    PSNR floor and the +inf empty-slot layout EXACTLY."""
    import dataclasses

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from scenery_insitu_tpu.parallel.pipeline import _composite_exchanged
    from scenery_insitu_tpu.utils.compat import shard_map

    _, mesh, _ = _scene()
    axis = mesh.axis_names[0]
    rng = np.random.default_rng(20)
    # N ranks' sub-VDIs, depth-banded per rank (the sort-last invariant).
    # The floor contract is defined on real renders (the builder tests),
    # so the synthetic scene stays representative of one: segment extents
    # wide relative to the rank's depth span (tens of qpack8 quanta;
    # quantum-thin segments are exercised and exactly bounded by the unit
    # tests) and spatially smooth colors — with per-pixel random colors a
    # quantum-scale depth perturbation that flips one adaptive
    # resegmentation merge decision shows up as a full-scale pixel delta,
    # which no wire precision short of f32 survives.
    cs, ds = [], []
    for r in range(N):
        c, d = _stream(rng, 4, H, W, live=3, lo=1.0 + r, hi=1.6 + r,
                       ext=(0.1, 0.3))
        c = jnp.broadcast_to(c.mean(axis=(2, 3), keepdims=True), c.shape)
        cs.append(c)
        ds.append(d)
    base_c = jnp.concatenate(cs)
    base_d = jnp.concatenate(ds)
    comp = CompositeConfig(max_output_supersegments=8, adaptive_iters=2)

    outs = {}
    for ex in EXCHANGES:
        for wire in ("f32",) + LOSSY:
            cfg_m = dataclasses.replace(comp, exchange=ex, wire=wire)

            def step(color, depth, cfg_m=cfg_m):
                out = _composite_exchanged(color, depth, N, axis, cfg_m)
                return out.color, out.depth

            f = jax.jit(shard_map(
                step, mesh=mesh, in_specs=(P(axis), P(axis)),
                out_specs=(P(None, None, None, axis),
                           P(None, None, None, axis)),
                check_vma=False))
            oc, od = f(jax.device_put(base_c, NamedSharding(mesh, P(axis))),
                       jax.device_put(base_d, NamedSharding(mesh, P(axis))))
            outs[(ex, wire)] = (np.asarray(oc), np.asarray(od))

    rc, rd = outs[("all_to_all", "f32")]
    for (ex, wire), (oc, od) in outs.items():
        # empty-slot layout survives every wire (sentinel contract)
        np.testing.assert_array_equal(np.isinf(od), np.isinf(rd),
                                      err_msg=f"{ex}/{wire}")
        if wire == "f32":
            np.testing.assert_array_equal(oc, rc, err_msg=f"{ex} f32")
            fin = np.isfinite(rd)
            np.testing.assert_array_equal(od[fin], rd[fin],
                                          err_msg=f"{ex} f32")
    imgs = {k: np.asarray(render_vdi_same_view(
        VDI(jnp.asarray(c), jnp.asarray(d)))) for k, (c, d) in outs.items()}
    _assert_floors(imgs, imgs[("all_to_all", "f32")], "composite-step")


def _qpack8_ring_vs_f32(build, run, label):
    """One end-to-end threading check for a distributed builder: the
    qpack8 ring output must differ from f32 (the wire actually engaged)
    while holding the documented floor against the f32 reference."""
    ref = run(build("f32"))
    q8 = run(build("qpack8"))
    assert np.isfinite(q8).all(), label
    assert not np.array_equal(q8, ref), \
        f"{label}: qpack8 output is bitwise f32 — wire not threaded"
    q = psnr(q8, ref)
    assert q >= PSNR_FLOOR, f"{label}: {q:.1f} dB"


def _ccfg(wire, exchange="ring"):
    return CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                           exchange=exchange, wire=wire)


def test_wire_vdi_step_gather():
    """Gather-engine VDI chain threads the wire (qpack8 ring vs f32)."""
    vol, mesh, data = _scene()
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    _qpack8_ring_vs_f32(
        lambda wire: distributed_vdi_step(mesh, _tf(), W, H, vcfg,
                                          _ccfg(wire), max_steps=STEPS),
        lambda step: _render(*step(data, vol.origin, vol.spacing, _cam())),
        "gather-vdi")


@pytest.mark.parametrize("eye,exchange", [
    ((0.0, 0.2, 4.0), "ring"),          # march axis z (sharded)
    ((3.8, 0.3, 0.6), "all_to_all")])   # march axis x (in-plane)
def test_wire_mxu_step(eye, exchange):
    """MXU slice-march VDI chain, both march regimes — one regime per
    exchange schedule so both collectives see the mxu engine."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    vol, mesh, data = _scene()
    cam = _cam(eye)
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)

    def run(step):
        vdi, _ = step(data, vol.origin, vol.spacing, cam)
        return _render(vdi.color, vdi.depth)

    _qpack8_ring_vs_f32(
        lambda wire: distributed_vdi_step_mxu(mesh, _tf(), spec, vcfg,
                                              _ccfg(wire, exchange)),
        run, f"mxu-{eye}-{exchange}")


def test_wire_mxu_temporal_carry():
    """Temporal mode: the carried threshold state is UPSTREAM of the
    exchange, so it must evolve bit-identically under a lossy wire while
    the composited frames hold the floor."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    vol, mesh, data = _scene()
    cam = _cam()
    cfg_t = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    frames, thrs = {}, {}
    for wire in ("f32", "qpack8"):
        thr = distributed_initial_threshold_mxu(
            mesh, _tf(), spec, cfg_t)(data, vol.origin, vol.spacing, cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, cfg_t,
                                                 _ccfg(wire))
        for _ in range(2):
            (vdi, _), thr = step(data, vol.origin, vol.spacing, cam, thr)
        frames[wire] = _render(vdi.color, vdi.depth)
        thrs[wire] = np.asarray(thr.thr)
    np.testing.assert_allclose(thrs["qpack8"], thrs["f32"], atol=1e-6,
                               rtol=0, err_msg="threshold drifted")
    assert not np.array_equal(frames["qpack8"], frames["f32"])
    q = psnr(frames["qpack8"], frames["f32"])
    assert q >= PSNR_FLOOR, f"mxu-temporal: {q:.1f} dB"


def test_wire_plain_step():
    """Plain gather-path frames (RGBA+single-depth wire): both exchange
    schedules thread the qpack8 wire."""
    vol, mesh, data = _scene()
    cfg = RenderConfig(max_steps=STEPS, early_exit_alpha=1.1)
    for ex in EXCHANGES:
        _qpack8_ring_vs_f32(
            lambda wire, ex=ex: distributed_plain_step(
                mesh, _tf(), W, H, cfg, exchange=ex, wire=wire),
            lambda step: np.asarray(
                step(data, vol.origin, vol.spacing, _cam())),
            f"plain-{ex}")


def test_wire_plain_mxu_step():
    """Plain MXU frames (intermediate-grid image + depth wire)."""
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu)

    vol, mesh, data = _scene()
    cam = _cam()
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)

    def run(step):
        img, _ = step(data, vol.origin, vol.spacing, cam)
        return np.asarray(img)

    _qpack8_ring_vs_f32(
        lambda wire: distributed_plain_step_mxu(mesh, _tf(), spec,
                                                exchange="ring", wire=wire),
        run, "plain-mxu")


def test_wire_hybrid_step():
    """Hybrid volume+particle frames: the VDI half composites under the
    configured wire; the splat half is exchange-independent."""
    import jax

    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu)
    from scenery_insitu_tpu.parallel.particles import shard_particles

    vol, mesh, data = _scene()
    cam = _cam()
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=N)
    pos = jax.random.uniform(jax.random.PRNGKey(7), (64, 3),
                             minval=-0.8, maxval=0.8)
    vel = jax.random.normal(jax.random.PRNGKey(8), (64, 3)) * 0.1
    p, v = shard_particles(pos, mesh), shard_particles(vel, mesh)

    def run(step):
        img, _ = step(data, vol.origin, vol.spacing, p, v, cam)
        return np.asarray(img)

    _qpack8_ring_vs_f32(
        lambda wire: distributed_hybrid_step_mxu(mesh, _tf(), spec, vcfg,
                                                 _ccfg(wire), radius=0.05,
                                                 stamp=3),
        run, "hybrid")


# -------------------------------------------------------------- obs counters

def test_wire_obs_counters():
    """A lossy-wire build mints wire_encode_builds + a wire_encode event,
    the ring build event carries the wire and its traffic model; an f32
    build mints NO wire counters (the fast path is structurally
    untouched)."""
    from scenery_insitu_tpu import obs

    vol, mesh, data = _scene()
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)

    def build(wire):
        rec = obs.Recorder(enabled=True)
        prev = obs.set_recorder(rec)
        try:
            step = distributed_vdi_step(
                mesh, _tf(), W, H, vcfg,
                CompositeConfig(max_output_supersegments=8,
                                adaptive_iters=2, exchange="ring",
                                wire=wire), max_steps=STEPS)
            step(data, vol.origin, vol.spacing, _cam())
        finally:
            obs.set_recorder(prev)
        return rec

    rec = build("qpack8")
    assert rec.counters.get("wire_encode_builds", 0) >= 1
    enc = [e for e in rec.events if e.get("name") == "wire_encode"]
    assert enc and enc[0]["attrs"]["wire"] == "qpack8"
    assert enc[0]["attrs"]["bytes_per_slot"] == 6
    builds = [e for e in rec.events
              if e.get("name") == "ring_exchange_build"]
    assert builds and builds[0]["attrs"]["wire"] == "qpack8"
    assert builds[0]["attrs"]["traffic"]["wire"] == "qpack8"

    rec32 = build("f32")
    assert rec32.counters.get("wire_encode_builds", 0) == 0


# ------------------------------------------------------- host-side quantize

def test_save_vdi_qpack8_roundtrip(tmp_path):
    """vdi_io's pre-codec quantize pass: the artifact shrinks ~4× before
    the byte codec, the precision tag lands in the metadata, and load
    dequantizes back to f32 within the wire error bound."""
    from scenery_insitu_tpu.io.vdi_io import load_vdi, save_vdi

    rng = np.random.default_rng(9)
    c, d = _stream(rng, 6, 24, 32, live=4)
    vdi = VDI(c, d)
    meta = VDIMetadata.create(np.eye(4), np.eye(4), volume_dims=(8, 8, 8),
                              window_dims=(32, 24), nw=0.1, index=3)
    raw = save_vdi(str(tmp_path / "f.npz"), vdi, meta, codec="none")
    qz = save_vdi(str(tmp_path / "q.npz"), vdi, meta, codec="none",
                  precision="qpack8")
    assert qz < raw * 0.35, (qz, raw)          # ~4× payload shrink
    back, bmeta = load_vdi(str(tmp_path / "q.npz"))
    assert int(np.asarray(bmeta.precision)) == wire_mod.WIRE_CODES["qpack8"]
    dn = np.asarray(d)
    np.testing.assert_array_equal(np.isinf(back.depth), np.isinf(dn))
    fin = np.isfinite(dn)
    span = dn[fin].max() - dn[fin].min()
    assert np.abs(back.depth[fin] - dn[fin]).max() <= 0.5 * span / 254 + 1e-5
    assert np.abs(back.color - np.asarray(c)).max() <= 0.5 / 255 + 1e-6
    # the f32 artifact still round-trips bit-exactly with precision
    fb, fmeta = load_vdi(str(tmp_path / "f.npz"))
    np.testing.assert_array_equal(fb.color, np.asarray(c))
    assert int(np.asarray(fmeta.precision)) == 0
    with pytest.raises(ValueError, match="precision"):
        save_vdi(str(tmp_path / "x.npz"), vdi, precision="u4")


def test_publisher_qpack8_quantize():
    """VDIPublisher's pre-codec quantize pass: smaller wire frames, the
    precision tag travels in header + metadata, the subscriber
    dequantizes transparently."""
    pytest.importorskip("zmq")
    pytest.importorskip("msgpack")
    import time

    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    rng = np.random.default_rng(10)
    c, d = _stream(rng, 4, 12, 16, live=3)
    meta = VDIMetadata.create(np.eye(4), np.eye(4), volume_dims=(8, 8, 8),
                              window_dims=(16, 12), nw=0.1, index=7)
    with pytest.raises(ValueError, match="precision"):
        VDIPublisher("tcp://127.0.0.1:0", precision="u4")
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8")
    sub = VDISubscriber(pub.endpoint)
    try:
        time.sleep(0.2)
        nbytes = pub.publish(VDI(c, d), meta)
        assert nbytes > 0
        got = sub.receive(timeout_ms=5000)
        assert got is not None
        rvdi, rmeta = got
        assert int(np.asarray(rmeta.precision)) == \
            wire_mod.WIRE_CODES["qpack8"]
        assert int(np.asarray(rmeta.index)) == 7
        dn = np.asarray(d)
        np.testing.assert_array_equal(np.isinf(rvdi.depth), np.isinf(dn))
        fin = np.isfinite(dn)
        span = dn[fin].max() - dn[fin].min()
        assert np.abs(rvdi.depth[fin] - dn[fin]).max() \
            <= 0.5 * span / 254 + 1e-5
        assert np.abs(rvdi.color - np.asarray(c)).max() <= 0.5 / 255 + 1e-6
    finally:
        pub.close()
        sub.close()
