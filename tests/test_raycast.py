import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import RenderConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops.raycast import raycast

W = H = 24


def _cam():
    return Camera.create((0.0, 0.0, 4.0), target=(0, 0, 0),
                         fov_y_deg=50.0, near=0.5, far=20.0)


def _const_tf(alpha):
    return TransferFunction.ramp(-1.0, 0.0, max_alpha=alpha)  # constant alpha


def test_background_pixels_empty():
    vol = Volume.centered(jnp.ones((8, 8, 8)), extent=1.0)
    out = raycast(vol, _const_tf(0.9), _cam(), W, H,
                  RenderConfig(max_steps=32, early_exit_alpha=1.1))
    img = np.asarray(out.image)
    assert img[3, 0, 0] == 0.0          # corner ray misses the small box
    assert np.isinf(np.asarray(out.depth)[0, 0])


def test_constant_volume_analytic_alpha():
    # transmittance through L world units with per-voxel alpha a:
    # T = (1-a)^(L / voxel) independent of step count
    size, extent = 16, 1.0
    vol = Volume.centered(jnp.ones((size, size, size)), extent=extent)
    a = 0.3
    cfg = RenderConfig(max_steps=64, early_exit_alpha=1.1)
    out = raycast(vol, _const_tf(a), _cam(), W, H, cfg)
    img = np.asarray(out.image)
    voxel = extent / size
    expected = 1.0 - (1.0 - a) ** (extent / voxel)
    center = img[3, H // 2, W // 2]
    assert np.isclose(center, expected, atol=5e-3), (center, expected)


def test_step_count_invariance():
    vol = Volume.centered(jnp.ones((8, 8, 8)), extent=1.0)
    outs = []
    for steps in (32, 128):
        cfg = RenderConfig(max_steps=steps, early_exit_alpha=1.1)
        outs.append(np.asarray(raycast(vol, _const_tf(0.5), _cam(), W, H, cfg).image))
    assert np.allclose(outs[0][3], outs[1][3], atol=1e-3)


def test_depth_is_entry_point():
    size, extent = 8, 1.0
    vol = Volume.centered(jnp.ones((size, size, size)), extent=extent)
    out = raycast(vol, _const_tf(0.9), _cam(), W, H, RenderConfig(max_steps=64))
    d = float(np.asarray(out.depth)[H // 2, W // 2])
    # camera at z=4 looking at origin; box front face at z=+0.5 → t ≈ 3.5
    assert abs(d - 3.5) < 0.1


def test_jit_and_grad():
    vol = procedural_volume(8)
    tf = TransferFunction.ramp(0.1, 0.9, 0.8)
    cam = _cam()
    f = jax.jit(lambda v: raycast(v, tf, cam, 8, 8,
                                  RenderConfig(max_steps=16)).image.sum())
    g = jax.grad(lambda data: f(vol._replace(data=data)))(vol.data)
    assert np.isfinite(float(f(vol)))
    assert np.isfinite(np.asarray(g)).all()


def test_asymmetric_image_dims():
    vol = procedural_volume(8)
    tf = TransferFunction.ramp(0.1, 0.9, 0.8)
    out = raycast(vol, tf, _cam(), 32, 16, RenderConfig(max_steps=16))
    assert out.image.shape == (4, 16, 32)
