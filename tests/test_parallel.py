"""Distribution-layer tests on the virtual 8-device CPU mesh: halo
exactness, all-to-all plumbing, and distributed-vs-single-device render
parity (the checks the reference could only do by eyeballing cluster runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scenery_insitu_tpu.config import CompositeConfig, RenderConfig, VDIConfig
from scenery_insitu_tpu.utils.compat import shard_map
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.parallel.mesh import (halo_exchange_z, make_mesh,
                                              volume_sharding)
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)
from scenery_insitu_tpu.utils.image import psnr

W = H = 16
STEPS = 48


def _cam():
    return Camera.create((0.0, 0.2, 4.0), fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def test_mesh_creation():
    mesh = make_mesh(4)
    assert mesh.shape["ranks"] == 4
    mesh8 = make_mesh()
    assert mesh8.shape["ranks"] == 8


def test_halo_exchange_matches_global():
    mesh = make_mesh(4)
    d = 8
    data = jnp.arange(d * 2 * 2, dtype=jnp.float32).reshape(d, 2, 2)

    f = jax.jit(shard_map(
        lambda x: halo_exchange_z(x),
        mesh=mesh, in_specs=P("ranks", None, None),
        out_specs=P("ranks", None, None), check_vma=False))
    out = np.asarray(f(data))                     # [4*(2+2), 2, 2] stacked
    dn = d // 4
    blocks = out.reshape(4, dn + 2, 2, 2)
    gd = np.asarray(data)
    for r in range(4):
        lo = max(r * dn - 1, 0)
        hi = min((r + 1) * dn + 1, d)
        expect = gd[lo:hi]
        if r == 0:
            expect = np.concatenate([gd[:1], expect], axis=0)
        if r == 3:
            expect = np.concatenate([expect, gd[-1:]], axis=0)
        assert np.array_equal(blocks[r], expect), r


def test_shard_volume_layout():
    mesh = make_mesh(4)
    data = jnp.zeros((8, 4, 4))
    sharded = shard_volume(data, mesh)
    assert sharded.sharding == volume_sharding(mesh)


@pytest.mark.parametrize("n,background", [(2, (0, 0, 0, 0)), (4, (0, 0, 0, 0)),
                                          (4, (1.0, 0.2, 0.1, 1.0))])
def test_distributed_plain_matches_single(n, background):
    mesh = make_mesh(n)
    vol = procedural_volume(16, kind="shell")
    cfg = RenderConfig(max_steps=STEPS, early_exit_alpha=1.1,
                       background=background)
    cam = _cam()
    ref = np.asarray(raycast(vol, _tf(), cam, W, H, cfg).image)

    step = distributed_plain_step(mesh, _tf(), W, H, cfg)
    img = np.asarray(step(shard_volume(vol.data, mesh), vol.origin,
                          vol.spacing, cam))
    assert img.shape == (4, H, W)
    assert psnr(ref, img) > 28.0, psnr(ref, img)


def test_distributed_vdi_matches_single():
    n = 4
    mesh = make_mesh(n)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    ref = np.asarray(raycast(vol, _tf(), cam, W, H,
                             RenderConfig(max_steps=STEPS,
                                          early_exit_alpha=1.1)).image)
    step = distributed_vdi_step(
        mesh, _tf(), W, H,
        VDIConfig(max_supersegments=10, adaptive_iters=4),
        CompositeConfig(max_output_supersegments=16), max_steps=STEPS)
    vdi = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    assert vdi.color.shape == (16, 4, H, W)
    img = np.asarray(render_vdi_same_view(vdi))
    assert psnr(ref, img) > 25.0, psnr(ref, img)


def test_distributed_vdi_output_sharding():
    mesh = make_mesh(2)
    vol = procedural_volume(8)
    step = distributed_vdi_step(mesh, _tf(), W, H,
                                VDIConfig(max_supersegments=6,
                                          adaptive=False, threshold=0.1),
                                max_steps=16)
    vdi = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, _cam())
    # composited output is W-sharded: each rank owns its column block
    spec = vdi.color.sharding.spec
    assert spec[-1] == "ranks", spec


def test_width_divisibility_check():
    mesh = make_mesh(4)
    with pytest.raises(ValueError):
        distributed_vdi_step(mesh, _tf(), 18, H)


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z (sharded)
                                 (3.8, 0.3, 0.6)])   # march axis x (in-plane z)
def test_distributed_vdi_mxu_matches_single(eye):
    """MXU slice-march distributed pipeline vs single-device MXU VDI:
    both march regimes (domain axis and in-plane-z with halo+ownership)."""
    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.vdi_render import render_vdi
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    n = 4
    mesh = make_mesh(n)
    vol = procedural_volume(16, kind="blobs")
    cam = Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)
    tf = _tf()
    cfg = VDIConfig(max_supersegments=10, adaptive_iters=4)

    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5))
    # single-device reference through the same engine
    vdi_s, meta_s, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg)
    ref = np.asarray(render_vdi(vdi_s, meta_s, cam, W, H, steps=STEPS))

    step = distributed_vdi_step_mxu(
        mesh, tf, spec, cfg, CompositeConfig(max_output_supersegments=16))
    vdi, meta = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing,
                     cam)
    assert vdi.color.shape == (16, 4, spec.nj, spec.ni)
    img = np.asarray(render_vdi(vdi, meta, cam, W, H, steps=STEPS))
    q = psnr(ref, img)
    assert q > 27.0, f"PSNR {q:.1f} dB at eye {eye}"


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z (sharded)
                                 (3.8, 0.3, 0.6)])   # march axis x (in-plane z)
def test_distributed_vdi_mxu_temporal_matches_histogram(eye):
    """Distributed temporal mode (per-rank carried threshold, one march
    per frame) converges to the same composited VDI quality as the
    per-frame histogram mode, in both march regimes."""
    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.vdi_render import render_vdi
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu,
        distributed_vdi_step_mxu_temporal)

    n = 4
    mesh = make_mesh(n)
    vol = procedural_volume(16, kind="blobs")
    cam = Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)
    tf = _tf()
    comp = CompositeConfig(max_output_supersegments=16)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5))
    data = shard_volume(vol.data, mesh)

    cfg_h = VDIConfig(max_supersegments=10, adaptive_mode="histogram")
    vdi_h, meta_h = distributed_vdi_step_mxu(mesh, tf, spec, cfg_h, comp)(
        data, vol.origin, vol.spacing, cam)
    ref = np.asarray(render_vdi(vdi_h, meta_h, cam, W, H, steps=STEPS))

    cfg_t = VDIConfig(max_supersegments=10, adaptive_mode="temporal")
    thr = distributed_initial_threshold_mxu(mesh, tf, spec, cfg_t)(
        data, vol.origin, vol.spacing, cam)
    assert thr.thr.shape == (n * spec.nj, spec.ni)   # rank-stacked maps
    step_t = distributed_vdi_step_mxu_temporal(mesh, tf, spec, cfg_t, comp)
    for _ in range(3):
        (vdi_t, meta_t), thr = step_t(data, vol.origin, vol.spacing, cam,
                                      thr)
    img = np.asarray(render_vdi(vdi_t, meta_t, cam, W, H, steps=STEPS))
    assert np.isfinite(img).all()
    q = psnr(ref, img)
    assert q > 27.0, f"PSNR {q:.1f} dB at eye {eye}"


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z (sharded)
                                 (3.8, 0.3, 0.6)])   # march axis x (in-plane z)
def test_distributed_plain_mxu_matches_single(eye):
    """Distributed MXU plain-image mode (render_slices + column exchange +
    nearest-first composite + display warp) vs the single-device MXU
    renderer — both march regimes (≅ the reference's plain pipeline,
    DistributedVolumeRenderer.kt:175-189, on the slice-march engine)."""
    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu)

    n = 4
    mesh = make_mesh(n)
    vol = procedural_volume(16, kind="blobs")
    cam = Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)
    tf = _tf()
    bg = (0.1, 0.2, 0.3, 1.0)
    spec = slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5),
                            multiple_of=n)

    ref = np.asarray(slicer.raycast_mxu(vol, tf, cam, W, H, spec,
                                        background=bg).image)

    step = distributed_plain_step_mxu(mesh, tf, spec)
    img_i, axcam = step(shard_volume(vol.data, mesh), vol.origin,
                        vol.spacing, cam)
    assert img_i.shape == (4, spec.nj, spec.ni)
    img = np.asarray(slicer.warp_to_camera(img_i, axcam, spec, cam, W, H,
                                           bg))
    q = psnr(ref, img)
    assert q > 32.0, f"PSNR {q:.1f} dB at eye {eye}"


def test_distributed_vdi_mxu_with_vtiles():
    """In-plane occupancy tiles composed with the distributed MXU VDI
    pipeline: each rank re-clamps the tile count against its own slab's
    v extent (which is far below the global clamp when marching across
    the sharded axis), and the result must match the untiled pipeline
    exactly (conservative gating)."""
    from scenery_insitu_tpu.config import (CompositeConfig,
                                           SliceMarchConfig, VDIConfig)
    from scenery_insitu_tpu.ops import slicer as slc
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    n = 4
    mesh = make_mesh(n)
    data = np.zeros((32, 32, 32), np.float32)
    data[6:18, 4:14, 8:20] = 0.7
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    cam = Camera.create((0.1, 2.9, 0.3), fov_y_deg=45.0, near=0.3,
                        far=10.0)   # looks down -y: marches ACROSS z shards
    vdi_cfg = VDIConfig(max_supersegments=4, adaptive_iters=2)
    comp_cfg = CompositeConfig(max_output_supersegments=6, adaptive_iters=2)

    outs = {}
    for vt in (0, 8):
        spec = slc.make_spec(cam, vol.data.shape,
                             SliceMarchConfig(matmul_dtype="f32", scale=1.0,
                                              occupancy_vtiles=vt),
                             multiple_of=n)
        step = distributed_vdi_step_mxu(mesh, _tf(), spec, vdi_cfg,
                                        comp_cfg)
        vdi, _ = step(shard_volume(vol.data, mesh), vol.origin,
                      vol.spacing, cam)
        outs[vt] = (np.asarray(vdi.color), np.asarray(vdi.depth))
    # block-split einsums fuse differently than the single einsum -> fp
    # association noise ~1e-7; a DROPPED block would differ by whole
    # sample values (~1e-1), so this tight bound still proves the gate
    # is conservative
    np.testing.assert_allclose(outs[8][0], outs[0][0], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(outs[8][1], outs[0][1], rtol=1e-5,
                               atol=1e-6)
