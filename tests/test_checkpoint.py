"""Session checkpoint/resume tests: a resumed session must continue
bit-exactly where the checkpointed one stopped (the aux subsystem the
reference lacks — it could only replay render-product dumps)."""

import numpy as np
import pytest

from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.runtime.checkpoint import (checkpoint_sink,
                                                   load_session,
                                                   save_session)
from scenery_insitu_tpu.runtime.session import InSituSession


def _cfg(**over):
    base = dict([
        ("slicer.engine", "mxu"), ("slicer.scale", "1.0"),
        ("sim.grid", "[16,16,16]"), ("sim.steps_per_frame", "2"),
        ("vdi.max_supersegments", "6"), ("vdi.adaptive_mode", "temporal"),
        ("composite.max_output_supersegments", "8"),
        ("mesh.num_devices", "4"),
    ])
    base.update(over)
    return FrameworkConfig().with_overrides(
        *(f"{k}={v}" for k, v in base.items()))


def test_resume_is_bit_exact(tmp_path):
    path = str(tmp_path / "ckpt.npz")

    # uninterrupted 5-frame run (orbiting camera, temporal thresholds)
    a = InSituSession(_cfg())
    a.orbit_rate = 0.05
    ref = a.run(5)

    # 3 frames -> checkpoint -> fresh session -> resume -> 2 more
    b = InSituSession(_cfg())
    b.orbit_rate = 0.05
    b.run(3)
    save_session(b, path)

    c = InSituSession(_cfg())
    c.orbit_rate = 0.123   # overwritten by the checkpoint
    load_session(c, path)
    assert c.frame_index == b.frame_index
    assert c.orbit_rate == 0.05
    assert len(c._mxu_thr) == len(b._mxu_thr)
    got = c.run(2)

    assert got["frame"] == ref["frame"]
    np.testing.assert_array_equal(ref["vdi_color"], got["vdi_color"])
    np.testing.assert_array_equal(ref["vdi_depth"], got["vdi_depth"])


def test_resume_particle_session(tmp_path):
    path = str(tmp_path / "p.npz")
    cfg = _cfg(**{"sim.kind": "sho", "sim.num_particles": "500",
                  "vdi.adaptive_mode": "histogram",
                  "render.width": "32", "render.height": "24"})
    a = InSituSession(cfg)
    ref = a.run(4)

    b = InSituSession(cfg)
    b.run(2)
    save_session(b, path)
    c = InSituSession(cfg)
    load_session(c, path)
    got = c.run(2)
    np.testing.assert_array_equal(ref["image"], got["image"])


def test_mismatched_checkpoint_rejected(tmp_path):
    path = str(tmp_path / "m.npz")
    a = InSituSession(_cfg())
    a.run(1)
    save_session(a, path)

    wrong_kind = InSituSession(_cfg(**{"sim.kind": "vortex"}))
    with pytest.raises(ValueError, match="sim kind"):
        load_session(wrong_kind, path)

    wrong_shape = InSituSession(_cfg(**{"sim.grid": "[32,32,32]"}))
    with pytest.raises(ValueError, match="shape"):
        load_session(wrong_shape, path)


def test_checkpoint_sink(tmp_path):
    sess = InSituSession(_cfg(**{"vdi.adaptive_mode": "histogram"}))
    sess.sinks.append(checkpoint_sink(str(tmp_path), every=2).bind(sess))
    sess.run(4)
    import glob
    files = sorted(glob.glob(str(tmp_path / "ckpt_*.npz")))
    assert len(files) >= 1
    # the dump must load back into a fresh same-config session
    c = InSituSession(_cfg(**{"vdi.adaptive_mode": "histogram"}))
    load_session(c, files[-1])


def test_resume_bit_exact_across_regime_switches(tmp_path):
    """Checkpoint taken mid-orbit with several march regimes' threshold
    state in flight: the resumed run must reproduce the uninterrupted one
    bit-exactly, including the regime tracker's drop/keep decisions."""
    path = str(tmp_path / "r.npz")

    def mk():
        s = InSituSession(_cfg(**{"sim.grid": "[12,12,12]",
                                  "mesh.num_devices": "2"}))
        s.orbit_rate = 0.35      # ~18 frames per revolution
        return s

    a = mk()
    ref = a.run(20)
    assert len(a._mxu_thr) >= 2          # the orbit crossed regimes

    b = mk()
    b.run(12)
    assert len(b._mxu_thr) >= 2   # the checkpoint itself is multi-regime
    save_session(b, path)
    c = mk()
    load_session(c, path)
    # the drop/keep tracker must survive the round trip — without it the
    # first post-resume frame makes a different drop decision than the
    # uninterrupted run whenever the boundary lands on a regime switch
    assert c._last_regime_key == b._last_regime_key
    got = c.run(8)

    assert got["frame"] == ref["frame"]
    np.testing.assert_array_equal(ref["vdi_color"], got["vdi_color"])
    np.testing.assert_array_equal(ref["vdi_depth"], got["vdi_depth"])


def test_hybrid_temporal_checkpoint_roundtrip(tmp_path):
    """Hybrid-mode temporal keys are ('hybrid', axis, sign) 3-tuples: both
    signs of an axis must checkpoint under DISTINCT tags and restore
    without cross-contamination."""
    import jax.numpy as jnp

    from scenery_insitu_tpu.ops.supersegments import ThresholdState

    path = str(tmp_path / "h.npz")
    cfg = _cfg(**{"sim.kind": "hybrid", "sim.num_particles": "32",
                  "sim.particle_radius": "0.8",
                  "sim.grid": "[12,12,12]", "mesh.num_devices": "2"})
    a = InSituSession(cfg)
    assert a._temporal
    a.run(2)
    (key,) = list(a._mxu_thr)
    assert key[0] == "hybrid" and len(key) == 3
    # fabricate the opposite-sign regime with distinct values: a tag
    # collision would make one of the two restore as the other
    other = (key[0], key[1], -key[2])
    a._mxu_thr[other] = ThresholdState(
        *(jnp.asarray(x) + 0.125 for x in a._mxu_thr[key]))
    save_session(a, path)

    b = InSituSession(cfg)
    b.run(2)
    load_session(b, path)
    assert set(b._mxu_thr) == {key, other}
    np.testing.assert_array_equal(np.asarray(a._mxu_thr[key].thr),
                                  np.asarray(b._mxu_thr[key].thr))
    np.testing.assert_array_equal(np.asarray(a._mxu_thr[other].thr),
                                  np.asarray(b._mxu_thr[other].thr))
    assert not np.array_equal(np.asarray(b._mxu_thr[key].thr),
                              np.asarray(b._mxu_thr[other].thr))


def test_steered_tf_survives_checkpoint(tmp_path):
    """A session whose TF was changed by steering must resume with THAT
    TF, not the constructor's — bit-exact across the round trip."""
    from scenery_insitu_tpu.runtime.streaming import make_tf_message

    path = str(tmp_path / "tf.npz")

    def mk():
        s = InSituSession(_cfg(**{"sim.grid": "[12,12,12]",
                                  "mesh.num_devices": "2"}))
        return s

    a = mk()
    a.run(2)
    msg = make_tf_message([(0.0, 0.85), (1.0, 0.85)], colormap="jet")
    for cb in a.on_steer:
        cb(msg)
    a.run(1)
    save_session(a, path)
    ref = a.run(2)

    b = mk()
    load_session(b, path)
    np.testing.assert_array_equal(np.asarray(b.tf.alpha_m),
                                  np.asarray(a.tf.alpha_m))
    got = b.run(2)
    np.testing.assert_array_equal(ref["vdi_color"], got["vdi_color"])
