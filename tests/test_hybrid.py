"""Hybrid volume+particle compositing (BASELINE.md Config 5; ops/hybrid.py,
models.pipelines.hybrid_vortex_frame_step, parallel distributed hybrid).

Covers: depth-correct insertion semantics (front/behind/inside a slab), the
one-depth-convention contract between splat and VDI, the single-chip frame
step, and distributed ≅ single-device parity on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import (CompositeConfig, SliceMarchConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.vdi import VDI, render_vdi_same_view
from scenery_insitu_tpu.ops.hybrid import composite_vdi_with_particles
from scenery_insitu_tpu.ops.splat import SplatOutput
from scenery_insitu_tpu.utils.image import psnr


def _one_seg_vdi(h, w, rgba, t0, t1, k=3):
    color = jnp.zeros((k, 4, h, w), jnp.float32)
    depth = jnp.full((k, 2, h, w), jnp.inf, jnp.float32)
    color = color.at[0].set(jnp.asarray(rgba, jnp.float32)[:, None, None])
    depth = depth.at[0, 0].set(t0).at[0, 1].set(t1)
    return VDI(color, depth)


def test_no_particle_reproduces_vdi_decode():
    vdi = _one_seg_vdi(4, 8, (0.2, 0.1, 0.0, 0.4), 2.0, 3.0)
    empty = SplatOutput(jnp.zeros((4, 4, 8)), jnp.full((4, 8), jnp.inf))
    out = composite_vdi_with_particles(vdi, empty)
    ref = render_vdi_same_view(vdi)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_particle_in_front_hides_volume():
    vdi = _one_seg_vdi(4, 8, (0.2, 0.1, 0.0, 0.9), 2.0, 3.0)
    pimg = jnp.zeros((4, 4, 8)).at[0].set(1.0).at[3].set(1.0)  # opaque red
    sp = SplatOutput(pimg, jnp.full((4, 8), 1.0))              # t=1 < 2
    out = composite_vdi_with_particles(vdi, sp)
    np.testing.assert_allclose(np.asarray(out[0]), 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), 0.0, atol=1e-6)


def test_particle_behind_fully_occluded_fraction():
    """Particle inside the slab: the slab contributes its traversed
    fraction in front, the particle shows through the remaining
    transmittance."""
    a = 0.6
    vdi = _one_seg_vdi(1, 1, (0.0, a, 0.0, a), 2.0, 4.0)   # green slab
    pimg = jnp.zeros((4, 1, 1)).at[0].set(1.0).at[3].set(1.0)
    sp = SplatOutput(pimg, jnp.full((1, 1), 3.0))          # halfway in
    out = np.asarray(composite_vdi_with_particles(vdi, sp))
    a_half = 1.0 - (1.0 - a) ** 0.5
    # red channel = particle through the half-slab transmittance
    np.testing.assert_allclose(out[0, 0, 0], 1.0 - a_half, atol=1e-6)
    # green = the front half-slab's effective contribution
    np.testing.assert_allclose(out[1, 0, 0], a_half * (a / a), atol=1e-5)
    np.testing.assert_allclose(out[3, 0, 0], 1.0, atol=1e-6)


def test_single_chip_hybrid_frame_step():
    from scenery_insitu_tpu.models.pipelines import hybrid_vortex_frame_step
    from scenery_insitu_tpu.sim import vortex

    grid = (16, 16, 16)
    flow = vortex.VortexFlow.init_ring(grid)
    pos = vortex.seed_tracers(grid, 64)
    step = jax.jit(hybrid_vortex_frame_step(
        48, 40, grid, axis_sign=(2, -1), sim_steps=2,
        vdi_cfg=VDIConfig(max_supersegments=4, adaptive_iters=2),
        slicer_cfg=SliceMarchConfig(matmul_dtype="f32")))
    eye = jnp.array([0.0, 0.5, 2.8], jnp.float32)
    img, u2, pos2 = step(flow.u, pos, eye)
    assert img.shape == (4, 40, 48)
    assert np.isfinite(np.asarray(img)).all()
    assert not np.array_equal(np.asarray(pos2), np.asarray(pos))
    # particles render: some pixel has near-opaque alpha (spheres are
    # opaque, the volume's TF alone is capped well below 1 here)
    assert np.asarray(img)[3].max() > 0.9


def test_distributed_hybrid_matches_single_device():
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.splat import speed_colors, splat_particles
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.particles import shard_particles
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu, shard_volume)
    from scenery_insitu_tpu.sim import vortex

    n = 8
    mesh = make_mesh(n)
    grid = (16, 16, 16)
    flow = vortex.VortexFlow.init_ring(grid)
    flow = vortex.multi_step(flow, 2)
    field = flow.field
    npart = 64
    pos = vortex.seed_tracers(grid, npart, seed=3)
    vel = vortex.tracer_velocities(flow.u, pos)

    tf = for_dataset("rotstrat")
    cam = Camera.create((0.0, 0.4, 2.8), fov_y_deg=50.0, near=0.5, far=20.0)
    cfg = VDIConfig(max_supersegments=4, adaptive_iters=2)
    spec = slicer.make_spec(cam, grid, SliceMarchConfig(matmul_dtype="f32"),
                            multiple_of=n)
    vol = Volume.centered(field, extent=2.0)
    world = vol.origin + pos * vol.spacing
    radius, stamp = 0.05, 5

    # single device reference
    vdi, _, axcam = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg)
    rgba = speed_colors(vel, "jet")
    sp = splat_particles(world, rgba, radius, None, spec.ni, spec.nj, stamp,
                         view=axcam.view, proj=axcam.proj)
    from scenery_insitu_tpu.config import CompositeConfig
    from scenery_insitu_tpu.ops.composite import composite_vdis
    ccfg = CompositeConfig(max_output_supersegments=6, adaptive_iters=2)
    comp1 = composite_vdis(vdi.color[None], vdi.depth[None], ccfg)
    ref = composite_vdi_with_particles(comp1, sp)

    # distributed
    step = distributed_hybrid_step_mxu(mesh, tf, spec, cfg, ccfg,
                                       radius=radius, stamp=stamp)
    img, meta = step(shard_volume(field, mesh), vol.origin, vol.spacing,
                     shard_particles(np.asarray(world), mesh),
                     shard_particles(np.asarray(vel), mesh), cam)
    got = np.asarray(img)
    want = np.asarray(ref)
    assert got.shape == want.shape
    p = psnr(got, want)
    assert p > 35.0, f"distributed hybrid diverges: PSNR {p:.1f} dB"


def test_distributed_hybrid_temporal_matches_untracked():
    """Hybrid step with carried temporal thresholds (one march/frame)
    converges to the same image as the per-frame histogram hybrid step."""
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.parallel.particles import shard_particles
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu, distributed_initial_threshold_mxu,
        shard_volume)
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.sim import vortex
    from scenery_insitu_tpu.utils.image import psnr

    n = 4
    mesh = make_mesh(n)
    grid = (16, 16, 16)
    flow = vortex.VortexFlow.init_ring(grid)
    flow = vortex.multi_step(flow, 2)
    vol = Volume.centered(flow.field, extent=2.0)
    pos = vortex.seed_tracers(grid, 64, seed=3)
    vel = vortex.tracer_velocities(flow.u, pos)
    world = vol.origin + pos * vol.spacing

    tf = for_dataset("rotstrat")
    cam = Camera.create((0.0, 0.4, 2.8), fov_y_deg=50.0, near=0.5, far=20.0)
    spec = slicer.make_spec(cam, grid, SliceMarchConfig(matmul_dtype="f32"),
                            multiple_of=n)
    comp = CompositeConfig(max_output_supersegments=6, adaptive_iters=2)
    data = shard_volume(vol.data, mesh)
    wsh = shard_particles(world, mesh)
    vsh = shard_particles(vel, mesh)

    cfg_h = VDIConfig(max_supersegments=4, adaptive_mode="histogram")
    img_h, _ = distributed_hybrid_step_mxu(
        mesh, tf, spec, cfg_h, comp, radius=0.05, stamp=3)(
        data, vol.origin, vol.spacing, wsh, vsh, cam)

    cfg_t = VDIConfig(max_supersegments=4, adaptive_mode="temporal")
    thr = distributed_initial_threshold_mxu(mesh, tf, spec, cfg_t)(
        data, vol.origin, vol.spacing, cam)
    step_t = distributed_hybrid_step_mxu(
        mesh, tf, spec, cfg_t, comp, radius=0.05, stamp=3, temporal=True)
    for _ in range(3):
        (img_t, _), thr = step_t(data, vol.origin, vol.spacing, wsh, vsh,
                                 cam, thr)
    assert np.isfinite(np.asarray(img_t)).all()
    q = psnr(np.asarray(img_h), np.asarray(img_t))
    assert q > 27.0, f"PSNR {q:.1f} dB"
