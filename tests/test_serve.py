"""The VDI edge-serving tier (scenery_insitu_tpu/serve; ISSUE 13):
batched-render bitwise parity, padded-bucket invariance, mixed-tier
loopback serving, camera-delta caching, admission control (sheds are
ledgered answers, not exceptions), bounded staleness, the mid-stream
join fixes, and viewer-side reprojection."""

import time

import jax
import numpy as np
import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import (FrameworkConfig, ServeConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera, orbit
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.vdi_novel import (render_vdi_batch,
                                              render_vdi_exact,
                                              render_vdi_mxu,
                                              render_vdi_proxy,
                                              stack_cameras,
                                              vdi_to_rgba_volume)

W, H, NS = 48, 40, 24
F32 = SliceMarchConfig(matmul_dtype="f32", scale=1.5)


@pytest.fixture(scope="module")
def fixture():
    vol = procedural_volume(32, kind="blobs", seed=3)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.1, 0.3, 2.8), fov_y_deg=45.0, near=0.3,
                         far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape, F32)
    vdi, meta, axcam = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=8,
                                       adaptive_iters=2))
    return vol, cam0, spec, vdi, meta, axcam


def _cams(cam0, n):
    return [orbit(cam0, 0.03 * i, 0.015 * i) for i in range(n)]


# ---------------------------------------------------- batch render parity


def test_batch_sweep_bitwise_vs_independent_mxu(fixture):
    """The batched N-camera render equals N independent render_vdi_mxu
    calls BITWISE (the lax.map body is the unmodified single-camera
    renderer — a vmapped batch would drift ~1e-5)."""
    vol, cam0, spec, vdi, meta, axcam = fixture
    regime = slicer.choose_axis(cam0)
    cams = _cams(cam0, 4)
    b = np.asarray(jax.jit(lambda cs: render_vdi_batch(
        vdi, axcam, spec, cs, W, H, tier="sweep", num_slices=NS,
        axis_sign=regime))(stack_cameras(cams)))
    s = np.stack([np.asarray(jax.jit(lambda c: render_vdi_mxu(
        vdi, axcam, spec, c, W, H, num_slices=NS, axis_sign=regime))(c))
        for c in cams])
    np.testing.assert_array_equal(b, s)


def test_batch_exact_bitwise_vs_independent_exact(fixture):
    vol, cam0, spec, vdi, meta, axcam = fixture
    cams = _cams(cam0, 3)
    b = np.asarray(jax.jit(lambda cs: render_vdi_batch(
        vdi, axcam, spec, cs, W, H, tier="exact"))(stack_cameras(cams)))
    s = np.stack([np.asarray(jax.jit(lambda c: render_vdi_exact(
        vdi, axcam, spec, c, W, H))(c)) for c in cams])
    np.testing.assert_array_equal(b, s)


def test_batch_proxy_bitwise_vs_independent_proxy(fixture):
    """Proxy tier: one shared vdi_to_rgba_volume expansion, per-camera
    marches — batch equals independent render_vdi_proxy calls bitwise."""
    vol, cam0, spec, vdi, meta, axcam = fixture
    regime = slicer.choose_axis(cam0)
    proxy = vdi_to_rgba_volume(vdi, axcam, spec, num_slices=NS)
    spec_new = slicer.make_spec(cam0, proxy.data.shape[-3:],
                                F32, axis_sign=regime)
    cams = _cams(cam0, 4)
    b = np.asarray(jax.jit(lambda cs: render_vdi_batch(
        None, None, spec, cs, W, H, tier="proxy", proxy=proxy,
        spec_new=spec_new))(stack_cameras(cams)))
    s = np.stack([np.asarray(jax.jit(lambda c: render_vdi_proxy(
        proxy, c, W, H, spec_new))(c)) for c in cams])
    np.testing.assert_array_equal(b, s)


def test_padded_bucket_invariance(fixture):
    """Padding a batch of 3 to a bucket of 4 (replicated last camera)
    leaves the real entries bit-unchanged, and each element is
    independent of what else shares the batch."""
    vol, cam0, spec, vdi, meta, axcam = fixture
    regime = slicer.choose_axis(cam0)
    cams = _cams(cam0, 3)
    f = lambda cs: render_vdi_batch(vdi, axcam, spec, cs, W, H,
                                    tier="sweep", num_slices=NS,
                                    axis_sign=regime)
    b3 = np.asarray(jax.jit(f)(stack_cameras(cams)))
    b4 = np.asarray(jax.jit(f)(stack_cameras(cams + [cams[-1]])))
    np.testing.assert_array_equal(b3, b4[:3])
    np.testing.assert_array_equal(b4[2], b4[3])        # replicated lane


def test_batch_requires_regime_for_traced_tiers(fixture):
    vol, cam0, spec, vdi, meta, axcam = fixture
    cams = stack_cameras(_cams(cam0, 2))
    with pytest.raises(ValueError, match="axis_sign"):
        render_vdi_batch(vdi, axcam, spec, cams, W, H, tier="sweep")
    with pytest.raises(ValueError, match="tier"):
        render_vdi_batch(vdi, axcam, spec, cams, W, H, tier="nope")


# ------------------------------------------------------ loopback serving


def _pump(srv, clients, cond, secs=30):
    deadline = time.monotonic() + secs
    while time.monotonic() < deadline:
        srv.run_once(timeout_ms=10)
        got = cond()
        if got is not None:
            return got
    return None


def _serve_pair(fixture, *overrides, publish=True):
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher
    from scenery_insitu_tpu.serve import ViewerServer

    vol, cam0, spec, vdi, meta, axcam = fixture
    cfg = FrameworkConfig().with_overrides(
        f"serve.width={W}", f"serve.height={H}", f"serve.num_slices={NS}",
        "serve.batch_size=8", "serve.buckets=[1,2,4,8]", *overrides)
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    srv = ViewerServer(cfg, connect=pub.endpoint, bind="tcp://127.0.0.1:0")
    if publish:
        time.sleep(0.25)
        pub.publish(vdi, meta._replace(index=np.int32(0)))
        got = _pump(srv, (), lambda: srv.frame)
        assert got is not None, "server never adopted a frame"
    return pub, srv


def test_loopback_mixed_tier_batch(fixture):
    """One server, three tiers in one pump cycle: every client gets its
    own tier's pixels, proxy == direct render bitwise, wire == the u8
    quantization of the same render."""
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture)
    cs = [ViewerClient(srv.endpoint, tier=t)
          for t in ("proxy", "exact", "wire")]
    try:
        novel = orbit(cam0, 0.15)
        for c in cs:
            c.hello(timeout_ms=0)
            c.request(novel)
        done = _pump(srv, cs, lambda: (
            True if all(c.last is not None
                        or isinstance(c.poll(timeout_ms=0), ViewerFrame)
                        for c in cs) and all(c.last for c in cs)
            else None))
        assert done, [c.stats for c in cs]
        fp, fe, fw = (c.last for c in cs)
        assert (fp.tier, fe.tier, fw.tier) == ("proxy", "exact", "wire")
        # proxy answer == the independent proxy render, bitwise (the
        # reference takes the proxy as jit ARGUMENTS like the server
        # does — a closure constant would fold differently)
        from scenery_insitu_tpu.core.volume import Volume

        regime = slicer.choose_axis(novel)
        proxy = srv._ensure_proxy()
        spec_new = srv._spec_new_for(regime,
                                     tuple(proxy.data.shape[-3:]))
        ref = np.asarray(jax.jit(lambda pd, po, ps, c: render_vdi_proxy(
            Volume(pd, po, ps), c, W, H, spec_new))(
            proxy.data, proxy.origin, proxy.spacing, novel))
        np.testing.assert_array_equal(fp.image, ref)
        # wire answer is the u8 wire quantization of that same render
        np.testing.assert_array_equal(
            fw.image,
            np.clip(np.round(ref * 255), 0, 255).astype(np.uint8)
            .astype(np.float32) / 255.0)
        # exact differs from proxy (different renderer) but is finite
        assert np.isfinite(fe.image).all() and fe.image[3].max() > 0.0
        # bytes/viewer: the wire tier ships 4x fewer bytes
        assert fp.wire_bytes == 4 * fw.wire_bytes
    finally:
        for c in cs:
            c.close()
        srv.close()
        pub.close()


def test_camera_delta_cache_and_tolerance(fixture):
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, "serve.cam_tol=1e-4")
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        novel = orbit(cam0, 0.15)
        c.request(novel)
        f1 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f1, ViewerFrame) and not f1.cached
        # bit-identical camera -> cached answer, identical pixels
        c.request(novel)
        f2 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert f2.cached and np.array_equal(f2.image, f1.image)
        # a sub-tolerance nudge still re-serves the cache
        c.request(novel._replace(
            eye=novel.eye + np.float32(5e-5)))
        f3 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert f3.cached
        # a real move re-renders
        c.request(orbit(cam0, 0.3))
        f4 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert not f4.cached
        assert not np.array_equal(f4.image, f1.image)
        assert srv.stats["cache_hits"] == 2
        # a tier re-negotiation invalidates the cache even for the same
        # camera (the payload dtype changes — a stale f32 blob must
        # never serve a wire client)
        c.tier = "wire"
        c.hello(timeout_ms=0)
        w = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(w, dict) and w["tier"] == "wire"
        c.request(orbit(cam0, 0.3))
        f5 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert f5.tier == "wire" and not f5.cached
        assert f5.wire_bytes == f4.wire_bytes // 4
    finally:
        c.close()
        srv.close()
        pub.close()


def test_admission_shed_is_ledgered_not_raised(fixture):
    from scenery_insitu_tpu.serve import ServeDrop, ViewerClient

    pub, srv = _serve_pair(fixture, "serve.max_viewers=1")
    c1 = ViewerClient(srv.endpoint, tier="proxy")
    c2 = ViewerClient(srv.endpoint, tier="proxy")
    try:
        c1.hello(timeout_ms=0)
        w = _pump(srv, (c1,), lambda: c1.poll(timeout_ms=0))
        assert isinstance(w, dict) and w["type"] == "welcome"
        c2.hello(timeout_ms=0)
        shed = _pump(srv, (c2,), lambda: c2.poll(timeout_ms=0))
        assert isinstance(shed, ServeDrop) and shed.kind == "shed"
        assert shed.reason == "max_viewers"
        comps = [e["component"] for e in obs.ledger()]
        assert "serve.shed" in comps
        assert srv.stats["sheds"] >= 1
    finally:
        c1.close()
        c2.close()
        srv.close()
        pub.close()


def test_queue_cap_sheds_and_coalescing(fixture):
    """Requests coalesce latest-wins per client (the queue holds one
    request per client), and distinct clients beyond queue_cap shed."""
    from scenery_insitu_tpu.serve import ServeDrop, ViewerClient

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, "serve.queue_cap=1",
                           "serve.max_viewers=4")
    c1 = ViewerClient(srv.endpoint, tier="proxy")
    c2 = ViewerClient(srv.endpoint, tier="proxy")
    try:
        # two requests from ONE client: coalesce, no shed
        c1.request(orbit(cam0, 0.1))
        c1.request(orbit(cam0, 0.2))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not srv.queue:
            srv.pump_clients()
            time.sleep(0.01)
        srv.pump_clients()
        assert len(srv.queue) == 1
        # a second client while the queue is full: shed
        c2.request(orbit(cam0, 0.3))
        shed = _pump(srv, (c2,), lambda: c2.poll(timeout_ms=0))
        assert isinstance(shed, ServeDrop) and shed.reason == "queue_cap"
    finally:
        c1.close()
        c2.close()
        srv.close()
        pub.close()


def test_bounded_staleness_stamped_and_ledgered(fixture):
    """Tiles of newer frames advance the stream head without completing;
    once the served VDI falls > staleness_frames behind, answers are
    stamped stale and serve.stale is minted."""
    from scenery_insitu_tpu.core.vdi import VDI as VDI_t
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, "serve.staleness_frames=2")
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        # newer frames exist but never complete (one tile of two)
        color = np.asarray(vdi.color)
        depth = np.asarray(vdi.depth)
        half = VDI_t(color[..., :color.shape[-1] // 2],
                     depth[..., :depth.shape[-1] // 2])
        for f in range(1, 6):
            pub.publish_tile(half, meta._replace(index=np.int32(f)),
                             0, 2, 0)
        got = _pump(srv, (), lambda: (
            True if srv.newest is not None and srv.newest >= 5 else None))
        assert got, srv.newest
        c.request(orbit(cam0, 0.12))
        f1 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f1, ViewerFrame) and f1.stale
        comps = [e["component"] for e in obs.ledger()]
        assert "serve.stale" in comps
        assert srv.stats["stale_answers"] >= 1
        # a cache hit re-stamps staleness too — the cached pixels are
        # the current frame's, but the head has moved past it
        c.request(orbit(cam0, 0.12))
        f2 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f2, ViewerFrame) and f2.cached and f2.stale
        assert srv.stats["stale_answers"] >= 2
    finally:
        c.close()
        srv.close()
        pub.close()


def test_staleness_head_advances_through_resync_drops(fixture):
    """Regression: during a temporal-delta resync window EVERY stream
    message surfaces as a typed drop — the staleness head must advance
    from those refused frames too, or answers read stale=False for
    exactly the degraded stretch the bounded-staleness contract
    targets."""
    from scenery_insitu_tpu.config import DeltaConfig
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher
    from scenery_insitu_tpu.serve import (ViewerClient, ViewerFrame,
                                          ViewerServer)
    from scenery_insitu_tpu.testing.faults import FaultSpec, inject

    vol, cam0, spec, vdi, meta, axcam = fixture
    cfg = FrameworkConfig().with_overrides(
        f"serve.width={W}", f"serve.height={H}", f"serve.num_slices={NS}",
        "serve.batch_size=8", "serve.buckets=[1,2,4,8]",
        "serve.staleness_frames=2")
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8",
                       delta=DeltaConfig(enabled=True, iframe_period=64))
    srv = ViewerServer(cfg, connect=pub.endpoint, bind="tcp://127.0.0.1:0")
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        time.sleep(0.25)
        pub.publish(vdi, meta._replace(index=np.int32(0)))   # I-frame
        got = _pump(srv, (), lambda: srv.frame)
        assert got is not None, "server never adopted the I-frame"
        # lose ONE message on the wire: the delta chain breaks, and with
        # iframe_period=64 every later record is a resync StreamDrop
        orig = pub.sock
        inject(pub, FaultSpec(drop=1.0))
        pub.publish(vdi, meta._replace(index=np.int32(1)))
        pub.sock = orig
        for f in range(2, 7):
            pub.publish(vdi, meta._replace(index=np.int32(f)))
        got = _pump(srv, (), lambda: (
            True if srv.stats["stream_drops"] >= 5 else None))
        assert got, srv.stats
        # the head advanced THROUGH the refused frames...
        assert srv.newest is not None and srv.newest >= 6, srv.newest
        # ...so the retained frame-0 answer is stamped stale
        c.request(orbit(cam0, 0.1))
        f1 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f1, ViewerFrame) and f1.stale
        assert srv.stats["stale_answers"] >= 1
    finally:
        c.close()
        srv.close()
        pub.close()


def test_garbage_camera_sender_does_not_occupy_admission(fixture):
    """Regression: a camera message that fails validation must not
    admit its sender — junk idents would otherwise fill max_viewers
    slots (renewable for client_timeout_s) and shed real viewers
    despite zero renderable load."""
    from scenery_insitu_tpu.runtime.streaming import _msgpack
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, "serve.max_viewers=1")
    junk = ViewerClient(srv.endpoint, tier="proxy")
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        # a garbage camera (non-finite eye) and a garbage seq: dropped
        # typed, and the sender is NOT admitted
        junk.sock.send(_msgpack().packb(
            {"type": "camera", "eye": "junk", "seq": 1}))
        junk.sock.send(_msgpack().packb(
            {"type": "camera", "eye": [0.0, 0.0, 3.0], "seq": "nope"}))
        # finite-but-degenerate: zero fov, inverted clip range — would
        # burn a full batched render producing a garbage frame
        junk.sock.send(_msgpack().packb(
            {"type": "camera", "eye": [0.0, 0.0, 3.0], "fov_y": 0.0,
             "near": 0.0, "far": -1.0, "seq": 2}))
        got = _pump(srv, (), lambda: (
            True if srv.stats["client_drops"] >= 3 else None))
        assert got, srv.stats
        assert len(srv.clients) == 0, "junk sender occupies a slot"
        # the one real viewer still fits under max_viewers=1
        c.request(orbit(cam0, 0.1))
        f = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f, ViewerFrame)
        assert srv.stats["sheds"] == 0, srv.stats
    finally:
        junk.close()
        c.close()
        srv.close()
        pub.close()


def test_request_without_hello_honors_tier(fixture):
    """Regression: a viewer that never says hello is implicitly
    admitted — its constructor tier must ride the camera request, not
    silently downgrade to serve.default_tier."""
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture)
    c = ViewerClient(srv.endpoint, tier="wire")
    try:
        c.request(orbit(cam0, 0.1))
        f = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f, ViewerFrame) and f.tier == "wire"
        assert f.wire_bytes == W * H * 4          # u8, not f32
    finally:
        c.close()
        srv.close()
        pub.close()


def test_client_refuses_frame_answer_missing_fields():
    """Regression: a corrupt-but-parseable frame answer (missing
    frame/seq/tier/stale/cached) is a typed ServeDrop, never an
    exception — the stated ViewerClient hardening contract."""
    from scenery_insitu_tpu.runtime.streaming import _msgpack, _zmq
    from scenery_insitu_tpu.serve import ServeDrop, ViewerClient

    zmq = _zmq()
    router = zmq.Context.instance().socket(zmq.ROUTER)
    port = router.bind_to_random_port("tcp://127.0.0.1")
    c = ViewerClient(f"tcp://127.0.0.1:{port}", tier="proxy")
    try:
        c.heartbeat()                       # teach the router the ident
        ident, _ = router.recv_multipart()
        blob = np.zeros((4, 2, 2), np.float32).tobytes()
        router.send_multipart([ident, _msgpack().packb(
            {"type": "frame", "shape": [4, 2, 2], "dtype": "f32"}),
            blob])
        got = c.poll(timeout_ms=5000)
        assert isinstance(got, ServeDrop) and got.kind == "malformed"
        assert c.stats["drops"] == 1
    finally:
        c.close()
        router.close(linger=0)


def test_client_heartbeat_pacer():
    """maybe_heartbeat fires only after fault.heartbeat_period_s of
    send silence (the PR-11 pacer convention)."""
    from scenery_insitu_tpu.config import FaultConfig
    from scenery_insitu_tpu.runtime.streaming import _zmq
    from scenery_insitu_tpu.serve import ViewerClient

    zmq = _zmq()
    router = zmq.Context.instance().socket(zmq.ROUTER)
    port = router.bind_to_random_port("tcp://127.0.0.1")
    c = ViewerClient(f"tcp://127.0.0.1:{port}",
                     fault=FaultConfig(heartbeat_period_s=0.2))
    try:
        assert not c.maybe_heartbeat()      # just constructed: quiet
        time.sleep(0.25)
        assert c.maybe_heartbeat()          # past the period: fires
        assert not c.maybe_heartbeat()      # freshly sent: quiet again
    finally:
        c.close()
        router.close(linger=0)


def test_unknown_tier_degrades_to_default(fixture):
    from scenery_insitu_tpu.serve import ViewerClient

    pub, srv = _serve_pair(fixture, publish=False)
    c = ViewerClient(srv.endpoint, tier="hologram")
    try:
        c.hello(timeout_ms=0)
        w = _pump(srv, (c,), lambda: c.poll(timeout_ms=0), secs=10)
        assert isinstance(w, dict) and w["tier"] == "proxy"
        comps = [e["component"] for e in obs.ledger()]
        assert "serve.tier" in comps
    finally:
        c.close()
        srv.close()
        pub.close()


def test_malformed_client_message_is_contained(fixture):
    """Garbage on the client socket drops typed (serve.client) and the
    server keeps serving the well-behaved viewer."""
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture)
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        c.sock.send(b"\xc1\x00\xff not msgpack")
        c.sock.send(b"\x00" * (srv.fault.max_message_bytes + 1))
        c.request(orbit(cam0, 0.1))
        f = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f, ViewerFrame)
        assert srv.stats["client_drops"] >= 2
        comps = [e["component"] for e in obs.ledger()]
        assert "serve.client" in comps
    finally:
        c.close()
        srv.close()
        pub.close()


# ------------------------------------------------- mid-stream join fixes


def test_receive_assembles_tile_streams(fixture):
    """Bugfix (ISSUE 13): VDISubscriber.receive on a TILE-granular
    stream returns whole assembled frames, never a mislabeled column
    block; a mid-stream join waits for the next complete frame."""
    from scenery_insitu_tpu.core.vdi import VDI as VDI_t
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    vol, cam0, spec, vdi, meta, axcam = fixture
    color = np.asarray(vdi.color)
    depth = np.asarray(vdi.depth)
    wb = color.shape[-1] // 2
    tiles = [VDI_t(color[..., i * wb:(i + 1) * wb],
                   depth[..., i * wb:(i + 1) * wb]) for i in range(2)]
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    sub = VDISubscriber(pub.endpoint)
    try:
        time.sleep(0.25)
        # mid-frame join shape: the subscriber sees only tile 1 of
        # frame 0, then both tiles of frame 1
        pub.publish_tile(tiles[1], meta._replace(index=np.int32(0)),
                         1, 2, wb)
        for t in range(2):
            pub.publish_tile(tiles[t], meta._replace(index=np.int32(1)),
                             t, 2, t * wb)
        got = sub.receive(timeout_ms=5000)
        assert got is not None and not hasattr(got, "kind")
        rvdi, rmeta = got
        assert int(np.asarray(rmeta.index)) == 1      # frame 0 never done
        assert rvdi.color.shape == color.shape        # FULL width
        np.testing.assert_array_equal(np.asarray(rvdi.color), color)
    finally:
        pub.close()
        sub.close()


def test_mid_stream_delta_join_waits_for_iframe(fixture):
    """A subscriber joining a temporal-delta stream mid-flight sees
    typed resync drops (never an exception) until the next I-frame,
    then clean frames."""
    from scenery_insitu_tpu.config import DeltaConfig
    from scenery_insitu_tpu.runtime.streaming import (StreamDrop,
                                                      VDIPublisher,
                                                      VDISubscriber)

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8", epoch=5,
                       delta=DeltaConfig(enabled=True, iframe_period=4))
    # consume the stream head so the encoder is past its first I-frame
    for i in range(2):
        pub.publish(vdi, meta._replace(index=np.int32(i)))
    sub = VDISubscriber(pub.endpoint)    # mid-stream join
    try:
        time.sleep(0.25)
        good, resyncs = None, 0
        for i in range(2, 8):
            pub.publish(vdi, meta._replace(index=np.int32(i)))
            got = sub.receive(timeout_ms=3000)
            if isinstance(got, StreamDrop):
                assert got.kind == "resync"
                resyncs += 1
                continue
            if got is not None:
                good = got
                break
        assert good is not None, "never recovered within iframe_period"
        assert resyncs >= 1                 # first contact was a P/SKIP
        assert sub.stats["resyncs"] == resyncs
        np.testing.assert_allclose(np.asarray(good[0].color),
                                   np.asarray(vdi.color), atol=0.05)
    finally:
        pub.close()
        sub.close()


def test_gather_vdi_served_with_derived_plane_count(fixture):
    """Regression (found driving the session chain): gather-engine VDIs
    (the session default on CPU) have their reconstructed plane ladder
    start at the camera NEAR PLANE, well before the volume — a fixed
    serve.num_slices that stops short serves blank proxy frames. The
    default (0) derives the count from the frame's own depth range and
    must produce content."""
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, _, _, _ = fixture
    tf = for_dataset("procedural")
    gvdi, gmeta = generate_vdi(vol, tf, cam0, 64, 48,
                               VDIConfig(max_supersegments=6,
                                         adaptive_iters=2), max_steps=96)
    pub, srv = _serve_pair(fixture, "serve.num_slices=0", publish=False)
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        time.sleep(0.25)
        pub.publish(gvdi, gmeta)
        got = _pump(srv, (), lambda: srv.frame)
        assert got is not None
        # derived count reaches past the near-plane gap to the content
        assert srv.frame["num_slices"] > 24
        c.request(orbit(cam0, 0.1))
        f = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f, ViewerFrame)
        assert float(f.image[3].max()) > 0.05, "blank proxy frame"
    finally:
        c.close()
        srv.close()
        pub.close()


def test_server_survives_publisher_restart(fixture):
    """A publisher restart (new epoch, frame indices reset) must reset
    the server's OWN assembler and stream-head tracking: without the
    mirror reset, the late-tile guard wedges assembly (new indices sit
    below the old head) and every answer reads stale forever."""
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher
    from scenery_insitu_tpu.serve import ViewerClient, ViewerFrame

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, publish=False)
    c = ViewerClient(srv.endpoint, tier="proxy")
    try:
        time.sleep(0.25)
        # first incarnation runs far ahead; answer once (fills the cache)
        pub.publish(vdi, meta._replace(index=np.int32(500)))
        got = _pump(srv, (), lambda: srv.frame)
        assert got is not None and srv.frame["index"] == 500
        c.request(orbit(cam0, 0.1))
        f0 = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f0, ViewerFrame) and not f0.cached
        # restart: new epoch, indices restart near zero
        pub.close()
        pub2 = VDIPublisher(pub.endpoint.replace("127.0.0.1", "*"),
                            codec="zlib")
        time.sleep(0.25)
        deadline = time.monotonic() + 15
        while (srv.frame["index"] != 1
               and time.monotonic() < deadline):
            pub2.publish(vdi, meta._replace(index=np.int32(1)))
            srv.pump_stream(timeout_ms=200)
        assert srv.frame["index"] == 1, srv.frame["index"]
        assert srv.newest == 1                     # head reset with it
        # same camera as before the restart: the cache is keyed by the
        # ADOPTION id, so the old incarnation's blob must not re-serve
        c.request(orbit(cam0, 0.1))
        f = _pump(srv, (c,), lambda: c.poll(timeout_ms=0))
        assert isinstance(f, ViewerFrame) and not f.stale
        assert not f.cached
        pub2.close()
    finally:
        c.close()
        srv.close()
        pub.close()


def test_server_joins_tile_stream_mid_frame(fixture):
    """The serve subscriber path end to end: a server that joins a tile
    stream mid-frame only ever adopts COMPLETE frames."""
    from scenery_insitu_tpu.core.vdi import VDI as VDI_t

    vol, cam0, spec, vdi, meta, axcam = fixture
    pub, srv = _serve_pair(fixture, publish=False)
    try:
        time.sleep(0.25)
        color = np.asarray(vdi.color)
        depth = np.asarray(vdi.depth)
        wb = color.shape[-1] // 2
        tiles = [VDI_t(color[..., i * wb:(i + 1) * wb],
                       depth[..., i * wb:(i + 1) * wb]) for i in range(2)]
        pub.publish_tile(tiles[1], meta._replace(index=np.int32(3)),
                         1, 2, wb)                    # mid-frame join
        for t in range(2):
            pub.publish_tile(tiles[t], meta._replace(index=np.int32(4)),
                             t, 2, t * wb)
        got = _pump(srv, (), lambda: srv.frame)
        assert got is not None
        assert srv.frame["index"] == 4
        assert srv.frame["vdi"].color.shape == color.shape
    finally:
        srv.close()
        pub.close()


# --------------------------------------------------- viewer reprojection


def test_reproject_identity_is_noop(fixture):
    from scenery_insitu_tpu.serve import reproject_planar

    vol, cam0, spec, vdi, meta, axcam = fixture
    img = np.asarray(render_vdi_exact(vdi, axcam, spec, cam0, W, H))
    rep = reproject_planar(img, cam0, cam0)
    np.testing.assert_allclose(rep, img, atol=1e-3)


def test_reproject_small_move_beats_stale_image(fixture):
    """The warped image approximates the true novel view better than
    re-showing the unwarped old frame — the whole point of play (c).
    Translation is the motion planar reprojection exists for (an orbit
    about the look-at target keeps the old image nearly centered, so
    the stale frame is already close there)."""
    import jax.numpy as jnp

    from scenery_insitu_tpu.serve import reproject_planar
    from scenery_insitu_tpu.utils.image import psnr

    vol, cam0, spec, vdi, meta, axcam = fixture
    shift = jnp.asarray([0.1, 0.0, 0.0], jnp.float32)
    cam1 = cam0._replace(eye=cam0.eye + shift, target=cam0.target + shift)
    old = np.asarray(render_vdi_exact(vdi, axcam, spec, cam0, W, H))
    true = np.asarray(render_vdi_exact(vdi, axcam, spec, cam1, W, H))
    warped = reproject_planar(old, cam0, cam1)
    assert np.isfinite(warped).all()
    assert psnr(warped, true) > psnr(old, true) + 3.0


def test_serve_config_validation():
    from scenery_insitu_tpu.serve import ViewerServer

    with pytest.raises(ValueError, match="buckets"):
        ServeConfig(buckets=(4, 2, 1))
    with pytest.raises(ValueError, match="default_tier"):
        ServeConfig(default_tier="fast")
    with pytest.raises(ValueError, match="max_viewers"):
        ServeConfig(max_viewers=0)
    cfg = FrameworkConfig().with_overrides("serve.max_viewers=128",
                                           "serve.default_tier=wire")
    assert cfg.serve.max_viewers == 128
    assert cfg.serve.default_tier == "wire"
    # the buckets/batch_size pair is order-INSENSITIVE through
    # with_overrides (cross-field validity is judged on the final
    # config, at the consumer) ...
    a = FrameworkConfig().with_overrides("serve.buckets=[1,2,4]",
                                         "serve.batch_size=4")
    b = FrameworkConfig().with_overrides("serve.batch_size=4",
                                         "serve.buckets=[1,2,4]")
    assert a.serve == b.serve
    # ... and an inconsistent FINAL pair is refused where it is consumed
    bad = FrameworkConfig().with_overrides("serve.buckets=[1,2,4]")
    with pytest.raises(ValueError, match="batch_size"):
        ViewerServer(bad, connect="tcp://localhost:1",
                     bind="tcp://127.0.0.1:0")
