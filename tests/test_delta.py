"""Temporal-delta VDI streams (docs/PERF.md "Temporal deltas"):
the P-frame wire codec must reconstruct BIT-EXACTLY vs the qpack8-only
publish (SKIP/residual/I-tile), recover through forced I-tiles after an
injected drop (testing/faults.ChaosSocket), and never SKIP a tile whose
codes changed; the dirty-tile re-march (CompositeConfig.temporal_reuse
= "ranges") must be bitwise vs recompute in exact mode on both the
frame and waves schedules, conservative on range-moving changes, and
ledger itself inert where no fragment can be carried."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, DeltaConfig,
                                       FrameworkConfig, SliceMarchConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.ops import delta as dl
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (
    distributed_initial_reuse_mxu, distributed_initial_threshold_mxu,
    distributed_vdi_step_mxu, distributed_vdi_step_mxu_temporal,
    shard_volume)

N = 8
ATOL = 1e-5     # separately-compiled programs carry ~1-ulp fusion noise


def _zmq_ok():
    try:
        import zmq  # noqa: F401
        return True
    except ImportError:
        return False


needs_zmq = pytest.mark.skipif(not _zmq_ok(), reason="pyzmq not installed")


# ===================================================== code-space residuals


def test_diff_apply_runs_roundtrip():
    rng = np.random.default_rng(3)
    prev = rng.integers(0, 2**31, 257, dtype=np.int64).astype(np.uint32)
    cur = prev.copy()
    for lo, hi in ((3, 9), (40, 41), (100, 160), (250, 257)):
        cur[lo:hi] = rng.integers(0, 2**31, hi - lo).astype(np.uint32)
    s, l, v = dl.diff_runs(prev, cur)
    # runs are maximal: every listed slot really changed, boundaries hold
    assert int(l.sum()) == v.size == int((prev != cur).sum())
    out = dl.apply_runs(prev, s, l, v)
    assert np.array_equal(out, cur)


def test_diff_runs_identical_and_validation():
    a = np.arange(10, dtype=np.uint16)
    s, l, v = dl.diff_runs(a, a.copy())
    assert s.size == l.size == v.size == 0
    assert np.array_equal(dl.apply_runs(a, s, l, v), a)
    with pytest.raises(ValueError, match="disagree"):
        dl.diff_runs(a, a.astype(np.uint32))
    with pytest.raises(ValueError, match="values"):
        dl.apply_runs(a, np.asarray([1], np.uint32),
                      np.asarray([3], np.uint32),
                      np.asarray([7], np.uint16))


def _codes(rng, shape=(3, 4, 6)):
    return (rng.integers(0, 2**31, shape).astype(np.uint32),
            rng.integers(0, 2**15, shape).astype(np.uint16))


def test_encoder_skip_p_i_modes():
    rng = np.random.default_rng(0)
    enc = dl.DeltaEncoder(iframe_period=100)
    c, d = _codes(rng)
    r0 = enc.encode(0, c, d, 0.0, 1.0)
    assert r0.mode == "I" and r0.reason == "first"
    # unchanged → SKIP, zero wire bytes
    r1 = enc.encode(0, c, d, 0.0, 1.0)
    assert r1.mode == "SKIP" and r1.wire_bytes == 0 \
        and r1.base_gen == r0.gen
    # one code flips → sparse P, decoder round-trips bit-exactly
    c2 = c.copy()
    c2.ravel()[5] ^= 0xFF
    r2 = enc.encode(0, c2, d, 0.0, 1.0)
    assert r2.mode == "P" and r2.wire_bytes < r2.full_bytes
    dec = dl.DeltaDecoder()
    for r in (r0, r1, r2):
        got = dec.apply(0, r.mode, r.gen, r.base_gen, r.c_payload,
                        r.d_payload, r.scale)
        assert got is not None
    cc, dd, near, far = got
    assert np.array_equal(cc, c2) and np.array_equal(dd, d)
    # a fully re-randomized tile makes the residual dense → I wins
    c3, d3 = _codes(rng)
    r3 = enc.encode(0, c3, d3, 0.0, 1.0)
    assert r3.mode == "I" and r3.reason == "dense_residual"


def test_encoder_scale_change_is_not_a_skip():
    """Equal codes under a DIFFERENT [near, far] dequantize to different
    depths — the encoder must not SKIP them."""
    rng = np.random.default_rng(1)
    enc = dl.DeltaEncoder()
    c, d = _codes(rng)
    enc.encode(0, c, d, 0.0, 1.0)
    r = enc.encode(0, c, d, 0.0, 2.0)
    assert r.mode != "SKIP"


def test_encoder_forced_iframe_period_and_reset():
    from scenery_insitu_tpu import obs

    rng = np.random.default_rng(2)
    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        enc = dl.DeltaEncoder(iframe_period=3)
        c, d = _codes(rng)
        modes = [enc.encode(0, c, d, 0.0, 1.0).mode for _ in range(7)]
        # I, SKIP, I(periodic), SKIP, SKIP→ period forces every 3rd
        assert modes[0] == "I" and modes.count("I") >= 3 \
            and "SKIP" in modes
        assert enc.stats["forced_i"] >= 2
        enc.reset()
        r = enc.encode(0, c, d, 0.0, 1.0)
        assert r.mode == "I" and r.reason == "reset"
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("iframe_forced", 0) >= 3
    assert rec.counters.get("delta_tiles_skipped", 0) >= 1
    assert rec.counters.get("delta_bytes_saved", 0) > 0


def test_encoder_never_skips_changed_codes():
    """Conservativeness property: ANY code change — one bit anywhere —
    must not SKIP, and the decoder must reconstruct it bit-exactly."""
    rng = np.random.default_rng(4)
    enc = dl.DeltaEncoder(iframe_period=10**6)
    dec = dl.DeltaDecoder()
    c, d = _codes(rng, (4, 8, 8))
    r = enc.encode(0, c, d, 0.0, 1.0)
    dec.apply(0, r.mode, r.gen, r.base_gen, r.c_payload, r.d_payload,
              r.scale)
    for _ in range(24):
        which = rng.integers(0, 2)
        c, d = c.copy(), d.copy()
        if which == 0:
            c.ravel()[rng.integers(0, c.size)] ^= np.uint32(
                1 << int(rng.integers(0, 32)))
        else:
            d.ravel()[rng.integers(0, d.size)] ^= np.uint16(
                1 << int(rng.integers(0, 16)))
        r = enc.encode(0, c, d, 0.0, 1.0)
        assert r.mode != "SKIP"
        got = dec.apply(0, r.mode, r.gen, r.base_gen, r.c_payload,
                        r.d_payload, r.scale)
        assert got is not None
        assert np.array_equal(got[0], c) and np.array_equal(got[1], d)


def test_decoder_resync_on_broken_chain():
    rng = np.random.default_rng(5)
    enc = dl.DeltaEncoder(iframe_period=10**6)
    dec = dl.DeltaDecoder()
    c, d = _codes(rng)
    r0 = enc.encode(0, c, d, 0.0, 1.0)
    dec.apply(0, r0.mode, r0.gen, r0.base_gen, r0.c_payload,
              r0.d_payload, r0.scale)
    c1 = c.copy(); c1.ravel()[0] ^= 1
    r1 = enc.encode(0, c1, d, 0.0, 1.0)              # P — "lost"
    c2 = c1.copy(); c2.ravel()[1] ^= 1
    r2 = enc.encode(0, c2, d, 0.0, 1.0)              # P on top of r1
    got = dec.apply(0, r2.mode, r2.gen, r2.base_gen, r2.c_payload,
                    r2.d_payload, r2.scale)
    assert got is None and dec.stats["resync"] == 1
    # the decoder is purely chain-driven: the "lost" record arriving
    # late (its base still matches) repairs the chain — in the live
    # protocol the subscriber's stale-seq drop refuses such replays
    # before they reach the decoder, so this is the recovery path for
    # reordering, not a replay hole
    got1 = dec.apply(0, r1.mode, r1.gen, r1.base_gen, r1.c_payload,
                     r1.d_payload, r1.scale)
    assert got1 is not None and np.array_equal(got1[0], c1)
    # and an I-tile always re-anchors regardless of chain state
    ri = dl.DeltaEncoder(iframe_period=10**6)
    rI = ri.encode(0, c2, d, 0.0, 1.0)
    assert dec.apply(0, rI.mode, rI.gen, rI.base_gen, rI.c_payload,
                     rI.d_payload, rI.scale) is not None


def test_pack_unpack_delta_blobs_roundtrip():
    from scenery_insitu_tpu.io.vdi_io import (decompress,
                                              delta_expected_bytes,
                                              pack_delta_blobs,
                                              unpack_delta_payload)

    rng = np.random.default_rng(6)
    enc = dl.DeltaEncoder(iframe_period=10**6)
    c, d = _codes(rng, (2, 5, 7))
    recs = [enc.encode(0, c, d, 0.0, 1.0)]
    recs.append(enc.encode(0, c, d, 0.0, 1.0))               # SKIP
    c2 = c.copy(); c2.ravel()[3:6] ^= 9
    recs.append(enc.encode(0, c2, d, 0.0, 1.0))              # P
    dec = dl.DeltaDecoder()
    for r in recs:
        h, cb, db = pack_delta_blobs(r, codec="zlib")
        craw = decompress(cb, "zlib") if cb else b""
        draw = decompress(db, "zlib") if db else b""
        assert (len(craw), len(draw)) == delta_expected_bytes(
            h, c.shape, d.shape)
        cp, dp = unpack_delta_payload(h, craw, draw, c.shape, d.shape)
        got = dec.apply(0, h["mode"], h["gen"], h["base"], cp, dp,
                        r.scale)
        assert got is not None
    assert np.array_equal(got[0], c2) and np.array_equal(got[1], d)


def test_modeled_delta_traffic():
    m = dl.modeled_delta_traffic(20, 720, 1280, skip_frac=0.6,
                                 p_frac=0.2, residual_frac=0.1,
                                 iframe_period=8)
    assert m["delta_bytes_per_frame"] < 0.4 * m["qpack8_bytes_per_frame"]
    full = dl.modeled_delta_traffic(20, 720, 1280, skip_frac=0.0)
    assert full["delta_bytes_per_frame"] == \
        full["qpack8_bytes_per_frame"]
    with pytest.raises(ValueError):
        dl.modeled_delta_traffic(20, 720, 1280, skip_frac=0.9,
                                 p_frac=0.2)


# ========================================================== stream plumbing


def _meta(i=0, w=24, h=16):
    return VDIMetadata.create(
        projection=np.eye(4, dtype=np.float32),
        view=np.eye(4, dtype=np.float32), volume_dims=(8, 8, 8),
        window_dims=(w, h), nw=1.0, index=i)


def _frames(seed=0, n=6, K=4, H=16, W=24):
    """A slow-evolving synthetic stream: frames 0-2 identical, then a
    localized change, then identical again."""
    rng = np.random.default_rng(seed)
    c = np.clip(rng.random((K, 4, H, W)), 0, 1).astype(np.float32)
    d = np.sort(rng.random((K, 2, H, W)).astype(np.float32), axis=1)
    out = []
    for i in range(n):
        ci, di = c.copy(), d.copy()
        if i >= 3:
            ci[:, :, :4, :4] = 0.9
        out.append(VDI(ci, di))
    return out


@needs_zmq
def test_stream_delta_bitwise_vs_plain_publish():
    """The delta stream decodes BIT-IDENTICALLY to the qpack8-only
    stream, while SKIP frames cost a small fraction of the bytes."""
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    pub_d = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                         precision="qpack8", epoch=11,
                         delta=DeltaConfig(enabled=True, iframe_period=16))
    pub_p = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                         precision="qpack8", epoch=12)
    sub_d = VDISubscriber(connect=pub_d.endpoint)
    sub_p = VDISubscriber(connect=pub_p.endpoint)
    time.sleep(0.3)
    try:
        sizes_d, sizes_p = [], []
        for i, v in enumerate(_frames()):
            m = _meta(i)
            sizes_d.append(pub_d.publish(v, m))
            sizes_p.append(pub_p.publish(v, m))
            got_d = sub_d.receive(timeout_ms=3000)
            got_p = sub_p.receive(timeout_ms=3000)
            assert got_d is not None and not hasattr(got_d, "kind")
            vd, md = got_d
            vp, mp = got_p
            assert np.array_equal(np.asarray(vd.color),
                                  np.asarray(vp.color))
            assert np.array_equal(np.asarray(vd.depth),
                                  np.asarray(vp.depth))
            assert int(np.asarray(md.index)) == i
        # frames 1, 2 are SKIPs; frame 4+ too (identical to 3)
        st = pub_d.delta_stats
        assert st["skip"] >= 3 and st["i"] >= 1
        assert sizes_d[1] < sizes_p[1] / 3
        assert sub_d._delta.stats["skip"] >= 3
    finally:
        for s in (pub_d, pub_p, sub_d, sub_p):
            s.close()


@needs_zmq
def test_stream_delta_tiles_assemble_bitwise():
    """Per-tile delta records (the PR-8 column block is the dirty unit)
    reassemble through the PR-11 FrameAssembler bit-exactly; unchanged
    tiles SKIP even while other tiles of the same frame change."""
    from scenery_insitu_tpu.runtime.streaming import (FrameAssembler,
                                                      VDIPublisher,
                                                      VDISubscriber)

    pub = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8", epoch=21,
                       delta=DeltaConfig(enabled=True, iframe_period=32))
    sub = VDISubscriber(connect=pub.endpoint)
    time.sleep(0.3)
    tiles = 4
    try:
        frames = _frames(seed=7, n=5)
        asm = FrameAssembler(window=4)
        done = {}
        for i, v in enumerate(frames):
            m = _meta(i)
            w = v.color.shape[-1]
            wb = w // tiles
            for t in range(tiles):
                pub.publish_tile(
                    VDI(v.color[..., t * wb:(t + 1) * wb],
                        v.depth[..., t * wb:(t + 1) * wb]),
                    m, t, tiles, t * wb)
            for _ in range(tiles):
                got = sub.receive_tile(timeout_ms=3000)
                assert got is not None and not hasattr(got, "kind")
                out = asm.add(*got)
                if out is not None:
                    done[int(np.asarray(out[1].index))] = out[0]
        assert sorted(done) == list(range(5))
        # bit-exact vs the qpack8 quantize→dequantize of the source
        from scenery_insitu_tpu.ops.wire import (qpack8_dequantize_np,
                                                 qpack8_quantize_np)
        for i, v in enumerate(frames):
            w = v.color.shape[-1]
            wb = w // tiles
            ref_c, ref_d = [], []
            for t in range(tiles):
                qc, qd, near, far = qpack8_quantize_np(
                    np.asarray(v.color[..., t * wb:(t + 1) * wb]),
                    np.asarray(v.depth[..., t * wb:(t + 1) * wb]))
                c, d = qpack8_dequantize_np(qc, qd, near, far)
                ref_c.append(c)
                ref_d.append(d)
            assert np.array_equal(np.asarray(done[i].color),
                                  np.concatenate(ref_c, axis=-1))
            assert np.array_equal(np.asarray(done[i].depth),
                                  np.concatenate(ref_d, axis=-1))
        # frame 3 changed only the first columns: tiles past the change
        # SKIP even though the frame as a whole changed
        st = pub.delta_stats
        assert st["skip"] >= 3 * tiles - 3
    finally:
        pub.close()
        sub.close()


@needs_zmq
def test_delta_requires_qpack8():
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher

    with pytest.raises(ValueError, match="qpack8"):
        VDIPublisher(bind="tcp://127.0.0.1:0", precision="f32",
                     delta=DeltaConfig(enabled=True))


@needs_zmq
def test_forced_i_recovery_after_injected_drop():
    """ChaosSocket drops messages on the wire; the subscriber refuses
    orphaned P/SKIP records as ``resync`` StreamDrops (ledgered
    stream.delta_resync) and recovers on the next forced I-tile — every
    frame that DOES decode is bit-exact vs the clean stream."""
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)
    from scenery_insitu_tpu.testing.faults import ChaosSocket, FaultSpec

    period = 3
    pub = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8", epoch=31,
                       delta=DeltaConfig(enabled=True,
                                         iframe_period=period))
    ref = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8", epoch=32)
    sub = VDISubscriber(connect=pub.endpoint)
    sub_ref = VDISubscriber(connect=ref.endpoint)
    time.sleep(0.3)
    pub.sock = ChaosSocket(pub.sock, FaultSpec(drop=0.35), seed=5)
    rng = np.random.default_rng(9)
    K, H, W = 3, 12, 16
    base_c = rng.random((K, 4, H, W)).astype(np.float32)
    base_d = np.sort(rng.random((K, 2, H, W)).astype(np.float32), axis=1)
    try:
        decoded, reference = {}, {}
        for i in range(14):
            c = base_c.copy()
            c[:, :, i % H, :] = (i % 5) / 5.0       # slow evolution
            v = VDI(c, base_d)
            m = _meta(i, w=W, h=H)
            pub.publish(v, m)
            ref.publish(v, m)
            got = sub.receive(timeout_ms=500)
            r = sub_ref.receive(timeout_ms=3000)
            assert r is not None
            reference[i] = r[0]
            if got is not None and not hasattr(got, "kind"):
                decoded[int(np.asarray(got[1].index))] = got[0]
        inj = pub.sock.report.injected
        assert inj.get("drop", 0) >= 1            # chaos actually fired
        assert len(decoded) >= 3                  # the stream recovered
        # a drop orphans its successors until the next I: either a
        # resync was refused or only I-frames happened to survive
        assert sub.stats["resyncs"] >= 1 or sub.stats["gaps"] >= 1
        # the frames that decoded are bit-exact — a resync wait can skip
        # frames but can never corrupt one
        for i, v in decoded.items():
            assert np.array_equal(np.asarray(v.color),
                                  np.asarray(reference[i].color))
            assert np.array_equal(np.asarray(v.depth),
                                  np.asarray(reference[i].depth))
        # recovery bound: after any miss, an I arrives within `period`
        # frames, so gaps between consecutive decoded indexes stay small
        idx = sorted(decoded)
        assert max(np.diff(idx), default=1) <= 2 * period
    finally:
        for s in (pub, ref, sub, sub_ref):
            s.close()


@needs_zmq
def test_epoch_change_resets_delta_state():
    """A restarted publisher (new epoch) must not patch residuals onto
    the old incarnation's tiles: the subscriber resets its decoder on
    the epoch change and the new stream's first I re-anchors it."""
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    v = _frames(n=1)[0]
    pub1 = VDIPublisher(bind="tcp://127.0.0.1:0", codec="zlib",
                        precision="qpack8", epoch=41,
                        delta=DeltaConfig(enabled=True))
    sub = VDISubscriber(connect=pub1.endpoint)
    time.sleep(0.3)
    try:
        pub1.publish(v, _meta(0))
        assert sub.receive(timeout_ms=3000) is not None
        assert sub._delta._state            # retained tile
        pub1.close()
        # the successor publisher (fresh epoch); the SUB socket joins
        # its endpoint — same stream identity from the subscriber's view
        pub2 = VDIPublisher(bind="tcp://127.0.0.1:0",
                            codec="zlib", precision="qpack8", epoch=42,
                            delta=DeltaConfig(enabled=True))
        sub.sock.connect(pub2.endpoint)
        time.sleep(0.4)
        pub2.publish(v, _meta(1))
        got = sub.receive(timeout_ms=3000)
        assert got is not None and not hasattr(got, "kind")
        assert sub.stats["epoch_changes"] == 1
        # state was rebuilt from the NEW stream's I-tile
        assert list(sub._delta._state.values())[0][0] == 1
        pub2.close()
    finally:
        sub.close()


# ================================================== dirty-tile re-marching


def _scene(n=N, size=32):
    rng = np.random.default_rng(0)
    field = np.zeros((size, size, size), np.float32)
    field[4:12, 8:24, 8:24] = rng.random((8, 16, 16)).astype(np.float32)
    tf = TransferFunction.ramp(0.1, 0.9, 0.8, "hot")
    cam = Camera.create((0.0, 0.4, 2.5))
    origin = jnp.asarray([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.full((3,), 2.0 / size, jnp.float32)
    return field, tf, cam, origin, spacing


def _spec(cam, shape, scale=1.0):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, shape, SliceMarchConfig(scale=scale),
                            multiple_of=2 * N)


@pytest.mark.parametrize("schedule", ["frame", "waves"])
def test_reuse_exact_mode_bitwise(schedule):
    """range_tol=0 + static camera + static field: frame 2 skips every
    march and is BITWISE equal to frame 1 AND to the reuse-off step —
    on both schedules."""
    mesh = make_mesh(N)
    field, tf, cam, origin, spacing = _scene()
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram")
    spec = _spec(cam, field.shape)
    kw = dict(schedule=schedule, wave_tiles=2) if schedule == "waves" \
        else {}
    cc_on = CompositeConfig(max_output_supersegments=6,
                            temporal_reuse="ranges", **kw)
    cc_off = CompositeConfig(max_output_supersegments=6, **kw)
    step_on = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc_on)
    step_off = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc_off)
    rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg, cc_on)
    f = shard_volume(jnp.asarray(field), mesh)
    ref, _ = step_off(f, origin, spacing, cam)
    ru = rseed(f, origin, spacing, cam)
    assert not np.asarray(ru.valid).any()
    (v1, m1), ru1 = step_on(f, origin, spacing, cam, ru)
    assert np.asarray(ru1.dirty).all()          # first frame marches
    (v2, m2), ru2 = step_on(f, origin, spacing, cam, ru1)
    assert not np.asarray(ru2.dirty).any()      # second frame skips
    assert np.array_equal(np.asarray(v2.color), np.asarray(v1.color))
    assert np.array_equal(np.asarray(v2.depth), np.asarray(v1.depth))
    # reuse-on equals reuse-off bitwise (the cond's march branch is the
    # same computation; holds on this backend — the waves/frame cross-
    # schedule comparison keeps the usual 1e-5 fusion gate elsewhere)
    assert np.array_equal(np.asarray(v1.color), np.asarray(ref.color))
    assert np.array_equal(np.asarray(v1.depth), np.asarray(ref.depth))


def test_reuse_parity_across_schedules():
    """Exact-mode reuse output on the waves schedule matches the frame
    schedule within the standard cross-schedule fusion gate."""
    mesh = make_mesh(N)
    field, tf, cam, origin, spacing = _scene()
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram")
    spec = _spec(cam, field.shape)
    outs = {}
    for schedule in ("frame", "waves"):
        kw = dict(schedule=schedule, wave_tiles=2) \
            if schedule == "waves" else {}
        cc = CompositeConfig(max_output_supersegments=6,
                             temporal_reuse="ranges", **kw)
        step = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc)
        rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg,
                                              cc)
        f = shard_volume(jnp.asarray(field), mesh)
        ru = rseed(f, origin, spacing, cam)
        (v1, _), ru1 = step(f, origin, spacing, cam, ru)
        (v2, _), _ = step(f, origin, spacing, cam, ru1)
        outs[schedule] = v2
    np.testing.assert_allclose(np.asarray(outs["frame"].color),
                               np.asarray(outs["waves"].color),
                               atol=ATOL, rtol=0)


def test_reuse_dirty_conservative_on_range_motion():
    """Changed brick ⇒ never SKIP: a value pushed OUTSIDE its cell's
    retained [lo, hi] must dirty exactly the owning rank, and the
    output must equal the reuse-off recompute."""
    mesh = make_mesh(N)
    field, tf, cam, origin, spacing = _scene()
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram")
    spec = _spec(cam, field.shape)
    cc = CompositeConfig(max_output_supersegments=6,
                         temporal_reuse="ranges")
    step = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc)
    step_off = distributed_vdi_step_mxu(
        mesh, tf, spec, vdi_cfg, CompositeConfig(
            max_output_supersegments=6))
    rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg, cc)
    f = shard_volume(jnp.asarray(field), mesh)
    ru = rseed(f, origin, spacing, cam)
    (_, _), ru = step(f, origin, spacing, cam, ru)
    # perturb one voxel per target rank ABOVE the global max — the
    # containing cell's hi must move, so the rank must re-march
    for z, rank in ((5, 1), (21, 5), (30, 7)):
        f2 = field.copy()
        f2[z, 16, 16] = 2.0
        fd = shard_volume(jnp.asarray(f2), mesh)
        (v, _), ru = step(fd, origin, spacing, cam, ru)
        d = np.asarray(ru.dirty)
        assert d[rank] == 1, (z, rank, d)
        ref, _ = step_off(fd, origin, spacing, cam)
        assert np.array_equal(np.asarray(v.color), np.asarray(ref.color))
        field = f2


def test_reuse_camera_move_dirties_every_rank():
    mesh = make_mesh(N)
    field, tf, cam, origin, spacing = _scene()
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram")
    spec = _spec(cam, field.shape)
    cc = CompositeConfig(max_output_supersegments=6,
                         temporal_reuse="ranges")
    step = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc)
    rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg, cc)
    f = shard_volume(jnp.asarray(field), mesh)
    (_, _), ru = step(f, origin, spacing, cam,
                      rseed(f, origin, spacing, cam))
    cam2 = Camera.create((0.05, 0.4, 2.5))
    (_, _), ru2 = step(f, origin, spacing, cam2, ru)
    assert np.asarray(ru2.dirty).all()


def test_reuse_range_tol_hysteresis():
    """Sub-tolerance range drift keeps skipping, accumulates against
    the last MARCHED signature, and re-marches once the accumulated
    drift crosses range_tol."""
    mesh = make_mesh(N)
    field, tf, cam, origin, spacing = _scene()
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram")
    spec = _spec(cam, field.shape)
    cc = CompositeConfig(max_output_supersegments=6,
                         temporal_reuse="ranges")
    step = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg, cc,
                                    reuse_tol=0.3)
    rseed = distributed_initial_reuse_mxu(mesh, tf, spec, vdi_cfg, cc)
    f0 = field.copy()
    f0[20, 16, 16] = 1.2            # rank 5's cell hi anchor
    f = shard_volume(jnp.asarray(f0), mesh)
    (_, _), ru = step(f, origin, spacing, cam,
                      rseed(f, origin, spacing, cam))
    # +0.2 < tol: clean; the signature stays anchored at the marched
    # frame, so another +0.2 (total 0.4 > tol) re-marches
    for bump, want_dirty in ((0.2, 0), (0.4, 1)):
        f2 = f0.copy()
        f2[20, 16, 16] = 1.2 + bump
        (_, _), ru = step(shard_volume(jnp.asarray(f2), mesh), origin,
                          spacing, cam, ru)
        assert np.asarray(ru.dirty)[5] == want_dirty, bump


def test_reuse_inert_ledger_on_unsupported_builders():
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step, distributed_vdi_step)

    mesh = make_mesh(N)
    _, tf, cam, origin, spacing = _scene()
    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        distributed_vdi_step(mesh, tf, 32, 32, VDIConfig(
            max_supersegments=4), CompositeConfig(
            temporal_reuse="ranges"))
        distributed_plain_step(mesh, tf, 32, 32,
                               temporal_reuse="ranges")
    finally:
        obs.set_recorder(prev)
    rows = [e for e in obs.ledger() if e["component"] == "delta.reuse"]
    assert rows and rows[0]["from"] == "ranges"


class _FrozenSim:
    """A static volume sim: the slow-evolving limit — every frame after
    the first must skip every rank."""

    kind = "frozen"

    def __init__(self, field):
        self._f = jnp.asarray(field)

    def advance(self, n: int) -> None:
        pass

    @property
    def field(self):
        return self._f


def test_session_reuse_counters_and_bitwise_frames(tmp_path):
    """A traced session with temporal_reuse="ranges" on a static scene:
    delta_march_skipped counts every post-first-frame tile, the dirty
    histogram event fires, and the fetched frames are bitwise equal."""
    from scenery_insitu_tpu.runtime.session import InSituSession

    field, tf, cam, origin, spacing = _scene()
    cfg = FrameworkConfig().with_overrides(
        "composite.temporal_reuse=ranges",
        "composite.max_output_supersegments=6",
        "vdi.max_supersegments=6",
        "vdi.adaptive_mode=histogram",
        "slicer.engine=mxu",         # CPU 'auto' resolves to gather
        "slicer.scale=1.0",
        "obs.enabled=true",
        "sim.grid=[32,32,32]")
    frames = {}
    sess = InSituSession(cfg, sim=_FrozenSim(field), tf=tf,
                         camera=cam,
                         sinks=[lambda i, p: frames.update(
                             {i: (p["vdi_color"], p["vdi_depth"])})])
    sess.run(4)
    assert sorted(frames) == [0, 1, 2, 3]
    for i in (1, 2, 3):
        assert np.array_equal(frames[i][0], frames[0][0])
        assert np.array_equal(frames[i][1], frames[0][1])
    # frames 1..3 skipped all 8 ranks' marches (frame 0 marched; its
    # decision is read one frame later, so >= 2 frames' worth count)
    assert sess.obs.counters.get("delta_march_skipped", 0) >= 2 * N
    evs = [e for e in sess.obs.events
           if e.get("name") == "delta_dirty_tiles"]
    assert evs and evs[-1]["attrs"]["skipped_tiles"] == N
    assert sess.obs.counters.get("reuse_steps_built", 0) >= 1


def test_config_validation():
    with pytest.raises(ValueError, match="temporal_reuse"):
        CompositeConfig(temporal_reuse="bogus")
    with pytest.raises(ValueError, match="iframe_period"):
        DeltaConfig(iframe_period=0)
    with pytest.raises(ValueError, match="range_tol"):
        DeltaConfig(range_tol=-1.0)
    with pytest.raises(ValueError, match="iframe_period"):
        dl.DeltaEncoder(iframe_period=0)
