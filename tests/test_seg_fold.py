"""Parity tests for the segmented-scan write fold (ops/seg_fold.py): the
parallel formulation must produce the same supersegments as sequential
``ss.push`` calls — same break predicates, same merge-overflow, same
depths — differing only in fp association of the within-segment sums."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.ops import seg_fold as sf
from scenery_insitu_tpu.ops import supersegments as ss


def _stream(key, n, h, w, empty_frac=0.4, dup_frac=0.3):
    """Depth-ordered stream with empty runs AND near-duplicate colors so
    all three paths fire: start-on-gap, break-on-diff, accumulate."""
    kr, ka, kd, ku = jax.random.split(key, 4)
    rgb = jax.random.uniform(kr, (n, 3, h, w))
    # near-duplicates: copy the previous item's color for ~dup_frac items
    # so diff <= thr accumulation paths are exercised
    dup = jax.random.uniform(ku, (n, 1, h, w)) < dup_frac
    rgb = jnp.where(dup & (jnp.arange(n)[:, None, None, None] > 0),
                    jnp.roll(rgb, 1, axis=0), rgb)
    alpha = jax.random.uniform(ka, (n, 1, h, w), minval=0.05, maxval=0.9)
    gate = jax.random.uniform(kd, (n, 1, h, w)) > empty_frac
    alpha = alpha * gate
    rgba = jnp.concatenate([rgb * alpha, alpha], axis=1)
    t0 = jnp.cumsum(jnp.full((n, h, w), 0.1), axis=0)
    return rgba, t0, t0 + 0.1


def _ref(rgba, t0, t1, thr, max_k):
    st = ss.init_state(max_k, rgba.shape[2], rgba.shape[3])
    cst = ss.init_count(rgba.shape[2], rgba.shape[3])
    for i in range(rgba.shape[0]):
        st = ss.push(st, max_k, thr, rgba[i], t0[i], t1[i])
        cst = ss.push_count(cst, thr, rgba[i])
    c, d = ss.finalize(st)
    return c, d, cst.count


def _seg(rgba, t0, t1, thr, max_k, chunks):
    st = sf.init_seg_state(max_k, rgba.shape[2], rgba.shape[3])
    lo = 0
    for c in chunks:
        st = sf.seg_fold_chunk(st, rgba[lo:lo + c], t0[lo:lo + c],
                               t1[lo:lo + c], thr, max_k=max_k)
        lo += c
    assert lo == rgba.shape[0]
    c_, d_ = sf.seg_finalize(st)
    return c_, d_, st.cnt


@pytest.mark.parametrize("chunks", [(12,), (7, 5), (1,) * 12, (3, 3, 3, 3)])
def test_matches_sequential_push(chunks):
    h, w = 16, 40
    max_k = 5
    rgba, t0, t1 = _stream(jax.random.PRNGKey(0), 12, h, w)
    thr = jnp.full((h, w), 0.35, jnp.float32)
    c_ref, d_ref, n_ref = _ref(rgba, t0, t1, thr, max_k)
    c_s, d_s, n_s = _seg(rgba, t0, t1, thr, max_k, chunks)
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)


def test_merge_overflow_parity():
    """Threshold 0 forces a break at every color change -> far more true
    segments than slots; the overflow tail must merge identically."""
    h, w = 8, 24
    max_k = 3
    rgba, t0, t1 = _stream(jax.random.PRNGKey(1), 20, h, w,
                           empty_frac=0.25, dup_frac=0.0)
    thr = jnp.zeros((h, w), jnp.float32)
    c_ref, d_ref, n_ref = _ref(rgba, t0, t1, thr, max_k)
    c_s, d_s, n_s = _seg(rgba, t0, t1, thr, max_k, (8, 12))
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)


def test_all_empty_and_leading_empty_chunks():
    h, w = 8, 16
    max_k = 4
    rgba, t0, t1 = _stream(jax.random.PRNGKey(2), 10, h, w)
    # force chunks 0-1 fully empty (the occupancy-skip path feeds exactly
    # this: explicit empty samples that must close open segments)
    rgba = rgba.at[:4].set(0.0)
    thr = jnp.full((h, w), 0.3, jnp.float32)
    c_ref, d_ref, n_ref = _ref(rgba, t0, t1, thr, max_k)
    c_s, d_s, n_s = _seg(rgba, t0, t1, thr, max_k, (2, 2, 6))
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)


def test_gap_splits_segment_across_chunk_boundary():
    """A segment open at a chunk boundary must continue (not restart):
    composition across the boundary uses the carried out_alpha."""
    h, w = 4, 8
    max_k = 4
    n = 6
    # constant color, constant alpha, no empties: ONE segment
    rgba = jnp.broadcast_to(
        jnp.asarray([0.2, 0.1, 0.05, 0.5], jnp.float32)[None, :, None, None],
        (n, 4, h, w))
    t0 = jnp.cumsum(jnp.full((n, h, w), 0.1), axis=0)
    thr = jnp.full((h, w), 0.5, jnp.float32)
    c_ref, d_ref, n_ref = _ref(rgba, t0, t0 + 0.1, thr, max_k)
    c_s, d_s, n_s = _seg(rgba, t0, t0 + 0.1, thr, max_k, (2, 2, 2))
    assert int(n_s.max()) == 1
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-6, atol=1e-6)


def test_pallas_seg_matches_xla_seg():
    """The VMEM twin (ops/pallas_seg.py, interpret mode off-TPU) must
    reproduce the XLA seg fold including carried state across chunks."""
    from scenery_insitu_tpu.ops import pallas_seg as psg

    h, w = 16, 40                          # w deliberately NOT 128-aligned
    max_k = 5
    rgba, t0, t1 = _stream(jax.random.PRNGKey(4), 12, h, w)
    thr = jnp.full((h, w), 0.35, jnp.float32)
    st_x = sf.init_seg_state(max_k, h, w)
    st_p = sf.init_seg_state(max_k, h, w)
    for lo, n in ((0, 7), (7, 5)):
        st_x = sf.seg_fold_chunk(st_x, rgba[lo:lo + n], t0[lo:lo + n],
                                 t1[lo:lo + n], thr, max_k=max_k)
        st_p = psg.seg_fold_chunk(st_p, rgba[lo:lo + n], t0[lo:lo + n],
                                  t1[lo:lo + n], thr, max_k=max_k)
    np.testing.assert_array_equal(np.asarray(st_p.cnt), np.asarray(st_x.cnt))
    for a, b, name in zip(sf.seg_finalize(st_x), sf.seg_finalize(st_p),
                          ("color", "depth")):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-6, atol=1e-6, err_msg=name)


@pytest.mark.parametrize("fold", ["seg", "pallas_seg", "pallas_fused",
                                  "fused_stream"])
def test_whole_march_parity(fold):
    """generate_vdi_mxu + temporal: the seg folds must reproduce the
    sequential-machine fold end to end, including the temporal threshold
    controller's feedback (integer counts must agree exactly)."""
    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer

    vol = procedural_volume(40, kind="blobs", seed=7)
    tf = for_dataset("procedural")
    cam = Camera.create((0.25, 0.5, 2.6), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    cfg = VDIConfig(max_supersegments=6, adaptive_mode="histogram",
                    histogram_bins=8)
    spec_x = slicer.make_spec(cam, vol.data.shape,
                              SliceMarchConfig(matmul_dtype="f32",
                                               scale=1.5, fold="xla"))
    spec_s = slicer.make_spec(cam, vol.data.shape,
                              SliceMarchConfig(matmul_dtype="f32",
                                               scale=1.5, fold=fold))
    vdi_x, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_x, cfg)
    vdi_s, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec_s, cfg)
    np.testing.assert_allclose(np.asarray(vdi_s.color),
                               np.asarray(vdi_x.color),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(vdi_s.depth),
                               np.asarray(vdi_x.depth),
                               rtol=1e-5, atol=1e-5)

    cfg_t = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    thr_x = slicer.initial_threshold(vol, tf, cam, spec_x, cfg_t)
    thr_s = slicer.initial_threshold(vol, tf, cam, spec_s, cfg_t)
    for _ in range(2):
        vdi_x, _, _, thr_x = slicer.generate_vdi_mxu_temporal(
            vol, tf, cam, spec_x, thr_x, cfg_t)
        vdi_s, _, _, thr_s = slicer.generate_vdi_mxu_temporal(
            vol, tf, cam, spec_s, thr_s, cfg_t)
        np.testing.assert_allclose(np.asarray(vdi_s.color),
                                   np.asarray(vdi_x.color),
                                   rtol=1e-5, atol=1e-5)
        # thresholds bisect from identical integer counts -> exact
        np.testing.assert_allclose(np.asarray(thr_s.thr),
                                   np.asarray(thr_x.thr),
                                   rtol=1e-6, atol=1e-6)


def test_scalar_threshold_and_jit():
    h, w = 8, 16
    max_k = 4
    rgba, t0, t1 = _stream(jax.random.PRNGKey(3), 8, h, w)
    c_ref, d_ref, n_ref = _ref(rgba, t0, t1,
                               jnp.full((h, w), 0.4, jnp.float32), max_k)

    @jax.jit
    def run(rgba, t0, t1):
        st = sf.init_seg_state(max_k, h, w)
        st = sf.seg_fold_chunk(st, rgba, t0, t1, 0.4, max_k=max_k)
        c, d = sf.seg_finalize(st)
        return c, d, st.cnt

    c_s, d_s, n_s = run(rgba, t0, t1)
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_with_vtiles_parity():
    """fold='pallas_fused' composed with in-plane occupancy tiles: gated
    row blocks emit the raw-mode -1 sentinel, which the fused kernel must
    treat exactly like the zero-alpha samples the ungated march feeds."""
    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import Volume
    from scenery_insitu_tpu.ops import slicer

    data = np.zeros((32, 32, 32), np.float32)
    data[4:12, 5:14, 6:16] = 0.8           # sparse corner blob
    vol = Volume.centered(jnp.asarray(data), extent=2.0)
    tf = for_dataset("procedural")
    cam = Camera.create((0.2, 0.3, 2.8), fov_y_deg=45.0, near=0.3,
                        far=10.0)
    cfg = VDIConfig(max_supersegments=5, adaptive=False, threshold=0.3)

    def gen(fold, vt):
        spec = slicer.make_spec(
            cam, vol.data.shape,
            SliceMarchConfig(matmul_dtype="f32", scale=1.0, fold=fold,
                             occupancy_vtiles=vt))
        vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, cfg)
        return np.asarray(vdi.color), np.asarray(vdi.depth)

    c_ref, d_ref = gen("xla", 0)
    c_f, d_f = gen("pallas_fused", 4)
    np.testing.assert_allclose(c_f, c_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(d_f, d_ref, rtol=1e-5, atol=1e-5)


def test_k1_everything_merges():
    """max_k=1: the machine's merge-overflow degenerates to 'one slot
    absorbs the whole stream'; the seg formulation must reproduce it
    (single reset at the first non-empty item, no resets after)."""
    h, w = 8, 16
    rgba, t0, t1 = _stream(jax.random.PRNGKey(5), 14, h, w)
    thr = jnp.zeros((h, w), jnp.float32)   # break at every color change
    c_ref, d_ref, n_ref = _ref(rgba, t0, t1, thr, 1)
    c_s, d_s, n_s = _seg(rgba, t0, t1, thr, 1, (7, 7))
    np.testing.assert_array_equal(np.asarray(n_s), np.asarray(n_ref))
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)


def test_compact_depth_equals_td_planes():
    """fold_chunk_packed's compact form (sk ratios + length, depths
    computed in-kernel) must equal the td-plane form when the planes are
    the same outer product the march materializes (t = sk * length) —
    the production path's 3.4 GB/march stream delete must be a pure
    traffic change, bit-for-bit."""
    import numpy as np
    from scenery_insitu_tpu.ops import pallas_seg as psg

    rng = np.random.default_rng(11)
    c, k, h, w = 6, 4, 8, 256
    rgba = jnp.asarray(rng.random((c, 4, h, w), dtype=np.float32))
    # sprinkle empties so segmentation paths (starts/gaps) are exercised
    rgba = rgba.at[:, 3].set(
        jnp.where(jnp.asarray(rng.random((c, h, w))) < 0.3, 0.0,
                  rgba[:, 3]))
    sk = jnp.asarray(np.sort(rng.random(c).astype(np.float32)) + 0.5)
    ds = jnp.float32(0.03)
    length = jnp.asarray(1.0 + rng.random((h, w), dtype=np.float32))
    thr = jnp.full((h, w), 0.15, jnp.float32)

    t0 = sk[:, None, None] * length[None]
    t1 = (sk + ds)[:, None, None] * length[None]

    pk0 = psg.init_seg_packed(k, h, w)
    ref = psg.fold_chunk_packed(pk0, rgba, t0, t1, thr, max_k=k,
                                interpret=True)
    got = psg.fold_chunk_packed(pk0, rgba, threshold=thr, max_k=k,
                                sk0=sk, sk1=sk + ds, length=length,
                                interpret=True)
    for a, b, name in zip(ref, got, ("color", "depth", "small")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6, err_msg=name)
