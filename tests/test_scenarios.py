"""The scenario zoo (scenery_insitu_tpu/scenarios; docs/SCENARIOS.md):
registry mechanics, the steered end-to-end smokes that promote the
vortex / hybrid / Lennard-Jones sims from orphan demos to tier-1
workloads, and the steered-TF recompile-or-reuse contract (a tf update
cycling through k distinct looks pays k compiles total)."""

import jax
import numpy as np
import pytest

from scenery_insitu_tpu import obs, scenarios

TINY = ("sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "render.width=32", "render.height=32")


def test_registry_names_and_lookup():
    names = scenarios.names()
    for expected in ("gray_scott", "vortex", "hybrid", "lennard_jones"):
        assert expected in names
    with pytest.raises(ValueError, match="unknown scenario"):
        scenarios.get("nope")
    with pytest.raises(ValueError, match="already registered"):
        scenarios.register(scenarios.get("vortex"))


def test_make_config_applies_overrides():
    cfg = scenarios.make_config("vortex",
                                extra_overrides=("sim.grid=[8,8,8]",))
    assert cfg.sim.kind == "vortex"
    assert cfg.runtime.dataset == "vortex"
    assert cfg.sim.grid == (8, 8, 8)
    cfg = scenarios.make_config("hybrid")
    assert cfg.sim.kind == "hybrid"


def test_tf_schedule_and_dolly_validation():
    with pytest.raises(ValueError):
        scenarios.tf_schedule([], period=3)
    msgs = [{"type": "tf", "points": [[0.0, 0.0], [1.0, 0.5]],
             "colormap": "hot"}]
    hook = scenarios.tf_schedule(msgs, period=2)

    class _S:
        pass

    assert hook(_S(), 0) is None          # frame 0 keeps the session TF
    assert hook(_S(), 1) is None
    assert hook(_S(), 2) is msgs[0]


def test_vortex_scenario_steered_end_to_end():
    """Vortex runs through the full session with its TF schedule firing
    over the steering consumer — a registered workload, not a demo."""
    scn = scenarios.get("vortex")
    sess = scenarios.make_session(
        "vortex", extra_overrides=TINY + ("slicer.engine=gather",
                                          "obs.enabled=true"))
    payload = scenarios.run_steered(sess, scn, 7)
    assert {"vdi_color", "vdi_depth", "meta"} <= set(payload)
    assert payload["frame"] == 6
    assert np.isfinite(payload["vdi_color"]).all()
    # the period-3 schedule fired at frames 3 and 6
    assert sess.obs.counters.get("tf_updates", 0) == 2


def test_tf_update_recompile_or_reuse():
    """Cycling 2 TFs over 13 frames: 4 updates, but only 2 distinct
    looks compile — the later updates restore cached steps
    (tf_steps_reused), and the first-contact recompiles land on the
    scenario.tf_update ledger."""
    obs.clear_ledger()
    scn = scenarios.get("vortex")
    sess = scenarios.make_session(
        "vortex", extra_overrides=TINY + ("slicer.engine=gather",
                                          "obs.enabled=true"))
    scenarios.run_steered(sess, scn, 13)
    assert sess.obs.counters.get("tf_updates", 0) == 4
    assert sess.obs.counters.get("tf_steps_reused", 0) == 2
    # initial build + one per DISTINCT steered TF
    assert sess.obs.counters.get("build_steps", 0) == 3
    assert any(e["component"] == "scenario.tf_update"
               for e in obs.ledger())
    reused = [e for e in sess.obs.events if e.get("name") == "tf_update"
              and e["attrs"].get("reused")]
    assert len(reused) == 2


def test_hybrid_scenario_multi_volume_smoke():
    """The multi-volume scene: vortex grid field + sort-first tracer
    splats composited in ONE frame (ops/hybrid.py) through the session,
    by name."""
    scn = scenarios.get("hybrid")
    sess = scenarios.make_session(
        "hybrid", extra_overrides=TINY + ("sim.num_particles=64",))
    assert sess.mode == "hybrid"
    payload = scenarios.run_steered(sess, scn, 2)
    img = payload["image"]
    assert img.shape == (4, 32, 32)
    assert np.isfinite(img).all()
    assert float(np.abs(img).sum()) > 0.0


def test_lennard_jones_scenario_camera_steering():
    """The MD particle scenario renders sort-first splats and its
    camera-dolly steering hook actually moves the camera through the
    protocol path."""
    scn = scenarios.get("lennard_jones")
    sess = scenarios.make_session(
        "lennard_jones",
        extra_overrides=("sim.num_particles=256", "render.width=32",
                         "render.height=32", "sim.steps_per_frame=1"))
    assert sess.mode == "particles"
    eye0 = np.asarray(sess.camera.eye).copy()
    payload = scenarios.run_steered(sess, scn, 3)
    assert {"image", "depth"} <= set(payload)
    assert not np.allclose(np.asarray(sess.camera.eye), eye0)


def test_run_one_call():
    payload = scenarios.run(
        "gray_scott", 2,
        extra_overrides=TINY + ("slicer.engine=gather",))
    assert "vdi_color" in payload


def test_steer_session_camera_message():
    from scenery_insitu_tpu.runtime.session import steer_session

    sess = scenarios.make_session(
        "gray_scott", extra_overrides=TINY + ("slicer.engine=gather",))
    steer_session(sess, {"type": "camera", "eye": [0.5, 0.5, 2.0]})
    np.testing.assert_allclose(np.asarray(sess.camera.eye),
                               [0.5, 0.5, 2.0])
    seen = []
    sess.on_steer.append(lambda m: seen.append(m))
    steer_session(sess, {"type": "custom", "x": 1})
    assert seen and seen[0]["x"] == 1
    jax.block_until_ready(sess.render_frame())
