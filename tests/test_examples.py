"""Smoke tests for the examples/ CLIs (the reference's app-entry-point
roles) — run as real subprocesses on tiny sizes so the documented
commands keep working."""

import glob
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EX = os.path.join(_ROOT, "examples")


def _run(script, *args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env["_EX_CHILD"] = "1"      # examples pin the cpu backend themselves
    return subprocess.run(
        [sys.executable, os.path.join(_EX, script), *args],
        env=env, timeout=timeout, capture_output=True, text=True)


def test_insitu_example_with_checkpoint_and_resume(tmp_path):
    out = str(tmp_path / "out")
    p = _run("insitu_grayscott.py", "--frames", "4", "--grid", "24",
             "--out", out, "--checkpoint-every", "2", "--orbit", "0.02")
    assert p.returncode == 0, p.stderr[-800:]
    assert len(glob.glob(os.path.join(out, "frame*.png"))) == 4
    ckpts = sorted(glob.glob(os.path.join(out, "ckpt_*.npz")))
    assert ckpts

    p = _run("insitu_grayscott.py", "--frames", "2", "--grid", "24",
             "--out", out, "--resume", ckpts[-1])
    assert p.returncode == 0, p.stderr[-800:]
    assert "resumed at frame" in p.stdout


def test_volume_from_file_example(tmp_path):
    out = str(tmp_path / "views")
    p = _run("volume_from_file.py", "--out", out, "--views", "2",
             "--width", "48", "--height", "48", "--store-vdis")
    assert p.returncode == 0, p.stderr[-800:]
    assert len(glob.glob(os.path.join(out, "view*.png"))) == 2
    assert len(glob.glob(os.path.join(out, "vdi*.npz"))) == 2


def test_producer_client_pair(tmp_path):
    pytest.importorskip("zmq")
    out = str(tmp_path / "client")
    port = 16655 + os.getpid() % 1000
    client = subprocess.Popen(
        [sys.executable, "-u", os.path.join(_EX, "vdi_client.py"),
         "--connect", f"tcp://localhost:{port}", "--frames", "1",
         "--timeout", "240",        # cold producer compiles first
         "--width", "48", "--height", "48", "--out", out],
        env={**os.environ, "PYTHONPATH": _ROOT, "JAX_PLATFORMS": "cpu",
             "_EX_CHILD": "1"},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        import select
        import time
        # readiness handshake, not a fixed sleep: the client prints
        # "listening" AFTER its (jax-import-heavy) startup subscribes —
        # under machine load that startup can far outlive any sleep, and
        # the PUB's frames would all fire before the SUB joins. Tolerate
        # import-time warning lines on the merged pipe, and bound the
        # wait so a wedged client cannot hang the suite.
        deadline = time.time() + 180
        seen = []
        while time.time() < deadline:
            r, _, _ = select.select([client.stdout], [], [], 5)
            if not r:
                continue
            line = client.stdout.readline()
            if not line:                   # EOF: client died during start
                break
            seen.append(line)
            if "listening" in line:
                break
        assert any("listening" in ln for ln in seen), \
            f"client never became ready; output so far: {seen[-5:]}"
        time.sleep(1.0)        # ZMQ slow-joiner: let the join propagate
        p = _run("volume_from_file.py", "--out", str(tmp_path / "v"),
                 "--views", "3", "--width", "32", "--height", "32",
                 "--publish", f"tcp://*:{port}")
        assert p.returncode == 0, p.stderr[-800:]
        client.wait(timeout=300)
        assert client.returncode == 0, client.stdout.read()[-800:]
        assert glob.glob(os.path.join(out, "novel*.png"))
    finally:
        if client.poll() is None:
            client.kill()
