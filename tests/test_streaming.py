"""Streaming + steering tests (SURVEY.md §7 step 10b): ZMQ VDI pub/sub
round-trip, steering message application, relay fan-out, video sink."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("zmq")

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.runtime.streaming import (SteeringEndpoint,
                                                  SteeringPublisher,
                                                  SteeringRelay,
                                                  VDIPublisher, VDISubscriber,
                                                  apply_steering,
                                                  make_camera_message,
                                                  video_sink)

K, H, W = 4, 12, 16


def _vdi_meta():
    rng = np.random.default_rng(0)
    color = rng.random((K, 4, H, W)).astype(np.float32)
    depth = rng.random((K, 2, H, W)).astype(np.float32)
    meta = VDIMetadata.create(np.eye(4), np.eye(4), volume_dims=(8, 8, 8),
                              window_dims=(W, H), nw=0.1, index=7)
    return VDI(jnp.asarray(color), jnp.asarray(depth)), meta


def _sync_pubsub(pub_sock, sub):
    """PUB/SUB needs a beat for the subscription to propagate."""
    time.sleep(0.2)


def test_vdi_pubsub_roundtrip():
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zstd")
    sub = VDISubscriber(pub.endpoint)
    try:
        _sync_pubsub(pub, sub)
        vdi, meta = _vdi_meta()
        nbytes = pub.publish(vdi, meta)
        assert nbytes > 0
        got = sub.receive(timeout_ms=5000)
        assert got is not None
        rvdi, rmeta = got
        np.testing.assert_array_equal(np.asarray(vdi.color), rvdi.color)
        np.testing.assert_array_equal(np.asarray(vdi.depth), rvdi.depth)
        assert int(rmeta.index) == 7
        assert tuple(np.asarray(rmeta.window_dims)) == (W, H)
    finally:
        pub.close()
        sub.close()


def test_subscriber_timeout_returns_none():
    pub = VDIPublisher("tcp://127.0.0.1:0")
    sub = VDISubscriber(pub.endpoint)
    try:
        assert sub.receive(timeout_ms=50) is None
    finally:
        pub.close()
        sub.close()


def test_apply_steering_camera():
    cam = Camera.create((0.0, 0.0, 5.0))
    msg = make_camera_message(Camera.create((1.0, 2.0, 3.0),
                                            target=(0.0, 1.0, 0.0),
                                            fov_y_deg=40.0))
    cam2, other = apply_steering(cam, msg)
    assert other == {}
    np.testing.assert_allclose(np.asarray(cam2.eye), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(np.asarray(cam2.target), [0.0, 1.0, 0.0])
    assert abs(float(cam2.fov_y) - np.deg2rad(40.0)) < 1e-6


def test_apply_steering_passthrough():
    cam = Camera.create((0.0, 0.0, 5.0))
    cam2, other = apply_steering(cam, {"type": "record", "on": True})
    assert other == {"record": {"type": "record", "on": True}}
    assert cam2 is cam


def test_steering_endpoint_and_relay():
    relay = SteeringRelay("tcp://127.0.0.1:0", "tcp://127.0.0.1:0")
    viewer = SteeringPublisher(relay.upstream)
    renderer = SteeringEndpoint(relay.downstream, bind=False)
    try:
        time.sleep(0.3)
        deadline = time.time() + 5.0
        kinds = set()
        # PUB/SUB joins are asynchronous on both hops; keep resending until
        # both message types make it through the relay
        while time.time() < deadline and kinds != {"camera", "record"}:
            viewer.send(make_camera_message(Camera.create((9.0, 0.0, 0.0))))
            viewer.send({"type": "record", "on": True})
            time.sleep(0.02)
            relay.pump()
            kinds |= {g["type"] for g in renderer.drain()}
        assert kinds == {"camera", "record"}
    finally:
        viewer.close()
        renderer.close()
        relay.close()


def test_session_applies_steering(tmp_path):
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=16",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
        "composite.max_output_supersegments=4", "composite.adaptive_iters=1")
    sess = InSituSession(cfg, mesh=make_mesh(2))
    ep = SteeringEndpoint("tcp://127.0.0.1:0")
    viewer = SteeringPublisher(ep.endpoint)
    sess.steering = ep
    seen = []
    sess.on_steer.append(seen.append)
    try:
        time.sleep(0.3)
        deadline = time.time() + 10.0
        while time.time() < deadline and float(sess.camera.eye[2]) != 9.0:
            # resend until the SUB join completes (PUB drops until then)
            viewer.send(make_camera_message(Camera.create((0.0, 0.0, 9.0))))
            viewer.send({"type": "record", "on": True})
            time.sleep(0.05)
            sess.run(1)
        assert float(sess.camera.eye[2]) == 9.0
        assert any(m.get("type") == "record" for m in seen)
    finally:
        viewer.close()
        ep.close()


def test_session_stream_to_novel_view_client(tmp_path):
    """The full streamed-VDI client story: in-situ session publishes
    composited VDIs; a client receives and renders a novel view
    (≅ transmit + remote VDI rendering, VolumeFromFileExample.kt:996-1046
    + EfficientVDIRaycast)."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.core.camera import orbit
    from scenery_insitu_tpu.ops.vdi_render import render_vdi
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession
    from scenery_insitu_tpu.runtime.streaming import stream_sink

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=16",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
        "composite.max_output_supersegments=4", "composite.adaptive_iters=1")
    pub = VDIPublisher("tcp://127.0.0.1:0")
    sub = VDISubscriber(pub.endpoint)
    try:
        sess = InSituSession(cfg, mesh=make_mesh(2),
                             sinks=[stream_sink(pub)])
        got = None
        deadline = time.time() + 10.0
        while time.time() < deadline and got is None:
            time.sleep(0.05)
            sess.run(1)
            got = sub.receive(timeout_ms=200)
        assert got is not None
        vdi, meta = got
        assert vdi.color.shape == (4, 4, 24, 32)
        img = np.asarray(render_vdi(
            VDI(jnp.asarray(vdi.color), jnp.asarray(vdi.depth)), meta,
            orbit(sess.camera, jnp.float32(0.2)), 32, 24, steps=24))
        assert np.isfinite(img).all()
        assert img[3].max() > 0.0
    finally:
        pub.close()
        sub.close()


def test_video_sink(tmp_path):
    pytest.importorskip("cv2")
    path = str(tmp_path / "out.mp4")
    sink = video_sink(path, fps=10.0)
    img = np.random.default_rng(1).random((4, 24, 32)).astype(np.float32)
    for i in range(5):
        sink(i, {"image": img, "frame": i})
    sink.release()
    import os
    assert os.path.getsize(path) > 0
    # the writer probes H264 first and records what it actually opened;
    # in this image (no libx264/openh264/ffmpeg) that resolves to mp4v —
    # the documented environment gap, not a silent downgrade
    assert sink.codec in ("avc1", "H264", "mp4v")


def test_live_video_stream_roundtrip():
    """UDP MJPEG live stream: chunked frames reassemble at the receiver
    (≅ the reference's H264/UDP:3337 transport role)."""
    pytest.importorskip("cv2")
    from scenery_insitu_tpu.runtime.streaming import (VideoReceiver,
                                                      VideoStreamer)

    rx = VideoReceiver(port=0, timeout_s=3.0)
    tx = VideoStreamer(port=rx.port, quality=90)
    try:
        img = np.zeros((4, 48, 64), np.float32)
        img[0, 8:24, 8:24] = 0.9      # red block
        img[3] = 1.0
        # big enough to force multi-datagram path at tiny CHUNK
        tx.CHUNK = 512
        sent = tx.send_frame(img)
        assert sent > 0
        frame = rx.receive_frame()
        assert frame is not None and frame.shape == (48, 64, 3)
        # red block present-ish after jpeg
        assert frame[16, 16, 0] > 120 and frame[40, 40, 0] < 60
    finally:
        tx.close()
        rx.close()


def test_head_node_composites_ranks():
    """Head-node viewer: two ranks push image+depth, the head depth-min
    composites exactly one full frame set (≅ Head.kt:98-134)."""
    pytest.importorskip("zmq")
    from scenery_insitu_tpu.runtime.head import HeadNode, RankImageSender

    got = []
    head = HeadNode(2, bind="tcp://*:0",
                    sinks=(lambda i, p: got.append((i, p)),))
    try:
        s0 = RankImageSender(0, head.endpoint.replace("*", "localhost"))
        s1 = RankImageSender(1, head.endpoint.replace("*", "localhost"))
        h, w = 8, 12
        img0 = np.zeros((4, h, w), np.float32)
        img0[0] = 1.0
        img0[3] = 1.0
        dep0 = np.full((h, w), 2.0, np.float32)
        img1 = np.zeros((4, h, w), np.float32)
        img1[1] = 1.0
        img1[3] = 1.0
        dep1 = np.full((h, w), 1.0, np.float32)     # rank 1 nearer
        dep1[:, :4] = 3.0                            # ...except left strip
        time.sleep(0.2)                              # PUSH connect settles
        s0.send(0, img0, dep0)
        s1.send(0, img1, dep1)
        n = head.run(frames=1, timeout_s=10.0)
        assert n == 1 and len(got) == 1
        out = got[0][1]["image"]
        assert out[1, 4, 8] == 1.0                   # rank 1 (green) wins
        assert out[0, 4, 2] == 1.0                   # left strip: rank 0
        s0.close()
        s1.close()
    finally:
        head.close()


def test_streamed_mxu_vdi_client_renders_novel_view():
    """The MXU streamed-VDI client chain end to end: generate on the slice
    march, ship over ZMQ, reconstruct spec+virtual camera from METADATA
    ALONE on the client, render a novel view with the gather-free plane
    sweep (≅ the stored-matrices client of EfficientVDIRaycast.comp)."""
    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.vdi_novel import (axis_camera_from_meta,
                                                  axis_spec_from_meta,
                                                  render_vdi_mxu)

    vol = procedural_volume(24, kind="blobs", seed=6)
    tf = for_dataset("procedural")
    cam0 = Camera.create((0.1, 0.3, 2.9), fov_y_deg=45.0, near=0.3, far=10.0)
    spec = slicer.make_spec(cam0, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32", scale=1.5))
    vdi, meta, _ = slicer.generate_vdi_mxu(
        vol, tf, cam0, spec, VDIConfig(max_supersegments=5,
                                       adaptive_iters=2))

    pub = VDIPublisher("tcp://127.0.0.1:0")
    sub = VDISubscriber(pub.endpoint)
    try:
        got = None
        deadline = time.time() + 10.0
        while time.time() < deadline and got is None:
            time.sleep(0.05)
            pub.publish(vdi, meta)
            got = sub.receive(timeout_ms=200)
        assert got is not None
        rvdi, rmeta = got

        rspec = axis_spec_from_meta(rmeta, matmul_dtype="f32")
        assert (rspec.axis, rspec.sign) == (spec.axis, spec.sign)
        assert (rspec.ni, rspec.nj) == (spec.ni, spec.nj)
        axcam = axis_camera_from_meta(rmeta, rspec)
        cam1 = Camera.create((0.35, 0.45, 2.7), fov_y_deg=45.0,
                             near=0.3, far=10.0)
        img = np.asarray(render_vdi_mxu(
            VDI(jnp.asarray(rvdi.color), jnp.asarray(rvdi.depth)),
            axcam, rspec, cam1, 64, 48, num_slices=24))
        assert np.isfinite(img).all()
        assert img[3].max() > 0.1
    finally:
        pub.close()
        sub.close()


def test_subscriber_drops_corrupt_blob_without_raising():
    """Satellite (ISSUE 11): a corrupt/truncated blob used to crash
    receive_tile on np.frombuffer(...).reshape(...); now it fails the
    CRC/byte-count validation BEFORE decode and comes back as a typed
    StreamDrop."""
    from scenery_insitu_tpu.runtime.streaming import StreamDrop

    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
    sub = VDISubscriber(pub.endpoint)
    try:
        _sync_pubsub(pub, sub)
        vdi, meta = _vdi_meta()

        class _Corrupting:
            def __init__(self, sock):
                self.sock = sock

            def send_multipart(self, parts):
                parts = list(parts)
                blob = bytearray(parts[1])
                blob[len(blob) // 2] ^= 0xFF        # one flipped byte
                parts[1] = bytes(blob)
                self.sock.send_multipart(parts)

            def __getattr__(self, name):
                return getattr(self.sock, name)

        inner = pub.sock
        pub.sock = _Corrupting(inner)
        pub.publish(vdi, meta)
        got = sub.receive_tile(timeout_ms=5000)
        assert isinstance(got, StreamDrop)
        assert got.kind == "integrity"
        # clean frames keep flowing on the same socket afterwards
        pub.sock = inner
        pub.publish(vdi, meta)
        got = sub.receive(timeout_ms=5000)
        assert got is not None and not isinstance(got, StreamDrop)
        np.testing.assert_array_equal(np.asarray(vdi.color), got[0].color)
    finally:
        pub.close()
        sub.close()


def test_tf_message_roundtrip():
    from scenery_insitu_tpu.runtime.streaming import (make_tf_message,
                                                      tf_from_message)

    msg = make_tf_message([(0.1, 0.0), (0.8, 0.9)], colormap="hot")
    assert msg["type"] == "tf"
    tf = tf_from_message(msg)
    import jax.numpy as jnp
    import numpy as np
    _, a = tf(jnp.asarray([0.05, 0.8]))
    np.testing.assert_allclose(np.asarray(a), [0.0, 0.9], atol=1e-5)


def test_session_applies_tf_steering():
    """A 'tf' steering message swaps the session's transfer function and
    rebuilds the compiled steps — the reference's updateVis TF path."""
    import numpy as np

    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession
    from scenery_insitu_tpu.runtime.streaming import make_tf_message

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "composite.max_output_supersegments=8",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=2")
    sess = InSituSession(cfg, mesh=make_mesh(2))
    p1 = sess.run(2)
    old_tf = sess.tf

    # dispatch through the steering handler list (what drain_steering does
    # for non-camera kinds)
    msg = make_tf_message([(0.0, 0.9), (1.0, 0.9)], colormap="jet")
    for cb in sess.on_steer:
        cb(msg)
    assert sess.tf is not old_tf
    p2 = sess.run(2)
    assert np.isfinite(p2["vdi_color"]).all()
    # near-opaque-everywhere TF must change the render
    assert not np.allclose(p1["vdi_color"], p2["vdi_color"])


def test_malformed_tf_message_is_contained():
    """A network-facing viewer sending a broken 'tf' payload must not
    kill the render loop — logged and ignored."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    lines = []
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=4", "composite.max_output_supersegments=4",
        "sim.grid=[12,12,12]", "sim.steps_per_frame=1")
    sess = InSituSession(cfg, mesh=make_mesh(2), log=lines.append)
    tf0 = sess.tf
    for bad in ({"type": "tf"},                              # no points
                {"type": "tf", "points": [[0, 0]] * 40},     # too many
                {"type": "tf", "points": [[0.1, 0.2]],
                 "colormap": "no_such_map"}):
        for cb in sess.on_steer:
            cb(bad)
    assert sess.tf is tf0                   # nothing applied
    assert any("malformed tf" in ln for ln in lines)
    import numpy as np
    assert np.isfinite(sess.run(1)["vdi_color"]).all()
