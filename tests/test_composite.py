"""Compositing tests: the dump->recomposite->compare loop the reference runs
by eye (VDICompositingExample) becomes numeric golden checks here."""

import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import CompositeConfig, RenderConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, render_vdi_same_view
from scenery_insitu_tpu.core.volume import Volume, procedural_volume
from scenery_insitu_tpu.ops.composite import (composite_depth_min,
                                              composite_plain, composite_vdis)
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
from scenery_insitu_tpu.utils.image import psnr

W = H = 16
STEPS = 48


def _cam():
    return Camera.create((0.0, 0.0, 4.0), fov_y_deg=50.0, near=0.5, far=20.0)


def _split_z(vol: Volume, parts: int):
    """Domain-decompose along the volume z axis (≅ OpenFPM grid splits)."""
    d = vol.data.shape[0]
    chunk = d // parts
    subs = []
    for p in range(parts):
        data = vol.data[p * chunk:(p + 1) * chunk]
        origin = vol.origin + jnp.array([0.0, 0.0, p * chunk]) * vol.spacing
        subs.append(Volume(data, origin, vol.spacing))
    return subs


def test_two_rank_composite_matches_full_render():
    vol = procedural_volume(16, kind="shell")
    tf = TransferFunction.ramp(0.05, 0.8, 0.7)
    cam = _cam()
    ref = np.asarray(raycast(vol, tf, cam, W, H,
                             RenderConfig(max_steps=STEPS,
                                          early_exit_alpha=1.1)).image)
    vcfg = VDIConfig(max_supersegments=12)
    subs = _split_z(vol, 2)
    vdis = [generate_vdi(s, tf, cam, W, H, vcfg, max_steps=STEPS)[0]
            for s in subs]
    colors = jnp.stack([v.color for v in vdis])
    depths = jnp.stack([v.depth for v in vdis])
    out = composite_vdis(colors, depths,
                         CompositeConfig(max_output_supersegments=16))
    img = np.asarray(render_vdi_same_view(out))
    assert psnr(ref, img) > 28.0, psnr(ref, img)


def test_composite_preserves_order_of_disjoint_segments():
    # rank 0 has a far segment, rank 1 a near one; composite must put the
    # near one in front regardless of rank order
    k = 4
    v0 = VDI.empty(k, 1, 1)
    v0 = VDI(v0.color.at[0].set(jnp.array([0.0, 0.8, 0.0, 0.8]).reshape(4, 1, 1)),
             v0.depth.at[0].set(jnp.array([5.0, 5.5]).reshape(2, 1, 1)))
    v1 = VDI.empty(k, 1, 1)
    v1 = VDI(v1.color.at[0].set(jnp.array([0.9, 0.0, 0.0, 0.9]).reshape(4, 1, 1)),
             v1.depth.at[0].set(jnp.array([2.0, 2.5]).reshape(2, 1, 1)))
    out = composite_vdis(jnp.stack([v0.color, v1.color]),
                         jnp.stack([v0.depth, v1.depth]),
                         CompositeConfig(max_output_supersegments=4,
                                         adaptive=False))
    img = np.asarray(render_vdi_same_view(out))[:, 0, 0]
    # red (near, alpha .9) dominates
    assert img[0] > img[1]
    d = np.asarray(out.depth)[:, :, 0, 0]
    assert np.isclose(d[0, 0], 2.0, atol=1e-5)


def test_composite_empty_inputs():
    k = 3
    empty = VDI.empty(k, 2, 2)
    out = composite_vdis(jnp.stack([empty.color, empty.color]),
                         jnp.stack([empty.depth, empty.depth]))
    assert np.asarray(out.count).sum() == 0


def test_plain_composite_depth_order():
    # two full-screen images; nearer one (rank 1) must win
    img0 = jnp.zeros((4, 2, 2)).at[1].set(0.8).at[3].set(0.8)   # green
    img1 = jnp.zeros((4, 2, 2)).at[0].set(0.9).at[3].set(0.9)   # red
    d0 = jnp.full((2, 2), 5.0)
    d1 = jnp.full((2, 2), 1.0)
    out = np.asarray(composite_plain(jnp.stack([img0, img1]),
                                     jnp.stack([d0, d1])))
    assert (out[0] > out[1]).all()
    # alpha-under: total alpha = .9 + .1*.8
    assert np.allclose(out[3], 0.98, atol=1e-6)


def test_depth_min_composite():
    img0 = jnp.ones((4, 2, 2)) * 0.2
    img1 = jnp.ones((4, 2, 2)) * 0.7
    d0 = jnp.array([[1.0, 9.0], [1.0, 9.0]])
    d1 = jnp.array([[5.0, 2.0], [5.0, 2.0]])
    img, d = composite_depth_min(jnp.stack([img0, img1]),
                                 jnp.stack([d0, d1]))
    img, d = np.asarray(img), np.asarray(d)
    assert img[0, 0, 0] == np.float32(0.2) and img[0, 0, 1] == np.float32(0.7)
    assert d[0, 0] == 1.0 and d[0, 1] == 2.0


def test_n1_composite_is_identity_pad():
    """N=1 with K_out >= K and the default backend: the composite's
    defined behavior is the verbatim input padded with empty slots (the
    merge fold's search floor would re-merge for no gain) — and it must
    render like the real fold, which explicit backends still run."""
    from scenery_insitu_tpu.config import VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
    from scenery_insitu_tpu.utils.image import psnr

    vol = procedural_volume(32, kind="blobs", seed=5)
    tf = for_dataset("procedural")
    cam = Camera.create((0.1, 0.4, 2.8), fov_y_deg=45.0, near=0.3, far=12.0)
    vdi, _ = generate_vdi(vol, tf, cam, 48, 40,
                          VDIConfig(max_supersegments=8, adaptive_iters=3),
                          max_steps=96)

    out = composite_vdis(vdi.color[None], vdi.depth[None],
                         CompositeConfig(max_output_supersegments=10))
    np.testing.assert_array_equal(np.asarray(out.color[:8]),
                                  np.asarray(vdi.color))
    np.testing.assert_array_equal(np.asarray(out.depth[:8]),
                                  np.asarray(vdi.depth))
    assert float(out.color[8:, 3].max()) == 0.0     # padding is empty
    assert np.isinf(np.asarray(out.depth[8:])).all()

    # an explicitly requested backend still runs the real merge fold, and
    # the two stay visually equivalent
    slow = composite_vdis(vdi.color[None], vdi.depth[None],
                          CompositeConfig(max_output_supersegments=10,
                                          backend="xla"))
    a = render_vdi_same_view(out)
    b = render_vdi_same_view(slow)
    q = psnr(np.asarray(b), np.asarray(a))
    assert q > 40.0, f"PSNR {q:.1f} dB"
