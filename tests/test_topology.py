"""The scale-out plane (ISSUE 14; docs/MULTIHOST.md): first-class mesh
topology + the hierarchical two-level composite, verified on the virtual
8-device mesh by EMULATING ICI domains as mesh sub-axes.

Parity is the contract: an (H hosts x D devices) hierarchical frame must
match the flat H*D-rank composite — BITWISE on the gather builder and
every f32 VDI path (re-segmentation happens once, at the top, so the
merged stream is the flat stream), <= 1e-5 on the plain paths (alpha-under
group association is exact only in exact arithmetic), and at a PSNR floor
under a lossy DCN wire. Single-host configurations must be bitwise the
flat path with the inert knob on the ledger.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, TopologyConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.parallel.hier import modeled_dcn_traffic
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (
    distributed_hybrid_step_mxu, distributed_initial_threshold_mxu,
    distributed_plain_step, distributed_plain_step_mxu,
    distributed_vdi_step, distributed_vdi_step_mxu,
    distributed_vdi_step_mxu_temporal, shard_volume)
from scenery_insitu_tpu.parallel.topology import (Topology,
                                                  make_topology_mesh,
                                                  resolve_mesh_topology,
                                                  resolve_topology,
                                                  topology_of)

W = H = 16
STEPS = 48
N = 8
ATOL = 1e-5     # separately-compiled programs carry ~1-ulp fusion noise


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _vol():
    return procedural_volume(16, kind="blobs")


def _mxu_spec(cam, vol, scale=2.0):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=scale),
                            multiple_of=N)


def _vcfg():
    return VDIConfig(max_supersegments=6, adaptive_iters=2)


def _ccfg(**kw):
    return CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                           **kw)


def _assert_vdi_equal(a, b, atol=0.0):
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    if atol == 0.0:
        np.testing.assert_array_equal(ac, bc)
    else:
        np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    if atol == 0.0:
        np.testing.assert_array_equal(ad[fin], bd[fin])
    else:
        np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


def _psnr(a, b, peak=1.0):
    mse = float(np.mean((np.asarray(a) - np.asarray(b)) ** 2))
    return float("inf") if mse == 0 else 10.0 * np.log10(peak ** 2 / mse)


# --------------------------------------------------- config + resolution

class TestTopologyConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="num_hosts"):
            TopologyConfig(num_hosts=0)
        with pytest.raises(ValueError, match="domain_size"):
            TopologyConfig(domain_size=-1)
        with pytest.raises(ValueError, match="dcn_wire"):
            TopologyConfig(dcn_wire="f16")
        with pytest.raises(ValueError, match="hosts_axis"):
            TopologyConfig(hosts_axis="")

    def test_domain_size_must_divide_device_count(self):
        with pytest.raises(ValueError, match="tile"):
            resolve_topology(TopologyConfig(num_hosts=3), 8)
        with pytest.raises(ValueError, match="tile"):
            resolve_topology(TopologyConfig(num_hosts=2, domain_size=3), 8)
        t = resolve_topology(TopologyConfig(num_hosts=2), 8)
        assert (t.num_hosts, t.domain_size) == (2, 4)
        assert t.n_ranks == 8
        assert t.flat_axis == ("hosts", "ranks")
        assert t.out_axis == ("ranks", "hosts")

    def test_hosts_axis_collision_rejected(self):
        with pytest.raises(ValueError, match="distinct"):
            resolve_topology(TopologyConfig(num_hosts=2,
                                            hosts_axis="ranks"), 8)

    def test_single_host_resolves_flat_with_inert_ledger(self):
        obs.clear_ledger()
        assert resolve_topology(TopologyConfig(), 8) is None
        assert obs.ledger() == []       # the default is not a degrade
        # a domain split with one host is an inert knob — ledgered
        assert resolve_topology(TopologyConfig(num_hosts=1,
                                               domain_size=4), 8) is None
        assert any(e["component"] == "topology.hier"
                   for e in obs.ledger()), obs.ledger()

    def test_make_topology_mesh_shapes(self):
        mesh, topo = make_topology_mesh(TopologyConfig(num_hosts=2))
        assert mesh.axis_names == ("hosts", "ranks")
        assert (mesh.shape["hosts"], mesh.shape["ranks"]) == (2, 4)
        assert topo.num_hosts == 2 and topo.domain_size == 4
        flat, _ = make_topology_mesh(TopologyConfig())
        assert flat.axis_names == ("ranks",)

    def test_topology_of_mesh_mismatch_raises(self):
        mesh, _ = make_topology_mesh(TopologyConfig(num_hosts=2))
        with pytest.raises(ValueError, match="disagrees"):
            topology_of(mesh, TopologyConfig(num_hosts=4))
        with pytest.raises(ValueError, match="flat 1-D"):
            topology_of(make_mesh(N), TopologyConfig(num_hosts=2))

    def test_resolve_mesh_topology_views(self):
        mesh, _ = make_topology_mesh(TopologyConfig(num_hosts=2))
        axis, n, topo = resolve_mesh_topology(mesh)
        assert axis == ("hosts", "ranks") and n == 8
        assert isinstance(topo, Topology)
        axis, n, topo = resolve_mesh_topology(make_mesh(4))
        assert axis == "ranks" and n == 4 and topo is None


# ------------------------------------------------- emulated-mesh parity

def _flat_ref(vol, cam, ccfg):
    mesh = make_mesh(N)
    step = distributed_vdi_step(mesh, _tf(), W, H, _vcfg(), ccfg,
                                max_steps=STEPS)
    out = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    return out.color, out.depth


def _hier(vol, cam, ccfg, tcfg):
    mesh, _ = make_topology_mesh(tcfg)
    step = distributed_vdi_step(mesh, _tf(), W, H, _vcfg(), ccfg,
                                max_steps=STEPS, topology=tcfg)
    out = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    return out.color, out.depth


@pytest.mark.parametrize("hosts", [2, 4])
def test_hier_gather_step_bitwise(hosts):
    """The acceptance gate: hierarchical == flat BITWISE on the gather
    builder (both topologies of the 8-device mesh)."""
    vol, cam, ccfg = _vol(), _cam(), _ccfg()
    ref = _flat_ref(vol, cam, ccfg)
    got = _hier(vol, cam, ccfg, TopologyConfig(num_hosts=hosts))
    _assert_vdi_equal(got, ref, atol=0.0)


@pytest.mark.parametrize("exchange", ["all_to_all", "ring"])
def test_hier_gather_step_exchange_modes_bitwise(exchange):
    """Both intra-domain (ICI) exchange schedules feed the same merged
    stream to the single top-level re-segmentation."""
    vol, cam = _vol(), _cam()
    ccfg = _ccfg(exchange=exchange)
    ref = _flat_ref(vol, cam, ccfg)
    got = _hier(vol, cam, ccfg, TopologyConfig(num_hosts=2))
    _assert_vdi_equal(got, ref, atol=0.0)


def test_hier_single_host_bitwise_flat():
    """num_hosts=1 IS the flat path (same 1-D mesh, same program)."""
    vol, cam, ccfg = _vol(), _cam(), _ccfg()
    ref = _flat_ref(vol, cam, ccfg)
    got = _hier(vol, cam, ccfg, TopologyConfig(num_hosts=1))
    _assert_vdi_equal(got, ref, atol=0.0)


def test_hier_mxu_step_parity():
    vol, cam = _vol(), _cam()
    ccfg = _ccfg()
    spec = _mxu_spec(cam, vol)
    mesh = make_mesh(N)
    ref = distributed_vdi_step_mxu(mesh, _tf(), spec, _vcfg(), ccfg)(
        shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)[0]
    tcfg = TopologyConfig(num_hosts=2)
    mesh2, _ = make_topology_mesh(tcfg)
    got = distributed_vdi_step_mxu(mesh2, _tf(), spec, _vcfg(), ccfg,
                                   topology=tcfg)(
        shard_volume(vol.data, mesh2), vol.origin, vol.spacing, cam)[0]
    _assert_vdi_equal((got.color, got.depth), (ref.color, ref.depth),
                      atol=ATOL)


def test_hier_mxu_waves_parity():
    """Tile waves x hierarchy: every wave runs the two-level composite;
    the assembled frame still matches the flat frame schedule."""
    vol, cam = _vol(), _cam()
    ccfg = _ccfg(schedule="waves", wave_tiles=2, exchange="ring")
    spec = _mxu_spec(cam, vol)
    mesh = make_mesh(N)
    ref = distributed_vdi_step_mxu(
        mesh, _tf(), spec, _vcfg(), _ccfg(exchange="ring"))(
        shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)[0]
    tcfg = TopologyConfig(num_hosts=2)
    mesh2, _ = make_topology_mesh(tcfg)
    got = distributed_vdi_step_mxu(mesh2, _tf(), spec, _vcfg(), ccfg,
                                   topology=tcfg)(
        shard_volume(vol.data, mesh2), vol.origin, vol.spacing, cam)[0]
    _assert_vdi_equal((got.color, got.depth), (ref.color, ref.depth),
                      atol=ATOL)


def test_hier_mxu_temporal_carry_parity():
    """Carried temporal threshold state threads through the flat axis
    view — 2 frames of hier == 2 frames of flat, thr state included."""
    vol, cam = _vol(), _cam()
    ccfg = _ccfg()
    vt = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    spec = _mxu_spec(cam, vol)
    mesh = make_mesh(N)
    f1 = shard_volume(vol.data, mesh)
    thr1 = distributed_initial_threshold_mxu(mesh, _tf(), spec, vt)(
        f1, vol.origin, vol.spacing, cam)
    st1 = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, vt, ccfg)
    tcfg = TopologyConfig(num_hosts=2)
    mesh2, _ = make_topology_mesh(tcfg)
    f2 = shard_volume(vol.data, mesh2)
    thr2 = distributed_initial_threshold_mxu(mesh2, _tf(), spec, vt)(
        f2, vol.origin, vol.spacing, cam)
    st2 = distributed_vdi_step_mxu_temporal(mesh2, _tf(), spec, vt, ccfg,
                                            topology=tcfg)
    for _ in range(2):
        (r, _), thr1 = st1(f1, vol.origin, vol.spacing, cam, thr1)
        (o, _), thr2 = st2(f2, vol.origin, vol.spacing, cam, thr2)
    _assert_vdi_equal((o.color, o.depth), (r.color, r.depth), atol=ATOL)
    for a, b in zip(thr2, thr1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=ATOL, rtol=0)


def test_hier_plain_steps_parity():
    """Plain gather + plain MXU: alpha-under group association holds to
    the 1e-5 gate (exact only in exact arithmetic)."""
    vol, cam = _vol(), _cam()
    tcfg = TopologyConfig(num_hosts=2)
    mesh = make_mesh(N)
    mesh2, _ = make_topology_mesh(tcfg)
    rcfg = RenderConfig(width=W, height=H, max_steps=STEPS)
    ref = distributed_plain_step(mesh, _tf(), W, H, rcfg)(
        shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    got = distributed_plain_step(mesh2, _tf(), W, H, rcfg, topology=tcfg)(
        shard_volume(vol.data, mesh2), vol.origin, vol.spacing, cam)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=ATOL, rtol=0)
    spec = _mxu_spec(cam, vol)
    ref, _ = distributed_plain_step_mxu(mesh, _tf(), spec)(
        shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    got, _ = distributed_plain_step_mxu(mesh2, _tf(), spec,
                                        topology=tcfg)(
        shard_volume(vol.data, mesh2), vol.origin, vol.spacing, cam)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=ATOL, rtol=0)


def test_hier_hybrid_step_parity():
    vol, cam = _vol(), _cam()
    spec = _mxu_spec(cam, vol)
    rng = np.random.default_rng(3)
    pos = jnp.asarray(rng.uniform(-0.8, 0.8, (32, 3)), jnp.float32)
    vel = jnp.asarray(rng.normal(0, 0.2, (32, 3)), jnp.float32)
    tcfg = TopologyConfig(num_hosts=2)
    mesh = make_mesh(N)
    mesh2, _ = make_topology_mesh(tcfg)

    def run(mesh, topology):
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax

        step = distributed_hybrid_step_mxu(
            mesh, _tf(), spec, _vcfg(), _ccfg(), radius=0.05,
            topology=topology)
        axes = (mesh.axis_names if len(mesh.axis_names) > 1
                else mesh.axis_names[0])
        sh = NamedSharding(mesh, P(axes, None))
        img, _ = step(shard_volume(vol.data, mesh), vol.origin,
                      vol.spacing, jax.device_put(pos, sh),
                      jax.device_put(vel, sh), cam)
        return np.asarray(img)

    ref = run(mesh, None)
    got = run(mesh2, tcfg)
    np.testing.assert_allclose(got, ref, atol=ATOL, rtol=0)


@pytest.mark.parametrize("dcn_wire", ["bf16", "qpack8"])
def test_hier_lossy_dcn_wire_psnr(dcn_wire):
    """A lossy DCN wire holds the documented floor vs the flat f32
    composite. The floor is 30 dB, BELOW the 40 dB ICI-wire floor, for a
    structural reason (docs/MULTIHOST.md "DCN wire protocol"): the DCN
    hop quantizes the MERGED [D*K]-slot accumulator — qpack8's
    per-fragment [near, far] normalization then spans the whole scene
    depth instead of one slab's narrow band, and the rounding sits
    immediately upstream of the adaptive re-segmentation decision, so a
    flipped merge shows as a full-scale delta on a handful of pixels
    (measured ~37.6 dB bf16 / ~32.5 dB qpack8 on this 16x16 scene;
    larger frames dilute the per-pixel flips). f32 DCN is the parity
    mode; the lossy wires are the bandwidth levers."""
    vol, cam, ccfg = _vol(), _cam(), _ccfg()
    ref = _flat_ref(vol, cam, ccfg)
    got = _hier(vol, cam, ccfg,
                TopologyConfig(num_hosts=2, dcn_wire=dcn_wire))
    p = _psnr(np.asarray(got[0]), np.asarray(ref[0]))
    assert p >= 30.0, p


def test_hier_rebalanced_plan_matches_flat_plan():
    """Render rebalancing x hierarchy: an uneven render z-plan
    materializes over the FLAT axis view (reslab_z ppermutes across the
    tuple axis), so a rebalanced hierarchical frame is BITWISE the
    rebalanced flat frame."""
    vol, cam = _vol(), _cam()
    ccfg = _ccfg(rebalance="occupancy", rebalance_min_depth=1,
                 rebalance_quantum=1)
    plan = (3, 1, 2, 2, 2, 2, 2, 2)
    mesh = make_mesh(N)
    ref = distributed_vdi_step(mesh, _tf(), W, H, _vcfg(), ccfg,
                               max_steps=STEPS, plan=plan)(
        shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    tcfg = TopologyConfig(num_hosts=2)
    mesh2, _ = make_topology_mesh(tcfg)
    got = distributed_vdi_step(mesh2, _tf(), W, H, _vcfg(), ccfg,
                               max_steps=STEPS, plan=plan,
                               topology=tcfg)(
        shard_volume(vol.data, mesh2), vol.origin, vol.spacing, cam)
    _assert_vdi_equal((got.color, got.depth), (ref.color, ref.depth),
                      atol=0.0)


def test_hier_geometry_rejected_at_build():
    """A width the two-level split does not tile fails at BUILD."""
    tcfg = TopologyConfig(num_hosts=2)
    mesh, _ = make_topology_mesh(tcfg)
    with pytest.raises(ValueError, match="divisible"):
        distributed_vdi_step(mesh, _tf(), 12, H, _vcfg(), _ccfg(),
                             topology=tcfg)


# ------------------------------------------------------ obs + the model

def test_hier_build_emits_obs_counters():
    rec = obs.Recorder(enabled=True)
    obs.set_recorder(rec)
    try:
        vol, cam = _vol(), _cam()
        got = _hier(vol, cam, _ccfg(), TopologyConfig(num_hosts=2))
        np.asarray(got[0])
        assert rec.counters.get("hier_composite_builds", 0) >= 1
        assert rec.counters.get("dcn_hops_built", 0) >= 1
        evs = [e for e in rec.events
               if e.get("name") == "hier_composite_build"]
        assert evs, [e.get("name") for e in rec.events]
        at = evs[0]["attrs"]
        assert at["hosts"] == 2 and at["domain_size"] == 4
        assert at["dcn"]["dcn_bytes_sent_per_host"] > 0
        hops = [e for e in rec.events if e.get("name") == "dcn_hop"]
        assert hops and all(h["attrs"]["wire"] == "f32" for h in hops)
    finally:
        obs.set_recorder(obs.Recorder(enabled=False))


def test_modeled_dcn_traffic_accounting():
    m = modeled_dcn_traffic(2, 4, 6, 16, 16, dcn_wire="f32")
    # 24 slots/pixel cross DCN, sub-block 2 columns wide, 24 B/slot,
    # 1 hop: (H-1) * M * height * sub * slot_bytes
    assert m["slots_per_pixel"] == 24
    assert m["dcn_bytes_sent_per_rank"] == 1 * 24 * 16 * 2 * 24
    assert m["dcn_bytes_sent_per_host"] == 4 * m["dcn_bytes_sent_per_rank"]
    q = modeled_dcn_traffic(2, 4, 6, 16, 16, dcn_wire="qpack8")
    assert q["dcn_bytes_sent_per_host"] * 4 == m["dcn_bytes_sent_per_host"]
    # a capped ring TRUNCATES the accumulator to the cap before it
    # crosses DCN (the +K incoming-fragment term is merge working
    # memory, not shipped bytes)
    capped = modeled_dcn_traffic(2, 4, 6, 16, 16, ring_slots=8)
    assert capped["slots_per_pixel"] == 8
    uncapped = modeled_dcn_traffic(2, 4, 6, 16, 16, ring_slots=64)
    assert uncapped["slots_per_pixel"] == 24


# ------------------------------------------------------- session plumbing

def test_session_hier_traced_frame(tmp_path):
    """An InSituSession on a hierarchical TopologyConfig builds the 2-D
    mesh, renders finite frames through the two-level composite, and the
    hier/dcn counters land in the trace."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8",
        "composite.adaptive_iters=2",
        "topology.num_hosts=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "obs.enabled=true")
    sess = InSituSession(cfg)
    assert sess.mesh.axis_names == ("hosts", "ranks")
    assert sess._n_ranks == 8
    payload = sess.run(1)
    assert np.isfinite(payload["vdi_color"]).all()
    assert sess.obs.counters.get("hier_composite_builds", 0) >= 1
    assert sess.obs.counters.get("dcn_hops_built", 0) >= 1


def test_session_flat_default_unchanged():
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1")
    sess = InSituSession(cfg)
    assert sess.mesh.axis_names == ("ranks",)
    assert sess._topo is None and sess._n_ranks == 8


def test_session_hier_checkpoint_roundtrip(tmp_path):
    """Checkpointing a hierarchical session round-trips: the header
    records the TOTAL rank count (not one domain's size) and a resumed
    session renders on from the restored state (the review finding —
    checkpoint.py read mesh.shape[axis_name], which on a 2-D mesh is
    domain_size)."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.checkpoint import (load_session,
                                                       save_session)
    from scenery_insitu_tpu.runtime.session import InSituSession

    def make():
        cfg = FrameworkConfig().with_overrides(
            "render.width=32", "render.height=24", "render.max_steps=24",
            "vdi.max_supersegments=6", "vdi.adaptive_mode=temporal",
            "composite.max_output_supersegments=8",
            "composite.adaptive_iters=2",
            "slicer.engine=mxu", "topology.num_hosts=2",
            "sim.grid=[16,16,16]", "sim.steps_per_frame=1")
        return InSituSession(cfg)

    a = make()
    a.run(2)
    path = str(tmp_path / "hier.ckpt")
    save_session(a, path)
    b = make()
    load_session(b, path)
    assert b.frame_index == a.frame_index
    p_a = a.run(1)
    p_b = b.run(1)
    np.testing.assert_array_equal(np.asarray(p_a["vdi_color"]),
                                  np.asarray(p_b["vdi_color"]))


def test_session_particles_hier_inert_ledger():
    """Particle sessions composite sort-first — a hierarchy request is
    inert, ledgered, and the flat mesh renders."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    obs.clear_ledger()
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24",
        "sim.kind=lennard_jones", "sim.num_particles=64",
        "topology.num_hosts=2")
    sess = InSituSession(cfg)
    assert sess.mesh.axis_names == ("ranks",)
    assert any(e["component"] == "topology.hier" for e in obs.ledger())
