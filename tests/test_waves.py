"""Tile-wave pipelined frames (CompositeConfig.schedule="waves") vs the
monolithic frame schedule: lossless waves must be parity-exact (<=1e-5,
the PR-6 fusion-noise gate — separately compiled programs) across every
distributed step builder on the 8-device virtual mesh, the tile-granular
delivery path must emit column blocks in order before the frame closes,
and the traffic model must account the overlap (docs/PERF.md "Tile
waves")."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)

W = H = 16
STEPS = 48
N = 8
T = 2           # wave tiles per rank block in these tests
ATOL = 1e-5     # separately-compiled schedules carry ~1-ulp fusion noise


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _mxu_spec(cam, vol, scale=2.0):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, vol.data.shape,
                            SliceMarchConfig(matmul_dtype="f32",
                                             scale=scale),
                            multiple_of=N)


def _assert_vdi_close(a, b, atol=ATOL):
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


# ------------------------------------------------- wave column helpers

def test_wave_cols_roundtrip():
    from scenery_insitu_tpu.ops import slicer

    x = jnp.arange(3 * 24, dtype=jnp.float32).reshape(3, 24)
    acc = jnp.zeros_like(x)
    for w in range(2):
        xw = slicer.wave_cols(x, 4, 2, jnp.int32(w))
        ref = np.asarray(x).reshape(3, 4, 2, 3)[:, :, w].reshape(3, 12)
        np.testing.assert_array_equal(np.asarray(xw), ref)
        acc = slicer.wave_update_cols(acc, xw, 4, 2, jnp.int32(w))
    np.testing.assert_array_equal(np.asarray(acc), np.asarray(x))


def test_wave_block_validation():
    from scenery_insitu_tpu.ops import slicer

    assert slicer.wave_block(32, 8, 2) == 2
    with pytest.raises(ValueError, match="wave_tiles"):
        slicer.wave_block(16, 8, 3)


def test_wave_tiles_config_validation():
    with pytest.raises(ValueError, match="schedule"):
        CompositeConfig(schedule="tiles")
    with pytest.raises(ValueError, match="wave_tiles"):
        CompositeConfig(wave_tiles=0)


def test_wave_geometry_rejected_at_build():
    """A width that does not split into ranks * wave_tiles blocks fails
    when the step is BUILT, not deep inside a trace."""
    mesh = make_mesh(N)
    with pytest.raises(ValueError, match="wave_tiles"):
        distributed_vdi_step(
            mesh, _tf(), W, H,
            VDIConfig(max_supersegments=6, adaptive_iters=2),
            CompositeConfig(max_output_supersegments=8, schedule="waves",
                            wave_tiles=3), max_steps=STEPS)


# ------------------------------------------------ parity: every builder

def _run_vdi_step(schedule, vol, cam, exchange="all_to_all"):
    mesh = make_mesh(N)
    ccfg = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                           exchange=exchange, schedule=schedule,
                           wave_tiles=T)
    step = distributed_vdi_step(
        mesh, _tf(), W, H, VDIConfig(max_supersegments=6,
                                     adaptive_iters=2),
        ccfg, max_steps=STEPS)
    vdi = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    return vdi.color, vdi.depth


@pytest.mark.parametrize("exchange", ["all_to_all", "ring"])
def test_waves_vdi_step_matches_frame(exchange):
    """Gather-engine VDI chain: lossless waves == the frame schedule
    under BOTH per-wave exchange modes (the waves scan reuses the frame
    compositor per wave — bitwise on this path)."""
    vol = procedural_volume(16, kind="blobs")
    frame = _run_vdi_step("frame", vol, _cam(), exchange)
    waves = _run_vdi_step("waves", vol, _cam(), exchange)
    _assert_vdi_close(waves, frame)


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z
                                 (3.8, 0.3, 0.6)])   # march axis x
def test_waves_mxu_step_matches_frame(eye):
    """MXU slice-march chain in both march regimes: the tile-scoped wave
    march (u-sliced wave camera, shared permuted copy + pyramid) must
    reproduce the monolithic march + composite."""
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam(eye)
    spec = _mxu_spec(cam, vol)
    data = shard_volume(vol.data, mesh)
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    outs = {}
    for sched in ("frame", "waves"):
        ccfg = CompositeConfig(max_output_supersegments=8,
                               adaptive_iters=2, schedule=sched,
                               wave_tiles=T)
        step = distributed_vdi_step_mxu(mesh, _tf(), spec, vcfg, ccfg)
        vdi, meta = step(data, vol.origin, vol.spacing, cam)
        outs[sched] = (vdi.color, vdi.depth, np.asarray(meta.window_dims))
    _assert_vdi_close(outs["waves"][:2], outs["frame"][:2])
    # the wave meta must describe the FULL frame, not one wave's columns
    np.testing.assert_array_equal(outs["waves"][2], outs["frame"][2])


def test_waves_mxu_temporal_threshold_carry_matches():
    """Temporal mode: each wave updates only its own threshold columns;
    across 3 carried frames both the per-frame composites and the final
    threshold maps must match the frame schedule."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    cfg_t = VDIConfig(max_supersegments=6, adaptive_mode="temporal")
    spec = _mxu_spec(cam, vol)
    data = shard_volume(vol.data, mesh)
    runs = {}
    for sched in ("frame", "waves"):
        comp = CompositeConfig(max_output_supersegments=8,
                               adaptive_iters=2, schedule=sched,
                               wave_tiles=T)
        thr = distributed_initial_threshold_mxu(mesh, _tf(), spec, cfg_t)(
            data, vol.origin, vol.spacing, cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, cfg_t,
                                                 comp)
        frames = []
        for _ in range(3):
            (vdi, _), thr = step(data, vol.origin, vol.spacing, cam, thr)
            frames.append((np.asarray(vdi.color), np.asarray(vdi.depth)))
        runs[sched] = (frames, np.asarray(thr.thr))
    np.testing.assert_allclose(runs["waves"][1], runs["frame"][1],
                               atol=1e-6, rtol=0)
    for fr_w, fr_f in zip(runs["waves"][0], runs["frame"][0]):
        _assert_vdi_close(fr_w, fr_f)


def test_waves_plain_step_matches_frame():
    """Plain gather chain: the wave scan slices pre-rendered fragments,
    so frames must be bitwise identical."""
    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="shell")
    cfg = RenderConfig(max_steps=STEPS, early_exit_alpha=1.1,
                       background=(1.0, 0.2, 0.1, 1.0))
    data = shard_volume(vol.data, mesh)
    imgs = {}
    for sched in ("frame", "waves"):
        step = distributed_plain_step(mesh, _tf(), W, H, cfg,
                                      schedule=sched, wave_tiles=T)
        imgs[sched] = np.asarray(step(data, vol.origin, vol.spacing,
                                      _cam()))
    np.testing.assert_array_equal(imgs["waves"], imgs["frame"])


def test_waves_plain_mxu_step_matches_frame():
    """Plain MXU chain: tile-scoped render_slices per wave (shared
    permuted copy + occupancy gate) + per-wave exchange."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    spec = _mxu_spec(cam, vol)
    data = shard_volume(vol.data, mesh)
    imgs = {}
    for sched in ("frame", "waves"):
        step = distributed_plain_step_mxu(mesh, _tf(), spec,
                                          schedule=sched, wave_tiles=T)
        img, _ = step(data, vol.origin, vol.spacing, cam)
        imgs[sched] = np.asarray(img)
    np.testing.assert_allclose(imgs["waves"], imgs["frame"], atol=ATOL,
                               rtol=0)


def test_waves_hybrid_step_matches_frame():
    """Hybrid frame: the VDI half runs at wave granularity, the splat
    half inserts into the assembled block — whole frames must match."""
    import jax

    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu)
    from scenery_insitu_tpu.parallel.particles import shard_particles

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    spec = _mxu_spec(cam, vol)
    vcfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    pos = jax.random.uniform(jax.random.PRNGKey(7), (64, 3),
                             minval=-0.8, maxval=0.8)
    vel = jax.random.normal(jax.random.PRNGKey(8), (64, 3)) * 0.1
    data = shard_volume(vol.data, mesh)
    p = shard_particles(pos, mesh)
    v = shard_particles(vel, mesh)
    imgs = {}
    for sched in ("frame", "waves"):
        ccfg = CompositeConfig(max_output_supersegments=8,
                               adaptive_iters=2, schedule=sched,
                               wave_tiles=T)
        step = distributed_hybrid_step_mxu(mesh, _tf(), spec, vcfg, ccfg,
                                           radius=0.05, stamp=3)
        img, _ = step(data, vol.origin, vol.spacing, p, v, cam)
        imgs[sched] = np.asarray(img)
    np.testing.assert_allclose(imgs["waves"], imgs["frame"], atol=ATOL,
                               rtol=0)


def test_waves_under_frame_scan_matches_eager():
    """A waves step rolls into parallel.pipeline.frame_scan unchanged:
    the wave scan nests inside the frame scan, per-wave temporal state
    crosses frames as the same full-frame carry."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_vdi_step_mxu, frame_scan)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    cam = _cam()
    spec = _mxu_spec(cam, vol)
    data = shard_volume(vol.data, mesh)
    ccfg = CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                           schedule="waves", wave_tiles=T)
    step = distributed_vdi_step_mxu(
        mesh, _tf(), spec, VDIConfig(max_supersegments=6,
                                     adaptive_iters=2), ccfg)
    eager, _ = step(data, vol.origin, vol.spacing, cam)
    run = frame_scan(step, lambda s: s, 2, field=lambda s: s)
    _, (vdis, _) = run(data, vol.origin, vol.spacing, cam,
                       jnp.float32(0.0))
    # static field + static camera: both scanned frames == the eager one
    for i in range(2):
        _assert_vdi_close((vdis.color[i], vdis.depth[i]),
                          (eager.color, eager.depth), atol=1e-6)


# -------------------------------------------- degrade + observability

def test_waves_single_rank_degrades_to_frame():
    from scenery_insitu_tpu import obs

    mesh = make_mesh(1)
    vol = procedural_volume(8, kind="blobs")
    step = distributed_vdi_step(
        mesh, _tf(), 8, 8, VDIConfig(max_supersegments=4,
                                     adaptive_iters=2),
        CompositeConfig(max_output_supersegments=6, schedule="waves",
                        wave_tiles=2), max_steps=16)
    vdi = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing,
               _cam())
    assert np.isfinite(np.asarray(vdi.color)).all()
    assert any(e["component"] == "composite.schedule"
               and e["from"] == "waves" and e["to"] == "frame"
               for e in obs.ledger())


def test_waves_build_emits_obs_counters():
    """The wave build mints schedule counters and one build event whose
    traffic block carries the overlap accounting
    (docs/OBSERVABILITY.md)."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        mesh = make_mesh(N)
        vol = procedural_volume(16, kind="blobs")
        cam = _cam()
        spec = _mxu_spec(cam, vol)
        step = distributed_vdi_step_mxu(
            mesh, _tf(), spec, VDIConfig(max_supersegments=6,
                                         adaptive_iters=2),
            CompositeConfig(max_output_supersegments=8, adaptive_iters=2,
                            schedule="waves", wave_tiles=T))
        step(shard_volume(vol.data, mesh), vol.origin, vol.spacing, cam)
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("wave_schedule_builds", 0) >= 1
    assert rec.counters.get("wave_steps_built", 0) >= T
    builds = [e for e in rec.events
              if e.get("name") == "wave_schedule_build"]
    assert builds and builds[0]["attrs"]["march_per_wave"]
    t = builds[0]["attrs"]["traffic"]
    assert t["schedule"] == "waves" and t["wave_tiles"] == T
    assert t["ici_bytes_hidden_per_rank"] + t["ici_bytes_exposed_per_rank"] \
        == t["ici_bytes_per_rank"]


def test_modeled_traffic_overlap_accounting():
    """Waves change WHEN bytes move, not how many: hidden + exposed ==
    the frame schedule's total, hidden fraction = (T-1)/T, per-pixel
    merge working set unchanged."""
    frame = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16)
    waves = modeled_exchange_traffic(8, 16, 720, 1280, k_out=16,
                                     schedule="waves", wave_tiles=4)
    assert frame["schedule"] == "frame" and "wave_tiles" not in frame
    assert waves["ici_bytes_per_rank"] == frame["ici_bytes_per_rank"]
    assert waves["ici_bytes_per_wave_per_rank"] * 4 \
        == waves["ici_bytes_per_rank"]
    assert (waves["ici_bytes_hidden_per_rank"]
            + waves["ici_bytes_exposed_per_rank"]
            == waves["ici_bytes_per_rank"])
    assert waves["overlap_hidden_frac"] == 0.75
    assert waves["peak_stream_slots_per_pixel"] \
        == frame["peak_stream_slots_per_pixel"]


# ---------------------------------------------- tile-granular delivery

def _waves_session(tmp_path, tile_sink=None, frames=2):
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=16",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=6",
        "composite.adaptive_iters=2",
        "composite.schedule=waves", "composite.wave_tiles=2",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1")
    sess = InSituSession(cfg)
    if tile_sink is not None:
        sess.tile_sinks.append(tile_sink)
    return sess


def test_partial_frame_tile_delivery_ordering(tmp_path):
    """Tiles arrive in ascending column order, cover the full width
    exactly once, and ALL precede the frame's own sinks (the partial
    frame is consumable before the frame closes)."""
    events = []

    def tile_sink(index, payload):
        assert payload["tiles"] == 8 * 2
        events.append(("tile", index, payload["tile"], payload["col0"],
                       payload["vdi_color"].shape[-1]))

    sess = _waves_session(tmp_path, tile_sink)
    sess.sinks.append(lambda i, p: events.append(("frame", i)))
    sess.run(2)
    frames = sorted({e[1] for e in events if e[0] == "tile"})
    assert frames == [0, 1]
    for f in frames:
        tiles = [e for e in events if e[0] == "tile" and e[1] == f]
        # ascending, exactly once, covering [0, 32)
        assert [t[2] for t in tiles] == list(range(16))
        assert [t[3] for t in tiles] == [i * 2 for i in range(16)]
        assert sum(t[4] for t in tiles) == 32
        # every tile of frame f lands before frame f's frame sink
        fi = events.index(("frame", f))
        assert all(events.index(t) < fi for t in tiles)
    assert sess.obs.counters.get("tiles_delivered", 0) == 2 * 16


def test_vdi_tile_sink_roundtrip(tmp_path):
    """Dumped tiles reassemble the frame (io.vdi_io tile placement)."""
    from scenery_insitu_tpu.io.vdi_io import load_vdi_tile
    from scenery_insitu_tpu.runtime.session import vdi_tile_sink

    d = str(tmp_path)
    frames = {}

    def capture(index, payload):
        frames.setdefault(index, []).append(payload)

    sess = _waves_session(tmp_path, vdi_tile_sink(d, codec="zlib"))
    sess.tile_sinks.append(capture)
    sess.run(1)
    tiles = frames[0]
    import glob
    import os

    paths = sorted(glob.glob(os.path.join(d, "*vditile*_00000.npz")))
    assert len(paths) == len(tiles) == 16
    cols = []
    for p in paths:
        vdi, meta, tile = load_vdi_tile(p)
        assert tile is not None and tile[1] == 16
        cols.append((tile[2], np.asarray(vdi.color)))
    cols.sort(key=lambda c: c[0])
    whole = np.concatenate([c[1] for c in cols], axis=-1)
    ref = np.concatenate([t["vdi_color"] for t in
                          sorted(tiles, key=lambda t: t["col0"])],
                         axis=-1)
    np.testing.assert_array_equal(whole, ref)


def test_gather_vdi_tiles_matches_compressed():
    """The rank-0 host gather's tile-granular path yields column blocks
    in order; concatenation == the whole-frame gather."""
    from scenery_insitu_tpu.parallel.multihost import (gather_vdi_compressed,
                                                       gather_vdi_tiles)

    mesh = make_mesh(N)
    vol = procedural_volume(16, kind="blobs")
    step = distributed_vdi_step(
        mesh, _tf(), W, H, VDIConfig(max_supersegments=4,
                                     adaptive_iters=2),
        CompositeConfig(max_output_supersegments=6, adaptive_iters=2),
        max_steps=24)
    vdi = step(shard_volume(vol.data, mesh), vol.origin, vol.spacing,
               _cam())
    color, depth = gather_vdi_compressed(vdi, codec="zlib")
    tiles = list(gather_vdi_tiles(vdi, codec="zlib"))
    assert [t[0] for t in tiles] == sorted(t[0] for t in tiles)
    np.testing.assert_array_equal(
        np.concatenate([t[1] for t in tiles], -1), color)
    np.testing.assert_array_equal(
        np.concatenate([t[2] for t in tiles], -1), depth)


def test_publish_tile_roundtrip():
    """VDIPublisher.publish_tile -> VDISubscriber.receive_tile carries
    the placement header; plain receive() still decodes the buffers."""
    pytest.importorskip("zmq")
    import time

    from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
    from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                      VDISubscriber)

    pub = VDIPublisher(bind="tcp://*:0", codec="zlib")
    sub = VDISubscriber(connect=pub.endpoint)
    time.sleep(0.3)
    color = np.random.default_rng(3).random((4, 4, 6, 4)).astype(np.float32)
    depth = np.random.default_rng(4).random((4, 2, 6, 4)).astype(np.float32)
    meta = VDIMetadata.create(projection=np.eye(4, dtype=np.float32),
                              view=np.eye(4, dtype=np.float32),
                              volume_dims=np.ones(3, np.float32),
                              window_dims=(16, 6), nw=0.1, index=7)
    got = None
    for _ in range(10):
        pub.publish_tile(VDI(color, depth), meta, tile=2, tiles=4, col0=8)
        got = sub.receive_tile(timeout_ms=500)
        if got is not None:
            break
    pub.close()
    sub.close()
    assert got is not None, "no tile message received"
    vdi, meta2, tile = got
    assert tile == {"tile": 2, "tiles": 4, "col0": 8}
    np.testing.assert_array_equal(np.asarray(vdi.color), color)
    assert int(np.asarray(meta2.index)) == 7
